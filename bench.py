"""Headline benchmark: candidate-tokens/sec/chip for self-consistency decode.

Measures the BASELINE.json metric on the bench flagship (``llama-1b``,
the single-chip preset): N-way candidate fan-out (the self-consistency
batch axis) decoding greedily from a prefilled prompt, steady-state,
excluding compile. Prints ONE JSON line:
``{"metric", "value", "unit", "vs_baseline"}`` where ``vs_baseline`` is
value / 1000 — BASELINE.json's north-star floor of >=1k
candidate-tokens/sec/chip (the reference itself publishes no numbers,
SURVEY.md §6).

Runs on whatever ``jax.devices()`` provides (the real TPU chip under the
driver; CPU elsewhere — pass --cpu to force).
"""

from __future__ import annotations

import argparse
import json
import sys
import time

import jax
import jax.numpy as jnp


def main() -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--model", default="llama-1b")
    # Default N matches BASELINE.json's north-star config (N=64
    # self-consistency). Decode is weight-bandwidth-bound, so candidate
    # throughput scales near-linearly in N on one chip.
    p.add_argument("--n-candidates", type=int, default=64)
    p.add_argument("--prompt-len", type=int, default=128)
    p.add_argument("--new-tokens", type=int, default=128)
    p.add_argument("--iters", type=int, default=3)
    p.add_argument("--cpu", action="store_true", help="force CPU backend")
    p.add_argument("--tiny", action="store_true", help="use test-tiny model")
    p.add_argument(
        "--no-shared-prefill",
        action="store_true",
        help="prefill all N rows instead of broadcasting one prompt's cache",
    )
    p.add_argument(
        "--quant",
        default="int8",
        choices=("none", "int8", "int4"),
        help="weight-only quantization (int8 halves decode HBM traffic; "
        "int4 packed nibbles halve it again)",
    )
    p.add_argument(
        "--kv-quant",
        default="int8",
        choices=("none", "int8"),
        help="KV-cache quantization (the dominant HBM term at large N)",
    )
    p.add_argument(
        "--no-pallas",
        action="store_true",
        help="skip the fused Pallas kernels (XLA-only decode path)",
    )
    args = p.parse_args()

    if args.cpu:
        jax.config.update("jax_platforms", "cpu")
    if args.tiny:
        args.model = "test-tiny"

    from llm_consensus_tpu.engine.generate import generate
    from llm_consensus_tpu.models.configs import get_config
    from llm_consensus_tpu.models.transformer import init_params

    cfg = get_config(args.model)
    dev = jax.devices()[0]
    # Fused Pallas kernels are single-chip TPU only (pallas_call is
    # opaque to GSPMD); default them on exactly there. The quant matmul
    # has its own auto-gate — align it so --no-pallas (and the fallback
    # below) really runs a kernel-free program.
    use_pallas = (
        not args.no_pallas
        and dev.platform == "tpu"
        and jax.device_count() == 1
    )
    cfg = cfg.with_(use_pallas=use_pallas)
    from llm_consensus_tpu.ops import quant as _quant

    if not use_pallas:
        _quant.set_kernel_enabled(False)
    print(
        f"[bench] model={cfg.name} device={dev.platform} "
        f"pallas={use_pallas}",
        file=sys.stderr,
    )

    # Flagship-scale guard: init+quantize on-device holds bf16 AND the
    # quantized copy at once (~24 GB for 8B int8) — OOM on a 16 GB v5e.
    # Stage big models through host RAM (init_params_quantized) so the
    # chip only ever sees the quantized tree.
    from llm_consensus_tpu.engine.engine import plan_memory

    bf16_plan = plan_memory(cfg, quant="none", n_candidates=1, prompt_len=8)
    # Real device HBM when the backend reports it (a v5p-class chip can
    # host-init 8B bf16 on-device; hardcoding v5e's 16 GiB would force
    # the ~30 min host-staging path for nothing); 16 GiB fallback.
    try:
        hbm_budget = int(dev.memory_stats()["bytes_limit"])
    except Exception:  # noqa: BLE001 - backend without memory stats
        hbm_budget = 16 << 30 if dev.platform != "cpu" else 64 << 30
    if args.quant in ("int8", "int4"):
        bits = 8 if args.quant == "int8" else 4
        if 2.2 * bf16_plan["params_bytes"] > hbm_budget:
            from llm_consensus_tpu.models.transformer import (
                init_params_quantized,
            )

            print(
                "[bench] staging init+quantize through host RAM "
                f"(bf16 {bf16_plan['params_bytes'] / 2**30:.1f} GiB "
                "won't coexist with the quantized copy on-chip)",
                file=sys.stderr,
            )
            params = init_params_quantized(
                cfg, jax.random.PRNGKey(0), bits=bits, device=dev
            )
        else:
            from llm_consensus_tpu.ops.quant import quantize_params

            params = init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.bfloat16)
            params = quantize_params(params, bits=bits)
    else:
        params = init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.bfloat16)
    b, s = args.n_candidates, args.prompt_len
    tokens = jnp.ones((b, s), jnp.int32)
    lengths = jnp.full((b,), s, jnp.int32)
    temps = jnp.full((b,), 0.7, jnp.float32)
    key = jax.random.PRNGKey(0)

    def make_run(run_cfg):
        def run(seed_key):
            out = generate(
                run_cfg,
                params,
                tokens,
                lengths,
                seed_key,
                temps,
                max_new_tokens=args.new_tokens,
                eos_id=-1,  # never stop early: fixed work per run
                # Self-consistency semantics: N candidates share one prompt.
                shared_prefill=not args.no_shared_prefill,
                kv_quant=args.kv_quant == "int8",
            )
            return out.tokens

        return run

    run = make_run(cfg)
    fallback = ""

    # Warmup/compile. A kernel regression must never zero the bench: if
    # the Pallas path fails to lower, record the XLA path instead and
    # say so in the metric string.
    t0 = time.perf_counter()
    try:
        run(key).block_until_ready()
    except Exception as e:  # noqa: BLE001 — any lowering/runtime failure
        if not cfg.use_pallas:
            raise
        print(
            f"[bench] Pallas path failed ({type(e).__name__}: {e}); "
            "falling back to the XLA decode path",
            file=sys.stderr,
        )
        cfg = cfg.with_(use_pallas=False)
        _quant.set_kernel_enabled(False)
        run = make_run(cfg)
        fallback = " FALLBACK:no-pallas"
        t0 = time.perf_counter()
        run(key).block_until_ready()
    compile_s = time.perf_counter() - t0
    print(f"[bench] compile+first run: {compile_s:.1f}s", file=sys.stderr)

    # Timed steady-state.
    t0 = time.perf_counter()
    for i in range(args.iters):
        run(jax.random.fold_in(key, i + 1)).block_until_ready()
    wall = (time.perf_counter() - t0) / args.iters

    candidate_tokens = b * args.new_tokens
    tps = candidate_tokens / wall
    n_chips = jax.device_count()
    tps_per_chip = tps / n_chips

    print(
        json.dumps(
            {
                "metric": f"candidate-tokens/sec/chip ({cfg.name}, N={b}, "
                f"decode {args.new_tokens} @ prompt {s}, quant={args.quant}, "
                f"kv={args.kv_quant}, pallas={cfg.use_pallas}{fallback})",
                "value": round(tps_per_chip, 2),
                "unit": "tokens/sec/chip",
                "vs_baseline": round(tps_per_chip / 1000.0, 4),
            }
        )
    )
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
