"""Headline benchmark: candidate-tokens/sec/chip for self-consistency decode.

Measures the BASELINE.json metric on the bench flagship (``llama-1b``,
the single-chip preset): N-way candidate fan-out (the self-consistency
batch axis) decoding greedily from a prefilled prompt, steady-state,
excluding compile. Prints ONE JSON line:
``{"metric", "value", "unit", "vs_baseline"}`` where ``vs_baseline`` is
value / 1000 — BASELINE.json's north-star floor of >=1k
candidate-tokens/sec/chip (the reference itself publishes no numbers,
SURVEY.md §6).

Runs on whatever ``jax.devices()`` provides (the real TPU chip under the
driver; CPU elsewhere — pass --cpu to force).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import jax
import jax.numpy as jnp


def _atomic_write_text(path: str, text: str) -> None:
    """Write an artifact via tmp file + ``os.replace``: a mid-write
    container recycle must leave either the previous artifact or the
    complete new one on disk — never a committed 0-byte file (round 5
    landed exactly that for spec_trained_r5.json, VERDICT.md)."""
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    tmp = f"{path}.tmp.{os.getpid()}"
    try:
        with open(tmp, "w") as f:
            f.write(text)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


def _emit(payload: dict, out: str | None) -> None:
    """The ONE result sink every leg shares: the JSON line goes to
    stdout (the historical contract scripts tail) and — with ``--out``
    — atomically to the artifact path, so driver scripts stop relying
    on shell redirection that can tear.

    Every payload carries a machine-readable ``status`` ("ok" unless
    the leg set one — the chip-unreachable path emits
    "chip-unreachable"), so history tooling
    (scripts/bench_history.py) stops string-matching the metric name
    to tell a measurement from a no-data round.
    """
    payload = dict(payload)
    payload.setdefault("status", "ok")
    line = json.dumps(payload)
    print(line, flush=True)
    if out:
        _atomic_write_text(out, line + "\n")


def _load_average() -> float | None:
    """1-minute loadavg (None where the platform lacks it)."""
    try:
        return os.getloadavg()[0]
    except (OSError, AttributeError):
        return None


def _box_contended() -> tuple[float | None, bool]:
    """Detect co-running load on the box. The bench itself keeps
    ~1 runnable thread (batcher worker) busy, so a 1-min loadavg past
    cpu_count + 1 means someone else is competing for the cores — the
    exact condition under which the PR-5 trace-overhead gate flaked
    during the PR-9 run (a concurrently-running bench). Used to size
    the overhead legs' escalation budget, not to skip the gate."""
    la = _load_average()
    return la, la is not None and la > (os.cpu_count() or 1) + 1.0


def _paired_overhead_pct(offs: list[float], ons: list[float]) -> float:
    """Median paired on-vs-off overhead in percent. Rounds alternate
    off/on, so pairing cancels the common-mode drift of a shared box
    (GC, other tenants); the MEDIAN pair is robust to one jittered
    round. A real instrumentation regression is in EVERY pair."""
    from statistics import median

    return 100.0 * median(1.0 - on / off for off, on in zip(offs, ons))


def _dual_gate_ok(
    offs: list[float], ons: list[float], pct: float = 2.0
) -> bool:
    """The PR-5 dual overhead gate: best-vs-best (bests approach the
    box's clean-run ceiling, so a TRUE overhead shifts them) OR the
    paired median. Smoke-size legs are ~fractions of a second on a
    shared 1-core box, where single hiccups swing one estimator by
    tens of percent — a real >= pct% regression moves BOTH, noise
    rarely moves both the same way."""
    return (
        max(ons) >= (1.0 - pct / 100.0) * max(offs)
        or _paired_overhead_pct(offs, ons) <= pct
    )


def _ab_rounds(leg, rounds: int) -> tuple[list[float], list[float]]:
    """The overhead legs' alternating off/on measurement rounds —
    within-pair order alternates so "runs second" (page cache, GC
    timing) is not systematically the on-leg. ONE copy for every
    overhead A/B (trace, flight); returns (runs_off, runs_on)."""
    runs_off: list[float] = []
    runs_on: list[float] = []
    for r in range(max(1, rounds)):
        if r % 2 == 0:
            runs_off.append(leg(f"off{r}", False))
            runs_on.append(leg(f"on{r}", True))
        else:
            runs_on.append(leg(f"on{r}", True))
            runs_off.append(leg(f"off{r}", False))
    return runs_off, runs_on


def _ab_escalate(leg, runs_off, runs_on, tag: str, pct: float = 2.0) -> None:
    """Escalate alternating off/on pairs until the dual gate passes or
    the budget runs out (the caller re-checks the gate for the final
    verdict). Budget: 3 extra pairs on a quiet box, 6 when the loadavg
    guard detects co-running load — box contention is the documented
    cause of the PR-9 flake, and buying more pairs under it beats
    failing on the first noisy one (a REAL regression fails all 6+).
    ``pct`` must match the caller's final-gate band, else a leg with a
    generous band burns its whole budget chasing the default 2%."""
    extra = 0
    while not _dual_gate_ok(runs_off, runs_on, pct=pct):
        la, contended = _box_contended()
        budget = 6 if contended else 3
        if extra >= budget:
            return
        extra += 1
        print(
            f"[bench] {tag}: paired overhead "
            f"{_paired_overhead_pct(runs_off, runs_on):.2f}% and best "
            f"ratio {max(runs_on) / max(runs_off):.4f} both fail "
            f"(loadavg {la if la is None else round(la, 2)}, "
            f"contended={contended}); extra round {extra}/{budget}",
            file=sys.stderr,
        )
        if extra % 2 == 0:
            runs_off.append(leg(f"off-x{extra}", False))
            runs_on.append(leg(f"on-x{extra}", True))
        else:
            runs_on.append(leg(f"on-x{extra}", True))
            runs_off.append(leg(f"off-x{extra}", False))


# The ONE probe body, run both in-process (_chip_responsive, via exec)
# and as a subprocess (_await_chip). Salted operand: the tunnel replays
# previously-seen (executable, inputs) pairs across processes — a fixed
# probe could "pass" from the replay cache with the chip dead (the
# half-up state the salt exists to catch). Host fetch (np.asarray), not
# block_until_ready: the only sync the tunnel runtime cannot fake.
_PROBE_SRC = """
import time
import jax, numpy as np, jax.numpy as jnp
jax.devices()
salt = float(int(time.time() * 1e6) % 9973)
x = jnp.ones((8, 8)).at[0, 0].set(salt)
v = np.asarray(x @ jnp.ones((8, 8)))
assert v.shape == (8, 8)
"""


#: Preflight retry backoff ladder (PR 16, hardened PR 19): start at
#: 45 s; EVERY further identical consecutive failure (same phase + rc —
#: the signature of a hard-down tunnel, not a flapping one) climbs one
#: rung. Probing a dead remote every 45 s only burns the wait budget on
#: subprocess startup; a changing failure mode resets to the bottom.
_CHIP_BACKOFF_S = (45.0, 90.0, 180.0)

#: Identical-failure retry cap (PR 19): after this many consecutive
#: probes failing the SAME way, give up early instead of re-probing a
#: provably hard-down tunnel for the whole wait budget — round-5's
#: postmortem showed the budget's tail attempts add stderr noise, not
#: information. A changing failure mode (flapping tunnel) resets the
#: count and keeps the full budget.
_CHIP_SAME_SIG_MAX = 5


def _await_chip(
    budget_s: float,
    probe_timeout_s: float = 90.0,
    attempts: list | None = None,
) -> bool:
    """Retry the preflight in SUBPROCESSES until the chip answers or the
    budget expires.

    Retrying in-process cannot work: when the tunnel's remote side is
    down, ``jax.devices()`` either hangs (wedging the backend-init lock
    for every later attempt in this process) or raises after a long
    internal stall. A child process is abandonable and leaves this
    process's JAX state untouched until a probe has actually succeeded.
    Bridges short outages so a driver-invoked bench records a number
    instead of 0.0 (round-4's official record); budget via
    BENCH_CHIP_WAIT_S, default 600 s — a multi-hour outage still fails.

    ``attempts`` (PR 16, enriched PR 19): pass a list to collect one
    structured record per probe — ``{"attempt": n, "phase": "probe"|
    "timeout", "rc": int|None, "elapsed": s, "t_offset": s}`` plus
    ``"stderr"`` (last line) on probe failures and ``"sleep_s"`` (the
    chosen backoff rung) on every retried attempt — so the CHIP
    UNREACHABLE artifact carries the full failure history instead of
    burying it in stderr. Every further identical consecutive failure
    climbs one backoff rung, and ``_CHIP_SAME_SIG_MAX`` identical
    failures in a row give up early (recorded as a final
    ``"gave_up"`` entry) — re-probing a provably hard-down tunnel for
    the rest of the budget adds noise, not information.
    """
    import subprocess

    start = time.time()
    deadline = start + budget_s
    attempt = 0
    last_sig = None
    same_sig = 0
    rung = 0
    while True:
        attempt += 1
        t0 = time.time()
        sig = None
        stderr_tail = ""
        try:
            r = subprocess.run(
                [sys.executable, "-c", _PROBE_SRC],
                timeout=probe_timeout_s,
                capture_output=True,
            )
            elapsed = time.time() - t0
            if r.returncode == 0:
                if attempts is not None:
                    attempts.append(
                        {
                            "attempt": attempt,
                            "phase": "probe",
                            "rc": 0,
                            "elapsed": round(elapsed, 3),
                            "t_offset": round(t0 - start, 3),
                        }
                    )
                return True
            sig = ("probe", r.returncode)
            err = (r.stderr or b"").decode(errors="replace").strip()
            stderr_tail = err.splitlines()[-1] if err else ""
            print(
                f"[bench] chip probe attempt {attempt} rc={r.returncode}"
                + (f": {stderr_tail}" if stderr_tail else ""),
                file=sys.stderr,
            )
        except subprocess.TimeoutExpired:
            elapsed = time.time() - t0
            sig = ("timeout", None)
            print(
                f"[bench] chip probe attempt {attempt} timed out "
                f"({probe_timeout_s:.0f}s)",
                file=sys.stderr,
            )
        rec = {
            "attempt": attempt,
            "phase": sig[0],
            "rc": sig[1],
            "elapsed": round(elapsed, 3),
            "t_offset": round(t0 - start, 3),
        }
        if sig[0] == "probe":
            rec["stderr"] = stderr_tail
        if attempts is not None:
            attempts.append(rec)
        if time.time() >= deadline:
            return False
        if sig == last_sig:
            same_sig += 1
        else:
            last_sig, same_sig = sig, 1
            rung = 0
        if same_sig >= _CHIP_SAME_SIG_MAX:
            print(
                f"[bench] chip probe gave up: {same_sig} identical "
                f"consecutive failures ({sig[0]}, rc={sig[1]})",
                file=sys.stderr,
            )
            if attempts is not None:
                attempts.append(
                    {
                        "attempt": attempt,
                        "phase": "gave_up",
                        "rc": sig[1],
                        "identical_failures": same_sig,
                        "t_offset": round(time.time() - start, 3),
                    }
                )
            return False
        if same_sig >= 2 and rung < len(_CHIP_BACKOFF_S) - 1:
            rung += 1
        rec["sleep_s"] = _CHIP_BACKOFF_S[rung]
        time.sleep(_CHIP_BACKOFF_S[rung])


def _chip_responsive(timeout_s: float = 180.0) -> bool:
    """Watchdog preflight: device discovery + a trivial op, with a
    deadline.

    When the tunnel's remote side is down, even ``jax.devices()`` hangs
    indefinitely (observed mid-round-4) — so BOTH discovery and the
    probe matmul run in a daemon thread the main thread can abandon.
    On success the backend is initialized and every later ``jax``
    call in the bench proceeds normally.
    """
    import threading

    ok: list[bool] = []

    def probe():
        try:
            exec(_PROBE_SRC, {})  # noqa: S102 - the shared probe body
            ok.append(True)
        except Exception as e:  # noqa: BLE001 - any failure = unresponsive
            print(f"[bench] chip probe raised: {e!r}", file=sys.stderr)

    t = threading.Thread(target=probe, daemon=True)
    t.start()
    t.join(timeout_s)
    return bool(ok)


def main() -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--model", default="llama-1b")
    # Default N matches BASELINE.json's north-star config (N=64
    # self-consistency). Decode is weight-bandwidth-bound, so candidate
    # throughput scales near-linearly in N on one chip.
    p.add_argument("--n-candidates", type=int, default=64)
    p.add_argument("--prompt-len", type=int, default=128)
    p.add_argument("--new-tokens", type=int, default=128)
    p.add_argument("--iters", type=int, default=3)
    p.add_argument("--cpu", action="store_true", help="force CPU backend")
    p.add_argument("--tiny", action="store_true", help="use test-tiny model")
    p.add_argument(
        "--no-shared-prefill",
        action="store_true",
        help="prefill all N rows instead of broadcasting one prompt's cache",
    )
    p.add_argument(
        "--quant",
        default="int8",
        choices=("none", "int8", "int4"),
        help="weight-only quantization (int8 halves decode HBM traffic; "
        "int4 packed nibbles halve it again)",
    )
    p.add_argument(
        "--kv-quant",
        default="int8",
        choices=("none", "int8"),
        help="KV-cache quantization (the dominant HBM term at large N)",
    )
    p.add_argument(
        "--no-pallas",
        action="store_true",
        help="skip the fused Pallas kernels (XLA-only decode path)",
    )
    p.add_argument(
        "--draft",
        default="",
        help="speculative-decoding bench: draft model preset (or 'self' "
        "for the acceptance=1.0 overhead ceiling). Greedy, bf16 KV; "
        "reports acceptance rate and tok/s vs the plain greedy path.",
    )
    p.add_argument("--k-spec", type=int, default=4)
    p.add_argument(
        "--serve",
        action="store_true",
        help="continuous-batching serving bench: submit a burst of "
        "requests through ContinuousBatcher (paged cache + paged "
        "Pallas decode attention on TPU), report requests/sec and "
        "generated tokens/sec",
    )
    p.add_argument("--serve-requests", type=int, default=64)
    p.add_argument("--serve-slots", type=int, default=16)
    p.add_argument(
        "--moe-dense",
        action="store_true",
        help="MoE presets: dense all-experts compute (capacity factor "
        "0) instead of capacity-bounded dispatch — at decode batch "
        "sizes the dispatch's sort/gather/scatter can cost more than "
        "the E/k extra FLOPs it saves",
    )
    p.add_argument(
        "--moe-capacity",
        action="store_true",
        help="MoE presets: pin the capacity-bounded dispatch at every "
        "shape (moe_dense_decode_tokens=0), disabling the decode-shape "
        "dense fallback — the A/B row against the default auto policy",
    )
    p.add_argument(
        "--serve-chunk",
        type=int,
        default=16,
        help="decode steps per device program in the serving bench "
        "(ContinuousConfig.steps_per_sync): the host pays one "
        "dispatch+fetch per chunk, and on a tunneled chip that RTT "
        "dominates the decode step itself",
    )
    p.add_argument(
        "--serve-shared-prefix",
        action="store_true",
        help="serving bench variant (implies --serve): every request "
        "shares one ~prompt_len-token prefix + a short unique suffix — "
        "the consensus-panel shape. Exercises copy-on-write prefix "
        "sharing + chunked prefill; reports prefix pages "
        "shared/copied, registry hit rate, and the prefill-stall "
        "histogram next to requests/sec (compare against the r5 "
        "chunk-1/chunk-16 --serve rows)",
    )
    p.add_argument(
        "--serve-prefill-chunk",
        type=int,
        default=64,
        help="prefill-chunk width for the serving bench "
        "(ContinuousConfig.prefill_chunk; 0 = legacy blocking dense "
        "prefill at admission)",
    )
    p.add_argument(
        "--serve-prefix-attention",
        action="store_true",
        help="serving A/B leg: the panel-shaped shared-prefix burst "
        "served twice — group-aware decode attention ON (shared prefix "
        "KV read once per group per step) vs OFF (the row kernel) — "
        "reporting tok/s for both, shared-KV bytes saved, and that the "
        "generated text is unchanged",
    )
    p.add_argument(
        "--serve-offload",
        action="store_true",
        help="hierarchical-KV A/B leg: a multi-round panel burst (same "
        "shared header re-sent round after round, interleaved with "
        "unique-prefix filler rounds) over a page pool sized BELOW the "
        "working set, served with the host-RAM offload tier ON "
        "(eviction demotes prefix pages to host; later rounds restore "
        "them) vs OFF (eviction destroys; later rounds re-prefill) — "
        "reporting restored pages, prefill tokens saved, per-page "
        "restore latency, and that the generated text is unchanged",
    )
    p.add_argument(
        "--serve-host-cache-mb",
        type=int,
        default=256,
        help="host-RAM KV tier byte budget for --serve-offload and "
        "the --serve-replicas fleet store "
        "(ContinuousConfig.host_cache_bytes, in MiB)",
    )
    p.add_argument(
        "--serve-replicas",
        type=int,
        default=0,
        help="replica-fleet A/B leg (PR 14): the PR-8 mixed panel "
        "burst (half sharing one header, half unique) served through "
        "K prefix-affinity-routed batcher replicas vs a K-replica "
        "random-routing control — gates the affinity leg's prefix "
        "hit rate STRICTLY above the control's and per-pair "
        "byte-identical text — then an overload-storm sub-leg "
        "through one gateway (queue bound far below the storm) "
        "gating ZERO 429s while preemption is possible: resident "
        "chains demote to the fleet-shared host tier "
        "(--serve-host-cache-mb) and the re-vote wave restores them "
        "(0 lost requests). 0 = leg off; pass K >= 2",
    )
    p.add_argument(
        "--serve-storm-requests",
        type=int,
        default=0,
        help="--serve-replicas overload sub-leg storm size "
        "(concurrent gateway requests; 0 = 2x --serve-requests)",
    )
    p.add_argument(
        "--serve-fleet-control",
        action="store_true",
        help="fleet control plane A/B leg (PR 19): a two-tenant mixed "
        "storm (flooding tenant at 10x the quiet tenant's request "
        "rate, equal offered modeled cost) through one gateway over a "
        "2-replica fleet, fleet control ON (SLO classes + tenant "
        "weighted fair share + FleetController steering) vs OFF "
        "(classic FIFO admission). Gates: the quiet tenant's p99 "
        "latency strictly better ON, the flooding tenant's admitted "
        "modeled-cost share capped at its fair weight +-10%%, zero "
        "quiet-tenant SLO misses ON while the OFF control records "
        ">= 1 against the same target, >= 1 deadline-aware shed "
        "witnessed in the flight ring, and one elastic spawn+retire "
        "cycle with zero lost requests and byte-identical quiet-"
        "tenant text across ON/OFF",
    )
    p.add_argument(
        "--serve-disagg",
        action="store_true",
        help="disaggregated prefill/decode A/B leg (PR 16): the PR-8 "
        "mixed panel burst through a 2-replica fleet with roles "
        "('prefill','decode') whose shared page store is a REMOTE "
        "page-store server (localhost subprocess) vs a mixed-role "
        "control — gates per-pair byte-identical text, >= 1 "
        "cross-process chain handoff with ZERO re-prefilled header "
        "pages on the decode side, then kills the store server and "
        "drives a burst through one gateway gating degrade-to-"
        "recompute (no 429s, /readyz stays ready, remote-store "
        "errors counted)",
    )
    p.add_argument(
        "--serve-fleet-obs",
        action="store_true",
        help="fleet observability federation A/B leg (PR 20): the same "
        "mixed burst through a front gateway forwarding to a REAL "
        "`serve --backend continuous --replicas 2 --role "
        "prefill,decode` subprocess over a remote page-store "
        "subprocess, federation/propagation ON (X-Trace-Id adoption, "
        "meta hops, /metrics?fleet=1, /debug/flight?fleet=1) vs OFF "
        "(--no-fleet-obs both tiers). Gates: ON tok/s within the PR-5 "
        "dual 2%% band of OFF (loadavg-aware escalation), >= 1 "
        "cross-process joined trace witnessed in the merged fleet "
        "export (a peer-process flight event carrying a front-minted "
        "trace id, monotonic after clock correction), the response "
        "hop breakdown summing within tolerance of the client-"
        "measured e2e latency, and byte-identical text across ON/OFF",
    )
    p.add_argument(
        "--serve-multi-model",
        action="store_true",
        help="multi-model consensus serving A/B leg (PR 18): a "
        "2-member ModelSet — a propose member whose weights are the "
        "target's vocab-PERMUTED twin under a shifted byte tokenizer, "
        "and the default judge member drafting from it through the "
        "exact-match vocab remap — serves debate-shaped traffic (N "
        "propose on the small member -> panel evaluate -> refine on "
        "the large) with cross-model speculation ON vs OFF on the "
        "judge. Gates: identical consensus decisions (all phase texts "
        "byte-equal) between the legs, spec-on tok/s >= the no-draft "
        "baseline under the PR-5 dual gate with loadavg-aware "
        "escalation, and >= 1 cross-model accept visible in stats, "
        "Prometheus, and the flight trace",
    )
    p.add_argument(
        "--mm-ab-rounds",
        type=int,
        default=2,
        help="alternating spec-off/on paired debate rounds for "
        "--serve-multi-model",
    )
    p.add_argument(
        "--serve-decode-pipeline",
        action="store_true",
        help="pipelined-dispatch A/B leg: the panel-shaped burst at "
        "ContinuousConfig.pipeline_depth 1 (serialized "
        "dispatch/sync/bookkeep loop) vs 2 (program n+1 enqueued "
        "before program n's fetch) through ONE batcher — "
        "byte-identical text required, reports tok/s per depth and "
        "the gateway_sched_overhead_seconds p50/mean collapse, plus a "
        "steps_per_sync x depth grid; fails (rc 1) on text divergence "
        "or a depth-2 regression past the dual gate",
    )
    p.add_argument(
        "--pipeline-ab-rounds",
        type=int,
        default=2,
        help="alternating depth-1/depth-2 paired rounds for "
        "--serve-decode-pipeline (dual gate over per-leg bests and "
        "the paired median, PR-5 style)",
    )
    p.add_argument(
        "--no-pipeline-grid",
        action="store_true",
        help="skip --serve-decode-pipeline's steps_per_sync x depth "
        "grid sweep (the PERF.md table)",
    )
    p.add_argument(
        "--serve-ragged-attention",
        action="store_true",
        help="fused-scheduler-step A/B leg (PR 8): a prefill-heavy "
        "MIXED burst (shared panel header + unique-prefix requests) "
        "served through ONE batcher with ContinuousConfig."
        "ragged_attention ON (a ready prefill chunk rides the decode "
        "dispatch as one ragged-kernel row — ONE device program per "
        "scheduler iteration) vs OFF (standalone chunk program + "
        "decode program, the PR-7 state) — byte-identical text "
        "REQUIRED per pair, reports tok/s per leg and device programs "
        "per scheduler iteration (target 1.0 on the fused leg), plus "
        "a pipeline depth {1,2} grid and a sliding-window parity "
        "sub-leg; fails (rc 1) on text divergence or a fused-leg "
        "ratio above 1",
    )
    p.add_argument(
        "--ragged-ab-rounds",
        type=int,
        default=2,
        help="alternating off/on paired rounds for "
        "--serve-ragged-attention",
    )
    p.add_argument(
        "--serve-mesh",
        action="store_true",
        help="mesh-native serving A/B leg (PR 13): the PR-8 mixed "
        "panel burst (shared headers + unique prefixes) served by a "
        "dp2×mp2 MESH batcher vs a single-device batcher — "
        "byte-identical text REQUIRED per pair (every serving "
        "feature now engages on the mesh), gates the mesh leg's "
        "device programs per scheduler iteration == 1.0 (fused "
        "ragged dispatch really engaged), and reports per-leg tok/s "
        "through the PR-5 dual gate at a generous band (a "
        "CPU-simulated mesh pays collective emulation on shared "
        "cores; the gate catches pathological collapse, the chip "
        "rows land with the next bench round). Needs >= 4 devices "
        "(the leg forces xla_force_host_platform_device_count=8 on "
        "CPU)",
    )
    p.add_argument(
        "--mesh-ab-rounds",
        type=int,
        default=2,
        help="alternating single/mesh paired rounds for --serve-mesh",
    )
    p.add_argument(
        "--serve-speculative",
        action="store_true",
        help="speculative-decoding A/B leg (PR 9): the same greedy "
        "panel burst (shared header, identical question — the "
        "consensus propose round) through ONE batcher flipping "
        "ContinuousConfig.spec_decode between bursts — spec ON "
        "dispatches one draft/verify/accept program per round (one "
        "shared draft stream per agreeing panel group), OFF is plain "
        "one-token decode — byte-identical text REQUIRED per pair, "
        "gates on verified tokens per spec device program > 1.0 "
        "(speculation beating the one-token-per-program roofline) and "
        "on the panel's shared streams drafting fewer tokens per "
        "generated token than a unique-prompt control burst; reports "
        "acceptance rate and tok/s per leg",
    )
    p.add_argument(
        "--serve-draft",
        default="self",
        help="--serve-speculative draft: 'self' (target as its own "
        "draft — the acceptance~1 ceiling, the CPU smoke default) or "
        "a preset name (e.g. arith-3m; random weights unless "
        "--serve-draft-ckpt, so treat preset-without-checkpoint as "
        "the pessimistic floor)",
    )
    p.add_argument(
        "--serve-draft-ckpt",
        default="",
        help="orbax checkpoint dir for --serve-draft's weights (the "
        "trained arith-14m + arith-3m pair from PERF.md r5 is the "
        "intended chip pairing, via --model arith-14m "
        "--serve-target-ckpt)",
    )
    p.add_argument(
        "--serve-target-ckpt",
        default="",
        help="orbax checkpoint dir for the TARGET model's weights on "
        "the --serve-speculative leg (acceptance is meaningless "
        "between random-weight models; both ckpt flags together run "
        "the trained pair)",
    )
    p.add_argument(
        "--spec-ab-rounds",
        type=int,
        default=2,
        help="alternating off/on paired rounds for --serve-speculative",
    )
    p.add_argument(
        "--serve-decode-rounds",
        action="store_true",
        help="multi-round on-device decode A/B leg (PR 12): the same "
        "greedy panel burst through ONE batcher flipping "
        "ContinuousConfig.decode_rounds between bursts — R=4 folds "
        "four decode rounds (device-side stop scan, sampling, "
        "emit/length bookkeeping, early-exit masking) into each "
        "dispatched program so the host fetches once per window, R=1 "
        "is today's one-round dispatch — byte-identical text REQUIRED "
        "per pair, gates on device programs per generated token "
        "dropping >= 3x at R=4 and on the PR-5 dual tok/s gate "
        "(loadavg-aware escalation); reports rounds/program and "
        "program-MBU sums per leg",
    )
    p.add_argument(
        "--rounds-ab-rounds",
        type=int,
        default=2,
        help="alternating R=1/R=4 paired rounds for "
        "--serve-decode-rounds",
    )
    p.add_argument(
        "--serve-adaptive",
        action="store_true",
        help="roofline-adaptive runtime control A/B leg (PR 15): ONE "
        "batcher carrying an adversarial random-weight draft serves "
        "the same mixed greedy burst under every fixed (spec_k x R) "
        "knob grid point — spec on at k in {1, K}, spec off at R in "
        "{1, R} — and under the adaptive controller steering "
        "spec_k/rounds/chunk/depth live from measured acceptance, "
        "modeled MBU, and un-overlapped overhead. Gates: per-pair "
        "byte-identical greedy text across every leg, adaptive tok/s "
        ">= every grid point under the PR-5 dual gate, >= 1 recorded "
        "spec_k shrink and >= 1 adaptive-R decision in the flight "
        "trace, and zero recompiles after warmup (program kinds + "
        "compile caches stable across the steering bursts)",
    )
    p.add_argument(
        "--adaptive-ab-rounds",
        type=int,
        default=2,
        help="measurement rounds per grid point for --serve-adaptive",
    )
    p.add_argument(
        "--serve-trace-overhead",
        action="store_true",
        help="observability A/B leg: the identical panel-shaped burst "
        "served twice through ContinuousBatcher — request-scoped "
        "tracing ON (one trace per request; prefill-chunk/decode-step "
        "spans + derived histograms) vs OFF (tracing.set_enabled "
        "False) — reporting tok/s for both and failing (rc 1) if the "
        "ON leg regresses > 2%%",
    )
    p.add_argument(
        "--trace-ab-rounds",
        type=int,
        default=2,
        help="alternating off/on measurement rounds for "
        "--serve-trace-overhead (best-of damping; the 2%% gate "
        "compares per-leg bests)",
    )
    p.add_argument(
        "--serve-flight-overhead",
        action="store_true",
        help="observability A/B leg (PR 10): the identical "
        "panel-shaped burst served with the serving flight recorder "
        "ON (typed scheduler events, program windows, per-request "
        "token timelines at /debug/flight) vs OFF — the PR-5 dual "
        "tok/s gate (per-leg bests within 2%% OR paired-median <= "
        "2%%, loadavg-aware escalation) proves the recorder is free "
        "when sampling",
    )
    p.add_argument(
        "--flight-ab-rounds",
        type=int,
        default=2,
        help="alternating off/on measurement rounds for "
        "--serve-flight-overhead",
    )
    p.add_argument(
        "--out",
        default="",
        help="also write the final JSON line to this path ATOMICALLY "
        "(tmp + os.replace) — driver scripts should prefer this over "
        "shell redirection, which can commit a torn 0-byte artifact",
    )
    p.add_argument(
        "--fanout-prefix-ab",
        action="store_true",
        help="engine-level A/B leg: the N-candidate shared-prefill "
        "fan-out decoded with the two-phase shared-prefix kernel ON "
        "(prefix KV read once per step for the whole batch) vs OFF, "
        "reporting candidate-tok/s for both and token parity",
    )
    args = p.parse_args()

    if args.cpu:
        jax.config.update("jax_platforms", "cpu")
    if args.tiny:
        args.model = "test-tiny"
    if args.serve_mesh and (
        args.cpu
        or os.environ.get("JAX_PLATFORMS", "").startswith("cpu")
    ):
        # The mesh leg needs >= 4 devices; on CPU that means simulated
        # host devices, whose count is an XLA backend-init flag. jax is
        # imported but the CPU backend initializes lazily at the first
        # device query, so setting the flag here (before any
        # jax.devices() below) is early enough — unless something
        # already initialized it, which the leg detects and reports.
        # Keyed on the resolved platform (--cpu OR the JAX_PLATFORMS
        # env convention), not the flag alone.
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count=8"
            ).strip()

    from llm_consensus_tpu.engine.generate import generate
    from llm_consensus_tpu.models.configs import get_config
    from llm_consensus_tpu.models.transformer import init_params

    cfg = get_config(args.model)
    if args.moe_dense and args.moe_capacity:
        print("--moe-dense and --moe-capacity are mutually exclusive",
              file=sys.stderr)
        return 2
    if args.moe_dense and cfg.is_moe:
        cfg = cfg.with_(moe_capacity_factor=0.0)
    if args.moe_capacity and cfg.is_moe:
        cfg = cfg.with_(
            moe_dense_decode_tokens=0,
            moe_capacity_factor=cfg.moe_capacity_factor or 1.25,
        )
    probe_timeout = 180.0
    import math

    try:
        wait_budget = float(os.environ.get("BENCH_CHIP_WAIT_S", "600"))
        if not math.isfinite(wait_budget) or wait_budget < 0:
            raise ValueError(wait_budget)
    except ValueError:
        print(
            "[bench] malformed BENCH_CHIP_WAIT_S "
            f"{os.environ['BENCH_CHIP_WAIT_S']!r}; using 600",
            file=sys.stderr,
        )
        wait_budget = 600.0
    preflight_attempts: list = []
    if not args.cpu and not (
        _await_chip(wait_budget, attempts=preflight_attempts)
        and _chip_responsive(probe_timeout)
    ):
        # The tunneled chip can go unreachable for hours (observed
        # mid-round-4); a bench that hangs forever is worse than an
        # explicit failure record. _await_chip bridges short outages
        # first (subprocess probes, BENCH_CHIP_WAIT_S budget).
        _emit(
            {
                "metric": "CHIP UNREACHABLE (subprocess probes "
                f"failed for the {wait_budget:.0f}s wait budget "
                "and/or the in-process preflight did not complete "
                f"in {probe_timeout:.0f}s; per-attempt errors on "
                "stderr)",
                "value": 0.0,
                "unit": "tokens/sec/chip",
                "vs_baseline": 0.0,
                # Machine-readable: a no-data round, NOT a 0-tok/s
                # measurement (bench_history treats it as such).
                "status": "chip-unreachable",
                # Structured per-attempt preflight report (PR 16,
                # enriched PR 19): attempt number, phase ("probe"
                # subprocess exit / "timeout" / terminal "gave_up"),
                # rc, elapsed seconds, wall offset into the budget,
                # stderr tail, and the backoff slept after — the
                # failure history a postmortem needs without scraping
                # stderr. A final "gave_up" entry means the identical-
                # failure cap fired before the budget expired. An
                # empty list means the SUBPROCESS probes passed and
                # the in-process preflight was what failed.
                "preflight_attempts": preflight_attempts,
            },
            args.out,
        )
        # _exit, not return: the JAX runtime's shutdown hooks block on
        # the same dead tunnel the probe just diagnosed.
        os._exit(2)
    dev = jax.devices()[0]
    # Fused Pallas kernels are single-chip TPU only (pallas_call is
    # opaque to GSPMD); default them on exactly there. The quant matmul
    # has its own auto-gate — align it so --no-pallas (and the fallback
    # below) really runs a kernel-free program.
    use_pallas = (
        not args.no_pallas
        and dev.platform == "tpu"
        and jax.device_count() == 1
    )
    cfg = cfg.with_(use_pallas=use_pallas)
    from llm_consensus_tpu.ops import quant as _quant

    if not use_pallas:
        _quant.set_kernel_enabled(False)
    print(
        f"[bench] model={cfg.name} device={dev.platform} "
        f"pallas={use_pallas}",
        file=sys.stderr,
    )

    if args.serve_mesh:
        # Dispatch BEFORE the main param build: the mesh leg re-inits
        # fp32 params itself (cross-topology byte parity needs
        # order-stable numerics), so building the bf16/quantized tree
        # here would be pure wasted startup time and transient double
        # param memory.
        return _bench_serving_mesh_ab(args, cfg, None)

    # Flagship-scale guard: init+quantize on-device holds bf16 AND the
    # quantized copy at once (~24 GB for 8B int8) — OOM on a 16 GB v5e.
    # Stage big models through host RAM (init_params_quantized) so the
    # chip only ever sees the quantized tree.
    from llm_consensus_tpu.engine.engine import plan_memory

    bf16_plan = plan_memory(cfg, quant="none", n_candidates=1, prompt_len=8)
    # Real device HBM when the backend reports it (a v5p-class chip can
    # host-init 8B bf16 on-device; hardcoding v5e's 16 GiB would force
    # the ~30 min host-staging path for nothing); 16 GiB fallback.
    try:
        hbm_budget = int(dev.memory_stats()["bytes_limit"])
    except Exception:  # noqa: BLE001 - backend without memory stats
        hbm_budget = 16 << 30 if dev.platform != "cpu" else 64 << 30
    if args.quant in ("int8", "int4"):
        bits = 8 if args.quant == "int8" else 4
        if 2.2 * bf16_plan["params_bytes"] > hbm_budget:
            from llm_consensus_tpu.models.transformer import (
                init_params_quantized,
            )

            print(
                "[bench] staging init+quantize through host RAM "
                f"(bf16 {bf16_plan['params_bytes'] / 2**30:.1f} GiB "
                "won't coexist with the quantized copy on-chip)",
                file=sys.stderr,
            )
            params = init_params_quantized(
                cfg, jax.random.PRNGKey(0), bits=bits, device=dev
            )
        else:
            from llm_consensus_tpu.ops.quant import quantize_params

            params = init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.bfloat16)
            params = quantize_params(params, bits=bits)
    else:
        params = init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.bfloat16)
    b, s = args.n_candidates, args.prompt_len
    # Time-salted prompt + key: the tunnel runtime short-circuits repeat
    # executions of a previously seen (executable, inputs) pair, even
    # across processes — a re-run of an unchanged bench with fixed
    # inputs would time the server's result cache, not the chip.
    salt = int(time.time() * 1e6) % 29989
    tokens = jnp.ones((b, s), jnp.int32).at[0, 0].set(1 + salt % 30000)
    lengths = jnp.full((b,), s, jnp.int32)
    temps = jnp.full((b,), 0.7, jnp.float32)
    key = jax.random.PRNGKey(salt)

    if args.serve_speculative:
        return _bench_serving_spec_ab(args, cfg, params)
    if args.draft:
        return _bench_speculative(args, cfg, params, tokens, lengths)
    if args.serve_decode_rounds:
        return _bench_serving_rounds_ab(args, cfg, params)
    if args.serve_adaptive:
        return _bench_serving_adaptive(args, cfg, params)
    if args.serve_decode_pipeline:
        return _bench_serving_pipeline_ab(args, cfg, params)
    if args.serve_ragged_attention:
        return _bench_serving_ragged_ab(args, cfg, params)
    if args.serve_trace_overhead:
        return _bench_serving_trace_overhead(args, cfg, params)
    if args.serve_flight_overhead:
        return _bench_serving_flight_overhead(args, cfg, params)
    if args.serve_replicas:
        return _bench_serving_replicas(args, cfg, params)
    if args.serve_fleet_control:
        return _bench_serve_fleet_control(args, cfg, params)
    if args.serve_disagg:
        return _bench_serving_disagg(args, cfg, params)
    if args.serve_fleet_obs:
        return _bench_serve_fleet_obs(args, cfg, params)
    if args.serve_multi_model:
        return _bench_serving_multimodel(args, cfg, params)
    if args.serve_offload:
        return _bench_serving_offload(args, cfg, params)
    if args.serve_prefix_attention:
        return _bench_serving_prefix_ab(args, cfg, params)
    if args.fanout_prefix_ab:
        return _bench_fanout_prefix_ab(args, cfg, params, tokens, lengths)
    if args.serve or args.serve_shared_prefix:
        return _bench_serving(args, cfg, params)

    # Synchronization caveat on this tunnel runtime: blocking a SINGLE
    # output array does NOT wait for remote completion (measured ~2 ms
    # "walls" for 128-step programs); jax.block_until_ready over the
    # WHOLE output tree does. Every timed leg below must use the
    # tree-level sync or the numbers are dispatch time, not compute.
    def make_run(run_cfg):
        def run(seed_key):
            out = generate(
                run_cfg,
                params,
                tokens,
                lengths,
                seed_key,
                temps,
                max_new_tokens=args.new_tokens,
                eos_id=-1,  # never stop early: fixed work per run
                # Self-consistency semantics: N candidates share one prompt.
                shared_prefill=not args.no_shared_prefill,
                kv_quant=args.kv_quant == "int8",
            )
            return out

        return run

    run = make_run(cfg)
    fallback = ""

    # Warmup/compile. A kernel regression must never zero the bench: if
    # the Pallas path fails to lower, record the XLA path instead and
    # say so in the metric string.
    t0 = time.perf_counter()
    try:
        jax.block_until_ready(run(key))
    except Exception as e:  # noqa: BLE001 — any lowering/runtime failure
        if not cfg.use_pallas:
            raise
        print(
            f"[bench] Pallas path failed ({type(e).__name__}: {e}); "
            "falling back to the XLA decode path",
            file=sys.stderr,
        )
        cfg = cfg.with_(use_pallas=False)
        _quant.set_kernel_enabled(False)
        run = make_run(cfg)
        fallback = " FALLBACK:no-pallas"
        t0 = time.perf_counter()
        jax.block_until_ready(run(key))
    compile_s = time.perf_counter() - t0
    print(f"[bench] compile+first run: {compile_s:.1f}s", file=sys.stderr)

    # Timed steady-state. Host-fetch sync (np.asarray of the token
    # buffer, 32 KB — negligible): tree-level block_until_ready was
    # enough for THIS program in r4/r5 measurements (plausible step
    # times), but r5 caught it not waiting on the speculative
    # while_loop program, so every timed leg now uses the one sync the
    # tunnel runtime cannot fake.
    import numpy as _np

    t0 = time.perf_counter()
    for i in range(args.iters):
        _np.asarray(run(jax.random.fold_in(key, i + 1)).tokens)
    wall = (time.perf_counter() - t0) / args.iters

    candidate_tokens = b * args.new_tokens
    tps = candidate_tokens / wall
    n_chips = jax.device_count()
    tps_per_chip = tps / n_chips

    _emit(
        {
            "metric": f"candidate-tokens/sec/chip ({cfg.name}, N={b}, "
            f"decode {args.new_tokens} @ prompt {s}, quant={args.quant}, "
            f"kv={args.kv_quant}, pallas={cfg.use_pallas}"
            + (
                # Which MLP path the N-token DECODE program traced.
                (", moe=dense" if cfg.moe_dense_at(b) else ", moe=capacity")
                if cfg.is_moe
                else ""
            )
            + f"{fallback})",
            "value": round(tps_per_chip, 2),
            "unit": "tokens/sec/chip",
            "vs_baseline": round(tps_per_chip / 1000.0, 4),
        },
        args.out,
    )
    return 0


def _burst_leg(batcher, prompts, new_tokens):
    """One quiesced burst through a batcher; returns (texts, tok/s,
    device programs per scheduler work iteration). ONE copy of the
    programs/iteration accounting for every leg that gates on it (the
    ragged and mesh A/B legs) — two copies of the stats-key sum is how
    the PR-9 dispatch-tail drift happened."""
    _quiesce_batcher(batcher)
    s0 = batcher.stats()
    t0 = time.perf_counter()
    futs = [
        batcher.submit(p, max_new_tokens=new_tokens) for p in prompts
    ]
    results = [f.result(timeout=600) for f in futs]
    wall = time.perf_counter() - t0
    _quiesce_batcher(batcher)
    s1 = batcher.stats()
    programs = sum(
        s1[k] - s0[k]
        for k in (
            "device_programs_fused",
            "device_programs_decode",
            "device_programs_prefill",
        )
    )
    iters = s1["work_iterations"] - s0["work_iterations"]
    toks = sum(r.num_tokens for r in results)
    return (
        [r.text for r in results],
        toks / wall,
        programs / max(1, iters),
    )


def _quiesce_batcher(batcher, timeout: float = 10.0) -> None:
    """Wait until a batcher's scheduler loop is fully idle — the
    previous burst's futures resolve at fetch time, but the loop can
    still be draining in-flight programs and overshoot steps; reading
    per-leg counters across that tail would smear a few iterations
    into the wrong leg, making any counter gate meaningless. ONE
    definition for every A/B leg that flips host-loop policy between
    bursts (ragged, speculative)."""
    t0 = time.perf_counter()
    while time.perf_counter() - t0 < timeout:
        s = batcher.stats()
        if (
            s["active_slots"] == 0
            and s["prefilling_slots"] == 0
            and s["dispatch_inflight"] == 0
            and s["waiting"] == 0
        ):
            return
        time.sleep(0.01)
    raise RuntimeError(
        f"batcher did not quiesce within {timeout}s "
        f"(stats: {batcher.stats()})"
    )


def _serve_pages_per_seq(largest_bucket: int, new_tokens: int,
                         chunk: int, pg: int, depth: int = 2) -> int:
    """Page-table width for the serving legs: prompt bucket + decode
    budget + the worst-case overshoot — a row finishing mid-chunk keeps
    writing to the chunk boundary, and pipelined dispatch (default
    depth 2) lags retirement by depth-1 more in-flight programs of
    chunk tokens. ONE definition for every leg: this mirrors
    ContinuousBatcher._table_pages, and a leg whose copy drifts
    under-reserves pages and fails at admission far from the edit."""
    return -(-(largest_bucket + new_tokens + depth * chunk - 1) // pg)


def _bench_speculative(args, cfg, params, tokens, lengths) -> int:
    """Speculative-decoding bench leg: greedy spec vs plain greedy.

    Reports acceptance rate (SpecOutput.accepted/drafted) and the
    speedup over the plain path at the same shapes. `--draft self`
    measures the acceptance=1.0 ceiling (pure overhead); a real draft
    preset measures what its agreement with the target actually buys —
    with RANDOM weights the two models agree at chance, so treat the
    preset number as the pessimistic floor and `self` as the ceiling.
    """
    import jax.numpy as jnp

    from llm_consensus_tpu.engine.generate import generate
    from llm_consensus_tpu.engine.speculative import speculative_generate
    from llm_consensus_tpu.models.configs import get_config
    from llm_consensus_tpu.models.transformer import init_params

    b = tokens.shape[0]
    if args.draft == "self":
        d_cfg, d_params = cfg, params
    else:
        d_cfg = get_config(args.draft).with_(use_pallas=cfg.use_pallas)
        d_params = init_params(d_cfg, jax.random.PRNGKey(1), dtype=jnp.bfloat16)
    print(
        f"[bench] speculative: draft={d_cfg.name} k_spec={args.k_spec}",
        file=sys.stderr,
    )

    # Inputs are SALTED per process AND perturbed per iteration: this
    # tunnel runtime short-circuits repeat executions of a previously
    # seen (executable, inputs) pair — even across processes (measured:
    # "128 sequential decode steps in 1.3 ms", physically impossible,
    # for exactly the input values an earlier invocation had run). A
    # time-derived token perturbation guarantees fresh work without
    # changing the workload.
    salt = int(time.time() * 1e6) % 29989

    def run_spec(i):
        toks = tokens.at[0, 0].set(1 + (salt + i) % 30000)
        return speculative_generate(
            cfg, params, d_cfg, d_params, toks, lengths,
            max_new_tokens=args.new_tokens, k_spec=args.k_spec,
            eos_id=-1, pad_id=0,
        )

    def run_plain(i):
        toks = tokens.at[0, 0].set(1 + (salt + i) % 30000)
        return generate(
            cfg, params, toks, lengths,
            jax.random.fold_in(jax.random.PRNGKey(salt), i),
            jnp.zeros((b,), jnp.float32),
            max_new_tokens=args.new_tokens, eos_id=-1,
            # bf16 KV on BOTH legs: speculative_generate has no quant-KV
            # path, and the speedup figure must isolate speculation, not
            # conflate it with the KV-quant delta.
            kv_quant=False,
        )

    import numpy as np

    t0 = time.perf_counter()
    np.asarray(run_spec(0).tokens)  # host fetch: see timed-loop note
    np.asarray(run_plain(0).tokens)
    print(
        f"[bench] compile+first run: {time.perf_counter() - t0:.1f}s",
        file=sys.stderr,
    )
    # HOST-FETCH sync, not block_until_ready: round 5 measured the
    # spec while_loop program "completing" in 1-2 ms under tree-level
    # block (515k tok/s plain at N=8 — physically impossible; ~170x
    # the real rate), i.e. on this tunnel runtime tree-level block is
    # not sufficient for every program shape. Fetching the token
    # buffer to host (32 KB) is the sync the runtime cannot fake.
    t0 = time.perf_counter()
    for i in range(args.iters):
        out = run_spec(i + 1)
        np.asarray(out.tokens)
    spec_wall = (time.perf_counter() - t0) / args.iters
    t0 = time.perf_counter()
    for i in range(args.iters):
        np.asarray(run_plain(i + 1).tokens)
    plain_wall = (time.perf_counter() - t0) / args.iters

    produced = float(jnp.sum(out.num_tokens))
    acc = float(out.accepted) / max(1.0, float(out.drafted))
    spec_tps = produced / spec_wall
    plain_tps = b * args.new_tokens / plain_wall
    _emit(
        {
            "metric": f"speculative tokens/sec/chip ({cfg.name} + draft "
            f"{d_cfg.name}, N={b}, k={args.k_spec}, decode "
            f"{args.new_tokens} @ prompt {tokens.shape[1]}, "
            f"acceptance={acc:.3f}, plain={plain_tps:.0f} tok/s, "
            f"speedup={spec_tps / plain_tps:.2f}x)",
            "value": round(spec_tps, 2),
            "unit": "tokens/sec/chip",
            "vs_baseline": round(spec_tps / 1000.0, 4),
        },
        args.out,
    )
    return 0


def _bench_serving_prefix_ab(args, cfg, params) -> int:
    """Group-aware decode attention A/B on the panel-shaped burst.

    Serves the same shared-prefix burst twice through ContinuousBatcher
    — ``prefix_attention`` on (shared prefix pages read once per group
    per decode step) vs off (the ungrouped row kernel) — and reports
    generated tok/s for both, the shared-KV bytes the grouped program
    skipped, the largest group size, and whether the generated text is
    byte-identical (the acceptance contract: the kernel is a pure
    bandwidth optimization).
    """
    from llm_consensus_tpu.serving.continuous import (
        ContinuousBatcher,
        ContinuousConfig,
    )

    if not cfg.use_pallas:
        if args.tiny or args.model == "test-tiny":
            # The grouped kernel requires the Pallas paged path; on a
            # CPU tiny run, engage it in interpret mode so the leg
            # still demonstrates the dedup end to end.
            cfg = cfg.with_(use_pallas=True)
            print(
                "[bench] tiny CPU run: Pallas interpret mode forced so "
                "the grouped kernel engages",
                file=sys.stderr,
            )
        else:
            print(
                "[bench] --serve-prefix-attention needs the Pallas "
                "paged decode path (single TPU chip, or --tiny --cpu "
                "for interpret mode)",
                file=sys.stderr,
            )
            return 2

    pg = 64
    salt = int(time.time() * 1e6) % 999983
    # Header sized to cover >= 2 FULL pages even at small --prompt-len:
    # full pages are the sharing unit (a sub-page prefix maps nothing),
    # and the bucket list is sized off the real prompt so truncation
    # can never silently misalign the shared prefix across requests.
    header_target = max(args.prompt_len, 2 * pg + 16)
    header = f"Panel header {salt}: " + "shared context " * (
        -(-header_target // 15)
    )
    prompts = [
        header + f"Q{i}: item {i * 37 % 101}?"
        for i in range(args.serve_requests)
    ]
    longest = max(len(p) for p in prompts) + 1
    buckets = [64]
    while buckets[-1] < longest:
        buckets.append(buckets[-1] * 2)
    pages_per_seq = _serve_pages_per_seq(
        buckets[-1], args.new_tokens, args.serve_chunk, pg
    )
    n_pages = 1 + args.serve_slots * pages_per_seq * 2
    prefill_chunk = args.serve_prefill_chunk or 64

    def run(prefix_attention: bool):
        batcher = ContinuousBatcher(
            cfg,
            params,
            config=ContinuousConfig(
                max_slots=args.serve_slots,
                page_size=pg,
                n_pages=n_pages,
                pages_per_seq=pages_per_seq,
                max_new_tokens=args.new_tokens,
                seq_buckets=tuple(buckets),
                steps_per_sync=args.serve_chunk,
                prefill_chunk=prefill_chunk,
                share_prefix=True,
                prefix_attention=prefix_attention,
            ),
        )
        try:
            # Warmup compiles the prefill/chunk/decode programs on a
            # prompt outside the burst set (replay hazard, see main()).
            batcher.submit(
                f"warmup {salt} " + "ctx " * (args.prompt_len // 5),
                max_new_tokens=args.new_tokens,
            ).result(timeout=600)
            before = batcher.stats()
            t0 = time.perf_counter()
            futs = [
                batcher.submit(p, max_new_tokens=args.new_tokens)
                for p in prompts
            ]
            results = [f.result(timeout=600) for f in futs]
            wall = time.perf_counter() - t0
            after = batcher.stats()
        finally:
            batcher.close()
        toks = sum(r.num_tokens for r in results)
        saved = (
            after["shared_kv_bytes_saved"] - before["shared_kv_bytes_saved"]
        )
        return [r.text for r in results], toks / wall, saved, after

    texts_on, tps_on, saved_on, stats_on = run(True)
    texts_off, tps_off, saved_off, _ = run(False)
    unchanged = texts_on == texts_off
    _emit(
        {
            "metric": f"serving tok/s, grouped prefix attention "
            f"({cfg.name}, {args.serve_requests} reqs, "
            f"slots={args.serve_slots}, decode {args.new_tokens} @ "
            f"~{args.prompt_len} shared prompt, chunk="
            f"{args.serve_chunk}, kernel OFF {tps_off:.0f} tok/s, "
            f"shared-KV saved {saved_on} B "
            f"[{saved_on / 2**20:.2f} MiB] (off leg {saved_off} B), "
            f"peak group {stats_on['decode_group_peak']}, "
            f"text unchanged={unchanged})",
            "value": round(tps_on, 2),
            "unit": "tokens/sec",
            "vs_baseline": round(tps_on / max(tps_off, 1e-9), 4),
        },
        args.out,
    )
    if not unchanged:
        print(
            "[bench] GENERATED TEXT DIVERGED between grouped and "
            "ungrouped attention — kernel regression",
            file=sys.stderr,
        )
        return 1
    return 0 if saved_on > 0 else 1


def _bench_fanout_prefix_ab(args, cfg, params, tokens, lengths) -> int:
    """Engine N-fanout A/B: shared-prefill decode with the two-phase
    shared-prefix kernel on vs off (same program shapes otherwise).

    The group here is the WHOLE batch — N candidates over one prompt —
    so the prefix half of the decode roofline drops from N*S to S; the
    measured delta is that bandwidth back as throughput. Greedy-free
    fixed-work legs (eos -1), host-fetch synced like the main bench.
    """
    from llm_consensus_tpu.engine.generate import generate

    if not cfg.use_pallas and (args.tiny or args.model == "test-tiny"):
        # Interpret mode on CPU so the two-phase kernel engages at all
        # (the A/B is meaningless if both legs run the jnp path).
        cfg = cfg.with_(use_pallas=True)
        print(
            "[bench] tiny CPU run: Pallas interpret mode forced so the "
            "shared-prefix kernel engages",
            file=sys.stderr,
        )
    b = tokens.shape[0]
    # Greedy legs: the parity check compares argmax streams, where the
    # two-phase merge's ~1e-6 reassociation noise cannot flip a token
    # short of an exact logit tie (sampled streams would be noisier).
    temps = jnp.zeros((b,), jnp.float32)
    salt = int(time.time() * 1e6) % 29989
    key = jax.random.PRNGKey(salt)

    def make_run(prefix_attention: bool):
        def run(i):
            toks = tokens.at[0, 0].set(1 + (salt + i) % 30000)
            return generate(
                cfg, params, toks, lengths,
                jax.random.fold_in(key, i), temps,
                max_new_tokens=args.new_tokens,
                eos_id=-1,
                shared_prefill=True,
                kv_quant=args.kv_quant == "int8",
                shared_prefix_attention=prefix_attention,
            )

        return run

    import numpy as _np

    legs = {}
    outs = {}
    for name, on in (("on", True), ("off", False)):
        run = make_run(on)
        t0 = time.perf_counter()
        _np.asarray(run(0).tokens)  # compile + first run
        print(
            f"[bench] fanout-prefix {name}: compile+first "
            f"{time.perf_counter() - t0:.1f}s",
            file=sys.stderr,
        )
        t0 = time.perf_counter()
        for i in range(args.iters):
            outs[name] = _np.asarray(run(i + 1).tokens)
        wall = (time.perf_counter() - t0) / args.iters
        legs[name] = b * args.new_tokens / wall
    parity = bool(_np.array_equal(outs["on"], outs["off"]))
    n_chips = jax.device_count()
    _emit(
        {
            "metric": f"candidate-tokens/sec/chip, shared-prefix "
            f"decode kernel ({cfg.name}, N={b}, decode "
            f"{args.new_tokens} @ prompt {tokens.shape[1]}, "
            f"kv={args.kv_quant}, kernel OFF "
            f"{legs['off'] / n_chips:.0f} tok/s/chip, "
            f"tokens equal={parity})",
            "value": round(legs["on"] / n_chips, 2),
            "unit": "tokens/sec/chip",
            "vs_baseline": round(legs["on"] / max(legs["off"], 1e-9), 4),
        },
        args.out,
    )
    return 0 if parity else 1


def _bench_serving_pipeline_ab(args, cfg, params) -> int:
    """Pipelined decode dispatch A/B (PR 6): the same panel-shaped
    burst at ``pipeline_depth`` 1 (the serialized
    dispatch→sync→bookkeep loop) vs 2 (program n+1 enqueued before
    program n's fetch) through ONE batcher — same compiled programs;
    depth is host-loop policy read per iteration, flipped between
    bursts while the batcher idles.

    Byte-identical text is REQUIRED between the two depths of every
    paired round (same prompts per pair; within-pair order alternates
    so page-cache warmth / the tunnel's replay cache cannot
    systematically favor one depth). tok/s gates with the PR-5 dual
    gate (per-leg bests within 2% OR paired-median ≤ 2%, escalating
    extra rounds): on the 1-core CPU box host and "device" share the
    core, so depth 2 is a throughput wash — there, the mechanical
    signal is `gateway_sched_overhead_seconds` collapsing (overlapped
    dispatches observe 0), which the leg gates on directly; on a chip
    the hidden host time becomes wall-clock. A steps_per_sync × depth
    grid (fresh batcher per sync value — steps_per_sync is baked into
    the compiled program) re-serves ONE fixed prompt set per cell and
    asserts text equality across the whole grid (the PRNG stream is
    (seed, index): chunk- and depth-invariant); grid tok/s is
    informational only (repeat prompts can hit the tunnel's replay
    cache).
    """
    from statistics import median

    from llm_consensus_tpu.server.metrics import SCHED_OVERHEAD_SECONDS
    from llm_consensus_tpu.serving.continuous import (
        ContinuousBatcher,
        ContinuousConfig,
    )

    pg = 64
    salt = int(time.time() * 1e6) % 999983
    header_target = max(args.prompt_len, 2 * pg + 16)
    n = args.serve_requests
    longest = header_target + 64
    buckets = [64]
    while buckets[-1] < longest:
        buckets.append(buckets[-1] * 2)
    pages_per_seq = _serve_pages_per_seq(
        buckets[-1], args.new_tokens, args.serve_chunk, pg
    )
    n_pages = 1 + args.serve_slots * pages_per_seq * 2
    header = f"Panel header {salt}: " + "shared context " * (
        -(-header_target // 15)
    )

    def make_batcher(sync):
        return ContinuousBatcher(
            cfg,
            params,
            config=ContinuousConfig(
                max_slots=args.serve_slots,
                page_size=pg,
                n_pages=n_pages,
                pages_per_seq=pages_per_seq,
                max_new_tokens=args.new_tokens,
                seq_buckets=tuple(buckets),
                steps_per_sync=sync,
                prefill_chunk=args.serve_prefill_chunk or 64,
                share_prefix=True,
                pipeline_depth=2,
            ),
        )

    def leg(batcher, depth, prompts):
        """One burst at the given depth; returns (texts, tok/s, mean
        un-overlapped overhead per dispatch, bucket-resolution p50)."""
        # Depth is read per loop iteration; the batcher idles between
        # bursts, so flipping it here is race-free (the loop drains
        # any excess in-flight depth before the next dispatch).
        batcher.config.pipeline_depth = depth
        h0 = (SCHED_OVERHEAD_SECONDS.sum, SCHED_OVERHEAD_SECONDS.count)
        cum0 = SCHED_OVERHEAD_SECONDS.cumulative()
        t0 = time.perf_counter()
        futs = [
            batcher.submit(p, max_new_tokens=args.new_tokens)
            for p in prompts
        ]
        results = [f.result(timeout=600) for f in futs]
        wall = time.perf_counter() - t0
        d_sum = SCHED_OVERHEAD_SECONDS.sum - h0[0]
        d_cnt = SCHED_OVERHEAD_SECONDS.count - h0[1]
        cum1 = SCHED_OVERHEAD_SECONDS.cumulative()
        total = cum1[-1][1] - cum0[-1][1]
        p50 = 0.0
        if total > 0:
            for (le, a), (_, b) in zip(cum1, cum0):
                if a - b >= 0.5 * total:
                    p50 = le
                    break
        toks = sum(r.num_tokens for r in results)
        return (
            [r.text for r in results],
            toks / wall,
            d_sum / d_cnt if d_cnt else 0.0,
            p50,
        )

    runs = {1: [], 2: []}  # depth -> [(tok/s, mean_ov, p50)]
    diverged = False
    batcher = make_batcher(args.serve_chunk)
    try:
        batcher.submit(
            header + "warmup tail", max_new_tokens=args.new_tokens
        ).result(timeout=600)

        def paired_round(r):
            nonlocal diverged
            prompts = [
                header + f"Q{i}-r{r}: item {i * 37 % 101}?" for i in range(n)
            ]
            order = (1, 2) if r % 2 == 0 else (2, 1)
            got = {}
            for depth in order:
                texts, tps, mean_ov, p50 = leg(batcher, depth, prompts)
                got[depth] = texts
                runs[depth].append((tps, mean_ov, p50))
            if got[1] != got[2]:
                diverged = True

        def gate_ok():
            # PR-5 dual gate: per-leg bests within 2% OR paired-median
            # regression <= 2% (smoke legs on the shared 1-core box
            # jitter far past 2%; a real regression moves both).
            best1 = max(t for t, _, _ in runs[1])
            best2 = max(t for t, _, _ in runs[2])
            paired = 100.0 * median(
                1.0 - b[0] / a[0] for a, b in zip(runs[1], runs[2])
            )
            return best2 >= 0.98 * best1 or paired <= 2.0

        for r in range(max(1, args.pipeline_ab_rounds)):
            paired_round(r)
        extra = 0
        while not gate_ok() and extra < 3:
            extra += 1
            print(
                f"[bench] depth-2 best {max(t for t, _, _ in runs[2]):.0f} "
                f"vs depth-1 best {max(t for t, _, _ in runs[1]):.0f} "
                f"tok/s fails the dual gate; extra round {extra}",
                file=sys.stderr,
            )
            paired_round(args.pipeline_ab_rounds + extra)
    finally:
        batcher.close()

    # steps_per_sync x depth grid: one FIXED prompt set across every
    # cell — the cross-cell text equality is the chunk/depth PRNG
    # invariance demonstrated end to end (tok/s informational only).
    grid_note = ""
    grid_ok = True
    if not args.no_pipeline_grid:
        grid_prompts = [
            header + f"G{i}: item {i * 37 % 101}?" for i in range(n)
        ]
        cells = []
        grid_texts = None
        for sync in (1, 4):
            gb = make_batcher(sync)
            try:
                gb.submit(
                    header + "grid warmup", max_new_tokens=args.new_tokens
                ).result(timeout=600)
                for depth in (1, 2):
                    texts, tps, mean_ov, _ = leg(gb, depth, grid_prompts)
                    cells.append(
                        f"sync{sync}/d{depth} {tps:.0f} tok/s "
                        f"ov {1e3 * mean_ov:.2f} ms"
                    )
                    if grid_texts is None:
                        grid_texts = texts
                    elif texts != grid_texts:
                        grid_ok = False
            finally:
                gb.close()
        grid_note = f", grid[{'; '.join(cells)}], grid text equal={grid_ok}"

    best1 = max(t for t, _, _ in runs[1])
    best2 = max(t for t, _, _ in runs[2])
    ov1 = median(m for _, m, _ in runs[1])
    ov2 = median(m for _, m, _ in runs[2])
    p50_1 = median(p for _, _, p in runs[1])
    p50_2 = median(p for _, _, p in runs[2])
    overlap_gain = ov1 > ov2 and p50_2 <= p50_1
    _emit(
        {
            "metric": f"serving tok/s, pipelined decode dispatch depth 2 "
            f"({cfg.name}, {len(runs[2])}x{n} reqs, "
            f"slots={args.serve_slots}, decode {args.new_tokens} @ "
            f"~{header_target} shared prompt, chunk={args.serve_chunk}, "
            f"depth-1 best {best1:.0f} tok/s, sched-overhead/dispatch "
            f"d1 {1e3 * ov1:.2f} -> d2 {1e3 * ov2:.2f} ms "
            f"(p50 {1e3 * p50_1:.1f} -> {1e3 * p50_2:.1f} ms), "
            f"text unchanged={not diverged}{grid_note})",
            "value": round(best2, 2),
            "unit": "tokens/sec",
            "vs_baseline": round(best2 / max(best1, 1e-9), 4),
        },
        args.out,
    )
    if diverged or not grid_ok:
        print(
            "[bench] GENERATED TEXT DIVERGED between pipeline depths — "
            "pipelining regression",
            file=sys.stderr,
        )
        return 1
    if not gate_ok():
        print(
            f"[bench] depth-2 tok/s fails the dual gate (best ratio "
            f"{best2 / max(best1, 1e-9):.4f}) — pipelining regression",
            file=sys.stderr,
        )
        return 1
    if not overlap_gain:
        print(
            f"[bench] sched-overhead did not collapse under depth 2 "
            f"(mean {1e3 * ov1:.2f} -> {1e3 * ov2:.2f} ms, p50 "
            f"{1e3 * p50_1:.1f} -> {1e3 * p50_2:.1f} ms) — the overlap "
            "window is not engaging",
            file=sys.stderr,
        )
        return 1
    return 0


def _bench_serving_ragged_ab(args, cfg, params) -> int:
    """Fused scheduler step A/B (PR 8): one ragged device program per
    scheduler iteration vs the PR-7 "one chunk program + one decode
    program" split.

    The burst is PREFILL-HEAVY and MIXED on purpose: half the requests
    share a panel header (prefix-registry hits — short chunked
    prefills), half carry unique headers (registry misses — full
    chunked prefills), all through one batcher with fewer slots than
    requests, so admissions keep trickling in while earlier requests
    decode and the scheduler constantly faces the chunk+decode
    iteration the fusion targets. ``ragged_attention`` is host-loop
    policy read per iteration, flipped between bursts on the idle
    batcher (the pipeline-AB pattern).

    Gates: per-pair byte-identical text (REQUIRED — the fused program
    and the ragged kernel are pure restructurings), fused-leg device
    programs per scheduler iteration == 1.0 (counted via
    gateway_device_programs_total / the work-iteration denominator),
    and the unfused leg ratio > 1 (the burst really exercised
    concurrent prefill+decode — otherwise the A/B proved nothing).
    tok/s is reported per leg (informational: on the 1-core CPU box
    host and device share the core; the chip rows land with the next
    bench round). A pipeline-depth {1,2} grid repeats the parity
    check, and a sliding-window sub-leg re-runs it on a windowed
    config — the configs that used to FALL BACK out of the grouped
    kernel now ride the same ragged program.
    """
    from llm_consensus_tpu.serving.continuous import (
        ContinuousBatcher,
        ContinuousConfig,
    )

    pg = 64
    salt = int(time.time() * 1e6) % 999983
    header_target = max(args.prompt_len, 2 * pg + 16)
    n = args.serve_requests
    longest = header_target + 64
    buckets = [64]
    while buckets[-1] < longest:
        buckets.append(buckets[-1] * 2)
    chunk = args.serve_prefill_chunk or 64
    pages_per_seq = _serve_pages_per_seq(
        buckets[-1], args.new_tokens, args.serve_chunk, pg
    )
    n_pages = 1 + args.serve_slots * pages_per_seq * 2
    header = f"Panel header {salt}: " + "shared context " * (
        -(-header_target // 15)
    )

    def make_batcher(model_cfg):
        return ContinuousBatcher(
            model_cfg,
            params,
            config=ContinuousConfig(
                max_slots=args.serve_slots,
                page_size=pg,
                n_pages=n_pages,
                pages_per_seq=pages_per_seq,
                max_new_tokens=args.new_tokens,
                seq_buckets=tuple(buckets),
                steps_per_sync=args.serve_chunk,
                prefill_chunk=chunk,
                share_prefix=True,
            ),
        )

    def mixed_prompts(tag):
        # Half panel-shaped (shared header, registry hits), half
        # unique-header (full chunked prefills) — the mixed load whose
        # chunk+decode iterations the fusion collapses.
        out = []
        for i in range(n):
            if i % 2 == 0:
                out.append(header + f"Q{tag}-{i}: item {i * 37 % 101}?")
            else:
                out.append(
                    f"Unique header {salt}-{tag}-{i}: "
                    + f"context {i} " * (-(-header_target // 11))
                    + "tail?"
                )
        return out

    def leg(batcher, ragged, prompts):
        """One burst; returns (texts, tok/s, programs-per-iteration)."""
        batcher.config.ragged_attention = ragged
        return _burst_leg(batcher, prompts, args.new_tokens)

    runs = {False: [], True: []}  # ragged -> [(tok/s, ratio)]
    diverged = False
    batcher = make_batcher(cfg)
    try:
        batcher.submit(
            header + "warmup tail", max_new_tokens=args.new_tokens
        ).result(timeout=600)
        # A CONCURRENT warmup burst compiles the fused program family
        # too (a chunk only rides a dispatch when rows are decoding) —
        # otherwise the first fused leg times XLA compilation.
        for ragged in (True, False):
            batcher.config.ragged_attention = ragged
            futs = [
                batcher.submit(
                    header + f"warm {ragged} {i}",
                    max_new_tokens=args.new_tokens,
                )
                for i in range(min(4, n))
            ]
            for f in futs:
                f.result(timeout=600)
        for r in range(max(1, args.ragged_ab_rounds)):
            prompts = mixed_prompts(f"r{r}")
            order = (False, True) if r % 2 == 0 else (True, False)
            got = {}
            for ragged in order:
                texts, tps, ratio = leg(batcher, ragged, prompts)
                got[ragged] = texts
                runs[ragged].append((tps, ratio))
            if got[False] != got[True]:
                diverged = True
        # Pipeline-depth grid: the fused fetch-side bookkeeping must
        # stay byte-identical under the PR-6 overlap window.
        grid_cells = []
        grid_ok = True
        grid_prompts = mixed_prompts("g")
        grid_texts = None
        for depth in (1, 2):
            batcher.config.pipeline_depth = depth
            for ragged in (False, True):
                texts, tps, ratio = leg(batcher, ragged, grid_prompts)
                grid_cells.append(
                    f"d{depth}/{'on' if ragged else 'off'} {tps:.0f} tok/s "
                    f"prog/iter {ratio:.2f}"
                )
                if grid_texts is None:
                    grid_texts = texts
                elif texts != grid_texts:
                    grid_ok = False
        batcher.config.pipeline_depth = 2
    finally:
        batcher.close()

    # Sliding-window sub-leg: the config that used to fall back out of
    # the grouped kernel entirely — same parity contract, same kernel.
    win_ok = True
    win_note = ""
    if cfg.sliding_window == 0:
        win_cfg = cfg.with_(sliding_window=96)
        wb = make_batcher(win_cfg)
        try:
            wb.submit(
                header + "win warmup", max_new_tokens=args.new_tokens
            ).result(timeout=600)
            wprompts = mixed_prompts("w")[: max(4, n // 2)]
            wtexts = {}
            for ragged in (False, True):
                wtexts[ragged], _, wratio = leg(wb, ragged, wprompts)
            win_ok = wtexts[False] == wtexts[True]
            win_note = (
                f", window96 text equal={win_ok} "
                f"(fused prog/iter {wratio:.2f})"
            )
        finally:
            wb.close()

    best_off = max(t for t, _ in runs[False])
    best_on = max(t for t, _ in runs[True])
    # Fused leg: WORST round gates (max — target is 1.0, higher means
    # a round where the fusion failed to engage; one good round must
    # not mask it). Unfused leg: ANY round above 1.0 is the sizing
    # evidence we need (the burst really produced chunk+decode
    # iterations) — scheduler timing can serialize an individual round
    # on a loaded box, which is noise, not a regression.
    ratio_on = max(r for _, r in runs[True])
    ratio_off = max(r for _, r in runs[False])
    _emit(
        {
            "metric": f"serving tok/s, fused ragged scheduler step "
            f"({cfg.name}, {len(runs[True])}x{n} mixed reqs, "
            f"slots={args.serve_slots}, decode {args.new_tokens} @ "
            f"~{header_target} prompts, chunk={chunk}, "
            f"programs/iteration {ratio_off:.2f} -> {ratio_on:.2f}, "
            f"unfused best {best_off:.0f} tok/s, "
            f"text unchanged={not diverged}, "
            f"grid[{'; '.join(grid_cells)}], grid text equal={grid_ok}"
            f"{win_note})",
            "value": round(best_on, 2),
            "unit": "tokens/sec",
            "vs_baseline": round(best_on / max(best_off, 1e-9), 4),
        },
        args.out,
    )
    if diverged or not grid_ok or not win_ok:
        print(
            "[bench] GENERATED TEXT DIVERGED between ragged_attention "
            "on/off — fused-step regression",
            file=sys.stderr,
        )
        return 1
    if ratio_on > 1.0 + 1e-9:
        print(
            f"[bench] fused leg ran {ratio_on:.3f} device programs per "
            "scheduler iteration (target 1.0) — fusion not engaging",
            file=sys.stderr,
        )
        return 1
    if ratio_off <= 1.0:
        print(
            "[bench] unfused leg never hit a chunk+decode iteration "
            f"(programs/iteration {ratio_off:.3f}) — the burst did not "
            "exercise the fusion; resize the leg",
            file=sys.stderr,
        )
        return 1
    return 0


def _bench_serving_mesh_ab(args, cfg, params) -> int:
    """Mesh-native serving hot path A/B (PR 13).

    The PR-8 mixed panel burst (shared headers + unique prefixes)
    served by a dp2×mp2 MESH batcher vs a single-device batcher —
    every serving feature now engages on the mesh, so the contract is
    the strong one: byte-identical text per pair, and the mesh leg
    runs EXACTLY one device program per scheduler work iteration
    (fused ragged dispatch engaged — the number that used to be
    unreachable because fusion fell back off-mesh). Two batchers, one
    per topology (a mesh is constructor state, not a live lever); the
    prompts of each round are shared verbatim so the text gate is a
    strict pair-wise equality.

    tok/s is reported per leg through the PR-5 dual gate at a
    GENEROUS band: on this CPU box the mesh is 8 simulated host
    devices time-slicing the same cores, so the leg can only gate
    against pathological collapse (per-step recompiles, a broken
    collective), not parity — the chip rows land with the next bench
    round.
    """
    from llm_consensus_tpu.models.transformer import init_params
    from llm_consensus_tpu.parallel.mesh import MeshConfig, make_mesh
    from llm_consensus_tpu.serving.continuous import (
        ContinuousBatcher,
        ContinuousConfig,
    )

    # Byte parity across TOPOLOGIES (unlike the single-batcher A/B
    # legs, whose two bursts share one reduction order) needs
    # order-stable numerics: bf16-input matmuls at the fast default
    # precision leave logit near-ties that the mesh's psum reordering
    # flips. fp32 params + full-precision accumulation keep the
    # greedy argmax stable — the same regime the tier-1 parity grid
    # pins (tests/test_mesh_serving.py). Both legs share the regime,
    # so the tok/s comparison stays fair. main() dispatches this leg
    # BEFORE its param build (``params`` arrives None) — this is the
    # one place the leg's tree is created.
    del params
    jax.config.update("jax_default_matmul_precision", "highest")
    params = init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32)

    if len(jax.devices()) < 4:
        _emit(
            {
                "metric": "serving tok/s, mesh-native hot path "
                f"({cfg.name}): SKIPPED — needs >= 4 devices, have "
                f"{len(jax.devices())} (backend initialized before "
                "the device-count flag could apply)",
                "value": 0.0,
                "unit": "tokens/sec",
                "vs_baseline": 0.0,
                "status": "mesh-unavailable",
            },
            args.out,
        )
        return 1
    mesh = make_mesh(
        MeshConfig(data=2, model=2), devices=jax.devices()[:4]
    )

    pg = 64
    salt = int(time.time() * 1e6) % 999983
    header_target = max(args.prompt_len, 2 * pg + 16)
    n = args.serve_requests
    longest = header_target + 64
    buckets = [64]
    while buckets[-1] < longest:
        buckets.append(buckets[-1] * 2)
    chunk = args.serve_prefill_chunk or 64
    pages_per_seq = _serve_pages_per_seq(
        buckets[-1], args.new_tokens, args.serve_chunk, pg
    )
    n_pages = 1 + args.serve_slots * pages_per_seq * 2
    # n_pages and max_slots must divide the data axis (2).
    n_pages += n_pages % 2
    slots = args.serve_slots + args.serve_slots % 2
    header = f"Mesh panel header {salt}: " + "shared context " * (
        -(-header_target // 15)
    )

    def make_batcher(topo_mesh):
        return ContinuousBatcher(
            cfg,
            params,
            config=ContinuousConfig(
                max_slots=slots,
                page_size=pg,
                n_pages=n_pages,
                pages_per_seq=pages_per_seq,
                max_new_tokens=args.new_tokens,
                seq_buckets=tuple(buckets),
                steps_per_sync=args.serve_chunk,
                prefill_chunk=chunk,
                share_prefix=True,
            ),
            mesh=topo_mesh,
        )

    def mixed_prompts(tag):
        out = []
        for i in range(n):
            if i % 2 == 0:
                out.append(header + f"Q{tag}-{i}: item {i * 37 % 101}?")
            else:
                out.append(
                    f"Unique header {salt}-{tag}-{i}: "
                    + f"context {i} " * (-(-header_target // 11))
                    + "tail?"
                )
        return out

    def leg(batcher, prompts):
        """One burst; returns (texts, tok/s, programs/iteration)."""
        return _burst_leg(batcher, prompts, args.new_tokens)

    batchers = {False: make_batcher(None), True: make_batcher(mesh)}
    runs = {False: [], True: []}  # on_mesh -> [(tok/s, ratio)]
    diverged = False
    try:
        # Concurrent warmup on each topology: compiles the fused
        # program family (a chunk only rides a dispatch when rows are
        # decoding) so the first timed round isn't XLA compilation.
        for on_mesh, b in batchers.items():
            futs = [
                b.submit(
                    header + f"warm {on_mesh} {i}",
                    max_new_tokens=args.new_tokens,
                )
                for i in range(min(4, n))
            ]
            for f in futs:
                f.result(timeout=600)
        for r in range(max(1, args.mesh_ab_rounds)):
            prompts = mixed_prompts(f"r{r}")
            order = (False, True) if r % 2 == 0 else (True, False)
            got = {}
            for on_mesh in order:
                texts, tps, ratio = leg(batchers[on_mesh], prompts)
                got[on_mesh] = texts
                runs[on_mesh].append((tps, ratio))
            if got[False] != got[True]:
                diverged = True
    finally:
        for b in batchers.values():
            b.close()

    best_single = max(t for t, _ in runs[False])
    best_mesh = max(t for t, _ in runs[True])
    ratio_mesh = max(r for _, r in runs[True])  # worst round gates
    stats_mesh = {
        "data": int(mesh.shape.get("data", 1)),
        "model": int(mesh.shape.get("model", 1)),
    }
    # Dual gate at a generous band: 75% collapse allowance on the
    # CPU-simulated mesh (collective emulation shares the cores); a
    # broken mesh path (per-step recompiles) blows through it.
    tput_ok = _dual_gate_ok(
        [t for t, _ in runs[False]], [t for t, _ in runs[True]], pct=75.0
    )
    # Gates decide status BEFORE the emit (the rounds-leg convention):
    # a regressed run must never land in the bench history as "ok".
    status = "ok"
    if diverged:
        status = "failed: text diverged between mesh and single device"
    elif ratio_mesh > 1.0 + 1e-9:
        status = (
            f"failed: mesh programs/iteration {ratio_mesh:.3f} "
            "(target 1.0) — fused dispatch not engaging"
        )
    elif not tput_ok:
        status = (
            f"failed: mesh tok/s collapsed past the generous band "
            f"(best {best_mesh:.0f} vs single {best_single:.0f})"
        )
    _emit(
        {
            "metric": f"serving tok/s, mesh-native hot path "
            f"({cfg.name}, dp{stats_mesh['data']}×mp"
            f"{stats_mesh['model']} vs single device, "
            f"{len(runs[True])}x{n} mixed reqs, slots={slots}, "
            f"decode {args.new_tokens} @ ~{header_target} prompts, "
            f"chunk={chunk}, mesh programs/iteration "
            f"{ratio_mesh:.2f}, single best {best_single:.0f} tok/s, "
            f"text equal={not diverged})",
            "value": round(best_mesh, 2),
            "unit": "tokens/sec",
            "vs_baseline": round(best_mesh / max(best_single, 1e-9), 4),
            "status": status,
        },
        args.out,
    )
    if status != "ok":
        print(f"[bench] serve-mesh leg: {status}", file=sys.stderr)
        return 1
    return 0


def _bench_serving_spec_ab(args, cfg, params) -> int:
    """Speculative decoding inside the batcher A/B (PR 9).

    The burst is the consensus propose round's shape: N greedy
    requests over ONE shared header with an identical question —
    prefix KV dedups at admission (PR 2), decode attention groups
    (PR 3), and under speculation the whole panel rides ONE draft
    stream (mates' committed texts agree, so each round drafts once
    and every mate verifies the donor's proposals). ``spec_decode`` is
    host-loop policy read per iteration, flipped between bursts on
    the idle batcher (the pipeline/ragged-AB pattern; a flip drains
    the dispatch pipeline, so plain and spec programs never share a
    window).

    Gates: per-pair byte-identical greedy text (REQUIRED — greedy
    accept emits the target argmax chain for ANY draft), verified
    tokens per spec device program > 1.0 on the spec leg (counted via
    gateway_device_programs_total{kind=spec} and the generated-token
    delta: > 1.0 is speculation beating the one-token-per-program
    roofline; the draft must actually agree with the target — run
    'self' or a TRAINED pair, a random-weight preset is the
    pessimistic floor and will fail this gate), and the panel's
    shared streams drafting FEWER tokens per generated token than a
    unique-prompt control burst (the amortization realized). tok/s
    per leg and the mean per-round acceptance are reported
    (informational on the 1-core CPU box; chip rows land with the
    next bench round).
    """
    import jax.numpy as jnp

    from llm_consensus_tpu.models.configs import get_config
    from llm_consensus_tpu.models.transformer import init_params
    from llm_consensus_tpu.serving.continuous import (
        ContinuousBatcher,
        ContinuousConfig,
    )

    if args.serve_target_ckpt:
        from llm_consensus_tpu.checkpoint.io import (
            restore_params_for_inference,
        )

        params, _ = restore_params_for_inference(
            cfg, args.serve_target_ckpt, jnp.bfloat16
        )
    if args.serve_draft == "self":
        d_cfg, d_params = cfg, params
    else:
        d_cfg = get_config(args.serve_draft).with_(use_pallas=cfg.use_pallas)
        if d_cfg.vocab_size != cfg.vocab_size:
            print(
                f"[bench] draft {d_cfg.name} vocab {d_cfg.vocab_size} != "
                f"target vocab {cfg.vocab_size}",
                file=sys.stderr,
            )
            return 1
        if args.serve_draft_ckpt:
            from llm_consensus_tpu.checkpoint.io import (
                restore_params_for_inference,
            )

            d_params, _ = restore_params_for_inference(
                d_cfg, args.serve_draft_ckpt, jnp.bfloat16
            )
        else:
            d_params = init_params(
                d_cfg, jax.random.PRNGKey(1), dtype=jnp.bfloat16
            )

    pg = 64
    k_spec = max(1, args.k_spec)
    salt = int(time.time() * 1e6) % 999983
    header_target = max(args.prompt_len, 2 * pg + 16)
    n = args.serve_requests
    longest = header_target + 64
    buckets = [64]
    while buckets[-1] < longest:
        buckets.append(buckets[-1] * 2)
    chunk = args.serve_prefill_chunk or 64
    # Page budget: the speculative round's k+1-token overshoot replaces
    # steps_per_sync (=1 here — the verify round IS the multi-token
    # step) as the per-program write unit (_round_tokens).
    pages_per_seq = _serve_pages_per_seq(
        buckets[-1], args.new_tokens, k_spec + 1, pg
    )
    n_pages = 1 + args.serve_slots * pages_per_seq * 2
    header = f"Panel header {salt}: " + "shared context " * (
        -(-header_target // 15)
    )
    question = " The panel's one question?"

    batcher = ContinuousBatcher(
        cfg,
        params,
        config=ContinuousConfig(
            max_slots=args.serve_slots,
            page_size=pg,
            n_pages=n_pages,
            pages_per_seq=pages_per_seq,
            max_new_tokens=args.new_tokens,
            seq_buckets=tuple(buckets),
            steps_per_sync=1,
            prefill_chunk=chunk,
            share_prefix=True,
            spec_k=k_spec,
        ),
        draft=(d_cfg, d_params),
    )

    def leg(spec_on, prompts):
        """One burst; returns (texts, tok/s, per-leg stats deltas)."""
        batcher.config.spec_decode = spec_on
        _quiesce_batcher(batcher)
        s0 = batcher.stats()
        t0 = time.perf_counter()
        futs = [
            batcher.submit(p, max_new_tokens=args.new_tokens)
            for p in prompts
        ]
        results = [f.result(timeout=600) for f in futs]
        wall = time.perf_counter() - t0
        _quiesce_batcher(batcher)
        s1 = batcher.stats()
        d = {k: s1[k] - s0[k] for k in (
            "generated_tokens",
            "device_programs_spec",
            "device_programs_decode",
            "spec_draft_tokens",
            "spec_accepted_tokens",
            "spec_acceptance_sum",
            "spec_acceptance_count",
            "spec_shared_draft_rows",
        )}
        toks = sum(r.num_tokens for r in results)
        return [r.text for r in results], toks / wall, d

    panel = [header + question] * n
    runs = {False: [], True: []}  # spec_on -> [(tok/s, stats delta)]
    diverged = False
    try:
        # Warmup compiles both program families (plain decode, the
        # spec draft/verify program, draft prefill-chunk mirrors).
        for on in (True, False):
            batcher.config.spec_decode = on
            futs = [
                batcher.submit(
                    header + f" warm {on} {i}",
                    max_new_tokens=args.new_tokens,
                )
                for i in range(min(4, n))
            ]
            for f in futs:
                f.result(timeout=600)
        for r in range(max(1, args.spec_ab_rounds)):
            order = (False, True) if r % 2 == 0 else (True, False)
            got = {}
            for on in order:
                texts, tps, d = leg(on, panel)
                got[on] = texts
                runs[on].append((tps, d))
            if got[False] != got[True]:
                diverged = True
        # Unique-prompt control (spec ON): prompts distinct from byte 0
        # — no shared pages, no groups, every row drafts for itself.
        # The panel's draft-tokens-per-generated-token must come in
        # BELOW this (the shared-stream amortization realized).
        unique = [
            f"{i} unique header {salt}-{i}: " + f"context {i} " * 8
            + "own question?"
            for i in range(n)
        ]
        _, _, d_uniq = leg(True, unique)
    finally:
        batcher.close()

    best_off = max(t for t, _ in runs[False])
    best_on = max(t for t, _ in runs[True])
    spec_tot = {
        k: sum(d[k] for _, d in runs[True])
        for k in runs[True][0][1]
    }
    # Verified tokens per spec program: WORST round gates (speculation
    # must beat one-token-per-program every round, not on average).
    # Each request's first token is sampled from prefill logits, not
    # emitted by a spec program — subtract the leg's request count or
    # a leg truly yielding < 1 token/program could still clear 1.0.
    tpp = min(
        (d["generated_tokens"] - n) / max(1, d["device_programs_spec"])
        for _, d in runs[True]
    )
    acc = spec_tot["spec_acceptance_sum"] / max(
        1, spec_tot["spec_acceptance_count"]
    )
    rate_panel = spec_tot["spec_draft_tokens"] / max(
        1, spec_tot["generated_tokens"]
    )
    rate_uniq = d_uniq["spec_draft_tokens"] / max(
        1, d_uniq["generated_tokens"]
    )
    _emit(
        {
            "metric": f"serving tok/s, speculative batcher "
            f"({cfg.name} + draft {d_cfg.name}, "
            f"{len(runs[True])}x{n} panel reqs, slots={args.serve_slots}, "
            f"k={k_spec}, decode {args.new_tokens} @ ~{header_target} "
            f"shared prompts, verified tokens/program {tpp:.2f}, "
            f"acceptance {acc:.3f}, draft tokens/generated token "
            f"panel {rate_panel:.2f} vs unique {rate_uniq:.2f}, "
            f"shared stream rows {spec_tot['spec_shared_draft_rows']}, "
            f"spec-off best {best_off:.0f} tok/s, "
            f"text unchanged={not diverged})",
            "value": round(best_on, 2),
            "unit": "tokens/sec",
            "vs_baseline": round(best_on / max(best_off, 1e-9), 4),
        },
        args.out,
    )
    if diverged:
        print(
            "[bench] GENERATED TEXT DIVERGED between spec_decode on/off "
            "— speculative-decoding regression",
            file=sys.stderr,
        )
        return 1
    if tpp <= 1.0:
        print(
            f"[bench] spec leg verified {tpp:.3f} tokens per device "
            "program (gate > 1.0) — speculation is not beating plain "
            "decode; check draft/target agreement (run --serve-draft "
            "self or a trained pair)",
            file=sys.stderr,
        )
        return 1
    if rate_panel >= rate_uniq:
        print(
            f"[bench] panel draft rate {rate_panel:.3f} >= unique-"
            f"control rate {rate_uniq:.3f} — shared draft streams did "
            "not amortize; resize the leg",
            file=sys.stderr,
        )
        return 1
    return 0


def _bench_serving_rounds_ab(args, cfg, params) -> int:
    """Multi-round on-device decode A/B (PR 12): the same greedy panel
    burst through ONE batcher flipping ``decode_rounds`` 1 <-> 4
    between bursts. R=4 folds four decode rounds — device-side stop
    scan, sampling, emit/length bookkeeping, early-exit masking — into
    each dispatched program, so the host fetches once per window.

    Gates (rc 1 on failure, mirrored in the JSON ``status``):
    byte-identical text per R=1/R=4 pair; device programs per
    generated token dropping >= 3x at R=4 (the dispatch-count win the
    feature exists for — 4x minus the shared prefill/fused chunk
    programs both legs pay); and the PR-5 dual tok/s gate with the
    PR-10 loadavg-aware escalation (R=4 must not cost throughput on a
    box whose dispatch is already cheap; on the chip it is the win).
    """
    from llm_consensus_tpu.serving.continuous import (
        ContinuousBatcher,
        ContinuousConfig,
    )

    pg = 64
    R = 4
    salt = int(time.time() * 1e6) % 999983
    header_target = max(args.prompt_len, 2 * pg + 16)
    n = args.serve_requests
    longest = header_target + 64
    buckets = [64]
    while buckets[-1] < longest:
        buckets.append(buckets[-1] * 2)
    chunk = args.serve_prefill_chunk or 64
    # Page budget: the R-round window replaces steps_per_sync as the
    # per-program overshoot unit (_round_tokens reads the CONFIG R, so
    # both legs run over the same reservation).
    pages_per_seq = _serve_pages_per_seq(
        buckets[-1], args.new_tokens, R, pg
    )
    n_pages = 1 + args.serve_slots * pages_per_seq * 2
    header = f"Panel header {salt}: " + "shared context " * (
        -(-header_target // 15)
    )
    panel = [
        header + f" Q{i}: item {i * 37 % 101}?" for i in range(n)
    ]

    batcher = ContinuousBatcher(
        cfg,
        params,
        config=ContinuousConfig(
            max_slots=args.serve_slots,
            page_size=pg,
            n_pages=n_pages,
            pages_per_seq=pages_per_seq,
            max_new_tokens=args.new_tokens,
            seq_buckets=tuple(buckets),
            steps_per_sync=1,
            prefill_chunk=chunk,
            share_prefix=True,
            decode_rounds=R,
        ),
    )

    texts_last: dict[bool, list[str]] = {}
    ppt: dict[bool, list[float]] = {True: [], False: []}
    mbu: dict[bool, list[float]] = {True: [], False: []}
    diverged = False

    _PROG_KEYS = tuple(
        f"device_programs_{k}"
        for k in ("fused", "decode", "prefill", "spec", "draft")
    )

    def leg(tag, rounds_on):
        """One burst at R=4 (on) or R=1 (off); returns tok/s and
        accumulates programs-per-token + modeled decode HBM rates."""
        nonlocal diverged
        batcher.config.decode_rounds = R if rounds_on else 1
        _quiesce_batcher(batcher)
        s0 = batcher.stats()
        t0 = time.perf_counter()
        futs = [
            batcher.submit(p, max_new_tokens=args.new_tokens)
            for p in panel
        ]
        results = [f.result(timeout=600) for f in futs]
        wall = time.perf_counter() - t0
        _quiesce_batcher(batcher)
        s1 = batcher.stats()
        toks = s1["generated_tokens"] - s0["generated_tokens"]
        progs = sum(s1[k] - s0[k] for k in _PROG_KEYS)
        ppt[rounds_on].append(progs / max(1, toks))
        secs = s1["mbu_seconds_decode"] - s0["mbu_seconds_decode"]
        mbu[rounds_on].append(
            (s1["mbu_hbm_bytes_decode"] - s0["mbu_hbm_bytes_decode"])
            / max(secs, 1e-9)
        )
        texts_last[rounds_on] = [r.text for r in results]
        if len(texts_last) == 2 and texts_last[True] != texts_last[False]:
            diverged = True
        return sum(r.num_tokens for r in results) / wall

    try:
        # Warmup compiles both program families (legacy one-round,
        # R-round masked scan, their fused chunk variants).
        for on in (True, False):
            batcher.config.decode_rounds = R if on else 1
            futs = [
                batcher.submit(
                    header + f" warm {on} {i}",
                    max_new_tokens=args.new_tokens,
                )
                for i in range(min(4, n))
            ]
            for f in futs:
                f.result(timeout=600)
        runs_off, runs_on = _ab_rounds(leg, args.rounds_ab_rounds)
        _ab_escalate(leg, runs_off, runs_on, "decode-rounds")
    finally:
        batcher.close()

    best_off = max(runs_off)
    best_on = max(runs_on)
    # Aggregate programs-per-token per leg (deterministic on an idle
    # box; aggregating keeps one jittered round from gating).
    ppt_off = sum(ppt[False]) / len(ppt[False])
    ppt_on = sum(ppt[True]) / len(ppt[True])
    drop = ppt_off / max(ppt_on, 1e-9)
    tput_ok = _dual_gate_ok(runs_off, runs_on)
    status = "ok"
    if diverged:
        status = "failed: text diverged between R=1 and R=4"
    elif drop < 3.0:
        status = (
            f"failed: programs/token dropped only {drop:.2f}x (gate 3x)"
        )
    elif not tput_ok:
        status = "failed: R=4 tok/s regressed past the dual gate"
    _emit(
        {
            "metric": f"serving tok/s, multi-round decode ({cfg.name}, "
            f"{len(runs_on)}x{n} panel reqs, slots={args.serve_slots}, "
            f"R={R}, decode {args.new_tokens} @ ~{header_target} "
            f"shared prompts, programs/token {ppt_off:.3f} -> "
            f"{ppt_on:.3f} ({drop:.2f}x drop), modeled decode HBM "
            f"{max(mbu[False]) / 1e9:.2f} -> "
            f"{max(mbu[True]) / 1e9:.2f} GB/s, "
            f"R=1 best {best_off:.0f} tok/s, "
            f"text unchanged={not diverged})",
            "value": round(best_on, 2),
            "unit": "tokens/sec",
            "vs_baseline": round(best_on / max(best_off, 1e-9), 4),
            "status": status,
        },
        args.out,
    )
    if status != "ok":
        print(f"[bench] decode-rounds leg: {status}", file=sys.stderr)
        return 1
    return 0


def _autotune_tally(flight_mod, k_full: int) -> tuple[int, int]:
    """Count (spec_k shrinks, rounds decisions) currently resident in
    the flight ring. Called right after warmup AND at the end of the
    adaptive leg — the ring is bounded evict-oldest, and the lone
    warmup shrink of an adversarial-draft run can be evicted by an
    escalated measurement's program events before the final scan."""
    shrinks = rounds_dec = 0
    for e in flight_mod.flight_recorder().events():
        if e.kind != "autotune":
            continue
        if (
            e.meta.get("knob") == "spec_k"
            and e.meta.get("value", k_full) < k_full
        ):
            shrinks += 1
        if e.meta.get("knob") == "rounds":
            rounds_dec += 1
    return shrinks, rounds_dec


def _bench_serving_adaptive(args, cfg, params) -> int:
    """Roofline-adaptive runtime control A/B (PR 15): adaptive mode
    vs the fixed (spec_k x R) knob grid, on ONE batcher.

    The batcher carries an ADVERSARIAL draft (same config, different
    random weights — acceptance ~0, the workload where fixed
    speculation is pure waste) and serves the same mixed greedy burst
    (half panel mates over one shared header, half unique headers)
    under each fixed grid point — speculation on at k in {1, K} and
    off at R in {1, R}, every knob static — then under the adaptive
    controller, which measures the rejects, shrinks the effective k,
    disengages speculation entirely (the PR-9 live-flip drain rules),
    and runs full adaptive-R plain windows, collapsing the final
    windows as the batch approaches its token budgets.

    Gates (rc 1 on failure, mirrored in ``status``): byte-identical
    greedy text across EVERY leg pair (the spec/rounds parity
    contracts compose); adaptive tok/s >= each grid point under the
    PR-5 dual gate with loadavg-aware escalation; >= 1 recorded
    spec_k shrink and >= 1 adaptive-R decision among the flight
    recorder's ``autotune`` events; and zero recompiles after warmup
    — the device-program KIND set and every compile cache (jit trace
    counts + chunk/fused wrapper families) stay stable across the
    steering bursts (the controller's menus are bounded by
    construction; this proves it).
    """
    import jax.numpy as jnp

    from llm_consensus_tpu.models.transformer import init_params
    from llm_consensus_tpu.serving import flight as _flight
    from llm_consensus_tpu.serving.continuous import (
        ContinuousBatcher,
        ContinuousConfig,
    )
    from llm_consensus_tpu.serving.control import (
        AdaptiveController,
        ControlConfig,
    )

    pg = 64
    R = 4
    K = max(2, args.k_spec)
    salt = int(time.time() * 1e6) % 999983
    header_target = max(args.prompt_len, 2 * pg + 16)
    # ONE admission cohort (n <= slots): every prompt admits up front
    # and the batch drains together, so near-stop windows happen only
    # at the burst tail with no chunk riding them — the compiled-trace
    # set the cache gate compares is deterministic (a mid-burst
    # admission could otherwise fuse a chunk into a capped window in
    # one burst and not the next).
    n = min(args.serve_requests, args.serve_slots)
    # Off the R grid so the final windows genuinely cap (max remaining
    # budget < R at the tail => the controller's near-stop decision).
    nt = args.new_tokens + (R // 2 if args.new_tokens % R == 0 else 0)
    longest = header_target + 64
    buckets = [64]
    while buckets[-1] < longest:
        buckets.append(buckets[-1] * 2)
    chunk = args.serve_prefill_chunk or 64
    pages_per_seq = _serve_pages_per_seq(
        buckets[-1], nt, max(R, K + 1), pg
    )
    n_pages = 1 + args.serve_slots * pages_per_seq * 2
    header = f"Panel header {salt}: " + "shared context " * (
        -(-header_target // 15)
    )
    prompts = [
        (
            header + " The panel's one question?"
            if i % 2 == 0
            else f"Unique header {salt + i}: "
            + "own context " * (-(-header_target // 12))
            + f" Q{i}?"
        )
        for i in range(n)
    ]

    # Adversarial draft: same config family (one vocab), different
    # random weights — proposes garbage, accepts ~nothing. The
    # workload adaptive control exists for: fixed spec pays full
    # verify width per round for ~1 token, fixed R=1 pays a dispatch
    # per token, and only the controller discovers both at runtime.
    d_params = init_params(cfg, jax.random.PRNGKey(1), dtype=jnp.bfloat16)
    ctrl = AdaptiveController(
        ControlConfig(
            accept_min_samples=2,
            # No re-probe during the measured bursts: regrow is the
            # tier-1 suite's contract; the bench isolates the steady
            # state (a probe is one spec window + a draft catch-up
            # replay — correct, but a moving target for the cache
            # gate).
            spec_probe_every=100_000,
            # Slow rounds + depth probes likewise: a probe runs the
            # losing arm (or a lower depth) for a burst-sized window
            # on this smoke's sizes, and the grid points it gates
            # against never pay one — probe robustness is the tier-1
            # unit suite's contract, steady-state throughput is this
            # gate.
            rounds_probe_stretches=100,
            depth_probe_every=100_000,
            # Smoke-sized stretches: an R-window burst at this leg's
            # token budget yields only ~4 countable windows (the
            # anchor fetch and each arm's first-ever window are
            # discarded), so the default rounds_stretch_min=5 would
            # discard EVERY R-arm stretch — the regime could never
            # calibrate its second arm and would run the cold-start
            # choice forever.
            rounds_stretch_windows=8,
            rounds_stretch_min=3,
        )
    )
    batcher = ContinuousBatcher(
        cfg,
        params,
        config=ContinuousConfig(
            max_slots=args.serve_slots,
            page_size=pg,
            n_pages=n_pages,
            pages_per_seq=pages_per_seq,
            max_new_tokens=nt,
            seq_buckets=tuple(buckets),
            steps_per_sync=1,
            prefill_chunk=chunk,
            share_prefix=True,
            spec_k=K,
            decode_rounds=R,
        ),
        draft=(cfg, d_params),
        controller=ctrl,
    )

    # (tag, spec_decode, spec_k, decode_rounds, adaptive?)
    GRID = {
        f"spec-k{K}": (True, K, 1, False),
        "spec-k1": (True, 1, 1, False),
        "plain-r1": (False, K, 1, False),
        f"plain-r{R}": (False, K, R, False),
        "adaptive": (True, K, R, True),
    }
    texts: dict[str, list[str]] = {}
    runs: dict[str, list[float]] = {tag: [] for tag in GRID}

    def leg(tag):
        spec_on, k, rounds, adaptive = GRID[tag]
        # Knob flips are between-bursts events on a quiesced batcher
        # (the spec/rounds legs' pattern); the controller attaches
        # only for the adaptive leg, warm across its bursts.
        batcher.controller = ctrl if adaptive else None
        batcher.config.spec_decode = spec_on
        batcher.config.spec_k = k
        batcher.config.decode_rounds = rounds
        _quiesce_batcher(batcher)
        t0 = time.perf_counter()
        futs = [batcher.submit(p, max_new_tokens=nt) for p in prompts]
        results = [f.result(timeout=600) for f in futs]
        wall = time.perf_counter() - t0
        _quiesce_batcher(batcher)
        texts[tag] = [r.text for r in results]
        return sum(r.num_tokens for r in results) / wall

    def compile_caches() -> dict:
        out = {
            "chunk": len(batcher._jit_chunk),
            "fused": len(batcher._jit_fused),
            "chunk_d": len(batcher._jit_chunk_d),
            "prefill": len(batcher._jit_prefill),
        }
        for name in ("_jit_decode", "_jit_rounds", "_jit_spec"):
            try:
                out[name] = getattr(batcher, name)._cache_size()
            except Exception:  # noqa: BLE001 - older jax without it
                out[name] = -1
        return out

    def program_kinds(s0, s1) -> set:
        return {
            k
            for k in (
                "device_programs_fused",
                "device_programs_decode",
                "device_programs_prefill",
                "device_programs_spec",
                "device_programs_draft",
            )
            if s1[k] - s0[k] > 0
        }

    status = "ok"
    try:
        # Warmup: one burst per grid point compiles every fixed trace
        # family (both spec widths, both round windows, their fused
        # chunk variants); a half-chunk burst compiles the steering
        # menu's other width; TWO adaptive bursts let the controller's
        # EWMAs settle (shrink + disengage land here — the flight scan
        # covers them) and compile anything steering touches.
        warm_s0 = batcher.stats()
        for tag in GRID:
            leg(tag)
        batcher.controller = None
        batcher.config.spec_decode = False
        batcher.config.decode_rounds = 1
        half = chunk // 2
        if half >= 1:
            batcher.config.prefill_chunk = half
            leg_prompts = prompts[: max(2, n // 4)]
            _quiesce_batcher(batcher)
            for f in [
                batcher.submit(p, max_new_tokens=nt) for p in leg_prompts
            ]:
                f.result(timeout=600)
            batcher.config.prefill_chunk = chunk
        # THREE more adaptive bursts: regime calibration (one stretch
        # per arm — the cut-stretch fold at each burst boundary is
        # what hands the rate to the arbiter, so calibrating BOTH
        # arms takes a burst more than the stretch arithmetic alone
        # suggests) and convergence land in warmup, so the measured
        # bursts run the settled regime.
        leg("adaptive")
        leg("adaptive")
        leg("adaptive")
        warm_s1 = batcher.stats()
        warm_kinds = program_kinds(warm_s0, warm_s1)
        caches0 = compile_caches()
        warm_tally = _autotune_tally(_flight, K)

        kinds_new: set = set()
        for r in range(max(1, args.adaptive_ab_rounds)):
            for tag in GRID:
                s0 = batcher.stats()
                runs[tag].append(leg(tag))
                if tag == "adaptive":
                    kinds_new |= program_kinds(s0, batcher.stats())
        # Escalate like the overhead legs: more full rounds while any
        # grid point still beats adaptive past the dual gate.
        extra = 0
        while any(
            not _dual_gate_ok(runs[tag], runs["adaptive"])
            for tag in GRID
            if tag != "adaptive"
        ):
            la, contended = _box_contended()
            budget = 6 if contended else 3
            if extra >= budget:
                break
            extra += 1
            print(
                f"[bench] adaptive: a grid point beats adaptive past "
                f"the dual gate (loadavg "
                f"{la if la is None else round(la, 2)}); extra round "
                f"{extra}/{budget}",
                file=sys.stderr,
            )
            for tag in GRID:
                runs[tag].append(leg(tag))
        caches1 = compile_caches()
    finally:
        batcher.close()

    ref = texts["adaptive"]
    diverged = [t for t, tx in texts.items() if tx != ref]
    # Second scan merged with the post-warmup one via max(): the
    # shrink typically lands ONCE in early warmup (probes are off),
    # and an escalated run records enough program events to evict it
    # from the bounded ring before this final scan — the early scan
    # is the eviction-proof witness, this one catches late decisions.
    shrinks, rounds_dec = (
        max(a, b)
        for a, b in zip(warm_tally, _autotune_tally(_flight, K))
    )
    gates = {
        tag: _dual_gate_ok(runs[tag], runs["adaptive"])
        for tag in GRID
        if tag != "adaptive"
    }
    if diverged:
        status = f"failed: text diverged on legs {diverged}"
    elif not all(gates.values()):
        losing = [t for t, ok in gates.items() if not ok]
        status = f"failed: adaptive lost to grid points {losing}"
    elif shrinks < 1:
        status = "failed: no spec_k shrink recorded in the flight trace"
    elif rounds_dec < 1:
        status = "failed: no adaptive-R decision in the flight trace"
    elif caches1 != caches0:
        status = (
            f"failed: compile caches grew across the steering bursts "
            f"({caches0} -> {caches1})"
        )
    elif not kinds_new <= warm_kinds:
        status = (
            f"failed: new program kinds after warmup "
            f"({sorted(kinds_new - warm_kinds)})"
        )
    best_adaptive = max(runs["adaptive"])
    best_grid = {
        tag: round(max(v), 2) for tag, v in runs.items() if tag != "adaptive"
    }
    _emit(
        {
            "metric": f"serving tok/s, adaptive control ({cfg.name}, "
            f"{n} mixed reqs x {len(runs['adaptive'])} rounds, "
            f"slots={args.serve_slots}, K={K}, R={R}, decode {nt} @ "
            f"~{header_target} prompts, adversarial draft; grid bests "
            f"{best_grid}, spec_k shrinks {shrinks}, rounds decisions "
            f"{rounds_dec}, text unchanged={not diverged})",
            "value": round(best_adaptive, 2),
            # Unit-tagged like every serving A/B leg (PR 12 rule):
            # bench_history's regression verdict compares SAME-UNIT
            # rounds only, so this row never ratios against the
            # chip's tokens/sec/chip headliners.
            "unit": "tokens/sec",
            "vs_baseline": round(
                best_adaptive / max(max(best_grid.values()), 1e-9), 4
            ),
            "status": status,
        },
        args.out,
    )
    if status != "ok":
        print(f"[bench] adaptive leg: {status}", file=sys.stderr)
        return 1
    return 0


def _bench_serving_trace_overhead(args, cfg, params) -> int:
    """Observability A/B: the identical panel-shaped burst with
    request-scoped tracing on vs off (PR 5 acceptance: < 2% tok/s
    overhead).

    ONE batcher serves every leg (shared compiled programs — the A/B
    isolates the tracing instrumentation, not compile variance), each
    leg gets its own salted header (no cross-leg prefix sharing to tilt
    the comparison), and legs alternate off/on for ``--trace-ab-rounds``
    rounds with the gate applied to per-leg bests (CPU smoke runs are
    noisy; best-of damps scheduler jitter without hiding a real
    regression).
    """
    from llm_consensus_tpu.serving.continuous import (
        ContinuousBatcher,
        ContinuousConfig,
    )
    from llm_consensus_tpu.utils import tracing as _tracing

    pg = 64
    salt = int(time.time() * 1e6) % 999983
    header_target = max(args.prompt_len, 2 * pg + 16)
    n = args.serve_requests
    longest = header_target + 64
    buckets = [64]
    while buckets[-1] < longest:
        buckets.append(buckets[-1] * 2)
    pages_per_seq = _serve_pages_per_seq(
        buckets[-1], args.new_tokens, args.serve_chunk, pg
    )
    n_pages = 1 + args.serve_slots * pages_per_seq * 2
    batcher = ContinuousBatcher(
        cfg,
        params,
        config=ContinuousConfig(
            max_slots=args.serve_slots,
            page_size=pg,
            n_pages=n_pages,
            pages_per_seq=pages_per_seq,
            max_new_tokens=args.new_tokens,
            seq_buckets=tuple(buckets),
            steps_per_sync=args.serve_chunk,
            prefill_chunk=args.serve_prefill_chunk or 64,
            share_prefix=True,
        ),
    )

    span_counts: list[int] = []
    # ONE header for every leg (the prefix-AB leg's discipline): the
    # registry reaches its steady state during warmup, so each leg
    # maps the same cached pages and does identical work — per-leg
    # unique headers made registry churn (prefills, evictions) dwarf
    # the µs-scale tracing delta at smoke sizes.
    header = f"Panel header {salt}: " + "shared context " * (
        -(-header_target // 15)
    )

    def leg(tag: str, traced: bool) -> float:
        prompts = [
            header + f"Q{i}-{tag}: item {i * 37 % 101}?" for i in range(n)
        ]
        # Fresh store per leg: the A/B measures span RECORDING, and
        # retained earlier-round traces would tax later legs' GC
        # asymmetrically.
        _tracing.trace_store().clear()
        _tracing.set_enabled(traced)
        try:
            t0 = time.perf_counter()
            futs = []
            for p in prompts:
                trace = (
                    _tracing.trace_store().start("bench", leg=tag)
                    if traced
                    else None
                )
                with _tracing.use_trace(trace):
                    futs.append(
                        batcher.submit(p, max_new_tokens=args.new_tokens)
                    )
            toks = sum(f.result(timeout=600).num_tokens for f in futs)
            wall = time.perf_counter() - t0
        finally:
            _tracing.set_enabled(True)
        if traced:
            span_counts.append(
                sum(t.n_spans for t in _tracing.trace_store().traces(n))
            )
        return toks / wall

    try:
        # Warmup at the BURST's own prompt shape AND with the burst's
        # header: the first measured leg must pay neither the chunk/
        # decode program compile for the burst's seq bucket nor the
        # header's cold prefill (asymmetries the A/B would misread).
        batcher.submit(
            header + "warmup tail", max_new_tokens=args.new_tokens
        ).result(timeout=600)
        runs_off, runs_on = _ab_rounds(leg, args.trace_ab_rounds)
        # Escalate before failing: smoke-size runs jitter more than
        # the 2% gate, and the loadavg guard buys extra pairs when
        # co-running load is detected (the PR-9 flake's cause).
        _ab_escalate(leg, runs_off, runs_on, "trace-overhead")
    finally:
        batcher.close()
    tps_off, tps_on = max(runs_off), max(runs_on)
    overhead_pct = _paired_overhead_pct(runs_off, runs_on)
    spans = span_counts[-1] if span_counts else 0
    _emit(
        {
            "metric": f"serving tok/s, request tracing ON "
            f"({cfg.name}, {max(1, args.trace_ab_rounds)}x{n} reqs, "
            f"slots={args.serve_slots}, decode {args.new_tokens} @ "
            f"~{header_target} shared prompt, tracing OFF "
            f"{tps_off:.0f} tok/s, overhead {overhead_pct:+.2f}%, "
            f"{spans} spans over the last on-leg burst)",
            "value": round(tps_on, 2),
            "unit": "tokens/sec",
            "vs_baseline": round(tps_on / max(tps_off, 1e-9), 4),
        },
        args.out,
    )
    if not _dual_gate_ok(runs_off, runs_on):
        print(
            f"[bench] TRACING OVERHEAD {overhead_pct:.2f}% paired-median "
            f"AND best ratio {tps_on / tps_off:.4f} < 0.98 — "
            "instrumentation regression",
            file=sys.stderr,
        )
        return 1
    return 0


def _bench_serving_flight_overhead(args, cfg, params) -> int:
    """Flight-recorder A/B (PR 10 acceptance): the identical
    panel-shaped burst served with the flight recorder ON (typed
    scheduler events — program windows, admissions, token timelines,
    request summaries — at /debug/flight) vs OFF
    (``flight.set_enabled(False)``), through ONE batcher with the
    PR-5 dual tok/s gate. The recorder must be free when sampling:
    per event it is one bool check + one lock+append, and per token
    one perf_counter read — if this leg fails on a quiet box, an
    instrumentation site regressed onto the hot path.
    """
    from llm_consensus_tpu.serving import flight as _flight
    from llm_consensus_tpu.serving.continuous import (
        ContinuousBatcher,
        ContinuousConfig,
    )

    pg = 64
    salt = int(time.time() * 1e6) % 999983
    header_target = max(args.prompt_len, 2 * pg + 16)
    n = args.serve_requests
    longest = header_target + 64
    buckets = [64]
    while buckets[-1] < longest:
        buckets.append(buckets[-1] * 2)
    pages_per_seq = _serve_pages_per_seq(
        buckets[-1], args.new_tokens, args.serve_chunk, pg
    )
    n_pages = 1 + args.serve_slots * pages_per_seq * 2
    batcher = ContinuousBatcher(
        cfg,
        params,
        config=ContinuousConfig(
            max_slots=args.serve_slots,
            page_size=pg,
            n_pages=n_pages,
            pages_per_seq=pages_per_seq,
            max_new_tokens=args.new_tokens,
            seq_buckets=tuple(buckets),
            steps_per_sync=args.serve_chunk,
            prefill_chunk=args.serve_prefill_chunk or 64,
            share_prefix=True,
        ),
    )

    event_counts: list[int] = []
    # ONE shared header for every leg (the trace-overhead leg's
    # discipline): the registry reaches steady state in warmup so each
    # leg does identical device work — the A/B isolates the recorder.
    header = f"Panel header {salt}: " + "shared context " * (
        -(-header_target // 15)
    )

    def leg(tag: str, on: bool) -> float:
        prompts = [
            header + f"Q{i}-{tag}: item {i * 37 % 101}?" for i in range(n)
        ]
        # Fresh ring per leg: the A/B measures event RECORDING, and a
        # ring already at capacity would tax later legs' evictions
        # asymmetrically.
        _flight.flight_recorder().clear()
        _flight.set_enabled(on)
        try:
            t0 = time.perf_counter()
            futs = [
                batcher.submit(p, max_new_tokens=args.new_tokens)
                for p in prompts
            ]
            toks = sum(f.result(timeout=600).num_tokens for f in futs)
            wall = time.perf_counter() - t0
        finally:
            _flight.set_enabled(True)
        if on:
            event_counts.append(len(_flight.flight_recorder()))
        return toks / wall

    try:
        batcher.submit(
            header + "warmup tail", max_new_tokens=args.new_tokens
        ).result(timeout=600)
        runs_off, runs_on = _ab_rounds(leg, args.flight_ab_rounds)
        _ab_escalate(leg, runs_off, runs_on, "flight-overhead")
    finally:
        batcher.close()
    tps_off, tps_on = max(runs_off), max(runs_on)
    overhead_pct = _paired_overhead_pct(runs_off, runs_on)
    events = event_counts[-1] if event_counts else 0
    _emit(
        {
            "metric": f"serving tok/s, flight recorder ON "
            f"({cfg.name}, {max(1, args.flight_ab_rounds)}x{n} reqs, "
            f"slots={args.serve_slots}, decode {args.new_tokens} @ "
            f"~{header_target} shared prompt, recorder OFF "
            f"{tps_off:.0f} tok/s, overhead {overhead_pct:+.2f}%, "
            f"{events} events over the last on-leg burst)",
            "value": round(tps_on, 2),
            "unit": "tokens/sec",
            "vs_baseline": round(tps_on / max(tps_off, 1e-9), 4),
        },
        args.out,
    )
    if events <= 0:
        print(
            "[bench] flight leg recorded no events with the recorder "
            "on — the A/B measured nothing",
            file=sys.stderr,
        )
        return 1
    if not _dual_gate_ok(runs_off, runs_on):
        print(
            f"[bench] FLIGHT-RECORDER OVERHEAD {overhead_pct:.2f}% "
            f"paired-median AND best ratio "
            f"{tps_on / tps_off:.4f} < 0.98 — instrumentation "
            "regression",
            file=sys.stderr,
        )
        return 1
    return 0


def _bench_serving_replicas(args, cfg, params) -> int:
    """Replica-fleet A/B (PR 14): prefix-affinity routing vs a
    random-routing control, then an overload storm through one gateway
    gating preemption-instead-of-429s.

    Leg A — the PR-8 mixed panel burst (half the requests share one
    multi-page header, half are unique from byte 0) served through a
    K-replica :class:`ReplicaSet` twice: routing policy "prefix" (the
    subsystem) vs "random" (round-robin control). Affinity lands the
    panel's mates where the header's chain lives, so its registry hit
    rate must be STRICTLY above the control's (which scatters the
    panel and re-prefills the header per replica); generated text is
    REQUIRED byte-identical per pair (routing must never change
    output — requests are seeded and batch-independent).

    Leg B — the overload storm: a fleet with working-set-starved pools
    behind one gateway whose admission queue bound sits far below the
    storm size. Wave 1 primes a header; the storm wave (a different
    header) overflows the queue on most submits — the fleet's
    overflow hook preempts resident chains to the fleet-shared host
    tier instead of shedding; the re-vote wave re-sends wave 1's
    header, which restores from the tier. Gates: ZERO 429s, every
    storm request completes with text, >= 1 router-requested
    preemption, >= 1 restored chain page.
    """
    from llm_consensus_tpu.server import metrics as _metrics
    from llm_consensus_tpu.server.admission import AdmissionConfig
    from llm_consensus_tpu.server.client import (
        GatewayClient,
        GatewayHTTPError,
    )
    from llm_consensus_tpu.server.gateway import (
        Gateway,
        GatewayConfig,
        GatewayThread,
    )
    from llm_consensus_tpu.serving.continuous import ContinuousConfig
    from llm_consensus_tpu.serving.fleet import (
        FleetBackend,
        FleetConfig,
        ReplicaSet,
    )

    k = args.serve_replicas
    if k < 2:
        print(
            f"[bench] --serve-replicas needs K >= 2, got {k}",
            file=sys.stderr,
        )
        return 2
    pg = 64
    salt = int(time.time() * 1e6) % 999983
    header_target = max(args.prompt_len, 2 * pg + 16)
    header = f"Fleet header {salt}: " + "shared context " * (
        -(-header_target // 15)
    )
    n = args.serve_requests
    uniq_pad = "distinct traffic padding " * (-(-header_target // 25))
    # Mixed burst, panel mates FIRST: the random control is
    # round-robin, so a shared-first order deterministically scatters
    # the panel across replicas (mates alternate) — the control's hit
    # rate sits strictly below affinity's by construction, no
    # coin-flip tie to flake the gate. The affinity leg is
    # order-independent (the router probes resident chains).
    prompts = [
        header + f"Q{i}: propose for item {i * 37 % 101}"
        for i in range(n // 2)
    ] + [f"{i} unique {salt}: " + uniq_pad for i in range(n - n // 2)]
    longest = max(len(p) for p in prompts) + 1
    buckets = [64]
    while buckets[-1] < longest:
        buckets.append(buckets[-1] * 2)
    pages_per_seq = _serve_pages_per_seq(
        buckets[-1], args.new_tokens, args.serve_chunk, pg
    )
    host_bytes = args.serve_host_cache_mb << 20

    def fleet_config(n_pages):
        return ContinuousConfig(
            max_slots=args.serve_slots,
            page_size=pg,
            n_pages=n_pages,
            pages_per_seq=pages_per_seq,
            max_new_tokens=args.new_tokens,
            seq_buckets=tuple(buckets),
            steps_per_sync=args.serve_chunk,
            prefill_chunk=args.serve_prefill_chunk or 64,
            share_prefix=True,
            host_cache_bytes=host_bytes,
        )

    def warm(fleet):
        # One warmup per replica: each compiles its own programs.
        futs = [
            fleet.submit_to(
                i, f"warmup {salt} r{i} " + "ctx " * (header_target // 5),
                max_new_tokens=args.new_tokens,
            )
            for i in range(k)
        ]
        for f in futs:
            f.result(timeout=600)

    def run(policy):
        # Pool sized ABOVE the burst working set: leg A isolates
        # routing, so eviction pressure stays out of it.
        fleet = ReplicaSet(
            cfg,
            params,
            config=fleet_config(1 + args.serve_slots * pages_per_seq * 2),
            fleet=FleetConfig(replicas=k, policy=policy),
        )
        try:
            warm(fleet)
            t0 = time.perf_counter()
            futs = [
                fleet.submit(p, max_new_tokens=args.new_tokens)
                for p in prompts
            ]
            results = [f.result(timeout=600) for f in futs]
            wall = time.perf_counter() - t0
            toks = sum(r.num_tokens for r in results)
            stats = fleet.stats()
        finally:
            fleet.close()
        return [r.text for r in results], toks / wall, stats

    texts_aff, tps_aff, s_aff = run("prefix")
    texts_rand, tps_rand, s_rand = run("random")
    text_equal = texts_aff == texts_rand
    hit_aff = s_aff["prefix_hit_rate"]
    hit_rand = s_rand["prefix_hit_rate"]

    # -- leg B: the overload storm through one gateway ------------------
    storm_n = args.serve_storm_requests or 2 * n
    prime_n = max(2, args.serve_slots)
    fleet = ReplicaSet(
        cfg,
        params,
        # Working-set-starved pools (the offload leg's trick): chains
        # cannot stay device-resident across waves, so preemption and
        # pool-pressure demotion have real work to do.
        config=fleet_config(1 + args.serve_slots * pages_per_seq),
        fleet=FleetConfig(replicas=k, policy="prefix"),
    )
    backend = FleetBackend(fleet)
    gw = GatewayThread(
        Gateway(
            backend,
            config=GatewayConfig(
                port=0,
                admission=AdmissionConfig(
                    # Bound far below the storm: most storm submits
                    # find the queue full and take the preempt path.
                    max_queue=2,
                    max_inflight=2,
                ),
            ),
        )
    ).start()
    shed_before = sum(
        v
        for kk, v in _metrics.REGISTRY.snapshot().items()
        if kk.startswith("gateway_shed_total")
    )
    # Failures collected per thread via list.append (atomic); the 429
    # tally is derived AFTER the joins — a nonlocal int += across
    # storm threads would race and undercount.
    errors: list[str] = []

    def storm_call(client, prompt):
        try:
            r = client.generate(
                prompt, max_new_tokens=args.new_tokens, temperature=0.0
            )
            if not isinstance(r.get("text"), str):
                errors.append(f"no text: {r}")
        except GatewayHTTPError as e:
            errors.append(f"HTTP {e.status}")
        except Exception as e:  # noqa: BLE001 - counted, not raised
            errors.append(repr(e))

    import threading as _threading

    try:
        warm(fleet)
        client = GatewayClient("127.0.0.1", gw.port, timeout=600.0)
        h1 = f"Storm header A {salt}: " + "shared context " * (
            -(-header_target // 15)
        )
        h2 = f"Storm header B {salt}: " + "shared context " * (
            -(-header_target // 15)
        )
        waves = [
            [h1 + f"P{i}: prime" for i in range(prime_n)],
            [
                h2 + f"S{i}: storm item {i * 37 % 101}"
                for i in range(storm_n)
            ],
            [h1 + f"R{i}: re-vote" for i in range(prime_n)],
        ]
        completed = 0
        t0 = time.perf_counter()
        for wave in waves:
            threads = [
                _threading.Thread(target=storm_call, args=(client, p))
                for p in wave
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            completed += len(wave)
        storm_wall = time.perf_counter() - t0
        storm_stats = fleet.stats()
    finally:
        gw.drain()
        fleet.close()
    shed_after = sum(
        v
        for kk, v in _metrics.REGISTRY.snapshot().items()
        if kk.startswith("gateway_shed_total")
    )
    shed = shed_after - shed_before
    e429 = sum(1 for e in errors if e == "HTTP 429")
    preempts = sum(storm_stats["preempt_requests"])
    restored = storm_stats["offload_restored_pages"]
    demoted = storm_stats["offload_demoted_pages"]
    lost = len(errors)

    gate_hit = hit_aff > hit_rand
    gate_storm = shed == 0 and e429 == 0 and lost == 0
    gate_preempt = preempts >= 1 and restored >= 1
    status = (
        "ok"
        if (text_equal and gate_hit and gate_storm and gate_preempt)
        else "failed"
    )
    _emit(
        {
            "metric": f"serving tok/s, prefix-affinity replica fleet "
            f"({cfg.name}, K={k}, {n} mixed reqs, slots="
            f"{args.serve_slots}/replica, decode {args.new_tokens} @ "
            f"~{header_target} header, hit-rate affinity "
            f"{hit_aff:.3f} vs random {hit_rand:.3f}, routed prefix "
            f"{s_aff['routed_prefix']}/{s_aff['routed_total']}, "
            f"random-control {tps_rand:.0f} tok/s, storm "
            f"{storm_n}+2x{prime_n} reqs in {storm_wall:.1f}s: "
            f"429s {e429}, shed {shed}, lost {lost}, preempts "
            f"{preempts}, demoted {demoted} / restored {restored} "
            f"pages, text unchanged={text_equal})",
            "value": round(tps_aff, 2),
            "unit": "tokens/sec",
            "vs_baseline": round(tps_aff / max(tps_rand, 1e-9), 4),
            "status": status,
        },
        args.out,
    )
    if not text_equal:
        print(
            "[bench] GENERATED TEXT DIVERGED between affinity and "
            "random routing — routing must never change output",
            file=sys.stderr,
        )
    if not gate_hit:
        print(
            f"[bench] affinity hit rate {hit_aff:.3f} NOT above "
            f"random-routing control {hit_rand:.3f}",
            file=sys.stderr,
        )
    if not gate_storm:
        print(
            f"[bench] overload storm lost work: {e429} x 429, shed "
            f"{shed}, {lost} failures ({errors[:5]})",
            file=sys.stderr,
        )
    if not gate_preempt:
        print(
            f"[bench] storm never exercised preemption (preempts "
            f"{preempts}, restored {restored}) — sizing regression",
            file=sys.stderr,
        )
    return 0 if status == "ok" else 1


def _bench_serve_fleet_control(args, cfg, params) -> int:
    """Fleet control plane A/B (PR 19): two tenants through one
    gateway, control plane ON vs OFF.

    Traffic: a "storm" tenant keeps ~8 closed-loop short requests
    outstanding (resubmitting the instant one finishes or sheds) while
    a "quiet" tenant runs 2 closed-loop workers of ~4x-cost requests —
    roughly a 10x request-rate flood. OFF is the classic cost-budget
    FIFO door (PR 15): the quiet tenant queues behind the whole storm
    backlog and eats plain 429s at a full lane. ON layers the PR-19
    admission discipline (SLO classes + weighted tenant fair-share,
    quiet weighted 2:1) plus a live :class:`FleetController` steering
    router weights, and finishes with a deterministic elastic cycle:
    spawn a replica, run a re-vote wave through it, retire it while
    the wave is in flight.

    Gates: (a) quiet p99 latency STRICTLY better ON; (b) >= 1
    deadline-aware shed witnessed in the flight ring (reason "slo"),
    lockstep with stats() and gateway_slo_shed_total; (c) quiet tenant
    ZERO SLO misses ON (stats + Prometheus agree) while the same
    target retro-applied to the OFF latencies misses >= 1; (d) the
    storm tenant's admitted cost share lands at its configured fair
    weight +-0.10 (stats lockstep with gateway_tenant_cost_bytes);
    (e) the elastic cycle loses ZERO requests, spawn/drain/retire are
    witnessed by all three sources (stats scale_events, Prometheus
    gateway_fleet_scale_total, flight "scale" events), and quiet +
    re-vote text is byte-identical ON vs OFF (control must never
    change output).
    """
    from llm_consensus_tpu.server import metrics as _metrics
    from llm_consensus_tpu.server.admission import AdmissionConfig
    from llm_consensus_tpu.server.client import (
        GatewayClient,
        GatewayHTTPError,
    )
    from llm_consensus_tpu.server.gateway import (
        Gateway,
        GatewayConfig,
        GatewayThread,
    )
    from llm_consensus_tpu.serving import flight as _flight
    from llm_consensus_tpu.serving.continuous import ContinuousConfig
    from llm_consensus_tpu.serving.fleet import (
        FleetBackend,
        FleetConfig,
        ReplicaSet,
    )
    from llm_consensus_tpu.serving.fleet_control import (
        FleetControlConfig,
        FleetController,
    )
    import threading as _threading

    k = args.serve_replicas if args.serve_replicas >= 2 else 2
    pg = 64
    salt = int(time.time() * 1e6) % 999983
    storm_len = max(args.prompt_len, 2 * pg + 16)
    storm_pad = "storm traffic padding " * (-(-storm_len // 22))
    quiet_pad = "quiet tenant context " * (-(-(4 * storm_len) // 21))
    quiet_workers, quiet_per_worker = 2, 3
    # Sized against the 12-storm-unit budget: 10 outstanding storm
    # requests keep the lane near-saturated (a second quiet request's
    # 4 units tips it over, so deadline-aware shedding fires), but a
    # lone quiet request always fits eventually — the OFF leg waits
    # out the whole FIFO backlog instead of starving forever.
    storm_workers = 10
    revote_n = 4
    # Quiet prompts are FIXED per (worker, slot) and identical across
    # legs — the ON/OFF byte-identity gate compares them pairwise.
    quiet_prompts = {
        (w, j): f"{salt} quiet w{w} q{j}: " + quiet_pad
        for w in range(quiet_workers)
        for j in range(quiet_per_worker)
    }
    revote_prompts = [
        f"{salt} revote {i}: " + quiet_pad for i in range(revote_n)
    ]
    longest = len(quiet_pad) + 64
    buckets = [64]
    while buckets[-1] < longest:
        buckets.append(buckets[-1] * 2)
    pages_per_seq = _serve_pages_per_seq(
        buckets[-1], args.new_tokens, args.serve_chunk, pg
    )

    def fleet_config():
        # Pool sized ABOVE the working set: this leg isolates the
        # admission door and controller, not pool pressure.
        return ContinuousConfig(
            max_slots=args.serve_slots,
            page_size=pg,
            n_pages=1 + args.serve_slots * pages_per_seq * 2,
            pages_per_seq=pages_per_seq,
            max_new_tokens=args.new_tokens,
            seq_buckets=tuple(buckets),
            steps_per_sync=args.serve_chunk,
            prefill_chunk=args.serve_prefill_chunk or 64,
            share_prefix=True,
            host_cache_bytes=args.serve_host_cache_mb << 20,
        )

    def snap(prefix):
        return {
            kk: v
            for kk, v in _metrics.REGISTRY.snapshot().items()
            if kk.startswith(prefix)
        }

    def delta(before, after):
        return {
            kk: v - before.get(kk, 0.0)
            for kk, v in after.items()
            if v - before.get(kk, 0.0)
        }

    def run(quiet_target):
        """One leg. quiet_target None = control OFF (the classic PR-15
        cost-budget FIFO door), a float = control ON with that quiet
        SLO target. Returns the leg's measurements."""
        on = quiet_target is not None
        fleet = ReplicaSet(
            cfg,
            params,
            config=fleet_config(),
            fleet=FleetConfig(replicas=k, policy="prefix"),
        )
        backend = FleetBackend(fleet)
        c_storm = backend.request_cost(
            f"{salt} storm w0 n0: " + storm_pad, args.new_tokens
        )
        budget = 12.0 * c_storm
        fc_cfg = FleetControlConfig(
            interval_s=0.1,
            # Storm class target far below any contended wait: every
            # storm shed at a warm full lane is deadline-aware by
            # construction (the would-miss walk or the est>target
            # classic branch — both reason "slo").
            slo_classes={"quiet": quiet_target or 1.0, "storm": 0.2},
            default_slo_class=None,
            fair_share=True,
            # Quiet weighted 2:1 — the storm's fair share (the gate's
            # center) is 1/3 of admitted cost, and WFQ bounds the
            # quiet tenant's wait to ~half its own modeled cost.
            tenant_weights={"quiet": 2.0, "storm": 1.0},
            elastic_max=0,
        )
        adm_kw = fc_cfg.admission_kwargs() if on else {}
        gwobj = Gateway(
            backend,
            config=GatewayConfig(
                port=0,
                admission=AdmissionConfig(
                    max_inflight=2,
                    cost_budget_bytes=budget,
                    **adm_kw,
                ),
            ),
        )
        # The fleet's preempt hook absorbs storms (PR 14's leg); this
        # leg isolates the DOOR, so sheds stay sheds in both legs.
        gwobj.admission.overflow_hook = None
        controller = FleetController(fleet, fc_cfg) if on else None
        gw = GatewayThread(gwobj).start()
        errors: list[str] = []
        quiet_lats: dict = {}
        quiet_texts: dict = {}
        revote_texts: dict = {}
        sheds_429 = [0]
        tokens = [0]
        tok_lock = _threading.Lock()
        stop = _threading.Event()

        def storm_loop(client, w):
            n = 0
            while not stop.is_set():
                kw = {"slo": "storm", "tenant": "storm"} if on else {}
                try:
                    r = client.generate(
                        f"{salt} storm w{w} n{n}: " + storm_pad,
                        max_new_tokens=args.new_tokens,
                        temperature=0.0,
                        **kw,
                    )
                    with tok_lock:
                        tokens[0] += int(r.get("num_tokens", 0))
                except GatewayHTTPError as e:
                    if e.status != 429:
                        errors.append(f"storm HTTP {e.status}")
                    with tok_lock:
                        sheds_429[0] += 1
                    time.sleep(0.1)
                except Exception as e:  # noqa: BLE001 - counted
                    errors.append(repr(e))
                n += 1
                time.sleep(0.05)

        def quiet_loop(client, w):
            kw = {"slo": "quiet", "tenant": "quiet"} if on else {}
            for j in range(quiet_per_worker):
                t0 = time.perf_counter()
                deadline = t0 + 300.0
                while True:
                    try:
                        r = client.generate(
                            quiet_prompts[(w, j)],
                            max_new_tokens=args.new_tokens,
                            temperature=0.0,
                            **kw,
                        )
                        break
                    except GatewayHTTPError as e:
                        # Shed at the door: retry — latency honestly
                        # charges the whole wait, retries included.
                        if (
                            e.status != 429
                            or time.perf_counter() > deadline
                        ):
                            errors.append(f"quiet HTTP {e.status}")
                            return
                        time.sleep(0.1)
                    except Exception as e:  # noqa: BLE001 - counted
                        errors.append(repr(e))
                        return
                quiet_lats[(w, j)] = time.perf_counter() - t0
                quiet_texts[(w, j)] = r.get("text")
                with tok_lock:
                    tokens[0] += int(r.get("num_tokens", 0))

        def revote_call(client, i, kw):
            # Same retry discipline as the quiet workers: the wave is
            # quiet-sized, so 4 concurrent submits legitimately exceed
            # the 10-storm-unit budget — door pushback is not lost
            # work, an unanswered request is.
            deadline = time.perf_counter() + 300.0
            while True:
                try:
                    r = client.generate(
                        revote_prompts[i],
                        max_new_tokens=args.new_tokens,
                        temperature=0.0,
                        **kw,
                    )
                    revote_texts[i] = r.get("text")
                    with tok_lock:
                        tokens[0] += int(r.get("num_tokens", 0))
                    return
                except GatewayHTTPError as e:
                    if e.status != 429 or time.perf_counter() > deadline:
                        errors.append(f"revote {i}: HTTP {e.status}")
                        return
                    time.sleep(0.1)
                except Exception as e:  # noqa: BLE001 - counted
                    errors.append(f"revote {i}: {e!r}")
                    return

        flight_mark = 0
        evs = _flight.flight_recorder().events()
        if evs:
            flight_mark = evs[-1].seq
        prom_before = {
            p: snap(p)
            for p in (
                "gateway_slo_",
                "gateway_tenant_",
                "gateway_fleet_scale_total",
            )
        }
        try:
            # One warmup per replica: each compiles its own programs.
            futs = [
                fleet.submit_to(
                    i,
                    f"warmup {salt} r{i} " + storm_pad,
                    max_new_tokens=args.new_tokens,
                )
                for i in range(k)
            ]
            for f in futs:
                f.result(timeout=600)
            if controller is not None:
                controller.start()
            client = GatewayClient("127.0.0.1", gw.port, timeout=600.0)
            t0 = time.perf_counter()
            # Quiet workers lead so the lane is contended from the
            # storm's first submit.
            qthreads = [
                _threading.Thread(target=quiet_loop, args=(client, w))
                for w in range(quiet_workers)
            ]
            for t in qthreads:
                t.start()
            time.sleep(0.2)
            sthreads = [
                _threading.Thread(target=storm_loop, args=(client, w))
                for w in range(storm_workers)
            ]
            for t in sthreads:
                t.start()
            for t in qthreads:
                t.join()
            stop.set()
            for t in sthreads:
                t.join()
            # Let the admitted backlog drain before the elastic cycle.
            drain_deadline = time.time() + 300
            while (
                gwobj.admission.pending() > 0
                and time.time() < drain_deadline
            ):
                time.sleep(0.1)
            spawned = fleet.spawn_replica() if on else None
            rthreads = [
                _threading.Thread(
                    target=revote_call,
                    args=(
                        client,
                        i,
                        {"slo": "quiet", "tenant": "quiet"}
                        if on
                        else {},
                    ),
                )
                for i in range(revote_n)
            ]
            for t in rthreads:
                t.start()
            if on:
                # Retire the spawned replica WHILE the wave is in
                # flight: drain-then-retire must lose nothing.
                time.sleep(0.3)
                fleet.retire_replica(spawned, wait_s=300.0)
            for t in rthreads:
                t.join()
            wall = time.perf_counter() - t0
            fleet_stats = fleet.stats()
            adm_stats = gwobj.admission.stats()
        finally:
            if controller is not None:
                controller.stop()
            gw.drain()
            fleet.close()
        prom_delta = {
            p: delta(prom_before[p], snap(p)) for p in prom_before
        }
        shed_evs = [
            e
            for e in _flight.flight_recorder().events()
            if e.seq > flight_mark and e.kind == "shed"
        ]
        scale_evs = [
            e
            for e in _flight.flight_recorder().events()
            if e.seq > flight_mark and e.kind == "scale"
        ]
        return {
            "lats": [quiet_lats[kk] for kk in sorted(quiet_lats)],
            "n_quiet": len(quiet_lats),
            "quiet_texts": quiet_texts,
            "revote_texts": revote_texts,
            "errors": errors,
            "sheds_429": sheds_429[0],
            "tps": tokens[0] / wall,
            "wall": wall,
            "fleet_stats": fleet_stats,
            "adm_stats": adm_stats,
            "prom": prom_delta,
            "shed_evs": shed_evs,
            "scale_evs": scale_evs,
            "spawned": spawned,
            "ctl_stats": controller.stats() if controller else {},
        }

    off = run(None)
    if off["errors"] or off["n_quiet"] != quiet_workers * quiet_per_worker:
        print(
            f"[bench] OFF leg lost work: {off['errors'][:5]} "
            f"({off['n_quiet']} quiet done)",
            file=sys.stderr,
        )
        return 1
    # Quiet SLO target derived from the OFF leg so the gate is about
    # the MECHANISM, not a magic number: 0.6x the BEST uncontrolled
    # latency sits below every OFF sample (>= 1 retro-miss is
    # structural) yet ~2x above the WFQ-bounded ON queue wait, which
    # is what the admission controller scores misses against.
    target_q = 0.6 * min(off["lats"])
    on = run(target_q)

    p99_off = max(off["lats"])
    p99_on = max(on["lats"]) if on["lats"] else float("inf")
    retro_miss_off = sum(1 for v in off["lats"] if v > target_q)
    on_quiet_miss = on["adm_stats"]["slo_miss"].get("quiet", 0)
    prom_quiet_miss = sum(
        v
        for kk, v in on["prom"]["gateway_slo_"].items()
        if kk.startswith("gateway_slo_miss_total")
        and 'class="quiet"' in kk
    )
    slo_shed_stats = on["adm_stats"]["slo_sheds"]
    slo_shed_prom = sum(
        v
        for kk, v in on["prom"]["gateway_slo_"].items()
        if kk.startswith("gateway_slo_shed_total")
    )
    slo_shed_flight = sum(
        1 for e in on["shed_evs"] if e.meta.get("reason") == "slo"
    )
    tenant_cost = on["adm_stats"]["tenant_cost_bytes"]
    cost_storm = tenant_cost.get("storm", 0.0)
    cost_total = sum(tenant_cost.values())
    storm_share = cost_storm / max(cost_total, 1e-9)
    fair_storm = 1.0 / 3.0  # weights storm 1 : quiet 2
    prom_cost_storm = sum(
        v
        for kk, v in on["prom"]["gateway_tenant_"].items()
        if kk.startswith("gateway_tenant_cost_bytes")
        and 'tenant="storm"' in kk
    )
    scale_stats = on["fleet_stats"]["scale_events"]
    scale_prom = {
        a: sum(
            v
            for kk, v in on["prom"][
                "gateway_fleet_scale_total"
            ].items()
            if f'action="{a}"' in kk
        )
        for a in ("spawn", "drain", "retire")
    }
    scale_flight = [
        e.meta.get("action")
        for e in on["scale_evs"]
        if e.meta.get("replica") == on["spawned"]
    ]
    texts_equal = (
        on["quiet_texts"] == off["quiet_texts"]
        and on["revote_texts"] == off["revote_texts"]
        and len(on["revote_texts"]) == revote_n
    )

    gate_p99 = p99_on < p99_off
    gate_shed = (
        slo_shed_flight >= 1
        and slo_shed_stats >= 1
        and slo_shed_prom >= 1
    )
    gate_miss = (
        on_quiet_miss == 0
        and prom_quiet_miss == 0
        and retro_miss_off >= 1
    )
    gate_share = (
        abs(storm_share - fair_storm) <= 0.10
        and abs(prom_cost_storm - cost_storm) < 1e-6
    )
    gate_elastic = (
        not on["errors"]
        and on["n_quiet"] == quiet_workers * quiet_per_worker
        and scale_stats.get("spawn") == 1
        and scale_stats.get("drain") == 1
        and scale_stats.get("retire") == 1
        and scale_prom == {"spawn": 1, "drain": 1, "retire": 1}
        and scale_flight == ["spawn", "drain", "retire"]
        and texts_equal
    )
    status = (
        "ok"
        if (
            gate_p99
            and gate_shed
            and gate_miss
            and gate_share
            and gate_elastic
        )
        else "failed"
    )
    _emit(
        {
            "metric": f"serving tok/s, fleet control plane ({cfg.name}"
            f", K={k}, {storm_workers} storm + {quiet_workers} quiet "
            f"closed-loop workers, decode {args.new_tokens}, quiet "
            f"p99 ON {p99_on:.2f}s vs OFF {p99_off:.2f}s @ target "
            f"{target_q:.2f}s, quiet misses ON {on_quiet_miss} / OFF "
            f"retro {retro_miss_off}, slo sheds {slo_shed_stats} "
            f"(flight {slo_shed_flight}), storm share "
            f"{storm_share:.3f} vs fair {fair_storm:.3f}, 429s "
            f"ON {on['sheds_429']} / OFF {off['sheds_429']}, scale "
            f"{scale_flight}, controller ticks "
            f"{on['ctl_stats'].get('fleet_ticks', 0)}, text "
            f"unchanged={texts_equal})",
            "value": round(on["tps"], 2),
            "unit": "tokens/sec",
            "vs_baseline": round(on["tps"] / max(off["tps"], 1e-9), 4),
            "status": status,
        },
        args.out,
    )
    if not gate_p99:
        print(
            f"[bench] quiet p99 NOT better with control ON: "
            f"{p99_on:.2f}s vs OFF {p99_off:.2f}s",
            file=sys.stderr,
        )
    if not gate_shed:
        print(
            f"[bench] no deadline-aware shed witnessed (stats "
            f"{slo_shed_stats}, prom {slo_shed_prom}, flight "
            f"{slo_shed_flight})",
            file=sys.stderr,
        )
    if not gate_miss:
        print(
            f"[bench] quiet SLO miss gate failed: ON {on_quiet_miss} "
            f"(prom {prom_quiet_miss}), OFF retro {retro_miss_off} @ "
            f"{target_q:.2f}s",
            file=sys.stderr,
        )
    if not gate_share:
        print(
            f"[bench] storm admitted share {storm_share:.3f} outside "
            f"fair {fair_storm:.3f} +-0.10 (stats {cost_storm:.0f} vs "
            f"prom {prom_cost_storm:.0f} bytes)",
            file=sys.stderr,
        )
    if not gate_elastic:
        print(
            f"[bench] elastic cycle gate failed: errors "
            f"{on['errors'][:5]}, scale stats {scale_stats}, prom "
            f"{scale_prom}, flight {scale_flight}, text "
            f"unchanged={texts_equal}",
            file=sys.stderr,
        )
    return 0 if status == "ok" else 1


# Multi-model leg's dual-gate band (the mesh leg's generous-band
# precedent): spec-on runs TWO equal-size engines on this box — the
# twin draft mirrors every judge prefill and adds k draft dispatches
# per verify window — so the HBM-bandwidth amortization speculation
# buys on a chip does not exist on a compute-bound 1-core CPU, and
# parity ± scheduler noise is the honest smoke expectation (observed
# bests 0.86-1.0x under full-suite residue). A broken remap path still
# blows through it: acceptance collapse wastes every verify round
# (~0.2-0.3x — and the no-cross-model-accept gate fires first), and
# per-step recompiles are 10x+.
_MM_PCT = 40.0


def _bench_serving_multimodel(args, cfg, params) -> int:
    """Multi-model consensus serving A/B (PR 18): debate-shaped
    traffic through a 2-member ModelSet with cross-model speculation.

    Members: "small" (the propose engine) carries the target's
    vocab-PERMUTED twin — the same network with embedding rows and
    lm_head columns gathered through the draft->target map — under a
    SHIFTED byte tokenizer (byte+4 layout vs byte+3); "large" (the
    judge, the set's default) carries the target weights and drafts
    from "small" through the exact-match vocab remap. The twin makes
    the pairing honest and the win deterministic at once: alignment is
    genuinely non-identity (every draft input and proposal crosses the
    remap, so every accept is a CROSS-MODEL accept), while the twin's
    greedy chain, remapped, is the target's own — acceptance is
    structural wherever the target's argmax lands in the mapped byte
    range, not random-weight luck.

    Traffic: N propose requests on the small member (one shared
    header), then a panel evaluate per proposal on the large member,
    then one refine on the large — the phase routing
    ``ModelSet.phase_models()`` hands the consensus Coordinator.
    Spec ON/OFF alternates on the judge's live ``spec_decode`` knob.

    Gates (rc 1, mirrored in the JSON ``status``): identical consensus
    decisions — every phase's texts byte-equal between ON and OFF legs
    and stable across rounds; spec-on tok/s >= the no-draft baseline
    under the PR-5 dual gate with PR-10 loadavg-aware escalation; and
    >= 1 cross-model accept visible in engine stats, Prometheus, and
    the flight trace.
    """
    import asyncio as _asyncio

    from llm_consensus_tpu.backends.base import (
        GenerationRequest,
        SamplingParams,
    )
    from llm_consensus_tpu.engine.tokenizer import ByteTokenizer, Tokenizer
    from llm_consensus_tpu.server.metrics import (
        SPEC_XMODEL_ACCEPTED_TOKENS,
    )
    from llm_consensus_tpu.serving import flight as _flight
    from llm_consensus_tpu.serving.continuous import ContinuousConfig
    from llm_consensus_tpu.serving.modelset import (
        ModelSet,
        ModelSetBackend,
        ModelSpec,
    )
    from llm_consensus_tpu.serving.vocab_align import align_vocabs

    class _ShiftedByteTokenizer(Tokenizer):
        """Byte layout at offset 4 (id 3 a hole) — the minimal
        heterogeneous tokenizer; see tests/test_multi_model.py."""

        def __init__(self):
            self.pad_id, self.bos_id, self.eos_id = 0, 1, 2
            self._offset = 4
            self.vocab_size = 256 + self._offset

        def encode(self, text, add_bos=True):
            ids = [
                b + self._offset
                for b in text.encode("utf-8", errors="surrogateescape")
            ]
            return [self.bos_id] + ids if add_bos else ids

        def decode(self, ids):
            data = bytes(
                i - self._offset
                for i in ids
                if self._offset <= i < self._offset + 256
            )
            return data.decode("utf-8", errors="surrogateescape")

    tok_large = ByteTokenizer()
    tok_small = _ShiftedByteTokenizer()
    vmap = align_vocabs(tok_large, tok_small)
    if vmap is None or vmap.identity:
        print(
            "[bench] multi-model leg: alignment did not produce the "
            "expected non-identity map",
            file=sys.stderr,
        )
        return 2
    vmap_full = vmap.sized_to(
        cfg.vocab_size,
        cfg.vocab_size,
        target_pad=tok_large.pad_id,
        draft_pad=tok_small.pad_id,
    )
    if cfg.vocab_size > tok_small.vocab_size:
        # sized_to leaves the models' padded vocab tail unmapped — the
        # right conservative default for two UNRELATED models, but here
        # the twin is DEFINED by the map, so extend it identity over
        # the tail (ids with no tokenizer meaning on either side).
        # Otherwise a random-weight argmax landing in the tail commits
        # a token the draft sees as pad, and that row's acceptance is
        # dead for the rest of its life. The tokenizer-space subset
        # (byte+4 vs byte+3) remains a genuine non-identity remap.
        import numpy as _np

        from llm_consensus_tpu.serving.vocab_align import VocabMap

        d2t = _np.asarray(vmap_full.d2t).copy()
        t2d = _np.asarray(vmap_full.t2d).copy()
        tail = _np.arange(
            tok_small.vocab_size, cfg.vocab_size, dtype=_np.int32
        )
        d2t[tail] = tail
        t2d[tail] = tail
        vmap_full = VocabMap(
            d2t=d2t,
            t2d=t2d,
            coverage=vmap.coverage,
            identity=False,
            n_mapped=vmap_full.n_mapped + len(tail),
        )
    from llm_consensus_tpu.models.transformer import init_params

    # The twin construction gathers embedding rows / lm_head columns,
    # which needs the RAW weight tree — re-init locally instead of
    # consuming main's (possibly int8-quantized) params.
    params = init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.bfloat16)
    g = jnp.asarray(vmap_full.d2t, jnp.int32)
    twin = dict(params)
    twin["embed"] = params["embed"][g]
    if "lm_head" in params:
        twin["lm_head"] = params["lm_head"][:, g]

    pg = 64
    k_spec = max(1, args.k_spec)
    n = args.serve_requests
    header_target = max(args.prompt_len, 2 * pg + 16)
    # Fixed header (no salt): the ON and OFF legs must pose the SAME
    # debate or "identical decisions" is vacuous.
    header = "Debate header: " + "shared context " * (
        -(-header_target // 15)
    )
    # Refine carries a slice of every evaluation; size buckets for it.
    longest = len(header) + 40 + max(80, 16 * n) + 1
    buckets = [64]
    while buckets[-1] < longest:
        buckets.append(buckets[-1] * 2)
    pages_per_seq = _serve_pages_per_seq(
        buckets[-1], args.new_tokens, k_spec + 1, pg
    )

    def member_config(spec_k):
        return ContinuousConfig(
            max_slots=args.serve_slots,
            page_size=pg,
            n_pages=1 + args.serve_slots * pages_per_seq * 2,
            pages_per_seq=pages_per_seq,
            max_new_tokens=args.new_tokens,
            seq_buckets=tuple(buckets),
            steps_per_sync=1,
            prefill_chunk=args.serve_prefill_chunk or 64,
            share_prefix=True,
            spec_k=spec_k,
        )

    ms = ModelSet(
        [
            ModelSpec(
                name="large",
                cfg=cfg,
                params=params,
                tokenizer=tok_large,
                config=member_config(k_spec),
                draft_from="small",
                # The twin is DEFINED by this map (tail included):
                # align_vocabs alone can't know the padded-tail
                # correspondence, so hand the full map over.
                vocab_map=vmap_full,
            ),
            ModelSpec(
                name="small",
                cfg=cfg,
                params=twin,
                tokenizer=tok_small,
                config=member_config(0),
            ),
        ],
        default="large",
    )
    be = ModelSetBackend(ms)
    judge = ms.members["large"].engine
    phases = ms.phase_models()
    sp = SamplingParams(max_new_tokens=args.new_tokens, temperature=0.0)

    def debate():
        """One debate: N propose -> N evaluate -> 1 refine. Returns
        (per-phase texts, generated tokens, wall seconds)."""

        async def run():
            props = await be.generate_batch([
                GenerationRequest(
                    header + f" P{i}: propose an answer.",
                    sp,
                    model=phases["propose"],
                )
                for i in range(n)
            ])
            evs = await be.generate_batch([
                GenerationRequest(
                    header + f" judge proposal {i}: " + p.text[:80],
                    sp,
                    model=phases["evaluate"],
                )
                for i, p in enumerate(props)
            ])
            ref = await be.generate_batch([
                GenerationRequest(
                    header + " refine: "
                    + "".join(e.text[:16] for e in evs),
                    sp,
                    model=phases["refine"],
                )
            ])
            return props + evs + ref

        t0 = time.perf_counter()
        results = _asyncio.run(run())
        wall = time.perf_counter() - t0
        toks = sum(r.num_tokens for r in results)
        return tuple(r.text for r in results), toks, wall

    decisions: dict[bool, tuple] = {}
    status = "ok"

    def leg(tag, on):
        nonlocal status
        judge.config.spec_decode = on
        _quiesce_batcher(judge)
        texts, toks, wall = debate()
        ref = decisions.setdefault(on, texts)
        if texts != ref:
            status = "decisions-unstable"
        return toks / wall

    xm_before = SPEC_XMODEL_ACCEPTED_TOKENS.value
    try:
        for on in (True, False):  # warm both program families
            judge.config.spec_decode = on
            _quiesce_batcher(judge)
            debate()
        runs_off, runs_on = _ab_rounds(leg, args.mm_ab_rounds)
        _ab_escalate(leg, runs_off, runs_on, "multi-model", pct=_MM_PCT)
        st = judge.stats()
    finally:
        _asyncio.run(be.close())

    xm_accepted = st["spec_cross_model_accepted_tokens"]
    if decisions.get(True) != decisions.get(False):
        status = "consensus-decisions-diverged"
    elif status == "ok" and not _dual_gate_ok(
        runs_off, runs_on, pct=_MM_PCT
    ):
        status = "spec-on-below-no-draft-baseline"
    elif status == "ok" and xm_accepted <= 0:
        status = "no-cross-model-accept"
    elif status == "ok" and not any(
        e.kind == "spec_xmodel_accept"
        for e in _flight.flight_recorder().events()
    ):
        status = "accept-missing-from-flight-trace"
    elif status == "ok" and (
        SPEC_XMODEL_ACCEPTED_TOKENS.value - xm_before != xm_accepted
    ):
        status = "prometheus-stats-mismatch"

    best_off = max(runs_off)
    best_on = max(runs_on)
    acc = st["spec_acceptance_sum"] / max(1, st["spec_acceptance_count"])
    # Side-channel rows first (non-tok/s units, PR-12 same-unit rule);
    # the headline tokens/sec line goes LAST so --out holds it.
    _emit(
        {
            "metric": "multi-model cross-model vocab coverage "
            f"(exact-match, {cfg.name} byte+3 vs twin byte+4)",
            "value": round(vmap.coverage, 4),
            "unit": "fraction",
            "status": status,
        },
        None,
    )
    _emit(
        {
            "metric": "multi-model cross-model accepted draft tokens "
            f"({len(runs_on)} spec-on debates)",
            "value": xm_accepted,
            "unit": "tokens",
            "status": status,
        },
        None,
    )
    _emit(
        {
            "metric": f"serving tok/s, multi-model debate ({cfg.name} "
            f"judge drafting from vocab-permuted twin, {n} propose + "
            f"{n} evaluate + 1 refine per debate, slots="
            f"{args.serve_slots}, k={k_spec}, decode {args.new_tokens} "
            f"@ ~{header_target} shared header, acceptance {acc:.3f}, "
            f"cross-model accepts {xm_accepted}, no-draft best "
            f"{best_off:.0f} tok/s, decisions unchanged="
            f"{decisions.get(True) == decisions.get(False)})",
            "value": round(best_on, 2),
            "unit": "tokens/sec",
            "vs_baseline": round(best_on / max(best_off, 1e-9), 4),
            "status": status,
        },
        args.out,
    )
    if status != "ok":
        print(f"[bench] multi-model leg: {status}", file=sys.stderr)
        return 1
    return 0


def _bench_serving_disagg(args, cfg, params) -> int:
    """Disaggregated prefill/decode A/B (PR 16): role-split fleet over
    a REMOTE page store vs a mixed-role control, then a degraded
    (killed-store) burst through one gateway.

    Leg A — the PR-8 mixed panel burst (half the requests share one
    multi-page header, half unique from byte 0) served through a
    2-replica fleet with roles ("prefill", "decode") whose shared page
    store is a remote page-store SERVER on localhost (a subprocess of
    ``python -m llm_consensus_tpu.serving.remote_store``): the first
    mate of the shared header triggers a warm-up on the prefill
    replica whose chain crosses the process boundary through the
    store, and the decode replica restores it at admission. Control:
    the same burst through a mixed-role fleet with an in-process
    store. Gates: per-pair byte-identical text (the PR-4 restore
    contract across processes), >= 1 completed chain handoff, ZERO
    re-prefilled header pages on the decode side (every shared-header
    request's header pages arrive shared or restored).

    Leg B — degrade: the store server is KILLED, then a burst runs
    through a gateway over the (now storeless) disagg fleet. Gates:
    every request completes with text (no 429s, nothing lost),
    ``/readyz`` stays 200 (the worker loop never wedged on the dead
    socket), and ``gateway_remote_store_errors_total`` counted the
    outage.

    Transport A/B (PR 17): before leg A, the SAME burst runs through a
    roled fleet in the PR-16 transport shape — wire v1 (pickled
    frames), sequential whole-chain export after the warm prefill, no
    prefetch — against its own fresh store server; leg A then runs the
    PR-17 shape (zero-copy v2 wire, streamed handoff, route-driven
    prefetch) against another fresh server. Gates: text byte-identical
    per pair across the two transports (and vs the mixed control), and
    the claim-to-exported handoff latency (``gateway_handoff_seconds``)
    no worse than the sync path's within the PR-5 dual-gate band. A
    loopback microbench also races the two wire formats over one
    in-process server — raw plane bytes/s moved by batched v2
    scatter-gather vs per-page v1 pickle round trips — gated at >= 2x.
    """
    import json as _json
    import subprocess
    import urllib.error
    import urllib.request

    from llm_consensus_tpu.engine.tokenizer import ByteTokenizer
    from llm_consensus_tpu.server import metrics as _metrics
    from llm_consensus_tpu.server.client import (
        GatewayClient,
        GatewayHTTPError,
    )
    from llm_consensus_tpu.server.gateway import (
        Gateway,
        GatewayConfig,
        GatewayThread,
    )
    from llm_consensus_tpu.serving.continuous import ContinuousConfig
    from llm_consensus_tpu.serving.fleet import (
        FleetBackend,
        FleetConfig,
        ReplicaSet,
    )
    from llm_consensus_tpu.serving.remote_store import RemotePageStore

    pg = 64
    salt = int(time.time() * 1e6) % 999983
    header_target = max(args.prompt_len, 2 * pg + 16)
    header = f"Disagg header {salt}: " + "shared context " * (
        -(-header_target // 15)
    )
    n = args.serve_requests
    uniq_pad = "distinct traffic padding " * (-(-header_target // 25))
    prompts = [
        header + f"Q{i}: propose for item {i * 37 % 101}"
        for i in range(n // 2)
    ] + [f"{i} unique {salt}: " + uniq_pad for i in range(n - n // 2)]
    longest = max(len(p) for p in prompts) + 1
    buckets = [64]
    while buckets[-1] < longest:
        buckets.append(buckets[-1] * 2)
    pages_per_seq = _serve_pages_per_seq(
        buckets[-1], args.new_tokens, args.serve_chunk, pg
    )
    host_bytes = args.serve_host_cache_mb << 20
    serve_config = ContinuousConfig(
        max_slots=args.serve_slots,
        page_size=pg,
        # Pool sized ABOVE the burst working set: leg A isolates the
        # role split + transport, so eviction pressure stays out.
        n_pages=1 + args.serve_slots * pages_per_seq * 2,
        pages_per_seq=pages_per_seq,
        max_new_tokens=args.new_tokens,
        seq_buckets=tuple(buckets),
        steps_per_sync=args.serve_chunk,
        prefill_chunk=args.serve_prefill_chunk or 64,
        share_prefix=True,
        host_cache_bytes=host_bytes,
    )

    # Remote page-store servers: real second processes on localhost.
    # Each transport mode gets a FRESH one, so both serve the identical
    # burst from a cold store (the per-pair text gate compares them).
    def spawn_store():
        proc = subprocess.Popen(
            [
                sys.executable,
                "-m",
                "llm_consensus_tpu.serving.remote_store",
                "--budget-mb",
                str(args.serve_host_cache_mb),
                "--port",
                "0",
            ],
            stdout=subprocess.PIPE,
            stderr=subprocess.DEVNULL,
            text=True,
        )
        ln = ""
        try:
            ln = proc.stdout.readline()
            ep = _json.loads(ln)["endpoint"]
        except Exception:
            proc.kill()
            print(
                f"[bench] remote store server failed to start: {ln!r}",
                file=sys.stderr,
            )
            return None, None
        print(f"[bench] remote page store at {ep}", file=sys.stderr)
        return proc, ep

    server, endpoint = spawn_store()
    if server is None:
        return 2

    def warm(fleet):
        futs = [
            fleet.submit_to(
                i, f"warmup {salt} r{i} " + "ctx " * (header_target // 5),
                max_new_tokens=args.new_tokens,
            )
            for i in range(2)
        ]
        for f in futs:
            f.result(timeout=600)

    def run(role, host_store=None, fleet_kw=None):
        fleet = ReplicaSet(
            cfg,
            params,
            config=serve_config,
            fleet=FleetConfig(
                replicas=2,
                role=role,
                policy="prefix",
                **(fleet_kw or {}),
            ),
            host_store=host_store,
        )
        try:
            warm(fleet)
            t0 = time.perf_counter()
            futs = [
                fleet.submit(
                    p, max_new_tokens=args.new_tokens, temperature=0.0
                )
                for p in prompts
            ]
            results = [f.result(timeout=600) for f in futs]
            wall = time.perf_counter() - t0
            toks = sum(r.num_tokens for r in results)
            stats = fleet.stats()
        finally:
            if host_store is None:
                fleet.close()
            # The disagg fleet is reused by the degrade leg (leg B).
        return fleet, results, toks / wall, stats

    # Full header pages every shared-header request must receive via
    # share/restore (the fleets run the default ByteTokenizer).
    header_pages = len(ByteTokenizer().encode(header)) // pg

    # -- leg 0: loopback wire microbench (v1 pickle vs v2 zero-copy) ----
    # Raw transport race over ONE in-process server: the same logical
    # workload (demote N pages, restore N pages) through the v1 client
    # (pickled frames, one blocking RTT per page) and the v2 client
    # (scatter-gather zero-copy frames, batched put_many/get_run). The
    # clients' own tx/rx mirrors count PLANE PAYLOAD bytes only on both
    # wires, so bytes/s compares the useful freight, not framing.
    def wire_bps() -> tuple[float, float]:
        import numpy as _np

        from llm_consensus_tpu.serving.offload import HostPageStore
        from llm_consensus_tpu.serving.remote_store import PageStoreServer

        srv = PageStoreServer(HostPageStore(1 << 30)).start()
        best = {"v1": 0.0, "v2": 0.0}
        try:
            rng = _np.random.default_rng(7)
            plane = rng.integers(0, 255, size=1 << 20, dtype=_np.uint8)
            n_pages = 24

            def one(wire: str, rnd: int) -> float:
                client = RemotePageStore(
                    srv.endpoint, wire=wire, timeout_s=60.0
                )
                keys = [("wire", wire, rnd, i) for i in range(n_pages)]
                t0 = time.perf_counter()
                if wire == "v2":
                    client.put_many([(k, (plane, plane)) for k in keys])
                    got = client.get_run(keys)
                else:
                    for k in keys:
                        client.put(k, (plane, plane))
                    got = [client.get(k) for k in keys]
                wall = time.perf_counter() - t0
                moved = client.tx_bytes + client.rx_bytes
                client.close()
                if len(got) != n_pages or any(g is None for g in got):
                    return 0.0  # transport broke: fail the gate
                return moved / wall

            # Best-of alternating rounds (the PR-5 convention): on a
            # quiet box one round clears the 2x gate with margin
            # (~2.4-2.8x measured), but under co-running tenant load
            # both legs collapse toward scheduler-jitter floor and the
            # RATIO compresses (observed 1.61x at loadavg ~5) — the
            # bests across extra rounds recover each leg's clean-run
            # ceiling, which is what the gate is about. A REAL v2
            # regression fails every round.
            rnd = 0
            while True:
                for wire in ("v1", "v2") if rnd % 2 == 0 else ("v2", "v1"):
                    bps = one(wire, rnd)
                    if bps <= 0.0:
                        return 0.0, 0.0
                    best[wire] = max(best[wire], bps)
                rnd += 1
                if best["v2"] >= 2.0 * best["v1"] > 0.0:
                    break
                la, contended = _box_contended()
                budget = 6 if contended else 3
                if rnd >= budget:
                    break
                print(
                    f"[bench] wire microbench: best ratio "
                    f"{best['v2'] / max(best['v1'], 1e-9):.2f}x below 2x "
                    f"(loadavg {la if la is None else round(la, 2)}, "
                    f"contended={contended}); extra round "
                    f"{rnd + 1}/{budget}",
                    file=sys.stderr,
                )
        finally:
            srv.close()
        return best["v1"], best["v2"]

    bps_v1, bps_v2 = wire_bps()
    gate_wire = bps_v2 >= 2.0 * bps_v1 > 0.0
    print(
        f"[bench] wire microbench: v1 {bps_v1 / 1e6:.0f} MB/s, "
        f"v2 {bps_v2 / 1e6:.0f} MB/s ({bps_v2 / max(bps_v1, 1e-9):.2f}x)",
        file=sys.stderr,
    )

    # -- transport mode A: the PR-16 shape (v1 wire, sync handoff, no
    # prefetch) over its own fresh store server --------------------------
    store_sync = RemotePageStore(endpoint, wire="v1")
    fleet_sync, res_sync, tps_sync, s_sync = run(
        ("prefill", "decode"),
        store_sync,
        fleet_kw=dict(handoff_stream=False, prefetch=False),
    )
    texts_sync = [r.text for r in res_sync]
    handoff_s_sync = s_sync["handoff_seconds_sum"] / max(
        1, s_sync["handoff_seconds_count"]
    )
    fleet_sync.close()
    store_sync.close()
    server.kill()
    server.wait(timeout=30)

    # -- transport mode B (= leg A): zero-copy v2 wire, streamed
    # handoff, route-driven prefetch — a fresh server, same burst ------
    server, endpoint = spawn_store()
    if server is None:
        return 2
    store = RemotePageStore(endpoint)
    fleet, res_dis, tps_dis, s_dis = run(("prefill", "decode"), store)
    _, res_mix, tps_mix, s_mix = run("mixed")
    texts_dis = [r.text for r in res_dis]
    texts_mix = [r.text for r in res_mix]
    text_equal = texts_dis == texts_mix and texts_dis == texts_sync
    handoff_s = s_dis["handoff_seconds_sum"] / max(
        1, s_dis["handoff_seconds_count"]
    )
    # PR-5 dual-gate band on the claim-to-exported handoff latency:
    # the streamed path must be no worse than sync within 2% plus a
    # small absolute floor (single-sample legs on a shared box see
    # scheduler jitter far above 2% of a millisecond-scale export).
    gate_transport = handoff_s <= handoff_s_sync * 1.02 + 0.05
    prefetch_hits = sum(
        r.get("prefetch_hit_pages", 0) for r in s_dis["per_replica"]
    )
    prefetch_fetched = sum(
        r.get("prefetch_fetched_pages", 0) for r in s_dis["per_replica"]
    )
    handoffs = s_dis.get("role_handoffs", 0)
    # Decode-side header provenance: every shared-header request's
    # header pages must have arrived SHARED (CoW off a resident mate)
    # or RESTORED (from the remote store) — zero re-prefilled.
    recomputed = 0
    restored_hdr = 0
    for r in res_dis[: n // 2]:
        t = r.timing or {}
        got = t.get("header_pages_shared", 0) + t.get(
            "header_pages_restored", 0
        )
        recomputed += max(0, header_pages - got)
        restored_hdr += t.get("header_pages_restored", 0)

    # -- leg B: kill the store; serving must degrade, not wedge ---------
    def _reg_sum(prefix):
        return sum(
            v
            for kk, v in _metrics.REGISTRY.snapshot().items()
            if kk.startswith(prefix)
        )

    err_before = _reg_sum("gateway_remote_store_errors_total")
    server.kill()
    server.wait(timeout=30)
    backend = FleetBackend(fleet)
    gw = GatewayThread(Gateway(backend, config=GatewayConfig(port=0))).start()
    errors: list[str] = []

    def degrade_call(client, prompt):
        try:
            r = client.generate(
                prompt, max_new_tokens=args.new_tokens, temperature=0.0
            )
            if not isinstance(r.get("text"), str):
                errors.append(f"no text: {r}")
        except GatewayHTTPError as e:
            errors.append(f"HTTP {e.status}")
        except Exception as e:  # noqa: BLE001 - counted, not raised
            errors.append(repr(e))

    import threading as _threading

    try:
        client = GatewayClient("127.0.0.1", gw.port, timeout=600.0)
        h2 = f"Degrade header {salt}: " + "shared context " * (
            -(-header_target // 15)
        )
        burst = [h2 + f"D{i}: degraded" for i in range(max(2, n // 2))]
        threads = [
            _threading.Thread(target=degrade_call, args=(client, p))
            for p in burst
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        with urllib.request.urlopen(
            f"http://127.0.0.1:{gw.port}/readyz", timeout=30
        ) as resp:
            ready_status = resp.status
    except urllib.error.HTTPError as e:
        ready_status = e.code
    finally:
        gw.drain()
        fleet.close()
        store.close()
        if server.poll() is None:
            server.kill()
    err_after = _reg_sum("gateway_remote_store_errors_total")
    store_errors = err_after - err_before
    e429 = sum(1 for e in errors if e == "HTTP 429")
    lost = len(errors)

    gate_handoff = handoffs >= 1 and recomputed == 0 and restored_hdr >= 1
    gate_degrade = (
        lost == 0 and e429 == 0 and ready_status == 200 and store_errors > 0
    )
    status = (
        "ok"
        if (
            text_equal
            and gate_handoff
            and gate_degrade
            and gate_wire
            and gate_transport
        )
        else "failed"
    )
    # Side channels first (unit-tagged so scripts/bench_history.py's
    # same-unit rule never ratios them against the tok/s trajectory),
    # headline tok/s last — the line drivers tail.
    _emit(
        {
            "metric": f"handoff claim-to-exported latency, streamed v2 "
            f"transport ({cfg.name}; sync v1 baseline "
            f"{handoff_s_sync:.3f}s)",
            "value": round(handoff_s, 4),
            "unit": "seconds",
            "vs_baseline": round(handoff_s / max(handoff_s_sync, 1e-9), 4),
            "status": "ok" if gate_transport else "failed",
        },
        None,
    )
    _emit(
        {
            "metric": "page-store wire throughput, zero-copy v2 "
            f"scatter-gather (loopback, 24x2MiB pages; v1 pickle "
            f"baseline {bps_v1 / 1e6:.0f} MB/s)",
            "value": round(bps_v2, 0),
            "unit": "bytes/sec",
            "vs_baseline": round(bps_v2 / max(bps_v1, 1e-9), 4),
            "status": "ok" if gate_wire else "failed",
        },
        None,
    )
    _emit(
        {
            "metric": f"serving tok/s, disaggregated prefill/decode "
            f"({cfg.name}, roles prefill+decode over remote store, "
            f"{n} mixed reqs, slots={args.serve_slots}/replica, "
            f"decode {args.new_tokens} @ ~{header_target} header, "
            f"handoffs {handoffs}, header pages {header_pages}/req: "
            f"{restored_hdr} restored / {recomputed} re-prefilled on "
            f"decode side, mixed-role control {tps_mix:.0f} tok/s, "
            f"sync-v1 transport {tps_sync:.0f} tok/s @ "
            f"{handoff_s_sync:.3f}s handoff vs streamed {handoff_s:.3f}s, "
            f"wire v2 {bps_v2 / 1e6:.0f} MB/s vs v1 "
            f"{bps_v1 / 1e6:.0f} MB/s, prefetch "
            f"{prefetch_hits}/{prefetch_fetched} staged pages consumed, "
            f"degrade burst {len(burst)} reqs: 429s {e429}, lost "
            f"{lost}, readyz {ready_status}, store errors "
            f"{store_errors}, text unchanged={text_equal})",
            "value": round(tps_dis, 2),
            "unit": "tokens/sec",
            "vs_baseline": round(tps_dis / max(tps_mix, 1e-9), 4),
            "status": status,
        },
        args.out,
    )
    if not gate_wire:
        print(
            f"[bench] wire gate failed: v2 {bps_v2 / 1e6:.0f} MB/s is "
            f"not >= 2x v1 {bps_v1 / 1e6:.0f} MB/s on loopback",
            file=sys.stderr,
        )
    if not gate_transport:
        print(
            f"[bench] transport gate failed: streamed handoff "
            f"{handoff_s:.3f}s vs sync {handoff_s_sync:.3f}s is outside "
            f"the dual-gate band",
            file=sys.stderr,
        )
    if not text_equal:
        print(
            "[bench] GENERATED TEXT DIVERGED between the disaggregated "
            "fleet and the mixed-role control — the cross-process "
            "restore contract is broken",
            file=sys.stderr,
        )
    if not gate_handoff:
        print(
            f"[bench] handoff gate failed: handoffs {handoffs}, "
            f"{recomputed} header pages re-prefilled on the decode "
            f"side, {restored_hdr} restored",
            file=sys.stderr,
        )
    if not gate_degrade:
        print(
            f"[bench] degrade gate failed: {e429} x 429, {lost} lost "
            f"({errors[:5]}), readyz {ready_status}, store errors "
            f"{store_errors}",
            file=sys.stderr,
        )
    return 0 if status == "ok" else 1


def _bench_serve_fleet_obs(args, cfg, params) -> int:
    """Fleet observability federation overhead A/B (PR 20).

    Topology (three REAL processes): this process runs a front
    gateway (FakeBackend; every /v1/* forwards) whose one peer is a
    ``serve --backend continuous --replicas 2 --role prefill,decode``
    SUBPROCESS whose fleet host tier is a remote page-store
    subprocess — the full disagg path, crossed by real sockets. Two
    such stacks boot side by side: federation/propagation ON (the
    default) and OFF (``--no-fleet-obs`` on the peer, ``fleet_obs=
    False`` on its front); alternating rounds drive the identical
    burst through each.

    Gates:
    - ON tok/s within the PR-5 dual 2% band of OFF (loadavg-aware
      escalation) — the observability plane must be ~free.
    - >= 1 cross-process JOINED trace in the merged fleet export: a
      flight event scraped from the PEER PROCESS carrying a trace id
      the front minted for one of this burst's requests, and the
      merged timeline monotone after clock correction.
    - The ON responses' ``meta["hops"]`` sums track the client-
      measured e2e latency (median within tolerance).
    - Byte-identical text across ON/OFF (both peers init the same
      PRNGKey(0) random weights; observability must not touch
      sampling).
    """
    import json as _json
    import queue as _queue
    import re as _re
    import subprocess
    import threading as _threading

    from llm_consensus_tpu.backends.fake import FakeBackend
    from llm_consensus_tpu.server.client import GatewayClient
    from llm_consensus_tpu.server.gateway import (
        Gateway,
        GatewayConfig,
        GatewayThread,
    )
    from llm_consensus_tpu.server.metrics import MetricsRegistry

    pg = 64
    salt = int(time.time() * 1e6) % 999983
    header_target = max(args.prompt_len, 2 * pg + 16)
    header = f"Fleet obs header {salt}: " + "shared context " * (
        -(-header_target // 15)
    )
    n = args.serve_requests
    prompts = [
        header + f"Q{i}: item {i * 37 % 101}" for i in range(n // 2)
    ] + [
        f"{i} unique {salt}: " + "distinct padding " * 8
        for i in range(n - n // 2)
    ]

    def spawn_store():
        proc = subprocess.Popen(
            [
                sys.executable,
                "-m",
                "llm_consensus_tpu.serving.remote_store",
                "--budget-mb",
                str(max(16, args.serve_host_cache_mb)),
                "--port",
                "0",
            ],
            stdout=subprocess.PIPE,
            stderr=subprocess.DEVNULL,
            text=True,
        )
        try:
            ep = _json.loads(proc.stdout.readline())["endpoint"]
        except Exception:
            proc.kill()
            return None, None
        return proc, ep

    def spawn_peer(store_ep: str, fleet_obs: bool):
        cmd = [
            sys.executable,
            "-m",
            "llm_consensus_tpu",
            "serve",
            "--port",
            "0",
            "--backend",
            "continuous",
            "--model",
            cfg.name,
            "--replicas",
            "2",
            "--role",
            "prefill,decode",
            "--serve-slots",
            str(args.serve_slots),
            "--prefill-chunk",
            str(args.serve_prefill_chunk or 64),
            "--host-cache-mb",
            str(max(16, args.serve_host_cache_mb)),
            "--host-store",
            store_ep,
            "--max-new-tokens",
            str(args.new_tokens),
        ]
        if not fleet_obs:
            cmd.append("--no-fleet-obs")
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        return subprocess.Popen(
            cmd,
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
            env=env,
        )

    def peer_port(proc, tag: str) -> int | None:
        lines: _queue.Queue = _queue.Queue()
        _threading.Thread(
            target=lambda: [lines.put(ln) for ln in proc.stdout],
            daemon=True,
        ).start()
        deadline = time.time() + 300
        while time.time() < deadline:
            try:
                line = lines.get(timeout=1.0)
            except _queue.Empty:
                if proc.poll() is not None:
                    break
                continue
            m = _re.search(r"listening on 127\.0\.0\.1:(\d+)", line)
            if m:
                return int(m.group(1))
        print(
            f"[bench] {tag} serve subprocess never bound", file=sys.stderr
        )
        return None

    stacks: dict[bool, dict] = {}
    procs: list = []
    try:
        for fleet_obs in (True, False):
            sproc, sep = spawn_store()
            if sproc is None:
                print(
                    "[bench] remote store failed to start",
                    file=sys.stderr,
                )
                return 2
            procs.append(sproc)
            stacks[fleet_obs] = {"store": sproc, "store_ep": sep}
        # Boot both serve subprocesses concurrently (each inits its own
        # random tiny weights — the slow part), then read both ports.
        for fleet_obs in (True, False):
            p = spawn_peer(stacks[fleet_obs]["store_ep"], fleet_obs)
            procs.append(p)
            stacks[fleet_obs]["peer"] = p
        for fleet_obs in (True, False):
            port = peer_port(
                stacks[fleet_obs]["peer"],
                "fleet-obs" if fleet_obs else "no-fleet-obs",
            )
            if port is None:
                return 2
            url = f"http://127.0.0.1:{port}"
            stacks[fleet_obs]["peer_url"] = url
            gw = Gateway(
                FakeBackend(),
                config=GatewayConfig(
                    port=0,
                    peers=(url,),
                    fleet_obs=fleet_obs,
                    peer_timeout_s=600.0,
                ),
                registry=MetricsRegistry(),
            )
            stacks[fleet_obs]["front"] = GatewayThread(gw).start()

        texts: dict[bool, list] = {True: [], False: []}
        on_samples: list[tuple[float, dict, str]] = []  # (e2e, hops, tid)

        def leg(tag: str, on: bool) -> float:
            front = stacks[on]["front"]
            results: list = [None] * len(prompts)

            def one(i: int, prompt: str) -> None:
                client = GatewayClient(
                    "127.0.0.1", front.port, timeout=600.0
                )
                t0 = time.perf_counter()
                try:
                    r = client.generate(
                        prompt,
                        max_new_tokens=args.new_tokens,
                        temperature=0.0,
                    )
                except Exception as e:  # noqa: BLE001 - fails text gate
                    r = {"num_tokens": 0, "text": f"<error: {e!r}>"}
                results[i] = (time.perf_counter() - t0, r)

            t0 = time.perf_counter()
            threads = [
                _threading.Thread(target=one, args=(i, p))
                for i, p in enumerate(prompts)
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            wall = time.perf_counter() - t0
            toks = sum(r["num_tokens"] for _, r in results)
            texts[on] = [r["text"] for _, r in results]
            if on:
                for e2e, r in results:
                    hops = (r.get("meta") or {}).get("hops") or {}
                    if hops and r.get("trace_id"):
                        on_samples.append((e2e, hops, r["trace_id"]))
            tps = toks / wall
            print(
                f"[bench] fleet-obs leg {tag}: {tps:.1f} tok/s "
                f"({len(prompts)} reqs, {wall:.2f}s)",
                file=sys.stderr,
            )
            return tps

        # One warm-up request per stack first: the peers' cold JIT
        # compiles must not land inside a timed round asymmetrically.
        for on in (True, False):
            GatewayClient(
                "127.0.0.1", stacks[on]["front"].port, timeout=600.0
            ).generate(
                header + " warmup",
                max_new_tokens=args.new_tokens,
                temperature=0.0,
            )

        runs_off, runs_on = _ab_rounds(leg, 2)
        _ab_escalate(leg, runs_off, runs_on, "serve-fleet-obs")
        gate_tps = _dual_gate_ok(runs_off, runs_on)
        text_equal = texts[True] == texts[False]

        # -- joined-trace gate: the merged export must witness a PEER-
        # process event carrying a front-minted id of this burst ------
        on_front = stacks[True]["front"]
        on_peer_url = stacks[True]["peer_url"]
        fclient = GatewayClient("127.0.0.1", on_front.port, timeout=60.0)
        merged = fclient._json(
            "GET", "/debug/flight?fleet=1&limit=100000"
        )
        tids = {tid for _, _, tid in on_samples}
        peer_joined = [
            e
            for e in merged["events"]
            if e.get("host") == on_peer_url and e.get("trace_id") in tids
        ]
        t0s = [e["t0"] for e in merged["events"]]
        monotone = t0s == sorted(t0s)
        chrome = fclient._json(
            "GET", "/debug/flight?fleet=1&format=chrome"
        )
        chrome_hosts = {
            ev["args"]["name"]
            for ev in chrome["traceEvents"]
            if ev.get("name") == "process_name"
        }
        chrome_ok = {"self serving", f"{on_peer_url} serving"} <= (
            chrome_hosts
        )
        gate_join = bool(peer_joined) and monotone and chrome_ok

        # -- hop-sum vs client e2e (median over the ON rounds) --------
        errs = sorted(
            abs(sum(h.values()) - e2e) / max(e2e, 1e-9)
            for e2e, h, _ in on_samples
        )
        med_err = errs[len(errs) // 2] if errs else 1.0
        gate_hops = bool(on_samples) and med_err <= 0.15

        # Federation text view sanity (host= labels from both tiers).
        fed = fclient._request("GET", "/metrics?fleet=1")[1].decode()
        fed_ok = 'host="self"' in fed and f'host="{on_peer_url}"' in fed

        status = (
            "ok"
            if (
                gate_tps
                and gate_join
                and gate_hops
                and text_equal
                and fed_ok
            )
            else "failed"
        )
        overhead = _paired_overhead_pct(runs_off, runs_on)
        _emit(
            {
                "metric": f"serving tok/s, fleet observability ON "
                f"({cfg.name}, front->serve[prefill,decode]->store, 3 "
                f"processes, {n} reqs x {args.new_tokens} tokens; OFF "
                f"control best {max(runs_off):.1f} tok/s, paired "
                f"overhead {overhead:.2f}%, joined peer events "
                f"{len(peer_joined)}, merged monotone={monotone}, "
                f"hop-sum median err {med_err * 100:.1f}% vs client "
                f"e2e over {len(on_samples)} reqs, federation "
                f"host-labels={fed_ok}, text unchanged={text_equal})",
                "value": round(max(runs_on), 2),
                "unit": "tokens/sec",
                "vs_baseline": round(
                    max(runs_on) / max(max(runs_off), 1e-9), 4
                ),
                "status": status,
            },
            args.out,
        )
        if not gate_tps:
            print(
                f"[bench] fleet-obs overhead gate failed: paired "
                f"{overhead:.2f}%, best ratio "
                f"{max(runs_on) / max(max(runs_off), 1e-9):.4f}",
                file=sys.stderr,
            )
        if not gate_join:
            print(
                f"[bench] joined-trace gate failed: peer events "
                f"{len(peer_joined)}, monotone={monotone}, "
                f"chrome hosts={sorted(chrome_hosts)}",
                file=sys.stderr,
            )
        if not gate_hops:
            print(
                f"[bench] hop-sum gate failed: median err "
                f"{med_err * 100:.1f}% over {len(on_samples)} samples",
                file=sys.stderr,
            )
        if not text_equal:
            print(
                "[bench] GENERATED TEXT DIVERGED between the fleet-obs "
                "ON and OFF stacks",
                file=sys.stderr,
            )
        return 0 if status == "ok" else 1
    finally:
        for key in (True, False):
            front = stacks.get(key, {}).get("front")
            if front is not None:
                try:
                    front.drain()
                except Exception:  # noqa: BLE001 - teardown best effort
                    pass
        for p in procs:
            if p.poll() is None:
                p.terminate()
        for p in procs:
            try:
                p.wait(timeout=30)
            except Exception:  # noqa: BLE001 - teardown best effort
                p.kill()


def _bench_serving_offload(args, cfg, params) -> int:
    """Hierarchical-KV A/B: the multi-round panel shape over a starved
    page pool, host offload tier on vs off.

    Round 1 serves the panel burst (one shared header, unique
    question tails); a filler round of unique-prefix requests then
    forces registry eviction — with the tier ON the header pages
    demote to host RAM, OFF they are destroyed; the re-vote round
    re-sends the same header, which the ON leg RESTORES (device_put
    between decode steps) and the OFF leg re-prefills. Reports
    restored pages, prompt tokens the restores saved, per-page restore
    latency, prefill-chunk counts for both legs, and the acceptance
    contract: generated text byte-identical across legs.
    """
    from llm_consensus_tpu.server.metrics import KV_RESTORE_SECONDS
    from llm_consensus_tpu.serving.continuous import (
        ContinuousBatcher,
        ContinuousConfig,
    )

    pg = 64
    salt = int(time.time() * 1e6) % 999983
    # Header covers >= 2 full pages even at small --prompt-len (full
    # pages are the demote/restore unit), tails stay short.
    header_target = max(args.prompt_len, 2 * pg + 16)
    header = f"Panel header {salt}: " + "shared context " * (
        -(-header_target // 15)
    )
    n = args.serve_requests
    # Filler round: prefixes unique from byte 0 (no cross-filler
    # sharing) and padded into the HEADER's bucket, so concurrent
    # filler admissions demand the whole starved pool and eviction
    # must walk past the per-request tail leaves up into the header's
    # chain (evict drops childless leaves first — short fillers would
    # only ever shave the leaves and prove nothing).
    filler_pad = "unrelated traffic padding " * (-(-header_target // 25))
    rounds = [
        [header + f"Q{i}: propose for item {i * 37 % 101}" for i in range(n)],
        [f"{i} filler {salt}: " + filler_pad for i in range(n)],
        [header + f"R{i}: re-vote on item {i * 37 % 101}" for i in range(n)],
    ]
    longest = max(len(p) for r in rounds for p in r) + 1
    buckets = [64]
    while buckets[-1] < longest:
        buckets.append(buckets[-1] * 2)
    pages_per_seq = _serve_pages_per_seq(
        buckets[-1], args.new_tokens, args.serve_chunk, pg
    )
    # The point of the leg: the pool holds exactly the slots' unshared
    # working set and NOTHING more, so cached prefixes cannot stay
    # device-resident across rounds — eviction pressure is guaranteed.
    n_pages = 1 + args.serve_slots * pages_per_seq

    def run(host_cache_bytes: int):
        batcher = ContinuousBatcher(
            cfg,
            params,
            config=ContinuousConfig(
                max_slots=args.serve_slots,
                page_size=pg,
                n_pages=n_pages,
                pages_per_seq=pages_per_seq,
                max_new_tokens=args.new_tokens,
                seq_buckets=tuple(buckets),
                steps_per_sync=args.serve_chunk,
                prefill_chunk=args.serve_prefill_chunk or 64,
                share_prefix=True,
                host_cache_bytes=host_cache_bytes,
            ),
        )
        try:
            batcher.submit(
                f"warmup {salt} " + "ctx " * (args.prompt_len // 5),
                max_new_tokens=args.new_tokens,
            ).result(timeout=600)
            texts = []
            t0 = time.perf_counter()
            toks = 0
            for burst in rounds:
                futs = [
                    batcher.submit(p, max_new_tokens=args.new_tokens)
                    for p in burst
                ]
                results = [f.result(timeout=600) for f in futs]
                texts.append([r.text for r in results])
                toks += sum(r.num_tokens for r in results)
            wall = time.perf_counter() - t0
            stats = batcher.stats()
        finally:
            batcher.close()
        return texts, toks / wall, stats

    r_before = (KV_RESTORE_SECONDS.sum, KV_RESTORE_SECONDS.count)
    texts_on, tps_on, s_on = run(args.serve_host_cache_mb << 20)
    r_sum = KV_RESTORE_SECONDS.sum - r_before[0]
    r_cnt = KV_RESTORE_SECONDS.count - r_before[1]
    texts_off, tps_off, s_off = run(0)
    unchanged = texts_on == texts_off
    restored = s_on["offload_restored_pages"]
    tokens_saved = restored * pg
    restore_ms = 1e3 * r_sum / r_cnt if r_cnt else 0.0
    _emit(
        {
            "metric": f"serving tok/s, hierarchical KV offload "
            f"({cfg.name}, 3x{n} reqs, slots={args.serve_slots}, "
            f"pool={n_pages} pages [working-set starved], host tier "
            f"{args.serve_host_cache_mb} MiB, decode {args.new_tokens} "
            f"@ ~{header_target} shared header, demoted "
            f"{s_on['offload_demoted_pages']} / restored {restored} / "
            f"dropped {s_on['offload_dropped_pages']} pages, prefill "
            f"tokens saved {tokens_saved}, restore avg {restore_ms:.1f} "
            f"ms/page, chunks ON {s_on['prefill_chunks']} vs OFF "
            f"{s_off['prefill_chunks']}, tier-off {tps_off:.0f} tok/s, "
            f"text unchanged={unchanged})",
            "value": round(tps_on, 2),
            "unit": "tokens/sec",
            "vs_baseline": round(tps_on / max(tps_off, 1e-9), 4),
        },
        args.out,
    )
    if not unchanged:
        print(
            "[bench] GENERATED TEXT DIVERGED between offload-on and "
            "offload-off serving — restore regression",
            file=sys.stderr,
        )
        return 1
    # The leg exists to demonstrate restores: a run where nothing
    # demoted+restored proves nothing (pool sizing regression).
    return 0 if restored > 0 and tokens_saved > 0 else 1


def _bench_serving(args, cfg, params) -> int:
    """Continuous-batching throughput: a burst of requests interleaved
    at decode-step granularity over the paged cache (the paged Pallas
    decode-attention kernel on TPU). Reports requests/sec; tokens/sec
    rides in the metric string."""
    from llm_consensus_tpu.serving.continuous import (
        ContinuousBatcher,
        ContinuousConfig,
    )

    pg = 64
    # Capacity sized from the REQUESTED prompt length: the largest seq
    # bucket must hold it (the batcher left-truncates past the largest
    # bucket, which would silently bench a smaller workload than the
    # metric string claims).
    buckets = [64]
    shared = args.serve_shared_prefix
    # Shared-prefix leg: prefix (~prompt_len) + unique suffix must fit.
    cap_target = args.prompt_len + (64 if shared else 0)
    while buckets[-1] < cap_target:
        buckets.append(buckets[-1] * 2)
    pages_per_seq = _serve_pages_per_seq(
        buckets[-1], args.new_tokens, args.serve_chunk, pg
    )
    n_pages = 1 + args.serve_slots * pages_per_seq * 2  # 2x headroom
    batcher = ContinuousBatcher(
        cfg,
        params,
        config=ContinuousConfig(
            max_slots=args.serve_slots,
            page_size=pg,
            n_pages=n_pages,
            pages_per_seq=pages_per_seq,
            max_new_tokens=args.new_tokens,
            seq_buckets=tuple(buckets),
            steps_per_sync=args.serve_chunk,
            prefill_chunk=args.serve_prefill_chunk,
            share_prefix=shared,
        ),
    )
    # Salted prompts (the tunnel runtime replays previously-seen
    # (executable, inputs) pairs — see main()); byte tokenizer: 1 token
    # per byte, so pad with 13-byte repeats to ~prompt_len tokens.
    salt = int(time.time() * 1e6) % 999983
    if shared:
        # The consensus-panel shape: one ~prompt_len-token shared
        # header, a short unique question tail per request. The header
        # should prefill once (first admission) and page-share into the
        # other serve_requests-1 tables.
        header = f"Panel header {salt}: " + "shared context " * (
            max(0, args.prompt_len - 24) // 15
        )
        prompts = [
            header + f"Q{i}: item {i * 37 % 101}?"
            for i in range(args.serve_requests)
        ]
    else:
        prompts = [
            f"Request {salt}-{i}: summarize item {i * 37 % 101} "
            + "with context " * (max(0, args.prompt_len - 40) // 13)
            for i in range(args.serve_requests)
        ]
    try:
        # Warmup: compile prefill buckets + the decode-step program. A
        # prompt OUTSIDE the burst set — re-running an identical prompt
        # in the timed window would replay from the runtime's result
        # cache (the replay hazard above) and inflate requests/sec.
        warm = f"warmup {salt} " + "with context " * (
            max(0, args.prompt_len - 40) // 13
        )
        batcher.submit(warm, max_new_tokens=args.new_tokens).result(
            timeout=600
        )
        before = batcher.stats()
        if shared:
            from llm_consensus_tpu.server.metrics import REGISTRY as _SREG

            _stall = _SREG.get("gateway_prefill_stall_seconds")
            stall_before = (
                (_stall.sum, _stall.count) if _stall else (0.0, 0)
            )
        t0 = time.perf_counter()
        futs = [
            batcher.submit(p, max_new_tokens=args.new_tokens)
            for p in prompts
        ]
        results = [f.result(timeout=600) for f in futs]
        wall = time.perf_counter() - t0
    finally:
        batcher.close()
    n_tokens = sum(r.num_tokens for r in results)
    rps = len(results) / wall
    after = batcher.stats()
    # Timed-window deltas only (warmup decoded solo before t0).
    steps = after["decode_steps"] - before["decode_steps"]
    prefix_note = ""
    if shared:
        pages_shared = (
            after["prefix_pages_shared"] - before["prefix_pages_shared"]
        )
        hits = after["prefix_hits"] - before["prefix_hits"]
        looks = after["prefix_lookups"] - before["prefix_lookups"]
        # Timed-window delta: the warmup prompt's prefill (and the first
        # chunk program's COMPILE, orders of magnitude above steady
        # state) already sits in the process-wide histogram.
        d_sum = (_stall.sum if _stall else 0.0) - stall_before[0]
        d_cnt = (_stall.count if _stall else 0) - stall_before[1]
        stall_ms = 1e3 * d_sum / d_cnt if d_cnt else 0.0
        prefix_note = (
            f", prefix: {pages_shared} pages shared / "
            f"{after['prefix_pages_copied'] - before['prefix_pages_copied']}"
            f" copied, hit {hits}/{looks}, "
            f"chunks={after['prefill_chunks'] - before['prefill_chunks']}, "
            f"stall avg {stall_ms:.1f} ms"
        )
    _emit(
        {
            "metric": f"serving requests/sec ({cfg.name}, "
            f"{args.serve_requests} reqs, slots={args.serve_slots}, "
            f"decode {args.new_tokens} @ ~{args.prompt_len} prompt"
            + (" SHARED" if shared else "")
            + f", chunk={args.serve_chunk}, "
            f"prefill_chunk={args.serve_prefill_chunk}, "
            f"paged pallas={cfg.use_pallas}, "
            f"{n_tokens / wall:.0f} generated tok/s, "
            f"{steps} decode steps{prefix_note})",
            "value": round(rps, 2),
            "unit": "requests/sec",
            "vs_baseline": round(rps, 4),
        },
        args.out,
    )
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
