"""Debate EM + per-question latency with a REAL trained engine.

BASELINE.md config[4] (multi-round debate with iterative re-vote),
measured the way the reference's own UX is experienced — one question
at a time (``src/main.rs:430-464``) — so the report carries per-question
wall clock alongside EM, on whatever device runs it (the recorded runs
use the driver's TPU chip).

Narrow SFT models answer reliably only in their trained format, so the
debate uses the training prompt as ``initial_template`` and a revise
template that embeds peers' answers ahead of the known format
(``DebateConfig.initial_template/revise_template``, the round-4
configurable-template work).

Usage:
    python examples/debate_arith_eval.py --ckpt runs/arith14m \
        [--task arith|arith2] [--model <preset>] --report out.json
"""

from __future__ import annotations

import argparse
import json
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).parent.parent))

import jax
import jax.numpy as jnp

from llm_consensus_tpu.checkpoint.io import restore_params_for_inference
from llm_consensus_tpu.consensus.debate import DebateConfig, run_debate
from llm_consensus_tpu.consensus.voting import extract_final_number
from llm_consensus_tpu.engine.engine import EngineConfig, InferenceEngine
from llm_consensus_tpu.engine.tokenizer import ByteTokenizer
from llm_consensus_tpu.eval.gsm8k import _PROMPT, exact_match
from llm_consensus_tpu.models.configs import get_config

# Revise template in the trained format: peers' answers arrive as
# leading context, then the EXACT prompt shape the model was trained on
# (everything after the peers block is byte-identical to _PROMPT).
_REVISE_TRAINED = (
    "Other attempts at this problem answered: {peers}\n\n" + _PROMPT
)


def main() -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--ckpt", default="runs/arith14m")
    p.add_argument("--model", default="")
    p.add_argument("--task", default="arith", choices=("arith", "arith2"))
    p.add_argument("--n-problems", type=int, default=20)
    p.add_argument("--n-candidates", type=int, default=8)
    p.add_argument("--max-rounds", type=int, default=2)
    p.add_argument("--temperature", type=float, default=0.7)
    p.add_argument("--quorum", type=float, default=0.9)
    p.add_argument("--max-new-tokens", type=int, default=0)
    p.add_argument("--method", default="majority")
    p.add_argument("--eval-seed", type=int, default=0)
    p.add_argument("--report", default="")
    p.add_argument("--cpu", action="store_true")
    args = p.parse_args()
    if args.cpu:
        jax.config.update("jax_platforms", "cpu")
    if not args.model:
        args.model = "arith-25m" if args.task == "arith2" else "arith-14m"
    if not args.max_new_tokens:
        args.max_new_tokens = 112 if args.task == "arith2" else 64

    if args.task == "arith2":
        from llm_consensus_tpu.eval.arith2 import eval_problems

        problems, _ = eval_problems(args.n_problems, seed=args.eval_seed)
    else:
        from llm_consensus_tpu.eval.arith import eval_split

        problems, _ = eval_split(args.n_problems, seed=args.eval_seed)

    cfg = get_config(args.model)
    params, step = restore_params_for_inference(cfg, args.ckpt, jnp.bfloat16)
    print(f"[debate] {cfg.name} @ step {step}", file=sys.stderr)
    engine = InferenceEngine(
        cfg,
        params,
        tokenizer=ByteTokenizer(),
        engine_config=EngineConfig(max_new_tokens=args.max_new_tokens),
    )
    dcfg = DebateConfig(
        n_candidates=args.n_candidates,
        max_rounds=args.max_rounds,
        temperature=args.temperature,
        quorum=args.quorum,
        max_new_tokens=args.max_new_tokens,
        method=args.method,
        initial_template=_PROMPT,
        revise_template=_REVISE_TRAINED,
        # Vote on the extracted final number (the EM key), not on whole
        # canonicalized texts — CoT wording varies per candidate.
    )

    correct = 0
    latencies, rounds_taken = [], []
    total_tokens = 0
    for i, prob in enumerate(problems):
        t0 = time.perf_counter()
        import dataclasses

        res = run_debate(
            engine,
            prob.question,
            dataclasses.replace(dcfg, seed=args.eval_seed * 1000 + i),
            key_fn=lambda t: extract_final_number(t) or "<none>",
        )
        latencies.append(time.perf_counter() - t0)
        rounds_taken.append(res.n_rounds)
        total_tokens += res.total_tokens
        pred = res.vote.winner if res.vote.winner != "<none>" else None
        ok = exact_match(pred, prob.answer)
        correct += ok
        print(
            f"[debate] q{i}: rounds={res.n_rounds} "
            f"t={latencies[-1]:.2f}s em={ok}",
            file=sys.stderr,
        )
    steady = sorted(latencies[1:]) or latencies
    out = {
        "model": cfg.name,
        "task": args.task,
        "n_problems": args.n_problems,
        "n_candidates": args.n_candidates,
        "max_rounds": args.max_rounds,
        "temperature": args.temperature,
        "quorum": args.quorum,
        "method": args.method,
        "em": round(correct / max(1, len(problems)), 4),
        "mean_rounds": (
            round(sum(rounds_taken) / len(rounds_taken), 2)
            if rounds_taken
            else None
        ),
        "total_candidate_tokens": total_tokens,
        "first_question_s": round(latencies[0], 3) if latencies else None,
        "latency_median_s": (
            round(steady[len(steady) // 2], 3) if steady else None
        ),
        "latency_max_s": round(max(steady), 3) if steady else None,
        "device": jax.devices()[0].platform,
    }
    print(json.dumps(out))
    if args.report:
        Path(args.report).parent.mkdir(parents=True, exist_ok=True)
        Path(args.report).write_text(json.dumps(out, indent=2))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
