"""Record the EM-vs-N self-consistency table on the bundled dataset.

Runs evaluate_self_consistency at N in {1, 3, 5, 9, 17} over
eval/data/gsm8k_mini.jsonl with the deterministic noisy-oracle candidate
stream (``--p`` per-candidate accuracy, default 0.6) so the table in
eval/EM_VS_N.md documents the *voting* effect reproducibly offline. For
model-accuracy numbers, call ``evaluate_self_consistency`` with a real
``InferenceEngine`` (weights via ``models/hf_loader.py``) instead of the
oracle — same harness, same report.

Usage: python examples/gsm8k_em_vs_n.py [--p 0.6] [--ns 1 3 5 9 17]
"""

import argparse
import json
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).parent.parent))

from llm_consensus_tpu.eval.gsm8k import (
    OracleEngine,
    evaluate_self_consistency,
    load_gsm8k,
)

DATA = (
    Path(__file__).parent.parent
    / "llm_consensus_tpu/eval/data/gsm8k_mini.jsonl"
)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--p", type=float, default=0.6)
    ap.add_argument("--ns", type=int, nargs="+", default=[1, 3, 5, 9, 17])
    args = ap.parse_args()

    problems = load_gsm8k(DATA)
    rows = []
    for n in args.ns:
        engine = OracleEngine(problems, args.p)
        rep = evaluate_self_consistency(
            engine, problems, n=n, temperature=0.7, seed=0
        )
        rows.append((n, rep.em))
        print(json.dumps({"n": n, "em": rep.em}))
    return rows


if __name__ == "__main__":
    main()
