"""Heterogeneous panel vote with REAL trained engines (config[3]).

BASELINE.md's config[3] is a weighted vote across DIFFERENT models.
This demo instantiates it with real checkpoints from the arithmetic
accuracy loop: by default three engines at different training maturities
(the 6000-step converged model, the 2500-step just-converged model, and
the 1500-step pre-transition model), each wrapped in its own
InferenceEngine and voting with its own weight through
``heterogeneous_panel_vote`` — the per-model calls fan out concurrently.
Mixing ARCHITECTURES works the same way: repeat ``--model``/``--ckpt``
pairs (e.g. arith-14m + arith-3m once both are trained).

EM is scored over held-out eval problems, demonstrating that a strong
model's weight can carry a panel diluted by weak members.

Usage:
    python examples/panel_arith_demo.py \
        --ckpt runs/arith14m --ckpt runs/arith14m_mid2 \
        --ckpt runs/arith14m_mid --weights 3,1,1 [--cpu]
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).parent.parent))

import jax
import jax.numpy as jnp

from llm_consensus_tpu.checkpoint.io import restore_params_for_inference
from llm_consensus_tpu.consensus.debate import DebateConfig, run_panel_debate
from llm_consensus_tpu.consensus.voting import (
    extract_final_number,
    heterogeneous_panel_vote,
)
from llm_consensus_tpu.engine.engine import EngineConfig, InferenceEngine
from llm_consensus_tpu.engine.tokenizer import ByteTokenizer
from llm_consensus_tpu.eval.arith import eval_split
from llm_consensus_tpu.eval.gsm8k import _PROMPT, exact_match
from llm_consensus_tpu.models.configs import get_config


def main() -> int:
    p = argparse.ArgumentParser()
    p.add_argument(
        "--ckpt",
        action="append",
        default=None,
        help="checkpoint dir (repeat; default: the three arith-14m "
        "training stages)",
    )
    p.add_argument(
        "--model",
        action="append",
        default=None,
        help="model preset per --ckpt (default arith-14m for each)",
    )
    p.add_argument("--weights", default="3,1,1")
    p.add_argument("--n-problems", type=int, default=20)
    p.add_argument("--n-per-model", type=int, default=4)
    p.add_argument("--temperature", type=float, default=0.7)
    p.add_argument("--max-new-tokens", type=int, default=64)
    p.add_argument(
        "--debate",
        type=int,
        default=0,
        metavar="ROUNDS",
        help="run run_panel_debate (cross-model debate with weighted "
        "vote + headcount quorum) for up to ROUNDS rounds per question "
        "instead of the single-round heterogeneous_panel_vote",
    )
    p.add_argument("--quorum", type=float, default=0.9)
    p.add_argument("--cpu", action="store_true")
    args = p.parse_args()
    if args.cpu:
        jax.config.update("jax_platforms", "cpu")

    ckpts = args.ckpt or [
        "runs/arith14m",
        "runs/arith14m_mid2",
        "runs/arith14m_mid",
    ]
    models = args.model or ["arith-14m"] * len(ckpts)
    weights = [float(w) for w in args.weights.split(",")]
    if not (len(ckpts) == len(models) == len(weights)):
        raise SystemExit("--ckpt/--model/--weights must align")

    tok = ByteTokenizer()
    engines = {}
    for i, (ckpt, model, w) in enumerate(zip(ckpts, models, weights)):
        cfg = get_config(model)
        params, step = restore_params_for_inference(cfg, ckpt, jnp.bfloat16)
        eng = InferenceEngine(
            cfg,
            params,
            tokenizer=tok,
            engine_config=EngineConfig(max_new_tokens=args.max_new_tokens),
        )
        # Index prefix: identical (model, dirname, step) members must
        # not collide in the dict and silently drop a weight.
        name = f"{i}:{model}@{Path(ckpt).name}(step {step})"
        engines[name] = (eng, w)
        print(f"[panel] member {i}: {name} weight={w}", file=sys.stderr)

    problems, _ = eval_split(args.n_problems, seed=0)
    correct = 0
    total_tokens = 0
    # Per-question wall clock: the reference's UX is interactive (one
    # question at a time at the REPL, src/main.rs:430-464), so what a
    # question COSTS end-to-end matters alongside EM. First question
    # carries compile time; report it separately from steady state.
    import time

    latencies = []
    rounds_taken = []
    for i, prob in enumerate(problems):
        t0 = time.perf_counter()
        if args.debate:
            # Narrow SFT members answer reliably only in their trained
            # format; peers arrive as leading context (the
            # debate_arith_eval.py convention).
            dres = run_panel_debate(
                engines,
                prob.question,
                DebateConfig(
                    n_candidates=args.n_per_model,
                    max_rounds=args.debate,
                    temperature=args.temperature,
                    quorum=args.quorum,
                    max_new_tokens=args.max_new_tokens,
                    seed=100 + i,
                    initial_template=_PROMPT,
                    revise_template=(
                        "Other attempts at this problem answered: "
                        "{peers}\n\n" + _PROMPT
                    ),
                ),
                key_fn=lambda t: extract_final_number(t) or "<none>",
            )
            rounds_taken.append(dres.n_rounds)
            total_tokens += dres.total_tokens
            winner = dres.vote.winner
        else:
            res = heterogeneous_panel_vote(
                engines,
                _PROMPT.format(q=prob.question),
                n_per_model=args.n_per_model,
                temperature=args.temperature,
                seed=100 + i,
                max_new_tokens=args.max_new_tokens,
            )
            total_tokens += res.total_tokens
            winner = res.vote.winner
        latencies.append(time.perf_counter() - t0)
        ok = exact_match(winner, prob.answer)
        correct += ok
    steady = sorted(latencies[1:]) or latencies
    out = {
        "panel": list(engines),
        "weights": weights,
        "n_problems": args.n_problems,
        "n_per_model": args.n_per_model,
        "debate_rounds": (
            round(sum(rounds_taken) / len(rounds_taken), 2)
            if rounds_taken
            else None
        ),
        "em": round(correct / max(1, args.n_problems), 4),
        "total_candidate_tokens": total_tokens,
        "first_question_s": round(latencies[0], 3) if latencies else None,
        "latency_median_s": (
            round(steady[len(steady) // 2], 3) if steady else None
        ),
        "latency_max_s": round(max(steady), 3) if steady else None,
        "device": jax.devices()[0].platform,
    }
    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
