"""REAL speculative-decoding acceptance: trained target + trained draft.

``bench.py --draft`` brackets speculation with random weights: ``self``
gives the acceptance~1 overhead ceiling, a random draft the ~0 floor.
This script measures the honest middle — a 14M target and a ~2.5M draft
BOTH trained on the arithmetic SFT corpus (``examples/train_arith_em.py``
recipe), decoding real eval prompts greedily:

1. train (or reuse) ``arith-14m`` and ``arith-3m`` checkpoints;
2. reload both through orbax;
3. run :func:`speculative_generate` on the eval problems' prompts and
   report acceptance rate + tokens/sec vs the plain greedy path.

Usage:
    python examples/spec_arith_demo.py \
        --target-ckpt runs/arith14m --draft-ckpt runs/arith3m \
        [--train-draft]  # trains the draft first if needed

    # Early-snapshot-as-draft: the SAME preset at an earlier training
    # step drafts for the converged target (no separate draft model):
    python examples/spec_arith_demo.py --draft-model arith-14m \
        --target-ckpt runs/arith14m --draft-ckpt runs/arith14m_mid2
"""

from __future__ import annotations

import argparse
import json
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).parent.parent))

import jax
import jax.numpy as jnp
import numpy as np

from llm_consensus_tpu.engine.speculative import speculative_generate
from llm_consensus_tpu.engine.generate import generate
from llm_consensus_tpu.engine.tokenizer import ByteTokenizer
from llm_consensus_tpu.eval.arith import eval_split
from llm_consensus_tpu.eval.gsm8k import _PROMPT
from llm_consensus_tpu.models.configs import get_config


def _load_params(model: str, ckpt_dir: str):
    from llm_consensus_tpu.checkpoint.io import restore_params_for_inference

    cfg = get_config(model)
    params, _ = restore_params_for_inference(cfg, ckpt_dir, jnp.bfloat16)
    return cfg, params


def main() -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--target-ckpt", default="runs/arith14m")
    p.add_argument("--draft-ckpt", default="runs/arith3m")
    p.add_argument("--target-model", default="arith-14m")
    p.add_argument(
        "--draft-model",
        default="arith-3m",
        help="draft preset; pass the TARGET's preset with an earlier "
        "training snapshot as --draft-ckpt to measure the "
        "early-checkpoint-as-draft configuration",
    )
    p.add_argument("--train-draft", action="store_true")
    p.add_argument("--draft-steps", type=int, default=6000)
    p.add_argument("--n-prompts", type=int, default=16)
    p.add_argument(
        "--holdout-n",
        type=int,
        default=50,
        help="size of the eval holdout the checkpoints were trained "
        "with (train_arith_em --n-problems; eval seed must match too) — "
        "prompts past this index were TRAINED ON and would inflate "
        "acceptance",
    )
    p.add_argument("--max-new-tokens", type=int, default=48)
    p.add_argument("--k-spec", type=int, default=4)
    p.add_argument("--iters", type=int, default=3)
    p.add_argument(
        "--cpu", action="store_true",
        help="force the CPU backend (the env preimports jax with the "
        "TPU tunnel registered)",
    )
    args = p.parse_args()
    if args.cpu:
        jax.config.update("jax_platforms", "cpu")

    if args.train_draft:
        # Reuse the training script via its CLI surface for an identical
        # recipe (same corpus, same holdout).
        import subprocess

        cmd = [
            sys.executable,
            str(Path(__file__).parent / "train_arith_em.py"),
            "--model", args.draft_model,
            "--steps", str(args.draft_steps),
            "--ckpt-dir", args.draft_ckpt,
            "--train-only",
        ] + (["--cpu"] if args.cpu else [])
        print("[spec-demo] training draft:", " ".join(cmd), file=sys.stderr)
        subprocess.run(cmd, check=True)

    t_cfg, t_params = _load_params(args.target_model, args.target_ckpt)
    d_cfg, d_params = _load_params(args.draft_model, args.draft_ckpt)
    tok = ByteTokenizer()

    if args.n_prompts > args.holdout_n:
        # Training held out exactly the first --holdout-n eval problems'
        # triples; prompts past that index were TRAINED ON by both
        # models and would inflate the acceptance number.
        raise SystemExit(
            f"--n-prompts {args.n_prompts} exceeds the training holdout "
            f"({args.holdout_n}; see --holdout-n) — extra prompts come "
            "from the training corpus"
        )
    problems, _ = eval_split(args.n_prompts, seed=0)
    prompts = [_PROMPT.format(q=pr.question) for pr in problems]
    ids = [tok.encode(t) for t in prompts]
    # +1 pad column: the time-salt below must land on a slot past EVERY
    # row's true length (never attended — masked like all prompt
    # padding), so the workload is bit-identical while the input array
    # is fresh per iteration.
    s = max(len(x) for x in ids) + 1
    b = len(ids)
    tokens = np.full((b, s), tok.pad_id, np.int32)
    for i, x in enumerate(ids):
        tokens[i, : len(x)] = x
    lengths = np.asarray([len(x) for x in ids], np.int32)
    tokens_j, lengths_j = jnp.asarray(tokens), jnp.asarray(lengths)

    # Time-salt the batch like bench.py (runtime replays identical
    # (executable, inputs) pairs).
    salt = int(time.time() * 1e6) % 251

    def _salted(i):
        return tokens_j.at[0, s - 1].set(salt + i)

    def run_spec(i):
        return speculative_generate(
            t_cfg, t_params, d_cfg, d_params, _salted(i), lengths_j,
            max_new_tokens=args.max_new_tokens, k_spec=args.k_spec,
            eos_id=tok.eos_id, pad_id=tok.pad_id,
        )

    def run_plain(i):
        return generate(
            t_cfg, t_params, _salted(i), lengths_j,
            jax.random.fold_in(jax.random.PRNGKey(salt), i),
            jnp.zeros((b,), jnp.float32),
            max_new_tokens=args.max_new_tokens, eos_id=tok.eos_id,
        )

    out = run_spec(0)
    plain = run_plain(0)
    # Host-fetch warmup sync too (tree-level block does not reliably
    # wait for the spec while_loop program on the tunnel runtime — see
    # the timed-loop note): warmup work must not bleed into iteration 1.
    np.asarray(out.tokens), np.asarray(plain.tokens)
    # Greedy speculative output must equal greedy plain output.
    match = bool(
        jnp.all(
            jnp.where(
                jnp.arange(args.max_new_tokens)[None, :]
                < plain.num_tokens[:, None],
                out.tokens == plain.tokens,
                True,
            )
        )
    )
    # Host-fetch sync (np.asarray of the token buffer), NOT
    # block_until_ready: round 5 caught the spec while_loop program
    # "finishing" in ~2 ms under tree-level block on the tunnel runtime
    # (bench.py records the incident) — a host fetch is the only sync
    # the runtime cannot fake.
    t0 = time.perf_counter()
    for i in range(args.iters):
        out = run_spec(i + 1)
        np.asarray(out.tokens)
    spec_wall = (time.perf_counter() - t0) / args.iters
    t0 = time.perf_counter()
    for i in range(args.iters):
        plain = run_plain(i + 1)
        np.asarray(plain.tokens)
    plain_wall = (time.perf_counter() - t0) / args.iters

    produced = float(jnp.sum(out.num_tokens))
    acc = float(out.accepted) / max(1.0, float(out.drafted))
    result = {
        "target": t_cfg.name,
        "draft": d_cfg.name,
        # Checkpoint dirs disambiguate same-preset configurations (the
        # early-snapshot-as-draft mode has target.name == draft.name).
        "target_ckpt": args.target_ckpt,
        "draft_ckpt": args.draft_ckpt,
        "n_prompts": b,
        "k_spec": args.k_spec,
        "acceptance": round(acc, 4),
        "greedy_output_matches_plain": match,
        "spec_tok_s": round(produced / spec_wall, 1),
        "plain_tok_s": round(
            float(jnp.sum(plain.num_tokens)) / plain_wall, 1
        ),
        "speedup": round(plain_wall / spec_wall, 3),
        "device": jax.devices()[0].platform,
    }
    print(json.dumps(result))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
