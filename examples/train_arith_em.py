"""End-to-end accuracy loop: train -> checkpoint -> reload -> EM-vs-N.

The first full proof that this framework does what the reference did —
answer questions — with every stage running through the repo's own
stack:

1. **Train** ``arith-14m`` (byte-level, ~14M params) on the synthetic
   arithmetic SFT corpus (``eval/arith.py``) with
   ``training/loop.run_training`` — eval triples held out, loss masked
   to completion tokens, orbax checkpoints along the way.
2. **Reload** the final checkpoint from disk (``checkpoint/io``) into a
   fresh :class:`InferenceEngine` (bf16 cast, prefix cache on).
3. **Evaluate** real sampled EM at N in {1, 8, 32} with
   ``evaluate_self_consistency`` — actual decoded text, actual votes.

The reference outsourced all of this to a remote API
(``src/main.rs:82-86``); here the model, the training, the serving, and
the vote are all local TPU programs.

Usage (the recorded run in eval/EM_VS_N.md):
    python examples/train_arith_em.py --steps 6000 \
        --ckpt-dir runs/arith14m --report runs/arith14m/report.json
    python examples/train_arith_em.py --eval-only --ckpt-dir runs/arith14m
"""

from __future__ import annotations

import argparse
import json
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).parent.parent))

import jax
import jax.numpy as jnp

from llm_consensus_tpu.engine.engine import EngineConfig, InferenceEngine
from llm_consensus_tpu.engine.tokenizer import ByteTokenizer
from llm_consensus_tpu.eval.arith import build_sft_examples, eval_split
from llm_consensus_tpu.eval.gsm8k import evaluate_self_consistency
from llm_consensus_tpu.models.configs import get_config
from llm_consensus_tpu.training.data import SftBatchLoader
from llm_consensus_tpu.training.loop import LoopConfig, run_training
from llm_consensus_tpu.training.train import TrainConfig


def _splits(args):
    """(eval_problems, holdout_signatures) for the selected task."""
    if args.task == "arith2":
        from llm_consensus_tpu.eval.arith2 import eval_problems

        return eval_problems(args.n_problems, seed=args.eval_seed)
    return eval_split(args.n_problems, seed=args.eval_seed)


def train(args, cfg, tok) -> None:
    _, holdout = _splits(args)
    if args.task == "arith2":
        from llm_consensus_tpu.eval.arith2 import (
            build_sft_examples as build2,
        )

        n_train = args.n_train
        if args.limit:
            n_train = min(n_train, args.limit)
        examples = build2(tok, n_train, exclude=holdout)
    else:
        examples = build_sft_examples(tok, exclude=holdout, limit=args.limit)
    loader = SftBatchLoader(
        examples, args.batch, args.seq, seed=1, pad_id=tok.pad_id
    )
    print(
        f"[train] {loader.n_examples} SFT examples "
        f"({len(holdout)} eval triples held out), "
        f"batch {args.batch} x seq {args.seq}",
        file=sys.stderr,
    )
    tcfg = TrainConfig(
        learning_rate=args.lr,
        warmup_steps=min(200, args.steps // 10),
        total_steps=args.steps,
        compute_dtype="bfloat16"
        if jax.devices()[0].platform == "tpu"
        else None,
    )
    loop = LoopConfig(
        total_steps=args.steps,
        log_every=max(1, args.steps // 30),
        ckpt_every=args.ckpt_every or max(1, args.steps // 4),
        ckpt_dir=args.ckpt_dir,
        seed=0,
    )
    t0 = time.perf_counter()
    _, report = run_training(cfg, tcfg, loader, loop)
    wall = time.perf_counter() - t0
    last = report.losses[-1] if report.losses else None
    print(
        f"[train] {report.final_step} steps in {wall:.0f}s"
        + (f", final loss {last.loss:.4f}" if last else ""),
        file=sys.stderr,
    )


def load_engine(args, cfg, tok) -> InferenceEngine:
    """Reload the latest checkpoint from disk into a fresh engine."""
    from llm_consensus_tpu.checkpoint.io import restore_params_for_inference

    try:
        params, step = restore_params_for_inference(
            cfg, args.ckpt_dir, jnp.bfloat16
        )
    except FileNotFoundError as e:
        raise SystemExit(f"{e}; train first") from e
    print(
        f"[eval] restored from {args.ckpt_dir} (step {step})",
        file=sys.stderr,
    )
    return InferenceEngine(
        cfg,
        params,
        tokenizer=tok,
        engine_config=EngineConfig(max_new_tokens=args.max_new_tokens),
    )


def evaluate(args, engine) -> dict:
    problems, _ = _splits(args)
    rows = []
    for n in args.ns:
        rep = evaluate_self_consistency(
            engine,
            problems,
            n=n,
            temperature=args.temperature,
            seed=1234,
            max_new_tokens=args.max_new_tokens,
        )
        rows.append(rep.to_dict())
        print(
            f"[eval] N={n:<3d} EM={rep.em:.3f} "
            f"({rep.total_candidate_tokens} candidate tokens, "
            f"{rep.candidate_tokens_per_sec:.0f} tok/s)",
            file=sys.stderr,
        )
    return {
        "model": engine.cfg.name,
        "task": args.task,
        "n_problems": args.n_problems,
        "temperature": args.temperature,
        "device": jax.devices()[0].platform,
        "rows": rows,
    }


def main() -> int:
    p = argparse.ArgumentParser()
    p.add_argument(
        "--task",
        default="arith",
        choices=("arith", "arith2"),
        help="arith: single-template (a+b)*c (the round-4 loop); "
        "arith2: multi-template 2-4-step chains with distractors "
        "(eval/arith2.py) — pair with --model arith-25m, --seq 704",
    )
    p.add_argument(
        "--model",
        default="",
        help="'' = per-task default (arith-14m for arith, arith-25m "
        "for arith2 — the 512-context arith-14m truncates arith2's "
        "~650-byte examples)",
    )
    p.add_argument(
        "--n-train",
        type=int,
        default=60000,
        help="arith2 only: SFT examples to sample (the chain space is "
        "effectively unbounded, unlike arith's 27,848 triples)",
    )
    p.add_argument("--steps", type=int, default=6000)
    p.add_argument("--batch", type=int, default=32)
    p.add_argument(
        "--seq",
        type=int,
        default=0,
        help="0 = per-task default (384 for arith, 704 for arith2; a "
        "too-short seq would silently cut the CoT + '####' answer "
        "off the training pairs)",
    )
    p.add_argument("--lr", type=float, default=1e-3)
    p.add_argument("--limit", type=int, default=0, help="cap SFT examples")
    p.add_argument("--ckpt-dir", default="runs/arith14m")
    p.add_argument("--ckpt-every", type=int, default=0)
    p.add_argument("--n-problems", type=int, default=50)
    p.add_argument("--eval-seed", type=int, default=0)
    p.add_argument("--ns", type=int, nargs="+", default=[1, 8, 32])
    p.add_argument("--temperature", type=float, default=0.7)
    p.add_argument(
        "--max-new-tokens",
        type=int,
        default=0,
        help="0 = per-task default (64 for arith's 2-step CoT, 112 for "
        "arith2's up-to-4-step CoT)",
    )
    p.add_argument("--eval-only", action="store_true")
    p.add_argument("--train-only", action="store_true")
    p.add_argument("--report", default="")
    p.add_argument(
        "--cpu",
        action="store_true",
        help="force the CPU backend (the env preimports jax with the "
        "TPU tunnel registered, so JAX_PLATFORMS alone is too late)",
    )
    args = p.parse_args()
    if args.cpu:
        jax.config.update("jax_platforms", "cpu")
    if not args.max_new_tokens:
        args.max_new_tokens = 112 if args.task == "arith2" else 64
    if not args.model:
        args.model = "arith-25m" if args.task == "arith2" else "arith-14m"
    if not args.seq:
        args.seq = 704 if args.task == "arith2" else 384

    cfg = get_config(args.model)
    if args.task == "arith2" and cfg.max_seq_len < 640:
        raise SystemExit(
            f"--task arith2 needs max_seq_len >= 640 (prompts+CoT reach "
            f"~650 bytes); {cfg.name} has {cfg.max_seq_len}. Use "
            f"--model arith-25m."
        )
    tok = ByteTokenizer()
    if not args.eval_only:
        train(args, cfg, tok)
    if args.train_only:
        return 0
    engine = load_engine(args, cfg, tok)
    result = evaluate(args, engine)
    print(json.dumps(result))
    if args.report:
        Path(args.report).parent.mkdir(parents=True, exist_ok=True)
        Path(args.report).write_text(json.dumps(result, indent=2))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
