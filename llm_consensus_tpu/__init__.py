"""llm_consensus_tpu — a TPU-native multi-agent LLM consensus framework.

A from-scratch rebuild of the capabilities of ``thepolytheist/llm-consensus``
(reference: a Rust/actix orchestrator fanning out HTTPS calls to Gemini,
``src/main.rs``), re-founded on local JAX/XLA/Pallas inference on TPU meshes:

- the propose -> panel-evaluate -> refine consensus protocol
  (reference ``src/main.rs:187-348``) as an asyncio state machine with
  epoch-tagged messages (fixing the reference's round races),
- persona/panel conditioning (reference ``src/main.rs:359-426``) driven by
  config instead of hard-coded literals,
- answer aggregation generalized from unanimity to self-consistency
  majority vote / weighted vote / logit pooling,
- a pluggable text-generation backend whose seam is exactly the reference's
  ``call_gemini`` (``src/main.rs:82-86``): ``prompt -> text``; the production
  backend is batched JAX inference on a TPU device mesh.
"""

from llm_consensus_tpu.version import __version__

__all__ = ["__version__"]
