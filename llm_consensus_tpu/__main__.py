"""``python -m llm_consensus_tpu`` — the REPL/CLI entry point."""

from llm_consensus_tpu.cli import main

raise SystemExit(main())
