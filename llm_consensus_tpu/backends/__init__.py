from llm_consensus_tpu.backends.base import (
    Backend,
    BackendError,
    GenerationRequest,
    GenerationResult,
    SamplingParams,
)
from llm_consensus_tpu.backends.fake import FakeBackend, ScriptedBackend
from llm_consensus_tpu.backends.fault import (
    FaultConfig,
    FaultInjectingBackend,
    FaultStats,
)

__all__ = [
    "Backend",
    "BackendError",
    "FakeBackend",
    "FaultConfig",
    "FaultInjectingBackend",
    "FaultStats",
    "GenerationRequest",
    "GenerationResult",
    "SamplingParams",
    "ScriptedBackend",
]
