from llm_consensus_tpu.backends.base import Backend, GenerationRequest, GenerationResult
from llm_consensus_tpu.backends.fake import FakeBackend, ScriptedBackend

__all__ = [
    "Backend",
    "GenerationRequest",
    "GenerationResult",
    "FakeBackend",
    "ScriptedBackend",
]
