"""Backend seam: the single boundary between the consensus protocol and the
compute substrate.

In the reference this seam is ``call_gemini(prompt) -> text``
(``src/main.rs:82-86``): one remote HTTPS round-trip per protocol step, one
fresh client per call. Here it is an abstract ``Backend`` with a batched
async ``generate`` so that:

- tests run against a deterministic :class:`FakeBackend` (the test strategy
  the reference lacks, SURVEY.md §4),
- production runs against :class:`~llm_consensus_tpu.backends.tpu.TPUBackend`
  — batched JAX decoding on a device mesh, where a whole panel fan-out
  becomes ONE batched forward instead of N HTTP requests,
- per-request sampling params and per-candidate PRNG seeds are first-class
  (needed for N-way self-consistency, BASELINE.md).
"""

from __future__ import annotations

import abc
from dataclasses import dataclass, field


@dataclass(frozen=True)
class SamplingParams:
    """Decode-time sampling configuration for one request."""

    max_new_tokens: int = 256
    temperature: float = 0.0  # 0 => greedy
    top_k: int = 0  # 0 => disabled
    top_p: float = 1.0  # 1.0 => disabled
    seed: int = 0
    # Stop sequences: generation text is trimmed at the earliest
    # occurrence (stop removed); backends end decoding early where their
    # substrate allows (engine: single-token device stops + chunked
    # host checks; continuous batcher: every token is host-checked).
    # A tuple so the dataclass stays frozen/hashable.
    stop: tuple[str, ...] = ()


@dataclass(frozen=True)
class GenerationRequest:
    prompt: str
    params: SamplingParams = field(default_factory=SamplingParams)
    # Optional model preset for heterogeneous panels; None = backend default.
    model: str | None = None


@dataclass
class GenerationResult:
    text: str
    # Number of generated (candidate) tokens; 0 when the backend does not
    # tokenize (e.g. the fake backend).
    num_tokens: int = 0
    # Sum of log-probabilities of the sampled tokens, for logit-pooled
    # aggregation; None when unavailable.
    logprob: float | None = None
    # Backend-specific serving metadata (PR 10): the continuous batcher
    # attaches its per-request timing summary (TTFT, inter-token-gap
    # percentiles, speculation tallies, header-page provenance) — the
    # gateway surfaces it as the response's "meta". None when the
    # backend records nothing. compare=False: result equality means
    # "same generation", and timing stamps never repeat.
    meta: dict | None = field(default=None, compare=False)


class Backend(abc.ABC):
    """Text-generation backend: the ``call_gemini`` seam, batched."""

    @abc.abstractmethod
    async def generate_batch(
        self, requests: list[GenerationRequest]
    ) -> list[GenerationResult]:
        """Generate one completion per request.

        Implementations should treat the list as a batch when the substrate
        allows (the TPU backend pads/batches into a single device program).
        """

    async def generate(self, request: GenerationRequest) -> GenerationResult:
        """Single-request convenience wrapper over :meth:`generate_batch`."""
        (result,) = await self.generate_batch([request])
        return result

    async def close(self) -> None:  # pragma: no cover - default no-op
        """Release resources (device buffers, threads)."""
        return None


class BackendError(RuntimeError):
    """Raised when a backend fails permanently (after retries).

    The reference ``expect``-panics on any backend error
    (``src/main.rs:85,97,138,178``); the rebuild surfaces a typed error the
    coordinator's failure-detection layer can handle (SURVEY.md §5).
    """
