"""Deterministic fake backends for protocol tests.

The reference has no tests and no fake backend (SURVEY.md §4); its seam is
``call_gemini(prompt) -> text`` (``src/main.rs:82-86``). These fakes plug
into that exact seam so the consensus state machine can be driven through
unanimous / split / round-cap / stale-message paths without any model.
"""

from __future__ import annotations

import asyncio
import re
from collections.abc import Callable

from llm_consensus_tpu.backends.base import (
    Backend,
    GenerationRequest,
    GenerationResult,
)
from llm_consensus_tpu.utils import tracing as _tracing


class FakeBackend(Backend):
    """Rule-based fake: classify the prompt kind and respond deterministically.

    By default every evaluation approves (``Good``), so a single
    propose -> evaluate round reaches unanimity — the happy path.
    Pass ``evaluator`` / ``answerer`` / ``refiner`` callables to script
    dissent, malformed verdicts, etc.
    """

    def __init__(
        self,
        answerer: Callable[[str], str] | None = None,
        evaluator: Callable[[str], str] | None = None,
        refiner: Callable[[str], str] | None = None,
        latency: float = 0.0,
    ):
        self._answerer = answerer or (lambda p: f"Echo: {_question_of(p)}")
        self._evaluator = evaluator or (lambda p: "Good\nLooks fine.")
        self._refiner = refiner or (lambda p: f"Refined: {_answer_of(p)}")
        self._latency = latency
        self.calls: list[str] = []  # raw prompts, for assertions

    async def generate_batch(
        self, requests: list[GenerationRequest]
    ) -> list[GenerationResult]:
        if self._latency:
            await asyncio.sleep(self._latency)
        results = []
        for req in requests:
            self.calls.append(req.prompt)
            kind = classify_prompt(req.prompt)
            if kind == "evaluate":
                text = self._evaluator(req.prompt)
            elif kind == "refine":
                text = self._refiner(req.prompt)
            else:
                text = self._answerer(req.prompt)
            # Synthetic engine-phase spans so a request-scoped trace
            # through the fake has the SAME tree shape as one through
            # the real serving stack (admission -> prefill -> decode) —
            # the gateway's tracing acceptance test runs entirely on
            # this backend.
            with _tracing.request_span(
                "prefill_chunk", synthetic=True, prompt_chars=len(req.prompt)
            ):
                pass
            with _tracing.request_span(
                "decode_step", synthetic=True, tokens=len(text.split())
            ):
                pass
            results.append(GenerationResult(text=text, num_tokens=len(text.split())))
        return results


class ScriptedBackend(Backend):
    """Returns scripted responses in FIFO order regardless of prompt.

    Useful for driving exact multi-round traces through the coordinator.
    """

    def __init__(self, script: list[str]):
        self.script = list(script)
        self.calls: list[str] = []

    async def generate_batch(
        self, requests: list[GenerationRequest]
    ) -> list[GenerationResult]:
        results = []
        for req in requests:
            self.calls.append(req.prompt)
            if not self.script:
                raise AssertionError("ScriptedBackend ran out of responses")
            results.append(GenerationResult(text=self.script.pop(0)))
        return results


def classify_prompt(prompt: str) -> str:
    """Heuristically classify which protocol step produced a prompt.

    Keyed off distinguishing phrases of the three prompt builders
    (reference ``src/main.rs:95,118,173``).
    """
    if "answer by consensus" in prompt and "evaluate this answer" in prompt:
        return "evaluate"
    if "you said it needed refinement" in prompt:
        return "refine"
    return "answer"


_QUESTION_RE = re.compile(r"Question: (.*)")
_ANSWER_RE = re.compile(r"Answer: (.*)")


def _question_of(prompt: str) -> str:
    m = _QUESTION_RE.search(prompt)
    if m:
        return m.group(1)
    # Initial-answer prompt: question is the text after the double newline
    # (reference src/main.rs:95).
    parts = prompt.split("\n\n", 1)
    return parts[1] if len(parts) > 1 else prompt


def _answer_of(prompt: str) -> str:
    m = _ANSWER_RE.search(prompt)
    return m.group(1) if m else prompt
