"""Fault-injection backend wrapper (chaos testing for the protocol).

The reference panics on ANY backend failure (`expect` at
``src/main.rs:85,97,138,178``) and so cannot be chaos-tested at all;
this framework's coordinator supervises its backend calls with timeouts
and bounded retries (``consensus/coordinator.py``). This wrapper proves
that supervision under adversarial conditions: it wraps any real
:class:`~llm_consensus_tpu.backends.base.Backend` and injects seeded,
reproducible faults —

- **errors**: a call raises :class:`BackendError` with probability
  ``error_rate`` (transient: the next retry of the same call may pass);
- **delays**: a call sleeps ``delay_s`` seconds with probability
  ``delay_rate`` (drives timeout paths without wall-clock-long tests);
- **garbage**: a result's text is replaced with malformed output with
  probability ``garbage_rate`` (exercises the verdict parser's
  unknown-evaluation handling, SURVEY.md §5 quirk #4).

Faults are drawn from a ``random.Random(seed)`` stream, so a failing
chaos run reproduces exactly. Counters record what was injected.
"""

from __future__ import annotations

import asyncio
import random
from dataclasses import dataclass, field

from llm_consensus_tpu.backends.base import (
    Backend,
    BackendError,
    GenerationRequest,
    GenerationResult,
)


@dataclass
class FaultStats:
    calls: int = 0
    errors_injected: int = 0
    delays_injected: int = 0
    garbage_injected: int = 0


@dataclass
class FaultConfig:
    error_rate: float = 0.0
    delay_rate: float = 0.0
    delay_s: float = 0.05
    garbage_rate: float = 0.0
    garbage_text: str = "?? GARBLED ??"
    seed: int = 0

    def __post_init__(self):
        for name in ("error_rate", "delay_rate", "garbage_rate"):
            v = getattr(self, name)
            if not 0.0 <= v <= 1.0:
                raise ValueError(f"{name} must be in [0, 1], got {v}")


class FaultInjectingBackend(Backend):
    """Wrap ``inner`` with seeded transient errors, delays, and garbage."""

    def __init__(self, inner: Backend, config: FaultConfig | None = None):
        self.inner = inner
        self.config = config or FaultConfig()
        self._rng = random.Random(self.config.seed)
        self.stats = FaultStats()

    async def generate_batch(
        self, requests: list[GenerationRequest]
    ) -> list[GenerationResult]:
        cfg = self.config
        self.stats.calls += 1
        # Draw EVERY fault decision for this call synchronously, before
        # any await: concurrent generate_batch calls (the coordinator
        # gathers panelists) would otherwise consume the shared RNG
        # stream in task-completion order, breaking seeded reproduction.
        delay = self._rng.random() < cfg.delay_rate
        error = self._rng.random() < cfg.error_rate
        garbage = [
            self._rng.random() < cfg.garbage_rate for _ in requests
        ]
        if delay:
            self.stats.delays_injected += 1
            await asyncio.sleep(cfg.delay_s)
        if error:
            self.stats.errors_injected += 1
            raise BackendError("injected transient fault")
        results = await self.inner.generate_batch(requests)
        out = []
        for r, garbled in zip(results, garbage):
            if garbled:
                self.stats.garbage_injected += 1
                out.append(
                    GenerationResult(
                        text=cfg.garbage_text,
                        num_tokens=r.num_tokens,
                        logprob=r.logprob,
                    )
                )
            else:
                out.append(r)
        return out

    async def close(self) -> None:
        await self.inner.close()
