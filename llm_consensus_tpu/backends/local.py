"""LocalBackend: the Backend seam implemented by the on-device engine.

This replaces the reference's L1 compute layer — one fresh HTTPS client
and one remote Gemini call per protocol step (``call_gemini``,
``src/main.rs:82-86``) — with local batched decoding: a whole panel
fan-out arrives as one ``generate_batch`` list and leaves as ONE compiled
device program (prefill + scan decode), per SURVEY.md §7 step 1.

Heterogeneous panels (BASELINE.md config[3]) register several engines
keyed by model name; requests route by ``GenerationRequest.model`` and
each engine still batches its own group.
"""

from __future__ import annotations

import asyncio
import logging
from collections import defaultdict

from llm_consensus_tpu.backends.base import (
    Backend,
    BackendError,
    GenerationRequest,
    GenerationResult,
)
from llm_consensus_tpu.engine.engine import InferenceEngine

log = logging.getLogger(__name__)


class LocalBackend(Backend):
    """Batched local inference over one or more :class:`InferenceEngine`s."""

    def __init__(
        self,
        engine: InferenceEngine,
        engines: dict[str, InferenceEngine] | None = None,
    ):
        self.engine = engine
        self.engines = engines or {}

    def _engine_for(self, model: str | None) -> InferenceEngine:
        if model is None:
            return self.engine
        if model in self.engines:
            return self.engines[model]
        if model == self.engine.cfg.name:
            return self.engine
        raise BackendError(
            f"no engine for model {model!r}; have "
            f"{[self.engine.cfg.name, *self.engines]}"
        )

    async def generate_batch(
        self, requests: list[GenerationRequest]
    ) -> list[GenerationResult]:
        if not requests:
            return []
        # Group by (engine, static sampling config); each group is one
        # device program honoring its requests' max_new_tokens/top_k/top_p
        # exactly (temperature and seed are dynamic data). The compute is
        # synchronous JAX — run it in a thread so the asyncio loop (and any
        # concurrent REPL/serving work) stays responsive.
        groups: dict[tuple, list[int]] = defaultdict(list)
        engines: dict[tuple, InferenceEngine] = {}
        for i, req in enumerate(requests):
            eng = self._engine_for(req.model)
            key = (
                id(eng),
                req.params.max_new_tokens,
                req.params.top_k,
                req.params.top_p,
                req.params.stop,
            )
            groups[key].append(i)
            engines[key] = eng

        results: list[GenerationResult | None] = [None] * len(requests)

        def _run(key: tuple, eng: InferenceEngine, idxs: list[int]) -> None:
            from llm_consensus_tpu.engine.sampler import SamplerConfig

            _, max_new, top_k, top_p, stop = key
            reqs = [requests[i] for i in idxs]
            # All-greedy groups ride speculative decoding when the
            # engine carries a draft model — safe because greedy
            # speculative output is exactly the greedy output (tested).
            # The speculative program is single-device, bf16-KV,
            # one-shot-prefill: engines configured otherwise keep the
            # plain path (routing must never change the numerics class
            # or drop the sharding/memory strategy the user configured).
            if (
                eng.draft is not None
                and eng.mesh is None
                and not eng.config.kv_quant
                and eng.config.prefill_chunk == 0
                and top_k == 0
                and top_p == 1.0
                and not stop  # the speculative program has no stop path
                and all(r.params.temperature == 0.0 for r in reqs)
            ):
                outs = eng.generate_texts_speculative(
                    [r.prompt for r in reqs], max_new_tokens=max_new
                )
            else:
                outs = eng.generate_texts(
                    [r.prompt for r in reqs],
                    temperatures=[r.params.temperature for r in reqs],
                    # One batch shares a PRNG key; per-row independence
                    # comes from the batched categorical. Mix the first
                    # seed in so distinct requests get distinct streams.
                    seed=reqs[0].params.seed,
                    max_new_tokens=max_new,
                    sampler=SamplerConfig(top_k=top_k, top_p=top_p),
                    stop=list(stop) or None,
                )
            for i, out in zip(idxs, outs):
                results[i] = GenerationResult(
                    text=out.text,
                    num_tokens=out.num_tokens,
                    logprob=out.logprob,
                )

        try:
            await asyncio.gather(
                *(
                    asyncio.to_thread(_run, key, engines[key], idxs)
                    for key, idxs in groups.items()
                )
            )
        except BackendError:
            raise
        except Exception as e:  # noqa: BLE001 - surface as typed error
            raise BackendError(f"local generation failed: {e}") from e
        assert all(r is not None for r in results)
        return results  # type: ignore[return-value]
