"""Checkpoint / resume.

NOT PRESENT in the reference — all its state is in-memory and reset per
question (``src/main.rs:198-203``; SURVEY.md §5). Here: orbax-backed
save/restore for model params and full train states, plus JSON
round-state snapshots so an interrupted consensus run can resume.
"""

from llm_consensus_tpu.checkpoint.io import (
    load_params,
    restore_train_state,
    save_params,
    save_train_state,
)

__all__ = [
    "load_params",
    "restore_train_state",
    "save_params",
    "save_train_state",
]
