"""Orbax-backed checkpoint IO for params and train states.

Checkpoint/resume is a build requirement the reference lacks entirely
(SURVEY.md §5 — every crash loses all state). Uses orbax's
StandardCheckpointer: async-friendly, works with sharded arrays (each
host writes its shards; restore honors a target sharding), so the same
API covers single-chip and multi-slice meshes.
"""

from __future__ import annotations

import json
from pathlib import Path

import jax
import orbax.checkpoint as ocp


def _ckptr() -> ocp.StandardCheckpointer:
    return ocp.StandardCheckpointer()


def _abstractify(tree, sharding=None):
    """Array leaves -> ShapeDtypeStructs for orbax restore targets.

    ``sharding``: None keeps each leaf's own sharding (or the file's,
    when the leaf is abstract) — the multi-host-safe default; a concrete
    Sharding overrides every leaf (restore-to-here, e.g. single-device
    inference reloads of checkpoints saved on another topology)."""
    return jax.tree_util.tree_map(
        lambda x: jax.ShapeDtypeStruct(
            x.shape,
            x.dtype,
            sharding=sharding
            if sharding is not None
            else getattr(x, "sharding", None),
        )
        if hasattr(x, "shape")
        else x,
        tree,
    )


def save_params(path: str | Path, params: dict) -> None:
    """Save a param pytree to ``path`` (a directory)."""
    path = Path(path).absolute()
    ckptr = _ckptr()
    ckptr.save(path / "params", params, force=True)
    ckptr.wait_until_finished()


def load_params(path: str | Path, target: dict | None = None) -> dict:
    """Restore params. ``target`` (abstract pytree of jax.ShapeDtypeStruct
    or concrete arrays) pins dtypes/shardings; None restores as saved."""
    path = Path(path).absolute()
    ckptr = _ckptr()
    if target is not None:
        return ckptr.restore(path / "params", _abstractify(target))
    return ckptr.restore(path / "params")


def save_train_state(path: str | Path, state, extra: dict | None = None) -> None:
    """Save a full TrainState (params + opt state + step) and optional
    JSON metadata (e.g. dataset position, rng seed) for exact resume."""
    path = Path(path).absolute()
    ckptr = _ckptr()
    ckptr.save(path / "state", state, force=True)
    ckptr.wait_until_finished()
    if extra is not None:
        (path / "meta.json").write_text(json.dumps(extra))


def restore_train_state(path: str | Path, target):
    """Restore a TrainState saved by :func:`save_train_state`.

    ``target``: a template TrainState (same treedef; arrays may be
    abstract) — required because opt states are arbitrary pytrees.
    Returns (state, extra_metadata_dict_or_None).
    """
    path = Path(path).absolute()
    ckptr = _ckptr()
    state = ckptr.restore(path / "state", _abstractify(target))
    meta_file = path / "meta.json"
    extra = json.loads(meta_file.read_text()) if meta_file.exists() else None
    return state, extra


def restore_params_for_inference(cfg, ckpt_dir, dtype=None):
    """Reload a training checkpoint's params for an InferenceEngine.

    The one restore recipe shared by the example scripts (train_arith_em
    eval phase, spec_arith_demo): resolve the newest complete checkpoint
    under ``ckpt_dir`` (training.loop's LATEST-pointer layout), restore
    through an abstract TrainState template, and cast float32 leaves to
    ``dtype`` (bfloat16 for TPU decode) leaving everything else alone.
    Returns (params, step_or_None).
    """
    import jax
    import jax.numpy as jnp

    from llm_consensus_tpu.models.transformer import init_params
    from llm_consensus_tpu.training.loop import _latest_checkpoint
    from llm_consensus_tpu.training.train import TrainConfig, init_train_state

    ckpt = _latest_checkpoint(str(ckpt_dir))
    if ckpt is None:
        raise FileNotFoundError(f"no checkpoint under {ckpt_dir}")
    template = jax.eval_shape(
        lambda: init_train_state(
            cfg,
            init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32),
            TrainConfig(),
        )
    )
    # Pin CONCRETE shardings on the HOST CPU device: without them orbax
    # falls back to the sharding recorded in the checkpoint file, which
    # names devices of the SAVING topology — restoring a TPU-saved
    # checkpoint in a CPU process (eval/demo runs) would fail. Staging
    # through host RAM also means the Adam moments (2x fp32 params)
    # never touch accelerator HBM: only the cast params are device_put
    # to the default device at the end. For multi-host or sharded
    # restores use restore_train_state with properly sharded templates.
    cpu = jax.local_devices(backend="cpu")[0]
    template = _abstractify(
        template, sharding=jax.sharding.SingleDeviceSharding(cpu)
    )
    state, extra = restore_train_state(ckpt, template)
    params = state.params
    if dtype is not None:
        params = jax.tree_util.tree_map(
            lambda x: x.astype(dtype)
            if hasattr(x, "dtype") and x.dtype == jnp.float32
            else x,
            params,
        )
    # CPU-committed arrays would pin later jits to the CPU backend;
    # move the (cast) params to the default device. Accelerator peak =
    # params only — the optimizer moments stay behind on the host.
    params = jax.device_put(params, jax.local_devices()[0])
    return params, (extra or {}).get("step")
