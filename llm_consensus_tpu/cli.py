"""CLI / REPL driver — parity with the reference's L4 layer.

The reference REPL (``src/main.rs:428-471``): prompt ``"Enter a question: "``,
read a line, ``exit`` terminates, ask the Coordinator, poll readiness
every 500 ms (a hot spin, ``src/main.rs:448-459``), print the final
answer, reset. Differences here, per SURVEY.md §7 step 5:

- the readiness poll is a real ``await`` on the protocol task — no spin;
- panel/backends/round-cap come from flags and JSON config instead of
  hard-coded literals (reference ``src/main.rs:359-426`` + TODO at
  ``:299``);
- a missing API key cannot happen: the default substrate is local. A
  ``fake`` backend stands in where the reference required
  ``GEMINI_API_KEY`` or died (``src/main.rs:354-357``);
- ``--eval-gsm8k`` runs the batch GSM8K harness instead of the REPL.

Run: ``python -m llm_consensus_tpu [--backend fake|local] ...``
"""

from __future__ import annotations

import argparse
import asyncio
import logging
import os
import sys

from llm_consensus_tpu.backends.base import SamplingParams
from llm_consensus_tpu.backends.fake import FakeBackend
from llm_consensus_tpu.consensus.coordinator import Coordinator, CoordinatorConfig
from llm_consensus_tpu.consensus.personas import default_panel, load_panel

log = logging.getLogger("llm_consensus_tpu")


def _init_logging() -> None:
    """env_logger parity (reference ``src/main.rs:352``): level comes from
    the ``LLM_CONSENSUS_LOG`` env var (RUST_LOG convention, default info)."""
    level = os.environ.get("LLM_CONSENSUS_LOG", "info").upper()
    logging.basicConfig(
        level=getattr(logging, level, logging.INFO),
        format="[%(asctime)s %(levelname)s %(name)s] %(message)s",
    )


def _load_checkpoint_params(cfg, path: str):
    """Load params from either checkpoint layout.

    A training-run directory (training.loop's LATEST-pointer layout,
    incl. a concrete ``step_N``/legacy flat dir holding ``state``)
    restores params-for-inference — train with this repo, serve the
    same dir with no export step. Anything else is a ``save_params``
    directory. Applies to --checkpoint and --draft-checkpoint alike.
    """
    from pathlib import Path

    from llm_consensus_tpu.checkpoint.io import (
        load_params,
        restore_params_for_inference,
    )

    root = Path(path)
    is_train_dir = (
        (root / "LATEST").exists()
        or (root / "state").exists()
        or any(root.glob("step_*/state"))
    )
    if is_train_dir:
        import jax.numpy as _jnp

        params, step = restore_params_for_inference(cfg, root, _jnp.bfloat16)
        log.info("loaded train checkpoint %s (step %s)", root, step)
        return params
    return load_params(path)



def _printable(text: str) -> str:
    """Model output for stdout: lone surrogates (the ByteTokenizer's
    reversible stand-ins for invalid bytes) render as U+FFFD instead of
    crashing the terminal's strict UTF-8 encoder. Display-only — the
    protocol/engine surfaces keep the exact reversible text."""
    return "".join(
        "\ufffd" if 0xD800 <= ord(ch) <= 0xDFFF else ch for ch in text
    )

def _build_backend(args):
    if args.backend == "fake":
        return FakeBackend()
    # Local on-device inference ("local" = engine whole-batch programs,
    # "continuous" = token-level continuous batching over the paged
    # cache with shared-prefix CoW page tables + chunked prefill).
    # Import lazily: jax/device init is heavy
    # and the fake path must stay instant.
    import jax

    from llm_consensus_tpu.utils.compile_cache import enable_compilation_cache

    enable_compilation_cache()

    from llm_consensus_tpu.backends.local import LocalBackend
    from llm_consensus_tpu.engine.engine import EngineConfig, InferenceEngine
    from llm_consensus_tpu.engine.tokenizer import load_tokenizer
    from llm_consensus_tpu.models.configs import get_config
    from llm_consensus_tpu.models.transformer import init_params

    if getattr(args, "model_spec", None):
        if args.backend != "continuous":
            raise SystemExit(
                "--model-spec needs --backend continuous (the "
                "multi-model plane is built from continuous engines)"
            )
        return _build_modelset_backend(args)
    if args.hf_checkpoint:
        from llm_consensus_tpu.models.hf_loader import (
            config_from_hf,
            load_hf_params,
        )

        cfg = config_from_hf(args.hf_checkpoint, name=args.model)
        params = load_hf_params(cfg, args.hf_checkpoint)
    elif args.checkpoint:
        cfg = get_config(args.model)
        params = _load_checkpoint_params(cfg, args.checkpoint)
    else:
        cfg = get_config(args.model)
        log.warning(
            "No --checkpoint given: using RANDOM weights for %s "
            "(protocol/e2e plumbing only; text will be gibberish).",
            cfg.name,
        )
        params = init_params(cfg, jax.random.PRNGKey(0))
    draft = None
    if args.draft_checkpoint and not args.draft_model:
        raise SystemExit(
            "--draft-checkpoint requires --draft-model (which preset "
            "should load those weights?)"
        )
    if args.draft_model:
        dcfg = get_config(args.draft_model)
        if args.draft_checkpoint:
            dparams = _load_checkpoint_params(dcfg, args.draft_checkpoint)
        else:
            log.warning(
                "No --draft-checkpoint: random draft weights for %s "
                "(speculation stays exact but accepts ~nothing).",
                dcfg.name,
            )
            dparams = init_params(dcfg, jax.random.PRNGKey(1))
        draft = (dcfg, dparams)
    mesh = None
    if args.mesh:
        from llm_consensus_tpu.parallel.mesh import MeshConfig, make_mesh

        mesh = make_mesh(MeshConfig(**_parse_axes(args.mesh)))
        if mesh.shape.get("seq", 1) > 1:
            cfg = cfg.with_(use_ring=True)
    if args.backend == "continuous":
        from llm_consensus_tpu.serving.continuous import (
            ContinuousBackend,
            ContinuousBatcher,
            ContinuousConfig,
        )

        if args.quant != "none":
            # Same weight-only quantization the engine path applies
            # (paged decode + chunk prefill read QuantizedTensor leaves
            # through ops.quant.matmul exactly like the dense programs).
            from llm_consensus_tpu.ops.quant import quantize_params

            params = quantize_params(
                params, bits=8 if args.quant == "int8" else 4
            )
        if draft is not None and args.spec_k <= 0:
            raise SystemExit(
                "--draft-model on --backend continuous needs --spec-k > 0 "
                "(draft tokens proposed per verify round)"
            )
        from llm_consensus_tpu.serving.control import (
            AdaptiveController,
            ControlConfig,
            resolve_hbm_gbps,
        )

        serve_config = ContinuousConfig(
            max_slots=args.serve_slots,
            max_new_tokens=args.max_new_tokens,
            prefill_chunk=args.prefill_chunk,
            share_prefix=not args.no_share_prefix,
            host_cache_bytes=args.host_cache_mb << 20,
            pipeline_depth=args.pipeline_depth,
            ragged_attention=not args.no_ragged_attention,
            spec_k=args.spec_k if draft is not None else 0,
            decode_rounds=args.decode_rounds,
            # "auto" resolves the roofline peak from the per-platform
            # table (PR 15); a number passes through unchanged.
            hbm_gbps=resolve_hbm_gbps(args.hbm_gbps),
        )
        control = ControlConfig() if args.adaptive else None
        if args.replicas > 1:
            # Prefix-affinity replica fleet (PR 14): K batchers behind
            # the one gateway, routed by resident-chain affinity with
            # preempt-to-host-tier under overload. --host-cache-mb
            # budgets the ONE fleet-shared store.
            from llm_consensus_tpu.serving.fleet import (
                FleetBackend,
                FleetConfig,
                ReplicaSet,
            )

            role = args.role
            if "," in role:
                role = tuple(r.strip() for r in role.split(","))
            host_store = None
            if args.host_store:
                # Remote page-store tier (PR 16): the fleet's shared
                # host tier lives in another process; --host-cache-mb
                # still gates tier ENGAGEMENT (the budget itself is
                # the server's).
                from llm_consensus_tpu.serving.remote_store import (
                    RemotePageStore,
                )

                host_store = RemotePageStore(args.host_store)
            return FleetBackend(
                ReplicaSet(
                    cfg,
                    params,
                    tokenizer=load_tokenizer(args.tokenizer),
                    config=serve_config,
                    host_store=host_store,
                    fleet=FleetConfig(
                        replicas=args.replicas,
                        role=role,
                        # Keep the router's wedged-replica threshold in
                        # lockstep with the gateway's /readyz one: two
                        # independent defaults would let /readyz report
                        # a replica wedged while the router still
                        # routes to it (or vice versa). The main
                        # parser has no --ready-stall-s; fall back to
                        # the serve default.
                        ready_stall_s=getattr(
                            args, "ready_stall_s", 10.0
                        ),
                    ),
                    mesh=mesh,
                    draft=draft,
                    control=control,
                )
            )
        single_kw = {}
        if args.host_store:
            from llm_consensus_tpu.serving.remote_store import (
                RemotePageStore,
            )

            single_kw["host_store"] = RemotePageStore(args.host_store)
        batcher = ContinuousBatcher(
            cfg,
            params,
            tokenizer=load_tokenizer(args.tokenizer),
            config=serve_config,
            mesh=mesh,
            draft=draft,
            controller=(
                AdaptiveController(control) if control is not None else None
            ),
            **single_kw,
        )
        return ContinuousBackend(batcher)
    engine = InferenceEngine(
        cfg,
        params,
        tokenizer=load_tokenizer(args.tokenizer),
        engine_config=EngineConfig(
            max_new_tokens=args.max_new_tokens, quant=args.quant
        ),
        mesh=mesh,
        draft=draft,
    )
    return LocalBackend(engine)


def _parse_model_spec(raw: str) -> dict[str, str]:
    """``"name=large,preset=llama-1b,draft_from=small"`` -> dict.
    Validates keys at parse time so a typo is argparse-style usage
    feedback, not a KeyError mid-engine-build."""
    allowed = {
        "name", "preset", "checkpoint", "tokenizer", "slots",
        "spec_k", "replicas", "adaptive", "draft_from",
    }
    kv: dict[str, str] = {}
    for part in raw.split(","):
        k, sep, v = part.partition("=")
        k = k.strip()
        if not sep or not k or not v.strip():
            raise SystemExit(
                f"bad --model-spec entry {part!r} (want KEY=VAL,...)"
            )
        if k not in allowed:
            raise SystemExit(
                f"unknown --model-spec key {k!r} (have {sorted(allowed)})"
            )
        kv[k] = v.strip()
    for req in ("name", "preset"):
        if req not in kv:
            raise SystemExit(f"--model-spec needs {req}= (got {raw!r})")
    return kv


def _build_modelset_backend(args):
    """Build the multi-model serving plane (PR 18) from --model-spec
    flags: one engine per member, cross-model draft pairings resolved
    through vocab alignment, one ModelSetBackend behind the gateway.
    Global continuous-serving flags (--prefill-chunk, --host-cache-mb,
    --decode-rounds, ...) set every member's baseline; per-member keys
    (slots, spec_k, replicas, adaptive) override. Each member gets its
    OWN ContinuousConfig instance — the live-knob aliasing contract is
    per model, never across models."""
    import jax

    from llm_consensus_tpu.engine.tokenizer import load_tokenizer
    from llm_consensus_tpu.models.configs import get_config
    from llm_consensus_tpu.models.transformer import init_params
    from llm_consensus_tpu.serving.continuous import ContinuousConfig
    from llm_consensus_tpu.serving.control import (
        ControlConfig,
        resolve_hbm_gbps,
    )
    from llm_consensus_tpu.serving.fleet import FleetConfig
    from llm_consensus_tpu.serving.modelset import (
        ModelSet,
        ModelSetBackend,
        ModelSpec,
    )

    specs = []
    for i, raw in enumerate(args.model_spec):
        kv = _parse_model_spec(raw)
        cfg = get_config(kv["preset"])
        if kv.get("checkpoint"):
            params = _load_checkpoint_params(cfg, kv["checkpoint"])
        else:
            log.warning(
                "member %r: no checkpoint — RANDOM weights for %s "
                "(plumbing only; text will be gibberish).",
                kv["name"],
                cfg.name,
            )
            # Distinct seed per member: two members of the same preset
            # must not alias weights (their store scopes and consensus
            # roles differ).
            params = init_params(cfg, jax.random.PRNGKey(i))
        pairs = bool(kv.get("draft_from"))
        config = ContinuousConfig(
            max_slots=int(kv.get("slots", args.serve_slots)),
            max_new_tokens=args.max_new_tokens,
            prefill_chunk=args.prefill_chunk,
            share_prefix=not args.no_share_prefix,
            host_cache_bytes=args.host_cache_mb << 20,
            pipeline_depth=args.pipeline_depth,
            ragged_attention=not args.no_ragged_attention,
            spec_k=int(kv.get("spec_k", args.spec_k)) if pairs else 0,
            decode_rounds=args.decode_rounds,
            hbm_gbps=resolve_hbm_gbps(args.hbm_gbps),
        )
        replicas = int(kv.get("replicas", 1))
        fleet = None
        if replicas > 1:
            fleet = FleetConfig(
                replicas=replicas,
                ready_stall_s=getattr(args, "ready_stall_s", 10.0),
            )
        adaptive = kv.get("adaptive")
        control = None
        if adaptive == "1" or (adaptive is None and args.adaptive):
            control = ControlConfig()
        specs.append(
            ModelSpec(
                name=kv["name"],
                cfg=cfg,
                params=params,
                tokenizer=load_tokenizer(
                    kv.get("tokenizer") or args.tokenizer
                ),
                config=config,
                fleet=fleet,
                draft_from=kv.get("draft_from"),
                control=control,
            )
        )
    return ModelSetBackend(ModelSet(specs, default=args.model_default))


def _add_backend_args(p: argparse.ArgumentParser) -> None:
    """Backend-construction flags — the ONE definition of everything
    `_build_backend` reads, shared by the main parser and `serve` so the
    two cannot drift apart."""
    p.add_argument(
        "--backend", choices=["fake", "local", "continuous"], default="fake"
    )
    p.add_argument(
        "--serve-slots",
        type=int,
        default=8,
        help="continuous backend: decode slots (batch width of the "
        "one compiled decode program)",
    )
    p.add_argument(
        "--replicas",
        type=int,
        default=1,
        help="continuous backend: batcher replicas behind the one "
        "gateway (PR 14) — requests route by prefix affinity (a "
        "request lands on the replica whose registry/host-tier "
        "already holds its prompt's chain; consensus panels make "
        "that the common case), fall back to least modeled cost, "
        "and under overload the fleet preempts resident chains to "
        "the shared host tier (--host-cache-mb, fleet-wide budget) "
        "instead of shedding 429s. 1 = a single batcher (the classic "
        "path)",
    )
    p.add_argument(
        "--role",
        default="mixed",
        help="continuous backend with --replicas > 1: replica roles "
        "(PR 16) — 'mixed' (default, uniform fleet), or a comma list "
        "naming each replica's role, e.g. 'prefill,decode': prefill "
        "replicas run admission + chunked prefill only (spec and "
        "R-round windows off) and hand finished chains through the "
        "fleet page store; decode replicas restore them and stream "
        "tokens. At least one replica must be decode-capable",
    )
    p.add_argument(
        "--host-store",
        default=None,
        metavar="ENDPOINT",
        help="continuous backend: serve the host KV tier from a REMOTE "
        "page-store server (PR 16) instead of an in-process one — "
        "'tcp://host:port' or 'uds:///path' of a running "
        "`python -m llm_consensus_tpu.serving.remote_store`. Requires "
        "--host-cache-mb > 0 (the tier must be engaged); store "
        "outages degrade to local recompute, never wedge serving",
    )
    p.add_argument(
        "--prefill-chunk",
        type=int,
        default=64,
        help="continuous backend: prefill-chunk tokens interleaved "
        "between decode steps (0 = legacy blocking prefill)",
    )
    p.add_argument(
        "--no-share-prefix",
        action="store_true",
        help="continuous backend: disable copy-on-write shared-prefix "
        "page dedup",
    )
    p.add_argument(
        "--host-cache-mb",
        type=int,
        default=0,
        help="continuous backend: host-RAM KV offload tier budget in "
        "MiB (0 = off) — evicted prefix-registry pages demote to host "
        "buffers and restore at the next same-prefix admission instead "
        "of re-prefilling",
    )
    p.add_argument(
        "--no-ragged-attention",
        action="store_true",
        help="continuous backend: disable the fused scheduler step "
        "(PR 8) — prefill chunks run as standalone device programs "
        "between decode steps instead of riding the decode dispatch "
        "as ragged-kernel rows (outputs are identical either way)",
    )
    p.add_argument(
        "--pipeline-depth",
        type=int,
        default=2,
        help="continuous backend: decode programs in flight at once — "
        "the host loop enqueues program n+1 before fetching program "
        "n's tokens, hiding scheduling work behind device compute "
        "(1 = the serialized loop; outputs are identical either way)",
    )
    p.add_argument(
        "--decode-rounds",
        type=int,
        default=1,
        help="continuous backend: decode rounds folded into one "
        "device program (PR 12) — stop scan, sampling, and emit/"
        "length bookkeeping run on device and a row hitting a stop "
        "or its token budget mid-window freezes (no further KV "
        "writes or PRNG folds) while neighbors keep decoding; the "
        "host fetches once per R rounds. Text is byte-identical to "
        "1 (the default); engages with steps-per-sync 1 on every "
        "topology (meshes included since PR 13), "
        "and requests whose stop sequences have no bounded device "
        "screen collapse the window to 1 while they decode",
    )
    def _hbm_gbps_arg(v: str) -> str:
        # Validate at parse time (argparse's clean usage error, not a
        # traceback mid-backend-build) but RETURN the string:
        # resolving "auto" needs jax.devices(), which must not run
        # before --cpu has had its chance to pin the platform.
        if v.strip().lower() != "auto":
            float(v)  # raises ValueError -> argparse "invalid value"
        return v

    p.add_argument(
        "--hbm-gbps",
        type=_hbm_gbps_arg,
        default="0",
        help="continuous backend: the device's peak HBM bandwidth in "
        "GB/s for roofline attribution — > 0 publishes "
        "gateway_program_mbu{kind} (modeled program HBM bytes / "
        "measured wall time / this peak; ~1.0 = at the weights+KV "
        "roofline). 'auto' resolves it from a per-platform table "
        "(TPU v4/v5e/v5p + a CPU-smoke sentinel; unresolvable warns "
        "once and disables MBU-driven adaptive decisions — "
        "acceptance/overhead steering keeps working). 0 = gauge off; "
        "the modeled-bytes and measured-seconds sums still "
        "accumulate in the batcher's stats()",
    )
    p.add_argument(
        "--adaptive",
        action="store_true",
        help="continuous backend: roofline-adaptive runtime control "
        "(PR 15) — auto-tune effective spec_k from measured per-group "
        "acceptance, decode-round windows from modeled MBU + token "
        "budgets, prefill-chunk width and pipeline depth from "
        "un-overlapped scheduler overhead, and pace preempt-to-host-"
        "tier demotions by modeled restore debt. Decisions ride "
        "gateway_autotune_* and the flight recorder; text stays "
        "byte-identical to any fixed knob setting (default off = "
        "every knob static)",
    )
    p.add_argument(
        "--cpu",
        action="store_true",
        help="force the CPU backend (the env may preimport jax with a "
        "TPU tunnel registered, so JAX_PLATFORMS alone is too late)",
    )
    p.add_argument("--model", default="llama-1b", help="model preset name")
    p.add_argument("--checkpoint", default=None, help="orbax checkpoint dir")
    p.add_argument(
        "--hf-checkpoint",
        default=None,
        help="HF safetensors checkpoint dir (config.json derives the "
        "model config; overrides --model/--checkpoint)",
    )
    p.add_argument(
        "--quant",
        choices=["none", "int8", "int4"],
        default="none",
        help="weight-only quantization for the local engine",
    )
    p.add_argument("--tokenizer", default=None, help="local HF tokenizer dir")
    p.add_argument(
        "--draft-model",
        default=None,
        help="model preset for a speculative-decoding draft (greedy "
        "requests then ride draft-and-verify; output is unchanged)",
    )
    p.add_argument(
        "--draft-checkpoint",
        default=None,
        help="orbax checkpoint dir for the draft model's weights",
    )
    p.add_argument(
        "--spec-k",
        type=int,
        default=4,
        help="continuous backend: draft tokens proposed per speculative "
        "verify round (with --draft-model; the batcher drafts once per "
        "shared-prefix panel group, verifies all slots' drafts in one "
        "ragged device program, and rolls back rejected tokens by "
        "count bookkeeping — greedy output is byte-identical to "
        "spec-off)",
    )
    p.add_argument(
        "--mesh",
        default=None,
        metavar="AXIS=N[,AXIS=N...]",
        help="shard the local engine over a device mesh, e.g. "
        "'data=4,model=2' (axes: data/model/expert/seq/pipe; product "
        "must equal the device count; seq>1 enables ring attention)",
    )
    p.add_argument(
        "--model-spec",
        action="append",
        default=None,
        metavar="KEY=VAL[,KEY=VAL...]",
        help="continuous backend: one multi-model SET member per flag "
        "(PR 18) — repeat to add members; overrides --model/"
        "--draft-model. Keys: name (required), preset (required "
        "model-config preset), checkpoint, tokenizer, slots, spec_k, "
        "replicas, adaptive=0/1, draft_from=<member> (mount that "
        "member's weights as this member's speculative draft across "
        "the tokenizer boundary via exact-match vocab alignment). "
        "Example: --model-spec name=large,preset=llama-1b,"
        "draft_from=small --model-spec name=small,preset=llama-debug",
    )
    p.add_argument(
        "--model-default",
        default=None,
        help="multi-model: member serving untagged requests (default: "
        "the first --model-spec)",
    )
    p.add_argument(
        "--model-lanes",
        action="store_true",
        help="multi-model: add one model:<name> admission lane per "
        "member — requests tagged with a model queue behind their own "
        "bound instead of the shared interactive lane",
    )


def _add_protocol_args(p: argparse.ArgumentParser) -> None:
    """Panel-protocol defaults shared by the REPL and `serve`."""
    p.add_argument("--panel", default=None, help="panel JSON file")
    p.add_argument(
        "--max-rounds",
        type=int,
        default=5,
        help="evaluation-round cap (the reference hard-codes 5, "
        "src/main.rs:299-300)",
    )
    p.add_argument("--max-new-tokens", type=int, default=256)
    p.add_argument("--temperature", type=float, default=0.7)
    p.add_argument("--seed", type=int, default=None)


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="llm_consensus_tpu",
        description="Multi-persona LLM consensus on local TPU inference.",
    )
    _add_backend_args(p)
    _add_protocol_args(p)
    p.add_argument(
        "--question", default=None, help="answer one question and exit"
    )
    p.add_argument(
        "--debate",
        type=int,
        default=None,
        metavar="N",
        help="answer --question via N-candidate multi-round debate "
        "(consensus/debate.py) instead of the panel protocol "
        "(needs --backend local)",
    )
    p.add_argument(
        "--debate-method",
        default="majority",
        choices=("majority", "logit_pool", "rescore"),
        help="per-round debate vote: head count, pool by sampling "
        "logprob, or teacher-forced judge re-scoring",
    )
    p.add_argument(
        "--stream",
        action="store_true",
        help="stream a single-model completion of --question token by "
        "token (bypasses the panel protocol; needs --backend local)",
    )
    p.add_argument(
        "--eval-gsm8k",
        default=None,
        metavar="JSONL|bundled|synthetic|synthetic2",
        help="run the GSM8K EM harness on a JSONL file, the bundled "
        "50-problem dataset (eval/data/gsm8k_mini.jsonl), 'synthetic' "
        "(single-template arithmetic), or 'synthetic2' (the hard "
        "multi-step multi-template task, eval/arith2.py)",
    )
    p.add_argument("--eval-n", type=int, default=8, help="candidates per problem")
    p.add_argument("--eval-limit", type=int, default=20)
    p.add_argument(
        "--plan",
        action="store_true",
        help="print the HBM capacity plan for --model at --plan-n/"
        "--plan-context (config-only, nothing is allocated): does the "
        "config fit one chip, and what does a mesh buy? Honors "
        "--plan-quant/--plan-mesh (e.g. 'data=4,model=2').",
    )
    p.add_argument("--plan-n", type=int, default=64)
    p.add_argument("--plan-context", type=int, default=2048)
    p.add_argument(
        "--plan-quant", default="int8", choices=("none", "int8", "int4")
    )
    p.add_argument(
        "--plan-kv",
        default="int8",
        choices=("none", "int8"),
        help="KV-cache quantization the plan assumes (bf16 doubles the "
        "cache term)",
    )
    p.add_argument("--plan-mesh", default="", metavar="AXIS=N,...")
    p.add_argument(
        "--plan-hbm-gib", type=float, default=16.0, help="per-chip HBM"
    )
    return p


def _parse_axes(spec: str) -> dict[str, int]:
    """``"data=4,model=2"`` -> ``{"data": 4, "model": 2}`` — the one
    parser behind both ``--mesh`` and ``--plan-mesh``."""
    sizes: dict[str, int] = {}
    for part in spec.split(","):
        axis, sep, n = part.partition("=")
        if not sep or not axis.strip() or not n.strip():
            raise SystemExit(
                f"bad mesh axis spec {part!r} (want AXIS=N,...)"
            )
        sizes[axis.strip()] = int(n)
    return sizes


def _run_plan(args) -> int:
    """Capacity planning without touching a device (``--plan``)."""
    import json as _json

    from llm_consensus_tpu.engine.engine import plan_memory
    from llm_consensus_tpu.models.configs import get_config

    mesh_shape = _parse_axes(args.plan_mesh) if args.plan_mesh else {}
    prompt = max(1, args.plan_context - args.max_new_tokens)
    plan = plan_memory(
        get_config(args.model),
        quant=args.plan_quant,
        kv_quant=args.plan_kv == "int8",
        n_candidates=args.plan_n,
        prompt_len=prompt,
        new_tokens=args.max_new_tokens,
        mesh_shape=mesh_shape or None,
        hbm_bytes=int(args.plan_hbm_gib * (1 << 30)),
    )
    gib = 1 << 30
    out = {
        "model": args.model,
        "quant": args.plan_quant,
        "kv_quant": args.plan_kv,
        "n_candidates": args.plan_n,
        "context": args.plan_context,
        "mesh": mesh_shape or "single chip",
        "params_gib": round(plan["params_bytes"] / gib, 2),
        "kv_cache_gib": round(plan["kv_cache_bytes"] / gib, 2),
        "total_gib": round(plan["total_bytes"] / gib, 2),
        "hbm_gib": args.plan_hbm_gib,
        "fits": plan["fits"],
    }
    print(_json.dumps(out, indent=2))
    return 0 if plan["fits"] else 1


async def repl(coord: Coordinator, stream=None) -> None:
    """Interactive loop with reference UX parity (``src/main.rs:428-471``)."""
    out = stream or sys.stdout
    while True:
        out.write("Enter a question: ")
        out.flush()
        line = await asyncio.to_thread(sys.stdin.readline)
        if not line:
            break
        question = line.strip()
        if question == "exit":
            break
        if not question:
            continue
        await coord.ask_question(question)
        answer = await coord.wait_for_answer()
        log.info("Final answer: %s", answer)
        out.write(f"\n{_printable(answer)}\n\n")
        coord.reset()


def build_serve_parser() -> argparse.ArgumentParser:
    """Parser for the ``serve`` subcommand (the serving gateway).

    Shares the backend-construction flags with the main parser so
    ``serve`` can front any substrate the REPL can (fake for tests,
    local engines incl. mesh/quant/draft for real serving).
    """
    p = argparse.ArgumentParser(
        prog="llm_consensus_tpu serve",
        description="HTTP serving gateway: /v1/generate, /v1/consensus, "
        "/metrics, /healthz (SIGTERM drains gracefully).",
    )
    _add_backend_args(p)
    _add_protocol_args(p)
    # Gateway flags.
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument(
        "--port",
        type=int,
        default=8080,
        help="TCP port (0 = ephemeral; the bound port is logged)",
    )
    p.add_argument(
        "--queue-bound",
        type=int,
        default=64,
        help="per-priority admission queue bound (full => 429 + "
        "Retry-After)",
    )
    p.add_argument(
        "--max-inflight",
        type=int,
        default=8,
        help="concurrent in-flight executions across priorities",
    )
    p.add_argument(
        "--admission-cost-budget-mb",
        type=int,
        default=0,
        help="cost-budget admission (PR 15): switch every queue bound "
        "from request counts to MODELED BYTES — each request charges "
        "its modeled KV schedule (the same unit the fleet router's "
        "load_cost compares), so a 32k-context request is no longer "
        "one unit of work and the overflow hard cap is bytes too. "
        "0 = classic request-count bounds (--queue-bound)",
    )
    p.add_argument(
        "--default-deadline-s",
        type=float,
        default=None,
        help="deadline applied to requests that do not carry one",
    )
    # Observability (PR 5): request-scoped tracing + profiler bridge.
    p.add_argument(
        "--no-trace",
        action="store_true",
        help="disable request-scoped tracing (trace ids, /debug/traces "
        "span trees, and the span-derived histograms' trace side; "
        "default ON — bench.py --serve-trace-overhead measures the "
        "cost at < 2%%)",
    )
    p.add_argument(
        "--trace-max-traces",
        type=int,
        default=256,
        help="bounded trace-store ring: retained request traces "
        "(evict-oldest; drops counted in gateway_trace_dropped_total)",
    )
    p.add_argument(
        "--trace-max-spans",
        type=int,
        default=2048,
        help="span budget per trace (excess spans dropped + counted)",
    )
    # Observability (PR 10): the serving flight recorder.
    p.add_argument(
        "--no-flight",
        action="store_true",
        help="disable the serving flight recorder (typed scheduler "
        "events at GET /debug/flight incl. the Perfetto-loadable "
        "?format=chrome export; default ON — bench.py "
        "--serve-flight-overhead holds the cost under the PR-5 2%% "
        "tok/s gate)",
    )
    p.add_argument(
        "--flight-events",
        type=int,
        default=8192,
        help="bounded flight-recorder ring: retained scheduler events "
        "(evict-oldest; drops counted in gateway_flight_dropped_total)",
    )
    p.add_argument(
        "--profile-dir",
        default=None,
        help="enable the X-Profile: 1 request header: capture a JAX "
        "device profile (TensorBoard format) into this directory for "
        "the flagged request, aligned with its host trace spans",
    )
    p.add_argument(
        "--ready-stall-s",
        type=float,
        default=10.0,
        help="GET /readyz returns 503 when the backend serving loop's "
        "heartbeat is older than this (wedged loop)",
    )
    p.add_argument(
        "--peer",
        action="append",
        default=None,
        metavar="URL",
        help="cross-host peer tier (PR 16, repeatable): run this "
        "gateway as a routing FRONT over peer gateways at these base "
        "URLs ('http://host:port') — each /v1/* request is forwarded "
        "to the peer whose GET /debug/chains probe shows the longest "
        "resident chain for its prompt (move the query, not the "
        "cache). The local backend still serves /healthz, /metrics "
        "and debug routes; use --backend fake for a pure front",
    )
    # Fleet observability (PR 20).
    p.add_argument(
        "--no-fleet-obs",
        action="store_true",
        help="disable fleet observability federation (PR 20): "
        "X-Trace-Id propagation/adoption across peer forwards, the "
        "per-hop meta['hops'] breakdown on /v1/* responses, and the "
        "/metrics?fleet=1 + /debug/flight?fleet=1 merged views "
        "(default ON — bench.py --serve-fleet-obs holds the cost "
        "under the PR-5 2%% tok/s gate)",
    )
    # Fleet control plane (PR 19).
    p.add_argument(
        "--fleet-control",
        action="store_true",
        help="fleet control plane (PR 19): run one FleetController "
        "over the --replicas fleet — SLO-aware admission (requests "
        "carry an optional 'slo' payload field; at a full queue the "
        "request that WILL miss its target is shed, never simply the "
        "newest), tenant weighted fair queueing over the 'tenant' "
        "field, router load-weight steering from live queue-cost "
        "signals, group/restore sizing, and elastic replica "
        "spawn/retire (--elastic-max). Requires --replicas > 1",
    )
    p.add_argument(
        "--slo-target",
        action="append",
        default=None,
        metavar="CLASS=SECONDS",
        help="fleet control: SLO class -> queue-wait target seconds "
        "(repeatable; default interactive=2,batch=30). Defines the "
        "classes the /v1/generate 'slo' payload field accepts",
    )
    p.add_argument(
        "--slo-class",
        default="interactive",
        help="fleet control: default SLO class for untagged requests "
        "('none' = untagged requests stay SLO-blind)",
    )
    p.add_argument(
        "--tenant-weight",
        action="append",
        default=None,
        metavar="TENANT=WEIGHT",
        help="fleet control: tenant fair-share weight (repeatable; "
        "unlisted tenants weigh 1.0)",
    )
    p.add_argument(
        "--elastic-max",
        type=int,
        default=0,
        help="fleet control: elastic replica ceiling (0 = fixed "
        "fleet; above --replicas the controller spawns batchers "
        "against sustained queue depth and retires them when the "
        "fleet idles, draining through the shared host tier)",
    )
    return p


def _parse_fleet_control(args):
    """``serve --fleet-control`` flags -> :class:`FleetControlConfig`
    (None when the flag is off). Shared by serve and bench."""
    if not getattr(args, "fleet_control", False):
        return None
    from llm_consensus_tpu.serving.fleet_control import FleetControlConfig

    cfg = FleetControlConfig()
    if args.slo_target:
        classes = {}
        for spec in args.slo_target:
            name, _, secs = spec.partition("=")
            if not name or not secs:
                raise SystemExit(
                    f"--slo-target expects CLASS=SECONDS, got {spec!r}"
                )
            classes[name] = float(secs)
        cfg.slo_classes = classes
    default = args.slo_class
    cfg.default_slo_class = None if default in (None, "none", "") else default
    if (
        cfg.default_slo_class is not None
        and cfg.default_slo_class not in cfg.slo_classes
    ):
        raise SystemExit(
            f"--slo-class {cfg.default_slo_class!r} is not one of the "
            f"--slo-target classes {sorted(cfg.slo_classes)}"
        )
    if args.tenant_weight:
        weights = {}
        for spec in args.tenant_weight:
            name, _, w = spec.partition("=")
            if not name or not w:
                raise SystemExit(
                    f"--tenant-weight expects TENANT=WEIGHT, got {spec!r}"
                )
            weights[name] = float(w)
        cfg.tenant_weights = weights
    if args.elastic_max:
        cfg.elastic_min = max(1, args.replicas)
        cfg.elastic_max = args.elastic_max
        if cfg.elastic_max < cfg.elastic_min:
            raise SystemExit(
                f"--elastic-max {cfg.elastic_max} is below "
                f"--replicas {cfg.elastic_min}"
            )
    return cfg


def _run_serve(argv: list[str]) -> int:
    """The ``serve`` subcommand: build backend + panel, run the gateway
    until SIGTERM/SIGINT, then drain (stop admitting, finish in-flight)."""
    import signal

    from llm_consensus_tpu.server.admission import AdmissionConfig
    from llm_consensus_tpu.server.gateway import Gateway, GatewayConfig

    args = build_serve_parser().parse_args(argv)
    if args.cpu:
        import jax

        jax.config.update("jax_platforms", "cpu")
    from llm_consensus_tpu.utils import tracing as _tracing

    if args.no_trace:
        _tracing.set_enabled(False)
    _tracing.trace_store().configure(
        max_traces=args.trace_max_traces, max_spans=args.trace_max_spans
    )
    from llm_consensus_tpu.serving import flight as _flight

    if args.no_flight:
        _flight.set_enabled(False)
    _flight.flight_recorder().configure(capacity=args.flight_events)
    panel = load_panel(args.panel) if args.panel else default_panel()
    fleet_cfg = _parse_fleet_control(args)
    backend = _build_backend(args)
    # Fleet control plane (PR 19): one controller over the replica
    # fleet. Its config also seeds the gateway's SLO classes and
    # tenant weights (admission_kwargs below) so the two layers agree.
    fleet_controller = None
    if fleet_cfg is not None:
        replicas = getattr(backend, "replicas", None)
        if replicas is None:
            raise SystemExit(
                "--fleet-control requires the replica fleet backend "
                "(--backend continuous --replicas 2+)"
            )
        from llm_consensus_tpu.serving.fleet_control import FleetController

        fleet_controller = FleetController(replicas, fleet_cfg)
    # Per-model admission lanes (PR 18): a multi-model backend adds one
    # ``model:<name>`` priority lane per member behind the base pair —
    # a request tagged with a model defaults into its own lane (the
    # gateway's _lane_for), so one member's burst queues behind its own
    # bound instead of starving the panel's other models.
    priorities: tuple[str, ...] = ("interactive", "batch")
    modelset = getattr(backend, "modelset", None)
    if modelset is not None and args.model_lanes:
        priorities = priorities + modelset.admission_lanes()
    admission_kw = fleet_cfg.admission_kwargs() if fleet_cfg else {}
    gateway = Gateway(
        backend,
        panel=panel,
        config=GatewayConfig(
            host=args.host,
            port=args.port,
            admission=AdmissionConfig(
                priorities=priorities,
                max_queue=args.queue_bound,
                max_inflight=args.max_inflight,
                default_deadline_s=args.default_deadline_s,
                cost_budget_bytes=float(
                    args.admission_cost_budget_mb << 20
                ),
                **admission_kw,
            ),
            sampling=SamplingParams(
                max_new_tokens=args.max_new_tokens,
                temperature=args.temperature,
            ),
            max_rounds=args.max_rounds,
            consensus_seed=args.seed,
            ready_stall_s=args.ready_stall_s,
            profile_dir=args.profile_dir,
            peers=tuple(args.peer or ()),
            fleet_obs=not args.no_fleet_obs,
        ),
    )
    if fleet_controller is not None:
        # Burn-rate pressure (PR 20): give the controller a live view
        # of the admission tier's per-class SLO burn so _steer_elastic
        # can spawn on sustained burn even before queues deepen.
        fleet_controller.attach_admission(gateway.admission)

    async def _serve() -> None:
        stop = asyncio.Event()
        loop = asyncio.get_running_loop()
        for sig in (signal.SIGTERM, signal.SIGINT):
            try:
                loop.add_signal_handler(sig, stop.set)
            except NotImplementedError:  # non-Unix event loops
                pass
        await gateway.run_until(stop)

    if fleet_controller is not None:
        fleet_controller.start()
    try:
        asyncio.run(_serve())
    finally:
        if fleet_controller is not None:
            fleet_controller.stop()
    return 0


def main(argv: list[str] | None = None) -> int:
    _init_logging()
    if argv is None:
        argv = sys.argv[1:]
    if argv[:1] == ["serve"]:
        return _run_serve(argv[1:])
    args = build_parser().parse_args(argv)

    if args.cpu:
        import jax

        jax.config.update("jax_platforms", "cpu")
    if args.plan:
        return _run_plan(args)
    if args.eval_gsm8k is not None:
        return _run_eval(args)
    if args.debate is not None:
        return _run_debate(args)
    if args.stream:
        return _run_stream(args)

    panel = load_panel(args.panel) if args.panel else default_panel()
    backend = _build_backend(args)
    coord = Coordinator(
        panel,
        backend,
        CoordinatorConfig(
            max_rounds=args.max_rounds,
            seed=args.seed,
            sampling=SamplingParams(
                max_new_tokens=args.max_new_tokens,
                temperature=args.temperature,
            ),
        ),
    )
    if args.question is not None:
        result = asyncio.run(coord.run(args.question))
        print(_printable(result.answer))
        return 0
    asyncio.run(repl(coord))
    return 0


def _run_stream(args) -> int:
    if args.backend != "local":
        print("--stream needs --backend local", file=sys.stderr)
        return 2
    if not args.question:
        print("--stream needs --question", file=sys.stderr)
        return 2
    backend = _build_backend(args)
    for piece in backend.engine.generate_stream(
        args.question,
        temperature=args.temperature,
        seed=args.seed if args.seed is not None else 0,
        max_new_tokens=args.max_new_tokens,
    ):
        print(_printable(piece), end="", flush=True)
    print()
    return 0


def _run_debate(args) -> int:
    from llm_consensus_tpu.consensus.debate import DebateConfig, run_debate

    if args.backend == "fake":
        print("--debate needs --backend local", file=sys.stderr)
        return 2
    if not args.question:
        print("--debate needs --question", file=sys.stderr)
        return 2
    if args.debate < 1:
        print(f"--debate needs N >= 1, got {args.debate}", file=sys.stderr)
        return 2
    backend = _build_backend(args)
    result = run_debate(
        backend.engine,
        args.question,
        DebateConfig(
            n_candidates=args.debate,
            max_rounds=args.max_rounds,
            temperature=args.temperature,
            max_new_tokens=args.max_new_tokens,
            seed=args.seed or 0,
            method=args.debate_method,
        ),
    )
    log.info(
        "Debate: %d rounds, %d candidate-tokens, winner tally %s",
        result.n_rounds,
        result.total_tokens,
        result.vote.tally,
    )
    print(_printable(result.answer))
    return 0


def _run_eval(args) -> int:
    import json

    from llm_consensus_tpu.eval.gsm8k import (
        evaluate_self_consistency,
        load_gsm8k,
        synthetic_problems,
    )

    if args.backend == "fake":
        print("GSM8K eval needs --backend local", file=sys.stderr)
        return 2
    backend = _build_backend(args)
    if args.eval_gsm8k == "synthetic":
        problems = synthetic_problems(args.eval_limit)
    elif args.eval_gsm8k == "synthetic2":
        # The hard offline task (eval/arith2.py): multi-step chains,
        # six narrative frames, distractors — serve an arith2-trained
        # checkpoint (--checkpoint runs/arith25m --model arith-25m)
        # and measure EM-vs-N from the same CLI the REPL uses.
        from llm_consensus_tpu.eval.arith2 import eval_problems

        problems, _ = eval_problems(args.eval_limit)
    elif args.eval_gsm8k == "bundled":
        import llm_consensus_tpu.eval as _eval_pkg

        bundled = os.path.join(
            os.path.dirname(_eval_pkg.__file__), "data", "gsm8k_mini.jsonl"
        )
        problems = load_gsm8k(bundled, limit=args.eval_limit)
    else:
        problems = load_gsm8k(args.eval_gsm8k, limit=args.eval_limit)
    report = evaluate_self_consistency(
        backend.engine,
        problems,
        n=args.eval_n,
        temperature=args.temperature,
        max_new_tokens=args.max_new_tokens,
    )
    print(json.dumps(report.to_dict()))
    return 0


if __name__ == "__main__":  # pragma: no cover
    raise SystemExit(main())
