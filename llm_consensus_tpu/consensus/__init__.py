from llm_consensus_tpu.consensus.messages import (
    AnswerEvaluation,
    AnswerRefinement,
    Feedback,
)
from llm_consensus_tpu.consensus.coordinator import (
    Coordinator,
    CoordinatorConfig,
    ConsensusResult,
)
from llm_consensus_tpu.consensus.personas import Persona, default_panel

__all__ = [
    "AnswerEvaluation",
    "AnswerRefinement",
    "Feedback",
    "Coordinator",
    "CoordinatorConfig",
    "ConsensusResult",
    "Persona",
    "default_panel",
]
