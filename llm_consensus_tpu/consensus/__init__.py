from llm_consensus_tpu.consensus.messages import (
    AnswerEvaluation,
    AnswerRefinement,
    Feedback,
)
from llm_consensus_tpu.consensus.coordinator import (
    Coordinator,
    CoordinatorConfig,
    ConsensusResult,
)
from llm_consensus_tpu.consensus.personas import (
    Persona,
    default_panel,
    load_panel,
    save_panel,
)
from llm_consensus_tpu.consensus.debate import (
    DebateConfig,
    DebateResult,
    run_debate,
    run_panel_debate,
)
from llm_consensus_tpu.consensus.voting import (
    VoteResult,
    logit_pool,
    rescore_vote,
    majority_vote,
    self_consistency,
    weighted_vote,
)

__all__ = [
    "AnswerEvaluation",
    "AnswerRefinement",
    "Feedback",
    "Coordinator",
    "CoordinatorConfig",
    "ConsensusResult",
    "DebateConfig",
    "DebateResult",
    "Persona",
    "VoteResult",
    "default_panel",
    "load_panel",
    "logit_pool",
    "rescore_vote",
    "majority_vote",
    "run_debate",
    "run_panel_debate",
    "save_panel",
    "self_consistency",
    "weighted_vote",
]
