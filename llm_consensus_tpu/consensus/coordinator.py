"""Consensus coordinator: the propose -> panel-evaluate -> refine state machine.

Parity target: the reference's ``Coordinator`` actix actor
(``src/main.rs:187-348``) — state {question, feedback map, answer,
evaluation_count} (``:189-195``), handlers for AskQuestion (``:220-239``,
random proposer), AnswerQuestion (``:242-256``, broadcast evaluate to ALL
panelists including the author), AnswerEvaluation (``:259-291``, tally; on
any dissent pick a random dissenter to refine), AnswerRefinement
(``:293-314``, round cap: below cap re-broadcast evaluation, at cap force
all feedback to Good), AnswerReadinessRequest (``:316-325``) and GetAnswer
(``:327-336``) read path, Reset (``:338-345``).

TPU-native redesign decisions (SURVEY.md §7 step 3):

- **No actors.** A plain state machine with pure transition methods
  (``on_answer`` / ``on_evaluation`` / ``on_refinement``) plus an asyncio
  driver (``run``). Concurrency lives in the backend, not the protocol.
- **Epoch/round tags** on every message; stale messages are dropped
  (fixes the reference race where a late round-k evaluation lands after
  ``feedback.clear()`` for round k+1 — SURVEY.md §5 quirk #6).
- **Batched fan-out.** A panel evaluation round is ONE
  ``Backend.generate_batch`` call — on TPU the whole panel is a batch axis
  of a single device program, not N HTTP requests
  (reference ``src/main.rs:250-253``).
- **Configurable round cap** (the reference hard-codes 5 with a TODO at
  ``src/main.rs:299-300``).
- **Failure detection**: per-call timeout + retries; a failed evaluation
  degrades to ``NeedsRefinement`` instead of panicking (the reference
  ``expect``-panics on any backend error, ``src/main.rs:85,97,138,178``).
"""

from __future__ import annotations

import asyncio
import contextlib
import dataclasses
import logging
import random
import time
from dataclasses import dataclass, field

from llm_consensus_tpu.backends.base import (
    Backend,
    BackendError,
    GenerationRequest,
    GenerationResult,
    SamplingParams,
)
from llm_consensus_tpu.consensus.messages import (
    AnswerEvaluation,
    AnswerQuestion,
    AnswerRefinement,
    EvaluateAnswer,
    Feedback,
    RefineAnswer,
    TranscriptEvent,
)
from llm_consensus_tpu.consensus.parsing import parse_evaluation
from llm_consensus_tpu.consensus.personas import Persona
from llm_consensus_tpu.consensus.prompts import (
    answer_prompt,
    evaluation_prompt,
    refinement_prompt,
)
from llm_consensus_tpu.server.metrics import (
    CONSENSUS_FORCED as _M_FORCED,
)
from llm_consensus_tpu.server.metrics import (
    CONSENSUS_QUESTIONS as _M_QUESTIONS,
)
from llm_consensus_tpu.server.metrics import (
    CONSENSUS_ROUND_SECONDS as _M_ROUND_SECONDS,
)
from llm_consensus_tpu.server.metrics import (
    CONSENSUS_ROUNDS as _M_ROUNDS,
)
from llm_consensus_tpu.server.metrics import (
    CONSENSUS_UNANIMOUS as _M_UNANIMOUS,
)
from llm_consensus_tpu.utils import tracing as _tracing

log = logging.getLogger(__name__)


@contextlib.contextmanager
def _phase_span(phase: str, round_: int):
    """One protocol-phase timing site, two surfaces in lockstep: a
    ``consensus_round`` span on the request's trace (when one is
    active) and a ``consensus_round_seconds{phase=...}`` observation —
    the phase-resolved latency the TPLA-style disaggregated-serving
    analysis needs (prefill and decode phases have different rooflines;
    so do propose/evaluate/refine)."""
    t0 = time.perf_counter()
    with _tracing.request_span("consensus_round", phase=phase, round=round_):
        try:
            yield
        finally:
            _M_ROUND_SECONDS.labels(phase=phase).observe(
                time.perf_counter() - t0
            )


@dataclass(frozen=True)
class CoordinatorConfig:
    # Max evaluation rounds; the reference hard-codes 5
    # ("TODO: Make max count configurable.", src/main.rs:299-300).
    max_rounds: int = 5
    # RNG seed for proposer/refiner selection; None = nondeterministic
    # (the reference uses thread_rng, src/main.rs:229,272).
    seed: int | None = None
    # Per-backend-call timeout (seconds); None disables. Failure-detection
    # subsystem — NOT PRESENT in the reference (SURVEY.md §5).
    call_timeout: float | None = None
    # Retries per backend call before declaring failure.
    retries: int = 1
    # Sampling params used for panel calls unless a persona overrides.
    sampling: SamplingParams = field(default_factory=SamplingParams)
    # Consensus phase -> model routing (PR 18): with a multi-model
    # backend (serving.modelset.ModelSetBackend), map
    # "propose"/"evaluate"/"refine" to member names — propose on the
    # small proposer, judge/refine on the large — and every request of
    # that phase carries the mapped model tag (overriding any
    # per-persona model). None (default) = per-persona models only,
    # the pre-PR-18 behavior. Phases absent from the map fall back the
    # same way. ``ModelSet.phase_models()`` builds the canonical map.
    phase_models: dict[str, str] | None = None


@dataclass
class ConsensusResult:
    answer: str
    rounds: int
    # True if the final answer was genuinely endorsed by a unanimous panel;
    # False when the round cap forced termination (the reference silently
    # overwrites feedback to Good at the cap, src/main.rs:308-311 —
    # SURVEY.md §5 quirk #5; we surface the distinction).
    endorsed: bool
    author: str
    feedback: dict[str, Feedback]
    transcript: list[TranscriptEvent]


class Coordinator:
    """Drives one panel through the consensus protocol.

    Offers two API styles:

    - :meth:`run` — sequential async driver returning a
      :class:`ConsensusResult` (the idiomatic entry point).
    - REPL-parity methods mirroring the reference message surface:
      :meth:`ask_question` (spawns a background task),
      :meth:`answer_ready`, :meth:`get_answer`, :meth:`reset`
      (reference ``src/main.rs:442-470``).
    """

    def __init__(
        self,
        panel: list[Persona],
        backend: Backend,
        config: CoordinatorConfig | None = None,
        backends: dict[str, Backend] | None = None,
    ):
        if not panel:
            raise ValueError("panel must contain at least one persona")
        names = [p.name for p in panel]
        if len(set(names)) != len(names):
            # The reference silently clobbers duplicate names in its actor
            # map (src/main.rs:214) — SURVEY.md §5 quirk #6; we reject.
            raise ValueError(f"duplicate persona names in panel: {names}")
        self.panel = list(panel)
        self.backend = backend
        self.backends = backends or {}
        self.config = config or CoordinatorConfig()
        self._rng = random.Random(self.config.seed)

        # Protocol state (reference src/main.rs:189-195).
        self.epoch = 0
        self.current_question: str | None = None
        self.answer: str | None = None
        self.answer_author: str | None = None
        self.feedback: dict[str, Feedback] = {}
        self.evaluation_count = 0
        self._forced_termination = False
        self.transcript: list[TranscriptEvent] = []
        self._task: asyncio.Task | None = None

    # ------------------------------------------------------------------
    # Registration / reset (reference src/main.rs:210-218, :198-203)
    # ------------------------------------------------------------------

    def register(self, persona: Persona, backend: Backend | None = None) -> None:
        """Add a panelist (reference ``Register``, ``src/main.rs:210-218``)."""
        if any(p.name == persona.name for p in self.panel):
            raise ValueError(f"persona {persona.name!r} already registered")
        self.panel.append(persona)
        if backend is not None:
            self.backends[persona.name] = backend
        log.debug("%s registered with Coordinator.", persona.name)

    def reset(self) -> None:
        """Clear per-question state, keep the panel
        (reference ``reset``, ``src/main.rs:198-203``); bumps the epoch so
        any in-flight stale message is dropped."""
        self._reset_state()
        self._task = None

    def _reset_state(self) -> None:
        # Used by run() at question start: clears protocol state WITHOUT
        # dropping the background-task handle that ask_question holds.
        self.current_question = None
        self.answer = None
        self.answer_author = None
        self.feedback.clear()
        self.evaluation_count = 0
        self._forced_termination = False
        self.epoch += 1

    # ------------------------------------------------------------------
    # Pure state transitions (unit-testable; epoch/round staleness checks)
    # ------------------------------------------------------------------

    def _stale(self, epoch: int, round_: int | None = None) -> bool:
        if epoch != self.epoch:
            return True
        return round_ is not None and round_ != self.evaluation_count

    def on_answer(self, msg: AnswerQuestion) -> list[EvaluateAnswer]:
        """Accept a proposed answer; emit the evaluation fan-out
        (reference ``src/main.rs:242-256``). The author is included in the
        fan-out, as in the reference broadcast (quirk #2)."""
        if self._stale(msg.epoch):
            log.debug("Dropping stale AnswerQuestion (epoch %d)", msg.epoch)
            return []
        self.answer = msg.answer
        self.answer_author = msg.author
        self.evaluation_count += 1
        self.feedback.clear()
        self._event("answer", {"author": msg.author, "answer": msg.answer})
        assert self.current_question is not None
        return [
            EvaluateAnswer(
                question=self.current_question,
                answer=msg.answer,
                epoch=self.epoch,
                round=self.evaluation_count,
            )
            for _ in self.panel
        ]

    def on_evaluation(
        self, msg: AnswerEvaluation
    ) -> tuple[str, RefineAnswer] | None:
        """Record one verdict; when the tally is complete and non-unanimous,
        pick a random dissenter and emit a refinement request
        (reference ``src/main.rs:259-291``). Stale (wrong epoch/round)
        verdicts are dropped — the fix for SURVEY.md §5 quirk #6."""
        if self._stale(msg.epoch, msg.round):
            log.debug(
                "Dropping stale AnswerEvaluation from %s (epoch %d round %d)",
                msg.name,
                msg.epoch,
                msg.round,
            )
            return None
        log.debug(
            "%s evaluated the answer as %s. %s",
            msg.name,
            msg.evaluation.value,
            msg.reasoning,
        )
        self.feedback[msg.name] = msg.evaluation
        self._event(
            "evaluation",
            {"name": msg.name, "verdict": msg.evaluation.value, "reasoning": msg.reasoning},
        )
        if len(self.feedback) != len(self.panel):
            return None
        if all(f is Feedback.GOOD for f in self.feedback.values()):
            return None
        dissenters = [
            name
            for name, f in self.feedback.items()
            if f is Feedback.NEEDS_REFINEMENT
        ]
        refiner = self._rng.choice(dissenters)
        log.debug("Asking %s to refine the answer.", refiner)
        assert self.current_question is not None and self.answer is not None
        return refiner, RefineAnswer(
            question=self.current_question,
            answer=self.answer,
            epoch=self.epoch,
            round=self.evaluation_count,
        )

    def on_refinement(self, msg: AnswerRefinement) -> list[EvaluateAnswer]:
        """Accept a refined answer. Below the round cap, clear feedback and
        re-emit the evaluation fan-out; at the cap, force-approve
        (reference ``src/main.rs:293-314``; cap semantics = quirk #5:
        the final answer may be un-endorsed)."""
        if self._stale(msg.epoch, msg.round):
            log.debug(
                "Dropping stale AnswerRefinement (epoch %d round %d)",
                msg.epoch,
                msg.round,
            )
            return []
        self.answer = msg.answer
        if msg.author:
            self.answer_author = msg.author
        self._event("refinement", {"author": msg.author, "answer": msg.answer})
        if self.evaluation_count < self.config.max_rounds:
            self.evaluation_count += 1
            self.feedback.clear()
            log.debug("Asking actors to evaluate new answer.")
            assert self.current_question is not None
            return [
                EvaluateAnswer(
                    question=self.current_question,
                    answer=msg.answer,
                    epoch=self.epoch,
                    round=self.evaluation_count,
                )
                for _ in self.panel
            ]
        log.debug("Evaluated the maximum number of times. Breaking the loop.")
        self._forced_termination = True
        for name in self.feedback:
            self.feedback[name] = Feedback.GOOD
        return []

    def answer_ready(self) -> bool:
        """Readiness predicate (reference ``src/main.rs:316-325``)."""
        return (
            self.answer is not None
            and bool(self.feedback)
            and len(self.feedback) == len(self.panel)
            and all(f is Feedback.GOOD for f in self.feedback.values())
        )

    def get_answer(self) -> str:
        """Read the answer; error string when absent
        (reference ``src/main.rs:327-336``)."""
        if self.answer is not None:
            return self.answer
        return "System error: Requested answer when answer was not ready."

    # ------------------------------------------------------------------
    # Async driver
    # ------------------------------------------------------------------

    async def run(self, question: str) -> ConsensusResult:
        """Drive one question to consensus and return the result."""
        self._reset_state()
        epoch = self.epoch
        self.current_question = question
        self._event("question", {"question": question})

        # Random proposer (reference src/main.rs:228-234; quirk #1).
        proposer = self._rng.choice(self.panel)
        log.debug("Received AskQuestion: %s", question)
        with _phase_span("propose", 0):
            result = await self._call_persona(
                proposer, answer_prompt(question), required=True,
                phase="propose",
            )
        fanout = self.on_answer(
            AnswerQuestion(answer=result.text, author=proposer.name, epoch=epoch)
        )

        while fanout:
            # Panel fan-out as ONE batched backend call per backend group
            # (the reference sends N concurrent HTTP requests,
            # src/main.rs:250-253; on TPU this is one batched decode).
            assert self.answer is not None
            round_ = self.evaluation_count
            with _phase_span("evaluate", round_):
                texts = await self._generate_for_panel(
                    [
                        evaluation_prompt(question, self.answer, p)
                        for p in self.panel
                    ],
                    phase="evaluate",
                )
            refinement_request: tuple[str, RefineAnswer] | None = None
            for persona, text in zip(self.panel, texts):
                verdict, reasoning = parse_evaluation(text)
                out = self.on_evaluation(
                    AnswerEvaluation(
                        name=persona.name,
                        evaluation=verdict,
                        reasoning=reasoning,
                        epoch=epoch,
                        round=round_,
                    )
                )
                if out is not None:
                    refinement_request = out
            if refinement_request is None:
                break  # unanimous
            refiner_name, refine_msg = refinement_request
            refiner = self._persona(refiner_name)
            with _phase_span("refine", round_):
                rres = await self._call_persona(
                    refiner,
                    refinement_prompt(
                        refine_msg.question, refine_msg.answer, refiner
                    ),
                    required=True,
                    phase="refine",
                )
            fanout = self.on_refinement(
                AnswerRefinement(
                    answer=rres.text,
                    author=refiner.name,
                    epoch=epoch,
                    round=round_,
                )
            )

        final = ConsensusResult(
            answer=self.get_answer(),
            rounds=self.evaluation_count,
            endorsed=self.answer_ready() and not self._forced_termination,
            author=self.answer_author or "",
            feedback=dict(self.feedback),
            transcript=list(self.transcript),
        )
        _M_QUESTIONS.inc()
        _M_ROUNDS.observe(final.rounds)
        (_M_UNANIMOUS if final.endorsed else _M_FORCED).inc()
        log.info("Final answer: %s", final.answer)
        return final

    # REPL-parity surface (reference src/main.rs:442-470) -----------------

    async def ask_question(self, question: str) -> bool:
        """Start answering in the background (reference ``AskQuestion`` send
        + polling loop contract, ``src/main.rs:442-459``)."""
        if self._task is not None and not self._task.done():
            return False
        self._task = asyncio.create_task(self.run(question))
        return True

    async def wait_for_answer(self, poll_interval: float = 0.0) -> str:
        """Await completion (replaces the reference's 500 ms hot-spin poll,
        ``src/main.rs:448-459``, with a real await)."""
        if self._task is None:
            return self.get_answer()
        await self._task
        return self.get_answer()

    # ------------------------------------------------------------------
    # Backend plumbing: grouping, timeout, retries
    # ------------------------------------------------------------------

    def _persona(self, name: str) -> Persona:
        for p in self.panel:
            if p.name == name:
                return p
        raise KeyError(name)

    def _backend_for(self, persona: Persona) -> Backend:
        return self.backends.get(persona.name, self.backend)

    def _model_for(self, persona: Persona, phase: str | None) -> str | None:
        """The model tag one phase call carries: the phase-routing map
        wins (cross-model consensus, PR 18), else the persona's own."""
        pm = self.config.phase_models
        if phase is not None and pm:
            routed = pm.get(phase)
            if routed is not None:
                return routed
        return persona.model

    def _params_for(self, persona: Persona) -> SamplingParams:
        base = self.config.sampling
        if persona.temperature is None:
            return base
        return dataclasses.replace(base, temperature=persona.temperature)

    async def _generate_for_panel(
        self, prompts: list[str], phase: str | None = None
    ) -> list[str]:
        """Batch prompts per backend (heterogeneous panels use several) and
        run the groups concurrently. A failed evaluation degrades to a
        ``NeedsRefinement`` verdict instead of crashing the protocol."""
        groups: dict[int, tuple[Backend, list[int], list[GenerationRequest]]] = {}
        for i, (persona, prompt) in enumerate(zip(self.panel, prompts)):
            backend = self._backend_for(persona)
            key = id(backend)
            if key not in groups:
                groups[key] = (backend, [], [])
            groups[key][1].append(i)
            groups[key][2].append(
                GenerationRequest(
                    prompt=prompt,
                    params=self._params_for(persona),
                    model=self._model_for(persona, phase),
                )
            )

        texts: list[str] = [""] * len(prompts)

        async def _run_group(backend: Backend, idxs: list[int], reqs) -> None:
            try:
                results = await self._with_supervision(
                    lambda: backend.generate_batch(reqs)
                )
            except BackendError as e:
                log.error("Evaluation batch failed: %s", e)
                results = [
                    GenerationResult(text="NeedsRefinement\nBackend failure: " + str(e))
                    for _ in reqs
                ]
            for i, r in zip(idxs, results):
                texts[i] = r.text

        await asyncio.gather(
            *(_run_group(b, idxs, reqs) for b, idxs, reqs in groups.values())
        )
        return texts

    async def _call_persona(
        self,
        persona: Persona,
        prompt: str,
        required: bool,
        phase: str | None = None,
    ) -> GenerationResult:
        backend = self._backend_for(persona)
        req = GenerationRequest(
            prompt=prompt,
            params=self._params_for(persona),
            model=self._model_for(persona, phase),
        )
        try:
            return await self._with_supervision(lambda: backend.generate(req))
        except BackendError:
            if required:
                raise
            return GenerationResult(text="")

    async def _with_supervision(self, thunk):
        """Timeout + bounded retries around a backend call (failure-detection
        subsystem; the reference panics instead, ``src/main.rs:85,97``)."""
        attempts = max(1, self.config.retries + 1)
        last: Exception | None = None
        for attempt in range(attempts):
            try:
                coro = thunk()
                if self.config.call_timeout is not None:
                    return await asyncio.wait_for(coro, self.config.call_timeout)
                return await coro
            except (asyncio.TimeoutError, BackendError, OSError) as e:
                last = e
                log.warning(
                    "Backend call failed (attempt %d/%d): %s", attempt + 1, attempts, e
                )
        raise BackendError(f"backend call failed after {attempts} attempts: {last}")

    def _event(self, kind: str, payload: dict) -> None:
        self.transcript.append(
            TranscriptEvent(
                kind=kind,
                epoch=self.epoch,
                round=self.evaluation_count,
                payload=payload,
            )
        )
