"""Multi-round debate / Tree-of-Thoughts with iterative re-vote.

BASELINE.md config[4]: "Multi-round debate / ToT N=32 with iterative
re-vote". This generalizes the reference's single-answer refine loop
(one random dissenter rewrites the one shared answer,
``src/main.rs:268-286``) to N parallel candidates that *see each other's
answers* and revise — all N revisions per round are ONE batched device
program, and the vote after every round is the standard
self-consistency reducer (:mod:`llm_consensus_tpu.consensus.voting`).

Protocol per round r:
  1. every candidate i revises its answer given the question, its own
     previous answer, and a digest of the other candidates' answers
     (debate conditioning);
  2. answers are canonicalized and voted; the tally is recorded;
  3. early exit when a super-majority (``quorum`` fraction) agrees —
     otherwise continue to the round cap (bounded like the reference's
     5-round cap, ``src/main.rs:299-300``, but configurable).
"""

from __future__ import annotations

from dataclasses import dataclass, field

from llm_consensus_tpu.consensus.voting import (
    VoteResult,
    canonicalize,
    logit_pool,
    majority_vote,
    rescore_vote,
)


@dataclass(frozen=True)
class DebateConfig:
    n_candidates: int = 8
    max_rounds: int = 3
    temperature: float = 0.8
    # Stop once the leading answer holds at least this fraction of votes.
    quorum: float = 0.75
    max_new_tokens: int | None = None
    # How many peer answers each candidate sees per round (digest size;
    # keeps prompts bounded at large N).
    peer_sample: int = 4
    seed: int = 0
    # Per-round vote: "majority" (count), "logit_pool" (pool by each
    # candidate's own sampling logprob), or "rescore" (teacher-forced
    # re-scoring of every answer under the engine — judge-model
    # reranking; needs ``engine.score_texts``).
    method: str = "majority"
    # Prompt templates. None = the built-in generic CoT templates.
    # Narrow/SFT models answer reliably only in their trained format —
    # pass the format they know. ``initial_template`` must contain
    # ``{q}``; ``revise_template`` may use ``{i}``/``{q}``/``{own}``/
    # ``{peers}`` (all optional: a template that reuses only ``{q}``
    # turns revision rounds into fresh re-samples, which still
    # re-votes).
    initial_template: str | None = None
    revise_template: str | None = None


@dataclass
class DebateRound:
    answers: list[str]
    vote: VoteResult


@dataclass
class DebateResult:
    answer: str
    vote: VoteResult
    rounds: list[DebateRound] = field(default_factory=list)
    total_tokens: int = 0

    @property
    def n_rounds(self) -> int:
        return len(self.rounds)


_INITIAL = (
    "Answer the question. Think step by step, then state your final "
    "answer on the last line.\n\nQuestion: {q}\nAnswer:"
)
_REVISE = (
    "You are candidate {i} in a panel debate answering a question.\n"
    "Question: {q}\n\nYour current answer:\n{own}\n\n"
    "Other candidates' answers:\n{peers}\n\n"
    "Reconsider. If another answer is better reasoned, adopt it; "
    "otherwise defend yours. State your final answer on the last "
    "line.\nRevised answer:"
)


def _quorum_reached(answers, key_fn, quorum: float) -> bool:
    """Quorum measures HEADCOUNT agreement — never a weighted/pooled
    tally: pooled probability mass is near-one-hot whenever sequence
    logprobs differ by a few nats, and a single heavy panel member
    must not end a debate unilaterally while most candidates/models
    still disagree."""
    heads = majority_vote(answers, key_fn)
    lead = max(heads.tally.values()) / max(sum(heads.tally.values()), 1e-9)
    return lead >= quorum


def _revise_prompts(
    revise_t: str,
    question: str,
    answers: list[str],
    base: int,
    n: int,
    peer_sample: int,
) -> list[str]:
    """Build n revision prompts for candidates [base, base+n) over the
    pooled ``answers`` (base=0, n=len(answers) for single-engine
    debate; per-member blocks for panel debate)."""
    return [
        revise_t.format(
            i=base + i,
            q=question,
            own=answers[base + i],
            peers=_peer_digest(answers, base + i, peer_sample),
        )
        for i in range(n)
    ]


def _checked_templates(
    cfg: DebateConfig, question: str
) -> tuple[str, str]:
    """Resolve + dry-run both templates (fail-fast invariant): a typo'd
    placeholder or a literal brace in a custom format must not surface
    only at round-2 prompt build, after an N-candidate device round has
    already been spent — and an initial template that drops {q} would
    debate a question-free prompt."""
    initial_t = cfg.initial_template or _INITIAL
    revise_t = cfg.revise_template or _REVISE
    try:
        probe = initial_t.format(q=question)
        revise_t.format(i=0, q=question, own="x", peers="y")
    except (KeyError, IndexError, ValueError) as e:
        raise ValueError(
            f"bad debate template (unknown placeholder or literal "
            f"brace? escape literals as {{{{...}}}}): {e!r}"
        ) from e
    if question not in probe:
        raise ValueError(
            "initial_template must embed the question via {q}"
        )
    return initial_t, revise_t


def run_debate(
    engine,
    question: str,
    config: DebateConfig | None = None,
    key_fn=canonicalize,
) -> DebateResult:
    """Drive one question through multi-round debate on an engine.

    ``engine`` is an :class:`~llm_consensus_tpu.engine.engine.InferenceEngine`
    (or anything with its ``generate_texts`` surface). Each round is one
    batched call — N is the data-parallel candidate axis on the mesh.
    """
    cfg = config or DebateConfig()
    # Fail before any generation: a typo'd method or an incompatible
    # engine must not burn an N-candidate TPU round first.
    if cfg.method not in ("majority", "logit_pool", "rescore"):
        raise ValueError(f"unknown debate vote method {cfg.method!r}")
    if cfg.method == "rescore" and not hasattr(engine, "score_texts"):
        raise ValueError(
            "method='rescore' needs an engine with score_texts "
            "(sharded engines included: completions shard over data)"
        )
    if cfg.max_rounds < 1:
        raise ValueError(f"max_rounds must be >= 1, got {cfg.max_rounds}")
    n = cfg.n_candidates
    rounds: list[DebateRound] = []
    total_tokens = 0
    initial_t, revise_t = _checked_templates(cfg, question)

    prompts = [initial_t.format(q=question)] * n
    answers: list[str] = []
    for r in range(cfg.max_rounds):
        results = engine.generate_texts(
            prompts,
            temperatures=[cfg.temperature] * n,
            seed=cfg.seed + r,
            max_new_tokens=cfg.max_new_tokens,
        )
        answers = [res.text for res in results]
        total_tokens += sum(res.num_tokens for res in results)
        if cfg.method == "majority":
            vote = majority_vote(answers, key_fn)
        elif cfg.method == "logit_pool":
            vote = logit_pool(
                answers, [res.logprob for res in results], key_fn
            )
        else:  # "rescore" (validated above)
            vote = rescore_vote(
                engine, initial_t.format(q=question), answers, key_fn
            )
        rounds.append(DebateRound(answers=answers, vote=vote))
        if _quorum_reached(answers, key_fn, cfg.quorum):
            break
        if r + 1 < cfg.max_rounds:
            prompts = _revise_prompts(
                revise_t, question, answers, 0, n, cfg.peer_sample
            )

    final = rounds[-1].vote
    return DebateResult(
        answer=final.text,
        vote=final,
        rounds=rounds,
        total_tokens=total_tokens,
    )


def run_panel_debate(
    engines: dict[str, tuple[object, float]],
    question: str,
    config: DebateConfig | None = None,
    key_fn=canonicalize,
) -> DebateResult:
    """Multi-MODEL debate: a heterogeneous panel (BASELINE config[3])
    debating through iterative re-vote rounds (config[4]).

    ``engines``: member name -> (engine, vote weight) — the same
    signature as :func:`~llm_consensus_tpu.consensus.voting.
    heterogeneous_panel_vote`. Each round, every member samples
    ``n_candidates`` with its OWN engine (one batched program per
    member; members fan out concurrently, and seeds are per-(round,
    member) so results are order-independent), and every candidate
    votes with its member's weight. Revision prompts draw peers from
    the POOLED answer set, so a strong member's answers reach weaker
    members' contexts — cross-model debate on local engines, which the
    reference's single-shared-answer refine loop
    (``src/main.rs:268-286``) cannot express.

    Votes are weighted-majority only: sequence logprobs are not
    calibrated ACROSS different models, so ``logit_pool``/``rescore``
    would let one member's logit scale dominate the pool.
    """
    cfg = config or DebateConfig()
    if cfg.method != "majority":
        raise ValueError(
            "panel debate votes by weighted majority; logprob-based "
            "methods are not calibrated across different models"
        )
    from llm_consensus_tpu.consensus.voting import (
        _panel_fanout,
        weighted_vote,
    )

    ordered = sorted(engines.items())
    if not ordered:
        raise ValueError("panel debate needs at least one engine")
    if cfg.max_rounds < 1:
        raise ValueError(f"max_rounds must be >= 1, got {cfg.max_rounds}")
    n = cfg.n_candidates
    initial_t, revise_t = _checked_templates(cfg, question)

    member_prompts = {
        name: [initial_t.format(q=question)] * n for name, _ in ordered
    }
    rounds: list[DebateRound] = []
    total_tokens = 0
    for r in range(cfg.max_rounds):
        outs = _panel_fanout(
            ordered,
            member_prompts.__getitem__,
            cfg.temperature,
            lambda mi: cfg.seed + r * len(ordered) + mi,
            cfg.max_new_tokens,
        )
        answers: list[str] = []
        weights: list[float] = []
        for _name, weight, res in outs:  # sorted-name order preserved
            answers.extend(x.text for x in res)
            weights.extend([weight] * len(res))
            total_tokens += sum(x.num_tokens for x in res)
        vote = weighted_vote(answers, weights, key_fn)
        rounds.append(DebateRound(answers=answers, vote=vote))
        if _quorum_reached(answers, key_fn, cfg.quorum):
            break
        if r + 1 < cfg.max_rounds:
            for bi, (name, _) in enumerate(ordered):
                member_prompts[name] = _revise_prompts(
                    revise_t, question, answers, bi * n, n, cfg.peer_sample
                )

    final = rounds[-1].vote
    return DebateResult(
        answer=final.text,
        vote=final,
        rounds=rounds,
        total_tokens=total_tokens,
    )


def _peer_digest(answers: list[str], own_idx: int, k: int) -> str:
    """Deterministic round-robin sample of k peers, skipping self."""
    peers = [a for j, a in enumerate(answers) if j != own_idx]
    # Rotate by own index so different candidates see different subsets.
    if peers:
        off = own_idx % len(peers)
        peers = (peers[off:] + peers[:off])[:k]
    return "\n---\n".join(
        f"[{j + 1}] {p}" for j, p in enumerate(peers)
    )
