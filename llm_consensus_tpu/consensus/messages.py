"""Consensus wire protocol: typed messages exchanged between the coordinator
and panel members.

Parity target: the reference's actix message types (``src/main.rs:7-69``) —
``Feedback``, ``Register``, ``AskQuestion``, ``AnswerQuestion``,
``AnswerReadinessRequest``, ``GetAnswer``, ``EvaluateAnswer``,
``AnswerEvaluation``, ``RefineAnswer``, ``AnswerRefinement``, ``Reset``.

Differences from the reference, by design (SURVEY.md §5 quirk #6):
every in-flight message carries an ``epoch`` (one per question) and a
``round`` (one per evaluation fan-out), so a stale evaluation from round k
arriving after ``feedback.clear()`` for round k+1 is *dropped* instead of
corrupting the tally. The reference has no such tags and exhibits that race.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field


class Feedback(enum.Enum):
    """Panel verdict on an answer (reference ``src/main.rs:8-12``)."""

    GOOD = "Good"
    NEEDS_REFINEMENT = "NeedsRefinement"


@dataclass(frozen=True)
class AskQuestion:
    """Request an answer to a question (reference ``src/main.rs:22-25``)."""

    question: str
    epoch: int = 0


@dataclass(frozen=True)
class AnswerQuestion:
    """A proposer's answer (reference ``src/main.rs:27-30``)."""

    answer: str
    author: str
    epoch: int = 0


@dataclass(frozen=True)
class EvaluateAnswer:
    """Fan-out request asking one panelist to judge the current answer
    (reference ``src/main.rs:41-46``)."""

    question: str
    answer: str
    epoch: int = 0
    round: int = 0


@dataclass(frozen=True)
class AnswerEvaluation:
    """A panelist's verdict (reference ``src/main.rs:48-54``)."""

    name: str
    evaluation: Feedback
    reasoning: str = ""
    epoch: int = 0
    round: int = 0


@dataclass(frozen=True)
class RefineAnswer:
    """Request that a dissenting panelist rewrite the answer
    (reference ``src/main.rs:56-61``)."""

    question: str
    answer: str
    epoch: int = 0
    round: int = 0


@dataclass(frozen=True)
class AnswerRefinement:
    """The refined answer (reference ``src/main.rs:63-65``)."""

    answer: str
    author: str = ""
    epoch: int = 0
    round: int = 0


@dataclass
class TranscriptEvent:
    """One entry of the consensus transcript (observability subsystem; the
    reference only has ``debug!`` log lines, e.g. ``src/main.rs:263,281``)."""

    kind: str
    epoch: int
    round: int
    payload: dict = field(default_factory=dict)
