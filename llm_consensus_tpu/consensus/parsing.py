"""Evaluation-response parsing as pure, unit-testable functions.

Parity target: the reference parses the judge's reply inline in the actor
handler (``src/main.rs:139-153``): split on newlines, drop empty lines, take
the first line with all spaces removed, map ``Good``/``NeedsRefinement``;
anything else logs an error and counts as ``NeedsRefinement`` (SURVEY.md §5
quirk #4). Remaining lines (joined with blank lines) are the reasoning.
"""

from __future__ import annotations

import logging

from llm_consensus_tpu.consensus.messages import Feedback

log = logging.getLogger(__name__)


def parse_evaluation(text: str) -> tuple[Feedback, str]:
    """Parse a judge's raw reply into (verdict, reasoning).

    Mirrors reference ``src/main.rs:139-153``: first non-empty line,
    space-stripped, must be exactly ``Good`` or ``NeedsRefinement``; an
    unrecognized verdict is logged and treated as ``NeedsRefinement``.
    An entirely empty reply is likewise ``NeedsRefinement``.
    """
    lines = [ln for ln in text.split("\n") if ln != ""]
    if not lines:
        log.error("Empty response from EvaluateAnswer")
        return Feedback.NEEDS_REFINEMENT, ""
    verdict_raw = lines[0].replace(" ", "")
    reasoning = "\n\n".join(lines[1:])
    if verdict_raw == "Good":
        return Feedback.GOOD, reasoning
    if verdict_raw == "NeedsRefinement":
        return Feedback.NEEDS_REFINEMENT, reasoning
    log.error("Unexpected response from EvaluateAnswer: %s", text)
    return Feedback.NEEDS_REFINEMENT, reasoning
