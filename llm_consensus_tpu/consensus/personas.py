"""Persona (panel member) definitions.

The reference hard-codes four personas inline in ``main``
(``src/main.rs:359-426``): each has a ``name``, a knowledge ``domain``, and a
ten-bullet ``tuning`` string that conditions its evaluation/refinement
prompts. Here personas are plain data, loadable from JSON/dict config
(fixing the hard-coding noted in SURVEY.md §7 step 4), and may additionally
pin a *model* and *sampling params* so heterogeneous panels (different
weights per persona, BASELINE.md config[3]) are expressible.
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field
from pathlib import Path


@dataclass(frozen=True)
class Persona:
    name: str
    domain: str
    tuning: str
    # TPU-build extensions (absent in the reference):
    model: str | None = None  # model preset name; None = panel default
    weight: float = 1.0  # vote weight for weighted aggregation
    temperature: float | None = None  # sampling override

    @staticmethod
    def from_dict(d: dict) -> "Persona":
        return Persona(
            name=d["name"],
            domain=d["domain"],
            tuning=d["tuning"],
            model=d.get("model"),
            weight=float(d.get("weight", 1.0)),
            temperature=d.get("temperature"),
        )

    def to_dict(self) -> dict:
        return {
            "name": self.name,
            "domain": self.domain,
            "tuning": self.tuning,
            "model": self.model,
            "weight": self.weight,
            "temperature": self.temperature,
        }


def load_panel(path: str | Path) -> list[Persona]:
    """Load a panel from a JSON file: a list of persona dicts."""
    data = json.loads(Path(path).read_text())
    return [Persona.from_dict(d) for d in data]


def save_panel(panel: list[Persona], path: str | Path) -> None:
    Path(path).write_text(json.dumps([p.to_dict() for p in panel], indent=2))


# The default panel ships the same four domain personas as the reference
# (``src/main.rs:359-426``): names, domains, and the ten tuning bullets per
# persona match the reference's inline literals so a switching user gets the
# same panel behavior out of the box.

_HIGH_SOCIETY_TUNING = """
* Social norms, values, and beliefs
* Historical context and events
* Cultural diversity and traditions
* Social structures and institutions (e.g., family, education, government)
* Impact on human behavior and interactions
* Ethical and moral considerations
* Current events and social issues
* Demographics and population trends
* Communication styles and languages
* Arts, literature, and folklore as reflections of society"""

_TECHNICIAN_TUNING = """
* Accuracy and precision of information
* Specific measurements, quantities, and units
* Technical specifications and standards
* Detailed procedures and processes
* Scientific principles and theories
* Mathematical formulas and equations
* Logical reasoning and problem-solving
* Causality and cause-and-effect relationships
* Step-by-step explanations and instructions
* Attention to detail and completeness"""

_ART_BOY_TUNING = """
* Creative expression and generation across various mediums (visual, auditory, written, etc.)
* Tools and techniques for artistic creation (digital and traditional)
* Exploration of emotions, ideas, and concepts through art
* Imagination, innovation, and originality
* Aesthetic qualities and principles (e.g., composition, color, form)
* Art history, movements, and styles
* Cultural and social influences on art
* Potential for visualizing data or creating simulations for artistic purposes
* Interactive art and installations
* The role of art in communication and storytelling"""

_PROGRAMMING_NERD_TUNING = """
* Algorithms and data structures
* Programming languages and paradigms
* Software engineering principles
* Computer architecture and hardware
* Networking and distributed systems
* Artificial intelligence and machine learning
* Cybersecurity and data privacy
* Computational theory and complexity
* Databases and data management
* Operating systems and system programming"""


def default_panel() -> list[Persona]:
    """The reference's four-persona panel (``src/main.rs:359-426``)."""
    return [
        Persona("High Society", "Society and Culture", _HIGH_SOCIETY_TUNING),
        Persona("The Technician", "Technical Detail", _TECHNICIAN_TUNING),
        Persona("Art Boy", "Art and Imagination", _ART_BOY_TUNING),
        Persona("Programming Nerd", "Computer Science", _PROGRAMMING_NERD_TUNING),
    ]
