"""Prompt builders for the consensus protocol.

Parity targets in the reference:
- answer prompt: ``src/main.rs:95``
- evaluation rubric with two few-shot examples: ``src/main.rs:111-136``
- refinement prompt: ``src/main.rs:166-175``

Both rubric-bearing prompts strip every double-quote character before being
sent (``.replace('\"', '')`` at ``src/main.rs:136,175``) — preserved here as
documented behavior so downstream eval parsing sees the same distribution.
"""

from __future__ import annotations

from llm_consensus_tpu.consensus.personas import Persona


def answer_prompt(question: str) -> str:
    """Initial-answer prompt (reference ``src/main.rs:95``)."""
    return (
        "Please answer the following question without referring to yourself "
        f"as a language model:\n\n{question}"
    )


def evaluation_prompt(question: str, answer: str, persona: Persona) -> str:
    """Panel-evaluation rubric (reference ``src/main.rs:111-136``).

    Instructs the judge to emit exactly ``Good`` or ``NeedsRefinement`` on the
    first line and reasoning on following lines; off-domain judges must answer
    ``Good`` (the reference's abstention-maps-to-approve rule,
    ``src/main.rs:122``).
    """
    prompt = f"""
---
Question: {question}
---
Answer: {answer}
---
Your Instructions:
You are part of a team of LLMs that were given the above question to answer by consensus. The first model chosen answered with the answer above. You need to evaluate this answer based on your knowledge domain of {persona.domain}. The only answers you may provide are Good and NeedsRefinement.

Consider how the answer might indirectly or tangentially relate to the domain. A direct connection is not required. Focus on how the answer could enable, inspire, or be used in activities related to the domain. Specifically, you should consider aspects like:{persona.tuning}

The most important part of choosing your answer is whether the question is related to your domain at all. If it is not, then you should answer exactly Good since you are not qualified to evaluate the answer. Otherwise, if you think this was a good answer, respond with exactly Good. If you think this was a bad answer, respond with exactly NeedsRefinement. Additionally, you must also provide reasoning for why you think this answer is Good or NeedsRefinement answer by putting that reasoning on a new line.
---
Examples:

Question: What's a good beginner programming language?
Answer: Python
Your domain: art and imagination
Evaluation: Good
Reasoning: This isn't related to your domain.

Question: How can I make my software easier to update?
Answer: Decoupling
Your domain: technical rigor
Evaluation: NeedsRefinement
Reasoning: Decoupling and high cohesion are only one aspect of maintainable software, and the answer doesn't go into enough detail."""
    return prompt.replace('"', "")


def refinement_prompt(question: str, answer: str, persona: Persona) -> str:
    """Refinement prompt (reference ``src/main.rs:166-175``)."""
    prompt = f"""
---
Question: {question}
---
Answer: {answer}
---
Your Instructions:
A user asked this question, and they received the specified answer. When asked to evaluate this answer, you said it needed refinement. Please refine the answer as necessary for your knowledge domain, {persona.domain}.

Specifically, keep the following things in mind while refining the answer. They do not need to be included, but they should influence your refinement:{persona.tuning}"""
    return prompt.replace('"', "")
