"""Answer aggregation: majority vote, weighted vote, logit pooling.

The reference's only aggregation rule is *unanimity* — every panelist's
feedback must be ``Good`` (``src/main.rs:316-325``), with forced approval
at the round cap (``:308-311``). Per SURVEY.md §7(c) and BASELINE.json,
the rebuild generalizes this to N-way self-consistency:

- :func:`majority_vote` / :func:`weighted_vote` — host-side aggregation
  over canonicalized answers (heterogeneous panels use persona weights).
- :func:`logit_pool` — pool candidates by total probability mass
  (sum of per-candidate sequence probabilities per distinct answer).
- :func:`device_majority_vote` — the on-device reducer from the north
  star: candidates live on the ``data`` mesh axis; the tally is a one-hot
  ``psum`` over that axis + argmax, so the vote rides ICI instead of a
  host gather.
- :func:`self_consistency` — end-to-end: one batched N-way sample on an
  :class:`InferenceEngine`, canonicalize, vote.
"""

from __future__ import annotations

import re
from collections import defaultdict
from dataclasses import dataclass
from functools import lru_cache, partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh
from jax.sharding import PartitionSpec as P

from llm_consensus_tpu.parallel.compat import shard_map

# ---------------------------------------------------------------------------
# Canonicalization
# ---------------------------------------------------------------------------

_NUM_RE = re.compile(r"-?\$?\d[\d,]*(?:\.\d+)?")


def extract_final_number(text: str) -> str | None:
    """Extract a final numeric answer (GSM8K-style EM key).

    Honors an explicit ``#### <answer>`` marker when present, else takes
    the last number in the text. Commas/dollar signs are stripped;
    ``42.0`` canonicalizes to ``42``.
    """
    marker = text.rsplit("####", 1)
    hay = marker[1] if len(marker) == 2 else text
    matches = _NUM_RE.findall(hay)
    if not matches:
        return None
    raw = matches[-1].replace(",", "").replace("$", "")
    try:
        val = float(raw)
    except ValueError:
        return None
    return str(int(val)) if val == int(val) else str(val)


def canonicalize(text: str) -> str:
    """Default answer key: final number when present, else normalized text."""
    num = extract_final_number(text)
    if num is not None:
        return num
    return " ".join(text.strip().lower().split())


# ---------------------------------------------------------------------------
# Host-side voting
# ---------------------------------------------------------------------------


@dataclass
class VoteResult:
    winner: str  # canonical key of the winning answer
    text: str  # a representative raw answer carrying the winning key
    tally: dict[str, float]
    n_candidates: int


def _vote(
    answers: list[str],
    scores: list[float],
    key_fn,
) -> VoteResult:
    if not answers:
        raise ValueError("no answers to vote over")
    tally: dict[str, float] = defaultdict(float)
    rep: dict[str, str] = {}
    for ans, sc in zip(answers, scores):
        k = key_fn(ans)
        tally[k] += sc
        rep.setdefault(k, ans)
    winner = max(tally.items(), key=lambda kv: kv[1])[0]
    return VoteResult(
        winner=winner,
        text=rep[winner],
        tally=dict(tally),
        n_candidates=len(answers),
    )


def majority_vote(answers: list[str], key_fn=canonicalize) -> VoteResult:
    """Uniform one-candidate-one-vote (self-consistency, Wang et al.)."""
    return _vote(answers, [1.0] * len(answers), key_fn)


def weighted_vote(
    answers: list[str], weights: list[float], key_fn=canonicalize
) -> VoteResult:
    """Per-candidate weights — heterogeneous panels vote with persona
    weights (BASELINE.md config[3])."""
    if len(weights) != len(answers):
        raise ValueError("weights and answers must align")
    return _vote(answers, list(weights), key_fn)


def logit_pool(
    answers: list[str], logprobs: list[float], key_fn=canonicalize
) -> VoteResult:
    """Pool by probability mass: each candidate contributes
    ``exp(logprob)`` (normalized over the batch for stability)."""
    if len(logprobs) != len(answers):
        raise ValueError("logprobs and answers must align")
    lp = np.asarray(logprobs, np.float64)
    w = np.exp(lp - lp.max())  # softmax-style stabilization
    return _vote(answers, list(w / w.sum()), key_fn)


def rescore_vote(
    engine,
    prompt: str,
    answers: list[str],
    key_fn=canonicalize,
    normalize: bool = True,
) -> VoteResult:
    """Logit-pool candidates under a JUDGE model's own scores.

    The candidates can come from anywhere — other panel models, debate
    rounds, humans; ``engine.score_texts`` (teacher-forced, one chunk
    forward) assigns each its log-probability given ``prompt``, and the
    pool weights by that mass. This is cross-model reranking: the
    generalization of logit pooling to candidates the judge did not
    sample itself. ``normalize`` length-normalizes so verbose answers
    aren't penalized linearly.
    """
    # Scorability is a TOKEN property, not a string one: an answer that
    # a tokenizer encodes to zero ids (possible with HF tokenizers on
    # e.g. control-char-only text) cannot be teacher-forced any more
    # than "" can. Both pool with ~zero mass instead of erroring.
    tok = getattr(engine, "tokenizer", None)

    def _scorable(a: str) -> bool:
        if not a:
            return False
        if tok is None:
            return True
        return len(tok.encode(a, add_bos=False)) > 0

    scorable = [_scorable(a) for a in answers]
    picked = [a for a, ok in zip(answers, scorable) if ok]
    scored = (
        engine.score_texts(prompt, picked, normalize=normalize)
        if picked
        else []
    )
    it = iter(scored)
    scores = [next(it) if ok else -1e30 for ok in scorable]
    return logit_pool(answers, scores, key_fn)


# ---------------------------------------------------------------------------
# On-device reducer (north-star: all-gather/psum + argmax over candidates)
# ---------------------------------------------------------------------------


# jit cache keys on function identity — a fresh shard_map closure per
# vote would recompile every call. One jitted reducer per
# (mesh, n_classes, axis_name); repeat votes on the same mesh hit it.
# lru_cache bounds retention: a long-lived process churning through
# distinct meshes must not pin every mesh + executable forever.
@lru_cache(maxsize=16)
def _vote_reducer(mesh: Mesh, n_classes: int, axis_name: str):
    def tally(ids, w):
        onehot = jax.nn.one_hot(ids, n_classes, dtype=jnp.float32)
        local = jnp.sum(onehot * w[:, None], axis=0)
        hist = jax.lax.psum(local, axis_name)
        return jnp.argmax(hist).astype(jnp.int32), hist

    spec = P(axis_name)
    return jax.jit(
        shard_map(
            tally,
            mesh=mesh,
            in_specs=(spec, spec),
            out_specs=(P(), P()),
        )
    )


def device_majority_vote(
    candidate_ids: jnp.ndarray,
    n_classes: int,
    mesh: Mesh,
    weights: jnp.ndarray | None = None,
    axis_name: str = "data",
) -> tuple[int, np.ndarray]:
    """Tally candidate class-ids across the ``data`` mesh axis on device.

    candidate_ids: [N] int32, sharded over ``axis_name`` (the candidate
    fan-out axis). The tally is a one-hot reduction ``psum``-ed over the
    axis; argmax of the pooled histogram picks the winner. Ties break
    toward the lower id (argmax convention).

    Returns (winner_id, histogram) on host.
    """
    if weights is None:
        weights = jnp.ones_like(candidate_ids, jnp.float32)
    winner, hist = _vote_reducer(mesh, n_classes, axis_name)(
        candidate_ids, weights
    )
    return int(winner), np.asarray(hist)


# ---------------------------------------------------------------------------
# End-to-end self-consistency over an engine
# ---------------------------------------------------------------------------


@dataclass
class PanelVoteResult:
    vote: VoteResult
    per_model: dict[str, list[str]]
    total_tokens: int


def _panel_fanout(
    ordered: list[tuple[str, tuple[object, float]]],
    prompts_for,
    temperature: float,
    seed_for,
    max_new_tokens: int | None,
):
    """Concurrent per-member sampling shared by
    :func:`heterogeneous_panel_vote` and
    :func:`~llm_consensus_tpu.consensus.debate.run_panel_debate`.

    One thread per engine: on a single shared chip the calls still
    serialize on the device queue, but engines on disjoint meshes/hosts
    overlap fully, and even single-chip panels overlap each model's
    host-side tokenize/detokenize work. ``seed_for(member_index)`` gives
    each member its own seed, so results are identical to the
    sequential path regardless of completion order. Returns
    ``[(name, weight, results)]`` in the input (sorted-name) order.
    """
    from concurrent.futures import ThreadPoolExecutor

    def _one(arg):
        mi, (name, (engine, weight)) = arg
        prompts = prompts_for(name)
        results = engine.generate_texts(
            prompts,
            temperatures=[temperature] * len(prompts),
            seed=seed_for(mi),
            max_new_tokens=max_new_tokens,
        )
        return name, weight, results

    with ThreadPoolExecutor(max_workers=max(1, len(ordered))) as ex:
        return list(ex.map(_one, enumerate(ordered)))


def heterogeneous_panel_vote(
    engines: dict[str, tuple[object, float]],
    prompt: str,
    n_per_model: int = 4,
    temperature: float = 0.7,
    seed: int = 0,
    max_new_tokens: int | None = None,
    key_fn=canonicalize,
) -> PanelVoteResult:
    """Weighted vote across DIFFERENT models (BASELINE.md config[3]).

    ``engines``: model name -> (engine, vote weight). Each model samples
    ``n_per_model`` candidates (one batched program per model — models
    have different weights/meshes so they cannot share a batch); every
    candidate votes with its model's weight.

    The per-model calls run CONCURRENTLY via :func:`_panel_fanout`
    (one thread per engine; per-model seeds = seed + model index in
    sorted-name order) — the deployment config[3] describes engines on
    disjoint meshes/hosts, which overlap fully.
    """
    ordered = sorted(engines.items())
    outs = _panel_fanout(
        ordered,
        lambda _name: [prompt] * n_per_model,
        temperature,
        lambda mi: seed + mi,
        max_new_tokens,
    )

    answers: list[str] = []
    weights: list[float] = []
    per_model: dict[str, list[str]] = {}
    total_tokens = 0
    for name, weight, results in outs:  # sorted-name order preserved
        texts = [r.text for r in results]
        per_model[name] = texts
        answers.extend(texts)
        weights.extend([weight] * len(texts))
        total_tokens += sum(r.num_tokens for r in results)
    vote = weighted_vote(answers, weights, key_fn)
    return PanelVoteResult(
        vote=vote, per_model=per_model, total_tokens=total_tokens
    )


def _device_vote(engine, texts: list[str], key_fn) -> VoteResult:
    """North-star reducer end-to-end: canonicalize on host, tally on the
    engine's mesh (one-hot psum over the ``data`` axis + argmax — the
    vote rides ICI instead of a host gather). Requires a mesh-wired
    engine; candidates pad to the data-axis size with zero-weight votes.
    """
    mesh = engine.mesh
    # First-seen class order, so argmax's lowest-index tie-break picks
    # the same winner as the host vote's insertion-ordered max().
    keys = [key_fn(t) for t in texts]
    classes = list(dict.fromkeys(keys))
    ids = [classes.index(k) for k in keys]
    dp = int(mesh.shape.get("data", 1))
    pad = (-len(ids)) % dp
    weights = jnp.asarray([1.0] * len(ids) + [0.0] * pad, jnp.float32)
    ids_arr = jnp.asarray(ids + [0] * pad, jnp.int32)
    winner_id, hist = device_majority_vote(
        ids_arr, len(classes), mesh, weights=weights
    )
    winner = classes[winner_id]
    rep = next(t for t, k in zip(texts, keys) if k == winner)
    tally = {c: float(hist[i]) for i, c in enumerate(classes)}
    return VoteResult(
        winner=winner, text=rep, tally=tally, n_candidates=len(texts)
    )


@dataclass
class SelfConsistencyResult:
    vote: VoteResult
    candidates: list[str]
    logprobs: list[float]
    total_tokens: int


def self_consistency(
    engine,
    prompt: str,
    n: int,
    temperature: float = 0.7,
    seed: int = 0,
    max_new_tokens: int | None = None,
    method: str = "majority",
    key_fn=canonicalize,
) -> SelfConsistencyResult:
    """N-way self-consistency: ONE batched sample of n candidates on the
    engine (the candidate axis is the mesh ``data`` axis when sharded),
    then vote. ``method``: majority | logit_pool | device_majority (the
    on-device psum+argmax reducer; needs a mesh-wired engine).
    """
    if method not in ("majority", "logit_pool", "device_majority"):
        raise ValueError(f"unknown aggregation method {method!r}")
    if method == "device_majority" and getattr(engine, "mesh", None) is None:
        # Fail before the expensive N-way generation, not after.
        raise ValueError("device_majority needs a mesh-wired engine")
    results = engine.generate_texts(
        [prompt] * n,
        temperatures=[temperature] * n,
        seed=seed,
        max_new_tokens=max_new_tokens,
    )
    texts = [r.text for r in results]
    lps = [r.logprob for r in results]
    if method == "majority":
        vote = majority_vote(texts, key_fn)
    elif method == "logit_pool":
        vote = logit_pool(texts, lps, key_fn)
    else:
        vote = _device_vote(engine, texts, key_fn)
    return SelfConsistencyResult(
        vote=vote,
        candidates=texts,
        logprobs=lps,
        total_tokens=sum(r.num_tokens for r in results),
    )
