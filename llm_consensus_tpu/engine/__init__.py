"""Inference engine: tokenizer, sampler, batched generation loop.

This package is the TPU-native replacement for the reference's entire
"compute layer" — one remote Gemini call per protocol step
(``src/main.rs:82-86``). Here a whole panel fan-out or N-way
self-consistency batch is ONE compiled device program: prefill + a
``lax.scan`` decode loop over static shapes.
"""

from llm_consensus_tpu.engine.engine import (
    EngineConfig,
    InferenceEngine,
    plan_memory,
)
from llm_consensus_tpu.engine.generate import (
    GenerateOutput,
    decode_steps,
    generate,
    generate_from_prefix,
    score_completions,
)
from llm_consensus_tpu.engine.prefix_cache import PrefixCache
from llm_consensus_tpu.engine.sampler import (
    SamplerConfig,
    sample_token,
    sample_token_per_request,
)
from llm_consensus_tpu.engine.speculative import (
    SpecOutput,
    leviathan_accept,
    speculative_generate,
)
from llm_consensus_tpu.engine.tokenizer import (
    ByteTokenizer,
    Tokenizer,
    load_tokenizer,
)

__all__ = [
    "ByteTokenizer",
    "EngineConfig",
    "GenerateOutput",
    "InferenceEngine",
    "PrefixCache",
    "SamplerConfig",
    "SpecOutput",
    "Tokenizer",
    "decode_steps",
    "generate",
    "generate_from_prefix",
    "score_completions",
    "leviathan_accept",
    "load_tokenizer",
    "sample_token",
    "sample_token_per_request",
    "plan_memory",
    "speculative_generate",
]
