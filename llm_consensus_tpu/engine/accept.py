"""Speculative-decoding acceptance rules — the ONE shared implementation.

Both speculative consumers verify a draft against the target's logits
for a whole ``k_spec + 1``-token chunk at once:

- :func:`llm_consensus_tpu.engine.speculative.speculative_generate`,
  the standalone dense-cache loop (the parity oracle), and
- the continuous batcher's paged verify program (PR 9,
  :mod:`llm_consensus_tpu.serving.continuous`), where the accept /
  rollback decision runs ON DEVICE inside the dispatched program.

This module holds the accept math and nothing else — no model code, no
generation loop — so the batcher can import it without dragging in the
standalone ``speculative_generate`` while the two implementations stay
pinned to the same decisions (tests/test_serve_speculative.py).

Two rules, per row:

- **Greedy** (temperature <= 0): accept draft tokens while they equal
  the target argmax; the correction token is the argmax at the first
  mismatch, the BONUS token the argmax at position k on full
  acceptance. Output is byte-identical to plain greedy decode for ANY
  draft — the draft only affects speed.
- **Sampled**: Leviathan et al. acceptance via :func:`leviathan_accept`
  with the draft's distribution q. The batcher drafts GREEDILY even
  for sampled rows (q = one-hot at the drafted token), which keeps the
  draft program sampler-free and the panel's shared draft streams
  valid across mates with different temperatures/seeds; the rule stays
  exact — accept with prob p(d), else resample from the residual
  ``norm(max(p - onehot(d), 0))`` = p conditioned on != d, whose
  marginal is exactly p.

Both rules are exact for ANY one-hot draft proposal, which is what
makes cross-model speculation (PR 18) a pure transport concern: a
vocab-remapped draft stream (:mod:`llm_consensus_tpu.serving.
vocab_align`) changes WHICH tokens get proposed — unmapped ids lift to
the target pad and are all but guaranteed a rejection — but never the
distribution of what is emitted. The accept math below needs no
remap awareness and takes none.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

__all__ = ["leviathan_accept", "verify_row", "verify_tokens"]

_EPS = 1e-20


def leviathan_accept(
    p: jnp.ndarray,
    q: jnp.ndarray,
    draft: jnp.ndarray,
    key: jax.Array,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """One Leviathan et al. acceptance decision (pure, testable).

    p: [V] target probs; q: [V] draft probs; draft: scalar token drawn
    from q. Accept with prob min(1, p[d]/q[d]); on rejection the caller
    replaces the token with one drawn from the residual
    ``norm(max(p - q, 0))``. Marginal over (draft, coin, correction) is
    EXACTLY p — verified by Monte Carlo in tests/test_speculative.py.

    Returns (accept bool, correction token int32).
    """
    k_coin, k_corr = jax.random.split(key)
    ratio = p[draft] / jnp.maximum(q[draft], _EPS)
    accept = jax.random.uniform(k_coin) < ratio
    resid = jnp.maximum(p - q, 0.0)
    total = jnp.sum(resid)
    # Identical distributions -> empty residual; rejection then has
    # probability 0, so any valid fallback distribution works.
    resid = jnp.where(total > _EPS, resid / jnp.maximum(total, _EPS), p)
    corr = jax.random.categorical(k_corr, jnp.log(jnp.maximum(resid, _EPS)))
    return accept, corr.astype(jnp.int32)


def verify_row(
    logits: jnp.ndarray,
    drafts: jnp.ndarray,
    temperature: jnp.ndarray,
    keys: jax.Array,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """One row's accept/rollback decision over a verify chunk with a
    GREEDY (deterministic) draft.

    logits: [K+1, V] fp32 target logits — position j conditions on the
    row's committed tokens plus drafts[:j] (the ragged-causal verify
    forward); drafts: [K] int32 greedy draft proposals; temperature:
    scalar (<= 0 = greedy row); keys: [K+1] PRNG keys, one per
    position (key j must be the SAME (seed, output-index) fold the
    plain sampler would burn for that token, so per-request streams
    stay reproducible regardless of speculation).

    Returns (emit [K+1] int32, emit_cnt scalar int32): the accepted
    draft prefix followed by the correction token at position
    ``emit_cnt - 1`` (the correction on a mismatch/rejection, the FREE
    bonus token on full acceptance — Leviathan et al.), pad-free: only
    ``emit[:emit_cnt]`` is meaningful. Position K of the leviathan
    call carries zero draft mass, so its residual is exactly the
    target distribution and ONE vmapped call yields both the K
    acceptance coins and every candidate correction/bonus token —
    the same structure as ``speculative_generate``'s sampled verify.
    """
    k = drafts.shape[0]
    v = logits.shape[-1]
    greedy_t = jnp.argmax(logits, axis=-1).astype(jnp.int32)  # [K+1]
    greedy = temperature <= 0.0
    t_eff = jnp.maximum(temperature, 1e-6)
    p = jax.nn.softmax(logits / t_eff, axis=-1)  # [K+1, V]
    # Greedy draft == a one-hot draft distribution; the bonus slot
    # (position K) carries zero mass so its residual is exactly p.
    q_pad = jnp.concatenate(
        [jax.nn.one_hot(drafts, v, dtype=p.dtype), jnp.zeros((1, v), p.dtype)]
    )
    d_pad = jnp.pad(drafts, (0, 1))  # [K+1]
    coin, corr = jax.vmap(leviathan_accept)(p, q_pad, d_pad, keys)
    match = jnp.where(greedy, drafts == greedy_t[:k], coin[:k])
    acc_mask = jnp.cumprod(match.astype(jnp.int32))  # [K]
    n_acc = jnp.sum(acc_mask)
    fix_of = jnp.where(greedy, greedy_t, corr)  # [K+1] per-position fix
    fix = fix_of[n_acc]
    j = jnp.arange(k + 1)
    emit = jnp.where(
        j < n_acc, d_pad, jnp.where(j == n_acc, fix, jnp.int32(0))
    ).astype(jnp.int32)
    return emit, (n_acc + 1).astype(jnp.int32)


def verify_tokens(
    logits: jnp.ndarray,
    drafts: jnp.ndarray,
    temps: jnp.ndarray,
    topks: jnp.ndarray,
    topps: jnp.ndarray,
    keys: jax.Array,
    *,
    filters_active: bool = False,
    all_greedy: bool = False,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """The continuous batcher's whole-batch accept/rollback decision.

    logits: [B, K+1, V] fp32 RAW target logits from the ragged verify
    forward; drafts: [B, K] greedy draft proposals; temps/topks/topps:
    [B] per-request sampler settings (the batcher's decode-step data);
    keys: [B, K+1] PRNG keys, key (i, j) the SAME (seed, output-index)
    fold the plain sampler would burn for that row's token.

    Per row this reproduces :func:`~llm_consensus_tpu.engine.sampler.
    sample_token_per_request`'s distribution transform — temperature
    scale, then the shared top-k/top-p filter
    (:func:`~llm_consensus_tpu.engine.sampler.filter_scaled_logits`,
    vmapped over the K+1 positions) — and hands the transformed
    distribution to :func:`verify_row`. With a one-hot draft the
    acceptance identity holds for ANY target distribution, so filters
    compose exactly here (unlike the real-draft-distribution case
    :mod:`llm_consensus_tpu.engine.speculative` documents): accept with
    prob p'(d), else resample from p' conditioned on != d, marginal
    exactly p' — the filtered, temperature-scaled target. Greedy rows
    (temperature <= 0) take the argmax-match rule on the same
    transformed logits; the filters keep the argmax, so greedy output
    is byte-identical to the plain sampler's for any draft.

    ``filters_active`` (static) mirrors the batcher's decode-step
    optimization: False skips the full-vocab sorts entirely.
    ``all_greedy`` (static): every row has temperature <= 0 — skip the
    leviathan machinery (softmax p, one-hot q, residual categorical —
    several full-vocab passes whose outputs the greedy branch would
    discard) for the pure argmax-chain rule, bit-identical to the
    general path on greedy rows. The batcher passes both as static jit
    args (two cached traces each).

    Returns (emit [B, K+1] int32, emit_cnt [B] int32) — see
    :func:`verify_row`.
    """
    b, k1, v = logits.shape
    temps = jnp.asarray(temps, jnp.float32)
    safe_t = jnp.where(temps > 0, temps, 1.0)[:, None, None]
    scaled = logits / safe_t
    if filters_active:
        from llm_consensus_tpu.engine.sampler import filter_scaled_logits

        flat = filter_scaled_logits(
            scaled.reshape(b * k1, v),
            jnp.repeat(jnp.asarray(topks, jnp.int32), k1),
            jnp.repeat(jnp.asarray(topps, jnp.float32), k1),
        )
        scaled = flat.reshape(b, k1, v)
    if all_greedy:
        # verify_row's greedy branch, batch-vectorized without the
        # dead leviathan call (the filters keep the argmax, so this is
        # transform-invariant like the general path).
        k = k1 - 1
        greedy_t = jnp.argmax(scaled, axis=-1).astype(jnp.int32)
        match = (drafts == greedy_t[:, :k]).astype(jnp.int32)
        n_acc = jnp.sum(jnp.cumprod(match, axis=1), axis=1)  # [B]
        d_pad = jnp.pad(drafts, ((0, 0), (0, 1)))
        fix = jnp.take_along_axis(greedy_t, n_acc[:, None], axis=1)
        j = jnp.arange(k1)[None, :]
        emit = jnp.where(
            j < n_acc[:, None],
            d_pad,
            jnp.where(j == n_acc[:, None], fix, jnp.int32(0)),
        ).astype(jnp.int32)
        return emit, (n_acc + 1).astype(jnp.int32)
    # Scaling already applied: sampled rows verify at temperature 1 on
    # the transformed logits; greedy rows keep t <= 0 for the argmax
    # rule (argmax is scale- and filter-invariant, so the transform is
    # harmless there).
    t_unit = jnp.where(temps > 0, 1.0, temps)
    return jax.vmap(verify_row)(scaled, drafts, t_unit, keys)
