"""InferenceEngine: text-in/text-out over the compiled generate loop.

The host-side runtime around :func:`llm_consensus_tpu.engine.generate`:
tokenization, right-padding, shape bucketing (so repeat calls hit the jit
cache instead of recompiling), PRNG key management, and detokenization.
This object is what :class:`llm_consensus_tpu.backends.local.LocalBackend`
exposes through the ``Backend`` seam — i.e. it stands exactly where the
reference's ``call_gemini`` stood (``src/main.rs:82-86``), but batched.
"""

from __future__ import annotations

import logging
from dataclasses import dataclass, field

import jax
import jax.numpy as jnp
import numpy as np

from llm_consensus_tpu.engine.generate import GenerateOutput, generate
from llm_consensus_tpu.engine.sampler import SamplerConfig
from llm_consensus_tpu.engine.tokenizer import ByteTokenizer, Tokenizer
from llm_consensus_tpu.models.configs import ModelConfig

log = logging.getLogger(__name__)

# Jitted prefix-prefill entry points (engine.prefix_cache misses). Module
# level so repeat misses at the same shapes hit the jit cache.
from llm_consensus_tpu.models.transformer import (  # noqa: E402
    prefill as _prefill_raw,
    prefill_chunked as _prefill_chunked_raw,
)

_jit_prefill = jax.jit(_prefill_raw, static_argnames=("cfg", "mesh"))
_jit_prefill_chunked = jax.jit(
    _prefill_chunked_raw, static_argnames=("cfg", "chunk")
)

from llm_consensus_tpu.engine.sampler import sample_token as _sample_raw  # noqa: E402

_jit_sample = jax.jit(_sample_raw, static_argnames=("config",))


def _next_bucket(n: int, buckets: tuple[int, ...]) -> int:
    for b in buckets:
        if n <= b:
            return b
    return buckets[-1]


def _kv_cache_bytes(
    cfg: ModelConfig,
    batch: int,
    cache_len: int,
    quant: bool,
    slack: int = 0,
    shared_len: int = 0,
) -> int:
    """KV-cache bytes for a generate call — the ONE copy of the cache
    capacity formula (memory_estimate and plan_memory both call it, so
    a cache-layout change cannot silently drift between them).

    ``shared_len``: prompt-prefix tokens STORED ONCE for the whole
    batch instead of once per row — the paged serving path's CoW page
    sharing (PR 2) dedups an N-fanout's common prompt in memory, so a
    post-PR-2 footprint prediction must count prefix + N*suffix, not
    N*(prefix + suffix). 0 (the default) models the dense per-row
    cache, which still duplicates.
    """
    shared_len = max(0, min(shared_len, cache_len))
    tokens = batch * (cache_len + slack) - (batch - 1) * shared_len
    slots = cfg.n_layers * tokens * cfg.n_kv_heads
    if quant:
        # int8 k+v + one f32 scale each per (slot, head)
        return slots * (2 * cfg.head_dim + 2 * 4)
    return slots * 2 * cfg.head_dim * 2  # bf16 k+v


def _logits_bytes(cfg: ModelConfig, batch: int) -> int:
    return batch * cfg.vocab_size * 4


@dataclass
class EngineConfig:
    max_new_tokens: int = 256
    # Prompt-length buckets (right-padded up; keeps the jit cache small).
    seq_buckets: tuple[int, ...] = (64, 128, 256, 512, 1024, 2048)
    # Batch-size buckets (padded up with dummy rows).
    batch_buckets: tuple[int, ...] = (1, 2, 4, 8, 16, 32, 64)
    sampler: SamplerConfig = field(default_factory=SamplerConfig)
    # Weight-only per-channel quantization at engine init (ops/quant.py):
    # "int8" halves weight HBM traffic on the decode hot loop, "int4"
    # (packed nibbles) halves it again at reduced precision.
    quant: str = "none"
    # int8 KV cache (models/cache.QuantKVCache): halves cache HBM
    # traffic per decode step (the dominant term at large N).
    kv_quant: bool = False
    # > 0: prefill prompts longer than this in fixed-size chunks
    # (models/transformer.prefill_chunked) — bounded activation memory
    # for long contexts. Composes with kv_quant: each chunk's K/V is
    # quantized at scatter time with the same per-(token, head) scale
    # granularity as the one-shot quant prefill, so the written cache is
    # bit-identical; only the chunk's attention reads go through the
    # dequantized slab (first-token logits differ from one-shot by int8
    # rounding only).
    prefill_chunk: int = 0
    # Host-side prefix cache (engine/prefix_cache.py): shared prompt
    # prefixes (few-shot headers, debate transcripts) are prefilled once
    # and their K/V reused across calls. Entry/byte budgets bound HBM.
    prefix_cache_entries: int = 8
    prefix_cache_bytes: int = 1 << 30
    # Decode-steps-per-host-check when a call carries MULTI-token stop
    # sequences: the device can only terminate single-token stops, so
    # the engine decodes in chunks this long and checks texts between
    # chunks — a '\n\n'-style stop ends decoding within one chunk
    # instead of running every row to EOS/max_new_tokens.
    stop_check_chunk: int = 16
    # Single-chip experiment: per-layer weight buffers + python-unrolled
    # layer loop (models.transformer.unstack_blocks). Measured SLOWER
    # than the stacked scan on v5e at bench shapes (the scan pipelines
    # weight streaming; 162 sequential pallas calls don't) — off by
    # default, kept for experimentation on other topologies.
    unroll_layers: bool = False


@dataclass
class EngineResult:
    text: str
    num_tokens: int
    logprob: float
    token_ids: list[int]


class InferenceEngine:
    """Batched local text generation on one model's weights.

    Pass ``mesh`` to run sharded (BASELINE.json north star): params are
    placed per :func:`llm_consensus_tpu.parallel.partitioning.param_pspecs`
    (TP over ``model``, EP over ``expert``, replicated over ``data``) and
    every batch shards its candidate axis over ``data`` — the N-way
    fan-out becomes one GSPMD program whose KV cache lives sharded in HBM.
    """

    def __init__(
        self,
        cfg: ModelConfig,
        params: dict,
        tokenizer: Tokenizer | None = None,
        engine_config: EngineConfig | None = None,
        mesh=None,
        draft: tuple[ModelConfig, dict] | None = None,
        tracer=None,
    ):
        self.cfg = cfg
        self.params = params
        # Optional utils.tracing.Tracer: generate calls record
        # "engine.generate" / "engine.generate_speculative" spans
        # (batch shape + real request count).
        self.tracer = tracer
        self.tokenizer = tokenizer or ByteTokenizer()
        if self.tokenizer.vocab_size > cfg.vocab_size:
            raise ValueError(
                f"tokenizer vocab {self.tokenizer.vocab_size} exceeds model "
                f"vocab {cfg.vocab_size}"
            )
        self.config = engine_config or EngineConfig()
        if self.config.quant in ("int8", "int4"):
            from llm_consensus_tpu.ops.quant import quantize_params

            self.params = quantize_params(
                self.params, bits=8 if self.config.quant == "int8" else 4
            )
        elif self.config.quant != "none":
            raise ValueError(f"unknown quant mode {self.config.quant!r}")
        # Optional draft model for generate_texts_speculative: a
        # (config, params) pair sharing this model's tokenizer/vocab.
        self.draft = draft
        if mesh is None and self.config.unroll_layers:
            from llm_consensus_tpu.models.transformer import unstack_blocks

            self.params = unstack_blocks(self.params)
            if self.draft is not None:
                d_cfg, d_params = self.draft
                self.draft = (d_cfg, unstack_blocks(d_params))
        from llm_consensus_tpu.engine.prefix_cache import PrefixCache

        self.prefix_cache = PrefixCache(
            max_entries=self.config.prefix_cache_entries,
            max_bytes=self.config.prefix_cache_bytes,
        )
        # Lifetime counters; see stats().
        self._calls = {"generate": 0, "speculative": 0, "stream": 0, "score": 0}
        self._tokens_generated = 0
        from llm_consensus_tpu.utils.stops import VisibleIdFilter

        # Empty-id-aware tail window for incremental stop checks (memo
        # persists across generate calls).
        self._vis_filter = VisibleIdFilter(
            self.tokenizer, skip_ids=(self.tokenizer.eos_id,)
        )
        self.mesh = mesh
        self._data_sharding = None
        if mesh is not None:
            from dataclasses import replace

            from jax.sharding import NamedSharding
            from jax.sharding import PartitionSpec as P

            from llm_consensus_tpu.parallel.partitioning import shard_params

            self.params = shard_params(self.params, mesh)
            if self.draft is not None:
                # The draft rides the same mesh as the target (its own
                # tp sharding over `model`; batch over `data` inside
                # speculative_generate).
                d_cfg, d_params = self.draft
                self.draft = (d_cfg, shard_params(d_params, mesh))
            self._data_sharding = NamedSharding(mesh, P("data"))
            # Batch buckets must tile the data axis evenly.
            dp = int(mesh.shape.get("data", 1))
            if dp > 1:
                bb = tuple(
                    b for b in self.config.batch_buckets if b % dp == 0
                ) or (dp,)
                self.config = replace(self.config, batch_buckets=bb)

    # ------------------------------------------------------------------

    def _prepare(
        self, prompts: list[str], add_bos: bool = True, max_cap: int | None = None
    ) -> tuple[np.ndarray, np.ndarray, int]:
        tok = self.tokenizer
        # Left-truncate over-long prompts (keep the question tail); the cap
        # is the model context, not just the largest bucket.
        max_prompt = min(self.config.seq_buckets[-1], self.cfg.max_seq_len - 1)
        if max_cap is not None:
            max_prompt = min(max_prompt, max_cap)
        native = self._native_encode(prompts, max_prompt, add_bos=add_bos)
        if native is not None:
            enc_tokens, enc_lengths = native
        else:
            encoded = [
                tok.encode(p, add_bos=add_bos)[-max_prompt:] for p in prompts
            ]
            enc_lengths = np.array([len(ids) for ids in encoded], np.int32)
            enc_tokens = np.full((len(prompts), max_prompt), tok.pad_id, np.int32)
            for i, ids in enumerate(encoded):
                enc_tokens[i, : len(ids)] = ids
        longest = int(enc_lengths.max())
        s = _next_bucket(longest, self.config.seq_buckets)
        s = min(s, self.cfg.max_seq_len)
        b = _next_bucket(len(prompts), self.config.batch_buckets)
        tokens = np.full((b, s), tok.pad_id, np.int32)
        w = min(s, enc_tokens.shape[1])  # bucket may exceed the prompt cap
        tokens[: len(prompts), :w] = enc_tokens[:, :w]
        lengths = np.zeros((b,), np.int32)
        lengths[: len(prompts)] = enc_lengths
        # Dummy pad rows get length 1 so gather/clip stay in range.
        lengths[len(prompts) :] = 1
        return tokens, lengths, len(prompts)

    def _native_encode(self, prompts, max_prompt, add_bos: bool = True):
        """Batch-encode via the native runtime when the tokenizer is the
        byte tokenizer and libconsensus_rt is available (one C pass
        instead of a Python loop per request)."""
        if type(self.tokenizer) is not ByteTokenizer:
            return None
        try:
            from llm_consensus_tpu.native import available, batch_encode

            if not available():
                return None
            return batch_encode(
                prompts, max_len=max_prompt, add_bos=add_bos
            )
        except Exception:  # noqa: BLE001 - any native issue -> python path
            return None

    def generate_texts(
        self,
        prompts: list[str],
        temperatures: list[float] | None = None,
        seed: int = 0,
        max_new_tokens: int | None = None,
        sampler: SamplerConfig | None = None,
        prefix: str | None = None,
        stop: list[str] | None = None,
        _outer: bool = True,
    ) -> list[EngineResult]:
        """Generate one completion per prompt.

        One device program per chunk of ``batch_buckets[-1]`` prompts;
        most calls fit a single chunk. ``sampler`` overrides the engine's
        default top-k/top-p config for this call.

        ``prefix``: a shared prompt prefix — the effective prompt for row
        i is ``prefix + prompts[i]``. The prefix's K/V is prefilled once
        and cached on device (``self.prefix_cache``), so later calls with
        the same prefix skip its prefill entirely — including on sharded
        engines (batch over ``data``, B=1 prefix broadcast) and quant-KV
        engines (stored bf16 header quantized into the int8 cache on
        entry). Prefix and suffix are tokenized separately (the universal
        prefix-caching caveat: for merge-based tokenizers, split at a
        whitespace/newline boundary).

        ``stop``: stop sequences. Generation text is trimmed at the
        earliest occurrence of any stop string (the stop itself is
        removed); stops that tokenize to a single id also terminate the
        device decode loop early for their row, like EOS.
        """
        if not prompts:
            return []
        if _outer:
            self._calls["generate"] += 1
        chunk = self.config.batch_buckets[-1]
        if len(prompts) > chunk:
            out: list[EngineResult] = []
            for i in range(0, len(prompts), chunk):
                temps_i = (
                    temperatures[i : i + chunk]
                    if temperatures is not None
                    else None
                )
                out.extend(
                    self.generate_texts(
                        prompts[i : i + chunk],
                        temperatures=temps_i,
                        seed=seed + i,
                        max_new_tokens=max_new_tokens,
                        sampler=sampler,
                        prefix=prefix,
                        stop=stop,
                        _outer=False,
                    )
                )
            return out
        if prefix:
            # Mesh engines shard the continuation batch over `data`
            # (GSPMD broadcasts the B=1 prefix); kv_quant engines
            # quantize the stored bf16 prefix into the int8 cache on
            # entry — the prefix cache works on exactly the north-star
            # sharded/quantized configs that reuse headers the most.
            return self._generate_with_prefix(
                prompts, prefix, temperatures, seed, max_new_tokens,
                sampler, stop,
            )
        tokens, lengths, n_real = self._prepare(prompts)
        with self._span(
            "engine.generate",
            batch=tokens.shape[0],
            seq=tokens.shape[1],
            n_real=n_real,
        ):
            return self._generate_prepared(
                prompts, tokens, lengths, n_real, temperatures, seed,
                max_new_tokens, sampler, stop=stop,
            )

    # -- prefix-cached generation --------------------------------------

    def _cache_sharding(self, cache):
        """NamedSharding pytree for a KV cache on this engine's mesh:
        batch over ``data``, kv heads over ``model`` (the
        ``partitioning.cache_pspecs`` layout, covering both cache
        classes — the quant cache is head-major so ``model`` rides
        axis 2)."""
        from jax.sharding import NamedSharding
        from jax.sharding import PartitionSpec as P

        from llm_consensus_tpu.models.cache import QuantKVCache

        mesh = self.mesh
        ln = NamedSharding(mesh, P("data"))
        if isinstance(cache, QuantKVCache):
            s5 = NamedSharding(mesh, P(None, "data", "model"))
            return QuantKVCache(
                k_q=s5, v_q=s5, k_scale=s5, v_scale=s5, length=ln
            )
        from llm_consensus_tpu.models.cache import KVCache

        s5 = NamedSharding(mesh, P(None, "data", None, "model"))
        return KVCache(k=s5, v=s5, length=ln)

    def _stop_ids(self, stop: list[str] | None) -> tuple[int, ...]:
        """Stops that tokenize to exactly one id terminate on device —
        the single-round path's share of the derived-stop machinery in
        :mod:`llm_consensus_tpu.utils.stops` (the multi-round batcher's
        conservative screen lives next to it)."""
        if not stop:
            return ()
        from llm_consensus_tpu.utils.stops import single_token_stop_ids

        return single_token_stop_ids(self.tokenizer, stop)

    @staticmethod
    def _trim_stops(results: list[EngineResult], stop: list[str] | None):
        """Cut each text at the earliest stop occurrence (stop removed).

        ``num_tokens``/``logprob`` keep the device-loop accounting here;
        the chunked multi-token-stop path follows up with
        :meth:`_exact_stop_accounting` so its reported counts match the
        device path's stop-token-inclusive accounting exactly.
        """
        if not stop:
            return results
        from llm_consensus_tpu.utils.stops import earliest_stop_cut

        for r in results:
            cut = earliest_stop_cut(r.text, stop)
            if cut >= 0:
                r.text = r.text[:cut]
        return results

    def _prefix_kv(self, ids: list[int]):
        """(k, v) for the prefilled prefix token ids (cached).

        The stored buffers are right-padded to the pow2 bucket of the
        true length (bounds distinct compiled programs at log2(ctx) and
        makes repeat cache hits zero-copy); pad-slot garbage is never
        attended — ``generate_from_prefix`` masks by the traced true
        length.
        """
        from llm_consensus_tpu.models.cache import KVCache

        max_prefix = self.cfg.max_seq_len - 2  # room for >=1 suffix token
        key = tuple(ids)
        p = len(ids)
        hit = self.prefix_cache.get(key)
        if hit is not None:
            return hit
        pb = min(1 << max(p - 1, 0).bit_length(), max_prefix)
        cache = KVCache.create(self.cfg, 1, pb)
        tokens = jnp.asarray(
            [ids + [self.tokenizer.pad_id] * (pb - p)], jnp.int32
        )
        lengths = jnp.asarray([p], jnp.int32)
        if self.config.prefill_chunk and pb > self.config.prefill_chunk:
            _, cache = _jit_prefill_chunked(
                self.cfg, self.params, tokens, lengths, cache,
                chunk=self.config.prefill_chunk,
            )
        else:
            _, cache = _jit_prefill(
                self.cfg, self.params, tokens, lengths, cache
            )
        entry = (cache.k, cache.v)
        self.prefix_cache.put(key, *entry)
        return entry

    def _generate_with_prefix(
        self, prompts, prefix, temperatures, seed, max_new_tokens, sampler,
        stop,
    ) -> list[EngineResult]:
        from llm_consensus_tpu.engine.generate import generate_from_prefix

        # One encode pass for everything: prefix ids feed both the fit
        # check and the prefix cache; suffix encodings feed both the fit
        # check and the batch (native byte-tokenizer batch path when
        # available). Suffixes that cannot sit whole after the prefix
        # (or that exceed the configured chunked-prefill bound) take the
        # plain concatenated path instead: it left-truncates keeping the
        # tail of prefix+question and honors prefill_chunk — silently
        # crushing the question to fit a long header would be worse than
        # losing the cache reuse.
        ctx = self.cfg.max_seq_len
        prefix_ids = self.tokenizer.encode(prefix)[-(ctx - 2) :]
        p = len(prefix_ids)

        def _fallback():
            log.debug("prefix cache bypassed (suffix does not fit)")
            return self.generate_texts(
                [prefix + q for q in prompts],
                temperatures=temperatures,
                seed=seed,
                max_new_tokens=max_new_tokens,
                sampler=sampler,
                stop=stop,
                _outer=False,
            )

        native = self._native_encode(prompts, ctx, add_bos=False)
        if native is not None:
            enc_tokens, enc_lengths = native
            suf = None
        else:
            suf = [self.tokenizer.encode(q, add_bos=False)[:ctx] for q in prompts]
            enc_lengths = np.array([len(x) for x in suf], np.int32)
        longest = int(enc_lengths.max()) if len(prompts) else 0
        if min(int(enc_lengths.min()), longest) < 1:
            return _fallback()  # an empty suffix: prefix alone, plain path
        if p + longest + 1 > ctx:
            return _fallback()
        s = min(_next_bucket(longest, self.config.seq_buckets), ctx - p - 1)
        s = max(s, longest)
        if self.config.prefill_chunk and s > self.config.prefill_chunk:
            return _fallback()  # suffix chunk would unbound prefill memory
        pk, pv = self._prefix_kv(prefix_ids)
        b = _next_bucket(len(prompts), self.config.batch_buckets)
        tokens = np.full((b, s), self.tokenizer.pad_id, np.int32)
        if suf is None:
            w = min(s, enc_tokens.shape[1])
            tokens[: len(prompts), :w] = enc_tokens[:, :w]
        else:
            for i, ids in enumerate(suf):
                tokens[i, : len(ids)] = ids
        lengths = np.ones((b,), np.int32)  # dummy rows: length 1
        lengths[: len(prompts)] = enc_lengths
        n_real = len(prompts)
        # The stored prefix is padded to the pow2 bucket of its true
        # length (zero-copy on hit); the true length rides as a traced
        # scalar, and the token budget below is charged at the TRUE
        # prefix length — only the suffix term carries bucket slack,
        # the same conservatism as the plain path.
        pb = pk.shape[2]
        if pb + s > ctx:
            pb = ctx - s
            if pb < p:
                return _fallback()  # bucket rounding left no room
            pk, pv = pk[:, :, :pb], pv[:, :, :pb]
        temps = np.zeros((b,), np.float32)
        if temperatures is not None:
            temps[:n_real] = np.asarray(temperatures, np.float32)
        mnt = max_new_tokens or self.config.max_new_tokens
        mnt = max(1, min(mnt, ctx - p - s))
        # Identical suffixes (self-consistency fan-out under a cached
        # header): chunk the suffix once at B=1 and broadcast.
        shared = n_real == b and len(set(prompts)) == 1 and b > 1
        # MoE dispatch-path alignment: resolve dense-vs-capacity for the
        # suffix chunk from the count the plain CONCATENATED path would
        # trace — batch x seq-bucket of the true concat length (B=1 when
        # its shared prefill collapses the batch, mirrored by `shared`
        # here). The prefix KV bucket width pb plays no part: it can
        # overshoot moe_dense_decode_tokens for a prompt whose concat
        # bucket sits under it (the round-5 divergence). Rides as a
        # static BOOL so the compiled-program count stays bounded by the
        # buckets. Only capacity-routed MoE configs pass it; everything
        # else keeps the jit key untouched with None. See
        # _prefix_prefill_impl.
        moe_dense = None
        if self.cfg.is_moe and self.cfg.moe_capacity_factor > 0:
            s_plain = min(
                _next_bucket(p + longest, self.config.seq_buckets),
                self.cfg.max_seq_len,
            )
            moe_dense = self.cfg.moe_dense_at((1 if shared else b) * s_plain)
        tokens_j = jnp.asarray(tokens)
        lengths_j = jnp.asarray(lengths)
        temps_j = jnp.asarray(temps)
        if self._data_sharding is not None:
            tokens_j = jax.device_put(tokens_j, self._data_sharding)
            lengths_j = jax.device_put(lengths_j, self._data_sharding)
            temps_j = jax.device_put(temps_j, self._data_sharding)
        multi_stop = stop and any(
            len(self.tokenizer.encode(x, add_bos=False)) > 1 for x in stop
        )
        if multi_stop:
            # Prefix-cached generation with multi-token stops rides the
            # same chunked host-checked decode as the plain path: the
            # header reuse and the early exit compose instead of the
            # prefix workload silently decoding to EOS/max_new_tokens.
            from llm_consensus_tpu.engine.generate import prefill_from_prefix

            with self._span(
                "engine.generate_prefix_chunked_stops",
                batch=b,
                prefix=p,
                seq=s,
                n_real=n_real,
            ):
                logits, cache = prefill_from_prefix(
                    self.cfg,
                    self.params,
                    pk,
                    pv,
                    jnp.asarray(p, jnp.int32),
                    tokens_j,
                    lengths_j,
                    cache_len=pb + s + mnt,
                    shared_suffix=shared,
                    kv_quant=self.config.kv_quant,
                    moe_suffix_dense=moe_dense,
                )
                return self._chunked_stop_decode(
                    logits, cache, temps_j, n_real, seed, mnt, sampler,
                    stop,
                )
        with self._span(
            "engine.generate_prefix",
            batch=b,
            prefix=p,
            seq=s,
            n_real=n_real,
        ):
            out = generate_from_prefix(
                self.cfg,
                self.params,
                pk,
                pv,
                jnp.asarray(p, jnp.int32),
                tokens_j,
                lengths_j,
                jax.random.PRNGKey(seed),
                temps_j,
                max_new_tokens=mnt,
                sampler=sampler if sampler is not None else self.config.sampler,
                eos_id=self.tokenizer.eos_id,
                pad_id=self.tokenizer.pad_id,
                stop_ids=self._stop_ids(stop),
                shared_suffix=shared,
                kv_quant=self.config.kv_quant,
                moe_suffix_dense=moe_dense,
            )
        return self._trim_stops(self._collect(out, n_real), stop)

    def memory_estimate(
        self,
        n_candidates: int = 1,
        prompt_len: int = 128,
        new_tokens: int | None = None,
        hbm_bytes: int | None = None,
        shared_prefix_len: int = 0,
    ) -> dict:
        """HBM budget estimate for a generate call at the given shapes.

        Returns PER-CHIP bytes for resident params (target + any draft
        model), the KV cache(s) a call would allocate (post-bucketing,
        honoring ``kv_quant``; speculative decoding's draft cache
        included when a draft is attached), the fp32 logits buffer, and
        their total — plus ``fits`` when ``hbm_bytes`` is given (e.g.
        16 GiB for one v5e chip). On a mesh, each term is divided by the
        axes it shards over (params over model x expert, replicated
        over data; cache/logits over data x model per ``cache_pspecs``).
        Capacity planning for the N-way fan-out: "does N=64 at 4k
        context fit?" without OOMing a real chip to find out.

        ``shared_prefix_len``: prompt-prefix tokens shared by every
        candidate and STORED ONCE — the paged serving path's CoW page
        sharing (PR 2/3), where an N-fanout's KV footprint is
        prefix + N*suffix. The default 0 models the engine's dense
        per-row cache, which duplicates the prefix (the pre-PR-2
        worst case; capped at the bucketed prompt length since decode
        suffixes are never shared).
        """
        from llm_consensus_tpu.ops.quant import quantized_bytes

        cfg = self.cfg
        s = min(
            _next_bucket(prompt_len, self.config.seq_buckets),
            cfg.max_seq_len,
        )
        mnt = new_tokens or self.config.max_new_tokens
        mnt = max(1, min(mnt, cfg.max_seq_len - s))
        b = _next_bucket(n_candidates, self.config.batch_buckets)
        cache_len = s + mnt

        kv = _kv_cache_bytes(
            cfg, b, cache_len, self.config.kv_quant,
            shared_len=min(shared_prefix_len, s),
        )
        if self.draft is not None:
            d_cfg, d_params = self.draft
            # Speculative decoding holds bf16 target + draft caches.
            kv += _kv_cache_bytes(d_cfg, b, cache_len, quant=False)
        logits = _logits_bytes(cfg, b)
        # Per-chip residency on a mesh: each param leaf divides by the
        # axes its OWN PartitionSpec names (replicated leaves — embeds,
        # norms, and on MoE models all non-expert weights — do not
        # shrink); the cache and batch shard over data and kv heads
        # over model.
        c_div = 1
        if self.mesh is not None:
            from llm_consensus_tpu.parallel.partitioning import (
                sharded_param_bytes,
            )

            shape = dict(self.mesh.shape)
            params_bytes = sharded_param_bytes(self.params, shape)
            if self.draft is not None:
                params_bytes += sharded_param_bytes(self.draft[1], shape)
            c_div = shape.get("data", 1) * shape.get("model", 1)
        else:
            params_bytes = quantized_bytes(self.params)
            if self.draft is not None:
                params_bytes += quantized_bytes(self.draft[1])
        kv //= c_div
        logits //= max(1, c_div)
        total = params_bytes + kv + logits
        out = {
            "params_bytes": params_bytes,
            "kv_cache_bytes": kv,
            "logits_bytes": logits,
            "total_bytes": total,
            "batch": b,
            "cache_len": cache_len,
        }
        if hbm_bytes is not None:
            out["fits"] = total <= hbm_bytes
        return out

    def stats(self) -> dict:
        """Lifetime engine counters (observability surface).

        Calls per API, total generated tokens, and the prefix cache's
        hit/miss/eviction counts + resident bytes — the numbers a
        serving dashboard or an eval report wants without tracing.
        """
        pc = self.prefix_cache
        return {
            "calls": dict(self._calls),
            "tokens_generated": self._tokens_generated,
            "prefix_cache": {
                "hits": pc.stats.hits,
                "misses": pc.stats.misses,
                "evictions": pc.stats.evictions,
                "entries": len(pc),
                "bytes": pc.nbytes,
            },
        }

    def _collect(self, out: GenerateOutput, n_real: int) -> list[EngineResult]:
        toks = np.asarray(out.tokens)
        nums = np.asarray(out.num_tokens)
        lps = np.asarray(out.logprob_sum)
        self._tokens_generated += int(nums[:n_real].sum())
        results = []
        for i in range(n_real):
            n = int(nums[i])
            ids = [int(t) for t in toks[i, :n] if t != self.tokenizer.eos_id]
            results.append(
                EngineResult(
                    text=self.tokenizer.decode(ids),
                    num_tokens=n,
                    logprob=float(lps[i]),
                    token_ids=ids,
                )
            )
        return results

    def _span(self, name: str, **meta):
        """Engine instrumentation site: the span lands on the engine's
        optional flat Tracer AND on the caller's request-scoped trace
        (propagated here through asyncio.to_thread's context copy) —
        gateway-driven engine calls show up in ``GET /debug/traces``
        with no per-call plumbing. Untraced engines keep the free
        nullcontext fast path."""
        import contextlib

        from llm_consensus_tpu.utils import tracing as _tracing

        traced = _tracing.current_trace() is not None
        if self.tracer is None:
            if not traced:
                return contextlib.nullcontext()
            return _tracing.request_span(name, **meta)
        if not traced:
            return self.tracer.span(name, **meta)
        stack = contextlib.ExitStack()
        stack.enter_context(_tracing.request_span(name, **meta))
        stack.enter_context(self.tracer.span(name, **meta))
        return stack

    def _generate_prepared(
        self,
        prompts,
        tokens,
        lengths,
        n_real,
        temperatures,
        seed,
        max_new_tokens,
        sampler,
        stop=None,
    ) -> list[EngineResult]:
        b = tokens.shape[0]
        temps = np.zeros((b,), np.float32)
        if temperatures is not None:
            temps[:n_real] = np.asarray(temperatures, np.float32)
        mnt = max_new_tokens or self.config.max_new_tokens
        # Clamp so prompt + generation fits the model context.
        mnt = max(1, min(mnt, self.cfg.max_seq_len - tokens.shape[1]))

        # Identical prompts (self-consistency fan-out) prefill once and
        # broadcast the cache instead of prefetching B copies.
        shared = n_real == b and len(set(prompts)) == 1 and b > 1
        tokens_j, lengths_j, temps_j = (
            jnp.asarray(tokens),
            jnp.asarray(lengths),
            jnp.asarray(temps),
        )
        if self._data_sharding is not None:
            tokens_j = jax.device_put(tokens_j, self._data_sharding)
            lengths_j = jax.device_put(lengths_j, self._data_sharding)
            temps_j = jax.device_put(temps_j, self._data_sharding)
        multi_stop = stop and any(
            len(self.tokenizer.encode(x, add_bos=False)) > 1 for x in stop
        )
        if multi_stop:
            return self._generate_chunked_stops(
                tokens_j, lengths_j, temps_j, n_real, seed, mnt, sampler,
                stop, shared,
            )
        out: GenerateOutput = generate(
            self.cfg,
            self.params,
            tokens_j,
            lengths_j,
            jax.random.PRNGKey(seed),
            temps_j,
            max_new_tokens=mnt,
            sampler=sampler if sampler is not None else self.config.sampler,
            eos_id=self.tokenizer.eos_id,
            pad_id=self.tokenizer.pad_id,
            shared_prefill=shared,
            kv_quant=self.config.kv_quant,
            # Ring prefill (long-context sequence parallelism) when the
            # model opts in and the mesh has a seq axis.
            mesh=self.mesh if self.cfg.use_ring else None,
            prefill_chunk=self.config.prefill_chunk,
            stop_ids=self._stop_ids(stop),
        )
        return self._trim_stops(self._collect(out, n_real), stop)

    def _generate_chunked_stops(
        self, tokens_j, lengths_j, temps_j, n_real, seed, mnt, sampler,
        stop, shared,
    ) -> list[EngineResult]:
        """Batch generation with MULTI-token stop sequences: decode in
        ``stop_check_chunk``-step device calls with host text checks
        between them, so stops like ``"\\n\\n"`` (several ids under any
        tokenizer) end decoding within one chunk instead of every row
        burning steps to EOS/max_new_tokens.

        Greedy output text matches the one-shot path exactly (modulo the
        earlier cutoff); sampled rows draw per-chunk PRNG subkeys (the
        ``generate_stream`` convention) — deterministic per seed, but a
        different stream than the no-stop program. A row whose text
        contains a stop is marked done on device at the next chunk
        boundary; the final :meth:`_exact_stop_accounting` pass then
        realigns ``num_tokens``/``logprob``/``token_ids`` to the prefix
        through the stop, so both stop paths report identical
        accounting (no chunk-granularity overshoot in vote weights)."""
        from llm_consensus_tpu.engine.generate import prefill_into_cache

        b, s = tokens_j.shape
        with self._span(
            "engine.generate_chunked_stops", batch=b, seq=s, n_real=n_real
        ):
            logits, cache = prefill_into_cache(
                self.cfg,
                self.params,
                tokens_j,
                lengths_j,
                cache_len=s + mnt,
                shared_prefill=shared,
                kv_quant=self.config.kv_quant,
                mesh=self.mesh if self.cfg.use_ring else None,
                prefill_chunk=self.config.prefill_chunk,
            )
            return self._chunked_stop_decode(
                logits, cache, temps_j, n_real, seed, mnt, sampler, stop
            )

    def _chunked_stop_decode(
        self, logits, cache, temps_j, n_real, seed, mnt, sampler, stop
    ) -> list[EngineResult]:
        """The decode half of the chunked multi-token-stop path, from
        first-token logits + a filled cache onward — shared by the plain
        batch path and the prefix-cached path (both prefill differently
        but stop identically)."""
        from llm_consensus_tpu.engine.generate import (
            GenerateOutput,
            decode_steps,
        )

        tok_ = self.tokenizer
        b = logits.shape[0]
        sampler_cfg = sampler if sampler is not None else self.config.sampler
        stop_ids = self._stop_ids(stop)
        terminal = {tok_.eos_id, *stop_ids}
        with self._span(
            "engine.chunked_stop_decode", batch=b, n_real=n_real
        ):
            key = jax.random.PRNGKey(seed)
            tok, lp0 = _jit_sample(
                logits, jax.random.fold_in(key, 0), temps_j, sampler_cfg
            )
            toks0 = np.asarray(tok)
            done_np = np.array([int(t) in terminal for t in toks0])
            lp_sum = np.asarray(lp0, np.float32).copy()
            cols_toks = [toks0[:, None].astype(np.int32)]
            cols_live = [np.ones((b, 1), bool)]
            cols_lp = [np.asarray(lp0, np.float32)[:, None]]
            stop_hit = np.zeros((b,), bool)
            done = jnp.asarray(done_np)
            if self._data_sharding is not None:
                done = jax.device_put(done, self._data_sharding)
            produced = 1
            chunk = max(1, self.config.stop_check_chunk)
            chunk_i = 0
            # Per-row incremental id streams + tail-window stop checks:
            # decoding each row's full history every chunk would be
            # O(T^2/chunk) host work (the continuous batcher's _hit_stop
            # learned the same lesson). The final _trim_stops pass
            # guarantees exact text regardless of the window.
            from llm_consensus_tpu.utils.stops import stop_tail_window

            win = stop_tail_window(tok_, stop)
            vis = self._vis_filter
            row_ids: list[list[int]] = [
                [] if done_np[r] else [int(toks0[r])] for r in range(n_real)
            ]

            def _row_stopped(r: int) -> bool:
                # Shared window-then-confirm shape (stops.py): a false
                # positive here would silently truncate a row that
                # _trim_stops then finds no stop in.
                ids = row_ids[r]
                return vis.confirmed_stop_hit(
                    ids, stop, win, lambda: tok_.decode(ids)
                )

            while produced < mnt:
                active = [
                    r
                    for r in range(n_real)
                    if not done_np[r] and not stop_hit[r]
                ]
                if not active:
                    break
                k = min(chunk, mnt - produced)
                chunk_i += 1
                out, live, cache, done, tok, lp = decode_steps(
                    self.cfg,
                    self.params,
                    cache,
                    tok,
                    done,
                    jax.random.fold_in(key, chunk_i),
                    temps_j,
                    steps=chunk,
                    sampler=sampler_cfg,
                    eos_id=tok_.eos_id,
                    pad_id=tok_.pad_id,
                    stop_ids=stop_ids,
                )
                out_np = np.asarray(out)[:, :k].astype(np.int32)
                live_np = np.asarray(live)[:, :k]
                cols_toks.append(out_np)
                cols_live.append(live_np)
                # Per-step logprobs, truncated to the consumed prefix —
                # tail-chunk overshoot must not inflate the sum.
                lp_np = np.asarray(lp, np.float32)[:, :k]
                cols_lp.append(lp_np)
                lp_sum += lp_np.sum(axis=1)
                produced += k
                done_np = np.asarray(done).copy()
                for r in active:
                    row_ids[r].extend(
                        int(t)
                        for t, alive in zip(out_np[r], live_np[r])
                        if alive and int(t) not in terminal
                    )
                    if not done_np[r] and _row_stopped(r):
                        stop_hit[r] = True
                if stop_hit.any():
                    # Stopped rows go done on device: they stop burning
                    # logprob accumulation and emit pad from here on.
                    done = jnp.asarray(done_np | stop_hit)
                    if self._data_sharding is not None:
                        done = jax.device_put(done, self._data_sharding)

        tokens_arr = np.concatenate(cols_toks, axis=1)
        live_arr = np.concatenate(cols_live, axis=1)
        lp_arr = np.concatenate(cols_lp, axis=1)
        out = GenerateOutput(
            tokens=jnp.asarray(tokens_arr),
            num_tokens=jnp.asarray(live_arr.sum(axis=1).astype(np.int32)),
            logprob_sum=jnp.asarray(lp_sum),
        )
        results = self._trim_stops(self._collect(out, n_real), stop)
        return self._exact_stop_accounting(results, tokens_arr, lp_arr, stop)

    def _exact_stop_accounting(
        self, results, toks_np, lp_np, stop
    ) -> list[EngineResult]:
        """Align the chunked multi-token-stop path's accounting with
        the device single-token-stop path: ``num_tokens`` / ``logprob``
        / ``token_ids`` cover exactly the prefix through the first
        complete stop occurrence (the stop's own tokens counted, like
        EOS) instead of including up to one ``stop_check_chunk`` of
        overshoot. Without this, the SAME stop reported different
        logit_pool/rescore vote weights depending on whether it
        tokenized to one id (device path, exact) or several (chunked
        path) — aggregation weights must not depend on tokenizer
        granularity. The prefix search assumes decoded-prefix
        containment is monotone in token count (exact for byte-level
        tokenizers; merge-based boundary effects can shift the cut by
        a token, never the text, which was already trimmed exactly).
        """
        from llm_consensus_tpu.utils.stops import earliest_stop_cut

        eos = self.tokenizer.eos_id
        for i, r in enumerate(results):
            n = r.num_tokens
            if n <= 1:
                continue

            def ids(m: int) -> list[int]:
                # Mirrors _collect's id construction (eos excluded) —
                # one predicate, shared by the probe and the result.
                return [int(t) for t in toks_np[i, :m] if int(t) != eos]

            if earliest_stop_cut(self.tokenizer.decode(ids(n)), stop) < 0:
                continue
            lo, hi = 1, n
            while lo < hi:
                mid = (lo + hi) // 2
                pref = self.tokenizer.decode(ids(mid))
                if earliest_stop_cut(pref, stop) >= 0:
                    hi = mid
                else:
                    lo = mid + 1
            if lo < n:
                # Keep the engine-wide generated-token counter honest
                # too (it was bumped with the overshoot included).
                self._tokens_generated -= n - lo
                r.num_tokens = lo
                r.logprob = float(lp_np[i, :lo].sum())
                r.token_ids = ids(lo)
        return results

    def generate_stream(
        self,
        prompt: str,
        *,
        temperature: float = 0.0,
        seed: int = 0,
        max_new_tokens: int | None = None,
        chunk: int = 16,
        sampler: SamplerConfig | None = None,
        stop: list[str] | None = None,
    ):
        """Yield text increments for one prompt as tokens decode.

        The streaming surface of the engine: prefill once, then decode
        in device calls of ``chunk`` steps, yielding the newly decoded
        text after each (REPL/interactive serving — the reference's UX
        blocks on the whole remote answer, ``src/main.rs:448-463``).
        Greedy streaming concatenates to exactly ``generate_texts``'s
        output; sampled streams draw per-chunk PRNG subkeys. Stop
        sequences are honored across chunk boundaries. Sharded engines
        stream incrementally too: the single request pads to the data
        axis (dummy greedy rows beyond row 0) and the cache/batch shard
        as in ``generate_texts`` — the REPL sees tokens as they decode
        on the north-star config, not one blocking yield.
        """
        self._calls["stream"] += 1
        from llm_consensus_tpu.engine.generate import decode_steps
        from llm_consensus_tpu.models.cache import KVCache, QuantKVCache

        tok_ = self.tokenizer
        tokens, lengths, _ = self._prepare([prompt])
        if self.mesh is None:
            # _prepare pads to the batch bucket; the stream decodes one
            # row. On a mesh the bucketed batch stays (it tiles `data`).
            tokens, lengths = tokens[:1], lengths[:1]
        b = tokens.shape[0]
        s = tokens.shape[1]
        mnt = max_new_tokens or self.config.max_new_tokens
        mnt = max(1, min(mnt, self.cfg.max_seq_len - s))
        chunk = max(1, chunk)
        sampler_cfg = sampler if sampler is not None else self.config.sampler
        stop = stop or []
        stop_ids = self._stop_ids(stop)
        terminal = {tok_.eos_id, *stop_ids}

        make_cache = (
            QuantKVCache.create if self.config.kv_quant else KVCache.create
        )
        cache = make_cache(self.cfg, b, s + mnt)
        tokens_j = jnp.asarray(tokens)
        lengths_j = jnp.asarray(lengths)
        temps_np = np.zeros((b,), np.float32)
        temps_np[0] = temperature
        temps = jnp.asarray(temps_np)
        if self._data_sharding is not None:
            tokens_j = jax.device_put(tokens_j, self._data_sharding)
            lengths_j = jax.device_put(lengths_j, self._data_sharding)
            temps = jax.device_put(temps, self._data_sharding)
            cache = jax.device_put(cache, self._cache_sharding(cache))
        if self.config.prefill_chunk and s > self.config.prefill_chunk:
            logits, cache = _jit_prefill_chunked(
                self.cfg, self.params, tokens_j, lengths_j, cache,
                chunk=self.config.prefill_chunk,
            )
        else:
            logits, cache = _jit_prefill(
                self.cfg, self.params, tokens_j, lengths_j, cache
            )
        key = jax.random.PRNGKey(seed)
        tok, _ = _jit_sample(
            logits, jax.random.fold_in(key, 0), temps, sampler_cfg
        )
        toks_np = np.asarray(tok)
        first = int(toks_np[0])
        ids: list[int] = [] if first in terminal else [first]
        done = jnp.asarray([int(t) in terminal for t in toks_np])
        if self._data_sharding is not None:
            done = jax.device_put(done, self._data_sharding)
        self._tokens_generated += 1
        yielded = 0

        def _flush(final: bool):
            """(increment, finished): emit decoded text past what was
            already yielded, holding back (a) any tail that is a partial
            match of a stop string (it may complete next chunk and must
            then be trimmed, never emitted) and (b) trailing replacement
            chars from split multi-byte sequences."""
            nonlocal yielded
            from llm_consensus_tpu.utils.stops import earliest_stop_cut

            t = tok_.decode(ids)
            cut = earliest_stop_cut(t, stop)
            finished = cut >= 0
            if finished:
                t = t[:cut]
            emit_to = len(t)
            if not finished and not final:
                hold = 0
                for x in stop:
                    for k in range(min(len(x) - 1, len(t)), 0, -1):
                        if t.endswith(x[:k]):
                            hold = max(hold, k)
                            break
                emit_to = len(t) - hold
                while emit_to > yielded and t[emit_to - 1] == "�":
                    emit_to -= 1
            inc = t[yielded:emit_to]
            yielded = max(yielded, emit_to)
            return inc, finished

        inc, finished = _flush(final=False)
        if inc:
            yield inc
        if finished:
            return
        produced = 1
        chunk_i = 0
        while produced < mnt and not bool(done[0]):
            # Always run a full `chunk` of steps — `steps` is a static
            # jit arg, so a shorter tail would compile a second decode
            # program mid-stream. Overshoot tokens past the budget are
            # discarded (their cache writes past capacity are dropped
            # by scatter OOB semantics, and the loop ends this chunk).
            k = min(chunk, mnt - produced)
            chunk_i += 1
            out, live, cache, done, tok, _ = decode_steps(
                self.cfg,
                self.params,
                cache,
                tok,
                done,
                jax.random.fold_in(key, chunk_i),
                temps,
                steps=chunk,
                sampler=sampler_cfg,
                eos_id=tok_.eos_id,
                pad_id=tok_.pad_id,
                stop_ids=stop_ids,
            )
            produced += k
            self._tokens_generated += int(np.asarray(live[0, :k]).sum())
            # A genuinely sampled pad id while live stays in the text
            # (matching generate_texts); only post-termination padding
            # and terminal tokens (eos / device stops) are dropped.
            ids.extend(
                t
                for t, alive in zip(out[0, :k].tolist(), live[0, :k].tolist())
                if alive and t not in terminal
            )
            inc, finished = _flush(final=False)
            if inc:
                yield inc
            if finished:
                return
        inc, _ = _flush(final=True)
        if inc:
            yield inc

    def score_texts(
        self,
        prompt: str,
        completions: list[str],
        *,
        normalize: bool = False,
        _outer: bool = True,
    ) -> list[float]:
        """Log-probability of each completion given ``prompt``.

        Teacher-forced scoring — no sampling: the prompt prefills once,
        its cache broadcasts, and every completion's tokens score in one
        ragged chunk forward. ``normalize``: divide by token count
        (length-normalized, for comparing completions of different
        lengths). Candidates can come from anywhere — another model of
        a heterogeneous panel, a debate round, a human draft — making
        this the reranking/logit-pooling half of answer aggregation.
        bf16 cache. On a mesh the completion rows shard over ``data``
        (the prompt and its B=1 prefill replicate; GSPMD broadcasts the
        cache into the sharded batch) — judge rescoring works on the
        north-star sharded config, same numbers as single-device.
        """
        if not completions:
            return []
        if _outer:
            self._calls["score"] += 1
        # Batches beyond the largest bucket score in chunks.
        max_b = self.config.batch_buckets[-1]
        if len(completions) > max_b:
            out: list[float] = []
            for i in range(0, len(completions), max_b):
                out.extend(
                    self.score_texts(
                        prompt,
                        completions[i : i + max_b],
                        normalize=normalize,
                        _outer=False,
                    )
                )
            return out
        from llm_consensus_tpu.engine.generate import score_completions

        tok = self.tokenizer
        ctx = self.cfg.max_seq_len
        p_ids = tok.encode(prompt)[-(ctx - 2) :]
        p = len(p_ids)
        # Prompt pads to a seq bucket (the true length rides as data) so
        # repeat calls with different prompt lengths share one compiled
        # program — the engine-wide bucketing contract.
        sp = max(p, min(_next_bucket(p, self.config.seq_buckets), ctx - 1))
        comp_cap = min(ctx - p, self.config.seq_buckets[-1])
        comp = [
            tok.encode(c, add_bos=False)[:comp_cap] for c in completions
        ]
        if any(len(c) < 1 for c in comp):
            raise ValueError("cannot score an empty completion")
        k = min(
            _next_bucket(max(len(c) for c in comp), self.config.seq_buckets),
            comp_cap,
        )
        k = max(k, max(len(c) for c in comp))
        b = _next_bucket(len(comp), self.config.batch_buckets)
        ctoks = np.full((b, k), tok.pad_id, np.int32)
        for i, ids in enumerate(comp):
            ctoks[i, : len(ids)] = ids
        clens = np.ones((b,), np.int32)
        clens[: len(comp)] = [len(c) for c in comp]
        ptoks = np.full((1, sp), tok.pad_id, np.int32)
        ptoks[0, :p] = p_ids
        ptoks_j = jnp.asarray(ptoks)
        plen_j = jnp.asarray([p], jnp.int32)
        ctoks_j = jnp.asarray(ctoks)
        clens_j = jnp.asarray(clens)
        if self._data_sharding is not None:
            from jax.sharding import NamedSharding
            from jax.sharding import PartitionSpec as P

            rep = NamedSharding(self.mesh, P())
            ptoks_j = jax.device_put(ptoks_j, rep)
            plen_j = jax.device_put(plen_j, rep)
            ctoks_j = jax.device_put(ctoks_j, self._data_sharding)
            clens_j = jax.device_put(clens_j, self._data_sharding)
        with self._span(
            "engine.score", batch=b, prompt=p, k=k, n_real=len(comp)
        ):
            sums, _ = score_completions(
                self.cfg,
                self.params,
                ptoks_j,
                plen_j,
                ctoks_j,
                clens_j,
                cache_len=sp + k,
            )
        out = np.asarray(sums)[: len(comp)].tolist()
        if normalize:
            out = [s / max(len(c), 1) for s, c in zip(out, comp)]
        return out

    def generate_texts_speculative(
        self,
        prompts: list[str],
        max_new_tokens: int | None = None,
        k_spec: int = 4,
        _outer: bool = True,
    ) -> list[EngineResult]:
        """Greedy generation accelerated by the draft model.

        Requires ``draft=(cfg, params)`` at engine construction. Output
        text is IDENTICAL to greedy ``generate_texts`` (speculation only
        changes speed — tested); greedy-only, bf16 KV, one-shot
        prefill. On a mesh engine the whole speculative program runs
        sharded (batch over ``data``, target+draft params over
        ``model`` — dp-mesh exactness tested). Logprobs follow the same
        convention as the plain path (target log_softmax of emitted
        tokens).
        """
        if self.draft is None:
            raise ValueError("engine was built without a draft model")
        if not prompts:
            return []
        if _outer:
            self._calls["speculative"] += 1
        chunk = self.config.batch_buckets[-1]
        if len(prompts) > chunk:
            out: list[EngineResult] = []
            for i in range(0, len(prompts), chunk):
                out.extend(
                    self.generate_texts_speculative(
                        prompts[i : i + chunk],
                        max_new_tokens=max_new_tokens,
                        k_spec=k_spec,
                        _outer=False,
                    )
                )
            return out
        from llm_consensus_tpu.engine.speculative import speculative_generate

        draft_cfg, draft_params = self.draft
        tokens, lengths, n_real = self._prepare(prompts)
        tokens_j, lengths_j = jnp.asarray(tokens), jnp.asarray(lengths)
        if self._data_sharding is not None:
            tokens_j = jax.device_put(tokens_j, self._data_sharding)
            lengths_j = jax.device_put(lengths_j, self._data_sharding)
        # Same clamp as generate_texts — the k_spec+1 chunk slack lives
        # in speculative_generate's cache_len, NOT in the token budget,
        # so outputs stay identical to the greedy path.
        mnt = max_new_tokens or self.config.max_new_tokens
        mnt = max(1, min(mnt, self.cfg.max_seq_len - tokens.shape[1]))
        with self._span(
            "engine.generate_speculative",
            batch=tokens.shape[0],
            seq=tokens.shape[1],
            n_real=n_real,
            k_spec=k_spec,
        ):
            out = speculative_generate(
                self.cfg,
                self.params,
                draft_cfg,
                draft_params,
                tokens_j,
                lengths_j,
                max_new_tokens=mnt,
                k_spec=k_spec,
                eos_id=self.tokenizer.eos_id,
                pad_id=self.tokenizer.pad_id,
                mesh=self.mesh,
            )
        return self._collect(out, n_real)


def plan_memory(
    cfg: ModelConfig,
    *,
    quant: str = "none",
    kv_quant: bool = False,
    n_candidates: int = 1,
    prompt_len: int = 128,
    new_tokens: int = 256,
    mesh_shape: dict | None = None,
    hbm_bytes: int | None = None,
    seq_buckets: tuple[int, ...] | None = None,
    batch_buckets: tuple[int, ...] | None = None,
    shared_prefix_len: int = 0,
    host_cache_bytes: int = 0,
    page_size: int = 64,
) -> dict:
    """Config-only HBM plan — no weights are ever allocated.

    The capacity-planning companion to :meth:`InferenceEngine.
    memory_estimate` for models too large to instantiate first (the
    question "can Mixtral-8x7B fit one v5e chip?" must be answerable
    without OOMing one). Param bytes come from ``jax.eval_shape`` over
    ``init_params`` + ``quantize_params`` — exact leaf-for-leaf sizes,
    zero allocation. KV/logit math matches ``memory_estimate``,
    INCLUDING the engine's shape bucketing: ``n_candidates``/
    ``prompt_len`` round up to ``batch_buckets``/``seq_buckets``
    (defaults = ``EngineConfig``'s) exactly as a real generate call
    would, so the ``fits`` verdict reflects what the engine actually
    allocates, not the raw request. Pass ``buckets=()``-style overrides
    to mirror a custom engine config. ``mesh_shape`` (e.g.
    ``{"data": 4, "model": 2}``) divides each term by the axes it
    shards over. ``shared_prefix_len``: prompt tokens stored once for
    the whole fan-out (the paged serving path's prefix sharing) — see
    :meth:`InferenceEngine.memory_estimate`.

    ``host_cache_bytes`` > 0 adds the hierarchical-cache tier (PR 4,
    ``ContinuousConfig.host_cache_bytes``) to the plan: how many
    ``page_size``-token KV pages — in this config's KV dtype,
    ``kv_quant`` scales included — the host-RAM tier can keep warm,
    and the prefix-token capacity that buys. Host bytes never count
    against ``hbm_bytes`` (pinned host RAM, not device memory); the
    tier changes how much RECOMPUTE eviction costs, not whether the
    device footprint fits.
    """
    from llm_consensus_tpu.models.transformer import init_params
    from llm_consensus_tpu.ops.quant import quantize_params, quantized_bytes

    tree = jax.eval_shape(
        lambda: init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.bfloat16)
    )
    if quant in ("int8", "int4"):
        bits = 8 if quant == "int8" else 4
        tree = jax.eval_shape(lambda t: quantize_params(t, bits=bits), tree)

    dflt = EngineConfig()
    sb = seq_buckets if seq_buckets is not None else dflt.seq_buckets
    bb = batch_buckets if batch_buckets is not None else dflt.batch_buckets
    s = min(_next_bucket(prompt_len, sb), cfg.max_seq_len)
    b = _next_bucket(n_candidates, bb)
    mnt = max(1, min(new_tokens, cfg.max_seq_len - s))
    cache_len = s + mnt
    kv = _kv_cache_bytes(
        cfg, b, cache_len, kv_quant, shared_len=min(shared_prefix_len, s)
    )
    logits = _logits_bytes(cfg, b)

    shape = dict(mesh_shape or {})
    if any(v > 1 for v in shape.values()):
        # Per-leaf division by the axes each leaf's PartitionSpec names:
        # on MoE models only the expert FFN stacks shard over `expert`;
        # attention/embeds/norms replicate and must count at full size
        # per chip (a global model*expert divide understates residency
        # and can claim a config fits when it OOMs).
        from llm_consensus_tpu.parallel.partitioning import (
            sharded_param_bytes,
        )

        params_bytes = sharded_param_bytes(tree, shape)
    else:
        params_bytes = quantized_bytes(tree)
    c_div = shape.get("data", 1) * shape.get("model", 1)
    kv //= c_div
    logits //= max(1, c_div)
    total = params_bytes + kv + logits
    out = {
        "params_bytes": params_bytes,
        "kv_cache_bytes": kv,
        "logits_bytes": logits,
        "total_bytes": total,
        "batch": b,
        "cache_len": cache_len,
    }
    if host_cache_bytes > 0:
        # One page of KV in this config's dtype, scales included — the
        # same _kv_cache_bytes formula the device terms use, so a cache
        # layout change cannot drift the two tiers apart.
        page_bytes = _kv_cache_bytes(cfg, 1, page_size, kv_quant)
        host_pages = host_cache_bytes // max(1, page_bytes)
        out["host_cache_bytes"] = host_cache_bytes
        out["host_page_bytes"] = page_bytes
        out["host_capacity_pages"] = host_pages
        out["host_capacity_tokens"] = host_pages * page_size
    if hbm_bytes is not None:
        out["fits"] = total <= hbm_bytes
    return out
