"""InferenceEngine: text-in/text-out over the compiled generate loop.

The host-side runtime around :func:`llm_consensus_tpu.engine.generate`:
tokenization, right-padding, shape bucketing (so repeat calls hit the jit
cache instead of recompiling), PRNG key management, and detokenization.
This object is what :class:`llm_consensus_tpu.backends.local.LocalBackend`
exposes through the ``Backend`` seam — i.e. it stands exactly where the
reference's ``call_gemini`` stood (``src/main.rs:82-86``), but batched.
"""

from __future__ import annotations

import logging
from dataclasses import dataclass, field

import jax
import jax.numpy as jnp
import numpy as np

from llm_consensus_tpu.engine.generate import GenerateOutput, generate
from llm_consensus_tpu.engine.sampler import SamplerConfig
from llm_consensus_tpu.engine.tokenizer import ByteTokenizer, Tokenizer
from llm_consensus_tpu.models.configs import ModelConfig

log = logging.getLogger(__name__)


def _next_bucket(n: int, buckets: tuple[int, ...]) -> int:
    for b in buckets:
        if n <= b:
            return b
    return buckets[-1]


@dataclass
class EngineConfig:
    max_new_tokens: int = 256
    # Prompt-length buckets (right-padded up; keeps the jit cache small).
    seq_buckets: tuple[int, ...] = (64, 128, 256, 512, 1024, 2048)
    # Batch-size buckets (padded up with dummy rows).
    batch_buckets: tuple[int, ...] = (1, 2, 4, 8, 16, 32, 64)
    sampler: SamplerConfig = field(default_factory=SamplerConfig)


@dataclass
class EngineResult:
    text: str
    num_tokens: int
    logprob: float
    token_ids: list[int]


class InferenceEngine:
    """Batched local text generation on one model's weights."""

    def __init__(
        self,
        cfg: ModelConfig,
        params: dict,
        tokenizer: Tokenizer | None = None,
        engine_config: EngineConfig | None = None,
    ):
        self.cfg = cfg
        self.params = params
        self.tokenizer = tokenizer or ByteTokenizer()
        if self.tokenizer.vocab_size > cfg.vocab_size:
            raise ValueError(
                f"tokenizer vocab {self.tokenizer.vocab_size} exceeds model "
                f"vocab {cfg.vocab_size}"
            )
        self.config = engine_config or EngineConfig()

    # ------------------------------------------------------------------

    def _prepare(
        self, prompts: list[str]
    ) -> tuple[np.ndarray, np.ndarray, int]:
        tok = self.tokenizer
        encoded = [tok.encode(p) for p in prompts]
        # Left-truncate over-long prompts (keep the question tail); the cap
        # is the model context, not just the largest bucket.
        max_prompt = min(self.config.seq_buckets[-1], self.cfg.max_seq_len - 1)
        encoded = [ids[-max_prompt:] for ids in encoded]
        longest = max(len(ids) for ids in encoded)
        s = _next_bucket(longest, self.config.seq_buckets)
        s = min(s, self.cfg.max_seq_len)
        b = _next_bucket(len(encoded), self.config.batch_buckets)
        tokens = np.full((b, s), tok.pad_id, np.int32)
        lengths = np.zeros((b,), np.int32)
        for i, ids in enumerate(encoded):
            tokens[i, : len(ids)] = ids
            lengths[i] = len(ids)
        # Dummy pad rows get length 1 so gather/clip stay in range.
        lengths[len(encoded) :] = 1
        return tokens, lengths, len(encoded)

    def generate_texts(
        self,
        prompts: list[str],
        temperatures: list[float] | None = None,
        seed: int = 0,
        max_new_tokens: int | None = None,
        sampler: SamplerConfig | None = None,
    ) -> list[EngineResult]:
        """Generate one completion per prompt.

        One device program per chunk of ``batch_buckets[-1]`` prompts;
        most calls fit a single chunk. ``sampler`` overrides the engine's
        default top-k/top-p config for this call.
        """
        if not prompts:
            return []
        chunk = self.config.batch_buckets[-1]
        if len(prompts) > chunk:
            out: list[EngineResult] = []
            for i in range(0, len(prompts), chunk):
                temps_i = (
                    temperatures[i : i + chunk]
                    if temperatures is not None
                    else None
                )
                out.extend(
                    self.generate_texts(
                        prompts[i : i + chunk],
                        temperatures=temps_i,
                        seed=seed + i,
                        max_new_tokens=max_new_tokens,
                        sampler=sampler,
                    )
                )
            return out
        tokens, lengths, n_real = self._prepare(prompts)
        b = tokens.shape[0]
        temps = np.zeros((b,), np.float32)
        if temperatures is not None:
            temps[:n_real] = np.asarray(temperatures, np.float32)
        mnt = max_new_tokens or self.config.max_new_tokens
        # Clamp so prompt + generation fits the model context.
        mnt = max(1, min(mnt, self.cfg.max_seq_len - tokens.shape[1]))

        out: GenerateOutput = generate(
            self.cfg,
            self.params,
            jnp.asarray(tokens),
            jnp.asarray(lengths),
            jax.random.PRNGKey(seed),
            jnp.asarray(temps),
            max_new_tokens=mnt,
            sampler=sampler if sampler is not None else self.config.sampler,
            eos_id=self.tokenizer.eos_id,
            pad_id=self.tokenizer.pad_id,
        )
        toks = np.asarray(out.tokens)
        nums = np.asarray(out.num_tokens)
        lps = np.asarray(out.logprob_sum)

        results = []
        for i in range(n_real):
            n = int(nums[i])
            ids = [int(t) for t in toks[i, :n] if t != self.tokenizer.eos_id]
            results.append(
                EngineResult(
                    text=self.tokenizer.decode(ids),
                    num_tokens=n,
                    logprob=float(lps[i]),
                    token_ids=ids,
                )
            )
        return results
