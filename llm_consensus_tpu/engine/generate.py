"""Batched generation: prefill + a ``lax.scan`` decode loop.

This is the hot loop of the whole framework — the TPU-native equivalent of
the reference's per-step remote call (``src/main.rs:82-86``), restructured
so a whole panel evaluation round or an N-way self-consistency fan-out is
ONE device program:

- prompts are right-padded into a [B, S] batch (B = panel size x
  candidates = the data-parallel axis of the mesh);
- ``prefill`` fills the KV cache and yields last-token logits;
- the decode loop is ``lax.scan`` over ``max_new_tokens`` static steps —
  no data-dependent Python control flow; early termination is a ``done``
  mask (rows that hit EOS keep stepping but emit pad and stop
  accumulating logprobs). XLA compiles one step body once.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial

import jax
import jax.numpy as jnp

from llm_consensus_tpu.engine.sampler import SamplerConfig, sample_token
from llm_consensus_tpu.models.cache import KVCache, QuantKVCache
from llm_consensus_tpu.models.configs import ModelConfig
from llm_consensus_tpu.models.transformer import (
    decode_step,
    prefill,
    prefill_chunked,
)


def _broadcast_cache(cache1, b: int):
    """Broadcast a B=1 cache's buffers to B rows (length included)."""

    def bc(x):
        return jnp.broadcast_to(x, (x.shape[0], b, *x.shape[2:]))

    if isinstance(cache1, QuantKVCache):
        return QuantKVCache(
            k_q=bc(cache1.k_q),
            v_q=bc(cache1.v_q),
            k_scale=bc(cache1.k_scale),
            v_scale=bc(cache1.v_scale),
            length=jnp.broadcast_to(cache1.length, (b,)),
        )
    return KVCache(
        k=bc(cache1.k),
        v=bc(cache1.v),
        length=jnp.broadcast_to(cache1.length, (b,)),
    )


@jax.tree_util.register_dataclass
@dataclass
class GenerateOutput:
    tokens: jnp.ndarray  # [B, max_new_tokens] int32, pad-filled after EOS
    num_tokens: jnp.ndarray  # [B] int32 generated tokens incl. EOS
    logprob_sum: jnp.ndarray  # [B] float32 sum of sampled-token logprobs


@partial(
    jax.jit,
    static_argnames=(
        "cfg",
        "max_new_tokens",
        "sampler",
        "eos_id",
        "pad_id",
        "cache_len",
        "shared_prefill",
        "kv_quant",
        "mesh",  # hashable; trace-time constant for the ring routing
        "prefill_chunk",
        "stop_ids",
        "shared_prefix_attention",
    ),
)
def generate(
    cfg: ModelConfig,
    params: dict,
    tokens: jnp.ndarray,
    lengths: jnp.ndarray,
    key: jax.Array,
    temperature: jnp.ndarray,
    *,
    max_new_tokens: int,
    sampler: SamplerConfig = SamplerConfig(),
    eos_id: int = 2,
    pad_id: int = 0,
    cache_len: int | None = None,
    shared_prefill: bool = False,
    kv_quant: bool = False,
    mesh=None,
    prefill_chunk: int = 0,
    stop_ids: tuple[int, ...] = (),
    shared_prefix_attention: bool = True,
) -> GenerateOutput:
    """Generate up to ``max_new_tokens`` for a batch of right-padded prompts.

    tokens: [B, S] int32 right-padded prompts; lengths: [B] true lengths;
    key: PRNG key (folded per decode step; rows draw independent samples
    from the batched categorical); temperature: [B] per-row (0 = greedy).

    ``shared_prefix_attention`` (static, default on): under
    ``shared_prefill`` every row's cache holds the SAME prompt K/V in
    slots [0, prompt_len) — the decode loop then reads that region once
    per step for the whole batch through the two-phase shared-prefix
    kernels (S + N*suffix HBM traffic instead of N*S) with an exact
    log-sum-exp merge. Off = the ungrouped row kernels (the A/B
    baseline; outputs identical). Only the single-chip Pallas
    non-windowed decode paths engage either way.
    """
    b, s = tokens.shape
    if cache_len is None:
        cache_len = s + max_new_tokens
    if cache_len < s + max_new_tokens:
        raise ValueError(
            f"cache_len {cache_len} < prompt {s} + max_new_tokens {max_new_tokens}"
        )

    logits, cache = _prefill_into_cache(
        cfg, params, tokens, lengths,
        cache_len=cache_len,
        shared_prefill=shared_prefill,
        kv_quant=kv_quant,
        mesh=mesh,
        prefill_chunk=prefill_chunk,
    )

    return _decode_loop(
        cfg,
        params,
        logits,
        cache,
        key,
        temperature,
        sampler=sampler,
        eos_id=eos_id,
        pad_id=pad_id,
        max_new_tokens=max_new_tokens,
        uniform_write=shared_prefill,
        stop_ids=stop_ids,
        # All rows share the prompt's K/V in [0, lengths[0]) — read it
        # once per step for the whole fan-out (N is where the KV term
        # of the decode roofline lives).
        shared_prefix_len=(
            lengths[0] if shared_prefill and shared_prefix_attention else None
        ),
    )


def _prefill_into_cache(
    cfg: ModelConfig,
    params: dict,
    tokens: jnp.ndarray,
    lengths: jnp.ndarray,
    *,
    cache_len: int,
    shared_prefill: bool = False,
    kv_quant: bool = False,
    mesh=None,
    prefill_chunk: int = 0,
):
    """The prefill half of :func:`generate`: allocate the cache, fill it,
    return (first-token logits [B, V], cache at B rows).

    Shared between :func:`generate`'s one-shot program and the engine's
    chunked-decode path (multi-token stop sequences need host checks
    between device calls, so prefill and decode must be separable)."""
    b = tokens.shape[0]
    make_cache = QuantKVCache.create if kv_quant else KVCache.create

    def _prefill(p_tokens, p_lengths, p_cache):
        # Chunked prefill (bounded activation memory for long prompts)
        # applies when the prompt exceeds the chunk; exactness-tested
        # against the one-shot path (bit-equal on the bf16 cache; int8
        # rounding-bounded on the quant cache, whose chunk scatter
        # quantizes at the same per-(token, head) granularity as the
        # one-shot write). A seq-mesh (ring attention) takes
        # precedence: the ring IS the long-context memory strategy
        # there, and the chunk pass has no sequence-parallel path.
        if (
            prefill_chunk > 0
            and p_tokens.shape[1] > prefill_chunk
            and mesh is None
        ):
            return prefill_chunked(
                cfg, params, p_tokens, p_lengths, p_cache,
                chunk=prefill_chunk,
            )
        return prefill(cfg, params, p_tokens, p_lengths, p_cache, mesh=mesh)

    if shared_prefill:
        # Self-consistency fan-out: all B rows decode from the SAME
        # prompt, so prefill once at B=1 and broadcast the cache — saves
        # (B-1)/B of the prefill FLOPs (BASELINE.json's N-way configs).
        cache1 = make_cache(cfg, 1, cache_len)
        logits1, cache1 = _prefill(tokens[:1], lengths[:1], cache1)
        logits = jnp.broadcast_to(logits1, (b, logits1.shape[-1]))
        cache = _broadcast_cache(cache1, b)
    else:
        cache = make_cache(cfg, b, cache_len)
        logits, cache = _prefill(tokens, lengths, cache)
    return logits, cache


prefill_into_cache = partial(
    jax.jit,
    static_argnames=(
        "cfg",
        "cache_len",
        "shared_prefill",
        "kv_quant",
        "mesh",
        "prefill_chunk",
    ),
)(_prefill_into_cache)


def _terminal_matcher(eos_id: int, stop_ids: tuple[int, ...]):
    """Token-level termination predicate shared by the batch decode loop
    and the streaming chunk loop — the semantics live in one place."""
    terminal = (eos_id,) + tuple(stop_ids)

    def _is_terminal(tok):
        hit = tok == terminal[0]
        for t in terminal[1:]:
            hit = hit | (tok == t)
        return hit

    return _is_terminal


def _decode_loop(
    cfg: ModelConfig,
    params: dict,
    logits: jnp.ndarray,
    cache,
    key: jax.Array,
    temperature: jnp.ndarray,
    *,
    sampler: SamplerConfig,
    eos_id: int,
    pad_id: int,
    max_new_tokens: int,
    uniform_write: bool,
    stop_ids: tuple[int, ...] = (),
    shared_prefix_len=None,
) -> GenerateOutput:
    """The shared lax.scan decode loop, from first-token logits onward.

    ``stop_ids`` (static): extra single-token terminators — a row that
    samples any of them finishes exactly as if it sampled EOS (the stop
    token is still emitted/counted, like EOS). Used by the engine for
    single-token stop sequences so finished rows stop burning steps'
    logprob accumulation and the host can trim deterministically.

    ``shared_prefix_len`` (traced scalar or None): the length of the
    identical-across-rows cache prefix — threaded into every decode
    step so the shared-prefix kernels read the common KV once per step
    (see :func:`~llm_consensus_tpu.models.transformer.decode_step`).
    """
    b = logits.shape[0]
    _is_terminal = _terminal_matcher(eos_id, stop_ids)

    key0 = jax.random.fold_in(key, 0)
    tok0, lp0 = sample_token(logits, key0, temperature, sampler)
    done0 = _is_terminal(tok0)
    # Logprob of a sampled token counts even if that token is EOS.
    carry0 = (tok0, cache, done0, lp0)

    def step(carry, i):
        tok, cache, done, lp_sum = carry
        # Uniform write: every row has the same fill length forever
        # (all start equal, all advance by one each step), so the cache
        # write can be a slice update instead of a scatter.
        logits, cache = decode_step(
            cfg, params, tok[:, None], cache, uniform_write=uniform_write,
            shared_prefix_len=shared_prefix_len,
        )
        step_key = jax.random.fold_in(key, i + 1)
        next_tok, lp = sample_token(logits, step_key, temperature, sampler)
        next_tok = jnp.where(done, pad_id, next_tok)
        lp_sum = lp_sum + jnp.where(done, 0.0, lp)
        next_done = done | _is_terminal(next_tok)
        # Emitted token for this scan slot is the PREVIOUS carry token:
        # slot i holds the (i+1)-th generated token.
        return (next_tok, cache, next_done, lp_sum), (next_tok, done)

    if max_new_tokens > 1:
        (tok_last, _, _, lp_sum), (toks, dones) = jax.lax.scan(
            step, carry0, jnp.arange(max_new_tokens - 1)
        )
        # [B, T]: first sampled token then the scanned ones.
        all_toks = jnp.concatenate([tok0[:, None], toks.T], axis=1)
        all_done_before = jnp.concatenate(
            [jnp.zeros((b, 1), bool), dones.T], axis=1
        )
    else:
        lp_sum = lp0
        all_toks = tok0[:, None]
        all_done_before = jnp.zeros((b, 1), bool)

    num = jnp.sum(~all_done_before, axis=1).astype(jnp.int32)
    all_toks = jnp.where(all_done_before, pad_id, all_toks)
    return GenerateOutput(
        tokens=all_toks, num_tokens=num, logprob_sum=lp_sum
    )


@partial(
    jax.jit,
    static_argnames=(
        "cfg",
        "max_new_tokens",
        "sampler",
        "eos_id",
        "pad_id",
        "cache_len",
        "stop_ids",
        "shared_suffix",
        "kv_quant",
        "moe_suffix_dense",
        "shared_prefix_attention",
    ),
)
def generate_from_prefix(
    cfg: ModelConfig,
    params: dict,
    prefix_k: jnp.ndarray,
    prefix_v: jnp.ndarray,
    prefix_len: jnp.ndarray,
    tokens: jnp.ndarray,
    lengths: jnp.ndarray,
    key: jax.Array,
    temperature: jnp.ndarray,
    *,
    max_new_tokens: int,
    sampler: SamplerConfig = SamplerConfig(),
    eos_id: int = 2,
    pad_id: int = 0,
    cache_len: int | None = None,
    stop_ids: tuple[int, ...] = (),
    shared_suffix: bool = False,
    kv_quant: bool = False,
    moe_suffix_dense: bool | None = None,
    shared_prefix_attention: bool = True,
) -> GenerateOutput:
    """Generate continuing from a prefilled shared prompt prefix.

    The TPU-native counterpart of radix/prefix caching in GPU servers:
    a prompt prefix shared by many calls (few-shot headers, a debate's
    question+transcript, consensus rubric preambles) is prefilled ONCE
    at B=1 — its per-layer K/V (``prefix_k``/``prefix_v``,
    [L, 1, P, Hkv, Dh] from :class:`~llm_consensus_tpu.models.cache.KVCache`)
    is then broadcast into every later batch instead of being recomputed.
    This program:

    1. allocates a fresh [B, cache_len] bf16 cache and copies the prefix
       into slots [0, Pb) of every row (a broadcast + slice update — pure
       HBM traffic, no FLOPs);
    2. runs the per-row suffixes ([B, S] right-padded ``tokens`` with
       true ``lengths``) through one chunk forward at position offset
       ``prefix_len`` (:func:`~llm_consensus_tpu.models.transformer.decode_chunk`
       semantics — each suffix token attends the prefix plus its chunk
       prefix);
    3. decodes with the shared scan loop.

    ``prefix_k``/``prefix_v`` may be right-padded past the true prefix:
    their static width Pb is a BUCKET, and ``prefix_len`` (traced [],
    int32) is the real token count — so distinct headers of similar
    length share one compiled program instead of recompiling per prefix
    length. Pad-slot garbage in [prefix_len, Pb) is never attended
    (valid-length masking) and is progressively overwritten by decode
    writes, the same convention as prefill padding.

    Exactness-tested against :func:`generate` on the concatenated
    prompts (bf16 cache; the ``kv_quant`` path matches to within int8 KV
    rounding — the same rounding the plain quant path pays). On a mesh
    the batch axes shard over ``data`` by GSPMD propagation from the
    engine-placed inputs; the B=1 prefix replicates and broadcasts into
    the sharded cache.

    ``kv_quant`` (static): continue into an int8 head-major
    :class:`~llm_consensus_tpu.models.cache.QuantKVCache` — the stored
    bf16 prefix K/V is quantized on entry with the SAME per-(token,
    head) rule prefill itself uses, so the cache holds identical int8
    values to a from-scratch quant prefill of the prefix.

    ``moe_suffix_dense`` (static): the MoE dispatch-path choice for the
    suffix chunk (dense fallback when True, capacity when False),
    resolved by the caller from the count a plain one-shot prefill of
    the CONCATENATED prompt traces (batch x seq-bucket of the true
    concat length) — the bucketed ``prefix_k`` width can overshoot the
    threshold that count sits under. A BOOLEAN rather than the raw
    length so the jit cache stays bucket-bounded (a length int would
    compile one program per distinct header size). ``None`` falls back
    to deciding from the bucket width; engines pass it only for
    capacity-routed MoE configs.
    """
    b, s = tokens.shape
    p = prefix_k.shape[2]  # bucket width Pb >= real prefix_len
    if cache_len is None:
        cache_len = p + s + max_new_tokens
    if cache_len < p + s + max_new_tokens:
        raise ValueError(
            f"cache_len {cache_len} < prefix bucket {p} + suffix {s} "
            f"+ max_new_tokens {max_new_tokens}"
        )

    logits, cache = _prefix_prefill_impl(
        cfg, params, prefix_k, prefix_v, prefix_len, tokens, lengths,
        cache_len=cache_len,
        shared_suffix=shared_suffix,
        kv_quant=kv_quant,
        moe_suffix_dense=moe_suffix_dense,
    )

    return _decode_loop(
        cfg,
        params,
        logits,
        cache,
        key,
        temperature,
        sampler=sampler,
        eos_id=eos_id,
        pad_id=pad_id,
        max_new_tokens=max_new_tokens,
        # Shared suffix => every row starts at the same fill length, so
        # decode cache writes compile to slice updates, not scatters.
        uniform_write=shared_suffix,
        stop_ids=stop_ids,
        # Under shared_suffix, prefix AND suffix chunk are identical
        # across rows: the whole prefilled region [0, plen + suffix)
        # reads once per decode step.
        shared_prefix_len=(
            jnp.asarray(prefix_len, jnp.int32) + lengths[0]
            if shared_suffix and shared_prefix_attention
            else None
        ),
    )


def _prefix_prefill_impl(
    cfg: ModelConfig,
    params: dict,
    prefix_k: jnp.ndarray,
    prefix_v: jnp.ndarray,
    prefix_len: jnp.ndarray,
    tokens: jnp.ndarray,
    lengths: jnp.ndarray,
    *,
    cache_len: int,
    shared_suffix: bool = False,
    kv_quant: bool = False,
    moe_suffix_dense: bool | None = None,
):
    """Steps 1-2 of :func:`generate_from_prefix` (copy prefix K/V into a
    fresh cache, run the suffix chunk): returns (first-token logits
    [B, V], cache at B rows). Shared with the engine's chunked-decode
    path so multi-token stop sequences get host checks on the
    prefix-cached workload too."""
    from llm_consensus_tpu.models.cache import quantize_kv
    from llm_consensus_tpu.models.transformer import _chunk_hidden, _unembed

    b, s = tokens.shape
    # shared_suffix (static): all B rows carry the SAME suffix (N-way
    # self-consistency fan-out) — run the suffix chunk once at B=1 and
    # broadcast, like generate()'s shared_prefill.
    cb = 1 if shared_suffix else b
    # Align the suffix chunk's MoE dispatch path with the one a plain
    # one-shot prefill of the CONCATENATED prompt would trace at this
    # batch: generate_from_prefix is exactness-tested against
    # generate(), and the prefix+suffix split must not flip the suffix
    # onto the other side of the trace-time dense fallback. The engine
    # resolves the choice from the count plain itself traces (batch x
    # seq-bucket of the true concat length) and passes it as
    # ``moe_suffix_dense``: the pow2 BUCKET width prefix_k.shape[2] can
    # overshoot ``moe_dense_decode_tokens`` for a prompt whose concat
    # bucket sits under it, pinning capacity where plain ran dense —
    # a real numeric divergence whenever capacity binds (tested in
    # test_engine.py::test_engine_prefix_moe_straddles_dense_threshold).
    # Remaining approximation, near the threshold only: on the capacity
    # side, per-program capacity still drops differently than one-shot
    # (ModelConfig.moe_pin_for). At generous capacity factors the
    # contract is bitwise.
    total = cb * (prefix_k.shape[2] + s)
    if moe_suffix_dense is None:
        cfg = cfg.moe_pin_for(total, total)  # bucket-width fallback
    elif moe_suffix_dense:
        cfg = cfg.with_moe_dense_up_to(total)
    else:
        cfg = cfg.with_moe_capacity_pinned()
    plen = jnp.asarray(prefix_len, jnp.int32)
    if kv_quant:
        qcache = QuantKVCache.create(cfg, cb, cache_len)
        kq, ks = quantize_kv(prefix_k)  # [L,1,P,H,D] / [L,1,P,H]
        vq, vs = quantize_kv(prefix_v)
        # Sequence-major -> the quant cache's head-major layout.
        kq, vq = kq.transpose(0, 1, 3, 2, 4), vq.transpose(0, 1, 3, 2, 4)
        ks, vs = ks.transpose(0, 1, 3, 2), vs.transpose(0, 1, 3, 2)

        def bc(x):
            return jnp.broadcast_to(x, (x.shape[0], cb, *x.shape[2:]))

        z5 = (0, 0, 0, 0, 0)
        cache = QuantKVCache(
            k_q=jax.lax.dynamic_update_slice(qcache.k_q, bc(kq), z5),
            v_q=jax.lax.dynamic_update_slice(qcache.v_q, bc(vq), z5),
            k_scale=jax.lax.dynamic_update_slice(
                qcache.k_scale, bc(ks), (0, 0, 0, 0)
            ),
            v_scale=jax.lax.dynamic_update_slice(
                qcache.v_scale, bc(vs), (0, 0, 0, 0)
            ),
            length=jnp.full((cb,), 1, jnp.int32) * plen,
        )
    else:
        cache = KVCache.create(cfg, cb, cache_len, dtype=prefix_k.dtype)
        kb = jnp.broadcast_to(
            prefix_k, (prefix_k.shape[0], cb, *prefix_k.shape[2:])
        )
        vb = jnp.broadcast_to(
            prefix_v, (prefix_v.shape[0], cb, *prefix_v.shape[2:])
        )
        cache = KVCache(
            k=jax.lax.dynamic_update_slice(cache.k, kb, (0, 0, 0, 0, 0)),
            v=jax.lax.dynamic_update_slice(cache.v, vb, (0, 0, 0, 0, 0)),
            length=jnp.full((cb,), 1, jnp.int32) * plen,
        )

    hidden, cache = _chunk_hidden(cfg, params, tokens[:cb], cache)
    last = jnp.clip(lengths[:cb] - 1, 0, s - 1)
    x_last = hidden[jnp.arange(cb), last]  # [cb, D]
    logits = _unembed(cfg, params, x_last)
    if shared_suffix:
        logits = jnp.broadcast_to(logits, (b, logits.shape[-1]))
        cache = _broadcast_cache(cache, b).with_length(plen + lengths)
    else:
        # Suffix padding slots hold garbage k/v past each row's true
        # length — masked out of decode attention and progressively
        # overwritten, the same convention as prefill padding.
        cache = cache.with_length(plen + lengths)
    return logits, cache


prefill_from_prefix = partial(
    jax.jit,
    static_argnames=(
        "cfg", "cache_len", "shared_suffix", "kv_quant", "moe_suffix_dense",
    ),
)(_prefix_prefill_impl)


@partial(
    jax.jit,
    static_argnames=("cfg", "steps", "sampler", "eos_id", "pad_id", "stop_ids"),
    donate_argnames=("cache",),
)
def decode_steps(
    cfg: ModelConfig,
    params: dict,
    cache,
    tok: jnp.ndarray,
    done: jnp.ndarray,
    key: jax.Array,
    temperature: jnp.ndarray,
    *,
    steps: int,
    sampler: SamplerConfig = SamplerConfig(),
    eos_id: int = 2,
    pad_id: int = 0,
    stop_ids: tuple[int, ...] = (),
):
    """Run ``steps`` decode iterations from an existing cache (streaming).

    The incremental sibling of :func:`generate`'s scan: the caller holds
    the cache across calls and consumes tokens chunk by chunk (REPL
    streaming, interactive serving). ``tok`` [B] is the last sampled
    token (already written? NO — not yet attended; it is fed as this
    chunk's first input), ``done`` [B] the rows already terminated.
    The cache argument is DONATED — the caller must replace its handle
    with the returned cache.

    Returns (tokens [B, steps] — pad after termination, live [B, steps]
    — True where the row was still generating when the slot was emitted
    (distinguishes post-termination padding from a genuinely sampled
    pad id), new_cache, new_done, new_tok, logprobs [B, steps] — the
    PER-STEP sampled-token logprobs, zero where the row was already
    done; callers that consume only a k-step prefix of the chunk sum
    ``lps[:, :k]`` so tail-chunk overshoot never leaks into accounting).
    """
    _is_terminal = _terminal_matcher(eos_id, stop_ids)

    def step(carry, i):
        tok, cache, done = carry
        logits, cache = decode_step(cfg, params, tok[:, None], cache)
        step_key = jax.random.fold_in(key, i)
        nxt, lp_i = sample_token(logits, step_key, temperature, sampler)
        nxt = jnp.where(done, pad_id, nxt)
        lp_i = jnp.where(done, 0.0, lp_i)
        next_done = done | _is_terminal(nxt)
        return (nxt, cache, next_done), (nxt, done, lp_i)

    (tok_n, cache, done_n), (toks, dones, lps) = jax.lax.scan(
        step, (tok, cache, done), jnp.arange(steps)
    )
    out = jnp.where(dones.T, pad_id, toks.T)  # [B, steps]
    return out, ~dones.T, cache, done_n, tok_n, lps.T


@partial(
    jax.jit,
    static_argnames=("cfg", "cache_len"),
)
def score_completions(
    cfg: ModelConfig,
    params: dict,
    prompt_tokens: jnp.ndarray,
    prompt_len: jnp.ndarray,
    comp_tokens: jnp.ndarray,
    comp_lens: jnp.ndarray,
    *,
    cache_len: int,
):
    """Teacher-forced log-probability of completions under the model.

    prompt_tokens: [1, S] right-padded shared prompt; prompt_len: [1];
    comp_tokens: [B, K] right-padded completions; comp_lens: [B].
    The prompt prefills ONCE at B=1, its cache broadcasts to the B
    completions, and all K completion positions score in one ragged
    chunk forward (:func:`~llm_consensus_tpu.models.transformer.decode_chunk`
    semantics) — no sampling, no decode loop. Returns (logprob_sum [B],
    per-token logprobs [B, K] — zero past each completion's length).

    The scoring half of candidate aggregation: logit pooling / weighted
    reranking over candidates that were produced elsewhere (another
    model of a heterogeneous panel, a debate round, a human draft).
    """
    from llm_consensus_tpu.models.transformer import decode_chunk

    b, k = comp_tokens.shape

    cache1 = KVCache.create(cfg, 1, cache_len)
    logits1, cache1 = prefill(cfg, params, prompt_tokens, prompt_len, cache1)
    cache = _broadcast_cache(cache1, b)

    chunk_logits, _ = decode_chunk(cfg, params, comp_tokens, cache)
    # Position i of the chunk predicts token i+1; the prompt's last
    # logits predict token 0.
    all_logits = jnp.concatenate(
        [
            jnp.broadcast_to(logits1, (b, logits1.shape[-1]))[:, None],
            chunk_logits[:, :-1].astype(jnp.float32),
        ],
        axis=1,
    )  # [B, K, V]
    lps = jax.nn.log_softmax(all_logits, axis=-1)
    tok_lp = jnp.take_along_axis(
        lps, comp_tokens[..., None].astype(jnp.int32), axis=-1
    )[..., 0]  # [B, K]
    mask = jnp.arange(k)[None, :] < comp_lens[:, None]
    tok_lp = jnp.where(mask, tok_lp, 0.0)
    return tok_lp.sum(axis=1), tok_lp
