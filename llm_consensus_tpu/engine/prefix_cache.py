"""Host-side LRU of prefilled prompt prefixes (radix-style KV reuse).

The consensus protocol re-sends the same prompt material constantly:
every GSM8K problem shares the few-shot/instruction header, every debate
round re-prefixes the question + transcript, an EM-vs-N sweep prefill's
the identical prompt once per N. The reference pays a full remote call
each time (``src/main.rs:82-86``); a local engine can do better — prefill
a shared prefix ONCE at B=1, keep its per-layer K/V on device, and let
:func:`llm_consensus_tpu.engine.generate.generate_from_prefix` broadcast
it into every later batch.

This module is the host bookkeeping only: an LRU keyed by the exact
token-id tuple of the prefix, holding B=1 bf16 ``(k, v)`` buffers
([L, 1, P, Hkv, Dh]) that live in device HBM. Eviction frees HBM via the
normal jax buffer GC. Capacity is bounded both by entry count and by a
byte budget so a long-header workload cannot silently eat the cache
memory the decode batch needs.
"""

from __future__ import annotations

from collections import OrderedDict
from dataclasses import dataclass

import jax.numpy as jnp


def _entry_bytes(k: jnp.ndarray, v: jnp.ndarray) -> int:
    return k.size * k.dtype.itemsize + v.size * v.dtype.itemsize


@dataclass
class PrefixCacheStats:
    hits: int = 0
    misses: int = 0
    evictions: int = 0

    @property
    def hit_rate(self) -> float:
        total = self.hits + self.misses
        return self.hits / total if total else 0.0


class PrefixCache:
    """LRU: token-id tuple -> (k, v) device buffers of a prefilled prefix."""

    def __init__(self, max_entries: int = 8, max_bytes: int = 1 << 30):
        if max_entries < 1:
            raise ValueError("max_entries must be >= 1")
        self.max_entries = max_entries
        self.max_bytes = max_bytes
        self._entries: OrderedDict[tuple[int, ...], tuple] = OrderedDict()
        self._bytes = 0
        self.stats = PrefixCacheStats()

    def __len__(self) -> int:
        return len(self._entries)

    @property
    def nbytes(self) -> int:
        return self._bytes

    def get(self, key: tuple[int, ...]):
        """(k, v) for the prefix, or None. Refreshes LRU order on hit."""
        entry = self._entries.get(key)
        if entry is None:
            self.stats.misses += 1
            return None
        self._entries.move_to_end(key)
        self.stats.hits += 1
        return entry

    def put(self, key: tuple[int, ...], k: jnp.ndarray, v: jnp.ndarray):
        """Insert a prefilled prefix; evicts LRU entries over budget."""
        size = _entry_bytes(k, v)
        if key in self._entries:
            old = self._entries.pop(key)
            self._bytes -= _entry_bytes(*old)
        self._entries[key] = (k, v)
        self._bytes += size
        while len(self._entries) > self.max_entries or (
            self._bytes > self.max_bytes and len(self._entries) > 1
        ):
            _, (ek, ev) = self._entries.popitem(last=False)
            self._bytes -= _entry_bytes(ek, ev)
            self.stats.evictions += 1

    def clear(self) -> None:
        # Dropped entries count as evictions so stats stay consistent
        # with observable cache history (hit_rate/evictions after a
        # clear must reflect that entries were freed, not lost).
        self.stats.evictions += len(self._entries)
        self._entries.clear()
        self._bytes = 0
