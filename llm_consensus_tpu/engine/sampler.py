"""On-device token sampling: greedy, temperature, top-k, top-p.

Reference counterpart: none — sampling happens inside the remote Gemini
service (``src/main.rs:82-86``). For self-consistency fan-out
(BASELINE.json configs, N up to 64) the sampler runs *on device inside the
compiled decode loop*: per-candidate PRNG keys live on the batch axis, so
one ``lax.scan`` step samples all N candidates.

XLA-first constraints honored here:
- ``top_k``/``top_p`` are **static** (part of the compiled program);
  per-example *temperature* is dynamic data ([B] array). temperature == 0
  selects greedy via ``jnp.where`` — no control flow on data.
- Everything is shape-static: top-p uses a sorted-scan mask, not dynamic
  slicing.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp

_NEG_INF = -1e30


@dataclass(frozen=True)
class SamplerConfig:
    """Static (compile-time) sampler configuration."""

    top_k: int = 0  # 0 => disabled
    top_p: float = 1.0  # 1.0 => disabled


def _apply_top_k(logits: jnp.ndarray, k: int) -> jnp.ndarray:
    """Mask all but the k highest logits. k is static."""
    if k <= 0 or k >= logits.shape[-1]:
        return logits
    vals, _ = jax.lax.top_k(logits, k)
    kth = vals[..., -1:]
    return jnp.where(logits < kth, _NEG_INF, logits)


def _apply_top_p(logits: jnp.ndarray, p: float) -> jnp.ndarray:
    """Nucleus filtering: keep the smallest prefix of the sorted
    distribution with cumulative probability >= p. p is static."""
    if p >= 1.0:
        return logits
    sorted_logits = jnp.sort(logits, axis=-1)[..., ::-1]
    sorted_probs = jax.nn.softmax(sorted_logits, axis=-1)
    cum = jnp.cumsum(sorted_probs, axis=-1)
    # Keep entries whose *preceding* cumulative mass is < p (so the first
    # token crossing the threshold is still kept).
    keep_sorted = (cum - sorted_probs) < p
    # Find the minimum kept logit; anything below it is masked.
    min_kept = jnp.min(
        jnp.where(keep_sorted, sorted_logits, jnp.inf), axis=-1, keepdims=True
    )
    return jnp.where(logits < min_kept, _NEG_INF, logits)


def sample_token(
    logits: jnp.ndarray,
    key: jax.Array,
    temperature: jnp.ndarray,
    config: SamplerConfig = SamplerConfig(),
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Sample one token per row.

    logits: [B, V] float32; key: single PRNG key (folded per step by the
    caller); temperature: [B] (0 => greedy for that row).

    Returns (tokens [B] int32, logprobs [B] float32) where logprobs are the
    log-probability of the sampled token under the *pre-filtering*
    temperature-scaled distribution (usable for logit-pooled vote
    aggregation, BASELINE.json north star).
    """
    b = logits.shape[0]
    temperature = jnp.asarray(temperature, jnp.float32)
    greedy_tok = jnp.argmax(logits, axis=-1).astype(jnp.int32)

    # Temperature-scale with a safe divisor for greedy rows.
    safe_t = jnp.where(temperature > 0, temperature, 1.0)[:, None]
    scaled = logits / safe_t
    filtered = _apply_top_p(_apply_top_k(scaled, config.top_k), config.top_p)
    sampled_tok = jax.random.categorical(key, filtered, axis=-1).astype(jnp.int32)

    tok = jnp.where(temperature > 0, sampled_tok, greedy_tok)

    logprobs_full = jax.nn.log_softmax(scaled, axis=-1)
    logprob = logprobs_full[jnp.arange(b), tok]
    return tok, logprob


def sample_token_per_row(
    logits: jnp.ndarray,
    keys: jax.Array,
    temperature: jnp.ndarray,
    config: SamplerConfig = SamplerConfig(),
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Like :func:`sample_token` but with an independent PRNG key per row
    (continuous batching: each request owns its stream, so results don't
    depend on which other requests share the batch)."""

    def one(lg, k, t):
        tok, lp = sample_token(lg[None], k, t[None], config)
        return tok[0], lp[0]

    return jax.vmap(one)(
        logits, keys, jnp.asarray(temperature, jnp.float32)
    )


def filter_scaled_logits(
    scaled: jnp.ndarray, top_k: jnp.ndarray, top_p: jnp.ndarray
) -> jnp.ndarray:
    """Per-row top-k + nucleus masking of temperature-scaled logits.

    scaled: [B, V]; top_k [B] int32 (0 = off); top_p [B] f32 (1.0 =
    off). ONE descending sort serves both filters. Extracted from
    :func:`sample_token_per_request` so the speculative verify path
    (:func:`llm_consensus_tpu.engine.accept.verify_tokens`) applies the
    EXACT same filter transform to its per-position target
    distributions — the two consumers cannot drift.
    """
    k = jnp.asarray(top_k, jnp.int32)
    p = jnp.asarray(top_p, jnp.float32)
    v = scaled.shape[-1]
    sorted_desc = jnp.sort(scaled, axis=-1)[..., ::-1]
    # top-k threshold from the shared sort.
    k_eff = jnp.where(k > 0, jnp.clip(k, 1, v), v)
    kth = jnp.take_along_axis(sorted_desc, (k_eff - 1)[:, None], axis=-1)
    filtered = jnp.where(scaled < kth, _NEG_INF, scaled)
    # Nucleus over the top-k-MASKED distribution (sequential
    # semantics, matching _apply_top_p(_apply_top_k(...))): mask by
    # VALUE, not position — the sequential top-k keeps every token
    # TIED at the kth logit, so the nucleus set must include the
    # ties too. The value mask is still a prefix of the descending
    # sort, so one sort serves both filters.
    in_k = sorted_desc >= kth
    sorted_k = jnp.where(in_k, sorted_desc, _NEG_INF)
    sorted_probs = jax.nn.softmax(sorted_k, axis=-1)
    cum = jnp.cumsum(sorted_probs, axis=-1)
    keep_sorted = ((cum - sorted_probs) < p[:, None]) & in_k
    min_kept = jnp.min(
        jnp.where(keep_sorted, sorted_k, jnp.inf),
        axis=-1,
        keepdims=True,
    )
    nucleus = jnp.where(filtered < min_kept, _NEG_INF, filtered)
    return jnp.where(p[:, None] >= 1.0, filtered, nucleus)


def sample_token_per_request(
    logits: jnp.ndarray,
    keys: jax.Array,
    temperature: jnp.ndarray,
    top_k: jnp.ndarray,
    top_p: jnp.ndarray,
    *,
    filters_active: bool = True,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """:func:`sample_token` with per-row keys AND per-row top_k/top_p.

    The continuous batcher's sampler: every slot belongs to a different
    request, so ALL sampler settings ride as data ([B] arrays) and the
    decode-step program never recompiles when a request with new
    settings joins the batch. Matches :func:`sample_token`'s filter and
    logprob semantics row-for-row (logprob is pre-filtering,
    temperature-scaled).

    ``filters_active`` (static): False compiles the filters away
    entirely — the caller knows from its host-side arrays that every
    row has top_k=0 and top_p=1.0 (the common all-defaults workload),
    so the two full-vocab sorts never run. When True, ONE descending
    sort is shared by both filters."""
    b = logits.shape[0]
    temperature = jnp.asarray(temperature, jnp.float32)
    greedy_tok = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    safe_t = jnp.where(temperature > 0, temperature, 1.0)[:, None]
    scaled = logits / safe_t
    if filters_active:
        filtered = filter_scaled_logits(scaled, top_k, top_p)
    else:
        filtered = scaled
    sampled = jax.vmap(
        lambda lg, kk: jax.random.categorical(kk, lg)
    )(filtered, keys).astype(jnp.int32)
    tok = jnp.where(temperature > 0, sampled, greedy_tok)
    logprobs_full = jax.nn.log_softmax(scaled, axis=-1)
    return tok, logprobs_full[jnp.arange(b), tok]


def stop_scan_hit(
    next_tok: jnp.ndarray,
    eos_id: int,
    screen: jnp.ndarray,
    emitted: jnp.ndarray,
    budgets: jnp.ndarray,
) -> jnp.ndarray:
    """Per-row ON-DEVICE stop scan for one multi-round decode round
    (PR 12) — the freeze predicate the batcher's scan body applies
    after sampling each round's token.

    next_tok/emitted/budgets: [B] (the just-sampled token, tokens
    emitted so far in this window INCLUDING it, and the row's
    remaining max-new-tokens budget at dispatch); screen: [B, W] int32
    candidate stop-completing ids per row, -1-padded (the conservative
    :func:`llm_consensus_tpu.utils.stops.derived_stop_screen` — a hit
    is a candidate the host's byte-level check confirms at fetch, so
    over-firing costs rounds, never text). Returns [B] bool: True
    where the row must FREEZE — EOS (exact), a screened candidate
    (conservative), or the max-tokens budget reached (exact at
    pipeline depth 1, an upper bound under retirement lag — the host
    trim discards overshoot either way). EOS and the budget are the
    same rules the host applies per fetched token; keeping all three
    in one predicate is what lets R rounds run between host looks
    without changing what a request observes.
    """
    hit = next_tok == jnp.int32(eos_id)
    hit = hit | jnp.any(screen == next_tok[:, None], axis=1)
    return hit | (emitted >= budgets)
