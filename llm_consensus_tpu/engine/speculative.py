"""Greedy speculative decoding: draft k tokens, verify in one pass.

Decode on TPU is weight-HBM-bound — every sequential step re-reads the
target's weights. Speculative decoding (Leviathan et al.) breaks the
sequential bottleneck: a cheap draft model proposes ``k_spec`` tokens
autoregressively, then the target scores the WHOLE draft in one
:func:`llm_consensus_tpu.models.transformer.decode_chunk` forward and
accepts the longest matching prefix. Accepted tokens cost one target
weight-read per ``k_spec`` instead of one per token.

The ragged KV-cache design makes rollback free: acceptance only sets
``cache.length`` (data, not shape) — rejected tokens' k/v stay as
masked-out garbage past the fill and are overwritten later, exactly
like prefill padding.

v1 scope: greedy only (temperature 0), bf16 caches. The key invariant —
tested in tests/test_speculative.py — is EXACTNESS: output tokens equal
vanilla greedy decode token-for-token for ANY draft model; the draft
only affects speed. (Sampled speculative decoding needs the
accept-with-prob-p(t)/p(d) residual scheme; the verification chunk op
and cache plumbing here are the hard part and are sampling-agnostic.)

The reference has no decoding at all to speed up (remote API,
``src/main.rs:82-86``); this is the TPU build's own perf work past
BASELINE.json's floor.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial

import jax
import jax.numpy as jnp

from llm_consensus_tpu.models.cache import KVCache
from llm_consensus_tpu.models.configs import ModelConfig
from llm_consensus_tpu.models.transformer import (
    decode_chunk,
    decode_step,
    prefill,
)


@jax.tree_util.register_dataclass
@dataclass
class SpecOutput:
    tokens: jnp.ndarray  # [B, max_new_tokens] int32, pad-filled after EOS
    num_tokens: jnp.ndarray  # [B] int32 generated tokens incl. EOS
    rounds: jnp.ndarray  # [] int32 — speculation rounds taken
    drafted: jnp.ndarray  # [] int32 — draft tokens proposed in total
    accepted: jnp.ndarray  # [] int32 — draft tokens accepted in total


@partial(
    jax.jit,
    static_argnames=(
        "cfg_t",
        "cfg_d",
        "max_new_tokens",
        "k_spec",
        "eos_id",
        "pad_id",
        "cache_len",
    ),
)
def speculative_generate(
    cfg_t: ModelConfig,
    params_t: dict,
    cfg_d: ModelConfig,
    params_d: dict,
    tokens: jnp.ndarray,
    lengths: jnp.ndarray,
    *,
    max_new_tokens: int,
    k_spec: int = 4,
    eos_id: int = 2,
    pad_id: int = 0,
    cache_len: int | None = None,
) -> SpecOutput:
    """Greedy speculative decode of right-padded prompts.

    tokens: [B, S] int32; lengths: [B]. The draft (``cfg_d/params_d``)
    must share the target's tokenizer/vocab. Each round: the draft
    proposes ``k_spec`` greedy tokens; the target verifies them with one
    ``decode_chunk`` over ``k_spec + 1`` inputs; the ``n_acc`` leading
    matches are emitted plus one more target token — the correction on a
    mismatch, the FREE bonus token on full acceptance (so a perfect
    round yields ``k_spec + 1`` tokens from one target forward). Every
    round emits >= 1 token, so at most ``max_new_tokens`` rounds run
    (the while_loop is data-dependent — decode stops as soon as every
    row is done).
    """
    b, s = tokens.shape
    if cache_len is None:
        # +k_spec+1 slack: a chunk may write past the last emitted slot.
        cache_len = s + max_new_tokens + k_spec + 1
    if cache_len < s + max_new_tokens + k_spec + 1:
        raise ValueError(f"cache_len {cache_len} too small")

    cache_t = KVCache.create(cfg_t, b, cache_len)
    logits_t, cache_t = prefill(cfg_t, params_t, tokens, lengths, cache_t)
    cache_d = KVCache.create(cfg_d, b, cache_len)
    _, cache_d = prefill(cfg_d, params_d, tokens, lengths, cache_d)

    # First token comes from the target's prefill logits directly.
    tok0 = jnp.argmax(logits_t, axis=-1).astype(jnp.int32)  # [B]
    out0 = jnp.full((b, max_new_tokens), pad_id, jnp.int32)
    out0 = out0.at[:, 0].set(tok0)
    n0 = jnp.ones((b,), jnp.int32)
    done0 = (tok0 == eos_id) | (max_new_tokens <= 1)

    def cond(state):
        _, _, _, _, n_out, done, rounds, _, _ = state
        return jnp.any(~done) & (rounds < max_new_tokens)

    def body(state):
        tok, cache_t, cache_d, out, n_out, done, rounds, drafted, accepted = (
            state
        )
        done_before = done
        len_t0 = cache_t.length
        len_d0 = cache_d.length

        # --- Draft proposes k_spec greedy tokens -----------------------
        def dstep(carry, _):
            x, cd = carry
            lg, cd = decode_step(cfg_d, params_d, x[:, None], cd)
            nxt = jnp.argmax(lg, axis=-1).astype(jnp.int32)
            return (nxt, cd), nxt

        (_, cache_d), drafts = jax.lax.scan(
            dstep, (tok, cache_d), None, length=k_spec
        )
        drafts = drafts.T  # [B, K]
        # One extra draft step consuming d_{K-1}: on full acceptance the
        # bonus token becomes the next input, and the draft cache must
        # then hold d_{K-1}'s k/v (its logits are discarded).
        _, cache_d = decode_step(cfg_d, params_d, drafts[:, -1:], cache_d)

        # --- Target verifies the whole draft in one chunk --------------
        # Chunk inputs: [tok, d_0 .. d_{K-1}] (K+1); logits_j predicts
        # the token after consuming input j, so g_j verifies d_j for
        # j < K, and g_K is the FREE bonus token after a fully accepted
        # draft (Leviathan et al.) — k_spec+1 tokens from one target
        # forward.
        chunk = jnp.concatenate([tok[:, None], drafts], axis=1)
        logits, cache_t = decode_chunk(cfg_t, params_t, chunk, cache_t)
        targets = jnp.argmax(logits, axis=-1).astype(jnp.int32)  # [B, K+1]

        match = drafts == targets[:, :k_spec]  # [B, K]
        acc_mask = jnp.cumprod(match.astype(jnp.int32), axis=1)  # [B, K]
        n_acc = jnp.sum(acc_mask, axis=1)  # [B] in [0, K]

        # Emitted this round: accepted drafts, then the target token at
        # position n_acc — the correction on a mismatch, the bonus on
        # full acceptance. Uniformly n_acc + 1 tokens.
        j = jnp.arange(k_spec + 1)[None, :]
        emit = jnp.where(
            j < n_acc[:, None],
            jnp.pad(drafts, ((0, 0), (0, 1))),
            jnp.where(j == n_acc[:, None], targets, pad_id),
        )  # [B, K+1]
        emit_cnt = n_acc + 1  # [B]

        # EOS inside the round truncates it.
        is_eos = (emit == eos_id) & (j < emit_cnt[:, None])
        any_eos = jnp.any(is_eos, axis=1)
        eos_pos = jnp.argmax(is_eos, axis=1)
        emit_cnt = jnp.where(any_eos, eos_pos + 1, emit_cnt)

        # Rows already done (or out of budget) emit nothing.
        emit_cnt = jnp.where(done, 0, emit_cnt)
        emit_cnt = jnp.minimum(emit_cnt, max_new_tokens - n_out)

        # Scatter into the output buffer at per-row offsets.
        batch = jnp.arange(b)
        new_out = out
        for jj in range(k_spec + 1):  # static, small
            idx = jnp.clip(n_out + jj, 0, max_new_tokens - 1)
            write = jj < emit_cnt
            new_out = new_out.at[batch, idx].set(
                jnp.where(write, emit[:, jj], new_out[batch, idx])
            )

        # Next input token: last emitted (correction or bonus).
        last = jnp.clip(emit_cnt - 1, 0, k_spec)
        tok_next = jnp.where(
            emit_cnt > 0, emit[batch, last], tok
        ).astype(jnp.int32)

        # Cache fills: consumed chunk inputs = emit_cnt (the next input's
        # k/v is not yet written — decode_step convention). Done rows
        # keep their fill.
        consumed = emit_cnt
        cache_t = cache_t.with_length(len_t0 + consumed)
        cache_d = cache_d.with_length(len_d0 + consumed)

        n_out = n_out + emit_cnt
        done = done | any_eos | (n_out >= max_new_tokens)
        drafted = drafted + k_spec * jnp.sum((~done_before).astype(jnp.int32))
        accepted = accepted + jnp.sum(jnp.minimum(n_acc, emit_cnt))
        return (
            tok_next,
            cache_t,
            cache_d,
            new_out,
            n_out,
            done,
            rounds + 1,
            drafted,
            accepted,
        )

    zero = jnp.zeros((), jnp.int32)
    state = (
        tok0,
        cache_t,
        cache_d,
        out0,
        n0,
        done0,
        zero,
        zero,
        zero,
    )
    state = jax.lax.while_loop(cond, body, state)
    _, _, _, out, n_out, _, rounds, drafted, accepted = state
    return SpecOutput(
        tokens=out,
        num_tokens=n_out,
        rounds=rounds,
        drafted=drafted,
        accepted=accepted,
    )
