"""Greedy speculative decoding: draft k tokens, verify in one pass.

Decode on TPU is weight-HBM-bound — every sequential step re-reads the
target's weights. Speculative decoding (Leviathan et al.) breaks the
sequential bottleneck: a cheap draft model proposes ``k_spec`` tokens
autoregressively, then the target scores the WHOLE draft in one
:func:`llm_consensus_tpu.models.transformer.decode_chunk` forward and
accepts the longest matching prefix. Accepted tokens cost one target
weight-read per ``k_spec`` instead of one per token.

The ragged KV-cache design makes rollback free: acceptance only sets
``cache.length`` (data, not shape) — rejected tokens' k/v stay as
masked-out garbage past the fill and are overwritten later, exactly
like prefill padding.

Two modes, both exactness-anchored (tests/test_speculative.py):

- **Greedy** (no ``temperature``): accept while draft == target argmax.
  Output tokens equal vanilla greedy decode token-for-token for ANY
  draft model; the draft only affects speed.
- **Sampled** (``temperature`` + ``key``): Leviathan et al. acceptance —
  accept d with prob min(1, p(d)/q(d)), else resample from the residual
  ``norm(max(p - q, 0))`` (:func:`leviathan_accept`, whose marginal is
  EXACTLY the target distribution — Monte-Carlo-verified). Plain
  temperature scaling; top-k/top-p do not compose with the acceptance
  identity and are not applied here.

The accept rule itself lives in :mod:`llm_consensus_tpu.engine.accept`
(PR 9) so the continuous batcher's on-device verify program shares it
without importing this standalone loop; this module keeps being the
parity oracle the batcher path is pinned against.

bf16 KV caches only (the verification chunk writes ragged per-row
positions; the int8 head-major scatter isn't worth it on this path).

The reference has no decoding at all to speed up (remote API,
``src/main.rs:82-86``); this is the TPU build's own perf work past
BASELINE.json's floor.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial

import jax
import jax.numpy as jnp

from llm_consensus_tpu.engine.accept import leviathan_accept
from llm_consensus_tpu.models.cache import KVCache
from llm_consensus_tpu.models.configs import ModelConfig
from llm_consensus_tpu.models.transformer import (
    decode_chunk,
    decode_step,
    prefill,
)

__all__ = ["SpecOutput", "leviathan_accept", "speculative_generate"]


@jax.tree_util.register_dataclass
@dataclass
class SpecOutput:
    tokens: jnp.ndarray  # [B, max_new_tokens] int32, pad-filled after EOS
    num_tokens: jnp.ndarray  # [B] int32 generated tokens incl. EOS
    # [B] float32 sum of emitted-token logprobs under the target's
    # distribution — same convention as engine.generate (temperature-
    # scaled log_softmax; scale 1 for greedy rows), so logit_pool
    # consumers see equivalent weights on either path.
    logprob_sum: jnp.ndarray
    rounds: jnp.ndarray  # [] int32 — speculation rounds taken
    drafted: jnp.ndarray  # [] int32 — draft tokens proposed in total
    accepted: jnp.ndarray  # [] int32 — draft tokens accepted in total


@partial(
    jax.jit,
    static_argnames=(
        "cfg_t",
        "cfg_d",
        "max_new_tokens",
        "k_spec",
        "eos_id",
        "pad_id",
        "cache_len",
        "mesh",
    ),
)
def speculative_generate(
    cfg_t: ModelConfig,
    params_t: dict,
    cfg_d: ModelConfig,
    params_d: dict,
    tokens: jnp.ndarray,
    lengths: jnp.ndarray,
    *,
    max_new_tokens: int,
    k_spec: int = 4,
    eos_id: int = 2,
    pad_id: int = 0,
    cache_len: int | None = None,
    temperature: jnp.ndarray | None = None,
    key: jax.Array | None = None,
    mesh=None,
) -> SpecOutput:
    """Greedy speculative decode of right-padded prompts.

    tokens: [B, S] int32; lengths: [B]. The draft (``cfg_d/params_d``)
    must share the target's tokenizer/vocab. Each round: the draft
    proposes ``k_spec`` greedy tokens; the target verifies them with one
    ``decode_chunk`` over ``k_spec + 1`` inputs; the ``n_acc`` leading
    matches are emitted plus one more target token — the correction on a
    mismatch, the FREE bonus token on full acceptance (so a perfect
    round yields ``k_spec + 1`` tokens from one target forward). Every
    round emits >= 1 token, so at most ``max_new_tokens`` rounds run
    (the while_loop is data-dependent — decode stops as soon as every
    row is done).

    ``temperature`` ([B], with ``key``) switches to SAMPLED speculative
    decoding: drafts are drawn from the draft's temperature-scaled
    distribution and verified with :func:`leviathan_accept`, whose
    marginal equals direct target sampling exactly. Rows with
    temperature 0 take the greedy accept rule. Plain temperature
    sampling only (no top-k/top-p composition).

    ``mesh`` (static) runs the whole program sharded: the batch axis —
    prompts, both KV caches, and every per-row carry — shards over the
    mesh's ``data`` axis (``partitioning.cache_pspecs`` layout, kv
    heads over ``model``); the caller shards params (target AND draft)
    with ``shard_params``. Draft proposal, chunk verification, and
    acceptance are all per-row ops, so dp adds no collectives beyond
    what the models' own tp shardings insert — output is bit-identical
    to the single-device path (tested).
    """
    b, s = tokens.shape
    # Prefill keeps the CALLER's config: spec's prefill runs the same
    # [B, S] one-shot program shape as the plain path's, so the same
    # cfg yields the same trace-time MoE dispatch choice there. The
    # decode-side programs (draft steps + verify chunks) pin to the
    # path the plain decode step (b tokens) would take — the verify
    # chunk's b*(k_spec+1) tokens could otherwise land on the other
    # side of the dense-fallback threshold and break greedy
    # token-identity with the plain path. The pin aligns the PATH
    # only: on the capacity side, per-program capacity means the
    # verify chunk and the plain step can still drop different tokens
    # when capacity genuinely binds (ModelConfig.moe_pin_for) — greedy
    # identity for capacity-MoE targets holds when nothing drops.
    cfg_t_prefill = cfg_t
    cfg_t = cfg_t.moe_pin_for(b, b * (k_spec + 1))
    if cache_len is None:
        # +k_spec+1 slack: a chunk may write past the last emitted slot.
        cache_len = s + max_new_tokens + k_spec + 1
    if cache_len < s + max_new_tokens + k_spec + 1:
        raise ValueError(f"cache_len {cache_len} too small")

    sampled = temperature is not None
    if sampled and key is None:
        raise ValueError("sampled speculative decoding needs a PRNG key")
    if sampled:
        temperature = jnp.asarray(temperature, jnp.float32)
        t_eff = jnp.maximum(temperature, 1e-6)[:, None]  # [B, 1]
        greedy_row = (temperature <= 0.0)[:, None]  # [B, 1]

    if mesh is not None:
        from jax.sharding import NamedSharding
        from jax.sharding import PartitionSpec as P

        from llm_consensus_tpu.parallel.partitioning import cache_pspecs

        _row = NamedSharding(mesh, P("data"))
        _cache_sh = jax.tree_util.tree_map(
            lambda s: NamedSharding(mesh, s), cache_pspecs()
        )

        def _shard_cache(c):
            return jax.lax.with_sharding_constraint(c, _cache_sh)

        tokens = jax.lax.with_sharding_constraint(
            tokens, NamedSharding(mesh, P("data", None))
        )
        lengths = jax.lax.with_sharding_constraint(lengths, _row)
    else:

        def _shard_cache(c):
            return c

    cache_t = _shard_cache(KVCache.create(cfg_t, b, cache_len))
    logits_t, cache_t = prefill(cfg_t_prefill, params_t, tokens, lengths, cache_t)
    cache_d = _shard_cache(KVCache.create(cfg_d, b, cache_len))
    _, cache_d = prefill(cfg_d, params_d, tokens, lengths, cache_d)

    def _pick(logits2d, k):
        """Per-row token from [B, V] logits: sampled or greedy."""
        greedy = jnp.argmax(logits2d, axis=-1).astype(jnp.int32)
        if not sampled:
            return greedy
        drawn = jax.random.categorical(k, logits2d / t_eff, axis=-1)
        return jnp.where(
            greedy_row[:, 0], greedy, drawn.astype(jnp.int32)
        )

    def _lp_of(logits_nd, toks):
        """Emitted-token logprobs, engine.sampler convention: scale 1
        for greedy (and the no-temperature mode), t elsewhere."""
        if sampled:
            scale = jnp.where(
                temperature > 0, temperature, 1.0
            ).reshape((b,) + (1,) * (logits_nd.ndim - 1))
            logits_nd = logits_nd / scale
        lp = jax.nn.log_softmax(logits_nd, axis=-1)
        return jnp.take_along_axis(lp, toks[..., None], axis=-1)[..., 0]

    # First token comes from the target's prefill logits directly.
    k0 = jax.random.fold_in(key, 0) if sampled else None
    tok0 = _pick(logits_t, k0)  # [B]
    out0 = jnp.full((b, max_new_tokens), pad_id, jnp.int32)
    out0 = out0.at[:, 0].set(tok0)
    n0 = jnp.ones((b,), jnp.int32)
    lp0 = _lp_of(logits_t, tok0)  # [B]
    done0 = (tok0 == eos_id) | (max_new_tokens <= 1)

    def cond(state):
        _, _, _, _, n_out, _, done, rounds, _, _ = state
        return jnp.any(~done) & (rounds < max_new_tokens)

    def body(state):
        (
            tok,
            cache_t,
            cache_d,
            out,
            n_out,
            lp_sum,
            done,
            rounds,
            drafted,
            accepted,
        ) = state
        done_before = done
        len_t0 = cache_t.length
        len_d0 = cache_d.length

        rkey = jax.random.fold_in(key, rounds + 1) if sampled else None

        # --- Draft proposes k_spec tokens ------------------------------
        def dstep(carry, i):
            x, cd = carry
            lg, cd = decode_step(cfg_d, params_d, x[:, None], cd)
            if sampled:
                nxt = _pick(lg, jax.random.fold_in(rkey, i))
                qp = jax.nn.softmax(lg / t_eff, axis=-1)  # [B, V]
            else:
                nxt = jnp.argmax(lg, axis=-1).astype(jnp.int32)
                qp = jnp.zeros((b, 1), jnp.float32)  # unused
            return (nxt, cd), (nxt, qp)

        (_, cache_d), (drafts, q_probs) = jax.lax.scan(
            dstep, (tok, cache_d), jnp.arange(k_spec)
        )
        drafts = drafts.T  # [B, K]
        # One extra draft step consuming d_{K-1}: on full acceptance the
        # bonus token becomes the next input, and the draft cache must
        # then hold d_{K-1}'s k/v (its logits are discarded).
        _, cache_d = decode_step(cfg_d, params_d, drafts[:, -1:], cache_d)

        # --- Target verifies the whole draft in one chunk --------------
        # Chunk inputs: [tok, d_0 .. d_{K-1}] (K+1); logits_j predicts
        # the token after consuming input j, so position j verifies d_j
        # for j < K, and position K yields the FREE bonus token after a
        # fully accepted draft (Leviathan et al.) — k_spec+1 tokens from
        # one target forward.
        chunk = jnp.concatenate([tok[:, None], drafts], axis=1)
        logits, cache_t = decode_chunk(cfg_t, params_t, chunk, cache_t)
        targets = jnp.argmax(logits, axis=-1).astype(jnp.int32)  # [B, K+1]

        greedy_match = drafts == targets[:, :k_spec]  # [B, K]
        if sampled:
            # q_probs: [K, B, V] -> [B, K, V]; p_probs: [B, K+1, V].
            # Position K (the bonus slot) carries zero draft mass: its
            # leviathan_accept residual is then exactly the target
            # distribution, so ONE vmapped call of the tested helper
            # yields both the K acceptance coins and every candidate
            # correction/bonus token.
            q_probs = q_probs.transpose(1, 0, 2)
            p_probs = jax.nn.softmax(logits / t_eff[:, :, None], axis=-1)
            q_pad = jnp.concatenate(
                [q_probs, jnp.zeros_like(q_probs[:, :1])], axis=1
            )  # [B, K+1, V]
            d_pad = jnp.pad(drafts, ((0, 0), (0, 1)))  # [B, K+1]
            flat_keys = jax.random.split(
                jax.random.fold_in(rkey, 1000), b * (k_spec + 1)
            )
            keys = flat_keys.reshape((b, k_spec + 1) + flat_keys.shape[1:])
            coin, corr = jax.vmap(jax.vmap(leviathan_accept))(
                p_probs, q_pad, d_pad, keys
            )
            match = jnp.where(greedy_row, greedy_match, coin[:, :k_spec])
        else:
            match = greedy_match
        acc_mask = jnp.cumprod(match.astype(jnp.int32), axis=1)  # [B, K]
        n_acc = jnp.sum(acc_mask, axis=1)  # [B] in [0, K]

        fix_greedy = jnp.take_along_axis(targets, n_acc[:, None], axis=1)[
            :, 0
        ]
        if sampled:
            fix_sampled = jnp.take_along_axis(corr, n_acc[:, None], axis=1)[
                :, 0
            ]
            fix = jnp.where(greedy_row[:, 0], fix_greedy, fix_sampled)
        else:
            fix = fix_greedy

        # Emitted this round: accepted drafts, then ``fix`` at position
        # n_acc — the correction on a rejection, the bonus on full
        # acceptance. Uniformly n_acc + 1 tokens.
        j = jnp.arange(k_spec + 1)[None, :]
        emit = jnp.where(
            j < n_acc[:, None],
            jnp.pad(drafts, ((0, 0), (0, 1))),
            jnp.where(j == n_acc[:, None], fix[:, None], pad_id),
        )  # [B, K+1]
        emit_cnt = n_acc + 1  # [B]

        # EOS inside the round truncates it.
        is_eos = (emit == eos_id) & (j < emit_cnt[:, None])
        any_eos = jnp.any(is_eos, axis=1)
        eos_pos = jnp.argmax(is_eos, axis=1)
        emit_cnt = jnp.where(any_eos, eos_pos + 1, emit_cnt)

        # Rows already done (or out of budget) emit nothing.
        emit_cnt = jnp.where(done, 0, emit_cnt)
        emit_cnt = jnp.minimum(emit_cnt, max_new_tokens - n_out)

        # Scatter into the output buffer at per-row offsets.
        batch = jnp.arange(b)
        new_out = out
        for jj in range(k_spec + 1):  # static, small
            idx = jnp.clip(n_out + jj, 0, max_new_tokens - 1)
            write = jj < emit_cnt
            new_out = new_out.at[batch, idx].set(
                jnp.where(write, emit[:, jj], new_out[batch, idx])
            )

        # Next input token: last emitted (correction or bonus).
        last = jnp.clip(emit_cnt - 1, 0, k_spec)
        tok_next = jnp.where(
            emit_cnt > 0, emit[batch, last], tok
        ).astype(jnp.int32)

        # Cache fills: consumed chunk inputs = emit_cnt (the next input's
        # k/v is not yet written — decode_step convention). Done rows
        # keep their fill.
        consumed = emit_cnt
        cache_t = cache_t.with_length(len_t0 + consumed)
        cache_d = cache_d.with_length(len_d0 + consumed)

        # Emitted-token logprobs under the target (engine convention).
        lp_emit = _lp_of(logits, emit)  # [B, K+1]
        lp_sum = lp_sum + jnp.sum(
            jnp.where(j < emit_cnt[:, None], lp_emit, 0.0), axis=1
        )

        n_out = n_out + emit_cnt
        done = done | any_eos | (n_out >= max_new_tokens)
        drafted = drafted + k_spec * jnp.sum((~done_before).astype(jnp.int32))
        accepted = accepted + jnp.sum(jnp.minimum(n_acc, emit_cnt))
        return (
            tok_next,
            cache_t,
            cache_d,
            new_out,
            n_out,
            lp_sum,
            done,
            rounds + 1,
            drafted,
            accepted,
        )

    zero = jnp.zeros((), jnp.int32)
    state = (
        tok0,
        cache_t,
        cache_d,
        out0,
        n0,
        lp0,
        done0,
        zero,
        zero,
        zero,
    )
    state = jax.lax.while_loop(cond, body, state)
    _, _, _, out, n_out, lp_sum, _, rounds, drafted, accepted = state
    return SpecOutput(
        tokens=out,
        num_tokens=n_out,
        logprob_sum=lp_sum,
        rounds=rounds,
        drafted=drafted,
        accepted=accepted,
    )
