"""Tokenizers for the inference engine.

The reference never tokenizes — text goes to the Gemini API verbatim
(``src/main.rs:82-86``). A local TPU engine needs token ids, so this module
provides:

- :class:`ByteTokenizer` — dependency-free byte-level tokenizer (UTF-8
  bytes offset past the special ids). Deterministic, reversible, works
  with the tiny test configs and in fully offline environments; the
  default for tests and the fake-weights bench path.
- :func:`load_tokenizer` — loads a HuggingFace tokenizer from a *local*
  directory when one is available (real checkpoints), else falls back to
  bytes. No network access is ever attempted.

Both expose the same small surface: ``encode``, ``decode``,
``vocab_size``, ``bos_id``, ``eos_id``, ``pad_id``.
"""

from __future__ import annotations

import abc
import os
from typing import Sequence


class Tokenizer(abc.ABC):
    """Minimal tokenizer interface used by the engine."""

    vocab_size: int
    bos_id: int
    eos_id: int
    pad_id: int

    @abc.abstractmethod
    def encode(self, text: str, add_bos: bool = True) -> list[int]: ...

    @abc.abstractmethod
    def decode(self, ids: Sequence[int]) -> str: ...


class ByteTokenizer(Tokenizer):
    """Byte-level tokenizer: id = byte + 3. Ids 0/1/2 are pad/bos/eos.

    Round-trips arbitrary UTF-8 text; vocab is 259 ids. Model configs used
    with this tokenizer need ``vocab_size >= 259``.
    """

    def __init__(self) -> None:
        self.pad_id = 0
        self.bos_id = 1
        self.eos_id = 2
        self._offset = 3
        self.vocab_size = 256 + self._offset

    def encode(self, text: str, add_bos: bool = True) -> list[int]:
        # surrogateescape mirrors decode(): text carved out of decoded
        # model output (stop sequences, prefix keys) may carry lone
        # surrogates standing in for invalid bytes; encoding them back
        # to those bytes keeps encode(decode(ids)) == ids.
        ids = [
            b + self._offset
            for b in text.encode("utf-8", errors="surrogateescape")
        ]
        return [self.bos_id] + ids if add_bos else ids

    def decode(self, ids: Sequence[int]) -> str:
        # Ignore ids outside the byte range — models whose vocab exceeds
        # 259 (e.g. test configs with padded vocabs) can sample them.
        data = bytes(
            i - self._offset
            for i in ids
            if self._offset <= i < self._offset + 256
        )
        # surrogateescape, not replace: invalid bytes must decode to
        # DISTINCT characters (U+DC80+byte) or the decode is lossy in a
        # way that breaks stop-sequence position arithmetic — with
        # errors="replace" every invalid byte aliases to U+FFFD, so a
        # stop string carved from decoded text str.find()-matches at an
        # EARLIER aliased position and the trim cuts the wrong prefix
        # (the engine/batcher stop contract trims at the earliest true
        # occurrence). surrogateescape is also reversible, preserving
        # the class promise that decode round-trips arbitrary bytes.
        return data.decode("utf-8", errors="surrogateescape")


class HFTokenizer(Tokenizer):
    """Wrapper over a locally available ``transformers`` tokenizer."""

    def __init__(self, tok) -> None:
        self._tok = tok
        self.vocab_size = len(tok)
        self.bos_id = tok.bos_token_id if tok.bos_token_id is not None else 1
        self.eos_id = tok.eos_token_id if tok.eos_token_id is not None else 2
        pad = tok.pad_token_id
        self.pad_id = pad if pad is not None else self.eos_id

    def encode(self, text: str, add_bos: bool = True) -> list[int]:
        ids = self._tok.encode(text, add_special_tokens=False)
        return [self.bos_id] + ids if add_bos else ids

    def decode(self, ids: Sequence[int]) -> str:
        return self._tok.decode(list(ids), skip_special_tokens=True)


def load_tokenizer(path: str | None = None) -> Tokenizer:
    """Load a tokenizer.

    ``path``: a local directory containing HF tokenizer files. When None or
    unloadable, returns :class:`ByteTokenizer`. Never touches the network
    (``local_files_only=True``).
    """
    if path and os.path.isdir(path):
        try:
            from transformers import AutoTokenizer

            return HFTokenizer(
                AutoTokenizer.from_pretrained(path, local_files_only=True)
            )
        except Exception:  # noqa: BLE001 - any load failure -> byte fallback
            pass
    return ByteTokenizer()
