"""Batch evaluation: GSM8K exact-match + throughput measurement.

SURVEY.md §7(f): the reference is manually tested via its REPL and
publishes no benchmarks (§6); the rebuild's accuracy/throughput targets
come from BASELINE.json (GSM8K EM at N∈{1,8,32,64} self-consistency,
candidate-tokens/sec/chip).
"""

from llm_consensus_tpu.eval.gsm8k import (
    EvalReport,
    Problem,
    evaluate_self_consistency,
    exact_match,
    few_shot_header,
    load_gsm8k,
    synthetic_problems,
)

__all__ = [
    "EvalReport",
    "Problem",
    "evaluate_self_consistency",
    "exact_match",
    "few_shot_header",
    "load_gsm8k",
    "synthetic_problems",
]
