"""Arithmetic SFT corpus for the end-to-end accuracy loop.

The reference's compute layer is a production remote LLM
(``src/main.rs:82-86``), so its consensus loop answered questions from
day one. This environment is zero-egress — no pretrained checkpoint can
be downloaded — so the framework proves the same property the honest
way: TRAIN a small model on the synthetic arithmetic task distribution
(:func:`llm_consensus_tpu.eval.gsm8k.synthetic_problems`), checkpoint
it, reload it through :class:`InferenceEngine`, and measure real
engine-backed EM-vs-N (``examples/train_arith_em.py``).

The task: "((a + b) * c" word problems, a,b in [2,60], c in [2,9] —
27,848 distinct triples. Training renders each triple as the EXACT
prompt the eval harness uses plus a chain-of-thought completion::

    <prompt from gsm8k._PROMPT> {a} + {b} = {s}. {s} * {c} = {x}. #### {x}<eos>

Held-out split is at the TRIPLE level: every (a, b, c) appearing in the
eval problem set is excluded from training, so EM measures
generalization to unseen operand combinations, not memorization of the
eval items.
"""

from __future__ import annotations

import re

from llm_consensus_tpu.eval.gsm8k import _PROMPT, Problem, synthetic_problems

_INT_RE = re.compile(r"\d+")


def problem_triple(p: Problem) -> tuple[int, int, int]:
    """Recover (a, b, c) from a synthetic problem's question text."""
    nums = _INT_RE.findall(p.question)
    if len(nums) < 3:
        raise ValueError(f"not a synthetic arithmetic question: {p.question!r}")
    return int(nums[0]), int(nums[1]), int(nums[2])


def all_triples() -> list[tuple[int, int, int]]:
    """Every (a, b, c) the synthetic generator can draw (27,848)."""
    return [
        (a, b, c)
        for a in range(2, 61)
        for b in range(2, 61)
        for c in range(2, 10)
    ]


def render_example(a: int, b: int, c: int) -> tuple[str, str]:
    """(prompt, completion) text for one triple.

    The prompt is byte-identical to what ``evaluate_self_consistency``
    sends (same ``_PROMPT`` template, same question wording as
    ``synthetic_problems``); the completion is a two-step
    chain-of-thought ending in the ``#### <answer>`` marker the EM
    extractor keys on.
    """
    s, x = a + b, (a + b) * c
    q = (
        f"A basket holds {a} apples. {b} more are added, then the "
        f"total is multiplied by {c} for a festival order. "
        f"How many apples are in the order?"
    )
    prompt = _PROMPT.format(q=q)
    completion = f" {a} + {b} = {s}. {s} * {c} = {x}. #### {x}"
    return prompt, completion


def build_sft_examples(
    tokenizer,
    *,
    exclude: set[tuple[int, int, int]] | None = None,
    limit: int | None = None,
) -> list[tuple[list[int], list[int]]]:
    """Tokenized (prompt_ids, completion_ids) pairs for SFT.

    ``exclude``: triples to hold out (the eval set's). The completion
    carries a trailing EOS so a trained model terminates its answers.
    """
    exclude = exclude or set()
    out = []
    for t in all_triples():
        if t in exclude:
            continue
        prompt, completion = render_example(*t)
        p_ids = tokenizer.encode(prompt)
        c_ids = tokenizer.encode(completion, add_bos=False) + [
            tokenizer.eos_id
        ]
        out.append((p_ids, c_ids))
        if limit and len(out) >= limit:
            break
    return out


def eval_split(
    n_eval: int, seed: int = 0
) -> tuple[list[Problem], set[tuple[int, int, int]]]:
    """The eval problems and their triples (the training holdout set)."""
    problems = synthetic_problems(n_eval, seed=seed)
    return problems, {problem_triple(p) for p in problems}
