"""Multi-template, multi-step arithmetic corpus (the hard accuracy task).

Round 4's accuracy evidence used ONE sentence frame computing (a+b)*c
(``eval/arith.py``) — a converged model saturates EM at 1.000 and N=1
already wins, so self-consistency had nothing to move. This corpus is
the non-trivial successor (VERDICT round-4, item 2): GSM8K-*style*
multi-step word problems, built offline (the env is zero-egress), hard
enough that a converged small model sits meaningfully below EM 1.0 on
held-out problems — the regime where Wang-et-al self-consistency
(majority vote over sampled chains) actually pays.

Problem = a **chain** of 2-4 arithmetic steps over a running value::

    v0 --(op1 b1)--> v1 --(op2 b2)--> ... --> answer

rendered through one of SIX narrative frames (different protagonist,
entity, and per-operation phrasing), with 1-2 **distractor sentences**
carrying numbers that must NOT enter the computation. The completion is
a step-by-step chain of thought ending in the ``#### <answer>`` marker
the EM extractor keys on (``consensus/voting.extract_final_number``)::

    " 17 + 24 = 41. 41 * 3 = 123. 123 - 38 = 85. #### 85"

Held-out split is at the CHAIN level: a chain signature
``(v0, ops, operands)`` appearing in the eval set is excluded from
training regardless of which frame renders it, so EM measures
generalization to unseen computations, not memorization of eval items.

The reference outsources answering to a remote LLM (``src/main.rs:82-86``)
and has no evaluation at all (SURVEY.md §4/§6); this corpus exists so the
rebuilt stack's accuracy claims come from a model it trained itself.
"""

from __future__ import annotations

import random
from dataclasses import dataclass

from llm_consensus_tpu.eval.gsm8k import Problem

# ---------------------------------------------------------------------------
# Chains

_OPS = ("+", "-", "*", "/")


@dataclass(frozen=True)
class Chain:
    """A multi-step computation: start value + (op, operand) steps."""

    v0: int
    ops: tuple[str, ...]
    operands: tuple[int, ...]

    @property
    def signature(self) -> tuple:
        return (self.v0, self.ops, self.operands)

    @property
    def values(self) -> list[int]:
        """All intermediate values [v0, v1, ..., answer]."""
        vals = [self.v0]
        for op, b in zip(self.ops, self.operands):
            v = vals[-1]
            if op == "+":
                vals.append(v + b)
            elif op == "-":
                vals.append(v - b)
            elif op == "*":
                vals.append(v * b)
            elif op == "/":
                if v % b:
                    raise ValueError(f"inexact division {v}/{b}")
                vals.append(v // b)
            else:
                raise ValueError(f"unknown op {op!r}")
        return vals

    @property
    def answer(self) -> int:
        return self.values[-1]


def sample_chain(rng: random.Random, n_steps: int | None = None) -> Chain:
    """Draw a chain with all intermediates in [2, 999].

    Steps: 2-4 (uniform). Operands: add/sub in [2, 99], mul in [2, 9]
    (result bounded), div a true divisor in [2, 9]. Ops are drawn per
    step from whichever of the four are feasible at the current value,
    so every chain is exact-arithmetic by construction.
    """
    k = n_steps or rng.randint(2, 4)
    for _ in range(64):  # rejection loop (rarely needed)
        v0 = rng.randint(3, 99)
        ops: list[str] = []
        operands: list[int] = []
        v = v0
        ok = True
        for _ in range(k):
            feasible = []
            if v + 2 <= 999:
                feasible.append("+")
            if v - 2 >= 2:
                feasible.append("-")
            if v * 2 <= 999:
                feasible.append("*")
            divisors = [d for d in range(2, 10) if v % d == 0 and v // d >= 2]
            if divisors:
                feasible.append("/")
            if not feasible:
                ok = False
                break
            op = rng.choice(feasible)
            if op == "+":
                b = rng.randint(2, min(99, 999 - v))
                v = v + b
            elif op == "-":
                b = rng.randint(2, min(99, v - 2))
                v = v - b
            elif op == "*":
                b = rng.randint(2, min(9, 999 // v))
                v = v * b
            else:
                b = rng.choice(divisors)
                v = v // b
            ops.append(op)
            operands.append(b)
        if ok:
            return Chain(v0, tuple(ops), tuple(operands))
    raise RuntimeError("could not sample a feasible chain")


# ---------------------------------------------------------------------------
# Narrative frames
#
# Each frame: protagonist + entity + one phrasing per op + distractor
# sentence templates. Six frames x varied phrasings = the multi-template
# surface diversity round 4 lacked. `{b}` is the step operand; `{d}` a
# distractor value the solution must ignore.

_FRAMES: list[dict] = [
    {
        "start": "Maya's basket holds {v0} apples.",
        "+": "She picks {b} more from the orchard.",
        "-": "She hands {b} to her neighbor.",
        "*": "A festival order multiplies her total by {b}.",
        "/": "She packs them into {b} equal crates and keeps one crate.",
        "q": "How many apples does Maya have at the end?",
        "d": [
            "Her orchard ladder is {d} feet tall.",
            "She has been picking for {d} minutes.",
            "Her neighbor lives {d} steps away.",
        ],
    },
    {
        "start": "Liam's jar contains {v0} marbles.",
        "+": "He wins {b} more at recess.",
        "-": "He trades away {b} of them.",
        "*": "A collector's swap multiplies his total by {b}.",
        "/": "He splits them into {b} equal bags and keeps a single bag.",
        "q": "How many marbles does Liam have at the end?",
        "d": [
            "His jar weighs {d} grams when empty.",
            "Recess lasts {d} minutes.",
            "He is {d} years old.",
        ],
    },
    {
        "start": "The library shelf starts with {v0} books.",
        "+": "A donation adds {b} books.",
        "-": "Readers borrow {b} books.",
        "*": "A merger with another branch multiplies the count by {b}.",
        "/": "The books are divided into {b} equal stacks and only one "
        "stack stays on the shelf.",
        "q": "How many books are on the shelf at the end?",
        "d": [
            "The shelf is {d} inches wide.",
            "The library opened {d} years ago.",
            "There are {d} chairs in the reading room.",
        ],
    },
    {
        "start": "Priya's pouch has {v0} coins.",
        "+": "She earns {b} more doing chores.",
        "-": "She spends {b} at the fair.",
        "*": "A lucky game multiplies her coins by {b}.",
        "/": "She shares them into {b} equal piles and keeps one pile.",
        "q": "How many coins does Priya have at the end?",
        "d": [
            "The fair ticket line had {d} people.",
            "Her pouch was a gift from {d} friends.",
            "The fair runs for {d} days.",
        ],
    },
    {
        "start": "The farmer collects {v0} eggs at dawn.",
        "+": "The afternoon coop yields {b} more.",
        "-": "The market sells {b} of them.",
        "*": "A wholesale contract multiplies the count by {b}.",
        "/": "The eggs are boxed into {b} equal cartons and one carton "
        "is kept.",
        "q": "How many eggs does the farmer have at the end?",
        "d": [
            "The coop is {d} meters from the house.",
            "The farm has {d} hens.",
            "Dawn broke at {d} minutes past five.",
        ],
    },
    {
        "start": "Noah's drawer holds {v0} raffle tickets.",
        "+": "He buys {b} more at the gate.",
        "-": "He gives {b} to his cousins.",
        "*": "A bonus round multiplies his tickets by {b}.",
        "/": "He sorts them into {b} equal envelopes and keeps just one "
        "envelope.",
        "q": "How many raffle tickets does Noah have at the end?",
        "d": [
            "The raffle drum spins {d} times.",
            "The gate opened {d} minutes early.",
            "His cousin's house is {d} blocks away.",
        ],
    },
]

N_FRAMES = len(_FRAMES)


def render_question(
    chain: Chain,
    frame_idx: int,
    rng: random.Random,
    n_distractors: int | None = None,
) -> str:
    """Render a chain through a frame, weaving in distractor sentences.

    Distractor values are drawn from the operand range ([2, 99]) so they
    are confusable with real quantities; their sentences are inserted at
    random positions among the step sentences (never before the start
    sentence, so the initial quantity stays first).
    """
    f = _FRAMES[frame_idx % N_FRAMES]
    nd = rng.randint(1, 2) if n_distractors is None else n_distractors
    sents = [f["start"].format(v0=chain.v0)]
    for op, b in zip(chain.ops, chain.operands):
        sents.append(f[op].format(b=b))
    for tmpl in rng.sample(f["d"], min(nd, len(f["d"]))):
        pos = rng.randint(1, len(sents))
        sents.insert(pos, tmpl.format(d=rng.randint(2, 99)))
    return " ".join(sents) + " " + f["q"]


def render_completion(chain: Chain) -> str:
    """Step-by-step CoT ending in the ``#### <answer>`` marker."""
    vals = chain.values
    parts = []
    for i, (op, b) in enumerate(zip(chain.ops, chain.operands)):
        parts.append(f"{vals[i]} {op} {b} = {vals[i + 1]}.")
    return " " + " ".join(parts) + f" #### {chain.answer}"


# ---------------------------------------------------------------------------
# Splits

def eval_problems(
    n: int, seed: int = 0
) -> tuple[list[Problem], set[tuple]]:
    """Deterministic eval set + its chain signatures (training holdout).

    Frames rotate round-robin so every frame is evaluated; distractor
    count/placement and the chains themselves come from the seeded rng.
    """
    rng = random.Random(seed)
    problems, sigs = [], set()
    while len(problems) < n:
        chain = sample_chain(rng)
        if chain.signature in sigs:
            continue
        q = render_question(chain, len(problems) % N_FRAMES, rng)
        problems.append(Problem(question=q, answer=f"#### {chain.answer}"))
        sigs.add(chain.signature)
    return problems, sigs


def build_sft_examples(
    tokenizer,
    n_examples: int,
    *,
    exclude: set[tuple] | None = None,
    seed: int = 1,
    prompt_template: str | None = None,
) -> list[tuple[list[int], list[int]]]:
    """Tokenized (prompt_ids, completion_ids) SFT pairs.

    Chains whose signature is in ``exclude`` (the eval holdout) are
    skipped. Prompts use the SAME template ``evaluate_self_consistency``
    sends (``gsm8k._PROMPT``) so train and eval token streams agree
    byte-for-byte; completions carry a trailing EOS so the trained model
    terminates its answers.
    """
    from llm_consensus_tpu.eval.gsm8k import _PROMPT

    template = prompt_template or _PROMPT
    exclude = exclude or set()
    rng = random.Random(seed)
    out = []
    while len(out) < n_examples:
        chain = sample_chain(rng)
        if chain.signature in exclude:
            continue
        q = render_question(chain, rng.randrange(N_FRAMES), rng)
        prompt = template.format(q=q)
        completion = render_completion(chain)
        p_ids = tokenizer.encode(prompt)
        c_ids = tokenizer.encode(completion, add_bos=False) + [
            tokenizer.eos_id
        ]
        out.append((p_ids, c_ids))
    return out
