"""GSM8K-style exact-match evaluation with self-consistency voting.

The accuracy metric of BASELINE.json: GSM8K EM at N-way self-consistency
majority vote. The reference has no evaluation at all (SURVEY.md §4/§6).

Data comes from a local JSONL file when available (fields ``question`` /
``answer``, GSM8K convention: gold answer after ``####``); this
environment is zero-egress, so :func:`synthetic_problems` provides a
deterministic arithmetic dataset with the same shape for offline tests
and plumbing benchmarks.
"""

from __future__ import annotations

import json
import random
import time
from dataclasses import dataclass, field
from pathlib import Path

from llm_consensus_tpu.consensus.voting import (
    extract_final_number,
    logit_pool,
    majority_vote,
)


@dataclass(frozen=True)
class Problem:
    question: str
    answer: str  # canonical gold answer (a number string for GSM8K)


def exact_match(predicted: str | None, gold: str) -> bool:
    """EM on canonical final numbers (commas/$ stripped, 42.0 == 42)."""
    if predicted is None:
        return False
    gold_c = extract_final_number(gold)
    return predicted == (gold_c if gold_c is not None else gold.strip())


def load_gsm8k(path: str | Path, limit: int | None = None) -> list[Problem]:
    """Load GSM8K JSONL: {"question": ..., "answer": "...#### N"}."""
    problems = []
    with open(path) as f:
        for line in f:
            if not line.strip():
                continue
            d = json.loads(line)
            problems.append(Problem(question=d["question"], answer=d["answer"]))
            if limit and len(problems) >= limit:
                break
    return problems


def synthetic_problems(n: int, seed: int = 0) -> list[Problem]:
    """Deterministic GSM8K-shaped arithmetic problems (offline stand-in)."""
    rng = random.Random(seed)
    out = []
    for _ in range(n):
        a, b, c = rng.randint(2, 60), rng.randint(2, 60), rng.randint(2, 9)
        q = (
            f"A basket holds {a} apples. {b} more are added, then the "
            f"total is multiplied by {c} for a festival order. "
            f"How many apples are in the order?"
        )
        ans = (a + b) * c
        out.append(Problem(question=q, answer=f"#### {ans}"))
    return out


@dataclass
class EvalReport:
    n_problems: int
    n_candidates: int
    em: float
    total_candidate_tokens: int
    wall_seconds: float
    method: str
    per_problem: list[dict] = field(default_factory=list)

    @property
    def candidate_tokens_per_sec(self) -> float:
        return self.total_candidate_tokens / max(self.wall_seconds, 1e-9)

    def to_dict(self) -> dict:
        return {
            "n_problems": self.n_problems,
            "n_candidates": self.n_candidates,
            "em": self.em,
            "total_candidate_tokens": self.total_candidate_tokens,
            "wall_seconds": self.wall_seconds,
            "candidate_tokens_per_sec": self.candidate_tokens_per_sec,
            "method": self.method,
        }


_PROMPT = (
    "Solve the math problem. Show your reasoning, then give the final "
    "numeric answer after '####'.\n\nQuestion: {q}\nAnswer:"
)


def few_shot_header(examples: list[Problem]) -> str:
    """Worked-example block for k-shot CoT prompting (GSM8K convention).

    Prepended to the prompt template's fixed header, it rides the
    engine's prefix cache: the k exemplars are prefilled once for the
    whole eval, not once per problem x N candidates.
    """
    parts = []
    for ex in examples:
        parts.append(f"Question: {ex.question}\nAnswer: {ex.answer}\n\n")
    return "".join(parts)


def evaluate_self_consistency(
    engine,
    problems: list[Problem],
    n: int = 8,
    temperature: float = 0.7,
    seed: int = 0,
    max_new_tokens: int | None = None,
    method: str = "majority",
    prompt_template: str = _PROMPT,
    few_shot: list[Problem] | None = None,
    prefix_mode: str = "auto",
) -> EvalReport:
    """EM with N-way self-consistency.

    All N candidates of one problem run as ONE batched device program on
    the engine (the candidate axis = the mesh ``data`` axis). N=1 with
    temperature 0 degenerates to the greedy correctness baseline
    (BASELINE.md config[0]). ``few_shot``: exemplar problems (disjoint
    from ``problems``) prepended as a k-shot header.

    ``prefix_mode``: "auto" rides the engine's prefix cache (header
    prefilled once for the whole eval) only when the split provably
    cannot change the token stream — byte-level tokenizer, and a
    template whose only format field is a single ``{q}``. "force"
    enables it for any tokenizer (merge-based BPE may tokenize the
    head/suffix boundary differently from the concatenated prompt —
    the standard prefix-caching caveat); "off" disables it.
    """
    correct = 0
    total_tokens = 0
    per_problem = []
    # The template's fixed header (everything before {q}) is identical
    # across problems and N-sweeps: engines with a prefix cache prefill
    # it once and reuse its K/V for every problem (engine/prefix_cache).
    # Head/tail splitting is only valid for templates whose one format
    # field is {q}; anything fancier takes the plain .format path.
    shots = few_shot_header(few_shot) if few_shot else ""
    splittable = (
        prompt_template.count("{q}") == 1
        and prompt_template.count("{") == 1
        and prompt_template.count("}") == 1
    )
    if prefix_mode not in ("auto", "force", "off"):
        raise ValueError(f"unknown prefix_mode {prefix_mode!r}")
    from llm_consensus_tpu.engine.tokenizer import ByteTokenizer

    use_prefix = (
        splittable
        and prefix_mode != "off"
        and hasattr(engine, "prefix_cache")
        and (
            prefix_mode == "force"
            or isinstance(getattr(engine, "tokenizer", None), ByteTokenizer)
        )
    )
    head, _, tail = prompt_template.partition("{q}")
    t0 = time.perf_counter()
    for i, prob in enumerate(problems):
        temps = [temperature if n > 1 else 0.0] * n
        if use_prefix and shots + head:
            results = engine.generate_texts(
                [prob.question + tail] * n,
                temperatures=temps,
                seed=seed + i,
                max_new_tokens=max_new_tokens,
                prefix=shots + head,
            )
        else:
            results = engine.generate_texts(
                [shots + prompt_template.format(q=prob.question)] * n,
                temperatures=temps,
                seed=seed + i,
                max_new_tokens=max_new_tokens,
            )
        texts = [r.text for r in results]
        total_tokens += sum(r.num_tokens for r in results)
        if method == "majority":
            vote = majority_vote(texts, key_fn=_answer_key)
        elif method == "logit_pool":
            vote = logit_pool(
                texts, [r.logprob for r in results], key_fn=_answer_key
            )
        else:
            raise ValueError(f"unknown method {method!r}")
        pred = vote.winner if vote.winner != _NO_ANSWER else None
        ok = exact_match(pred, prob.answer)
        correct += ok
        per_problem.append(
            {"question": prob.question, "pred": pred, "gold": prob.answer, "em": ok}
        )
    wall = time.perf_counter() - t0
    return EvalReport(
        n_problems=len(problems),
        n_candidates=n,
        em=correct / max(len(problems), 1),
        total_candidate_tokens=total_tokens,
        wall_seconds=wall,
        method=method,
        per_problem=per_problem,
    )


_NO_ANSWER = "<no-answer>"


def _answer_key(text: str) -> str:
    """Vote key: the extracted final number; answerless candidates pool
    under a sentinel so they can't outvote real answers by accident
    unless they truly dominate."""
    num = extract_final_number(text)
    return num if num is not None else _NO_ANSWER


class OracleEngine:
    """Engine stub answering correctly with probability ``p_correct``.

    Deterministic (seeded) per construction — the reproducible offline
    backend for documenting/testing the self-consistency voting effect
    (EM rising with N) without a model. Shared by
    ``tests/test_gsm8k_eval.py`` and ``examples/gsm8k_em_vs_n.py`` so
    the recorded EM_VS_N.md table and the tested behavior cannot drift
    apart.
    """

    def __init__(self, problems: list[Problem], p_correct: float = 0.6):
        self._rng = random.Random(123)
        self._gold = {
            p.question: extract_final_number(p.answer) for p in problems
        }
        self.p = p_correct

    def generate_texts(
        self,
        prompts,
        temperatures=None,
        seed=0,
        max_new_tokens=None,
        sampler=None,
    ):
        from llm_consensus_tpu.engine.engine import EngineResult

        out = []
        for prompt in prompts:
            gold = next(g for q, g in self._gold.items() if q in prompt)
            ans = (
                gold
                if self._rng.random() < self.p
                else str(int(gold) + self._rng.randint(1, 9))
            )
            out.append(
                EngineResult(
                    text=f"Reasoning... #### {ans}",
                    num_tokens=8,
                    logprob=-1.0,
                    token_ids=[],
                )
            )
        return out
