from llm_consensus_tpu.models.configs import ModelConfig, get_config, PRESETS
from llm_consensus_tpu.models.cache import KVCache
from llm_consensus_tpu.models.transformer import (
    init_params,
    init_params_quantized,
    forward,
    prefill,
    prefill_chunked,
    decode_chunk,
    decode_step,
    param_count,
)

__all__ = [
    "ModelConfig",
    "get_config",
    "PRESETS",
    "KVCache",
    "init_params",
    "init_params_quantized",
    "forward",
    "prefill",
    "prefill_chunked",
    "decode_chunk",
    "decode_step",
    "param_count",
]
