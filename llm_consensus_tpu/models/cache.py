"""KV cache: a fixed-shape pytree so decode steps compile once.

Per BASELINE.json's north star the cache shards per-candidate in HBM: the
batch axis (= candidate axis for self-consistency fan-out) carries the
``data`` mesh axis, kv heads carry ``model`` (see
``llm_consensus_tpu.parallel.partitioning``). Static max_len keeps XLA
shapes fixed; per-sequence fill lengths are data, not shape.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp

from llm_consensus_tpu.models.configs import ModelConfig


@jax.tree_util.register_dataclass
@dataclass
class KVCache:
    # [n_layers, B, max_len, n_kv_heads, head_dim]
    k: jnp.ndarray
    v: jnp.ndarray
    # [B] number of filled slots per sequence.
    length: jnp.ndarray

    @staticmethod
    def create(
        cfg: ModelConfig, batch: int, max_len: int, dtype=jnp.bfloat16
    ) -> "KVCache":
        shape = (cfg.n_layers, batch, max_len, cfg.n_kv_heads, cfg.head_dim)
        return KVCache(
            k=jnp.zeros(shape, dtype),
            v=jnp.zeros(shape, dtype),
            length=jnp.zeros((batch,), jnp.int32),
        )

    @property
    def max_len(self) -> int:
        return self.k.shape[2]

    def advanced(self, n: int | jnp.ndarray = 1) -> "KVCache":
        """Return a cache with fill length advanced by n."""
        return KVCache(k=self.k, v=self.v, length=self.length + n)

    def with_length(self, length: jnp.ndarray) -> "KVCache":
        return KVCache(k=self.k, v=self.v, length=length)


@jax.tree_util.register_dataclass
@dataclass
class QuantKVCache:
    """int8 KV cache: per-(token, kv-head) symmetric scales.

    Decode re-reads the whole cache every step, so at large candidate
    counts the cache rivals the weights for HBM traffic (llama-1b N=64:
    ~2.1 GB/step bf16). int8 halves it; scales are per-(position, head)
    amax over head_dim, which preserves decode logits to ~1%% (tested
    against the bf16 cache).

    Layout is head-major ``[L, B, Hkv, S, D]`` (unlike KVCache's
    ``[L, B, S, Hkv, D]``): the int8 decode-attention kernel reads
    per-(batch, head) [S, D] slabs, and head-major makes that a
    zero-copy reshape instead of a per-step transposed materialization.
    """

    # [n_layers, B, n_kv_heads, max_len, head_dim] int8
    k_q: jnp.ndarray
    v_q: jnp.ndarray
    # [n_layers, B, n_kv_heads, max_len] float32
    k_scale: jnp.ndarray
    v_scale: jnp.ndarray
    length: jnp.ndarray  # [B]

    @staticmethod
    def create(cfg: ModelConfig, batch: int, max_len: int) -> "QuantKVCache":
        shape = (cfg.n_layers, batch, cfg.n_kv_heads, max_len, cfg.head_dim)
        sshape = shape[:-1]
        return QuantKVCache(
            k_q=jnp.zeros(shape, jnp.int8),
            v_q=jnp.zeros(shape, jnp.int8),
            k_scale=jnp.zeros(sshape, jnp.float32),
            v_scale=jnp.zeros(sshape, jnp.float32),
            length=jnp.zeros((batch,), jnp.int32),
        )

    @property
    def max_len(self) -> int:
        return self.k_q.shape[3]

    def advanced(self, n: int | jnp.ndarray = 1) -> "QuantKVCache":
        return QuantKVCache(
            k_q=self.k_q,
            v_q=self.v_q,
            k_scale=self.k_scale,
            v_scale=self.v_scale,
            length=self.length + n,
        )

    def with_length(self, length: jnp.ndarray) -> "QuantKVCache":
        return QuantKVCache(
            k_q=self.k_q,
            v_q=self.v_q,
            k_scale=self.k_scale,
            v_scale=self.v_scale,
            length=length,
        )


def quantize_kv(x: jnp.ndarray) -> tuple[jnp.ndarray, jnp.ndarray]:
    """[..., D] -> (int8 [..., D], f32 scale [...]) amax-symmetric over D."""
    xf = x.astype(jnp.float32)
    amax = jnp.max(jnp.abs(xf), axis=-1)
    scale = jnp.maximum(amax, 1e-8) / 127.0
    q = jnp.clip(jnp.round(xf / scale[..., None]), -127, 127).astype(jnp.int8)
    return q, scale
