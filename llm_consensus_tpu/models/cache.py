"""KV cache: a fixed-shape pytree so decode steps compile once.

Per BASELINE.json's north star the cache shards per-candidate in HBM: the
batch axis (= candidate axis for self-consistency fan-out) carries the
``data`` mesh axis, kv heads carry ``model`` (see
``llm_consensus_tpu.parallel.partitioning``). Static max_len keeps XLA
shapes fixed; per-sequence fill lengths are data, not shape.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp

from llm_consensus_tpu.models.configs import ModelConfig


@jax.tree_util.register_dataclass
@dataclass
class KVCache:
    # [n_layers, B, max_len, n_kv_heads, head_dim]
    k: jnp.ndarray
    v: jnp.ndarray
    # [B] number of filled slots per sequence.
    length: jnp.ndarray

    @staticmethod
    def create(
        cfg: ModelConfig, batch: int, max_len: int, dtype=jnp.bfloat16
    ) -> "KVCache":
        shape = (cfg.n_layers, batch, max_len, cfg.n_kv_heads, cfg.head_dim)
        return KVCache(
            k=jnp.zeros(shape, dtype),
            v=jnp.zeros(shape, dtype),
            length=jnp.zeros((batch,), jnp.int32),
        )

    @property
    def max_len(self) -> int:
        return self.k.shape[2]

    def advanced(self, n: int | jnp.ndarray = 1) -> "KVCache":
        """Return a cache with fill length advanced by n."""
        return KVCache(k=self.k, v=self.v, length=self.length + n)

    def with_length(self, length: jnp.ndarray) -> "KVCache":
        return KVCache(k=self.k, v=self.v, length=length)
