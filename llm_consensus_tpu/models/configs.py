"""Model configurations for the persona-panel model families.

The reference has no model code (its model is the remote Gemini API,
``src/main.rs:82-86``). The families here are the ones BASELINE.md's target
configs name: Llama-3-8B (north star), Mistral-7B and Qwen2-7B
(heterogeneous panel, config[3]), Mixtral-8x7B MoE (config[2]), plus small
test/bench presets. All are one architecture family — pre-norm transformer,
GQA attention, RoPE, SwiGLU — differing in dims and two flags (qkv bias for
Qwen2, MoE for Mixtral), so one functional implementation serves all.
"""

from __future__ import annotations

from dataclasses import dataclass, replace


@dataclass(frozen=True)
class RopeScaling:
    """Llama-3.1-style frequency rescaling (HF rope_scaling type
    'llama3'). Hashable so ModelConfig stays a valid jit static arg."""

    factor: float
    low_freq_factor: float
    high_freq_factor: float
    original_max_position_embeddings: int


@dataclass(frozen=True)
class ModelConfig:
    name: str
    vocab_size: int
    d_model: int
    n_layers: int
    n_heads: int
    n_kv_heads: int
    d_ff: int
    rope_theta: float = 10000.0
    rope_scaling: RopeScaling | None = None  # Llama-3.1 long-context
    rms_norm_eps: float = 1e-5
    max_seq_len: int = 8192
    # Sliding-window attention (Mistral): 0 = full causal.
    sliding_window: int = 0
    qkv_bias: bool = False  # Qwen2 uses bias on q/k/v projections
    tie_embeddings: bool = False
    # MoE (Mixtral): 0 experts = dense MLP.
    n_experts: int = 0
    n_experts_per_token: int = 2
    # > 0 enables capacity-bounded GShard-style dispatch (compute only
    # routed tokens, capacity = ceil(T*k/E * factor)); 0 = dense
    # all-experts compute (exact, E/k x the FLOPs).
    moe_capacity_factor: float = 0.0
    # Token count at or below which an MoE layer takes the dense
    # all-experts path even when moe_capacity_factor > 0. At decode
    # shapes every expert's weights stream from HBM regardless of
    # routing (any batch of >= E tokens touches all E experts), so the
    # capacity dispatch saves no bandwidth there — it only adds the
    # [T, E, C] mask-build chain (top_k/cumsum/one_hot/scatter) to a
    # memory-bound step (measured 10x off the weight-read roofline on
    # v5e, PERF.md r5). Shapes are static under jit, so the switch is
    # trace-time Python with zero runtime cost; prefill/training token
    # counts exceed the threshold and keep the capacity path. 0 pins
    # the capacity path at every shape (tests / A-B benches). The two
    # paths differ numerically when capacity binds, so call sites that
    # promise cross-program identity pin one path for all their
    # programs (speculative_generate, prefill_chunked).
    moe_dense_decode_tokens: int = 256
    # Router auxiliary loss weights for MoE TRAINING (Switch-style
    # load-balance + router z-loss, models/transformer.moe_router_aux);
    # inference ignores them.
    moe_aux_loss_weight: float = 0.01
    moe_z_loss_weight: float = 1e-3
    # Use the fused Pallas kernels (ops/pallas) for attention + RMSNorm on
    # the hot path; False = pure-XLA jnp reference ops.
    use_pallas: bool = False
    # Route full/prefill attention through ring attention
    # (parallel/ring.py) when a mesh with seq > 1 is passed to
    # forward/prefill — sequence-parallel long-context support.
    use_ring: bool = False

    @property
    def head_dim(self) -> int:
        return self.d_model // self.n_heads

    @property
    def is_moe(self) -> bool:
        return self.n_experts > 0

    def with_(self, **kw) -> "ModelConfig":
        return replace(self, **kw)

    # -- MoE dispatch-path selection (single source of truth) ----------
    # _mlp picks its path with moe_dense_at; call sites that promise
    # cross-program numeric identity pin one path for all their
    # programs with the two helpers below.

    def moe_dense_at(self, n_tokens: int) -> bool:
        """True when an MoE layer at this per-program token count traces
        the dense all-experts path (capacity factor 0, or at/below the
        trace-time dense-fallback threshold)."""
        return (
            self.moe_capacity_factor == 0
            or n_tokens <= self.moe_dense_decode_tokens
        )

    def with_moe_capacity_pinned(self) -> "ModelConfig":
        """Capacity dispatch at EVERY program shape (threshold 0)."""
        return self.with_(moe_dense_decode_tokens=0)

    def with_moe_dense_up_to(self, n_tokens: int) -> "ModelConfig":
        """Dense path for every program of <= n_tokens tokens (raises
        the threshold; never lowers it)."""
        return self.with_(
            moe_dense_decode_tokens=max(
                self.moe_dense_decode_tokens, n_tokens
            )
        )

    def moe_pin_for(
        self, ref_tokens: int, dense_up_to: int
    ) -> "ModelConfig":
        """Pin the dispatch path for a FAMILY of programs to the choice
        a reference program of ``ref_tokens`` tokens makes: dense for
        every program up to ``dense_up_to`` tokens when the reference
        side is dense, capacity at every shape otherwise. No-op for
        non-MoE / capacity-disabled configs.

        Pinning aligns the PATH only. When capacity genuinely binds,
        capacity dispatch remains approximate across program shapes
        (capacity C = ceil(T*k/E*factor) is per-program, so programs of
        different T can drop different tokens — GShard semantics);
        bitwise cross-program contracts hold on the dense side and at
        capacity factors generous enough that nothing drops."""
        if not (self.is_moe and self.moe_capacity_factor > 0):
            return self
        return (
            self.with_moe_dense_up_to(dense_up_to)
            if self.moe_dense_at(ref_tokens)
            else self.with_moe_capacity_pinned()
        )


PRESETS: dict[str, ModelConfig] = {
    # North-star flagship (BASELINE.json).
    "llama3-8b": ModelConfig(
        name="llama3-8b",
        vocab_size=128256,
        d_model=4096,
        n_layers=32,
        n_heads=32,
        n_kv_heads=8,
        d_ff=14336,
        rope_theta=500000.0,
        max_seq_len=8192,
    ),
    "mistral-7b": ModelConfig(
        name="mistral-7b",
        vocab_size=32000,
        d_model=4096,
        n_layers=32,
        n_heads=32,
        n_kv_heads=8,
        d_ff=14336,
        rope_theta=10000.0,
        max_seq_len=8192,
        sliding_window=4096,  # Mistral-7B-v0.1 windowed attention
    ),
    "qwen2-7b": ModelConfig(
        name="qwen2-7b",
        vocab_size=152064,
        d_model=3584,
        n_layers=28,
        n_heads=28,
        n_kv_heads=4,
        d_ff=18944,
        rope_theta=1000000.0,
        qkv_bias=True,
        max_seq_len=8192,
    ),
    "mixtral-8x7b": ModelConfig(
        name="mixtral-8x7b",
        vocab_size=32000,
        d_model=4096,
        n_layers=32,
        n_heads=32,
        n_kv_heads=8,
        d_ff=14336,
        rope_theta=1000000.0,
        n_experts=8,
        n_experts_per_token=2,
        # Capacity-bounded dispatch by default: the dense all-experts
        # path would spend E/k = 4x the needed FLOPs at this scale.
        moe_capacity_factor=1.25,
        max_seq_len=8192,
    ),
    # ~100M draft model sharing llama-1b's vocab — the speculative-
    # decoding draft for `bench.py --draft llama-draft-100m` (same
    # tokenizer/vocab is the only hard requirement for speculation).
    "llama-draft-100m": ModelConfig(
        name="llama-draft-100m",
        vocab_size=32000,
        d_model=768,
        n_layers=8,
        n_heads=12,
        n_kv_heads=4,
        d_ff=2048,
        rope_theta=10000.0,
        max_seq_len=4096,
    ),
    # ~1.1B dense config for single-chip benchmarking (fits v5e HBM in bf16
    # with a large candidate batch).
    "llama-1b": ModelConfig(
        name="llama-1b",
        vocab_size=32000,
        d_model=2048,
        n_layers=16,
        n_heads=16,
        n_kv_heads=8,
        d_ff=5632,
        rope_theta=10000.0,
        max_seq_len=4096,
    ),
    # ~14M byte-level model for the end-to-end accuracy loop
    # (examples/train_arith_em.py): small enough to train to high EM on
    # the synthetic arithmetic task in minutes on one chip, big enough
    # to actually learn two-step chain-of-thought arithmetic.
    "arith-14m": ModelConfig(
        name="arith-14m",
        vocab_size=384,
        d_model=384,
        n_layers=6,
        n_heads=6,
        n_kv_heads=6,
        d_ff=1536,
        max_seq_len=512,
    ),
    # ~25M byte-level model (6 x (4*512^2 + 3*512*2048) = 25.2M
    # non-embedding) for the MULTI-STEP accuracy loop (eval/arith2.py:
    # 2-4 chained ops, 6 narrative frames, distractor quantities).
    # Bigger than arith-14m because the task is genuinely harder, and
    # max_seq_len 768 because multi-step prompts+CoT reach ~650 bytes
    # (arith-14m's 512 truncates them).
    "arith-25m": ModelConfig(
        name="arith-25m",
        vocab_size=384,
        d_model=512,
        n_layers=6,
        n_heads=8,
        n_kv_heads=8,
        d_ff=2048,
        max_seq_len=768,
    ),
    # ~5.4M model with arith2's 768 context: the draft-scale sibling of
    # arith-25m (speculative decoding on the multi-step task needs a
    # draft whose context fits the ~650-byte prompts+CoT — arith-3m's
    # is too short). Measured 22 s/step on the 1-core host: NOT a CPU
    # training fallback; train it on chip (~1-2 min).
    "arith-6m": ModelConfig(
        name="arith-6m",
        vocab_size=384,
        d_model=256,
        n_layers=5,
        n_heads=4,
        n_kv_heads=4,
        d_ff=1024,
        max_seq_len=768,
    ),
    # ~0.94B-total-param MoE sized to run on ONE chip (VERDICT r4 item
    # 5: no MoE had ever touched real silicon — Mixtral-8x7B needs an
    # expert>=4 mesh, PERF.md). 4 experts top-2, Mixtral-style routing
    # and capacity bound; bf16 weights ~1.9 GiB, int8 ~0.95 GiB, so
    # decode at N=64 fits v5e HBM with room for the KV cache. Exercises
    # _moe_dispatch + the capacity-bounded path under REAL sampling.
    "moe-1b-4e": ModelConfig(
        name="moe-1b-4e",
        vocab_size=32000,
        d_model=1024,
        n_layers=16,
        n_heads=16,
        n_kv_heads=8,
        d_ff=4096,
        rope_theta=10000.0,
        n_experts=4,
        n_experts_per_token=2,
        moe_capacity_factor=1.25,
        max_seq_len=4096,
    ),
    # ~2.5M draft for arith-14m: trained on the same corpus it gives a
    # REAL speculative-decoding acceptance rate (examples/
    # spec_arith_demo.py) — between bench.py's --draft self ceiling and
    # random-weight floor.
    "arith-3m": ModelConfig(
        name="arith-3m",
        vocab_size=384,
        d_model=192,
        n_layers=4,
        n_heads=4,
        n_kv_heads=4,
        d_ff=768,
        max_seq_len=512,
    ),
    # Tiny configs for tests (CPU-simulated meshes). vocab 384 >= the
    # ByteTokenizer's 259 ids so end-to-end text tests can run on them.
    "test-tiny": ModelConfig(
        name="test-tiny",
        vocab_size=384,
        d_model=64,
        n_layers=2,
        n_heads=4,
        n_kv_heads=2,
        d_ff=128,
        max_seq_len=128,
    ),
    # Draft-sized sibling of test-tiny (same vocab — the one hard
    # requirement for speculation): the continuous batcher's
    # draft/verify tests and the CPU smoke of `bench.py
    # --serve-speculative` run this as the cheap proposal model.
    "test-tiny-draft": ModelConfig(
        name="test-tiny-draft",
        vocab_size=384,
        d_model=32,
        n_layers=1,
        n_heads=2,
        n_kv_heads=1,
        d_ff=64,
        max_seq_len=128,
    ),
    "test-tiny-moe": ModelConfig(
        name="test-tiny-moe",
        vocab_size=384,
        d_model=64,
        n_layers=2,
        n_heads=4,
        n_kv_heads=2,
        d_ff=128,
        n_experts=4,
        n_experts_per_token=2,
        max_seq_len=128,
    ),
}


def get_config(name: str) -> ModelConfig:
    try:
        return PRESETS[name]
    except KeyError:
        raise KeyError(
            f"unknown model preset {name!r}; available: {sorted(PRESETS)}"
        ) from None
