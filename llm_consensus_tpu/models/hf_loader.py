"""Load HuggingFace safetensors checkpoints into the stacked param tree.

The reference has no weights at all (its model is the remote Gemini API,
``src/main.rs:82-86``); this loader is how the TPU build gets real
Llama-3 / Mistral / Qwen2 / Mixtral weights (the model families named by
BASELINE.json's configs) into :mod:`llm_consensus_tpu.models.transformer`'s
layout:

- HF stores one ``[out, in]`` torch Linear weight per layer per proj;
  ours are ``[in, out]`` matmul weights stacked on a leading layer axis
  (one ``lax.scan`` block, SURVEY.md §7 step 1) — so each proj is
  transposed and the per-layer tensors stacked.
- HF RoPE uses the rotate-half convention, as does
  :mod:`llm_consensus_tpu.ops.rope` — weights map 1:1, no permutation.
- bf16 tensors cross torch→numpy via a uint16 view (numpy itself has no
  bfloat16; ml_dtypes supplies the dtype on the jax side).

Memory: tensors are read on demand through mmap'd shard handles (closed
when loading finishes) and cast to the target dtype as each stacked
tensor is assembled, so peak host memory stays ~1 model copy at target
dtype plus the transiently-mapped shards.
"""

from __future__ import annotations

import json
from pathlib import Path

import jax
import jax.numpy as jnp
import numpy as np

from llm_consensus_tpu.models.configs import ModelConfig, RopeScaling

# name templates: ours -> HF (dense). {i} = layer index.
_DENSE_MAP = {
    "attn_norm": "model.layers.{i}.input_layernorm.weight",
    "mlp_norm": "model.layers.{i}.post_attention_layernorm.weight",
    "wq": "model.layers.{i}.self_attn.q_proj.weight",
    "wk": "model.layers.{i}.self_attn.k_proj.weight",
    "wv": "model.layers.{i}.self_attn.v_proj.weight",
    "wo": "model.layers.{i}.self_attn.o_proj.weight",
    "bq": "model.layers.{i}.self_attn.q_proj.bias",
    "bk": "model.layers.{i}.self_attn.k_proj.bias",
    "bv": "model.layers.{i}.self_attn.v_proj.bias",
    "w_gate": "model.layers.{i}.mlp.gate_proj.weight",
    "w_up": "model.layers.{i}.mlp.up_proj.weight",
    "w_down": "model.layers.{i}.mlp.down_proj.weight",
}
_MOE_MAP = {
    "router": "model.layers.{i}.block_sparse_moe.gate.weight",
    # experts get an extra {e} axis; HF w1=gate, w3=up, w2=down.
    "w_gate": "model.layers.{i}.block_sparse_moe.experts.{e}.w1.weight",
    "w_up": "model.layers.{i}.block_sparse_moe.experts.{e}.w3.weight",
    "w_down": "model.layers.{i}.block_sparse_moe.experts.{e}.w2.weight",
}
# Linear weights stored [out, in] by torch; transpose to our [in, out].
_TRANSPOSED = {
    "wq", "wk", "wv", "wo", "w_gate", "w_up", "w_down", "router", "lm_head",
}


def _to_numpy(t) -> np.ndarray:
    """torch tensor (possibly bf16) -> numpy, zero-copy where possible."""
    import ml_dtypes
    import torch

    if t.dtype == torch.bfloat16:
        return t.view(torch.uint16).numpy().view(ml_dtypes.bfloat16)
    return t.numpy()


class _ShardedCheckpoint:
    """Random access over one or more .safetensors files in a directory."""

    def __init__(self, path: Path):
        self.path = path
        files = sorted(path.glob("*.safetensors"))
        if not files:
            raise FileNotFoundError(f"no .safetensors under {path}")
        index_file = path / "model.safetensors.index.json"
        self._name_to_file: dict[str, Path] = {}
        if index_file.exists():
            weight_map = json.loads(index_file.read_text())["weight_map"]
            for name, fname in weight_map.items():
                self._name_to_file[name] = path / fname
        else:
            from safetensors import safe_open

            for f in files:
                with safe_open(f, framework="pt") as sf:
                    for name in sf.keys():
                        self._name_to_file[name] = f
        self._open: dict[Path, object] = {}

    def __contains__(self, name: str) -> bool:
        return name in self._name_to_file

    def names(self):
        return self._name_to_file.keys()

    def get(self, name: str) -> np.ndarray:
        from safetensors import safe_open

        f = self._name_to_file[name]
        if f not in self._open:
            self._open[f] = safe_open(f, framework="pt")
        return _to_numpy(self._open[f].get_tensor(name))

    def close(self) -> None:
        """Release shard handles (and their mmaps)."""
        self._open.clear()


def _fetch(ckpt: _ShardedCheckpoint, name: str, ours: str, dtype):
    arr = ckpt.get(name).astype(dtype)
    if ours in _TRANSPOSED:
        arr = arr.T
    return arr


def load_hf_params(
    cfg: ModelConfig, path: str | Path, dtype=jnp.bfloat16
) -> dict:
    """Build an ``init_params``-shaped tree from an HF checkpoint dir.

    ``cfg`` must structurally match the checkpoint (layer count, dims,
    MoE-ness, qkv bias); mismatches raise with the offending tensor name.
    """
    path = Path(path)
    ckpt = _ShardedCheckpoint(path)
    try:
        return _load_hf_params(cfg, ckpt, dtype)
    finally:
        ckpt.close()


def _load_hf_params(cfg: ModelConfig, ckpt: _ShardedCheckpoint, dtype) -> dict:
    np_dtype = jnp.dtype(dtype)

    def stack_layers(ours: str, template: str) -> np.ndarray:
        per_layer = []
        for i in range(cfg.n_layers):
            name = template.format(i=i)
            if name not in ckpt:
                raise KeyError(
                    f"checkpoint missing {name!r} (for param {ours!r})"
                )
            per_layer.append(_fetch(ckpt, name, ours, np_dtype))
        return np.stack(per_layer)

    def stack_experts(ours: str, template: str) -> np.ndarray:
        per_layer = []
        for i in range(cfg.n_layers):
            per_layer.append(
                np.stack(
                    [
                        _fetch(
                            ckpt, template.format(i=i, e=e), ours, np_dtype
                        )
                        for e in range(cfg.n_experts)
                    ]
                )
            )
        return np.stack(per_layer)

    blocks: dict = {}
    for ours in ("attn_norm", "mlp_norm", "wq", "wk", "wv", "wo"):
        blocks[ours] = stack_layers(ours, _DENSE_MAP[ours])
    if cfg.qkv_bias:
        for ours in ("bq", "bk", "bv"):
            blocks[ours] = stack_layers(ours, _DENSE_MAP[ours])
    if cfg.is_moe:
        blocks["router"] = stack_layers("router", _MOE_MAP["router"])
        for ours in ("w_gate", "w_up", "w_down"):
            blocks[ours] = stack_experts(ours, _MOE_MAP[ours])
    else:
        for ours in ("w_gate", "w_up", "w_down"):
            blocks[ours] = stack_layers(ours, _DENSE_MAP[ours])

    params: dict = {
        "embed": ckpt.get("model.embed_tokens.weight").astype(np_dtype),
        "blocks": blocks,
        "norm_f": ckpt.get("model.norm.weight").astype(np_dtype),
    }
    if "lm_head.weight" in ckpt:
        if cfg.tie_embeddings:
            raise ValueError(
                "checkpoint has lm_head.weight but cfg.tie_embeddings=True"
            )
        params["lm_head"] = _fetch(
            ckpt, "lm_head.weight", "lm_head", np_dtype
        )
    elif not cfg.tie_embeddings:
        raise ValueError(
            "checkpoint has no lm_head.weight; set cfg.tie_embeddings=True"
        )

    _validate_shapes(cfg, params)
    return jax.tree_util.tree_map(jnp.asarray, params)


def _validate_shapes(cfg: ModelConfig, params: dict) -> None:
    L, D = cfg.n_layers, cfg.d_model
    Dh = cfg.head_dim
    expect = {
        ("blocks", "wq"): (L, D, cfg.n_heads * Dh),
        ("blocks", "wk"): (L, D, cfg.n_kv_heads * Dh),
        ("blocks", "wo"): (L, cfg.n_heads * Dh, D),
        ("embed",): (cfg.vocab_size, D),
    }
    for keys, shape in expect.items():
        node = params
        for k in keys:
            node = node[k]
        if tuple(node.shape) != shape:
            raise ValueError(
                f"{'.'.join(keys)}: checkpoint shape {tuple(node.shape)} != "
                f"config {shape} — wrong ModelConfig for this checkpoint?"
            )


def config_from_hf(path: str | Path, name: str = "hf") -> ModelConfig:
    """Derive a ModelConfig from an HF ``config.json``.

    Raises on config features we would otherwise silently mis-compute
    (unknown rope_scaling types).
    """
    hf = json.loads((Path(path) / "config.json").read_text())
    arch = (hf.get("architectures") or [""])[0]
    is_moe = "Mixtral" in arch or "num_local_experts" in hf

    rope_scaling = None
    rs = hf.get("rope_scaling")
    if rs:
        rs_type = rs.get("rope_type") or rs.get("type")
        if rs_type != "llama3":
            raise ValueError(
                f"unsupported rope_scaling type {rs_type!r} — only 'llama3' "
                "(Llama-3.1) frequency rescaling is implemented"
            )
        rope_scaling = RopeScaling(
            factor=float(rs["factor"]),
            low_freq_factor=float(rs["low_freq_factor"]),
            high_freq_factor=float(rs["high_freq_factor"]),
            original_max_position_embeddings=int(
                rs["original_max_position_embeddings"]
            ),
        )

    # Mistral: sliding_window set => windowed attention. Qwen2 ships a
    # sliding_window value but gates it off with use_sliding_window.
    sliding_window = int(hf.get("sliding_window") or 0)
    if "Qwen2" in arch and not hf.get("use_sliding_window", False):
        sliding_window = 0

    return ModelConfig(
        name=name,
        vocab_size=hf["vocab_size"],
        d_model=hf["hidden_size"],
        n_layers=hf["num_hidden_layers"],
        n_heads=hf["num_attention_heads"],
        n_kv_heads=hf.get("num_key_value_heads", hf["num_attention_heads"]),
        d_ff=hf.get("moe_intermediate_size") or hf["intermediate_size"],
        rope_theta=float(hf.get("rope_theta", 10000.0)),
        rope_scaling=rope_scaling,
        rms_norm_eps=float(hf.get("rms_norm_eps", 1e-5)),
        max_seq_len=int(hf.get("max_position_embeddings", 8192)),
        sliding_window=sliding_window,
        qkv_bias="Qwen2" in arch,
        tie_embeddings=bool(hf.get("tie_word_embeddings", False)),
        n_experts=int(hf.get("num_local_experts", 0)) if is_moe else 0,
        n_experts_per_token=int(hf.get("num_experts_per_tok", 2)),
    )
