"""Paged KV cache: fixed page pool + per-sequence page tables.

The dense :class:`llm_consensus_tpu.models.cache.KVCache` allocates
``B x max_len`` up front — fine for uniform self-consistency fan-out,
wasteful for a serving mix of short and long requests. The paged layout
(vLLM-style, re-founded on XLA static shapes) keeps one global pool of
fixed-size pages; each sequence owns an ordered list of page ids. All
shapes are static: admission/eviction mutate *data* (page tables,
lengths), never shapes, so the decode program compiles exactly once.

The reference has no KV cache (no model code at all, SURVEY.md §0); this
is infrastructure for the serving path the build adds (SURVEY.md §7
step 5-6, BASELINE.json throughput targets).

Layout:
- pool k/v: ``[L, n_pages, page_size, Hkv, Dh]``
- page_table: ``[max_seqs, pages_per_seq]`` int32 page ids (unused
  entries can hold any valid id; masking is by ``length``).
- length: ``[max_seqs]`` tokens written per sequence.

Page 0 is reserved as the "null" page so freshly-reset tables are valid.

Two host-side structures complete the picture (PR 2):

- :class:`PagePool` — refcounted page allocator. A page mapped into N
  live page tables (plus optionally the prefix registry) carries
  refcount N(+1) and returns to the free list only when the last holder
  releases it, which is what makes COPY-ON-WRITE page sharing safe:
  full pages of a common prompt prefix are *mapped*, never rewritten
  (decode writes only at positions >= prompt_len, i.e. never into a
  fully-shared prefix page), and any page that WOULD be written —
  the partially-filled boundary page — is copied, never shared.
- :class:`PrefixRegistry` — a radix tree of page-aligned prompt
  prefixes keyed by page-sized token runs, so the consensus panel's N
  requests over one question prefill the shared header once and every
  later admission maps the already-resident pages.
"""

from __future__ import annotations

from collections import deque
from dataclasses import dataclass, field
from typing import Iterable, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from llm_consensus_tpu.models.configs import ModelConfig

NULL_PAGE = 0


def prefix_chain_key(
    ids: Sequence[int], page_size: int
) -> tuple[tuple[int, ...], ...]:
    """A prompt's page-aligned prefix-chain fingerprint: the tuple of
    page-sized token runs that key both the :class:`PrefixRegistry`
    radix walk and the host tier's chain keys — capped at the USABLE
    full pages (at least the last prompt token is always recomputed,
    so a prompt's final partial/whole page never participates in
    sharing; the same ``usable_full`` cap :meth:`PrefixRegistry.match`
    applies).

    Exported for the replica fleet (PR 14): the router fingerprints a
    request ONCE and compares it against every replica's resident
    chains — "requests sharing a radix-registry chain land where the
    pages already live" needs exactly this identity, computed the same
    way the registry computes it.
    """
    usable_full = (len(ids) - 1) // page_size
    return tuple(
        tuple(int(t) for t in ids[k * page_size : (k + 1) * page_size])
        for k in range(usable_full)
    )


@jax.tree_util.register_dataclass
@dataclass
class PagedKVCache:
    k: jnp.ndarray  # [L, n_pages, page_size, Hkv, Dh]
    v: jnp.ndarray
    page_table: jnp.ndarray  # [max_seqs, pages_per_seq] int32
    length: jnp.ndarray  # [max_seqs] int32

    @staticmethod
    def create(
        cfg: ModelConfig,
        n_pages: int,
        page_size: int,
        max_seqs: int,
        pages_per_seq: int,
        dtype=jnp.bfloat16,
    ) -> "PagedKVCache":
        shape = (cfg.n_layers, n_pages, page_size, cfg.n_kv_heads, cfg.head_dim)
        return PagedKVCache(
            k=jnp.zeros(shape, dtype),
            v=jnp.zeros(shape, dtype),
            page_table=jnp.full((max_seqs, pages_per_seq), NULL_PAGE, jnp.int32),
            length=jnp.zeros((max_seqs,), jnp.int32),
        )

    @property
    def page_size(self) -> int:
        return self.k.shape[2]

    @property
    def n_pages(self) -> int:
        return self.k.shape[1]

    @property
    def max_seqs(self) -> int:
        return self.page_table.shape[0]

    @property
    def pages_per_seq(self) -> int:
        return self.page_table.shape[1]


def gather_seq_kv(
    cache: PagedKVCache, seq_ids: jnp.ndarray
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Materialize contiguous [L, B, pages_per_seq*page, Hkv, Dh] K/V for
    the given sequences (the jnp reference path; a Pallas kernel can read
    through the table instead)."""
    tables = cache.page_table[seq_ids]  # [B, P]
    k = cache.k[:, tables]  # [L, B, P, page, Hkv, Dh]
    v = cache.v[:, tables]
    L, b, p, pg, h, d = k.shape
    return k.reshape(L, b, p * pg, h, d), v.reshape(L, b, p * pg, h, d)


def write_decode_kv(
    cache: PagedKVCache,
    seq_ids: jnp.ndarray,  # [B]
    k_new: jnp.ndarray,  # [L, B, Hkv, Dh]
    v_new: jnp.ndarray,
) -> PagedKVCache:
    """Write one token's K/V for each sequence at its current length."""
    pos = cache.length[seq_ids]  # [B]
    page_idx = pos // cache.page_size
    offset = pos % cache.page_size
    pages = cache.page_table[seq_ids, page_idx]  # [B]
    k = cache.k.at[:, pages, offset].set(k_new.astype(cache.k.dtype))
    v = cache.v.at[:, pages, offset].set(v_new.astype(cache.v.dtype))
    length = cache.length.at[seq_ids].add(1)
    return PagedKVCache(k=k, v=v, page_table=cache.page_table, length=length)


def write_prefill_kv(
    cache: PagedKVCache,
    seq_id: jnp.ndarray,  # scalar int32
    k_seq: jnp.ndarray,  # [L, S, Hkv, Dh] (S = padded prompt bucket)
    v_seq: jnp.ndarray,
    length: jnp.ndarray,  # scalar true prompt length
) -> PagedKVCache:
    """Scatter one prefilled sequence's K/V into its assigned pages.

    S must be a multiple of page_size; slots past ``length`` hold padding
    garbage, masked out of attention by ``length`` exactly as the dense
    cache masks by ``valid_len``.
    """
    L, s, h, d = k_seq.shape
    pg = cache.page_size
    if s % pg:
        raise ValueError(f"prefill length {s} not a multiple of page {pg}")
    n = s // pg
    pages = jax.lax.dynamic_slice_in_dim(
        cache.page_table[seq_id], 0, n
    )  # [n]
    k_pages = k_seq.reshape(L, n, pg, h, d).astype(cache.k.dtype)
    v_pages = v_seq.reshape(L, n, pg, h, d).astype(cache.v.dtype)
    k = cache.k.at[:, pages].set(k_pages)
    v = cache.v.at[:, pages].set(v_pages)
    new_len = cache.length.at[seq_id].set(length.astype(jnp.int32))
    return PagedKVCache(k=k, v=v, page_table=cache.page_table, length=new_len)


def assign_pages(
    cache: PagedKVCache, seq_id: jnp.ndarray, pages: jnp.ndarray
) -> PagedKVCache:
    """Install a page list (padded with NULL_PAGE) for one sequence."""
    table = cache.page_table.at[seq_id].set(pages.astype(jnp.int32))
    return PagedKVCache(
        k=cache.k, v=cache.v, page_table=table, length=cache.length
    )


def release_seq(cache: PagedKVCache, seq_id: jnp.ndarray) -> PagedKVCache:
    """Clear a sequence's table/length (page recycling is host-side)."""
    table = cache.page_table.at[seq_id].set(NULL_PAGE)
    length = cache.length.at[seq_id].set(0)
    return PagedKVCache(
        k=cache.k, v=cache.v, page_table=table, length=length
    )


def install_seq(
    cache: PagedKVCache,
    seq_id: jnp.ndarray,
    pages: jnp.ndarray,
    length: jnp.ndarray,
) -> PagedKVCache:
    """Install table AND length for one sequence in one pass — the
    moment a chunk-prefilled sequence (whose pages were written through
    an explicit host-side table, invisible to the decode program)
    becomes a live decode row."""
    table = cache.page_table.at[seq_id].set(pages.astype(jnp.int32))
    new_len = cache.length.at[seq_id].set(length.astype(jnp.int32))
    return PagedKVCache(
        k=cache.k, v=cache.v, page_table=table, length=new_len
    )


def copy_page(
    cache: PagedKVCache, src: jnp.ndarray, dst: jnp.ndarray
) -> PagedKVCache:
    """Copy one page's K/V across all layers (``src`` -> ``dst``).

    The copy-on-write primitive: when an admission's prompt shares a
    registered prefix that ends INSIDE a page, that boundary page's
    already-computed K/V is copied into a freshly-allocated private
    page — sharing it would let this sequence's later prefill/decode
    writes corrupt every other reader.
    """
    k = cache.k.at[:, dst].set(cache.k[:, src])
    v = cache.v.at[:, dst].set(cache.v[:, src])
    return PagedKVCache(
        k=k, v=v, page_table=cache.page_table, length=cache.length
    )


def install_page(
    cache: PagedKVCache,
    page: jnp.ndarray,
    k_page: jnp.ndarray,  # [L, page_size, Hkv, Dh]
    v_page: jnp.ndarray,
) -> PagedKVCache:
    """Write one page's K/V across all layers from host-side planes.

    The offload tier's promote primitive
    (:mod:`llm_consensus_tpu.serving.offload`): a page demoted to host
    RAM comes back through this op verbatim — same dtype, same bytes —
    so a restored prefix is indistinguishable from one that never left
    the pool.
    """
    k = cache.k.at[:, page].set(k_page.astype(cache.k.dtype))
    v = cache.v.at[:, page].set(v_page.astype(cache.v.dtype))
    return PagedKVCache(
        k=k, v=v, page_table=cache.page_table, length=cache.length
    )


def install_pages(
    cache: PagedKVCache,
    pages: jnp.ndarray,  # [N]
    k_pages: jnp.ndarray,  # [L, N, page_size, Hkv, Dh]
    v_pages: jnp.ndarray,
) -> PagedKVCache:
    """:func:`install_page` for N pages in one scatter — the restore
    half of the batching contract the demote side already keeps (one
    ``device_get`` per evict walk): one host->device transfer and one
    program launch per restore BATCH instead of per page. ``pages``
    must be distinct (restore plans are, by construction: each page is
    a different chain prefix)."""
    k = cache.k.at[:, pages].set(k_pages.astype(cache.k.dtype))
    v = cache.v.at[:, pages].set(v_pages.astype(cache.v.dtype))
    return PagedKVCache(
        k=k, v=v, page_table=cache.page_table, length=cache.length
    )


# ---------------------------------------------------------------------------
# Host-side allocation: refcounted pages + prefix radix tree
# ---------------------------------------------------------------------------


class PagePool:
    """Refcounted host-side page allocator over a fixed id range.

    Callers hold pages by id; a page is free exactly when its refcount
    is zero. Fresh allocations start at refcount 1; mapping an existing
    page into another sequence's table goes through :meth:`share`;
    every holder (sequences AND the prefix registry) pairs its hold
    with exactly one :meth:`release`. Not thread-safe — callers
    serialize under their own lock (the continuous batcher's worker
    owns its pools).
    """

    def __init__(self, page_ids: Iterable[int]):
        self._free: deque[int] = deque(page_ids)
        self._rc: dict[int, int] = {}

    @property
    def available(self) -> int:
        """Pages allocatable right now (excludes shared/cached pages)."""
        return len(self._free)

    @property
    def held(self) -> int:
        return len(self._rc)

    def refcount(self, page: int) -> int:
        return self._rc.get(page, 0)

    def alloc(self, n: int) -> list[int]:
        if n > len(self._free):
            raise RuntimeError(
                f"page pool exhausted: want {n}, have {len(self._free)}"
            )
        pages = [self._free.popleft() for _ in range(n)]
        for p in pages:
            self._rc[p] = 1
        return pages

    def share(self, page: int) -> None:
        if page not in self._rc:
            raise ValueError(f"page {page} is not allocated")
        self._rc[page] += 1

    def release(self, page: int) -> None:
        rc = self._rc.get(page)
        if rc is None:
            raise ValueError(f"page {page} is not allocated")
        if rc == 1:
            del self._rc[page]
            self._free.append(page)
        else:
            self._rc[page] = rc - 1


@dataclass
class _PrefixNode:
    """One page-sized token run in the prefix radix tree."""

    tokens: tuple[int, ...]
    page: int
    parent: "_PrefixNode | None"
    children: dict[tuple[int, ...], "_PrefixNode"] = field(
        default_factory=dict
    )
    # Content of ``page`` is fully written (the registering sequence's
    # prefill has passed this page's end). Readers — a matching
    # admission's chunk prefill, the boundary-page copy — must wait for
    # this flag; the page ids themselves are safe to map immediately.
    ready: bool = False
    # LRU tick for eviction (registry-maintained).
    last_used: int = 0


@dataclass
class PrefixMatch:
    """What an admission gets back from :meth:`PrefixRegistry.match`."""

    pages: list[int]  # full shared pages, prefix order (refs bumped)
    nodes: list[_PrefixNode]  # their nodes (readiness gates)
    shared_tokens: int  # len(pages) * page_size
    # Boundary page eligible for copy-on-write: its first
    # ``boundary_common`` tokens extend this prompt's prefix past the
    # full-page match. None when no partially-matching sibling exists
    # or its content is not ready yet (copying garbage helps nobody).
    boundary_page: int | None = None
    boundary_common: int = 0


class PrefixRegistry:
    """Radix tree of page-aligned prompt prefixes over one PagePool.

    Nodes are keyed by the exact token tuple of each page-sized run, so
    lookup is a dict walk (no hashing subtleties — the token run IS the
    key). The registry holds one refcount on every node's page; match
    bumps refcounts for the caller (caller releases per page on
    retirement, exactly like privately-allocated pages).

    Registration happens at ADMISSION (before content exists) so that a
    burst of same-prefix requests — the consensus panel — dedups
    against the FIRST request's in-flight prefill instead of racing it;
    ``_PrefixNode.ready`` gates content readers.
    """

    def __init__(self, pool: PagePool, page_size: int):
        self.pool = pool
        self.page_size = page_size
        self._root = _PrefixNode(tokens=(), page=NULL_PAGE, parent=None)
        self._nodes = 0
        self._tick = 0
        # Monotonic counters (the serving layer exports these).
        self.lookups = 0
        self.hits = 0
        self.pages_shared = 0
        self.pages_copied = 0
        self.evictions = 0
        # Offload tier (PR 4): called ONCE per evict() walk with the
        # list of READY victim nodes, turning eviction from destruction
        # into demotion — the callback spills the pages' content to
        # host RAM keyed by :meth:`chain_tokens`, in one batched host
        # transfer (a per-victim hook would stall admission on N
        # sequential device_gets). None = plain eviction.
        self.on_evict = None

    def __len__(self) -> int:
        return self._nodes

    @property
    def cached_pages(self) -> int:
        return self._nodes

    def reclaimable_pages(self) -> int:
        """Registry pages held by nobody else AND actually freeable via
        :meth:`evict`.

        evict() only ever drops leaves, so an interior node's page is
        reclaimable only when its whole subtree is: a registry-only
        parent above a child some live sequence still maps (refcount
        > 1) can never be reached by eviction and must not be counted —
        counting every refcount-1 node would overstate free capacity
        and break the pool invariant ``available + pinned + reclaimable
        == total`` (evict(∞) frees exactly this number; tested).
        """

        def subtree(node: _PrefixNode) -> tuple[int, bool]:
            total, children_ok = 0, True
            for child in node.children.values():
                n, ok = subtree(child)
                total += n
                children_ok = children_ok and ok
            ok = children_ok and self.pool.refcount(node.page) == 1
            return total + (1 if ok else 0), ok

        return sum(subtree(c)[0] for c in self._root.children.values())

    def _walk(self):
        stack = list(self._root.children.values())
        while stack:
            node = stack.pop()
            stack.extend(node.children.values())
            yield node

    def match(self, ids: Sequence[int], min_boundary: int = 1) -> PrefixMatch:
        """Longest registered page-aligned prefix of ``ids``.

        Sharing is capped at ``len(ids) - 1`` tokens: at least the last
        prompt token must be (re)computed so the admission has a hidden
        state to sample the first token from. Matched pages' refcounts
        are bumped FOR THE CALLER (release per page on retirement).
        The boundary page (a sibling run extending the match part-way)
        is reported for copy-on-write but NOT ref-bumped — the caller
        copies content, so it allocates its own destination page.

        ``min_boundary``: smallest common run worth a page copy —
        below it the caller recomputes those tokens anyway, and a
        trivial overlap (every prompt shares BOS) must not trigger a
        copy per admission.
        """
        pg = self.page_size
        self.lookups += 1
        self._tick += 1
        node = self._root
        pages: list[int] = []
        nodes: list[_PrefixNode] = []
        # Only prefixes strictly shorter than the prompt are usable.
        usable_full = (len(ids) - 1) // pg
        k = 0
        while k < usable_full:
            key = tuple(int(t) for t in ids[k * pg : (k + 1) * pg])
            child = node.children.get(key)
            if child is None:
                break
            child.last_used = self._tick
            self.pool.share(child.page)
            pages.append(child.page)
            nodes.append(child)
            node = child
            k += 1
        match = PrefixMatch(
            pages=pages,
            nodes=nodes,
            shared_tokens=k * pg,
        )
        # Boundary: a child run whose first tokens extend our prefix
        # but diverge (or run past our prompt) before the page ends.
        rem = tuple(int(t) for t in ids[k * pg :])
        cap = len(rem) - 1  # leave >= 1 token to prefill
        if cap > 0:
            best, best_child = 0, None
            for key, child in node.children.items():
                if not child.ready:
                    continue
                common = 0
                for a, b in zip(key, rem):
                    if a != b:
                        break
                    common += 1
                if common > best:
                    best, best_child = common, child
            if best_child is not None and min(best, cap) >= min_boundary:
                best_child.last_used = self._tick
                match.boundary_page = best_child.page
                match.boundary_common = min(best, cap)
        return match

    def probe(self, ids: Sequence[int]) -> tuple[list[_PrefixNode], int]:
        """Read-only longest-prefix walk: which registered nodes cover
        this prompt's page-aligned prefix, and how many tokens they
        span. NO side effects — no refcount bumps, no LRU ticks, no
        hit/lookup counters — so the fleet router (PR 14) can probe
        every replica per request without perturbing the eviction
        order or the admission-committed hit statistics that
        :meth:`match` + :meth:`record_commit` own.

        Unready nodes COUNT: their page identity is established at
        admission (PR 2), so a concurrent same-prefix burst probes the
        donor's replica as a match while the donor's prefill is still
        in flight — exactly the affinity the router needs.
        """
        pg = self.page_size
        node = self._root
        nodes: list[_PrefixNode] = []
        usable_full = (len(ids) - 1) // pg
        k = 0
        while k < usable_full:
            key = tuple(int(t) for t in ids[k * pg : (k + 1) * pg])
            child = node.children.get(key)
            if child is None:
                break
            nodes.append(child)
            node = child
            k += 1
        return nodes, k * pg

    def record_commit(self, match: PrefixMatch, copied: bool) -> None:
        """Count a match the caller actually ADMITTED on. Kept separate
        from :meth:`match` so a plan that rolls back (pool too full,
        table overflow) never inflates hits/pages_shared — the numbers
        stats()/bench report must agree with the Prometheus counters,
        which also count only committed admissions."""
        if match.pages or match.boundary_common:
            self.hits += 1
        self.pages_shared += len(match.pages)
        if copied:
            self.pages_copied += 1

    def register(
        self, ids: Sequence[int], pages: Sequence[int]
    ) -> list[tuple[_PrefixNode, int]]:
        """Offer a sequence's full prompt pages to the tree.

        ``pages[i]`` must hold tokens ``ids[i*pg : (i+1)*pg]`` (or be
        about to — see readiness). Runs already present are skipped (the
        existing node keeps its page; ours stays private). Returns the
        [(node, end_position)] list of NEWLY created nodes the caller
        must mark ready (:meth:`mark_ready`) as its prefill writes past
        each ``end_position``.
        """
        pg = self.page_size
        self._tick += 1
        node = self._root
        created: list[tuple[_PrefixNode, int]] = []
        full = min(len(ids) // pg, len(pages))
        for k in range(full):
            key = tuple(int(t) for t in ids[k * pg : (k + 1) * pg])
            child = node.children.get(key)
            if child is None:
                self.pool.share(pages[k])  # the registry's own hold
                child = _PrefixNode(
                    tokens=key, page=pages[k], parent=node
                )
                node.children[key] = child
                self._nodes += 1
                created.append((child, (k + 1) * pg))
            child.last_used = self._tick
            node = child
        return created

    @staticmethod
    def mark_ready(node: _PrefixNode) -> None:
        node.ready = True

    @staticmethod
    def chain_tokens(node: _PrefixNode) -> tuple[int, ...]:
        """Every token from the prefix root through ``node``'s page —
        the offload tier's key. A page's K/V content is a function of
        the WHOLE token chain above it (attention reads every earlier
        position), so the page run alone is not a sound identity; the
        full chain is.
        """
        runs: list[tuple[int, ...]] = []
        while node is not None and node.parent is not None:
            runs.append(node.tokens)
            node = node.parent
        return tuple(t for run in reversed(runs) for t in run)

    def evict(self, n_pages: int) -> int:
        """Free up to ``n_pages`` registry-only pages (LRU leaves first).

        Only leaves whose page nobody else holds are dropped — evicting
        a page mapped into a live sequence would free nothing and
        forfeit future sharing. One tree walk total (this runs inside
        the batcher's admission lock): eligible leaves are collected
        once into an LRU heap, and a parent enters the heap only when
        evicting its last child exposes it. Returns pages freed.

        With :attr:`on_evict` set (the offload tier), the READY victims
        are offered to the callback — once, as a batch — before evict()
        returns: demotion, not destruction. Their pages are back on the
        free list by then, but nothing re-WRITES a page until a later
        alloc+prefill/copy enqueues work, and the callback completes
        its host fetch synchronously first. Unready victims — their
        prefill/restore never completed — hold garbage and are dropped
        without a callback.
        """
        import heapq

        heap = [
            (node.last_used, id(node), node)
            for node in self._walk()
            if not node.children and self.pool.refcount(node.page) == 1
        ]
        heapq.heapify(heap)
        freed = 0
        demote: list[_PrefixNode] = []
        while heap and freed < n_pages:
            _, _, victim = heapq.heappop(heap)
            parent = victim.parent
            if self.on_evict is not None and victim.ready:
                demote.append(victim)
            del parent.children[victim.tokens]
            self.pool.release(victim.page)
            self._nodes -= 1
            self.evictions += 1
            freed += 1
            if (
                parent is not self._root
                and not parent.children
                and self.pool.refcount(parent.page) == 1
            ):
                heapq.heappush(heap, (parent.last_used, id(parent), parent))
        if demote:
            # Unlinked nodes keep their parent/tokens attrs, so
            # chain_tokens still resolves the full key here.
            self.on_evict(demote)
        return freed


# ---------------------------------------------------------------------------
# Decode groups: which resident sequences share a prefix page run
# ---------------------------------------------------------------------------


@jax.tree_util.register_dataclass
@dataclass
class DecodeGroupArrays:
    """Device-side group metadata for the group-aware decode kernel
    (:func:`llm_consensus_tpu.ops.pallas.paged_decode_attention_grouped`).

    All int32. ``group_id`` [max_seqs]: group per row, -1 ungrouped;
    ``group_rep`` [Gm]: a member row whose page table holds the group's
    shared run; ``group_pages`` [Gm]: pages in that run (0 = padding
    slot); ``shared_start`` [max_seqs]: tokens the shared phase covers
    per row (page-aligned; 0 for ungrouped rows).
    """

    group_id: jnp.ndarray
    group_rep: jnp.ndarray
    group_pages: jnp.ndarray
    shared_start: jnp.ndarray


class GroupTracker:
    """Host-side decode-group metadata over shared prefix page runs.

    Every decoding sequence registers its PREFIX RUN — the page ids
    covering its prompt's full pages, in table order. Two runs that
    begin with the same page ids hold the same tokens by construction
    (pages are shared exclusively through the :class:`PrefixRegistry`'s
    refcount mapping, and decode never writes into a full prompt page),
    so sequences are grouped by the longest common prefix of their
    runs: a consensus panel's donor (which allocated and REGISTERED the
    header pages) and its N-1 mappers land in one group even though the
    donor's own run extends past the header. The grouped kernel then
    reads each group's common run once per step; members' remaining
    pages are their suffix.

    Membership updates incrementally at activation/retirement (O(1)
    dict ops); the device arrays rebuild lazily on the next
    :meth:`arrays` after a change. Grouping is one level (common prefix
    per first-page bucket, not a full trie): nested sharing patterns
    degrade to the bucket-wide common run, never to wrong output.
    Single-member buckets emit nothing (reading a run once for one
    reader is what the ungrouped kernel already does) and only the
    ``max_groups`` largest groups emit — overflow rows simply stay
    ungrouped, correct either way.

    Not thread-safe: the continuous batcher's worker owns it, exactly
    like the pools/registries.
    """

    def __init__(
        self, max_seqs: int, page_size: int, max_groups: int | None = None
    ):
        self.max_seqs = max_seqs
        self.page_size = page_size
        self.max_groups = max_groups or max(1, max_seqs // 2)
        self._run_of_seq: dict[int, tuple[int, ...]] = {}
        self._dirty = True
        self._cached: DecodeGroupArrays | None = None
        # Step stats for the arrays most recently built: tokens of KV
        # the grouped read dedups per decode step, and the largest
        # group's member count (the observability satellites).
        # ``peak_group`` is the lifetime high-water mark — the number a
        # post-burst stats() read still sees after every member retired.
        self.saved_tokens_per_step = 0
        self.largest_group = 0
        self.peak_group = 0

    def add(self, seq_id: int, prefix_run: Sequence[int]) -> None:
        """Register a decoding sequence's prompt prefix page run (no-op
        for an empty run — a sub-page prompt stays ungrouped)."""
        run = tuple(int(p) for p in prefix_run)
        self.remove(seq_id)
        if not run:
            return
        self._run_of_seq[seq_id] = run
        self._dirty = True

    def remove(self, seq_id: int) -> None:
        if self._run_of_seq.pop(seq_id, None) is not None:
            self._dirty = True

    def stream_buckets(self) -> list[list[int]]:
        """Registered seqs bucketed by shared FIRST prefix page — the
        candidate sets for panel-shared draft streams (PR 9: members of
        one bucket decode over one prompt header, so a donor's
        committed-suffix + fresh-draft stream is reusable by any mate
        whose committed text still agrees). First-page granularity like
        :meth:`arrays`' grouping; only >= 2-member buckets return."""
        buckets: dict[int, list[int]] = {}
        for seq, run in self._run_of_seq.items():
            buckets.setdefault(run[0], []).append(seq)
        return [sorted(s) for s in buckets.values() if len(s) >= 2]

    @staticmethod
    def _common_prefix(runs: list[tuple[int, ...]]) -> int:
        k = 0
        for pages in zip(*runs):
            if any(p != pages[0] for p in pages[1:]):
                break
            k += 1
        return k

    def arrays(self) -> DecodeGroupArrays | None:
        """Current group metadata as device arrays, or None when no
        group has >= 2 members (the caller then runs the plain
        ungrouped program — the automatic fallback)."""
        if not self._dirty:
            return self._cached
        self._dirty = False
        pg = self.page_size
        buckets: dict[int, list[int]] = {}
        for seq, run in self._run_of_seq.items():
            buckets.setdefault(run[0], []).append(seq)
        groups: list[tuple[int, list[int]]] = []  # (lcp_pages, members)
        for seqs in buckets.values():
            if len(seqs) < 2:
                continue
            lcp = self._common_prefix([self._run_of_seq[s] for s in seqs])
            if lcp > 0:
                groups.append((lcp, sorted(seqs)))
        groups.sort(key=lambda g: -(g[0] * len(g[1])))
        groups = groups[: self.max_groups]
        if not groups:
            self._cached = None
            self.saved_tokens_per_step = 0
            self.largest_group = 0
            return None
        gid = np.full((self.max_seqs,), -1, np.int32)
        rep = np.zeros((self.max_groups,), np.int32)
        gpages = np.zeros((self.max_groups,), np.int32)
        start = np.zeros((self.max_seqs,), np.int32)
        saved = 0
        largest = 0
        for g, (lcp, members) in enumerate(groups):
            rep[g] = members[0]
            gpages[g] = lcp
            largest = max(largest, len(members))
            saved += (len(members) - 1) * lcp * pg
            for s in members:
                gid[s] = g
                start[s] = lcp * pg
        self.saved_tokens_per_step = saved
        self.largest_group = largest
        self.peak_group = max(self.peak_group, largest)
        self._cached = DecodeGroupArrays(
            group_id=jnp.asarray(gid),
            group_rep=jnp.asarray(rep),
            group_pages=jnp.asarray(gpages),
            shared_start=jnp.asarray(start),
        )
        return self._cached
