"""Paged KV cache: fixed page pool + per-sequence page tables.

The dense :class:`llm_consensus_tpu.models.cache.KVCache` allocates
``B x max_len`` up front — fine for uniform self-consistency fan-out,
wasteful for a serving mix of short and long requests. The paged layout
(vLLM-style, re-founded on XLA static shapes) keeps one global pool of
fixed-size pages; each sequence owns an ordered list of page ids. All
shapes are static: admission/eviction mutate *data* (page tables,
lengths), never shapes, so the decode program compiles exactly once.

The reference has no KV cache (no model code at all, SURVEY.md §0); this
is infrastructure for the serving path the build adds (SURVEY.md §7
step 5-6, BASELINE.json throughput targets).

Layout:
- pool k/v: ``[L, n_pages, page_size, Hkv, Dh]``
- page_table: ``[max_seqs, pages_per_seq]`` int32 page ids (unused
  entries can hold any valid id; masking is by ``length``).
- length: ``[max_seqs]`` tokens written per sequence.

Page 0 is reserved as the "null" page so freshly-reset tables are valid.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp

from llm_consensus_tpu.models.configs import ModelConfig

NULL_PAGE = 0


@jax.tree_util.register_dataclass
@dataclass
class PagedKVCache:
    k: jnp.ndarray  # [L, n_pages, page_size, Hkv, Dh]
    v: jnp.ndarray
    page_table: jnp.ndarray  # [max_seqs, pages_per_seq] int32
    length: jnp.ndarray  # [max_seqs] int32

    @staticmethod
    def create(
        cfg: ModelConfig,
        n_pages: int,
        page_size: int,
        max_seqs: int,
        pages_per_seq: int,
        dtype=jnp.bfloat16,
    ) -> "PagedKVCache":
        shape = (cfg.n_layers, n_pages, page_size, cfg.n_kv_heads, cfg.head_dim)
        return PagedKVCache(
            k=jnp.zeros(shape, dtype),
            v=jnp.zeros(shape, dtype),
            page_table=jnp.full((max_seqs, pages_per_seq), NULL_PAGE, jnp.int32),
            length=jnp.zeros((max_seqs,), jnp.int32),
        )

    @property
    def page_size(self) -> int:
        return self.k.shape[2]

    @property
    def n_pages(self) -> int:
        return self.k.shape[1]

    @property
    def max_seqs(self) -> int:
        return self.page_table.shape[0]

    @property
    def pages_per_seq(self) -> int:
        return self.page_table.shape[1]


def gather_seq_kv(
    cache: PagedKVCache, seq_ids: jnp.ndarray
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Materialize contiguous [L, B, pages_per_seq*page, Hkv, Dh] K/V for
    the given sequences (the jnp reference path; a Pallas kernel can read
    through the table instead)."""
    tables = cache.page_table[seq_ids]  # [B, P]
    k = cache.k[:, tables]  # [L, B, P, page, Hkv, Dh]
    v = cache.v[:, tables]
    L, b, p, pg, h, d = k.shape
    return k.reshape(L, b, p * pg, h, d), v.reshape(L, b, p * pg, h, d)


def write_decode_kv(
    cache: PagedKVCache,
    seq_ids: jnp.ndarray,  # [B]
    k_new: jnp.ndarray,  # [L, B, Hkv, Dh]
    v_new: jnp.ndarray,
) -> PagedKVCache:
    """Write one token's K/V for each sequence at its current length."""
    pos = cache.length[seq_ids]  # [B]
    page_idx = pos // cache.page_size
    offset = pos % cache.page_size
    pages = cache.page_table[seq_ids, page_idx]  # [B]
    k = cache.k.at[:, pages, offset].set(k_new.astype(cache.k.dtype))
    v = cache.v.at[:, pages, offset].set(v_new.astype(cache.v.dtype))
    length = cache.length.at[seq_ids].add(1)
    return PagedKVCache(k=k, v=v, page_table=cache.page_table, length=length)


def write_prefill_kv(
    cache: PagedKVCache,
    seq_id: jnp.ndarray,  # scalar int32
    k_seq: jnp.ndarray,  # [L, S, Hkv, Dh] (S = padded prompt bucket)
    v_seq: jnp.ndarray,
    length: jnp.ndarray,  # scalar true prompt length
) -> PagedKVCache:
    """Scatter one prefilled sequence's K/V into its assigned pages.

    S must be a multiple of page_size; slots past ``length`` hold padding
    garbage, masked out of attention by ``length`` exactly as the dense
    cache masks by ``valid_len``.
    """
    L, s, h, d = k_seq.shape
    pg = cache.page_size
    if s % pg:
        raise ValueError(f"prefill length {s} not a multiple of page {pg}")
    n = s // pg
    pages = jax.lax.dynamic_slice_in_dim(
        cache.page_table[seq_id], 0, n
    )  # [n]
    k_pages = k_seq.reshape(L, n, pg, h, d).astype(cache.k.dtype)
    v_pages = v_seq.reshape(L, n, pg, h, d).astype(cache.v.dtype)
    k = cache.k.at[:, pages].set(k_pages)
    v = cache.v.at[:, pages].set(v_pages)
    new_len = cache.length.at[seq_id].set(length.astype(jnp.int32))
    return PagedKVCache(k=k, v=v, page_table=cache.page_table, length=new_len)


def assign_pages(
    cache: PagedKVCache, seq_id: jnp.ndarray, pages: jnp.ndarray
) -> PagedKVCache:
    """Install a page list (padded with NULL_PAGE) for one sequence."""
    table = cache.page_table.at[seq_id].set(pages.astype(jnp.int32))
    return PagedKVCache(
        k=cache.k, v=cache.v, page_table=table, length=cache.length
    )


def release_seq(cache: PagedKVCache, seq_id: jnp.ndarray) -> PagedKVCache:
    """Clear a sequence's table/length (page recycling is host-side)."""
    table = cache.page_table.at[seq_id].set(NULL_PAGE)
    length = cache.length.at[seq_id].set(0)
    return PagedKVCache(
        k=cache.k, v=cache.v, page_table=table, length=length
    )
