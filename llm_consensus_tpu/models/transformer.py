"""Functional pre-norm transformer (Llama/Mistral/Qwen2/Mixtral family).

The reference delegates all model compute to a remote API
(``src/main.rs:82-86``); this module is its TPU-native replacement per
BASELINE.json's north star. Design choices are XLA-first:

- **Params are a flat pytree with layers stacked on a leading axis**, and
  the layer loop is ``lax.scan`` — one traced block, compiled once,
  regardless of depth (compile time stays flat as n_layers grows).
- **Static shapes everywhere**: the KV cache is a fixed-size buffer,
  per-sequence fill state is data (``KVCache.length``), never shape.
- **bf16 weights/activations, fp32 softmax/norms/logits** — MXU-friendly
  matmuls with numerically safe reductions.
- GQA is computed without materializing repeated KV heads
  (see :mod:`llm_consensus_tpu.ops.attention`).
- Mixtral-style MoE computes all experts densely and combines with the
  top-k router weights — correct and simple; the ragged-dispatch
  optimization is a later kernel (tracked in ops/pallas).

Three entry points:
- :func:`forward` — full causal forward, logits for every position
  (training / scoring).
- :func:`prefill` — fill the KV cache from right-padded prompts, return
  last-valid-token logits only (avoids a [B, S, V] logits buffer).
- :func:`decode_step` — one-token step against the cache.
"""

from __future__ import annotations

import math
from functools import partial

import jax
import jax.numpy as jnp

from llm_consensus_tpu.models.cache import KVCache, QuantKVCache, quantize_kv
from llm_consensus_tpu.models.configs import ModelConfig
from llm_consensus_tpu.ops.activations import swiglu
from llm_consensus_tpu.ops.attention import (
    causal_attention,
    chunk_decode_attention,
    decode_attention,
)
from llm_consensus_tpu.ops.norms import rms_norm
from llm_consensus_tpu.ops.quant import matmul as _qmm
from llm_consensus_tpu.ops.quant import maybe_dequantize as _w
from llm_consensus_tpu.ops.rope import apply_rope, rope_cos_sin


def _rms(cfg: ModelConfig, x, w):
    if cfg.use_pallas:
        from llm_consensus_tpu.ops.pallas import fused_rms_norm

        return fused_rms_norm(x, w, cfg.rms_norm_eps)
    return rms_norm(x, w, cfg.rms_norm_eps)


def _attn_causal(cfg: ModelConfig, q, k, v, positions, mesh=None):
    # Sequence-parallel long context: the ring (parallel/ring.py) handles
    # index-causal layouts over a seq-sharded mesh; K/V chunks rotate on
    # ICI instead of any device holding the full sequence.
    if (
        cfg.use_ring
        and mesh is not None
        and positions is None
        and cfg.sliding_window == 0
        and mesh.shape.get("seq", 1) > 1
        and q.shape[1] % mesh.shape["seq"] == 0
    ):
        from llm_consensus_tpu.parallel.ring import ring_attention_sharded

        return ring_attention_sharded(q, k, v, mesh)
    # The fused kernel implements index-causal masking; packed/offset
    # layouts (explicit positions) and sliding windows use the jnp path.
    if (
        cfg.use_pallas
        and positions is None
        and cfg.sliding_window == 0
        and q.shape[1] % _pallas_blk(q.shape[1]) == 0
    ):
        from llm_consensus_tpu.ops.pallas import flash_causal_attention

        return flash_causal_attention(q, k, v, blk_q=_pallas_blk(q.shape[1]))
    return causal_attention(q, k, v, positions, window=cfg.sliding_window)


def _pallas_blk(s: int) -> int:
    blk = min(256, s)
    while s % blk:
        blk //= 2
    return max(blk, 1)


def _attn_decode(
    cfg: ModelConfig, q, k_cache, v_cache, valid_len, shared_prefix_len=None
):
    """``shared_prefix_len`` (traced scalar or None): every row's cache
    slots [0, shared_prefix_len) hold identical K/V — the
    shared-prefill fan-out invariant — so the two-phase kernel reads
    that region ONCE for the whole batch instead of once per row.
    Engages only on the Pallas path with no sliding window; everything
    else falls back to the ungrouped read (same outputs)."""
    if cfg.use_pallas and cfg.sliding_window == 0:
        if shared_prefix_len is not None:
            from llm_consensus_tpu.ops.pallas import (
                flash_decode_attention_shared_prefix,
            )

            return flash_decode_attention_shared_prefix(
                q, k_cache, v_cache, valid_len, shared_prefix_len
            )
        from llm_consensus_tpu.ops.pallas import flash_decode_attention

        return flash_decode_attention(q, k_cache, v_cache, valid_len)
    return decode_attention(
        q, k_cache, v_cache, valid_len, window=cfg.sliding_window
    )


_STACKED_DECODE = False


def set_stacked_decode(enabled: bool) -> None:
    """Toggle the stacked-cache decode path (see ``_run_layers``).

    The flag is read at TRACE time, so already-compiled decode programs
    would silently keep their old path — the setter clears the jit
    caches so the next call really recompiles with the new setting.
    """
    global _STACKED_DECODE
    _STACKED_DECODE = enabled
    jax.clear_caches()


def _attn_decode_quant_stacked(
    cfg: ModelConfig, q, k_q, k_s, v_q, v_s, valid_len, layer,
    shared_prefix_len=None,
):
    """Decode attention over ONE layer of the stacked int8 cache.

    k_q/v_q: [L, B, Hkv, S, D]; k_s/v_s: [L, B, Hkv, S]; ``layer`` is a
    traced index. The Pallas path reads the stack in place (scalar
    prefetch); the jnp fallback slices the layer (XLA fuses the slice
    into the dequant + einsum).

    ``shared_prefix_len`` (traced scalar or None): the shared-prefill
    fan-out invariant now engages HERE too — the ragged kernel's
    stacked layout reads the common prefix once for the whole batch
    (the stacked-decode fallback PR 3 documented is gone).
    """
    use_kernel = (
        cfg.use_pallas and jax.device_count() == 1 and cfg.sliding_window == 0
    )
    if use_kernel:
        if shared_prefix_len is not None:
            from llm_consensus_tpu.ops.pallas import (
                flash_decode_attention_shared_prefix_q8_stacked,
            )

            return flash_decode_attention_shared_prefix_q8_stacked(
                q, k_q, k_s, v_q, v_s, valid_len, shared_prefix_len, layer
            )
        from llm_consensus_tpu.ops.pallas import (
            flash_decode_attention_q8_stacked,
        )

        return flash_decode_attention_q8_stacked(
            q, k_q, k_s, v_q, v_s, valid_len, layer
        )
    from llm_consensus_tpu.ops.attention import decode_attention_quant

    def sl(a):
        return jax.lax.dynamic_index_in_dim(a, layer, 0, keepdims=False)

    return decode_attention_quant(
        q, sl(k_q), sl(k_s), sl(v_q), sl(v_s), valid_len,
        window=cfg.sliding_window,
    )


def _attn_decode_quant(
    cfg: ModelConfig, q, k_q, k_s, v_q, v_s, valid_len,
    shared_prefix_len=None,
):
    """int8-cache decode attention: the Pallas kernel reads int8 straight
    from HBM (the whole point of the quantized cache) but pallas_call is
    opaque to GSPMD, so it is strictly opt-in via ``cfg.use_pallas`` and
    single-device; sharded meshes take the shardable jnp dequant path.
    (ops.quant._use_kernel auto-detects instead — its off-switch is
    ``ops.quant.set_kernel_enabled(False)``.)

    ``shared_prefix_len``: as :func:`_attn_decode` — the two-phase
    shared-prefix kernel reads the fan-out's common prefix KV once for
    the whole batch (kernel path only; the jnp dequant path has no
    bandwidth to save and stays ungrouped)."""
    use_kernel = cfg.use_pallas and jax.device_count() == 1
    if use_kernel and cfg.sliding_window == 0:
        if shared_prefix_len is not None:
            from llm_consensus_tpu.ops.pallas import (
                flash_decode_attention_shared_prefix_q8,
            )

            return flash_decode_attention_shared_prefix_q8(
                q, k_q, k_s, v_q, v_s, valid_len, shared_prefix_len
            )
        from llm_consensus_tpu.ops.pallas import flash_decode_attention_q8

        return flash_decode_attention_q8(q, k_q, k_s, v_q, v_s, valid_len)
    from llm_consensus_tpu.ops.attention import decode_attention_quant

    return decode_attention_quant(
        q, k_q, k_s, v_q, v_s, valid_len, window=cfg.sliding_window
    )

# ---------------------------------------------------------------------------
# Init
# ---------------------------------------------------------------------------


def init_params(cfg: ModelConfig, key: jax.Array, dtype=jnp.bfloat16) -> dict:
    """Random-init parameters (truncated-normal-free simple scheme:
    normal(0, 0.02), residual projections scaled by 1/sqrt(2*n_layers))."""
    keys = iter(jax.random.split(key, 16))

    def normal(k, shape, scale=0.02):
        return (jax.random.normal(k, shape, jnp.float32) * scale).astype(dtype)

    L, D, H, Hkv, F, V = (
        cfg.n_layers,
        cfg.d_model,
        cfg.n_heads,
        cfg.n_kv_heads,
        cfg.d_ff,
        cfg.vocab_size,
    )
    Dh = cfg.head_dim
    resid_scale = 0.02 / math.sqrt(2 * L)

    blocks: dict = {
        "attn_norm": jnp.ones((L, D), dtype),
        "mlp_norm": jnp.ones((L, D), dtype),
        "wq": normal(next(keys), (L, D, H * Dh)),
        "wk": normal(next(keys), (L, D, Hkv * Dh)),
        "wv": normal(next(keys), (L, D, Hkv * Dh)),
        "wo": normal(next(keys), (L, H * Dh, D), resid_scale),
    }
    if cfg.qkv_bias:
        blocks["bq"] = jnp.zeros((L, H * Dh), dtype)
        blocks["bk"] = jnp.zeros((L, Hkv * Dh), dtype)
        blocks["bv"] = jnp.zeros((L, Hkv * Dh), dtype)
    if cfg.is_moe:
        E = cfg.n_experts
        blocks["router"] = normal(next(keys), (L, D, E))
        blocks["w_gate"] = normal(next(keys), (L, E, D, F))
        blocks["w_up"] = normal(next(keys), (L, E, D, F))
        blocks["w_down"] = normal(next(keys), (L, E, F, D), resid_scale)
    else:
        blocks["w_gate"] = normal(next(keys), (L, D, F))
        blocks["w_up"] = normal(next(keys), (L, D, F))
        blocks["w_down"] = normal(next(keys), (L, F, D), resid_scale)

    params = {
        "embed": normal(next(keys), (V, D)),
        "blocks": blocks,
        "norm_f": jnp.ones((D,), dtype),
    }
    if not cfg.tie_embeddings:
        params["lm_head"] = normal(next(keys), (D, V))
    return params


def param_count(params) -> int:
    return sum(p.size for p in jax.tree_util.tree_leaves(params))


def model_param_bytes(params) -> tuple[int, int]:
    """``(hbm_bytes, n_params)`` of a parameter tree as it sits in HBM.

    The roofline cost model's weight term (PR 10): every decode-shaped
    device program streams the whole tree once, so its byte size (at
    the ACTUAL leaf dtypes — int8 quantized leaves count 1 byte + their
    scales, not the bf16 they stand for) is the floor of the program's
    HBM traffic. ``n_params`` (scales included — they are read too) is
    the matmul-FLOPs multiplier :func:`program_hbm_cost` uses.
    """
    leaves = [
        p for p in jax.tree_util.tree_leaves(params) if hasattr(p, "dtype")
    ]
    return (
        int(sum(p.size * jnp.dtype(p.dtype).itemsize for p in leaves)),
        int(sum(p.size for p in leaves)),
    )


def kv_plane_token_bytes(cfg: ModelConfig, kv_dtype) -> int:
    """HBM bytes one token position costs per full K+V read/write across
    all layers at the pool's dtype — the cost model's KV unit (and the
    unit of ``gateway_shared_kv_bytes_saved_total``, same formula)."""
    return (
        cfg.n_layers
        * cfg.n_kv_heads
        * cfg.head_dim
        * 2
        * jnp.dtype(kv_dtype).itemsize
    )


def program_hbm_cost(
    cfg: ModelConfig,
    *,
    weight_bytes: int,
    weight_params: int,
    kv_token_bytes: int,
    kv_read_tokens: int,
    kv_write_tokens: int,
    tokens: int,
) -> dict:
    """Static HBM-bytes + FLOPs model for ONE device program (PR 10).

    The decode roofline in the terms ClusterFusion++ and the
    operation-fusion paper argue it (PAPERS.md): a program moves
    ``weight_bytes`` (the whole tree, once — the term fusion amortizes
    across rows and speculation amortizes across tokens) plus
    ``(kv_read_tokens + kv_write_tokens) * kv_token_bytes`` of KV pages
    it actually touches (group-shared prefix reads counted ONCE per
    group — callers pass post-dedup token counts), and computes
    ``2 * weight_params`` matmul FLOPs per processed token plus the
    attention dot-products (4 * n_heads * head_dim per (query, kv)
    pair). Measured wall time / (hbm_bytes / peak_bw) is the program's
    model-bandwidth-utilization — ``gateway_program_mbu{kind}``.

    A MODEL, not a measurement: activation traffic, index/table reads,
    and padding rows are excluded; on a chip whose decode programs are
    truly bandwidth-bound the modeled bytes are the dominant term and
    MBU lands near 1.0. Multi-round programs (PR 12) are R rounds of
    KV growth under ONE weight read: the caller passes the summed
    per-round reads (``k*L + k*(k-1)/2`` per row at committed length
    L) and ``k`` writes/tokens per row, so amortization shows up as
    hbm_bytes growing sublinearly in k while tokens grow linearly —
    rows frozen by early-exit masking make the passed counts an upper
    bound, exactly like padding rows make the weight term a floor.
    """
    hbm_bytes = int(
        weight_bytes + (kv_read_tokens + kv_write_tokens) * kv_token_bytes
    )
    flops = int(
        2 * weight_params * tokens
        + 4 * cfg.n_heads * cfg.head_dim * kv_read_tokens
    )
    return {
        "hbm_bytes": hbm_bytes,
        "flops": flops,
        "kv_read_tokens": int(kv_read_tokens),
        "kv_write_tokens": int(kv_write_tokens),
        "tokens": int(tokens),
    }


def init_params_quantized(
    cfg: ModelConfig,
    key: jax.Array,
    *,
    bits: int = 8,
    dtype=jnp.bfloat16,
    device=None,
) -> dict:
    """Init on the host CPU, quantize there, then transfer to ``device``.

    Peak device HBM is the *quantized* footprint, never the bf16 one.
    ``init_params`` + ``quantize_params`` on-device would hold both copies
    at once (~24 GB for Llama-3-8B int8) and OOM a 16 GB v5e chip; this
    path stages through host RAM so the chip only ever sees int8/int4
    leaves (~8.6 GB for 8B int8).
    """
    from llm_consensus_tpu.ops.quant import quantize_params

    cpu = jax.devices("cpu")[0]
    with jax.default_device(cpu):
        params = init_params(cfg, key, dtype=dtype)
        params = quantize_params(params, bits=bits)
        # Materialize on CPU before transfer so the donor buffers free.
        params = jax.tree_util.tree_map(lambda x: x.block_until_ready(), params)
    if device is not None:
        params = jax.device_put(params, device)
    return params


# ---------------------------------------------------------------------------
# Blocks
# ---------------------------------------------------------------------------


def _project_qkv(cfg: ModelConfig, p: dict, h: jnp.ndarray):
    b, s, _ = h.shape
    q = _qmm(h, p["wq"])
    k = _qmm(h, p["wk"])
    v = _qmm(h, p["wv"])
    if cfg.qkv_bias:
        q = q + p["bq"]
        k = k + p["bk"]
        v = v + p["bv"]
    q = q.reshape(b, s, cfg.n_heads, cfg.head_dim)
    k = k.reshape(b, s, cfg.n_kv_heads, cfg.head_dim)
    v = v.reshape(b, s, cfg.n_kv_heads, cfg.head_dim)
    return q, k, v


def moe_router_aux(
    cfg: ModelConfig, router_logits: jnp.ndarray, top_idx: jnp.ndarray
) -> dict:
    """Router auxiliary losses for MoE training (Mixtral config).

    router_logits: [..., E] pre-softmax; top_idx: [..., k] chosen experts.
    Returns {"load_balance", "z_loss"} scalars:

    - load_balance: Switch-Transformer style ``E * sum_e f_e * P_e``
      where f_e is the fraction of (token, choice) assignments routed to
      expert e and P_e the mean router probability mass — equals 1.0
      under perfectly uniform routing, grows as experts collapse.
    - z_loss: ``mean(logsumexp(logits)^2)`` — keeps router logits from
      drifting to magnitudes where the softmax saturates.
    """
    e = cfg.n_experts
    logits2 = router_logits.reshape(-1, e)
    probs = jax.nn.softmax(logits2, axis=-1)
    p_e = probs.mean(axis=0)  # [E]
    assign = jax.nn.one_hot(top_idx.reshape(-1), e, dtype=jnp.float32)
    f_e = assign.mean(axis=0)  # fraction of assignments per expert
    load_balance = e * jnp.sum(f_e * p_e)
    z = jnp.mean(jax.nn.logsumexp(logits2, axis=-1) ** 2)
    return {"load_balance": load_balance, "z_loss": z}


def _zero_aux() -> dict:
    return {
        "load_balance": jnp.zeros((), jnp.float32),
        "z_loss": jnp.zeros((), jnp.float32),
    }


def _mlp(
    cfg: ModelConfig, p: dict, h: jnp.ndarray, collect_aux: bool = False
):
    if not cfg.is_moe:
        y = swiglu(h, p["w_gate"], p["w_up"], p["w_down"])
        return (y, _zero_aux()) if collect_aux else y
    if not cfg.moe_dense_at(h.shape[0] * h.shape[1]):
        return _moe_dispatch(cfg, p, h, collect_aux=collect_aux)
    # Mixtral MoE: top-k routing, dense all-experts compute, weighted combine.
    router_logits = (h @ p["router"]).astype(jnp.float32)  # [B, S, E]
    top_vals, top_idx = jax.lax.top_k(router_logits, cfg.n_experts_per_token)
    top_w = jax.nn.softmax(top_vals, axis=-1)  # [B, S, k]
    # combine weights scattered back over the expert axis: [B, S, E]
    combine = jnp.sum(
        jax.nn.one_hot(top_idx, cfg.n_experts, dtype=jnp.float32)
        * top_w[..., None],
        axis=-2,
    )
    gate = jax.nn.silu(jnp.einsum("bsd,edf->bsef", h, _w(p["w_gate"])))
    up = jnp.einsum("bsd,edf->bsef", h, _w(p["w_up"]))
    expert_out = jnp.einsum("bsef,efd->bsed", gate * up, _w(p["w_down"]))
    y = jnp.einsum(
        "bsed,bse->bsd", expert_out, combine.astype(expert_out.dtype)
    )
    if collect_aux:
        return y, moe_router_aux(cfg, router_logits, top_idx)
    return y


def _moe_dispatch(
    cfg: ModelConfig, p: dict, h: jnp.ndarray, collect_aux: bool = False
):
    """GShard/Switch-style capacity-bounded expert dispatch.

    The dense path above computes EVERY expert for every token (E/k times
    the needed FLOPs — 4x for Mixtral's 8-choose-2); this packs each
    expert's assigned tokens into a fixed-capacity [E, C, D] buffer via
    einsum dispatch masks, so only routed tokens are computed and the
    expert axis shards cleanly over ``expert`` (the dispatch einsums
    become GSPMD all-to-alls). Static capacity
    C = ceil(T * k / E * capacity_factor); tokens past an expert's
    capacity fall back to that expert contributing nothing (standard
    GShard semantics — first-come within (choice-rank, token) order).
    """
    b, s, d = h.shape
    t = b * s
    e, k = cfg.n_experts, cfg.n_experts_per_token
    cap = -(-t * k * cfg.moe_capacity_factor // e)
    cap = int(min(max(cap, 1), t * k))
    x = h.reshape(t, d)

    router_logits = (x @ p["router"]).astype(jnp.float32)  # [T, E]
    top_vals, top_idx = jax.lax.top_k(router_logits, k)
    top_w = jax.nn.softmax(top_vals, axis=-1)  # [T, k]

    # Queue position of each (choice-rank, token) in its expert's buffer:
    # rank-major order gives first choices priority when capacity binds.
    # Built one rank at a time so peak temporaries stay [T, E, C] (a
    # k-expanded [k*T, E, C] buffer would be ~1.3 GB per copy at
    # Mixtral prefill scale).
    onehot = jax.nn.one_hot(top_idx, e, dtype=jnp.float32)  # [T, k, E]
    counts = jnp.zeros((e,), jnp.float32)
    disp_mask = jnp.zeros((t, e, cap), jnp.float32)
    combine = jnp.zeros((t, e, cap), jnp.float32)
    for r in range(k):
        oh_r = onehot[:, r, :]  # [T, E]
        pos_r = jnp.cumsum(oh_r, axis=0) - oh_r + counts  # [T, E]
        keep_r = (pos_r < cap) * oh_r
        slot_r = (
            jax.nn.one_hot(pos_r.astype(jnp.int32), cap, dtype=jnp.float32)
            * keep_r[..., None]
        )  # [T, E, C]
        disp_mask = disp_mask + slot_r
        combine = combine + slot_r * top_w[:, r][:, None, None]
        counts = counts + oh_r.sum(axis=0)

    xin = jnp.einsum("td,tec->ecd", x.astype(jnp.float32), disp_mask)
    xin = xin.astype(h.dtype)
    gate = jax.nn.silu(jnp.einsum("ecd,edf->ecf", xin, _w(p["w_gate"])))
    up = jnp.einsum("ecd,edf->ecf", xin, _w(p["w_up"]))
    out_e = jnp.einsum("ecf,efd->ecd", gate * up, _w(p["w_down"]))
    y = jnp.einsum("ecd,tec->td", out_e.astype(jnp.float32), combine)
    y = y.astype(h.dtype).reshape(b, s, d)
    if collect_aux:
        return y, moe_router_aux(cfg, router_logits, top_idx)
    return y


def _block(
    cfg: ModelConfig,
    p: dict,
    x: jnp.ndarray,
    cos: jnp.ndarray,
    sin: jnp.ndarray,
    kv_layer: tuple | None,
    mode: str,
    valid_len: jnp.ndarray | None,
    positions: jnp.ndarray | None,
    uniform_write: bool = False,
    mesh=None,
    collect_aux: bool = False,
    shared_prefix_len=None,
):
    """One transformer block.

    ``kv_layer``: this layer's cache leaves — (k, v) for the bf16 cache,
    (k_q, v_q, k_scale, v_scale) for the int8 cache (head-major). Returns
    (x, new_kv_layer_tuple_or_None).

    ``uniform_write`` (static): caller guarantees every row writes at
    the SAME position (self-consistency fan-out after shared prefill) —
    the decode cache write becomes one ``dynamic_update_slice`` instead
    of a per-row scatter, which XLA:TPU serializes badly.

    ``shared_prefix_len`` (traced scalar or None; decode mode only):
    rows share identical cache content in [0, shared_prefix_len) — the
    decode attention reads that region once for the whole batch via the
    shared-prefix kernels (see :func:`_attn_decode`).
    """
    h = _rms(cfg, x, p["attn_norm"])
    q, k, v = _project_qkv(cfg, p, h)
    q = apply_rope(q, cos, sin)
    k = apply_rope(k, cos, sin)

    if mode == "full":
        attn = _attn_causal(cfg, q, k, v, positions, mesh=mesh)
        new_kv = None
    elif mode == "prefill":
        attn = _attn_causal(cfg, q, k, v, positions, mesh=mesh)
        s = k.shape[1]
        if len(kv_layer) == 2:
            k_l, v_l = kv_layer
            new_kv = (
                k_l.at[:, :s].set(k.astype(k_l.dtype)),
                v_l.at[:, :s].set(v.astype(v_l.dtype)),
            )
        else:
            kq_l, vq_l, ks_l, vs_l = kv_layer
            kq, ks = quantize_kv(k)  # [B,S,Hkv,D] / [B,S,Hkv]
            vq, vs = quantize_kv(v)
            new_kv = (
                kq_l.at[:, :, :s].set(kq.transpose(0, 2, 1, 3)),
                vq_l.at[:, :, :s].set(vq.transpose(0, 2, 1, 3)),
                ks_l.at[:, :, :s].set(ks.transpose(0, 2, 1)),
                vs_l.at[:, :, :s].set(vs.transpose(0, 2, 1)),
            )
    elif mode == "chunk":
        # K-token chunk (speculative verification / prefix-cached
        # continuation): write all K tokens' k/v at slots
        # [valid_len, valid_len + K) (ragged per row), then ragged-causal
        # attention over the cache.
        b, kq = x.shape[0], x.shape[1]
        batch_idx = jnp.arange(b)[:, None]  # [B, 1]
        pos = valid_len[:, None] + jnp.arange(kq)[None, :]  # [B, K]
        if len(kv_layer) == 2:
            k_l, v_l = kv_layer
            new_k = k_l.at[batch_idx, pos].set(k.astype(k_l.dtype))
            new_v = v_l.at[batch_idx, pos].set(v.astype(v_l.dtype))
            new_kv = (new_k, new_v)
            attn = chunk_decode_attention(
                q, new_k, new_v, valid_len, window=cfg.sliding_window
            )
        else:
            # int8 head-major cache (prefix-cached generation on
            # kv_quant engines). The chunk path is prefill-like, not
            # the decode hot loop: quantized writes keep the cache
            # layout canonical; attention reads a dequantized slab
            # (bf16) through the same ragged-causal rule — exactness
            # vs the bf16 path bounded only by int8 KV rounding.
            kq_l, vq_l, ks_l, vs_l = kv_layer
            kqn, ksn = quantize_kv(k)  # [B,K,Hkv,D] / [B,K,Hkv]
            vqn, vsn = quantize_kv(v)
            hidx = jnp.arange(kq_l.shape[1])[None, :, None]  # [1,Hkv,1]
            pos_h = pos[:, None, :]  # [B,1,K]
            bidx_h = batch_idx[:, :, None]  # [B,1,1]
            new_kq = kq_l.at[bidx_h, hidx, pos_h].set(kqn.transpose(0, 2, 1, 3))
            new_vq = vq_l.at[bidx_h, hidx, pos_h].set(vqn.transpose(0, 2, 1, 3))
            new_ks = ks_l.at[bidx_h, hidx, pos_h].set(ksn.transpose(0, 2, 1))
            new_vs = vs_l.at[bidx_h, hidx, pos_h].set(vsn.transpose(0, 2, 1))
            new_kv = (new_kq, new_vq, new_ks, new_vs)
            deq_k = (
                (new_kq.astype(jnp.float32) * new_ks[..., None])
                .astype(q.dtype)
                .transpose(0, 2, 1, 3)  # -> [B, S, Hkv, D]
            )
            deq_v = (
                (new_vq.astype(jnp.float32) * new_vs[..., None])
                .astype(q.dtype)
                .transpose(0, 2, 1, 3)
            )
            attn = chunk_decode_attention(
                q, deq_k, deq_v, valid_len, window=cfg.sliding_window
            )
    elif mode == "decode":
        b = x.shape[0]
        batch_idx = jnp.arange(b)
        # valid_len is the pre-write fill length; write the new token there.
        if isinstance(kv_layer[0], str) and kv_layer[0] == "stacked":
            # Quant cache, WHOLE stacked buffers + traced layer index:
            # the new token's k/v is written into the stack, and decode
            # attention reads the stack directly (scalar-prefetch kernel
            # — no per-layer cache slice materialization).
            _, (kq_f, vq_f, ks_f, vs_f), layer_idx = kv_layer
            kq1, ks1 = quantize_kv(k[:, 0])  # [B,Hkv,D] / [B,Hkv]
            vq1, vs1 = quantize_kv(v[:, 0])
            if uniform_write:
                pos0 = valid_len[0]
                zero = jnp.zeros((), pos0.dtype)
                li = layer_idx.astype(pos0.dtype)
                kq_f = jax.lax.dynamic_update_slice(
                    kq_f, kq1[None, :, :, None, :], (li, zero, zero, pos0, zero)
                )
                vq_f = jax.lax.dynamic_update_slice(
                    vq_f, vq1[None, :, :, None, :], (li, zero, zero, pos0, zero)
                )
                ks_f = jax.lax.dynamic_update_slice(
                    ks_f, ks1[None, :, :, None], (li, zero, zero, pos0)
                )
                vs_f = jax.lax.dynamic_update_slice(
                    vs_f, vs1[None, :, :, None], (li, zero, zero, pos0)
                )
            else:
                kq_f = kq_f.at[layer_idx, batch_idx, :, valid_len].set(kq1)
                vq_f = vq_f.at[layer_idx, batch_idx, :, valid_len].set(vq1)
                ks_f = ks_f.at[layer_idx, batch_idx, :, valid_len].set(ks1)
                vs_f = vs_f.at[layer_idx, batch_idx, :, valid_len].set(vs1)
            new_kv = (kq_f, vq_f, ks_f, vs_f)
            attn = _attn_decode_quant_stacked(
                cfg, q, kq_f, ks_f, vq_f, vs_f, valid_len + 1, layer_idx,
                shared_prefix_len=shared_prefix_len,
            )
        elif len(kv_layer) == 2:
            k_l, v_l = kv_layer
            if uniform_write:
                pos0 = valid_len[0]
                new_k = jax.lax.dynamic_update_slice(
                    k_l, k.astype(k_l.dtype), (0, pos0, 0, 0)
                )
                new_v = jax.lax.dynamic_update_slice(
                    v_l, v.astype(v_l.dtype), (0, pos0, 0, 0)
                )
            else:
                new_k = k_l.at[batch_idx, valid_len].set(
                    k[:, 0].astype(k_l.dtype)
                )
                new_v = v_l.at[batch_idx, valid_len].set(
                    v[:, 0].astype(v_l.dtype)
                )
            new_kv = (new_k, new_v)
            attn = _attn_decode(
                cfg, q, new_k, new_v, valid_len + 1,
                shared_prefix_len=shared_prefix_len,
            )
        else:
            kq_l, vq_l, ks_l, vs_l = kv_layer
            kq1, ks1 = quantize_kv(k[:, 0])  # [B,Hkv,D] / [B,Hkv]
            vq1, vs1 = quantize_kv(v[:, 0])
            if uniform_write:
                pos0 = valid_len[0]
                zero = jnp.zeros((), pos0.dtype)
                new_kq = jax.lax.dynamic_update_slice(
                    kq_l, kq1[:, :, None, :], (zero, zero, pos0, zero)
                )
                new_vq = jax.lax.dynamic_update_slice(
                    vq_l, vq1[:, :, None, :], (zero, zero, pos0, zero)
                )
                new_ks = jax.lax.dynamic_update_slice(
                    ks_l, ks1[:, :, None], (zero, zero, pos0)
                )
                new_vs = jax.lax.dynamic_update_slice(
                    vs_l, vs1[:, :, None], (zero, zero, pos0)
                )
            else:
                new_kq = kq_l.at[batch_idx, :, valid_len].set(kq1)
                new_vq = vq_l.at[batch_idx, :, valid_len].set(vq1)
                new_ks = ks_l.at[batch_idx, :, valid_len].set(ks1)
                new_vs = vs_l.at[batch_idx, :, valid_len].set(vs1)
            new_kv = (new_kq, new_vq, new_ks, new_vs)
            attn = _attn_decode_quant(
                cfg, q, new_kq, new_ks, new_vq, new_vs, valid_len + 1,
                shared_prefix_len=shared_prefix_len,
            )
    else:  # pragma: no cover
        raise ValueError(mode)

    x = x + _qmm(attn.reshape(*x.shape[:-1], -1), p["wo"])
    h2 = _rms(cfg, x, p["mlp_norm"])
    if collect_aux:
        y, aux = _mlp(cfg, p, h2, collect_aux=True)
        return x + y, new_kv, aux
    x = x + _mlp(cfg, p, h2)
    return x, new_kv


def unstack_blocks(params: dict) -> dict:
    """Per-layer weight buffers: "blocks" [L, ...] -> tuple of L dicts.

    Makes :func:`_run_layers` unroll a python loop over separate
    per-layer buffers instead of scanning the stacked layer axis. In
    principle this avoids materializing each layer's weight slice as a
    Pallas-operand copy; MEASURED on v5e at bench shapes it is a net
    LOSS (default bench config 24.8k -> 22.8k tok/s/chip, bf16-cache
    pallas path ~10x worse): the scan pipelines weight streaming across
    layers, and per-layer cache slices still materialize. Kept as an
    opt-in experiment (``EngineConfig.unroll_layers``) for other
    topologies; the cache-copy problem the unroll targeted is fixed
    inside the scan itself (cache leaves ride the scan carry, see
    ``_run_layers``). Training and sharded paths always use the stacked
    layout (compile time, pspecs).
    """
    blocks = params["blocks"]
    if isinstance(blocks, (list, tuple)):
        return params
    n_layers = jax.tree_util.tree_leaves(blocks)[0].shape[0]
    out = dict(params)
    out["blocks"] = tuple(
        jax.tree.map(lambda a: a[i], blocks) for i in range(n_layers)
    )
    return out


def _run_layers(
    cfg: ModelConfig,
    params: dict,
    x: jnp.ndarray,
    cos: jnp.ndarray,
    sin: jnp.ndarray,
    cache: KVCache | None,
    mode: str,
    valid_len: jnp.ndarray | None,
    positions: jnp.ndarray | None,
    remat: bool = False,
    uniform_write: bool = False,
    mesh=None,
    collect_aux: bool = False,
    shared_prefix_len=None,
):
    """lax.scan over the stacked layer axis (python-unrolled loop when
    ``params["blocks"]`` is a tuple of per-layer dicts — see
    :func:`unstack_blocks`).

    ``collect_aux`` (full mode only): also return the per-layer MoE
    router aux losses averaged over layers ({"load_balance", "z_loss"}).
    ``shared_prefix_len`` (decode mode): see :func:`_block`.
    """
    blocks = params["blocks"]

    if isinstance(blocks, (list, tuple)):
        return _run_layers_unrolled(
            cfg, blocks, x, cos, sin, cache, mode, valid_len, positions,
            remat=remat, uniform_write=uniform_write, mesh=mesh,
            collect_aux=collect_aux, shared_prefix_len=shared_prefix_len,
        )

    if mode == "full":

        def body(carry, p):
            out = _block(
                cfg, p, carry, cos, sin, None, "full", None, positions,
                mesh=mesh, collect_aux=collect_aux,
            )
            if collect_aux:
                y, _, aux = out
                return y, aux
            y, _ = out
            return y, None

        if remat:
            body = jax.checkpoint(body)
        x, auxes = jax.lax.scan(body, x, blocks)
        if collect_aux:
            aux = jax.tree.map(jnp.mean, auxes)
            return x, cache, aux
        return x, cache

    if isinstance(cache, QuantKVCache):
        kv_leaves = (cache.k_q, cache.v_q, cache.k_scale, cache.v_scale)
    else:
        kv_leaves = (cache.k, cache.v)

    # Cache leaves ride in the scan CARRY and are updated in place at
    # the layer index — NOT as scanned xs with stacked ys outputs. The
    # ys form allocates a fresh stacked cache buffer every call, which
    # in the token-decode loop defeats the outer scan's carry aliasing
    # and copies the ENTIRE cache each step (profiler-measured ~1 GB of
    # pure copy per step at bench shapes on v5e). Weights are NOT
    # scanned either: per-layer views are built from the closed-over
    # stack — quantized matmul weights as lazy ``StackedQuant`` views
    # (the Pallas kernel indexes the resident stack via scalar prefetch
    # instead of forcing a per-layer slice copy), everything else as a
    # dynamic_index XLA fuses into its consumer.
    # Quant-cache decode via the WHOLE stacked cache + layer index (the
    # token write and attention read happen on the resident buffers with
    # no per-layer slice or write-back). MEASURED SLOWER than
    # slice+row-kernel on v5e at bench shapes (24.7k vs 25.5k tok/s/chip
    # — the materialized slice feeds the row kernel with better DMA
    # locality than the scalar-prefetch 5-d blocks) and its standalone
    # compile is pathologically slow; opt-in via set_stacked_decode for
    # experimentation on other topologies.
    stacked_decode = (
        _STACKED_DECODE and mode == "decode" and isinstance(cache, QuantKVCache)
    )

    def body(carry, layer_idx):
        y, *leaves = carry
        p = _layer_view(blocks, layer_idx)
        if stacked_decode:
            y, new_leaves = _block(
                cfg,
                p,
                y,
                cos,
                sin,
                ("stacked", tuple(leaves), layer_idx),
                mode,
                valid_len,
                positions,
                uniform_write=uniform_write,
                mesh=mesh,
                shared_prefix_len=shared_prefix_len,
            )
            return (y, *new_leaves), None
        layer_kv = tuple(
            jax.lax.dynamic_index_in_dim(
                leaf, layer_idx, axis=0, keepdims=False
            )
            for leaf in leaves
        )
        y, new_kv = _block(
            cfg,
            p,
            y,
            cos,
            sin,
            layer_kv,
            mode,
            valid_len,
            positions,
            uniform_write=uniform_write,
            mesh=mesh,
            shared_prefix_len=shared_prefix_len,
        )
        leaves = tuple(
            jax.lax.dynamic_update_index_in_dim(leaf, nk, layer_idx, axis=0)
            for leaf, nk in zip(leaves, new_kv)
        )
        return (y, *leaves), None

    if remat:
        body = jax.checkpoint(body)
    layer_ids = jnp.arange(len(jax.tree_util.tree_leaves(blocks)[0]))
    (x, *new_leaves), _ = jax.lax.scan(body, (x, *kv_leaves), layer_ids)
    if isinstance(cache, QuantKVCache):
        return x, QuantKVCache(*new_leaves, length=cache.length)
    return x, KVCache(k=new_leaves[0], v=new_leaves[1], length=cache.length)


def _layer_view(blocks: dict, layer_idx) -> dict:
    """One layer's params from the stacked blocks, sliced lazily.

    int8 ``QuantizedTensor`` stacks become :class:`StackedQuant` views
    (consumed by ``ops.quant.matmul``'s scalar-prefetch kernel without
    materializing the slice); every other leaf is a ``dynamic_index``
    that XLA fuses into its consumer.
    """
    from llm_consensus_tpu.ops.quant import QuantizedTensor, StackedQuant

    view = {}
    for name, leaf in blocks.items():
        if isinstance(leaf, QuantizedTensor) and leaf.q.ndim == 3:
            view[name] = StackedQuant(full=leaf, layer=layer_idx)
        else:
            view[name] = jax.tree.map(
                lambda a: jax.lax.dynamic_index_in_dim(
                    a, layer_idx, 0, keepdims=False
                ),
                leaf,
            )
    return view


def _run_layers_unrolled(
    cfg: ModelConfig,
    blocks,
    x: jnp.ndarray,
    cos: jnp.ndarray,
    sin: jnp.ndarray,
    cache: KVCache | None,
    mode: str,
    valid_len: jnp.ndarray | None,
    positions: jnp.ndarray | None,
    remat: bool = False,
    uniform_write: bool = False,
    mesh=None,
    collect_aux: bool = False,
    shared_prefix_len=None,
):
    """Python-unrolled layer loop over per-layer weight buffers.

    Cache leaves are sliced/written at STATIC layer indices, so XLA
    keeps every update in place on the carried buffers (no per-step
    cache or weight copies — the point of :func:`unstack_blocks`).
    """
    step = _block
    if remat:
        step = jax.checkpoint(
            _block,
            static_argnums=(0, 6),
            static_argnames=("uniform_write", "collect_aux"),
        )

    if mode == "full":
        auxes = []
        for p in blocks:
            out = step(
                cfg, p, x, cos, sin, None, "full", None, positions,
                mesh=mesh, collect_aux=collect_aux,
            )
            if collect_aux:
                x, _, aux = out
                auxes.append(aux)
            else:
                x, _ = out
        if collect_aux:
            aux = jax.tree.map(
                lambda *xs: jnp.mean(jnp.stack(xs)), *auxes
            )
            return x, cache, aux
        return x, cache

    quant = isinstance(cache, QuantKVCache)
    leaves = (
        (cache.k_q, cache.v_q, cache.k_scale, cache.v_scale)
        if quant
        else (cache.k, cache.v)
    )
    for i, p in enumerate(blocks):
        layer_kv = tuple(leaf[i] for leaf in leaves)
        x, new_kv = step(
            cfg, p, x, cos, sin, layer_kv, mode, valid_len, positions,
            uniform_write=uniform_write, mesh=mesh,
            shared_prefix_len=shared_prefix_len,
        )
        leaves = tuple(
            leaf.at[i].set(nk) for leaf, nk in zip(leaves, new_kv)
        )
    if quant:
        return x, QuantKVCache(*leaves, length=cache.length)
    return x, KVCache(k=leaves[0], v=leaves[1], length=cache.length)


def _unembed(cfg: ModelConfig, params: dict, x: jnp.ndarray) -> jnp.ndarray:
    x = _rms(cfg, x, params["norm_f"])
    if cfg.tie_embeddings:
        return jnp.einsum(
            "...d,dv->...v",
            x,
            params["embed"].T,
            preferred_element_type=jnp.float32,
        )
    return _qmm(x, params["lm_head"], out_dtype=jnp.float32)


# ---------------------------------------------------------------------------
# Entry points
# ---------------------------------------------------------------------------


def forward(
    cfg: ModelConfig,
    params: dict,
    tokens: jnp.ndarray,
    positions: jnp.ndarray | None = None,
    remat: bool = False,
    mesh=None,
    return_moe_aux: bool = False,
) -> jnp.ndarray:
    """Full causal forward: tokens [B, S] -> logits [B, S, V] (float32).

    ``mesh``: pass a mesh with ``seq > 1`` (and ``cfg.use_ring``) to run
    attention as sequence-parallel ring attention — the long-context
    path; trace-time constant, so it composes with jit.

    ``return_moe_aux`` (static): also return the layer-averaged MoE
    router aux losses ({"load_balance", "z_loss"} — zeros for dense
    models) for the training loss.
    """
    x = params["embed"][tokens]
    if positions is None:
        positions_arr = jnp.broadcast_to(
            jnp.arange(tokens.shape[1]), tokens.shape
        )
    else:
        positions_arr = positions
    cos, sin = rope_cos_sin(
        positions_arr, cfg.head_dim, cfg.rope_theta, cfg.rope_scaling
    )
    out = _run_layers(
        cfg, params, x, cos, sin, None, "full", None, positions,
        remat=remat, mesh=mesh, collect_aux=return_moe_aux,
    )
    if return_moe_aux:
        x, _, aux = out
        return _unembed(cfg, params, x), aux
    x, _ = out
    return _unembed(cfg, params, x)


def prefill(
    cfg: ModelConfig,
    params: dict,
    tokens: jnp.ndarray,
    lengths: jnp.ndarray,
    cache: KVCache,
    mesh=None,
) -> tuple[jnp.ndarray, KVCache]:
    """Prefill right-padded prompts.

    tokens: [B, S] right-padded; lengths: [B] true prompt lengths.
    Returns (last-valid-token logits [B, V] float32, cache with k/v written
    at slots [0, S) and length set to ``lengths``).

    Padded slots do write garbage k/v into the cache, but they sit at
    indices >= lengths[b] and are (a) masked out of every later decode
    step's attention (``valid_len`` masking) and (b) progressively
    overwritten by decode writes at slot ``length``.
    """
    x = params["embed"][tokens]
    positions = jnp.broadcast_to(jnp.arange(tokens.shape[1]), tokens.shape)
    cos, sin = rope_cos_sin(
        positions, cfg.head_dim, cfg.rope_theta, cfg.rope_scaling
    )
    x, cache = _run_layers(
        cfg, params, x, cos, sin, cache, "prefill", None, None, mesh=mesh
    )
    # Gather hidden state at the last real token of each sequence.
    b = tokens.shape[0]
    last = jnp.clip(lengths - 1, 0, tokens.shape[1] - 1)
    x_last = x[jnp.arange(b), last]  # [B, D]
    logits = _unembed(cfg, params, x_last)
    return logits, cache.with_length(lengths)


def _replicated(mesh, x, head_axis=None):
    """Constrain ``x`` so its leading (batch/concat) axis is
    UNSHARDED on ``mesh`` (no-op off-mesh). The fused step
    concatenates per-row arrays along the batch axis, and XLA's SPMD
    partitioner MISCOMPILES a concatenation along a sharded dimension
    on this jax (observed on 0.4.37 CPU: every element comes out
    doubled — each shard's halo contribution is summed twice).
    De-sharding the concat axis on both operands AND the result
    sidesteps the broken lowering (propagation from downstream
    consumers can re-shard a pinned-input concat, so the result is
    pinned too). ``head_axis``: keep THAT axis sharded over ``model``
    when it divides — the attention outputs feed the row-sharded
    ``wo`` GEMM, and fully replicating them would forfeit the TP
    sharding of the attention→wo contraction on a real chip mesh; the
    position/token vectors pass no head_axis (a handful of scalars
    per row — full replication is noise next to the GEMMs)."""
    if mesh is None:
        return x
    from jax.sharding import NamedSharding, PartitionSpec

    parts = [None] * x.ndim
    if head_axis is not None:
        mp = int(mesh.shape.get("model", 1))
        if mp > 1 and x.shape[head_axis] % mp == 0:
            parts[head_axis] = "model"
    return jax.lax.with_sharding_constraint(
        x, NamedSharding(mesh, PartitionSpec(*parts))
    )


def ragged_mesh_shardable(cfg: ModelConfig, mesh, max_slots: int,
                          n_pages: int) -> bool:
    """Whether the Pallas ragged kernel can run under ``shard_map`` on
    this mesh (PR 13): kv heads must split over ``model`` and the
    decode rows / page pool over ``data``. When any axis fails to
    divide, the serving stack still engages every feature on the mesh
    — attention just takes the XLA reference (sharded by GSPMD) instead
    of the manually-partitioned kernel. This predicate is the ONE
    remaining kernel fallback condition on a mesh; callers surface it
    as the residual construction warning."""
    if mesh is None:
        return False
    dp = int(mesh.shape.get("data", 1))
    mp = int(mesh.shape.get("model", 1))
    return (
        cfg.n_kv_heads % mp == 0
        and max_slots % dp == 0
        and n_pages % dp == 0
    )


def _attn_paged(
    cfg: ModelConfig,
    q_dec,
    q_chunk,
    k_pool,
    v_pool,
    tables,
    valid,
    chunk_table=None,
    chunk_start=None,
    groups=None,
    mesh=None,
):
    """Paged attention for one layer's decode rows (+ optional prefill
    chunk row) — THE kernel-selection seam of the serving stack, and
    deliberately a short one: ``cfg.use_pallas`` picks the ragged
    kernel, anything else the XLA gather reference with identical
    ragged semantics. Window, groups, and mixed rows are all cases of
    the one kernel — the old per-feature fallback matrix is gone.

    ``mesh`` (trace-time constant, PR 13): on a dp×mp mesh the Pallas
    kernel runs under ``shard_map`` — kv heads partitioned over
    ``model``, decode rows and the page pool over ``data`` (the page
    allocator's slot→shard affinity keeps every row's table
    shard-local), group programs riding with their members' shard and
    the chunk lane resolved on its owner shard. When the mesh shapes
    don't divide (``ragged_mesh_shardable``) the XLA reference runs
    instead — GSPMD shards it — so every serving feature still engages.

    q_dec: [B, H, D]; q_chunk: [C, H, D] or None; returns out_dec
    [B, H, D] (and out_chunk [C, H, D] when q_chunk is given).
    """
    window = cfg.sliding_window
    if cfg.use_pallas:
        gtuple = None
        if groups is not None:
            gtuple = (
                groups.group_id,
                groups.group_rep,
                groups.group_pages.astype(jnp.int32) * k_pool.shape[1],
                groups.shared_start,
            )
        if mesh is not None:
            if ragged_mesh_shardable(
                cfg, mesh, q_dec.shape[0], k_pool.shape[0]
            ):
                from llm_consensus_tpu.ops.pallas.attention import (
                    ragged_paged_attention_sharded,
                )

                return ragged_paged_attention_sharded(
                    mesh, q_dec, k_pool, v_pool, tables, valid,
                    q_chunk=q_chunk, chunk_table=chunk_table,
                    chunk_start=chunk_start, groups=gtuple, window=window,
                )
        else:
            from llm_consensus_tpu.ops.pallas.attention import (
                ragged_paged_attention,
            )

            return ragged_paged_attention(
                q_dec, k_pool, v_pool, tables, valid,
                q_chunk=q_chunk, chunk_table=chunk_table,
                chunk_start=chunk_start, groups=gtuple, window=window,
            )
    from llm_consensus_tpu.ops.attention import (
        ragged_paged_attention_reference,
    )

    return ragged_paged_attention_reference(
        q_dec, k_pool, v_pool, tables, valid,
        q_chunk=q_chunk, chunk_table=chunk_table, chunk_start=chunk_start,
        window=window,
    )


def decode_step_paged(
    cfg: ModelConfig,
    params: dict,
    tokens: jnp.ndarray,
    cache,
    groups=None,
    write_mask=None,
    mesh=None,
) -> tuple[jnp.ndarray, object]:
    """One decode step for every cache sequence, paged layout.

    tokens: [max_seqs, 1]. Each row b writes its new K/V at
    ``page_table[b, length[b] // page]`` offset ``length[b] % page`` and
    attends over its gathered pages. Inactive rows (empty tables) write
    into the reserved NULL page — harmless garbage, outputs discarded by
    the serving layer. Returns (logits [max_seqs, V] fp32, new cache).

    ``groups`` (a :class:`~llm_consensus_tpu.models.paged_cache.
    DecodeGroupArrays` or None): sequences sharing a prefix page run
    (the PrefixRegistry's CoW mappings) attend that run through the
    ragged kernel's group phase — one HBM read of the shared pages per
    GROUP per step instead of one per member, with per-row suffix pages
    read as before and the two partial softmaxes merged exactly.
    Grouped and ungrouped rows coexist in the one program (ungrouped
    rows carry group_id -1), and sliding-window configs group too (the
    window is per-row masking in the same kernel — the old fallback is
    gone). The jnp gather path ignores ``groups`` (outputs are
    identical either way — the callers' parity contract).

    ``write_mask`` ([max_seqs] bool or None): device-side early-exit
    masking for multi-round decode (PR 12). A False row is FROZEN: its
    K/V write is redirected into the reserved NULL page (the same sink
    inactive rows already decode into), its ``length`` does not
    advance, and its attention reads stay bounded by the unchanged
    length — so a row that hit a stop inside a multi-round window
    leaves zero trace in its real pages while its batch neighbors keep
    decoding. Frozen rows still flow through the matmuls (SIMD rows
    are not skippable); their logits are garbage the caller discards.
    None (default) = every row live, exactly the pre-PR-12 step.

    ``mesh`` (trace-time constant, PR 13): run the attention read
    through the mesh-partitioned kernel seam (see :func:`_attn_paged`).
    Everything else in the step — the QKV/WO/MLP GEMMs, the K/V pool
    scatter — is plain jnp that GSPMD shards from the operands'
    NamedShardings; only the pallas_call needs the explicit seam.
    """
    from llm_consensus_tpu.models.paged_cache import NULL_PAGE, PagedKVCache

    b = tokens.shape[0]
    pos = cache.length  # [B] current write position
    x = params["embed"][tokens]  # [B, 1, D]
    cos, sin = rope_cos_sin(
        pos[:, None], cfg.head_dim, cfg.rope_theta, cfg.rope_scaling
    )
    pg = cache.page_size
    pages_now = cache.page_table[jnp.arange(b), pos // pg]  # [B]
    offset = pos % pg
    if write_mask is None:
        adv = 1
    else:
        pages_now = jnp.where(write_mask, pages_now, NULL_PAGE)
        adv = write_mask.astype(pos.dtype)
    tables = cache.page_table  # [B, P]

    def body(carry, layer_in):
        p, k_pool, v_pool = layer_in  # pools [n_pages, page, Hkv, Dh]
        h = _rms(cfg, carry, p["attn_norm"])
        q, k, v = _project_qkv(cfg, p, h)
        q = apply_rope(q, cos, sin)
        k = apply_rope(k, cos, sin)
        k_pool = k_pool.at[pages_now, offset].set(k[:, 0].astype(k_pool.dtype))
        v_pool = v_pool.at[pages_now, offset].set(v[:, 0].astype(v_pool.dtype))
        attn = _attn_paged(
            cfg, q[:, 0], None, k_pool, v_pool, tables, pos + adv,
            groups=groups, mesh=mesh,
        )[:, None]  # [B, H, D] -> [B, 1, H, D] (seq axis restored)
        y = carry + _qmm(attn.reshape(*carry.shape[:-1], -1), p["wo"])
        h2 = _rms(cfg, y, p["mlp_norm"])
        y = y + _mlp(cfg, p, h2)
        return y, (k_pool, v_pool)

    x, (new_k, new_v) = jax.lax.scan(
        body, x, (params["blocks"], cache.k, cache.v)
    )
    logits = _unembed(cfg, params, x[:, 0])
    new_cache = PagedKVCache(
        k=new_k, v=new_v, page_table=cache.page_table, length=pos + adv
    )
    return logits, new_cache


def verify_step_paged(
    cfg: ModelConfig,
    params: dict,
    tokens: jnp.ndarray,
    cache,
    groups=None,
    mesh=None,
) -> tuple[jnp.ndarray, object]:
    """Speculative VERIFY step: NQ tokens per cache sequence, one
    program (PR 9 — :func:`decode_step_paged` widened to k+1-token
    ragged rows).

    tokens: [max_seqs, NQ] — row b's previous committed token followed
    by NQ-1 draft proposals, at absolute positions ``length[b] + i``.
    Embedding, RoPE, the QKV/WO/MLP matmuls, and the K/V pool scatter
    all run over the [B, NQ] token grid (one weight read serves NQ
    tokens per row — the point of speculation), and attention is the
    ragged kernel's verify lane: queries at ``valid_len - NQ + i`` with
    the chunk lane's ragged-causal rule, so position j conditions on
    the row's committed tokens plus drafts[:j]. K/V for ALL NQ
    positions are written through the row's table (decode rows write
    only private pages — shared prefix pages cover prompts only);
    positions past the eventually-accepted prefix hold garbage the
    caller truncates by REWINDING ``length``, never by copying pages —
    slots past ``length`` are invisible to every later read and get
    overwritten by later writes, exactly like a mid-chunk retirement's
    overshoot tokens.

    Returns (logits [max_seqs, NQ, V] fp32 — one distribution per
    verify position, the accept rule's input — and the cache with
    ``length`` UNCHANGED: the caller advances it by each row's emitted
    count after the accept decision). ``groups`` as in
    :func:`decode_step_paged` (every verify query of a member stacks
    against one read of the shared run).
    """
    from llm_consensus_tpu.models.paged_cache import PagedKVCache

    b, nq = tokens.shape
    pos0 = cache.length  # [B] first write position per row
    pos = pos0[:, None] + jnp.arange(nq)[None]  # [B, NQ]
    x = params["embed"][tokens]  # [B, NQ, D]
    cos, sin = rope_cos_sin(
        pos, cfg.head_dim, cfg.rope_theta, cfg.rope_scaling
    )
    pg = cache.page_size
    pages = jnp.take_along_axis(
        cache.page_table, pos // pg, axis=1
    )  # [B, NQ] destination page per token
    offs = pos % pg
    tables = cache.page_table

    def body(carry, layer_in):
        p, k_pool, v_pool = layer_in  # pools [n_pages, page, Hkv, Dh]
        h = _rms(cfg, carry, p["attn_norm"])
        q, k, v = _project_qkv(cfg, p, h)  # [B, NQ, H, Dh]
        q = apply_rope(q, cos, sin)
        k = apply_rope(k, cos, sin)
        k_pool = k_pool.at[pages, offs].set(k.astype(k_pool.dtype))
        v_pool = v_pool.at[pages, offs].set(v.astype(v_pool.dtype))
        attn = _attn_paged(
            cfg, q, None, k_pool, v_pool, tables, pos0 + nq, groups=groups,
            mesh=mesh,
        )  # [B, NQ, H, D]
        y = carry + _qmm(attn.reshape(*carry.shape[:-1], -1), p["wo"])
        h2 = _rms(cfg, y, p["mlp_norm"])
        y = y + _mlp(cfg, p, h2)
        return y, (k_pool, v_pool)

    x, (new_k, new_v) = jax.lax.scan(
        body, x, (params["blocks"], cache.k, cache.v)
    )
    logits = _unembed(cfg, params, x)  # [B, NQ, V]
    new_cache = PagedKVCache(
        k=new_k, v=new_v, page_table=cache.page_table, length=cache.length
    )
    return logits, new_cache


def prefill_chunk_paged(
    cfg: ModelConfig,
    params: dict,
    tokens: jnp.ndarray,
    table: jnp.ndarray,
    start: jnp.ndarray,
    cache,
    mesh=None,
) -> tuple[jnp.ndarray, object]:
    """One prompt chunk for ONE sequence, scattered into paged K/V.

    tokens: [1, C] — chunk token ids at absolute positions
    ``start + i``; table: [pages_per_seq] int32 page ids (position p
    lives in ``table[p // page_size]`` at offset ``p % page_size``);
    start: scalar int32. Writes each chunk token's K/V through
    ``table`` and attends over the table's content so far plus the
    chunk itself — the same ragged-causal rule as
    :func:`decode_chunk`, so a sequence of chunk calls writes the
    identical cache a dense :func:`prefill` + scatter would.

    The table rides as an ARGUMENT, not through ``cache.page_table``:
    a mid-prefill sequence must stay invisible to the concurrently
    running decode program (its device table row stays NULL until the
    last chunk lands — see serving/continuous). This is also what lets
    chunk positions start past zero: a shared page-aligned prefix (and
    an optionally copied boundary page) already populates the table's
    head, and this program only ever writes positions >= ``start``, so
    refcount-shared pages are read, never written.

    Returns ([1, C, D] hidden states, cache). ``cache.page_table`` and
    ``cache.length`` are untouched. The serving layer gathers the
    last-valid position's hidden state from the FINAL chunk and
    unembeds that single row (see :func:`unembed_one`) — never a
    [C, V] logits buffer per chunk.
    """
    from llm_consensus_tpu.models.paged_cache import PagedKVCache

    c = tokens.shape[1]
    pos = start + jnp.arange(c)  # [C] absolute positions
    x = params["embed"][tokens]  # [1, C, D]
    cos, sin = rope_cos_sin(
        pos[None], cfg.head_dim, cfg.rope_theta, cfg.rope_scaling
    )
    pg = cache.page_size
    pages = table[pos // pg]  # [C] destination page per chunk token
    offs = pos % pg
    nb = 1 if mesh is None else int(mesh.shape.get("data", 1))

    def body(carry, layer_in):
        p, k_pool, v_pool = layer_in  # pools [n_pages, page, Hkv, Dh]
        h = _rms(cfg, carry, p["attn_norm"])
        q, k, v = _project_qkv(cfg, p, h)
        q = apply_rope(q, cos, sin)
        k = apply_rope(k, cos, sin)
        k_pool = k_pool.at[pages, offs].set(k[0].astype(k_pool.dtype))
        v_pool = v_pool.at[pages, offs].set(v[0].astype(v_pool.dtype))
        # Chunk-only ragged call through the SAME kernel seam as the
        # fused step (dead decode rows: NULL table, valid 0) — a
        # standalone chunk and a fused chunk must write bit-identical
        # cache bytes, which means one attention arithmetic for both
        # (on use_pallas configs the kernel and the XLA reference only
        # agree to tolerance, so mixing them would break the
        # ragged_attention on/off byte-parity contract mid-prefill).
        # On a mesh the dummy decode batch is sized to the data axis:
        # a 1-row batch cannot shard over dp > 1, which would silently
        # route the STANDALONE chunk to the reference while fused
        # chunks run the sharded kernel — the same mixed-arithmetic
        # hazard, reintroduced by topology instead of by feature flag.
        attn = _attn_paged(
            cfg,
            jnp.zeros((nb, cfg.n_heads, cfg.head_dim), q.dtype),
            q[0],
            k_pool,
            v_pool,
            jnp.zeros((nb, table.shape[0]), jnp.int32),
            jnp.zeros((nb,), jnp.int32),
            chunk_table=table,
            chunk_start=start,
            mesh=mesh,
        )[1][None]  # out_chunk [C, H, D] -> [1, C, H, D]
        y = carry + _qmm(attn.reshape(*carry.shape[:-1], -1), p["wo"])
        h2 = _rms(cfg, y, p["mlp_norm"])
        y = y + _mlp(cfg, p, h2)
        return y, (k_pool, v_pool)

    x, (new_k, new_v) = jax.lax.scan(
        body, x, (params["blocks"], cache.k, cache.v)
    )
    new_cache = PagedKVCache(
        k=new_k, v=new_v, page_table=cache.page_table, length=cache.length
    )
    return x, new_cache


def fused_step_paged(
    cfg: ModelConfig,
    params: dict,
    tokens: jnp.ndarray,
    cache,
    chunk_tokens: jnp.ndarray,
    chunk_table: jnp.ndarray,
    chunk_start: jnp.ndarray,
    groups=None,
    cfg_chunk: ModelConfig | None = None,
    mesh=None,
) -> tuple[jnp.ndarray, jnp.ndarray, object]:
    """One decode step for every cache sequence PLUS one prefill chunk
    — a single device program (the fused scheduler step).

    tokens: [B, 1] decode inputs; chunk_tokens: [1, C] one sequence's
    prompt chunk at absolute positions ``chunk_start + i``, written
    through the explicit host-side ``chunk_table`` [P] exactly as
    :func:`prefill_chunk_paged` (the mid-prefill row stays invisible to
    the decode rows — its device table row is still NULL). The decode
    rows and the chunk share ONE token axis: embedding, RoPE, the
    QKV/WO/MLP matmuls, and the K/V pool scatter all run over the
    [B + C] concatenation (bigger GEMMs, one scatter), and attention is
    the ragged kernel with the chunk riding as one more row — chunked
    prefill stops being a separate device program serializing against
    decode.

    The two workloads are independent by construction: decode rows
    write only their own private pages, the chunk writes only positions
    >= ``chunk_start`` of its own table (shared prefix pages are read,
    never written), so each side's outputs equal the split programs'.
    ``cfg_chunk`` (default ``cfg``): the MoE-pinned config the
    standalone chunk program would have used — when it differs (MoE
    configs), the MLP runs split per side so each side's dispatch path
    matches its parity baseline; dense models share one MLP call.

    Returns (decode logits [B, V] fp32, chunk hidden [1, C, D], cache).
    ``cache.length`` advances for the decode rows only.
    """
    from llm_consensus_tpu.models.paged_cache import PagedKVCache

    if cfg_chunk is None:
        cfg_chunk = cfg
    b = tokens.shape[0]
    c = chunk_tokens.shape[1]
    pos = cache.length  # [B] decode write positions
    chunk_pos = chunk_start + jnp.arange(c)  # [C] absolute positions
    # Concats along the [B + C] axis go through _replicated on BOTH
    # the operands and the result: the decode-side operands arrive
    # data-sharded and XLA's partitioner miscompiles a concatenation
    # along a sharded dim (see _replicated) — and sharding propagation
    # from downstream consumers can re-shard the concat node even when
    # its inputs are pinned, so the output is pinned too.
    # Scatter/gather indices keep their native sharding.
    all_pos = _replicated(
        mesh, jnp.concatenate([_replicated(mesh, pos), chunk_pos])
    )
    x = params["embed"][
        _replicated(
            mesh,
            jnp.concatenate(
                [_replicated(mesh, tokens[:, 0]), chunk_tokens[0]]
            ),
        )
    ][None]  # [1, B+C, D]
    cos, sin = rope_cos_sin(
        all_pos[None], cfg.head_dim, cfg.rope_theta, cfg.rope_scaling
    )
    pg = cache.page_size
    pages_dec = cache.page_table[jnp.arange(b), pos // pg]  # [B]
    offs_dec = pos % pg
    pages_ch = chunk_table[chunk_pos // pg]
    offs_ch = chunk_pos % pg
    tables = cache.page_table
    mlp_split = cfg.is_moe and cfg_chunk is not cfg

    def body(carry, layer_in):
        p, k_pool, v_pool = layer_in  # pools [n_pages, page, Hkv, Dh]
        h = _rms(cfg, carry, p["attn_norm"])
        q, k, v = _project_qkv(cfg, p, h)  # [1, B+C, H, Dh]
        q = apply_rope(q, cos, sin)
        k = apply_rope(k, cos, sin)
        # TWO scatters, decode rows then the chunk lane, over DISJOINT
        # real pages (decode writes private pages, the chunk writes
        # positions >= chunk_start of its own table). One concatenated
        # scatter would mix a data-sharded index vector with the
        # chunk's replicated one, which XLA's SPMD partitioner
        # miscompiles on a mesh (observed on jax 0.4.37 CPU: spurious
        # writes to a page nobody indexed); split, each scatter's
        # indices carry ONE consistent sharding and both partition
        # correctly. Single-device bytes are unchanged (disjoint
        # targets; only the NULL page's garbage ordering can differ).
        k0 = k[0].astype(k_pool.dtype)
        v0 = v[0].astype(v_pool.dtype)
        k_pool = k_pool.at[pages_dec, offs_dec].set(k0[:b])
        v_pool = v_pool.at[pages_dec, offs_dec].set(v0[:b])
        k_pool = k_pool.at[pages_ch, offs_ch].set(k0[b:])
        v_pool = v_pool.at[pages_ch, offs_ch].set(v0[b:])
        attn_dec, attn_ch = _attn_paged(
            cfg, q[0, :b], q[0, b:], k_pool, v_pool, tables, pos + 1,
            chunk_table=chunk_table, chunk_start=chunk_start, groups=groups,
            mesh=mesh,
        )
        attn = _replicated(
            mesh,
            jnp.concatenate(
                [
                    _replicated(mesh, attn_dec, head_axis=1),
                    _replicated(mesh, attn_ch, head_axis=1),
                ]
            ),
            head_axis=1,
        )[None]  # [1, B+C, H, Dh]
        y = carry + _qmm(attn.reshape(1, b + c, -1), p["wo"])
        h2 = _rms(cfg, y, p["mlp_norm"])
        if mlp_split:
            y = y + jnp.concatenate(
                [_mlp(cfg, p, h2[:, :b]), _mlp(cfg_chunk, p, h2[:, b:])],
                axis=1,
            )
        else:
            y = y + _mlp(cfg, p, h2)
        return y, (k_pool, v_pool)

    x, (new_k, new_v) = jax.lax.scan(
        body, x, (params["blocks"], cache.k, cache.v)
    )
    logits = _unembed(cfg, params, x[0, :b])
    hidden_chunk = x[:, b:]  # [1, C, D]
    new_cache = PagedKVCache(
        k=new_k, v=new_v, page_table=cache.page_table, length=pos + 1
    )
    return logits, hidden_chunk, new_cache


def unembed_one(cfg: ModelConfig, params: dict, h: jnp.ndarray) -> jnp.ndarray:
    """Logits [V] fp32 for ONE hidden state [D] — the final-chunk
    unembed of the chunked-prefill path (a D x V matvec, not C x V)."""
    return _unembed(cfg, params, h[None])[0]


def decode_chunk(
    cfg: ModelConfig,
    params: dict,
    tokens: jnp.ndarray,
    cache: KVCache,
) -> tuple[jnp.ndarray, KVCache]:
    """Score K tokens per row against the cache in ONE forward.

    tokens: [B, K]. Token (b, i) sits at position ``cache.length[b] + i``
    and attends everything before it plus the chunk prefix — the
    speculative-decoding verification step (a whole draft's target
    logits from one pass instead of K sequential decode_steps).

    Returns (logits [B, K, V] float32, cache with the K tokens' k/v
    written). ``cache.length`` is NOT advanced: the caller decides how
    many chunk tokens were actually consumed (accepted) and sets the
    length via ``cache.with_length`` — rejected tokens' k/v stay as
    masked-out garbage past the fill, exactly like prefill padding.
    Sliding-window configs (Mistral) mask per the same rule as
    :func:`llm_consensus_tpu.ops.attention.decode_attention`.
    """
    x, cache = _chunk_hidden(cfg, params, tokens, cache)
    logits = _unembed(cfg, params, x)  # [B, K, V]
    return logits, cache


def _chunk_hidden(
    cfg: ModelConfig,
    params: dict,
    tokens: jnp.ndarray,
    cache: KVCache,
) -> tuple[jnp.ndarray, KVCache]:
    """The chunk forward without the unembed: ([B, K, D] hidden, cache).

    Callers that need only a few positions' logits (chunked prefill
    keeps one per row) gather from the hidden states and unembed those
    — skipping the B*K*V logits matmul per chunk."""
    kq = tokens.shape[1]
    x = params["embed"][tokens]  # [B, K, D]
    positions = cache.length[:, None] + jnp.arange(kq)[None, :]
    cos, sin = rope_cos_sin(
        positions, cfg.head_dim, cfg.rope_theta, cfg.rope_scaling
    )
    x, cache = _run_layers(
        cfg, params, x, cos, sin, cache, "chunk", cache.length, None
    )
    return x, cache


def prefill_chunked(
    cfg: ModelConfig,
    params: dict,
    tokens: jnp.ndarray,
    lengths: jnp.ndarray,
    cache: KVCache,
    chunk: int = 512,
) -> tuple[jnp.ndarray, KVCache]:
    """Prefill in fixed-size chunks — bounded activation memory.

    One-shot :func:`prefill` materializes activations for the whole
    [B, S] prompt at once; for long contexts this chunks the prompt into
    ``ceil(S / chunk)`` :func:`decode_chunk` passes (each chunk attends
    the cache so far plus itself — same ragged-causal rule), keeping
    peak activation memory at O(B * chunk) while writing the identical
    cache. Returns (last-valid-token logits [B, V] fp32, cache with
    length = ``lengths``) — same contract as :func:`prefill`, and
    exactness-tested against it.
    """
    b, s = tokens.shape
    # Pin each chunk's MoE dispatch path to the one a ONE-SHOT prefill
    # of this prompt would trace (the b*s total decides), not the
    # chunk's own token count — otherwise a prompt above the
    # dense-fallback threshold whose chunks sit below it would mix
    # paths across the two prefill entry points. Dense side covers
    # b*chunk: padding can widen a chunk past s. Residual capacity-side
    # caveat: ModelConfig.moe_pin_for.
    cfg = cfg.moe_pin_for(b * s, b * chunk)
    if s % chunk:
        pad = chunk - s % chunk
        tokens = jnp.pad(tokens, ((0, 0), (0, pad)))
        s += pad
    cache = cache.with_length(jnp.zeros((b,), jnp.int32))
    last = jnp.clip(lengths - 1, 0, s - 1)
    batch = jnp.arange(b)
    x_last = jnp.zeros((b, cfg.d_model), jnp.float32)
    for c0 in range(0, s, chunk):
        hidden, cache = _chunk_hidden(
            cfg, params, tokens[:, c0 : c0 + chunk], cache
        )
        cache = cache.with_length(cache.length + chunk)
        # Keep only each row's last-valid hidden state; the unembed (a
        # B*V matmul, not B*chunk*V) happens ONCE after the loop.
        in_chunk = (last >= c0) & (last < c0 + chunk)
        got = hidden[batch, jnp.clip(last - c0, 0, chunk - 1)]
        x_last = jnp.where(in_chunk[:, None], got.astype(jnp.float32), x_last)
    cache = cache.with_length(lengths)
    logits = _unembed(cfg, params, x_last.astype(hidden.dtype))
    return logits, cache


def decode_step(
    cfg: ModelConfig,
    params: dict,
    tokens: jnp.ndarray,
    cache: KVCache,
    uniform_write: bool = False,
    shared_prefix_len=None,
) -> tuple[jnp.ndarray, KVCache]:
    """One decode step: tokens [B, 1] -> (logits [B, V] float32, new cache).

    The new token's k/v is written at slot ``cache.length`` and the fill
    length advances by one. ``uniform_write`` (static): all rows share
    one fill length (shared-prefill fan-out) — the cache write compiles
    to a slice update instead of a scatter.

    ``shared_prefix_len`` (traced scalar or None): rows hold IDENTICAL
    K/V in cache slots [0, shared_prefix_len) — the shared-prefill
    fan-out invariant — so decode attention reads that region once for
    the whole batch through the two-phase shared-prefix kernels (one
    HBM read per step instead of one per row; exact LSE merge with each
    row's suffix). Only the Pallas non-windowed non-stacked paths
    engage; every other path ignores it (same outputs either way).
    """
    x = params["embed"][tokens]  # [B, 1, D]
    positions = cache.length[:, None]  # [B, 1]
    cos, sin = rope_cos_sin(
        positions, cfg.head_dim, cfg.rope_theta, cfg.rope_scaling
    )
    x, cache = _run_layers(
        cfg,
        params,
        x,
        cos,
        sin,
        cache,
        "decode",
        cache.length,
        None,
        uniform_write=uniform_write,
        shared_prefix_len=shared_prefix_len,
    )
    logits = _unembed(cfg, params, x[:, 0])
    return logits, cache.advanced(1)
