"""ctypes bindings for the native runtime (native/src/consensus_rt.cpp).

Builds lazily with ``make`` on first use if the shared library is absent
(g++ is in the image; pybind11 is not, hence ctypes). Everything is
gated: callers use :func:`available` / :func:`load` and keep a pure-
Python fallback, so the framework works without the toolchain.
"""

from llm_consensus_tpu.native.runtime import (
    NativeLoader,
    NativeRing,
    available,
    batch_encode,
    batch_decode,
    load,
)

__all__ = [
    "NativeLoader",
    "NativeRing",
    "available",
    "batch_decode",
    "batch_encode",
    "load",
]
