"""ctypes surface over libconsensus_rt.so.

Three native components (see native/src/consensus_rt.cpp):
batch byte tokenizer, bounded MPMC request ring, mmap token data loader.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from pathlib import Path

import numpy as np

_REPO_ROOT = Path(__file__).resolve().parents[2]
_NATIVE_DIR = _REPO_ROOT / "native"
_LIB_PATH = _NATIVE_DIR / "build" / "libconsensus_rt.so"

_lib = None
_lib_lock = threading.Lock()


def _build() -> bool:
    try:
        subprocess.run(
            ["make", "-C", str(_NATIVE_DIR)],
            check=True,
            capture_output=True,
            timeout=120,
        )
        return _LIB_PATH.exists()
    except Exception:  # noqa: BLE001 - no toolchain / build failure
        return False


def load():
    """Load (building if needed) the native library, or return None."""
    global _lib
    with _lib_lock:
        if _lib is not None:
            return _lib
        if not _LIB_PATH.exists() and not _build():
            return None
        lib = ctypes.CDLL(str(_LIB_PATH))

        lib.rt_byte_encode_batch.restype = ctypes.c_int
        lib.rt_byte_encode_batch.argtypes = [
            ctypes.POINTER(ctypes.c_char_p),
            ctypes.POINTER(ctypes.c_int64),
            ctypes.c_int32,
            ctypes.POINTER(ctypes.c_int32),
            ctypes.c_int32,
            ctypes.POINTER(ctypes.c_int32),
            ctypes.c_int32,
        ]
        lib.rt_byte_decode.restype = ctypes.c_int64
        lib.rt_byte_decode.argtypes = [
            ctypes.POINTER(ctypes.c_int32),
            ctypes.c_int64,
            ctypes.c_char_p,
            ctypes.c_int64,
        ]
        lib.rt_ring_create.restype = ctypes.c_void_p
        lib.rt_ring_create.argtypes = [ctypes.c_int64]
        lib.rt_ring_destroy.argtypes = [ctypes.c_void_p]
        lib.rt_ring_push.restype = ctypes.c_int
        lib.rt_ring_push.argtypes = [
            ctypes.c_void_p,
            ctypes.POINTER(ctypes.c_uint8),
            ctypes.c_int64,
            ctypes.c_int64,
        ]
        lib.rt_ring_pop.restype = ctypes.c_int
        lib.rt_ring_pop.argtypes = [
            ctypes.c_void_p,
            ctypes.POINTER(ctypes.c_uint8),
            ctypes.c_int64,
            ctypes.POINTER(ctypes.c_int64),
            ctypes.c_int64,
        ]
        lib.rt_ring_size.restype = ctypes.c_int64
        lib.rt_ring_size.argtypes = [ctypes.c_void_p]
        lib.rt_ring_close.argtypes = [ctypes.c_void_p]
        lib.rt_loader_create.restype = ctypes.c_void_p
        lib.rt_loader_create.argtypes = [
            ctypes.c_char_p,
            ctypes.c_int64,
            ctypes.c_int64,
            ctypes.c_uint64,
        ]
        lib.rt_loader_next.restype = ctypes.c_int
        lib.rt_loader_next.argtypes = [
            ctypes.c_void_p,
            ctypes.POINTER(ctypes.c_int32),
        ]
        lib.rt_loader_destroy.argtypes = [ctypes.c_void_p]
        lib.rt_loader_n_tokens.restype = ctypes.c_int64
        lib.rt_loader_n_tokens.argtypes = [ctypes.c_void_p]
        if hasattr(lib, "rt_loader_skip"):  # older built libs lack it
            lib.rt_loader_skip.restype = ctypes.c_int
            lib.rt_loader_skip.argtypes = [ctypes.c_void_p, ctypes.c_int64]
        _lib = lib
        return _lib


def available() -> bool:
    return load() is not None


# ---------------------------------------------------------------------------
# Tokenizer
# ---------------------------------------------------------------------------


def batch_encode(
    texts: list[str], max_len: int, add_bos: bool = True
) -> tuple[np.ndarray, np.ndarray]:
    """Encode texts into a right-padded [n, max_len] int32 batch + lengths.

    Same id scheme as :class:`llm_consensus_tpu.engine.tokenizer.ByteTokenizer`
    (0/1/2 pad/bos/eos, byte+3), same tail-keeping truncation.
    """
    lib = load()
    if lib is None:
        raise RuntimeError("native runtime unavailable")
    raw = [t.encode("utf-8") for t in texts]
    n = len(raw)
    arr = (ctypes.c_char_p * n)(*raw)
    lens = (ctypes.c_int64 * n)(*[len(r) for r in raw])
    out = np.zeros((n, max_len), np.int32)
    out_lens = np.zeros((n,), np.int32)
    rc = lib.rt_byte_encode_batch(
        arr,
        lens,
        n,
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        max_len,
        out_lens.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        1 if add_bos else 0,
    )
    if rc != 0:
        raise RuntimeError(f"rt_byte_encode_batch failed: {rc}")
    return out, out_lens


def batch_decode(ids: np.ndarray) -> list[str]:
    """Decode each row of an int32 id array (stops at EOS per row)."""
    lib = load()
    if lib is None:
        raise RuntimeError("native runtime unavailable")
    ids = np.ascontiguousarray(ids, np.int32)
    out = []
    cap = ids.shape[-1] + 8
    buf = ctypes.create_string_buffer(cap)
    for row in ids.reshape(-1, ids.shape[-1]):
        n = lib.rt_byte_decode(
            row.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            row.shape[0],
            buf,
            cap,
        )
        if n < 0:
            raise RuntimeError("rt_byte_decode overflow")
        out.append(buf.raw[:n].decode("utf-8", errors="replace"))
    return out


# ---------------------------------------------------------------------------
# Request ring
# ---------------------------------------------------------------------------


class NativeRing:
    """Bounded MPMC byte-payload queue (the serving scheduler's spine)."""

    def __init__(self, capacity: int, max_item: int = 1 << 20):
        lib = load()
        if lib is None:
            raise RuntimeError("native runtime unavailable")
        self._lib = lib
        self._h = lib.rt_ring_create(capacity)
        if not self._h:
            raise ValueError("bad ring capacity")
        self._max_item = max_item

    def push(self, payload: bytes, timeout: float | None = None) -> bool:
        """True on success; False on timeout. Raises if closed."""
        t = -1 if timeout is None else int(timeout * 1000)
        buf = (ctypes.c_uint8 * len(payload)).from_buffer_copy(payload)
        rc = self._lib.rt_ring_push(self._h, buf, len(payload), t)
        if rc == 2:
            raise RuntimeError("ring closed")
        return rc == 0

    def pop(self, timeout: float | None = None) -> bytes | None:
        """Payload, or None on timeout/closed-and-drained."""
        t = -1 if timeout is None else int(timeout * 1000)
        buf = (ctypes.c_uint8 * self._max_item)()
        out_len = ctypes.c_int64()
        rc = self._lib.rt_ring_pop(
            self._h, buf, self._max_item, ctypes.byref(out_len), t
        )
        if rc in (1, 2):
            return None
        if rc == 3:
            raise RuntimeError("payload exceeds max_item")
        return bytes(buf[: out_len.value])

    def __len__(self) -> int:
        return int(self._lib.rt_ring_size(self._h))

    def close(self) -> None:
        self._lib.rt_ring_close(self._h)

    def __del__(self):  # pragma: no cover - best effort
        try:
            if getattr(self, "_h", None):
                self._lib.rt_ring_destroy(self._h)
                self._h = None
        except Exception:
            pass


# ---------------------------------------------------------------------------
# Data loader
# ---------------------------------------------------------------------------


class NativeLoader:
    """mmap'd token-shard loader with a native prefetch thread.

    ``path`` is a raw little-endian int32 token file; yields random
    [batch, seq] windows (the standard LM pretraining sampler) without
    holding the GIL during copy/shuffle.
    """

    def __init__(self, path: str | os.PathLike, batch: int, seq: int, seed: int = 0):
        lib = load()
        if lib is None:
            raise RuntimeError("native runtime unavailable")
        self._lib = lib
        self.batch, self.seq = batch, seq
        self._h = lib.rt_loader_create(
            str(path).encode(), batch, seq, seed
        )
        if not self._h:
            raise FileNotFoundError(f"cannot open token shard {path}")

    @property
    def n_tokens(self) -> int:
        return int(self._lib.rt_loader_n_tokens(self._h))

    def next(self) -> np.ndarray:
        out = np.empty((self.batch, self.seq), np.int32)
        rc = self._lib.rt_loader_next(
            self._h, out.ctypes.data_as(ctypes.POINTER(ctypes.c_int32))
        )
        if rc != 0:
            raise RuntimeError("loader stopped")
        return out

    def skip(self, n: int) -> None:
        """Discard n batches in C (checkpoint-resume fast-forward)."""
        if n <= 0:
            return
        if hasattr(self._lib, "rt_loader_skip"):
            if self._lib.rt_loader_skip(self._h, n) != 0:
                raise RuntimeError("loader stopped")
        else:  # old lib: draw-and-discard (correct, slower)
            for _ in range(n):
                self.next()

    def close(self) -> None:
        if getattr(self, "_h", None):
            self._lib.rt_loader_destroy(self._h)
            self._h = None

    def __del__(self):  # pragma: no cover - best effort
        try:
            self.close()
        except Exception:
            pass
