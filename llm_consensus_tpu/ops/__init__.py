from llm_consensus_tpu.ops.norms import rms_norm
from llm_consensus_tpu.ops.rope import apply_rope, rope_cos_sin
from llm_consensus_tpu.ops.activations import swiglu
from llm_consensus_tpu.ops.attention import causal_attention, decode_attention
from llm_consensus_tpu.ops.quant import (
    QuantizedTensor,
    dequantize,
    quantize_params,
    quantize_tensor,
)

__all__ = [
    "QuantizedTensor",
    "rms_norm",
    "apply_rope",
    "rope_cos_sin",
    "swiglu",
    "causal_attention",
    "decode_attention",
    "dequantize",
    "quantize_params",
    "quantize_tensor",
]
