from llm_consensus_tpu.ops.norms import rms_norm
from llm_consensus_tpu.ops.rope import apply_rope, rope_cos_sin
from llm_consensus_tpu.ops.activations import swiglu
from llm_consensus_tpu.ops.attention import causal_attention, decode_attention

__all__ = [
    "rms_norm",
    "apply_rope",
    "rope_cos_sin",
    "swiglu",
    "causal_attention",
    "decode_attention",
]
