"""SwiGLU MLP.

jnp implementation; the two up-projections and the gate multiply are a
single fused region under XLA on TPU (the matmuls land on the MXU, the
silu*gate elementwise fuses into the second matmul's prologue). Weights
may be plain arrays or int8 :class:`~llm_consensus_tpu.ops.quant.
QuantizedTensor` leaves — matmuls route through the quantization-aware
dispatcher either way.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from llm_consensus_tpu.ops.quant import matmul as _qmm


def swiglu(
    x: jnp.ndarray,
    w_gate: jnp.ndarray,
    w_up: jnp.ndarray,
    w_down: jnp.ndarray,
) -> jnp.ndarray:
    """SwiGLU feed-forward: silu(x @ w_gate) * (x @ w_up) @ w_down.

    x: [..., d_model]; w_gate/w_up: [d_model, d_ff]; w_down: [d_ff, d_model]
    (each a plain array or a QuantizedTensor).
    """
    gate = jax.nn.silu(_qmm(x, w_gate))
    return _qmm(gate * _qmm(x, w_up), w_down)
