"""Attention: causal prefill and single-step decode against a KV cache.

Reference counterpart: none (the reference's compute is a remote API call,
``src/main.rs:82-86``); BASELINE.json's north star requires native attention
for the TPU candidate-sampling hot loop. The jnp path here is the
XLA-compiled baseline; :mod:`llm_consensus_tpu.ops.pallas` provides the
flash-style kernels that replace it on the hot path.

Conventions:
- q/k/v are [B, S, H, D] / [B, S, Hkv, D]; GQA groups are expanded by
  broadcasting (no materialized repeat: the einsum indexes kv heads).
- Softmax runs in float32; outputs are cast back to the input dtype.
- Masks are additive-free boolean `where` selects (XLA folds them).
"""

from __future__ import annotations

import jax.numpy as jnp

_NEG_INF = -1e30


def _gqa_scores(q: jnp.ndarray, k: jnp.ndarray) -> jnp.ndarray:
    """Scores [B, Hkv, G, Sq, Sk] where H = Hkv * G (GQA without repeat)."""
    b, sq, h, d = q.shape
    hkv = k.shape[2]
    g = h // hkv
    qg = q.reshape(b, sq, hkv, g, d)
    return jnp.einsum("bqkgd,bskd->bkgqs", qg, k, preferred_element_type=jnp.float32)


def _gqa_out(probs: jnp.ndarray, v: jnp.ndarray, dtype) -> jnp.ndarray:
    b, hkv, g, sq, sk = probs.shape
    out = jnp.einsum(
        "bkgqs,bskd->bqkgd", probs, v.astype(jnp.float32),
        preferred_element_type=jnp.float32,
    )
    return out.reshape(b, sq, hkv * g, -1).astype(dtype)


def causal_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    positions: jnp.ndarray | None = None,
    window: int = 0,
) -> jnp.ndarray:
    """Causal self-attention over a full (prefill) sequence.

    q: [B, S, H, D]; k/v: [B, S, Hkv, D] with H a multiple of Hkv (GQA).
    positions: optional [B, S] integer positions; when given, key j attends
    to query i iff pos_j <= pos_i (supports packed/offset layouts). Default
    is index-causal. ``window`` > 0 adds Mistral-style sliding-window
    masking: query i also ignores keys with pos_i - pos_j >= window.
    """
    scale = q.shape[-1] ** -0.5
    scores = _gqa_scores(q, k) * scale  # [B, Hkv, G, Sq, Sk] fp32
    sq, sk = scores.shape[-2], scores.shape[-1]
    if positions is None:
        qi = jnp.arange(sq)[:, None]
        kj = jnp.arange(sk)[None, :]
        mask = kj <= qi  # [Sq, Sk]
        if window > 0:
            mask &= (qi - kj) < window
        mask = mask[None, None, None]
    else:
        qi = positions[:, :, None]  # [B, Sq, 1]
        kj = positions[:, None, :]  # [B, 1, Sk]
        mask = kj <= qi
        if window > 0:
            mask &= (qi - kj) < window
        mask = mask[:, None, None]  # [B, 1, 1, Sq, Sk]
    scores = jnp.where(mask, scores, _NEG_INF)
    probs = jnp.exp(scores - scores.max(axis=-1, keepdims=True))
    probs = probs / probs.sum(axis=-1, keepdims=True)
    return _gqa_out(probs, v, q.dtype)


def decode_attention_quant(
    q: jnp.ndarray,
    k_q: jnp.ndarray,
    k_scale: jnp.ndarray,
    v_q: jnp.ndarray,
    v_scale: jnp.ndarray,
    valid_len: jnp.ndarray,
    window: int = 0,
) -> jnp.ndarray:
    """Decode attention over an int8 cache (jnp reference path).

    q: [B, 1, H, D]; k_q/v_q: [B, Hkv, S, D] int8 (head-major,
    QuantKVCache layout); k_scale/v_scale: [B, Hkv, S] f32.
    Dequantizes and defers to :func:`decode_attention` — correct
    everywhere, but materializes the bf16 cache; the Pallas kernel
    (ops/pallas.flash_decode_attention_q8) is the TPU hot path.
    """
    k = (k_q.astype(jnp.float32) * k_scale[..., None]).astype(q.dtype)
    v = (v_q.astype(jnp.float32) * v_scale[..., None]).astype(q.dtype)
    # [B, Hkv, S, D] -> [B, S, Hkv, D]
    return decode_attention(
        q,
        k.transpose(0, 2, 1, 3),
        v.transpose(0, 2, 1, 3),
        valid_len,
        window=window,
    )


def decode_attention(
    q: jnp.ndarray,
    k_cache: jnp.ndarray,
    v_cache: jnp.ndarray,
    valid_len: jnp.ndarray,
    window: int = 0,
) -> jnp.ndarray:
    """One-token decode attention against a fixed-size KV cache.

    q: [B, 1, H, D]; k_cache/v_cache: [B, max_len, Hkv, D];
    valid_len: [B] number of valid cache slots per sequence (the new token's
    k/v must already be written; slots >= valid_len are masked out).
    ``window`` > 0: only the last ``window`` cache slots attend (cache slot
    index == token position; the query sits at position valid_len - 1).
    """
    scale = q.shape[-1] ** -0.5
    scores = _gqa_scores(q, k_cache) * scale  # [B, Hkv, G, 1, max_len]
    max_len = k_cache.shape[1]
    slot = jnp.arange(max_len)[None, :]  # [1, max_len]
    mask = slot < valid_len[:, None]
    if window > 0:
        mask &= slot >= (valid_len[:, None] - window)
    mask = mask[:, None, None, None]  # [B,1,1,1,max_len]
    scores = jnp.where(mask, scores, _NEG_INF)
    probs = jnp.exp(scores - scores.max(axis=-1, keepdims=True))
    probs = probs / probs.sum(axis=-1, keepdims=True)
    return _gqa_out(probs, v_cache, q.dtype)


def chunk_decode_attention(
    q: jnp.ndarray,
    k_cache: jnp.ndarray,
    v_cache: jnp.ndarray,
    valid_len: jnp.ndarray,
    window: int = 0,
) -> jnp.ndarray:
    """K-token chunk decode against the cache (speculative verification).

    q: [B, K, H, D] — K new tokens per row whose k/v are already written
    at slots [valid_len, valid_len + K); k_cache/v_cache: [B, S, Hkv, D];
    valid_len: [B] pre-chunk fill. Chunk token i attends cache slots
    < valid_len + i + 1 — ragged causal within the chunk, exactly the
    one-token :func:`decode_attention` rule extended to K queries (one
    forward verifies a whole draft, the speculative-decoding hot path).
    ``window`` > 0 (Mistral): token i also ignores slots
    <= valid_len + i - window (cache slot j holds position j).
    """
    scale = q.shape[-1] ** -0.5
    scores = _gqa_scores(q, k_cache) * scale  # [B, Hkv, G, K, S]
    kq = q.shape[1]
    s = k_cache.shape[1]
    limit = valid_len[:, None, None] + jnp.arange(kq)[None, :, None] + 1
    slots = jnp.arange(s)[None, None, :]
    mask = slots < limit  # [B, K, S]
    if window > 0:
        mask &= slots > limit - 1 - window
    scores = jnp.where(mask[:, None, None], scores, _NEG_INF)
    probs = jnp.exp(scores - scores.max(axis=-1, keepdims=True))
    probs = probs / probs.sum(axis=-1, keepdims=True)
    return _gqa_out(probs, v_cache, q.dtype)
