"""Attention: causal prefill and single-step decode against a KV cache.

Reference counterpart: none (the reference's compute is a remote API call,
``src/main.rs:82-86``); BASELINE.json's north star requires native attention
for the TPU candidate-sampling hot loop. The jnp path here is the
XLA-compiled baseline; :mod:`llm_consensus_tpu.ops.pallas` provides the
flash-style kernels that replace it on the hot path.

Conventions:
- q/k/v are [B, S, H, D] / [B, S, Hkv, D]; GQA groups are expanded by
  broadcasting (no materialized repeat: the einsum indexes kv heads).
- Softmax runs in float32; outputs are cast back to the input dtype.
- Masks are additive-free boolean `where` selects (XLA folds them).
"""

from __future__ import annotations

import jax.numpy as jnp

_NEG_INF = -1e30


def _gqa_scores(q: jnp.ndarray, k: jnp.ndarray) -> jnp.ndarray:
    """Scores [B, Hkv, G, Sq, Sk] where H = Hkv * G (GQA without repeat)."""
    b, sq, h, d = q.shape
    hkv = k.shape[2]
    g = h // hkv
    qg = q.reshape(b, sq, hkv, g, d)
    return jnp.einsum("bqkgd,bskd->bkgqs", qg, k, preferred_element_type=jnp.float32)


def _gqa_out(probs: jnp.ndarray, v: jnp.ndarray, dtype) -> jnp.ndarray:
    b, hkv, g, sq, sk = probs.shape
    out = jnp.einsum(
        "bkgqs,bskd->bqkgd", probs, v.astype(jnp.float32),
        preferred_element_type=jnp.float32,
    )
    return out.reshape(b, sq, hkv * g, -1).astype(dtype)


def causal_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    positions: jnp.ndarray | None = None,
    window: int = 0,
) -> jnp.ndarray:
    """Causal self-attention over a full (prefill) sequence.

    q: [B, S, H, D]; k/v: [B, S, Hkv, D] with H a multiple of Hkv (GQA).
    positions: optional [B, S] integer positions; when given, key j attends
    to query i iff pos_j <= pos_i (supports packed/offset layouts). Default
    is index-causal. ``window`` > 0 adds Mistral-style sliding-window
    masking: query i also ignores keys with pos_i - pos_j >= window.
    """
    scale = q.shape[-1] ** -0.5
    scores = _gqa_scores(q, k) * scale  # [B, Hkv, G, Sq, Sk] fp32
    sq, sk = scores.shape[-2], scores.shape[-1]
    if positions is None:
        qi = jnp.arange(sq)[:, None]
        kj = jnp.arange(sk)[None, :]
        mask = kj <= qi  # [Sq, Sk]
        if window > 0:
            mask &= (qi - kj) < window
        mask = mask[None, None, None]
    else:
        qi = positions[:, :, None]  # [B, Sq, 1]
        kj = positions[:, None, :]  # [B, 1, Sk]
        mask = kj <= qi
        if window > 0:
            mask &= (qi - kj) < window
        mask = mask[:, None, None]  # [B, 1, 1, Sq, Sk]
    scores = jnp.where(mask, scores, _NEG_INF)
    probs = jnp.exp(scores - scores.max(axis=-1, keepdims=True))
    probs = probs / probs.sum(axis=-1, keepdims=True)
    return _gqa_out(probs, v, q.dtype)


def decode_attention_quant(
    q: jnp.ndarray,
    k_q: jnp.ndarray,
    k_scale: jnp.ndarray,
    v_q: jnp.ndarray,
    v_scale: jnp.ndarray,
    valid_len: jnp.ndarray,
    window: int = 0,
) -> jnp.ndarray:
    """Decode attention over an int8 cache (jnp reference path).

    q: [B, 1, H, D]; k_q/v_q: [B, Hkv, S, D] int8 (head-major,
    QuantKVCache layout); k_scale/v_scale: [B, Hkv, S] f32.
    Dequantizes and defers to :func:`decode_attention` — correct
    everywhere, but materializes the bf16 cache; the Pallas kernel
    (ops/pallas.flash_decode_attention_q8) is the TPU hot path.
    """
    k = (k_q.astype(jnp.float32) * k_scale[..., None]).astype(q.dtype)
    v = (v_q.astype(jnp.float32) * v_scale[..., None]).astype(q.dtype)
    # [B, Hkv, S, D] -> [B, S, Hkv, D]
    return decode_attention(
        q,
        k.transpose(0, 2, 1, 3),
        v.transpose(0, 2, 1, 3),
        valid_len,
        window=window,
    )


def decode_attention(
    q: jnp.ndarray,
    k_cache: jnp.ndarray,
    v_cache: jnp.ndarray,
    valid_len: jnp.ndarray,
    window: int = 0,
) -> jnp.ndarray:
    """One-token decode attention against a fixed-size KV cache.

    q: [B, 1, H, D]; k_cache/v_cache: [B, max_len, Hkv, D];
    valid_len: [B] number of valid cache slots per sequence (the new token's
    k/v must already be written; slots >= valid_len are masked out).
    ``window`` > 0: only the last ``window`` cache slots attend (cache slot
    index == token position; the query sits at position valid_len - 1).
    """
    scale = q.shape[-1] ** -0.5
    scores = _gqa_scores(q, k_cache) * scale  # [B, Hkv, G, 1, max_len]
    max_len = k_cache.shape[1]
    slot = jnp.arange(max_len)[None, :]  # [1, max_len]
    mask = slot < valid_len[:, None]
    if window > 0:
        mask &= slot >= (valid_len[:, None] - window)
    mask = mask[:, None, None, None]  # [B,1,1,1,max_len]
    scores = jnp.where(mask, scores, _NEG_INF)
    probs = jnp.exp(scores - scores.max(axis=-1, keepdims=True))
    probs = probs / probs.sum(axis=-1, keepdims=True)
    return _gqa_out(probs, v_cache, q.dtype)


def merge_decode_partials(
    m1: jnp.ndarray,
    l1: jnp.ndarray,
    o1: jnp.ndarray,
    m2: jnp.ndarray,
    l2: jnp.ndarray,
    o2: jnp.ndarray,
) -> jnp.ndarray:
    """Exact two-way merge of partial softmax-attention results.

    Each partial is the flash-decoding (m, l, o) triple over a disjoint
    slice of the key/value slots: ``m`` the running max score, ``l`` the
    softmax denominator at that max, ``o = acc / l`` the normalized
    partial output (m/l broadcast against o's trailing dims). The merge
    is the standard log-sum-exp recombination

        m = max(m1, m2);  a_i = l_i * exp(m_i - m)
        out = (a1 * o1 + a2 * o2) / (a1 + a2)

    which reproduces the single-pass softmax EXACTLY (up to float
    associativity) — the identity that makes the shared-prefix /
    per-sequence-suffix attention split lossless. Empty partials ride
    through as (m = -inf, l = 0): their weight a_i is forced to zero, so
    a row whose phase contributed nothing (an ungrouped sequence's
    shared phase) falls back to the other phase's result alone.
    """
    m = jnp.maximum(m1, m2)
    # exp(-inf - -inf) is NaN; substitute 0 for the max when BOTH
    # phases are empty (the all-masked row — output is garbage anyway,
    # but it must be finite garbage, mirroring the paged kernel).
    m_safe = jnp.where(m <= _NEG_INF / 2, 0.0, m)
    a1 = jnp.where(l1 > 0, l1 * jnp.exp(m1 - m_safe), 0.0)
    a2 = jnp.where(l2 > 0, l2 * jnp.exp(m2 - m_safe), 0.0)
    denom = jnp.maximum(a1 + a2, 1e-30)
    return (a1 * o1 + a2 * o2) / denom


def _partial_softmax(scores: jnp.ndarray, v: jnp.ndarray, mask: jnp.ndarray):
    """(m, l, o) partial over one masked slot range.

    scores: [B, Hkv, G, 1, S] fp32; v: [B, S, Hkv, D]; mask broadcastable
    to scores. Returns m/l [B, Hkv, G, 1, 1] and o [B, Hkv, G, 1, D]
    (normalized; zeros where the range is empty).
    """
    scores = jnp.where(mask, scores, _NEG_INF)
    m = scores.max(axis=-1, keepdims=True)
    m_safe = jnp.where(m <= _NEG_INF / 2, 0.0, m)
    p = jnp.exp(scores - m_safe)
    l = p.sum(axis=-1, keepdims=True)
    acc = jnp.einsum(
        "bkgqs,bskd->bkgqd", p, v.astype(jnp.float32),
        preferred_element_type=jnp.float32,
    )
    o = acc / jnp.maximum(l, 1e-30)
    return m, l, o


def decode_attention_shared_prefix(
    q: jnp.ndarray,
    k_cache: jnp.ndarray,
    v_cache: jnp.ndarray,
    valid_len: jnp.ndarray,
    prefix_len: jnp.ndarray,
) -> jnp.ndarray:
    """Two-phase decode attention over a batch sharing one prompt prefix.

    The XLA reference for the shared-prefix kernel family
    (:mod:`llm_consensus_tpu.ops.pallas`): every row's cache slots
    [0, prefix_len) hold IDENTICAL K/V (the self-consistency fan-out
    after a shared prefill), so phase 1 attends all rows' queries
    against ROW 0's copy of the prefix — one logical read of the common
    KV — and phase 2 attends each row against its own suffix slots
    [prefix_len, valid_len). The two partial softmaxes merge exactly
    via :func:`merge_decode_partials`. Output equals
    :func:`decode_attention` whenever the shared-prefix precondition
    holds (and ``prefix_len`` may be 0, degrading to the plain path).

    q: [B, 1, H, D]; k_cache/v_cache: [B, max_len, Hkv, D];
    valid_len: [B]; prefix_len: scalar int32 (uniform — the fan-out's
    shared prompt length). No sliding-window support: callers fall back
    to :func:`decode_attention` for windowed configs.
    """
    scale = q.shape[-1] ** -0.5
    b = q.shape[0]
    max_len = k_cache.shape[1]
    slot = jnp.arange(max_len)[None, :]  # [1, max_len]

    # Phase 1: all B rows' queries vs row 0's prefix KV.
    k_shared = k_cache[:1]  # [1, S, Hkv, D] — the one copy phase 1 reads
    v_shared = v_cache[:1]
    scores1 = _gqa_scores(q, jnp.broadcast_to(k_shared, k_cache.shape))
    scores1 = scores1 * scale
    mask1 = (slot < prefix_len)[:, None, None, None]
    m1, l1, o1 = _partial_softmax(
        scores1, jnp.broadcast_to(v_shared, v_cache.shape), mask1
    )

    # Phase 2: each row vs its own suffix slots [prefix_len, valid).
    scores2 = _gqa_scores(q, k_cache) * scale
    mask2 = ((slot >= prefix_len) & (slot < valid_len[:, None]))[
        :, None, None, None
    ]
    m2, l2, o2 = _partial_softmax(scores2, v_cache, mask2)

    out = merge_decode_partials(m1, l1, o1, m2, l2, o2)
    hkv, g = out.shape[1], out.shape[2]
    return out.transpose(0, 3, 1, 2, 4).reshape(b, 1, hkv * g, -1).astype(
        q.dtype
    )


def decode_attention_shared_prefix_quant(
    q: jnp.ndarray,
    k_q: jnp.ndarray,
    k_scale: jnp.ndarray,
    v_q: jnp.ndarray,
    v_scale: jnp.ndarray,
    valid_len: jnp.ndarray,
    prefix_len: jnp.ndarray,
) -> jnp.ndarray:
    """Shared-prefix decode attention over the int8 head-major cache
    (jnp reference path — dequantize, defer). Layouts as
    :func:`decode_attention_quant`."""
    k = (k_q.astype(jnp.float32) * k_scale[..., None]).astype(q.dtype)
    v = (v_q.astype(jnp.float32) * v_scale[..., None]).astype(q.dtype)
    return decode_attention_shared_prefix(
        q, k.transpose(0, 2, 1, 3), v.transpose(0, 2, 1, 3),
        valid_len, prefix_len,
    )


def ragged_paged_attention_reference(
    q: jnp.ndarray,
    k_pool: jnp.ndarray,
    v_pool: jnp.ndarray,
    page_table: jnp.ndarray,
    valid_len: jnp.ndarray,
    *,
    q_chunk: jnp.ndarray | None = None,
    chunk_table: jnp.ndarray | None = None,
    chunk_start=None,
    window: int = 0,
):
    """XLA reference for the ragged paged attention kernel — the parity
    oracle, the non-Pallas serving path, AND the mesh fallback: when
    the Pallas kernel can't shard over a mesh (``transformer.
    ragged_mesh_shardable`` — e.g. kv heads indivisible by the model
    axis), the serving stack runs THIS function under GSPMD, which
    partitions the gathers/softmax automatically, so every feature
    still engages (PR 13).

    Same ragged semantics as
    :func:`llm_consensus_tpu.ops.pallas.ragged_paged_attention`,
    composed from the gather-then-attend references: decode rows
    materialize their tables out of the pool and apply
    :func:`decode_attention`'s one-token rule; the optional
    prefill-chunk row (``q_chunk`` [C, H, D], queries at absolute
    positions ``chunk_start + i`` through ``chunk_table`` [P]) applies
    :func:`chunk_decode_attention`'s ragged-causal rule. Shared-prefix
    groups are a pure bandwidth optimization in the kernel and do not
    exist here — the kernel's grouped output must match this ungrouped
    math (the PR 3 contract, extended to mixed rows).

    q: [B, H, D] — one query per decode row — or [B, NQ, H, D]:
    NQ-token speculative VERIFY rows (PR 9), row b's queries at
    positions ``valid_len[b] - NQ + i`` (``valid_len`` stays "tokens
    readable", the NQ new tokens' K/V already written), masked by
    :func:`chunk_decode_attention`'s ragged-causal rule per row — a
    verify row is exactly a chunk row over the row's own table.
    k_pool/v_pool: [n_pages, page, Hkv, D]; page_table: [B, P];
    valid_len: [B]. Returns out_dec shaped like ``q`` (and out_chunk
    [C, H, D] when ``q_chunk`` is given).
    """
    nq = None
    if q.ndim == 4:
        b, nq, h, d = q.shape
    else:
        b, h, d = q.shape
    hkv = k_pool.shape[2]
    k_seq = k_pool[page_table].reshape(b, -1, hkv, d)
    v_seq = v_pool[page_table].reshape(b, -1, hkv, d)
    if nq is None:
        out = decode_attention(
            q[:, None], k_seq, v_seq, valid_len, window=window
        )[:, 0]
    else:
        out = chunk_decode_attention(
            q, k_seq, v_seq, valid_len - nq, window=window
        )
    if q_chunk is None:
        return out
    kc = k_pool[chunk_table].reshape(1, -1, hkv, d)
    vc = v_pool[chunk_table].reshape(1, -1, hkv, d)
    start = jnp.asarray(chunk_start, jnp.int32).reshape(1)
    out_chunk = chunk_decode_attention(
        q_chunk[None], kc, vc, start, window=window
    )[0]
    return out, out_chunk


def chunk_decode_attention(
    q: jnp.ndarray,
    k_cache: jnp.ndarray,
    v_cache: jnp.ndarray,
    valid_len: jnp.ndarray,
    window: int = 0,
) -> jnp.ndarray:
    """K-token chunk decode against the cache (speculative verification).

    q: [B, K, H, D] — K new tokens per row whose k/v are already written
    at slots [valid_len, valid_len + K); k_cache/v_cache: [B, S, Hkv, D];
    valid_len: [B] pre-chunk fill. Chunk token i attends cache slots
    < valid_len + i + 1 — ragged causal within the chunk, exactly the
    one-token :func:`decode_attention` rule extended to K queries (one
    forward verifies a whole draft, the speculative-decoding hot path).
    ``window`` > 0 (Mistral): token i also ignores slots
    <= valid_len + i - window (cache slot j holds position j).
    """
    scale = q.shape[-1] ** -0.5
    scores = _gqa_scores(q, k_cache) * scale  # [B, Hkv, G, K, S]
    kq = q.shape[1]
    s = k_cache.shape[1]
    limit = valid_len[:, None, None] + jnp.arange(kq)[None, :, None] + 1
    slots = jnp.arange(s)[None, None, :]
    mask = slots < limit  # [B, K, S]
    if window > 0:
        mask &= slots > limit - 1 - window
    scores = jnp.where(mask[:, None, None], scores, _NEG_INF)
    probs = jnp.exp(scores - scores.max(axis=-1, keepdims=True))
    probs = probs / probs.sum(axis=-1, keepdims=True)
    return _gqa_out(probs, v_cache, q.dtype)
