"""RMSNorm.

The reference has no compute kernels at all (its "device layer" is a remote
HTTPS call, ``src/main.rs:82-86``); per BASELINE.json's north star the TPU
build supplies RMSNorm natively. The default path is plain jnp — XLA fuses
the reduction + scale into surrounding ops on TPU — with an optional Pallas
kernel (:mod:`llm_consensus_tpu.ops.pallas.rmsnorm`) for the fused
norm+scale hot path in decode.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def rms_norm(x: jnp.ndarray, weight: jnp.ndarray, eps: float = 1e-5) -> jnp.ndarray:
    """Root-mean-square layer norm (Llama convention: scale only, no bias).

    The reduction runs in float32 regardless of input dtype (bf16 activations
    would lose precision in the mean-of-squares), and the result is cast back.
    """
    dtype = x.dtype
    xf = x.astype(jnp.float32)
    var = jnp.mean(jnp.square(xf), axis=-1, keepdims=True)
    normed = xf * jax.lax.rsqrt(var + eps)
    return (normed * weight.astype(jnp.float32)).astype(dtype)
