"""Pallas TPU kernels for the inference hot loop.

The reference has no native compute of its own (its FLOPs live behind the
Gemini API, ``src/main.rs:82-86``); these kernels are the TPU build's
"native op" layer per SURVEY.md §7 step 1 — fused attention (prefill and
cached decode) and RMSNorm that keep the softmax pipeline in VMEM instead
of round-tripping score matrices through HBM.

On non-TPU backends the kernels run in Pallas interpret mode (tests), and
every wrapper has a jnp reference twin in :mod:`llm_consensus_tpu.ops`
used for numerics cross-checks.
"""

from llm_consensus_tpu.ops.pallas.attention import (
    flash_causal_attention,
    flash_decode_attention,
)
from llm_consensus_tpu.ops.pallas.norms import fused_rms_norm

__all__ = [
    "flash_causal_attention",
    "flash_decode_attention",
    "fused_rms_norm",
]
