"""Pallas TPU kernels for the inference hot loop.

The reference has no native compute of its own (its FLOPs live behind the
Gemini API, ``src/main.rs:82-86``); these kernels are the TPU build's
"native op" layer per SURVEY.md §7 step 1 — fused attention (prefill,
cached decode, int8-cache decode), RMSNorm, and the fused int8-dequant
matmul that keep score matrices / dequantized weights in VMEM instead of
round-tripping through HBM.

On non-TPU backends the kernels run in Pallas interpret mode (tests), and
every wrapper has a jnp reference twin in :mod:`llm_consensus_tpu.ops`
used for numerics cross-checks.
"""

from llm_consensus_tpu.ops.pallas.attention import (
    flash_causal_attention,
    flash_decode_attention,
    flash_decode_attention_q8,
    flash_decode_attention_q8_stacked,
    flash_decode_attention_shared_prefix,
    flash_decode_attention_shared_prefix_q8,
    flash_decode_attention_shared_prefix_q8_stacked,
    paged_decode_attention,
    paged_decode_attention_grouped,
    ragged_paged_attention,
)
from llm_consensus_tpu.ops.pallas.norms import fused_rms_norm
from llm_consensus_tpu.ops.pallas.quant_matmul import quant_matmul_2d

__all__ = [
    "flash_causal_attention",
    "flash_decode_attention",
    "flash_decode_attention_q8",
    "flash_decode_attention_q8_stacked",
    "flash_decode_attention_shared_prefix",
    "flash_decode_attention_shared_prefix_q8",
    "flash_decode_attention_shared_prefix_q8_stacked",
    "paged_decode_attention",
    "paged_decode_attention_grouped",
    "ragged_paged_attention",
    "fused_rms_norm",
    "quant_matmul_2d",
]
