"""Fused attention kernels (Pallas/Mosaic).

Three kernel families, mirroring the jnp reference paths in
:mod:`llm_consensus_tpu.ops.attention`:

- :func:`flash_causal_attention` — prefill/full attention. Grid over
  (batch x kv-head, query blocks); each program holds its (b, kv) K/V
  slab in VMEM, computes a [G*blk_q, S] score tile in fp32 on the MXU,
  applies the causal mask, does the softmax in VMEM, and writes the
  [G*blk_q, D] output — the score matrix never touches HBM.
- :func:`flash_decode_attention` — single-token decode against the KV
  cache with per-sequence ``valid_len`` masking (the ragged-decode op of
  BASELINE.json's north star). Grid over (batch, kv-head).
- :func:`ragged_paged_attention` — ONE program for the whole serving
  mix: decode rows, prefill-chunk rows, shared-prefix groups, and
  sliding windows over the page pool (and, via thin wrappers, the
  dense bf16 / int8 head-major / stacked int8 caches), with per-row
  metadata riding scalar prefetch. Everything that used to be its own
  kernel (plain paged decode, the grouped two-phase family) is now a
  wrapper over this body.

GQA layout: H = Hkv * G query heads share each kv head; programs are
per-(batch, kv-head) and process all G group heads at once, so K/V are
read exactly once per program (no repeated-KV materialization anywhere).

Tiling: D (head_dim) and S pad to lane width (128); fp32 accumulation via
``preferred_element_type``. On CPU tests, ``interpret=True`` is selected
automatically (same kernels, interpreted).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG_INF = -1e30


def _interpret_default() -> bool:
    return jax.default_backend() != "tpu"


# ---------------------------------------------------------------------------
# Prefill / full causal attention
# ---------------------------------------------------------------------------


def _causal_kernel(q_ref, k_ref, v_ref, o_ref, *, blk_q: int, scale: float):
    """One (b, kv-head, q-block) program.

    q_ref: [1, blk_q, G, D]; k_ref/v_ref: [1, S, D]; o_ref: [1, blk_q, G, D].
    """
    qi = pl.program_id(1)
    _, _, g, d = q_ref.shape
    s = k_ref.shape[1]

    q = q_ref[0].astype(jnp.float32)  # [blk_q, G, D]
    q2 = q.reshape(blk_q * g, d)
    k = k_ref[0]  # [S, D]
    scores = jax.lax.dot_general(
        q2,
        k.astype(jnp.float32),
        dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    ) * scale  # [blk_q*G, S]
    scores = scores.reshape(blk_q, g, s)

    q_pos = qi * blk_q + jax.lax.broadcasted_iota(jnp.int32, (blk_q, 1, 1), 0)
    k_pos = jax.lax.broadcasted_iota(jnp.int32, (1, 1, s), 2)
    scores = jnp.where(k_pos <= q_pos, scores, _NEG_INF)

    m = jnp.max(scores, axis=-1, keepdims=True)
    p = jnp.exp(scores - m)
    denom = jnp.sum(p, axis=-1, keepdims=True)
    p = (p / denom).reshape(blk_q * g, s)

    out = jax.lax.dot_general(
        p,
        v_ref[0].astype(jnp.float32),
        dimension_numbers=(((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )  # [blk_q*G, D]
    o_ref[0] = out.reshape(blk_q, g, d).astype(o_ref.dtype)


def flash_causal_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    blk_q: int = 256,
    interpret: bool | None = None,
) -> jnp.ndarray:
    """Causal attention, index-causal positions (the prefill hot path).

    q: [B, S, H, D]; k/v: [B, S, Hkv, D]. S must divide by ``blk_q``
    (callers pad prompts to buckets, ``engine.EngineConfig.seq_buckets``).
    Returns [B, S, H, D] in q's dtype.
    """
    b, s, h, d = q.shape
    hkv = k.shape[2]
    g = h // hkv
    blk_q = min(blk_q, s)
    if s % blk_q:
        raise ValueError(f"seq len {s} not divisible by q block {blk_q}")
    if interpret is None:
        interpret = _interpret_default()
    scale = d**-0.5

    # [B, S, Hkv, G, D] -> per-(b, kv) programs see [blk_q, G, D] q tiles.
    q5 = q.reshape(b, s, hkv, g, d)
    kt = k.transpose(0, 2, 1, 3).reshape(b * hkv, s, d)
    vt = v.transpose(0, 2, 1, 3).reshape(b * hkv, s, d)
    q5 = q5.transpose(0, 2, 1, 3, 4).reshape(b * hkv, s, g, d)

    out = pl.pallas_call(
        functools.partial(_causal_kernel, blk_q=blk_q, scale=scale),
        out_shape=jax.ShapeDtypeStruct((b * hkv, s, g, d), q.dtype),
        grid=(b * hkv, s // blk_q),
        in_specs=[
            pl.BlockSpec(
                (1, blk_q, g, d),
                lambda bh, qi: (bh, qi, 0, 0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(
                (1, s, d), lambda bh, qi: (bh, 0, 0), memory_space=pltpu.VMEM
            ),
            pl.BlockSpec(
                (1, s, d), lambda bh, qi: (bh, 0, 0), memory_space=pltpu.VMEM
            ),
        ],
        out_specs=pl.BlockSpec(
            (1, blk_q, g, d),
            lambda bh, qi: (bh, qi, 0, 0),
            memory_space=pltpu.VMEM,
        ),
        interpret=interpret,
    )(q5, kt, vt)
    # [B*Hkv, S, G, D] -> [B, S, H, D]
    return (
        out.reshape(b, hkv, s, g, d).transpose(0, 2, 1, 3, 4).reshape(b, s, h, d)
    )


# ---------------------------------------------------------------------------
# Decode attention against the KV cache
# ---------------------------------------------------------------------------


def _decode_kernel(len_ref, q_ref, k_ref, v_ref, o_ref, *, scale: float):
    """One (batch, kv-head) program.

    len_ref: [B*Hkv] whole-array SMEM valid lengths (unblocked — Mosaic
    rejects rank-1 blocked SMEM specs; index by program id instead);
    q_ref: [1, 1, G, D]; k_ref/v_ref: [1, S, D]; o_ref: [1, 1, G, D].
    """
    _, _, g, d = q_ref.shape
    s = k_ref.shape[1]
    valid = len_ref[pl.program_id(0)]

    q = q_ref[0, 0].astype(jnp.float32)  # [G, D]
    scores = jax.lax.dot_general(
        q,
        k_ref[0].astype(jnp.float32),
        dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    ) * scale  # [G, S]
    slot = jax.lax.broadcasted_iota(jnp.int32, (1, s), 1)
    scores = jnp.where(slot < valid, scores, _NEG_INF)

    m = jnp.max(scores, axis=-1, keepdims=True)
    p = jnp.exp(scores - m)
    p = p / jnp.sum(p, axis=-1, keepdims=True)

    out = jax.lax.dot_general(
        p,
        v_ref[0].astype(jnp.float32),
        dimension_numbers=(((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )  # [G, D]
    o_ref[0, 0] = out.astype(o_ref.dtype)


def _q8_attend(q, kq, ks_row, vq, vs_row, mask, scale: float):
    """Shared q8 decode-attention arithmetic for one (row, kv-head).

    q: [G, D]; kq/vq: [S, D] int8; ks_row/vs_row: [1, S] f32;
    mask: [1, S] bool. Returns [G, D] f32. All three q8 decode kernels
    (per-head grid, batch-row grid, stacked-cache grid) call this — the
    numerics live in exactly one place.

    Dequant is linear: fold the per-slot scales into the [G, S]
    scores/probs instead of scaling the [S, D] K/V slabs (D-times
    fewer VPU ops; int8 slabs feed the MXU after a bare cast).
    """
    scores = jax.lax.dot_general(
        q.astype(jnp.float32),
        kq.astype(jnp.float32),
        dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    ) * (ks_row * scale)  # [G, S] * [1, S]
    scores = jnp.where(mask, scores, _NEG_INF)
    m = jnp.max(scores, axis=-1, keepdims=True)
    p = jnp.exp(scores - m)
    p = p / jnp.sum(p, axis=-1, keepdims=True)
    return jax.lax.dot_general(
        p * vs_row,  # [G, S] * [1, S]
        vq.astype(jnp.float32),
        dimension_numbers=(((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )  # [G, D]


def _decode_q8_kernel(
    len_ref, q_ref, kq_ref, ks_ref, vq_ref, vs_ref, o_ref, *, scale: float
):
    """One (batch, kv-head) program over an int8 cache.

    len_ref: [B*Hkv] whole-array SMEM (unblocked, indexed by program id);
    q_ref: [1, 1, G, D]; kq_ref/vq_ref: [1, S, D] int8;
    ks_ref/vs_ref: [1, 1, S] f32 (leading singleton keeps the block's
    trailing dims equal to the array's — the Mosaic tiling rule);
    o_ref: [1, 1, G, D]. K/V dequantize in-register — HBM reads stay
    int8 (+ one f32 scale per slot).
    """
    s = kq_ref.shape[1]
    valid = len_ref[pl.program_id(0)]
    slot = jax.lax.broadcasted_iota(jnp.int32, (1, s), 1)
    out = _q8_attend(
        q_ref[0, 0], kq_ref[0], ks_ref[0], vq_ref[0], vs_ref[0],
        slot < valid, scale,
    )
    o_ref[0, 0] = out.astype(o_ref.dtype)


def _decode_q8_row_kernel(
    len_ref, q_ref, kq_ref, ks_ref, vq_ref, vs_ref, o_ref, *, scale: float
):
    """One batch-row program over the int8 cache, ALL kv heads.

    len_ref: [B] whole-array SMEM; q_ref: [1, Hkv, G, D];
    kq_ref/vq_ref: [1, Hkv, S, D] int8; ks_ref/vs_ref: [1, Hkv, S] f32;
    o_ref: [1, Hkv, G, D].

    Per-(batch, head) programs (``_decode_q8_kernel``) move ~64 KB of
    cache each — too little work per grid step, and at bench shapes the
    per-step pipeline overhead dominates (measured 4.7x slower than this
    row-program on v5e at B=64, Hkv=8, S=256). One program per batch row
    streams Hkv slabs (~0.5 MB) and unrolls the per-head attention; the
    arithmetic is identical (f32 dots), so outputs are bit-equal.
    """
    hkv = q_ref.shape[1]
    s = kq_ref.shape[2]
    valid = len_ref[pl.program_id(0)]
    slot = jax.lax.broadcasted_iota(jnp.int32, (1, s), 1)
    mask = slot < valid
    for head in range(hkv):  # static unroll over kv heads
        out = _q8_attend(
            q_ref[0, head],
            kq_ref[0, head],
            ks_ref[0, head][None, :],
            vq_ref[0, head],
            vs_ref[0, head][None, :],
            mask,
            scale,
        )
        o_ref[0, head] = out.astype(o_ref.dtype)


# Per-program K+V int8 block budget for the row kernel (double-buffered
# by the grid pipeline); caches larger than this fall back to the
# per-(batch, head) grid, whose blocks are Hkv-times smaller.
_ROW_KERNEL_MAX_KV_BYTES = 4 * 1024 * 1024


def flash_decode_attention_q8(
    q: jnp.ndarray,
    k_q: jnp.ndarray,
    k_scale: jnp.ndarray,
    v_q: jnp.ndarray,
    v_scale: jnp.ndarray,
    valid_len: jnp.ndarray,
    interpret: bool | None = None,
) -> jnp.ndarray:
    """Decode attention over the int8 head-major cache.

    q: [B, 1, H, D]; k_q/v_q: [B, Hkv, S, D] int8 (QuantKVCache layout —
    the reshape to per-(b, head) [S, D] slabs is zero-copy, unlike the
    bf16 kernel's transpose); k_scale/v_scale: [B, Hkv, S] f32;
    valid_len: [B]. Returns [B, 1, H, D] in q's dtype.

    Dispatches to the batch-row program (one grid step per row, all kv
    heads — the fast path at decode shapes) when the row's K+V block
    fits the VMEM budget, else to the per-(batch, head) program.
    """
    b, _, h, d = q.shape
    hkv, s = k_q.shape[1], k_q.shape[2]
    g = h // hkv
    if interpret is None:
        interpret = _interpret_default()
    scale = d**-0.5

    if 2 * hkv * s * d <= _ROW_KERNEL_MAX_KV_BYTES:
        q4 = q.reshape(b, 1, hkv, g, d).transpose(0, 2, 1, 3, 4).reshape(
            b, hkv, g, d
        )
        out = pl.pallas_call(
            functools.partial(_decode_q8_row_kernel, scale=scale),
            out_shape=jax.ShapeDtypeStruct((b, hkv, g, d), q.dtype),
            grid=(b,),
            in_specs=[
                pl.BlockSpec(memory_space=pltpu.SMEM),
                pl.BlockSpec(
                    (1, hkv, g, d),
                    lambda i: (i, 0, 0, 0),
                    memory_space=pltpu.VMEM,
                ),
                pl.BlockSpec(
                    (1, hkv, s, d),
                    lambda i: (i, 0, 0, 0),
                    memory_space=pltpu.VMEM,
                ),
                pl.BlockSpec(
                    (1, hkv, s), lambda i: (i, 0, 0), memory_space=pltpu.VMEM
                ),
                pl.BlockSpec(
                    (1, hkv, s, d),
                    lambda i: (i, 0, 0, 0),
                    memory_space=pltpu.VMEM,
                ),
                pl.BlockSpec(
                    (1, hkv, s), lambda i: (i, 0, 0), memory_space=pltpu.VMEM
                ),
            ],
            out_specs=pl.BlockSpec(
                (1, hkv, g, d), lambda i: (i, 0, 0, 0), memory_space=pltpu.VMEM
            ),
            interpret=interpret,
        )(valid_len.astype(jnp.int32), q4, k_q, k_scale, v_q, v_scale)
        return (
            out.reshape(b, hkv, 1, g, d)
            .transpose(0, 2, 1, 3, 4)
            .reshape(b, 1, h, d)
        )

    q4 = q.reshape(b, 1, hkv, g, d).transpose(0, 2, 1, 3, 4).reshape(
        b * hkv, 1, g, d
    )
    kq2 = k_q.reshape(b * hkv, s, d)
    vq2 = v_q.reshape(b * hkv, s, d)
    ks2 = k_scale.reshape(b * hkv, 1, s)
    vs2 = v_scale.reshape(b * hkv, 1, s)
    lens = jnp.repeat(valid_len.astype(jnp.int32), hkv)

    out = pl.pallas_call(
        functools.partial(_decode_q8_kernel, scale=scale),
        out_shape=jax.ShapeDtypeStruct((b * hkv, 1, g, d), q.dtype),
        grid=(b * hkv,),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(
                (1, 1, g, d), lambda bh: (bh, 0, 0, 0), memory_space=pltpu.VMEM
            ),
            pl.BlockSpec(
                (1, s, d), lambda bh: (bh, 0, 0), memory_space=pltpu.VMEM
            ),
            pl.BlockSpec(
                (1, 1, s), lambda bh: (bh, 0, 0), memory_space=pltpu.VMEM
            ),
            pl.BlockSpec(
                (1, s, d), lambda bh: (bh, 0, 0), memory_space=pltpu.VMEM
            ),
            pl.BlockSpec(
                (1, 1, s), lambda bh: (bh, 0, 0), memory_space=pltpu.VMEM
            ),
        ],
        out_specs=pl.BlockSpec(
            (1, 1, g, d), lambda bh: (bh, 0, 0, 0), memory_space=pltpu.VMEM
        ),
        interpret=interpret,
    )(lens, q4, kq2, ks2, vq2, vs2)
    return (
        out.reshape(b, hkv, 1, g, d).transpose(0, 2, 1, 3, 4).reshape(b, 1, h, d)
    )


def flash_decode_attention(
    q: jnp.ndarray,
    k_cache: jnp.ndarray,
    v_cache: jnp.ndarray,
    valid_len: jnp.ndarray,
    interpret: bool | None = None,
) -> jnp.ndarray:
    """One-token decode attention with ragged valid lengths.

    q: [B, 1, H, D]; k_cache/v_cache: [B, max_len, Hkv, D];
    valid_len: [B] int32. Returns [B, 1, H, D] in q's dtype.
    """
    b, _, h, d = q.shape
    s = k_cache.shape[1]
    hkv = k_cache.shape[2]
    g = h // hkv
    if interpret is None:
        interpret = _interpret_default()
    scale = d**-0.5

    q4 = q.reshape(b, 1, hkv, g, d).transpose(0, 2, 1, 3, 4).reshape(
        b * hkv, 1, g, d
    )
    kt = k_cache.transpose(0, 2, 1, 3).reshape(b * hkv, s, d)
    vt = v_cache.transpose(0, 2, 1, 3).reshape(b * hkv, s, d)
    lens = jnp.repeat(valid_len.astype(jnp.int32), hkv)  # [B*Hkv]

    out = pl.pallas_call(
        functools.partial(_decode_kernel, scale=scale),
        out_shape=jax.ShapeDtypeStruct((b * hkv, 1, g, d), q.dtype),
        grid=(b * hkv,),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(
                (1, 1, g, d), lambda bh: (bh, 0, 0, 0), memory_space=pltpu.VMEM
            ),
            pl.BlockSpec(
                (1, s, d), lambda bh: (bh, 0, 0), memory_space=pltpu.VMEM
            ),
            pl.BlockSpec(
                (1, s, d), lambda bh: (bh, 0, 0), memory_space=pltpu.VMEM
            ),
        ],
        out_specs=pl.BlockSpec(
            (1, 1, g, d), lambda bh: (bh, 0, 0, 0), memory_space=pltpu.VMEM
        ),
        interpret=interpret,
    )(lens, q4, kt, vt)
    return (
        out.reshape(b, hkv, 1, g, d).transpose(0, 2, 1, 3, 4).reshape(b, 1, h, d)
    )


def flash_decode_attention_q8_stacked(
    q: jnp.ndarray,
    k_q: jnp.ndarray,
    k_scale: jnp.ndarray,
    v_q: jnp.ndarray,
    v_scale: jnp.ndarray,
    valid_len: jnp.ndarray,
    layer: jnp.ndarray,
    interpret: bool | None = None,
) -> jnp.ndarray:
    """Decode attention reading ONE layer of the stacked int8 cache.

    q: [B, 1, H, D]; k_q/v_q: [L, B, Hkv, S, D] int8 (the WHOLE stacked
    QuantKVCache buffer); k_scale/v_scale: [L, B, Hkv, S] f32;
    valid_len: [B]; layer: traced scalar.

    Inside the layer scan a sliced cache layer must be materialized
    before it can feed ``flash_decode_attention_q8`` (Pallas operands
    are whole buffers) — XLA copies ~2 x B*Hkv*S*D bytes per layer per
    step. Here the stack itself is the operand and the layer index rides
    scalar prefetch into the index_maps, so each row's slab DMAs
    straight from the resident cache. Same arithmetic as the row
    program (:func:`_decode_q8_row_kernel`). Falls back to the sliced
    kernel when the row block exceeds the VMEM budget.
    """
    b, _, h, d = q.shape
    hkv, s = k_q.shape[2], k_q.shape[3]
    g = h // hkv
    if interpret is None:
        interpret = _interpret_default()
    if 2 * hkv * s * d > _ROW_KERNEL_MAX_KV_BYTES:
        idx = layer
        return flash_decode_attention_q8(
            q,
            jax.lax.dynamic_index_in_dim(k_q, idx, 0, keepdims=False),
            jax.lax.dynamic_index_in_dim(k_scale, idx, 0, keepdims=False),
            jax.lax.dynamic_index_in_dim(v_q, idx, 0, keepdims=False),
            jax.lax.dynamic_index_in_dim(v_scale, idx, 0, keepdims=False),
            valid_len,
            interpret=interpret,
        )
    scale = d**-0.5

    q4 = q.reshape(b, 1, hkv, g, d).transpose(0, 2, 1, 3, 4).reshape(
        b, hkv, g, d
    )
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,  # layer index, per-row valid lengths
        grid=(b,),
        in_specs=[
            pl.BlockSpec((1, hkv, g, d), lambda i, l, lens: (i, 0, 0, 0)),
            pl.BlockSpec(
                (1, 1, hkv, s, d), lambda i, l, lens: (l[0], i, 0, 0, 0)
            ),
            pl.BlockSpec((1, 1, hkv, s), lambda i, l, lens: (l[0], i, 0, 0)),
            pl.BlockSpec(
                (1, 1, hkv, s, d), lambda i, l, lens: (l[0], i, 0, 0, 0)
            ),
            pl.BlockSpec((1, 1, hkv, s), lambda i, l, lens: (l[0], i, 0, 0)),
        ],
        out_specs=pl.BlockSpec(
            (1, hkv, g, d), lambda i, l, lens: (i, 0, 0, 0)
        ),
    )
    out = pl.pallas_call(
        functools.partial(_decode_q8_stacked_kernel, scale=scale),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, hkv, g, d), q.dtype),
        interpret=interpret,
    )(
        jnp.atleast_1d(layer).astype(jnp.int32),
        valid_len.astype(jnp.int32),
        q4,
        k_q,
        k_scale,
        v_q,
        v_scale,
    )
    return (
        out.reshape(b, hkv, 1, g, d)
        .transpose(0, 2, 1, 3, 4)
        .reshape(b, 1, h, d)
    )


def _decode_q8_stacked_kernel(
    l_ref, len_ref, q_ref, kq_ref, ks_ref, vq_ref, vs_ref, o_ref, *,
    scale: float,
):
    """One batch-row program against the stacked cache, all kv heads.

    l_ref: [1] layer (consumed by index_maps); len_ref: [B] valid
    lengths; q_ref: [1, Hkv, G, D]; kq_ref/vq_ref: [1, 1, Hkv, S, D]
    int8; ks_ref/vs_ref: [1, 1, Hkv, S] f32; o_ref: [1, Hkv, G, D].
    Arithmetic is identical to :func:`_decode_q8_row_kernel`.
    """
    hkv = q_ref.shape[1]
    s = kq_ref.shape[3]
    valid = len_ref[pl.program_id(0)]
    slot = jax.lax.broadcasted_iota(jnp.int32, (1, s), 1)
    mask = slot < valid
    for head in range(hkv):  # static unroll over kv heads
        out = _q8_attend(
            q_ref[0, head],
            kq_ref[0, 0, head],
            ks_ref[0, 0, head][None, :],
            vq_ref[0, 0, head],
            vs_ref[0, 0, head][None, :],
            mask,
            scale,
        )
        o_ref[0, head] = out.astype(o_ref.dtype)


# ---------------------------------------------------------------------------
# Ragged paged attention: ONE program for mixed decode + prefill-chunk rows
# ---------------------------------------------------------------------------
#
# The serving hot loop used to run a zoo of per-shape kernels — plain
# paged decode, grouped shared-prefix decode (two pallas_calls + a host
# merge), dense/int8 shared-prefix pairs — each with its own
# engage/fallback matrix entry (sliding window, stacked cache), and
# chunked prefill as a SEPARATE device program serializing against
# decode. The kernel below replaces the family with one program in the
# style of TPU Ragged Paged Attention (PAPERS.md): every row carries
# per-row (length, suffix-start, group-id) metadata via scalar
# prefetch, and row KIND is a grid-position case of the same body:
#
#   programs [0, B)          decode rows — one query token each, pages
#                            walked through the row's scalar-prefetched
#                            table, sliding window as extra masking;
#   program  B (optional)    ONE prefill-chunk row — C query tokens with
#                            the ragged-causal rule (query i at absolute
#                            position start+i sees slots <= start+i),
#                            walked through the chunk's own host table;
#   programs [B+nc, +Gm)     shared-prefix groups — ALL decode queries
#                            stacked against one read of the group's
#                            shared page run (members masked in),
#                            folding into a separate accumulator.
#
# Every class folds pages with the same :func:`_online_fold`; row
# partials come out per row, the group phase comes out once, and the
# two merge EXACTLY on the host via flash-decoding log-sum-exp
# (:func:`~llm_consensus_tpu.ops.attention.merge_decode_partials`) —
# bit-for-bit the arithmetic of the two-phase kernels this replaces.
# Pages outside a row's live range (before the suffix start, past the
# fill, or wholly before the sliding window) are sentinel-remapped to
# page 0 in the index map, so consecutive dead grid steps request the
# SAME block and their DMAs collapse.
#
# Three static layouts share the body (there is one kernel, not three):
# the serving pool [n_pages, page, Hkv, D]; the dense int8 head-major
# cache [B, Hkv, S, D] (+ scales), viewed as identity-tabled pages; and
# the STACKED int8 cache [L, B, Hkv, S, D] with the layer index riding
# scalar prefetch into the index maps. The dense bf16 cache needs no
# layout of its own — [B, S, Hkv, D] reshapes into pool pages for free.
# The XLA reference (ops.attention.ragged_paged_attention_reference) is
# the parity oracle and the non-Pallas path.


def _sp_block(s: int, cap: int = 128) -> int:
    """Largest divisor of ``s`` <= cap — the S-axis page width the
    DENSE-cache wrappers use to view a contiguous cache as pool pages.

    The cap trades DMA size against skip granularity: the suffix pass
    can only skip whole blocks, so a prefix shorter than one block
    saves nothing there while the group phase still pays one extra
    read of the prefix region — a bounded overhead of < blk slots per
    row plus one prefix read, flipping to a win as soon as the prefix
    spans a block (the canonical fan-out prompt buckets are >= 128).
    128 keeps the blocks at lane width; the paged variant's unit is
    the pool page and needs none of this.
    """
    blk = min(cap, s)
    while s % blk:
        blk -= 1
    return blk


def _online_fold(m_ref, l_ref, acc_ref, idx, scores, v, v_row_scale=None):
    """Fold one score block into running (m, l, acc) softmax state.

    ``idx`` selects the scratch slice (slice or int); scores [R, blk]
    fp32 (already masked to -inf outside the live range); v [blk, D].
    ``v_row_scale`` [1, blk]: per-slot dequant scale folded into the
    VALUE product only (the l denominator stays the true softmax sum) —
    the same linear-dequant trick as :func:`_q8_attend`. Every program
    class of the ragged kernel folds through this one function.
    """
    m_prev = m_ref[idx]
    m_new = jnp.maximum(m_prev, jnp.max(scores, axis=-1, keepdims=True))
    m_safe = jnp.where(m_new <= _NEG_INF / 2, 0.0, m_new)
    p = jnp.exp(scores - m_safe)
    alpha = jnp.where(m_prev <= _NEG_INF / 2, 0.0, jnp.exp(m_prev - m_safe))
    l_ref[idx] = l_ref[idx] * alpha + jnp.sum(p, axis=-1, keepdims=True)
    pv = jax.lax.dot_general(
        p if v_row_scale is None else p * v_row_scale,
        v.astype(jnp.float32),
        dimension_numbers=(((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    acc_ref[idx] = acc_ref[idx] * alpha + pv
    m_ref[idx] = m_new


def _ragged_kernel(
    *refs,
    scale: float,
    b: int,
    hkv: int,
    g: int,
    d: int,
    nc: int,
    cq: int,
    nq: int,
    gm: int,
    pg: int,
    p_per: int,
    window: int,
    quant: bool,
    stacked: bool,
):
    """One (program-class row, page) step of the ragged kernel.

    ``nq`` (static, default 1): queries per DECODE row. > 1 is the
    speculative-verify lane (PR 9): row b carries its previous token
    plus k draft tokens at positions ``kvlen[b] - nq + i``, masked by
    the same ragged-causal rule as the chunk lane — a verify row IS a
    chunk row over the row's own table, which is why the one kernel
    body serves both.

    ``refs`` is parsed positionally by the same static layout the
    wrapper builds: scalar prefetch ([layer?], tbl, kvlen, sstart,
    [rep, gend]), VMEM inputs ([gid, kvlen_v?], q_dec, [q_chunk?],
    [q_all?], K(+scales), V(+scales)), outputs (decode partials,
    [chunk partials?], [group partials?]), then scratch. Row scratch is
    re-initialized at every row's first page; the group accumulator
    persists across all group programs (they run last) and is written
    once at the very last program.
    """
    i = 0
    if stacked:
        i += 1  # layer index: consumed by the index maps only
    tbl_ref, kvlen_ref, sstart_ref = refs[i : i + 3]
    i += 3
    del tbl_ref  # pages are resolved by the index maps
    if gm:
        rep_ref, gend_ref = refs[i : i + 2]
        i += 2
        del rep_ref
        gid_ref, kvv_ref = refs[i : i + 2]
        i += 2
    q_dec_ref = refs[i]
    i += 1
    if nc:
        q_chunk_ref = refs[i]
        i += 1
    if gm:
        q_all_ref = refs[i]
        i += 1
    if quant:
        kq_ref, ks_ref, vq_ref, vs_ref = refs[i : i + 4]
        i += 4
    else:
        k_ref, v_ref = refs[i : i + 2]
        i += 2
    md_ref, ld_ref, od_ref = refs[i : i + 3]
    i += 3
    if nc:
        mc_ref, lc_ref, oc_ref = refs[i : i + 3]
        i += 3
    if gm:
        mg_ref, lg_ref, og_ref = refs[i : i + 3]
        i += 3
    m_s, l_s, acc_s = refs[i : i + 3]
    i += 3
    if gm:
        m2_s, l2_s, acc2_s = refs[i : i + 3]

    s = pl.program_id(0)
    j = pl.program_id(1)
    R = b + nc
    total = R + gm

    def _k_head(head):
        """This page's K slab [pg, D] (+ [1, pg] dequant row or None)."""
        if quant:
            kq = kq_ref[0, 0, head] if stacked else kq_ref[0, head]
            ks = (ks_ref[0, 0, head] if stacked else ks_ref[0, head])[None, :]
            return kq, ks
        return k_ref[0, :, head, :], None

    def _v_head(head):
        if quant:
            vq = vq_ref[0, 0, head] if stacked else vq_ref[0, head]
            vs = (vs_ref[0, 0, head] if stacked else vs_ref[0, head])[None, :]
            return vq, vs
        return v_ref[0, :, head, :], None

    def _fold(idx, q, head, mask, mr, lr, ar):
        k, ks = _k_head(head)
        v, vs = _v_head(head)
        scores = jax.lax.dot_general(
            q.astype(jnp.float32),
            k.astype(jnp.float32),
            dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * (scale if ks is None else ks * scale)
        scores = jnp.where(mask, scores, _NEG_INF)
        _online_fold(mr, lr, ar, idx, scores, v, v_row_scale=vs)

    # Row scratch: re-initialized per row (its page walk is contiguous
    # in the grid), shared by decode and chunk programs.
    @pl.when(j == 0)
    def _init_row():
        rows = m_s.shape[0]
        m_s[...] = jnp.full((rows, 1), _NEG_INF, jnp.float32)
        l_s[...] = jnp.zeros((rows, 1), jnp.float32)
        acc_s[...] = jnp.zeros((rows, d), jnp.float32)

    @pl.when(s < b)
    def _decode_row():
        valid = kvlen_ref[s]
        qbase = valid - nq  # first query's absolute position
        lo = sstart_ref[s]
        lo_all = lo
        if window > 0:
            # Sliding window: query i sits at qbase + i and sees slots
            # (qbase + i - window, qbase + i] — the union of the nq
            # windows starts at the FIRST query's edge (nq == 1
            # reduces to ops.attention.decode_attention's rule).
            lo_all = jnp.maximum(lo, qbase + 1 - window)
        live = ((j + 1) * pg > lo_all) & (j * pg < valid)

        @pl.when(live)
        def _fold_page():
            slot = j * pg + jax.lax.broadcasted_iota(
                jnp.int32, (nq, 1, pg), 2
            )
            qpos = qbase + jax.lax.broadcasted_iota(
                jnp.int32, (nq, 1, pg), 0
            )
            # Ragged causal: query i sees slots <= its own position —
            # chunk_decode_attention's rule; nq == 1 is the classic
            # slot < valid decode mask.
            mask3 = (slot <= qpos) & (slot >= lo)
            if window > 0:
                mask3 &= slot > qpos - window
            mask = jnp.broadcast_to(mask3, (nq, g, pg)).reshape(
                nq * g, pg
            )
            for head in range(hkv):  # static unroll over kv heads
                _fold(
                    slice(head * nq * g, (head + 1) * nq * g),
                    q_dec_ref[0, head],
                    head,
                    mask,
                    m_s,
                    l_s,
                    acc_s,
                )

    if nc:

        @pl.when(s == b)
        def _chunk_row():
            valid = kvlen_ref[b]  # chunk start + cq
            qbase = valid - cq
            lo = sstart_ref[b]
            lo_all = lo
            if window > 0:
                # The union of the cq queries' windows starts at the
                # FIRST query's window edge.
                lo_all = jnp.maximum(lo, qbase + 1 - window)
            live = ((j + 1) * pg > lo_all) & (j * pg < valid)

            @pl.when(live)
            def _fold_page():
                slot = j * pg + jax.lax.broadcasted_iota(
                    jnp.int32, (cq, 1, pg), 2
                )
                qpos = qbase + jax.lax.broadcasted_iota(
                    jnp.int32, (cq, 1, pg), 0
                )
                # Ragged causal: chunk query i (absolute position
                # qbase + i) sees slots <= its own — the cache so far
                # plus the chunk itself, chunk_decode_attention's rule.
                mask3 = (slot <= qpos) & (slot >= lo)
                if window > 0:
                    mask3 &= slot > qpos - window
                mask = jnp.broadcast_to(mask3, (cq, g, pg)).reshape(
                    cq * g, pg
                )
                for head in range(hkv):  # static unroll over kv heads
                    _fold(
                        slice(head * cq * g, (head + 1) * cq * g),
                        q_chunk_ref[0, head],
                        head,
                        mask,
                        m_s,
                        l_s,
                        acc_s,
                    )

    if gm:
        # Group programs run LAST; their accumulator spans all of them.
        @pl.when((s == R) & (j == 0))
        def _init_group():
            m2_s[...] = jnp.full((hkv, b * nq * g, 1), _NEG_INF, jnp.float32)
            l2_s[...] = jnp.zeros((hkv, b * nq * g, 1), jnp.float32)
            acc2_s[...] = jnp.zeros((hkv, b * nq * g, d), jnp.float32)

        @pl.when(s >= R)
        def _group():
            gi = s - R
            ge = gend_ref[gi]

            @pl.when(j * pg < ge)
            def _fold_page():
                member = gid_ref[...] == gi  # [B, 1]
                mrow = jnp.broadcast_to(
                    member[:, None], (b, nq, g)
                ).reshape(b * nq * g, 1)
                slot = j * pg + jax.lax.broadcasted_iota(
                    jnp.int32, (1, pg), 1
                )
                # Every decode query sits past the shared run's end
                # (shared pages cover prompt prefixes only), so the
                # causal limit never binds here — mask is membership +
                # run extent, for all nq queries alike.
                mask = mrow & (slot < ge)
                if window > 0:
                    # Per-member, per-query window edge: members of one
                    # group can sit at different fills, and the nq
                    # verify queries of one member at different
                    # positions.
                    qoff = jax.lax.broadcasted_iota(
                        jnp.int32, (b, nq, g), 1
                    )
                    kvv = jnp.broadcast_to(
                        kvv_ref[...][:, :, None], (b, nq, g)
                    )
                    wlo = (kvv - nq + qoff + 1 - window).reshape(
                        b * nq * g, 1
                    )
                    mask &= slot >= wlo
                for head in range(hkv):  # static unroll over kv heads
                    _fold(
                        head, q_all_ref[head], head, mask, m2_s, l2_s, acc2_s
                    )

    # -- writes ---------------------------------------------------------

    @pl.when((s < b) & (j == p_per - 1))
    def _write_dec():
        m = m_s[0 : hkv * nq * g]
        l = l_s[0 : hkv * nq * g]
        md_ref[0] = m
        ld_ref[0] = l
        od_ref[0] = (
            acc_s[0 : hkv * nq * g] / jnp.maximum(l, 1e-30)
        ).reshape(hkv, nq * g, d)

    if nc:

        @pl.when((s == b) & (j == p_per - 1))
        def _write_chunk():
            # Slice, never [...]: the scratch is sized for the WIDER of
            # the chunk lane (cq) and the verify lane (nq) — with nq >
            # cq the chunk's rows are the leading hkv * cq * g.
            m = m_s[0 : hkv * cq * g]
            l = l_s[0 : hkv * cq * g]
            mc_ref[0] = m
            lc_ref[0] = l
            oc_ref[0] = (
                acc_s[0 : hkv * cq * g] / jnp.maximum(l, 1e-30)
            ).reshape(hkv, cq * g, d)

    if gm:

        @pl.when((s == total - 1) & (j == p_per - 1))
        def _write_group():
            l = l2_s[...]
            mg_ref[...] = m2_s[...]
            lg_ref[...] = l
            og_ref[...] = acc2_s[...] / jnp.maximum(l, 1e-30)


def _ragged_attention(
    q_dec,
    k_kv,
    v_kv,
    page_table,
    kv_len,
    suffix_start,
    *,
    pg: int,
    q_chunk=None,
    gid=None,
    rep=None,
    gend=None,
    window: int = 0,
    k_scale=None,
    v_scale=None,
    layer=None,
    interpret: bool | None = None,
):
    """Assemble and launch ONE ragged program; merge group partials.

    q_dec: [B, H, D] (one query per decode row) or [B, NQ, H, D]
    (NQ-query verify rows, PR 9 — queries at kv_len - NQ + i, the
    chunk lane's ragged-causal rule per row); page_table: [B + nc, P]
    (row B is the chunk's table when ``q_chunk`` [C, H, D] rides
    along); kv_len/suffix_start: [B + nc]. K/V layout is static: the
    pool [n_pages, pg, Hkv, D] (``k_scale`` None), the int8 head-major
    cache [B, Hkv, S, D] with [B, Hkv, S] scales, or the stacked int8
    cache [L, B, Hkv, S, D] (``layer`` a traced index) — the dense
    layouts are addressed as identity-tabled virtual pages of width
    ``pg``. Returns out_dec shaped like q_dec (and out_chunk [C, H, D]
    when ``q_chunk``) in q's dtype.
    """
    squeeze_nq = q_dec.ndim == 3
    if squeeze_nq:
        b, h, d = q_dec.shape
        nq = 1
    else:
        b, nq, h, d = q_dec.shape
    quant = k_scale is not None
    stacked = layer is not None
    if quant:
        s_len = k_kv.shape[-2]
        hkv = k_kv.shape[-3]
        npp = s_len // pg
        if s_len % pg:
            raise ValueError(f"cache len {s_len} not a multiple of {pg}")
    else:
        hkv = k_kv.shape[2]
        npp = 0  # unused
    g = h // hkv
    nc = 0 if q_chunk is None else 1
    cq = q_chunk.shape[0] if nc else 1
    gm = 0 if gid is None else int(rep.shape[0])
    p_per = page_table.shape[1]
    R = b + nc
    total = R + gm
    if interpret is None:
        interpret = _interpret_default()
    scale = d**-0.5

    kvlen = kv_len.astype(jnp.int32)
    sstart = suffix_start.astype(jnp.int32)
    pf = []
    if stacked:
        pf.append(jnp.atleast_1d(layer).astype(jnp.int32))
    pf += [page_table.reshape(-1).astype(jnp.int32), kvlen, sstart]
    if gm:
        pf += [rep.astype(jnp.int32), gend.astype(jnp.int32)]
    i_tbl = 1 if stacked else 0

    def _page_of(s, j, pf):
        """Pool page for program (s, j), dead steps sentinel-remapped
        to page 0 so their DMAs collapse."""
        tbl, kvl, sst = pf[i_tbl], pf[i_tbl + 1], pf[i_tbl + 2]
        row = jnp.where(s < R, s, 0)
        lo = sst[row]
        if window > 0:
            nq_row = jnp.where(row < b, nq, cq) if nc else nq
            lo = jnp.maximum(lo, kvl[row] - (nq_row - 1) - window)
        live = ((j + 1) * pg > lo) & (j * pg < kvl[row])
        page = jnp.where(live, tbl[row * p_per + j], 0)
        if gm:
            rep_a, gend_a = pf[i_tbl + 3], pf[i_tbl + 4]
            gi = jnp.clip(s - R, 0, gm - 1)
            g_page = jnp.where(
                j * pg < gend_a[gi], tbl[rep_a[gi] * p_per + j], 0
            )
            page = jnp.where(s < R, page, g_page)
        return page

    def _kv_map(s, j, *pf):
        page = _page_of(s, j, pf)
        if stacked:
            return (pf[0][0], page // npp, 0, page % npp, 0)
        if quant:
            return (page // npp, 0, page % npp, 0)
        return (page, 0, 0, 0)

    def _scale_map(s, j, *pf):
        page = _page_of(s, j, pf)
        if stacked:
            return (pf[0][0], page // npp, 0, page % npp)
        return (page // npp, 0, page % npp)

    inputs = []
    in_specs = []
    if gm:
        inputs.append(gid.astype(jnp.int32).reshape(b, 1))
        in_specs.append(pl.BlockSpec((b, 1), lambda s, j, *pf: (0, 0)))
        inputs.append(kvlen[:b].reshape(b, 1))
        in_specs.append(pl.BlockSpec((b, 1), lambda s, j, *pf: (0, 0)))
    # Per-row q block rows are (nq, g)-ordered — the order the decode
    # fold's mask reshape and the write-out both assume.
    q4 = q_dec.reshape(b, nq, hkv, g, d)
    inputs.append(q4.transpose(0, 2, 1, 3, 4).reshape(b, hkv, nq * g, d))
    in_specs.append(
        pl.BlockSpec(
            (1, hkv, nq * g, d),
            lambda s, j, *pf: (jnp.where(s < b, s, 0), 0, 0, 0),
        )
    )
    if nc:
        inputs.append(
            q_chunk.reshape(cq, hkv, g, d)
            .transpose(1, 0, 2, 3)
            .reshape(1, hkv, cq * g, d)
        )
        in_specs.append(
            pl.BlockSpec(
                (1, hkv, cq * g, d), lambda s, j, *pf: (0, 0, 0, 0)
            )
        )
    if gm:
        inputs.append(
            q4.transpose(2, 0, 1, 3, 4).reshape(hkv, b * nq * g, d)
        )
        in_specs.append(
            pl.BlockSpec(
                (hkv, b * nq * g, d), lambda s, j, *pf: (0, 0, 0)
            )
        )
    if quant:
        if stacked:
            kv_spec = pl.BlockSpec((1, 1, hkv, pg, d), _kv_map)
            sc_spec = pl.BlockSpec((1, 1, hkv, pg), _scale_map)
        else:
            kv_spec = pl.BlockSpec((1, hkv, pg, d), _kv_map)
            sc_spec = pl.BlockSpec((1, hkv, pg), _scale_map)
        inputs += [k_kv, k_scale, v_kv, v_scale]
        in_specs += [kv_spec, sc_spec, kv_spec, sc_spec]
    else:
        kv_spec = pl.BlockSpec((1, pg, hkv, d), _kv_map)
        inputs += [k_kv, v_kv]
        in_specs += [kv_spec, kv_spec]

    # Outputs. Row partials are blocked per row with one TRASH block
    # (index b / index nc) absorbing the write-backs of programs that
    # own a different class's output — an output block revisited after
    # its owner moved on would otherwise land stale buffer contents.
    def _dec_out_map3(s, j, *pf):
        return (jnp.where(s < b, s, b), 0, 0)

    def _dec_out_map4(s, j, *pf):
        return (jnp.where(s < b, s, b), 0, 0, 0)

    out_shapes = [
        jax.ShapeDtypeStruct((b + 1, hkv * nq * g, 1), jnp.float32),
        jax.ShapeDtypeStruct((b + 1, hkv * nq * g, 1), jnp.float32),
        jax.ShapeDtypeStruct((b + 1, hkv, nq * g, d), jnp.float32),
    ]
    out_specs = [
        pl.BlockSpec((1, hkv * nq * g, 1), _dec_out_map3),
        pl.BlockSpec((1, hkv * nq * g, 1), _dec_out_map3),
        pl.BlockSpec((1, hkv, nq * g, d), _dec_out_map4),
    ]
    if nc:

        def _chunk_out_map3(s, j, *pf):
            return (jnp.where(s == b, 0, 1), 0, 0)

        def _chunk_out_map4(s, j, *pf):
            return (jnp.where(s == b, 0, 1), 0, 0, 0)

        out_shapes += [
            jax.ShapeDtypeStruct((2, hkv * cq * g, 1), jnp.float32),
            jax.ShapeDtypeStruct((2, hkv * cq * g, 1), jnp.float32),
            jax.ShapeDtypeStruct((2, hkv, cq * g, d), jnp.float32),
        ]
        out_specs += [
            pl.BlockSpec((1, hkv * cq * g, 1), _chunk_out_map3),
            pl.BlockSpec((1, hkv * cq * g, 1), _chunk_out_map3),
            pl.BlockSpec((1, hkv, cq * g, d), _chunk_out_map4),
        ]
    if gm:
        out_shapes += [
            jax.ShapeDtypeStruct((hkv, b * nq * g, 1), jnp.float32),
            jax.ShapeDtypeStruct((hkv, b * nq * g, 1), jnp.float32),
            jax.ShapeDtypeStruct((hkv, b * nq * g, d), jnp.float32),
        ]
        out_specs += [
            pl.BlockSpec((hkv, b * nq * g, 1), lambda s, j, *pf: (0, 0, 0)),
            pl.BlockSpec((hkv, b * nq * g, 1), lambda s, j, *pf: (0, 0, 0)),
            pl.BlockSpec((hkv, b * nq * g, d), lambda s, j, *pf: (0, 0, 0)),
        ]

    qs = max(nq, cq if nc else 1)
    scratch = [
        pltpu.VMEM((hkv * qs * g, 1), jnp.float32),
        pltpu.VMEM((hkv * qs * g, 1), jnp.float32),
        pltpu.VMEM((hkv * qs * g, d), jnp.float32),
    ]
    if gm:
        scratch += [
            pltpu.VMEM((hkv, b * nq * g, 1), jnp.float32),
            pltpu.VMEM((hkv, b * nq * g, 1), jnp.float32),
            pltpu.VMEM((hkv, b * nq * g, d), jnp.float32),
        ]

    outs = pl.pallas_call(
        functools.partial(
            _ragged_kernel,
            scale=scale,
            b=b,
            hkv=hkv,
            g=g,
            d=d,
            nc=nc,
            cq=cq,
            nq=nq,
            gm=gm,
            pg=pg,
            p_per=p_per,
            window=window,
            quant=quant,
            stacked=stacked,
        ),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=len(pf),
            grid=(total, p_per),
            in_specs=in_specs,
            out_specs=out_specs,
            scratch_shapes=scratch,
        ),
        out_shape=tuple(out_shapes),
        interpret=interpret,
    )(*pf, *inputs)

    md, ld, od = outs[0][:b], outs[1][:b], outs[2][:b]
    od5 = od.reshape(b, hkv, nq, g, d)
    if gm:
        from llm_consensus_tpu.ops.attention import merge_decode_partials

        mg, lg, og = outs[-3], outs[-2], outs[-1]
        m1r = mg.reshape(hkv, b, nq, g, 1).transpose(1, 0, 2, 3, 4)
        l1r = lg.reshape(hkv, b, nq, g, 1).transpose(1, 0, 2, 3, 4)
        o1r = og.reshape(hkv, b, nq, g, d).transpose(1, 0, 2, 3, 4)
        m2r = md.reshape(b, hkv, nq, g, 1)
        l2r = ld.reshape(b, hkv, nq, g, 1)
        out5 = merge_decode_partials(m1r, l1r, o1r, m2r, l2r, od5)
    else:
        out5 = od5
    out_dec = (
        out5.transpose(0, 2, 1, 3, 4)
        .reshape(b, nq, h, d)
        .astype(q_dec.dtype)
    )
    if squeeze_nq:
        out_dec = out_dec[:, 0]
    if not nc:
        return out_dec
    oc = outs[5][0]  # [Hkv, cq*G, D]
    out_chunk = (
        oc.reshape(hkv, cq, g, d)
        .transpose(1, 0, 2, 3)
        .reshape(cq, h, d)
        .astype(q_dec.dtype)
    )
    return out_dec, out_chunk


def ragged_paged_attention(
    q: jnp.ndarray,
    k_pool: jnp.ndarray,
    v_pool: jnp.ndarray,
    page_table: jnp.ndarray,
    valid_len: jnp.ndarray,
    *,
    q_chunk: jnp.ndarray | None = None,
    chunk_table: jnp.ndarray | None = None,
    chunk_start=None,
    groups: tuple | None = None,
    window: int = 0,
    interpret: bool | None = None,
):
    """Mixed prefill+decode attention over the page pool — ONE program.

    q: [B, H, D] decode-row queries, or [B, NQ, H, D] NQ-token
    speculative VERIFY rows (PR 9): row b's queries sit at absolute
    positions ``valid_len[b] - NQ + i`` (``valid_len`` stays "tokens
    readable" — the NQ new tokens' K/V already written), masked by the
    chunk lane's ragged-causal rule per row. k_pool/v_pool: [n_pages,
    page, Hkv, D]; page_table: [B, P]; valid_len: [B] tokens readable
    per decode row.

    ``q_chunk`` [C, H, D] adds ONE prefill-chunk row: C queries at
    absolute positions ``chunk_start + i``, walking ``chunk_table``
    [P] (the chunk's K/V must already be scattered through it), with
    the ragged-causal rule of
    :func:`~llm_consensus_tpu.ops.attention.chunk_decode_attention`.
    ``groups`` = (group_id [B] (-1 ungrouped), group_rep [Gm],
    group_end [Gm] tokens, shared_start [B]) — decode rows sharing a
    prefix page run read it ONCE per group (all member queries
    stacked), each row's own walk starting at ``shared_start``; the
    partials merge exactly via flash-decoding LSE. ``window`` > 0
    applies sliding-window masking to every row kind. Returns
    out_dec [B, H, D] (and out_chunk [C, H, D] when ``q_chunk``).
    """
    b = q.shape[0]
    pg = k_pool.shape[1]
    kvlen = valid_len.astype(jnp.int32)
    if groups is not None:
        gid, rep, gend, sstart = groups
        sstart = sstart.astype(jnp.int32)
    else:
        gid = rep = gend = None
        sstart = jnp.zeros((b,), jnp.int32)
    tbl = page_table
    if q_chunk is not None:
        cq = q_chunk.shape[0]
        tbl = jnp.concatenate(
            [page_table.astype(jnp.int32), chunk_table[None].astype(jnp.int32)]
        )
        kvlen = jnp.concatenate(
            [kvlen, jnp.asarray(chunk_start, jnp.int32).reshape(1) + cq]
        )
        sstart = jnp.concatenate([sstart, jnp.zeros((1,), jnp.int32)])
    return _ragged_attention(
        q,
        k_pool,
        v_pool,
        tbl,
        kvlen,
        sstart,
        pg=pg,
        q_chunk=q_chunk,
        gid=gid,
        rep=rep,
        gend=gend,
        window=window,
        interpret=interpret,
    )


def ragged_paged_attention_sharded(
    mesh,
    q: jnp.ndarray,
    k_pool: jnp.ndarray,
    v_pool: jnp.ndarray,
    page_table: jnp.ndarray,
    valid_len: jnp.ndarray,
    *,
    q_chunk: jnp.ndarray | None = None,
    chunk_table: jnp.ndarray | None = None,
    chunk_start=None,
    groups: tuple | None = None,
    window: int = 0,
    interpret: bool | None = None,
):
    """:func:`ragged_paged_attention` under ``shard_map`` on a dp×mp
    mesh (PR 13) — the serving kernel's mesh-native lowering.

    Partitioning: kv heads over ``model`` (each shard's kernel runs the
    same body over Hkv/mp heads — GQA keeps K/V read once per local
    head program); decode rows, their page tables, and the page pool
    over ``data``. The batcher's slot→shard page affinity is the
    correctness invariant: every row's table references only pages of
    its own data shard, so per-shard the GLOBAL page ids rebase to
    local pool indices (``id - shard * local_pages``, clamped — NULL
    and foreign ids appear only in dead/masked steps, where the clamp
    lands on a harmless masked read, exactly like the kernel's own
    page-0 sentinel remap). Shared-prefix groups live entirely on one
    shard for the same reason (one prefix registry per shard), so the
    group phase rides along by rebasing ``group_rep``: a shard that
    holds no members of group g folds an all-masked read (l = 0) that
    the LSE merge ignores. The prefill-chunk lane's pages live on its
    admitting slot's shard; every shard folds the lane against its
    local pool and the owner's result is selected with one psum over
    ``data`` (non-owners contribute exact zeros).

    Semantics are identical to the single-device kernel — this wrapper
    only decides which shard reads which bytes.
    """
    from jax.sharding import PartitionSpec as P

    from llm_consensus_tpu.parallel.compat import shard_map

    has_chunk = q_chunk is not None
    has_groups = groups is not None
    q_spec = (
        P("data", None, "model", None)
        if q.ndim == 4
        else P("data", "model", None)
    )
    pool_spec = P("data", None, "model", None)
    in_specs = [q_spec, pool_spec, pool_spec, P("data", None), P("data")]
    args = [
        q,
        k_pool,
        v_pool,
        page_table.astype(jnp.int32),
        valid_len.astype(jnp.int32),
    ]
    if has_chunk:
        args += [
            q_chunk,
            chunk_table.astype(jnp.int32),
            jnp.asarray(chunk_start, jnp.int32),
        ]
        in_specs += [P(None, "model", None), P(None), P()]
    if has_groups:
        gid, rep, gend, sstart = groups
        args += [
            gid.astype(jnp.int32),
            rep.astype(jnp.int32),
            gend.astype(jnp.int32),
            sstart.astype(jnp.int32),
        ]
        in_specs += [P("data"), P(None), P(None), P("data")]
    out_specs = (q_spec, P(None, "model", None)) if has_chunk else q_spec

    def fn(*a):
        q_l, kp_l, vp_l, tbl_l, val_l = a[:5]
        i = 5
        local_pages = kp_l.shape[0]
        bl = q_l.shape[0]
        didx = jax.lax.axis_index("data")
        poff = didx * local_pages
        tbl = jnp.clip(tbl_l - poff, 0, local_pages - 1)
        qc = ct = cs = None
        if has_chunk:
            qc, ct, cs = a[i : i + 3]
            i += 3
        g_l = None
        if has_groups:
            gid_l, rep_g, gend_g, sst_l = a[i : i + 4]
            g_l = (
                gid_l,
                jnp.clip(rep_g - didx * bl, 0, bl - 1),
                gend_g,
                sst_l,
            )
        if has_chunk:
            out_dec, out_chunk = ragged_paged_attention(
                q_l,
                kp_l,
                vp_l,
                tbl,
                val_l,
                q_chunk=qc,
                chunk_table=jnp.clip(ct - poff, 0, local_pages - 1),
                chunk_start=cs,
                groups=g_l,
                window=window,
                interpret=interpret,
            )
            # Position 0's page identifies the chunk's owner shard (the
            # admitting slot's pool); the other shards folded local
            # garbage under the same masks and are zeroed exactly.
            owner = (ct[0] >= poff) & (ct[0] < poff + local_pages)
            out_chunk = jax.lax.psum(
                jnp.where(owner, out_chunk, jnp.zeros_like(out_chunk)),
                "data",
            )
            return out_dec, out_chunk
        return ragged_paged_attention(
            q_l,
            kp_l,
            vp_l,
            tbl,
            val_l,
            groups=g_l,
            window=window,
            interpret=interpret,
        )

    return shard_map(
        fn, mesh, in_specs=tuple(in_specs), out_specs=out_specs
    )(*args)


# -- thin wrappers: the pre-ragged kernel family ----------------------------
#
# Everything below is signature-compatible with the kernels it replaced
# (PR 3's two-phase family and the plain paged row kernel) but runs the
# ONE ragged kernel body above — same arithmetic, one implementation.


def paged_decode_attention(
    q: jnp.ndarray,
    k_pool: jnp.ndarray,
    v_pool: jnp.ndarray,
    page_table: jnp.ndarray,
    valid_len: jnp.ndarray,
    window: int = 0,
    interpret: bool | None = None,
) -> jnp.ndarray:
    """Decode attention THROUGH the page table — no pool gather.

    q: [B, H, D]; k_pool/v_pool: [n_pages, page, Hkv, D]; page_table:
    [B, P]; valid_len: [B]. The all-decode, ungrouped case of
    :func:`ragged_paged_attention`.
    """
    return ragged_paged_attention(
        q, k_pool, v_pool, page_table, valid_len,
        window=window, interpret=interpret,
    )


def paged_decode_attention_grouped(
    q: jnp.ndarray,
    k_pool: jnp.ndarray,
    v_pool: jnp.ndarray,
    page_table: jnp.ndarray,
    valid_len: jnp.ndarray,
    group_id: jnp.ndarray,
    group_rep: jnp.ndarray,
    group_pages: jnp.ndarray,
    shared_start: jnp.ndarray,
    window: int = 0,
    interpret: bool | None = None,
) -> jnp.ndarray:
    """Group-aware paged decode attention (serving hot path).

    Group metadata as built by
    :class:`~llm_consensus_tpu.models.paged_cache.GroupTracker`:
    group_id [B] (-1 ungrouped), group_rep [Gm] (a member row whose
    table phase 1 walks), group_pages [Gm] (pages in the shared run,
    0 = padding), shared_start [B] (tokens the shared phase covers,
    page-aligned). Output-equal to :func:`paged_decode_attention` —
    the grouped read is a bandwidth optimization, not a semantic one.
    Sliding windows now ride through (``window``); the old fallback is
    gone.
    """
    pg = k_pool.shape[1]
    return ragged_paged_attention(
        q,
        k_pool,
        v_pool,
        page_table,
        valid_len,
        groups=(
            group_id,
            group_rep,
            group_pages.astype(jnp.int32) * pg,
            shared_start,
        ),
        window=window,
        interpret=interpret,
    )


def flash_decode_attention_shared_prefix(
    q: jnp.ndarray,
    k_cache: jnp.ndarray,
    v_cache: jnp.ndarray,
    valid_len: jnp.ndarray,
    prefix_len: jnp.ndarray,
    window: int = 0,
    interpret: bool | None = None,
) -> jnp.ndarray:
    """Shared-prefix decode attention, dense bf16 cache (engine fan-out).

    q: [B, 1, H, D]; k_cache/v_cache: [B, max_len, Hkv, D]; valid_len:
    [B]; prefix_len: traced scalar — every row's slots [0, prefix_len)
    hold identical K/V. The dense cache reshapes (zero-copy) into pool
    pages and the whole batch forms one group of the ragged kernel:
    the prefix region streams once for all rows, each row walks only
    its own suffix blocks. Matches
    :func:`~llm_consensus_tpu.ops.attention.decode_attention_shared_prefix`
    wherever the precondition holds.
    """
    b, _, h, d = q.shape
    s = k_cache.shape[1]
    hkv = k_cache.shape[2]
    blk = _sp_block(s)
    npp = s // blk
    k_pool = k_cache.reshape(b * npp, blk, hkv, d)
    v_pool = v_cache.reshape(b * npp, blk, hkv, d)
    table = jnp.arange(b * npp, dtype=jnp.int32).reshape(b, npp)
    plen = jnp.asarray(prefix_len, jnp.int32)
    out = ragged_paged_attention(
        q[:, 0],
        k_pool,
        v_pool,
        table,
        valid_len,
        groups=(
            jnp.zeros((b,), jnp.int32),
            jnp.zeros((1,), jnp.int32),
            plen.reshape(1),
            jnp.broadcast_to(plen, (b,)),
        ),
        window=window,
        interpret=interpret,
    )
    return out[:, None]


def flash_decode_attention_shared_prefix_q8(
    q: jnp.ndarray,
    k_q: jnp.ndarray,
    k_scale: jnp.ndarray,
    v_q: jnp.ndarray,
    v_scale: jnp.ndarray,
    valid_len: jnp.ndarray,
    prefix_len: jnp.ndarray,
    window: int = 0,
    interpret: bool | None = None,
) -> jnp.ndarray:
    """Shared-prefix decode attention over the int8 head-major cache.

    q: [B, 1, H, D]; k_q/v_q: [B, Hkv, S, D] int8 (QuantKVCache
    layout, addressed in place as identity-tabled virtual pages — no
    transpose, no dequantized materialization); k_scale/v_scale:
    [B, Hkv, S] f32; valid_len: [B]; prefix_len: traced scalar. Same
    one-group ragged program as the bf16 wrapper with the dequant
    scales folded into scores/values in-register.
    """
    b, _, h, d = q.shape
    hkv, s = k_q.shape[1], k_q.shape[2]
    blk = _sp_block(s)
    npp = s // blk
    table = jnp.arange(b * npp, dtype=jnp.int32).reshape(b, npp)
    plen = jnp.asarray(prefix_len, jnp.int32)
    out = _ragged_attention(
        q[:, 0],
        k_q,
        v_q,
        table,
        valid_len.astype(jnp.int32),
        jnp.broadcast_to(plen, (b,)),
        pg=blk,
        gid=jnp.zeros((b,), jnp.int32),
        rep=jnp.zeros((1,), jnp.int32),
        gend=plen.reshape(1),
        window=window,
        k_scale=k_scale,
        v_scale=v_scale,
        interpret=interpret,
    )
    return out[:, None]


def flash_decode_attention_shared_prefix_q8_stacked(
    q: jnp.ndarray,
    k_q: jnp.ndarray,
    k_scale: jnp.ndarray,
    v_q: jnp.ndarray,
    v_scale: jnp.ndarray,
    valid_len: jnp.ndarray,
    prefix_len: jnp.ndarray,
    layer: jnp.ndarray,
    window: int = 0,
    interpret: bool | None = None,
) -> jnp.ndarray:
    """Shared-prefix decode attention reading ONE layer of the stacked
    int8 cache — the case that used to FALL BACK to the ungrouped
    stacked kernel. k_q/v_q: [L, B, Hkv, S, D] int8 (the whole stacked
    buffer); k_scale/v_scale: [L, B, Hkv, S]; ``layer`` a traced index
    riding scalar prefetch into the index maps, exactly like
    :func:`flash_decode_attention_q8_stacked`.
    """
    b, _, h, d = q.shape
    hkv, s = k_q.shape[2], k_q.shape[3]
    blk = _sp_block(s)
    npp = s // blk
    table = jnp.arange(b * npp, dtype=jnp.int32).reshape(b, npp)
    plen = jnp.asarray(prefix_len, jnp.int32)
    out = _ragged_attention(
        q[:, 0],
        k_q,
        v_q,
        table,
        valid_len.astype(jnp.int32),
        jnp.broadcast_to(plen, (b,)),
        pg=blk,
        gid=jnp.zeros((b,), jnp.int32),
        rep=jnp.zeros((1,), jnp.int32),
        gend=plen.reshape(1),
        window=window,
        k_scale=k_scale,
        v_scale=v_scale,
        layer=layer,
        interpret=interpret,
    )
    return out[:, None]
