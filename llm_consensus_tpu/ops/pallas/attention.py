"""Fused attention kernels (Pallas/Mosaic).

Two kernels, mirroring the two jnp reference paths in
:mod:`llm_consensus_tpu.ops.attention`:

- :func:`flash_causal_attention` — prefill/full attention. Grid over
  (batch x kv-head, query blocks); each program holds its (b, kv) K/V
  slab in VMEM, computes a [G*blk_q, S] score tile in fp32 on the MXU,
  applies the causal mask, does the softmax in VMEM, and writes the
  [G*blk_q, D] output — the score matrix never touches HBM.
- :func:`flash_decode_attention` — single-token decode against the KV
  cache with per-sequence ``valid_len`` masking (the ragged-decode op of
  BASELINE.json's north star). Grid over (batch, kv-head).

GQA layout: H = Hkv * G query heads share each kv head; programs are
per-(batch, kv-head) and process all G group heads at once, so K/V are
read exactly once per program (no repeated-KV materialization anywhere).

Tiling: D (head_dim) and S pad to lane width (128); fp32 accumulation via
``preferred_element_type``. On CPU tests, ``interpret=True`` is selected
automatically (same kernels, interpreted).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG_INF = -1e30


def _interpret_default() -> bool:
    return jax.default_backend() != "tpu"


# ---------------------------------------------------------------------------
# Prefill / full causal attention
# ---------------------------------------------------------------------------


def _causal_kernel(q_ref, k_ref, v_ref, o_ref, *, blk_q: int, scale: float):
    """One (b, kv-head, q-block) program.

    q_ref: [1, blk_q, G, D]; k_ref/v_ref: [1, S, D]; o_ref: [1, blk_q, G, D].
    """
    qi = pl.program_id(1)
    _, _, g, d = q_ref.shape
    s = k_ref.shape[1]

    q = q_ref[0].astype(jnp.float32)  # [blk_q, G, D]
    q2 = q.reshape(blk_q * g, d)
    k = k_ref[0]  # [S, D]
    scores = jax.lax.dot_general(
        q2,
        k.astype(jnp.float32),
        dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    ) * scale  # [blk_q*G, S]
    scores = scores.reshape(blk_q, g, s)

    q_pos = qi * blk_q + jax.lax.broadcasted_iota(jnp.int32, (blk_q, 1, 1), 0)
    k_pos = jax.lax.broadcasted_iota(jnp.int32, (1, 1, s), 2)
    scores = jnp.where(k_pos <= q_pos, scores, _NEG_INF)

    m = jnp.max(scores, axis=-1, keepdims=True)
    p = jnp.exp(scores - m)
    denom = jnp.sum(p, axis=-1, keepdims=True)
    p = (p / denom).reshape(blk_q * g, s)

    out = jax.lax.dot_general(
        p,
        v_ref[0].astype(jnp.float32),
        dimension_numbers=(((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )  # [blk_q*G, D]
    o_ref[0] = out.reshape(blk_q, g, d).astype(o_ref.dtype)


def flash_causal_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    blk_q: int = 256,
    interpret: bool | None = None,
) -> jnp.ndarray:
    """Causal attention, index-causal positions (the prefill hot path).

    q: [B, S, H, D]; k/v: [B, S, Hkv, D]. S must divide by ``blk_q``
    (callers pad prompts to buckets, ``engine.EngineConfig.seq_buckets``).
    Returns [B, S, H, D] in q's dtype.
    """
    b, s, h, d = q.shape
    hkv = k.shape[2]
    g = h // hkv
    blk_q = min(blk_q, s)
    if s % blk_q:
        raise ValueError(f"seq len {s} not divisible by q block {blk_q}")
    if interpret is None:
        interpret = _interpret_default()
    scale = d**-0.5

    # [B, S, Hkv, G, D] -> per-(b, kv) programs see [blk_q, G, D] q tiles.
    q5 = q.reshape(b, s, hkv, g, d)
    kt = k.transpose(0, 2, 1, 3).reshape(b * hkv, s, d)
    vt = v.transpose(0, 2, 1, 3).reshape(b * hkv, s, d)
    q5 = q5.transpose(0, 2, 1, 3, 4).reshape(b * hkv, s, g, d)

    out = pl.pallas_call(
        functools.partial(_causal_kernel, blk_q=blk_q, scale=scale),
        out_shape=jax.ShapeDtypeStruct((b * hkv, s, g, d), q.dtype),
        grid=(b * hkv, s // blk_q),
        in_specs=[
            pl.BlockSpec(
                (1, blk_q, g, d),
                lambda bh, qi: (bh, qi, 0, 0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(
                (1, s, d), lambda bh, qi: (bh, 0, 0), memory_space=pltpu.VMEM
            ),
            pl.BlockSpec(
                (1, s, d), lambda bh, qi: (bh, 0, 0), memory_space=pltpu.VMEM
            ),
        ],
        out_specs=pl.BlockSpec(
            (1, blk_q, g, d),
            lambda bh, qi: (bh, qi, 0, 0),
            memory_space=pltpu.VMEM,
        ),
        interpret=interpret,
    )(q5, kt, vt)
    # [B*Hkv, S, G, D] -> [B, S, H, D]
    return (
        out.reshape(b, hkv, s, g, d).transpose(0, 2, 1, 3, 4).reshape(b, s, h, d)
    )


# ---------------------------------------------------------------------------
# Decode attention against the KV cache
# ---------------------------------------------------------------------------


def _decode_kernel(len_ref, q_ref, k_ref, v_ref, o_ref, *, scale: float):
    """One (batch, kv-head) program.

    len_ref: [B*Hkv] whole-array SMEM valid lengths (unblocked — Mosaic
    rejects rank-1 blocked SMEM specs; index by program id instead);
    q_ref: [1, 1, G, D]; k_ref/v_ref: [1, S, D]; o_ref: [1, 1, G, D].
    """
    _, _, g, d = q_ref.shape
    s = k_ref.shape[1]
    valid = len_ref[pl.program_id(0)]

    q = q_ref[0, 0].astype(jnp.float32)  # [G, D]
    scores = jax.lax.dot_general(
        q,
        k_ref[0].astype(jnp.float32),
        dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    ) * scale  # [G, S]
    slot = jax.lax.broadcasted_iota(jnp.int32, (1, s), 1)
    scores = jnp.where(slot < valid, scores, _NEG_INF)

    m = jnp.max(scores, axis=-1, keepdims=True)
    p = jnp.exp(scores - m)
    p = p / jnp.sum(p, axis=-1, keepdims=True)

    out = jax.lax.dot_general(
        p,
        v_ref[0].astype(jnp.float32),
        dimension_numbers=(((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )  # [G, D]
    o_ref[0, 0] = out.astype(o_ref.dtype)


def _q8_attend(q, kq, ks_row, vq, vs_row, mask, scale: float):
    """Shared q8 decode-attention arithmetic for one (row, kv-head).

    q: [G, D]; kq/vq: [S, D] int8; ks_row/vs_row: [1, S] f32;
    mask: [1, S] bool. Returns [G, D] f32. All three q8 decode kernels
    (per-head grid, batch-row grid, stacked-cache grid) call this — the
    numerics live in exactly one place.

    Dequant is linear: fold the per-slot scales into the [G, S]
    scores/probs instead of scaling the [S, D] K/V slabs (D-times
    fewer VPU ops; int8 slabs feed the MXU after a bare cast).
    """
    scores = jax.lax.dot_general(
        q.astype(jnp.float32),
        kq.astype(jnp.float32),
        dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    ) * (ks_row * scale)  # [G, S] * [1, S]
    scores = jnp.where(mask, scores, _NEG_INF)
    m = jnp.max(scores, axis=-1, keepdims=True)
    p = jnp.exp(scores - m)
    p = p / jnp.sum(p, axis=-1, keepdims=True)
    return jax.lax.dot_general(
        p * vs_row,  # [G, S] * [1, S]
        vq.astype(jnp.float32),
        dimension_numbers=(((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )  # [G, D]


def _decode_q8_kernel(
    len_ref, q_ref, kq_ref, ks_ref, vq_ref, vs_ref, o_ref, *, scale: float
):
    """One (batch, kv-head) program over an int8 cache.

    len_ref: [B*Hkv] whole-array SMEM (unblocked, indexed by program id);
    q_ref: [1, 1, G, D]; kq_ref/vq_ref: [1, S, D] int8;
    ks_ref/vs_ref: [1, 1, S] f32 (leading singleton keeps the block's
    trailing dims equal to the array's — the Mosaic tiling rule);
    o_ref: [1, 1, G, D]. K/V dequantize in-register — HBM reads stay
    int8 (+ one f32 scale per slot).
    """
    s = kq_ref.shape[1]
    valid = len_ref[pl.program_id(0)]
    slot = jax.lax.broadcasted_iota(jnp.int32, (1, s), 1)
    out = _q8_attend(
        q_ref[0, 0], kq_ref[0], ks_ref[0], vq_ref[0], vs_ref[0],
        slot < valid, scale,
    )
    o_ref[0, 0] = out.astype(o_ref.dtype)


def _decode_q8_row_kernel(
    len_ref, q_ref, kq_ref, ks_ref, vq_ref, vs_ref, o_ref, *, scale: float
):
    """One batch-row program over the int8 cache, ALL kv heads.

    len_ref: [B] whole-array SMEM; q_ref: [1, Hkv, G, D];
    kq_ref/vq_ref: [1, Hkv, S, D] int8; ks_ref/vs_ref: [1, Hkv, S] f32;
    o_ref: [1, Hkv, G, D].

    Per-(batch, head) programs (``_decode_q8_kernel``) move ~64 KB of
    cache each — too little work per grid step, and at bench shapes the
    per-step pipeline overhead dominates (measured 4.7x slower than this
    row-program on v5e at B=64, Hkv=8, S=256). One program per batch row
    streams Hkv slabs (~0.5 MB) and unrolls the per-head attention; the
    arithmetic is identical (f32 dots), so outputs are bit-equal.
    """
    hkv = q_ref.shape[1]
    s = kq_ref.shape[2]
    valid = len_ref[pl.program_id(0)]
    slot = jax.lax.broadcasted_iota(jnp.int32, (1, s), 1)
    mask = slot < valid
    for head in range(hkv):  # static unroll over kv heads
        out = _q8_attend(
            q_ref[0, head],
            kq_ref[0, head],
            ks_ref[0, head][None, :],
            vq_ref[0, head],
            vs_ref[0, head][None, :],
            mask,
            scale,
        )
        o_ref[0, head] = out.astype(o_ref.dtype)


# Per-program K+V int8 block budget for the row kernel (double-buffered
# by the grid pipeline); caches larger than this fall back to the
# per-(batch, head) grid, whose blocks are Hkv-times smaller.
_ROW_KERNEL_MAX_KV_BYTES = 4 * 1024 * 1024


def flash_decode_attention_q8(
    q: jnp.ndarray,
    k_q: jnp.ndarray,
    k_scale: jnp.ndarray,
    v_q: jnp.ndarray,
    v_scale: jnp.ndarray,
    valid_len: jnp.ndarray,
    interpret: bool | None = None,
) -> jnp.ndarray:
    """Decode attention over the int8 head-major cache.

    q: [B, 1, H, D]; k_q/v_q: [B, Hkv, S, D] int8 (QuantKVCache layout —
    the reshape to per-(b, head) [S, D] slabs is zero-copy, unlike the
    bf16 kernel's transpose); k_scale/v_scale: [B, Hkv, S] f32;
    valid_len: [B]. Returns [B, 1, H, D] in q's dtype.

    Dispatches to the batch-row program (one grid step per row, all kv
    heads — the fast path at decode shapes) when the row's K+V block
    fits the VMEM budget, else to the per-(batch, head) program.
    """
    b, _, h, d = q.shape
    hkv, s = k_q.shape[1], k_q.shape[2]
    g = h // hkv
    if interpret is None:
        interpret = _interpret_default()
    scale = d**-0.5

    if 2 * hkv * s * d <= _ROW_KERNEL_MAX_KV_BYTES:
        q4 = q.reshape(b, 1, hkv, g, d).transpose(0, 2, 1, 3, 4).reshape(
            b, hkv, g, d
        )
        out = pl.pallas_call(
            functools.partial(_decode_q8_row_kernel, scale=scale),
            out_shape=jax.ShapeDtypeStruct((b, hkv, g, d), q.dtype),
            grid=(b,),
            in_specs=[
                pl.BlockSpec(memory_space=pltpu.SMEM),
                pl.BlockSpec(
                    (1, hkv, g, d),
                    lambda i: (i, 0, 0, 0),
                    memory_space=pltpu.VMEM,
                ),
                pl.BlockSpec(
                    (1, hkv, s, d),
                    lambda i: (i, 0, 0, 0),
                    memory_space=pltpu.VMEM,
                ),
                pl.BlockSpec(
                    (1, hkv, s), lambda i: (i, 0, 0), memory_space=pltpu.VMEM
                ),
                pl.BlockSpec(
                    (1, hkv, s, d),
                    lambda i: (i, 0, 0, 0),
                    memory_space=pltpu.VMEM,
                ),
                pl.BlockSpec(
                    (1, hkv, s), lambda i: (i, 0, 0), memory_space=pltpu.VMEM
                ),
            ],
            out_specs=pl.BlockSpec(
                (1, hkv, g, d), lambda i: (i, 0, 0, 0), memory_space=pltpu.VMEM
            ),
            interpret=interpret,
        )(valid_len.astype(jnp.int32), q4, k_q, k_scale, v_q, v_scale)
        return (
            out.reshape(b, hkv, 1, g, d)
            .transpose(0, 2, 1, 3, 4)
            .reshape(b, 1, h, d)
        )

    q4 = q.reshape(b, 1, hkv, g, d).transpose(0, 2, 1, 3, 4).reshape(
        b * hkv, 1, g, d
    )
    kq2 = k_q.reshape(b * hkv, s, d)
    vq2 = v_q.reshape(b * hkv, s, d)
    ks2 = k_scale.reshape(b * hkv, 1, s)
    vs2 = v_scale.reshape(b * hkv, 1, s)
    lens = jnp.repeat(valid_len.astype(jnp.int32), hkv)

    out = pl.pallas_call(
        functools.partial(_decode_q8_kernel, scale=scale),
        out_shape=jax.ShapeDtypeStruct((b * hkv, 1, g, d), q.dtype),
        grid=(b * hkv,),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(
                (1, 1, g, d), lambda bh: (bh, 0, 0, 0), memory_space=pltpu.VMEM
            ),
            pl.BlockSpec(
                (1, s, d), lambda bh: (bh, 0, 0), memory_space=pltpu.VMEM
            ),
            pl.BlockSpec(
                (1, 1, s), lambda bh: (bh, 0, 0), memory_space=pltpu.VMEM
            ),
            pl.BlockSpec(
                (1, s, d), lambda bh: (bh, 0, 0), memory_space=pltpu.VMEM
            ),
            pl.BlockSpec(
                (1, 1, s), lambda bh: (bh, 0, 0), memory_space=pltpu.VMEM
            ),
        ],
        out_specs=pl.BlockSpec(
            (1, 1, g, d), lambda bh: (bh, 0, 0, 0), memory_space=pltpu.VMEM
        ),
        interpret=interpret,
    )(lens, q4, kq2, ks2, vq2, vs2)
    return (
        out.reshape(b, hkv, 1, g, d).transpose(0, 2, 1, 3, 4).reshape(b, 1, h, d)
    )


def flash_decode_attention(
    q: jnp.ndarray,
    k_cache: jnp.ndarray,
    v_cache: jnp.ndarray,
    valid_len: jnp.ndarray,
    interpret: bool | None = None,
) -> jnp.ndarray:
    """One-token decode attention with ragged valid lengths.

    q: [B, 1, H, D]; k_cache/v_cache: [B, max_len, Hkv, D];
    valid_len: [B] int32. Returns [B, 1, H, D] in q's dtype.
    """
    b, _, h, d = q.shape
    s = k_cache.shape[1]
    hkv = k_cache.shape[2]
    g = h // hkv
    if interpret is None:
        interpret = _interpret_default()
    scale = d**-0.5

    q4 = q.reshape(b, 1, hkv, g, d).transpose(0, 2, 1, 3, 4).reshape(
        b * hkv, 1, g, d
    )
    kt = k_cache.transpose(0, 2, 1, 3).reshape(b * hkv, s, d)
    vt = v_cache.transpose(0, 2, 1, 3).reshape(b * hkv, s, d)
    lens = jnp.repeat(valid_len.astype(jnp.int32), hkv)  # [B*Hkv]

    out = pl.pallas_call(
        functools.partial(_decode_kernel, scale=scale),
        out_shape=jax.ShapeDtypeStruct((b * hkv, 1, g, d), q.dtype),
        grid=(b * hkv,),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(
                (1, 1, g, d), lambda bh: (bh, 0, 0, 0), memory_space=pltpu.VMEM
            ),
            pl.BlockSpec(
                (1, s, d), lambda bh: (bh, 0, 0), memory_space=pltpu.VMEM
            ),
            pl.BlockSpec(
                (1, s, d), lambda bh: (bh, 0, 0), memory_space=pltpu.VMEM
            ),
        ],
        out_specs=pl.BlockSpec(
            (1, 1, g, d), lambda bh: (bh, 0, 0, 0), memory_space=pltpu.VMEM
        ),
        interpret=interpret,
    )(lens, q4, kt, vt)
    return (
        out.reshape(b, hkv, 1, g, d).transpose(0, 2, 1, 3, 4).reshape(b, 1, h, d)
    )


def flash_decode_attention_q8_stacked(
    q: jnp.ndarray,
    k_q: jnp.ndarray,
    k_scale: jnp.ndarray,
    v_q: jnp.ndarray,
    v_scale: jnp.ndarray,
    valid_len: jnp.ndarray,
    layer: jnp.ndarray,
    interpret: bool | None = None,
) -> jnp.ndarray:
    """Decode attention reading ONE layer of the stacked int8 cache.

    q: [B, 1, H, D]; k_q/v_q: [L, B, Hkv, S, D] int8 (the WHOLE stacked
    QuantKVCache buffer); k_scale/v_scale: [L, B, Hkv, S] f32;
    valid_len: [B]; layer: traced scalar.

    Inside the layer scan a sliced cache layer must be materialized
    before it can feed ``flash_decode_attention_q8`` (Pallas operands
    are whole buffers) — XLA copies ~2 x B*Hkv*S*D bytes per layer per
    step. Here the stack itself is the operand and the layer index rides
    scalar prefetch into the index_maps, so each row's slab DMAs
    straight from the resident cache. Same arithmetic as the row
    program (:func:`_decode_q8_row_kernel`). Falls back to the sliced
    kernel when the row block exceeds the VMEM budget.
    """
    b, _, h, d = q.shape
    hkv, s = k_q.shape[2], k_q.shape[3]
    g = h // hkv
    if interpret is None:
        interpret = _interpret_default()
    if 2 * hkv * s * d > _ROW_KERNEL_MAX_KV_BYTES:
        idx = layer
        return flash_decode_attention_q8(
            q,
            jax.lax.dynamic_index_in_dim(k_q, idx, 0, keepdims=False),
            jax.lax.dynamic_index_in_dim(k_scale, idx, 0, keepdims=False),
            jax.lax.dynamic_index_in_dim(v_q, idx, 0, keepdims=False),
            jax.lax.dynamic_index_in_dim(v_scale, idx, 0, keepdims=False),
            valid_len,
            interpret=interpret,
        )
    scale = d**-0.5

    q4 = q.reshape(b, 1, hkv, g, d).transpose(0, 2, 1, 3, 4).reshape(
        b, hkv, g, d
    )
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,  # layer index, per-row valid lengths
        grid=(b,),
        in_specs=[
            pl.BlockSpec((1, hkv, g, d), lambda i, l, lens: (i, 0, 0, 0)),
            pl.BlockSpec(
                (1, 1, hkv, s, d), lambda i, l, lens: (l[0], i, 0, 0, 0)
            ),
            pl.BlockSpec((1, 1, hkv, s), lambda i, l, lens: (l[0], i, 0, 0)),
            pl.BlockSpec(
                (1, 1, hkv, s, d), lambda i, l, lens: (l[0], i, 0, 0, 0)
            ),
            pl.BlockSpec((1, 1, hkv, s), lambda i, l, lens: (l[0], i, 0, 0)),
        ],
        out_specs=pl.BlockSpec(
            (1, hkv, g, d), lambda i, l, lens: (i, 0, 0, 0)
        ),
    )
    out = pl.pallas_call(
        functools.partial(_decode_q8_stacked_kernel, scale=scale),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, hkv, g, d), q.dtype),
        interpret=interpret,
    )(
        jnp.atleast_1d(layer).astype(jnp.int32),
        valid_len.astype(jnp.int32),
        q4,
        k_q,
        k_scale,
        v_q,
        v_scale,
    )
    return (
        out.reshape(b, hkv, 1, g, d)
        .transpose(0, 2, 1, 3, 4)
        .reshape(b, 1, h, d)
    )


def _decode_q8_stacked_kernel(
    l_ref, len_ref, q_ref, kq_ref, ks_ref, vq_ref, vs_ref, o_ref, *,
    scale: float,
):
    """One batch-row program against the stacked cache, all kv heads.

    l_ref: [1] layer (consumed by index_maps); len_ref: [B] valid
    lengths; q_ref: [1, Hkv, G, D]; kq_ref/vq_ref: [1, 1, Hkv, S, D]
    int8; ks_ref/vs_ref: [1, 1, Hkv, S] f32; o_ref: [1, Hkv, G, D].
    Arithmetic is identical to :func:`_decode_q8_row_kernel`.
    """
    hkv = q_ref.shape[1]
    s = kq_ref.shape[3]
    valid = len_ref[pl.program_id(0)]
    slot = jax.lax.broadcasted_iota(jnp.int32, (1, s), 1)
    mask = slot < valid
    for head in range(hkv):  # static unroll over kv heads
        out = _q8_attend(
            q_ref[0, head],
            kq_ref[0, 0, head],
            ks_ref[0, 0, head][None, :],
            vq_ref[0, 0, head],
            vs_ref[0, 0, head][None, :],
            mask,
            scale,
        )
        o_ref[0, head] = out.astype(o_ref.dtype)


# ---------------------------------------------------------------------------
# Paged decode attention (vLLM-style page tables, TPU-native)
# ---------------------------------------------------------------------------


def _paged_decode_kernel(
    tbl_ref,  # [B*P] int32 scalar-prefetch: flattened page table
    len_ref,  # [B] int32 scalar-prefetch: valid lengths
    q_ref,  # [1, Hkv, G, D]
    k_ref,  # [1, pg, Hkv, D] — ONE page of the pool, all kv heads
    v_ref,
    o_ref,  # [1, Hkv, G, D]
    m_ref,  # [Hkv*G, 1] f32 scratch: running max
    l_ref,  # [Hkv*G, 1] f32 scratch: running denominator
    acc_ref,  # [Hkv*G, D] f32 scratch: running numerator
    *,
    scale: float,
    window: int,
):
    """One (row, page) program — online softmax across pages, all kv
    heads per program (static unroll; Mosaic requires the pool block's
    trailing dims to cover the [Hkv, D] axes whole, so a per-head grid
    axis cannot legally block the native pool layout).

    The page grid dimension is innermost, so TPU's sequential grid
    execution makes the VMEM scratch a legal accumulator: page j=0
    initializes, every page folds its per-head [G, pg] score tile in,
    the last page writes ``acc / l``. Pages beyond the row's valid
    length contribute exp(-inf)=0 — the NULL page's garbage never
    reaches the output, mirroring the gather path's masking."""
    b = pl.program_id(0)
    j = pl.program_id(1)
    n_pages = pl.num_programs(1)
    _, pg, hkv, d = k_ref.shape
    g = q_ref.shape[2]

    @pl.when(j == 0)
    def _init():
        m_ref[...] = jnp.full((hkv * g, 1), _NEG_INF, jnp.float32)
        l_ref[...] = jnp.zeros((hkv * g, 1), jnp.float32)
        acc_ref[...] = jnp.zeros((hkv * g, d), jnp.float32)

    valid = len_ref[b]
    # Pages wholly BEFORE the sliding window contribute exactly nothing
    # (every slot masked): skip their compute entirely — paired with the
    # sentinel-page remap in the wrapper's index maps, a long-context
    # windowed row costs O(window), not O(total length).
    live = (j + 1) * pg > valid - window if window > 0 else j >= 0

    @pl.when(live)
    def _fold_page():
        slot = j * pg + jax.lax.broadcasted_iota(jnp.int32, (1, pg), 1)
        in_range = slot < valid
        if window > 0:
            # Sliding window (Mistral): only the last `window` slots
            # attend — same rule as ops.attention.decode_attention.
            in_range &= slot >= valid - window
        for head in range(hkv):  # static unroll over kv heads
            hs = slice(head * g, (head + 1) * g)
            q = q_ref[0, head].astype(jnp.float32)  # [G, D]
            k = k_ref[0, :, head, :]  # [pg, D]
            scores = jax.lax.dot_general(
                q,
                k.astype(jnp.float32),
                dimension_numbers=(((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            ) * scale  # [G, pg]
            scores = jnp.where(in_range, scores, _NEG_INF)

            m_prev = m_ref[hs]
            m_new = jnp.maximum(
                m_prev, jnp.max(scores, axis=-1, keepdims=True)
            )
            # A fully-masked page (or row) keeps m at -inf;
            # exp(-inf - -inf) would be NaN — substitute 0 so p stays 0
            # for masked slots.
            m_safe = jnp.where(m_new <= _NEG_INF / 2, 0.0, m_new)
            p = jnp.exp(scores - m_safe)  # [G, pg]
            alpha = jnp.where(
                m_prev <= _NEG_INF / 2, 0.0, jnp.exp(m_prev - m_safe)
            )
            l_ref[hs] = l_ref[hs] * alpha + jnp.sum(
                p, axis=-1, keepdims=True
            )
            pv = jax.lax.dot_general(
                p,
                v_ref[0, :, head, :].astype(jnp.float32),
                dimension_numbers=(((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )  # [G, D]
            acc_ref[hs] = acc_ref[hs] * alpha + pv
            m_ref[hs] = m_new

    @pl.when(j == n_pages - 1)
    def _write():
        denom = jnp.maximum(l_ref[...], 1e-30)
        out = acc_ref[...] / denom  # [Hkv*G, D]
        o_ref[0] = out.reshape(hkv, g, d).astype(o_ref.dtype)


def paged_decode_attention(
    q: jnp.ndarray,
    k_pool: jnp.ndarray,
    v_pool: jnp.ndarray,
    page_table: jnp.ndarray,
    valid_len: jnp.ndarray,
    window: int = 0,
    interpret: bool | None = None,
) -> jnp.ndarray:
    """Decode attention THROUGH the page table — no pool gather.

    q: [B, H, D]; k_pool/v_pool: [n_pages, page, Hkv, D] (one layer's
    pool); page_table: [B, P] int32 page ids (NULL page for unused
    slots); valid_len: [B] tokens readable per row. Returns [B, H, D].

    The jnp reference path (``decode_step_paged``'s
    ``k_pool[tables]``) materializes every row's full padded sequence
    out of the pool per layer per step — O(B * P * page) HBM traffic
    regardless of true lengths. Here each (row, kv-head) program walks
    the row's OWN pages via the scalar-prefetched table: the BlockSpec
    index map reads ``page_table`` to choose which pool page lands in
    VMEM, so only real pages are streamed and the score tile never
    touches HBM. ``window`` > 0 applies the sliding-window rule (only
    the last ``window`` slots attend — Mistral configs). SURVEY §7's
    "ragged/paged decode attention in Pallas" hard part, paged half.
    """
    b, h, d = q.shape
    n_pages, pg, hkv, _ = k_pool.shape
    p_per = page_table.shape[1]
    g = h // hkv
    if interpret is None:
        interpret = _interpret_default()
    scale = d**-0.5

    # [B, Hkv, G, D] q blocks; pool stays in its native layout (any
    # transpose would materialize the whole pool and defeat the point).
    q4 = q.reshape(b, hkv, g, d)
    tbl = page_table.reshape(-1).astype(jnp.int32)
    lens = valid_len.astype(jnp.int32)

    def _page_map(bi, ji, tbl, lens):
        page = tbl[bi * p_per + ji]
        if window > 0:
            # Pages wholly before the window remap to the sentinel page
            # 0: consecutive skipped grid steps then request the SAME
            # block, so their DMAs collapse instead of streaming K/V the
            # kernel would only mask away (the pl.when skip inside).
            page = jnp.where((ji + 1) * pg > lens[bi] - window, page, 0)
        return (page, 0, 0, 0)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,  # page table, valid lengths
        grid=(b, p_per),
        in_specs=[
            pl.BlockSpec(
                (1, hkv, g, d), lambda bi, ji, tbl, lens: (bi, 0, 0, 0)
            ),
            pl.BlockSpec((1, pg, hkv, d), _page_map),
            pl.BlockSpec((1, pg, hkv, d), _page_map),
        ],
        out_specs=pl.BlockSpec(
            (1, hkv, g, d), lambda bi, ji, tbl, lens: (bi, 0, 0, 0)
        ),
        scratch_shapes=[
            pltpu.VMEM((hkv * g, 1), jnp.float32),
            pltpu.VMEM((hkv * g, 1), jnp.float32),
            pltpu.VMEM((hkv * g, d), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        functools.partial(_paged_decode_kernel, scale=scale, window=window),
        out_shape=jax.ShapeDtypeStruct((b, hkv, g, d), q.dtype),
        grid_spec=grid_spec,
        interpret=interpret,
    )(tbl, lens, q4, k_pool, v_pool)
    return out.reshape(b, h, d)
