"""Fused attention kernels (Pallas/Mosaic).

Two kernels, mirroring the two jnp reference paths in
:mod:`llm_consensus_tpu.ops.attention`:

- :func:`flash_causal_attention` — prefill/full attention. Grid over
  (batch x kv-head, query blocks); each program holds its (b, kv) K/V
  slab in VMEM, computes a [G*blk_q, S] score tile in fp32 on the MXU,
  applies the causal mask, does the softmax in VMEM, and writes the
  [G*blk_q, D] output — the score matrix never touches HBM.
- :func:`flash_decode_attention` — single-token decode against the KV
  cache with per-sequence ``valid_len`` masking (the ragged-decode op of
  BASELINE.json's north star). Grid over (batch, kv-head).

GQA layout: H = Hkv * G query heads share each kv head; programs are
per-(batch, kv-head) and process all G group heads at once, so K/V are
read exactly once per program (no repeated-KV materialization anywhere).

Tiling: D (head_dim) and S pad to lane width (128); fp32 accumulation via
``preferred_element_type``. On CPU tests, ``interpret=True`` is selected
automatically (same kernels, interpreted).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG_INF = -1e30


def _interpret_default() -> bool:
    return jax.default_backend() != "tpu"


# ---------------------------------------------------------------------------
# Prefill / full causal attention
# ---------------------------------------------------------------------------


def _causal_kernel(q_ref, k_ref, v_ref, o_ref, *, blk_q: int, scale: float):
    """One (b, kv-head, q-block) program.

    q_ref: [1, blk_q, G, D]; k_ref/v_ref: [1, S, D]; o_ref: [1, blk_q, G, D].
    """
    qi = pl.program_id(1)
    _, _, g, d = q_ref.shape
    s = k_ref.shape[1]

    q = q_ref[0].astype(jnp.float32)  # [blk_q, G, D]
    q2 = q.reshape(blk_q * g, d)
    k = k_ref[0]  # [S, D]
    scores = jax.lax.dot_general(
        q2,
        k.astype(jnp.float32),
        dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    ) * scale  # [blk_q*G, S]
    scores = scores.reshape(blk_q, g, s)

    q_pos = qi * blk_q + jax.lax.broadcasted_iota(jnp.int32, (blk_q, 1, 1), 0)
    k_pos = jax.lax.broadcasted_iota(jnp.int32, (1, 1, s), 2)
    scores = jnp.where(k_pos <= q_pos, scores, _NEG_INF)

    m = jnp.max(scores, axis=-1, keepdims=True)
    p = jnp.exp(scores - m)
    denom = jnp.sum(p, axis=-1, keepdims=True)
    p = (p / denom).reshape(blk_q * g, s)

    out = jax.lax.dot_general(
        p,
        v_ref[0].astype(jnp.float32),
        dimension_numbers=(((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )  # [blk_q*G, D]
    o_ref[0] = out.reshape(blk_q, g, d).astype(o_ref.dtype)


def flash_causal_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    blk_q: int = 256,
    interpret: bool | None = None,
) -> jnp.ndarray:
    """Causal attention, index-causal positions (the prefill hot path).

    q: [B, S, H, D]; k/v: [B, S, Hkv, D]. S must divide by ``blk_q``
    (callers pad prompts to buckets, ``engine.EngineConfig.seq_buckets``).
    Returns [B, S, H, D] in q's dtype.
    """
    b, s, h, d = q.shape
    hkv = k.shape[2]
    g = h // hkv
    blk_q = min(blk_q, s)
    if s % blk_q:
        raise ValueError(f"seq len {s} not divisible by q block {blk_q}")
    if interpret is None:
        interpret = _interpret_default()
    scale = d**-0.5

    # [B, S, Hkv, G, D] -> per-(b, kv) programs see [blk_q, G, D] q tiles.
    q5 = q.reshape(b, s, hkv, g, d)
    kt = k.transpose(0, 2, 1, 3).reshape(b * hkv, s, d)
    vt = v.transpose(0, 2, 1, 3).reshape(b * hkv, s, d)
    q5 = q5.transpose(0, 2, 1, 3, 4).reshape(b * hkv, s, g, d)

    out = pl.pallas_call(
        functools.partial(_causal_kernel, blk_q=blk_q, scale=scale),
        out_shape=jax.ShapeDtypeStruct((b * hkv, s, g, d), q.dtype),
        grid=(b * hkv, s // blk_q),
        in_specs=[
            pl.BlockSpec(
                (1, blk_q, g, d),
                lambda bh, qi: (bh, qi, 0, 0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(
                (1, s, d), lambda bh, qi: (bh, 0, 0), memory_space=pltpu.VMEM
            ),
            pl.BlockSpec(
                (1, s, d), lambda bh, qi: (bh, 0, 0), memory_space=pltpu.VMEM
            ),
        ],
        out_specs=pl.BlockSpec(
            (1, blk_q, g, d),
            lambda bh, qi: (bh, qi, 0, 0),
            memory_space=pltpu.VMEM,
        ),
        interpret=interpret,
    )(q5, kt, vt)
    # [B*Hkv, S, G, D] -> [B, S, H, D]
    return (
        out.reshape(b, hkv, s, g, d).transpose(0, 2, 1, 3, 4).reshape(b, s, h, d)
    )


# ---------------------------------------------------------------------------
# Decode attention against the KV cache
# ---------------------------------------------------------------------------


def _decode_kernel(len_ref, q_ref, k_ref, v_ref, o_ref, *, scale: float):
    """One (batch, kv-head) program.

    len_ref: [B*Hkv] whole-array SMEM valid lengths (unblocked — Mosaic
    rejects rank-1 blocked SMEM specs; index by program id instead);
    q_ref: [1, 1, G, D]; k_ref/v_ref: [1, S, D]; o_ref: [1, 1, G, D].
    """
    _, _, g, d = q_ref.shape
    s = k_ref.shape[1]
    valid = len_ref[pl.program_id(0)]

    q = q_ref[0, 0].astype(jnp.float32)  # [G, D]
    scores = jax.lax.dot_general(
        q,
        k_ref[0].astype(jnp.float32),
        dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    ) * scale  # [G, S]
    slot = jax.lax.broadcasted_iota(jnp.int32, (1, s), 1)
    scores = jnp.where(slot < valid, scores, _NEG_INF)

    m = jnp.max(scores, axis=-1, keepdims=True)
    p = jnp.exp(scores - m)
    p = p / jnp.sum(p, axis=-1, keepdims=True)

    out = jax.lax.dot_general(
        p,
        v_ref[0].astype(jnp.float32),
        dimension_numbers=(((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )  # [G, D]
    o_ref[0, 0] = out.astype(o_ref.dtype)


def _q8_attend(q, kq, ks_row, vq, vs_row, mask, scale: float):
    """Shared q8 decode-attention arithmetic for one (row, kv-head).

    q: [G, D]; kq/vq: [S, D] int8; ks_row/vs_row: [1, S] f32;
    mask: [1, S] bool. Returns [G, D] f32. All three q8 decode kernels
    (per-head grid, batch-row grid, stacked-cache grid) call this — the
    numerics live in exactly one place.

    Dequant is linear: fold the per-slot scales into the [G, S]
    scores/probs instead of scaling the [S, D] K/V slabs (D-times
    fewer VPU ops; int8 slabs feed the MXU after a bare cast).
    """
    scores = jax.lax.dot_general(
        q.astype(jnp.float32),
        kq.astype(jnp.float32),
        dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    ) * (ks_row * scale)  # [G, S] * [1, S]
    scores = jnp.where(mask, scores, _NEG_INF)
    m = jnp.max(scores, axis=-1, keepdims=True)
    p = jnp.exp(scores - m)
    p = p / jnp.sum(p, axis=-1, keepdims=True)
    return jax.lax.dot_general(
        p * vs_row,  # [G, S] * [1, S]
        vq.astype(jnp.float32),
        dimension_numbers=(((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )  # [G, D]


def _decode_q8_kernel(
    len_ref, q_ref, kq_ref, ks_ref, vq_ref, vs_ref, o_ref, *, scale: float
):
    """One (batch, kv-head) program over an int8 cache.

    len_ref: [B*Hkv] whole-array SMEM (unblocked, indexed by program id);
    q_ref: [1, 1, G, D]; kq_ref/vq_ref: [1, S, D] int8;
    ks_ref/vs_ref: [1, 1, S] f32 (leading singleton keeps the block's
    trailing dims equal to the array's — the Mosaic tiling rule);
    o_ref: [1, 1, G, D]. K/V dequantize in-register — HBM reads stay
    int8 (+ one f32 scale per slot).
    """
    s = kq_ref.shape[1]
    valid = len_ref[pl.program_id(0)]
    slot = jax.lax.broadcasted_iota(jnp.int32, (1, s), 1)
    out = _q8_attend(
        q_ref[0, 0], kq_ref[0], ks_ref[0], vq_ref[0], vs_ref[0],
        slot < valid, scale,
    )
    o_ref[0, 0] = out.astype(o_ref.dtype)


def _decode_q8_row_kernel(
    len_ref, q_ref, kq_ref, ks_ref, vq_ref, vs_ref, o_ref, *, scale: float
):
    """One batch-row program over the int8 cache, ALL kv heads.

    len_ref: [B] whole-array SMEM; q_ref: [1, Hkv, G, D];
    kq_ref/vq_ref: [1, Hkv, S, D] int8; ks_ref/vs_ref: [1, Hkv, S] f32;
    o_ref: [1, Hkv, G, D].

    Per-(batch, head) programs (``_decode_q8_kernel``) move ~64 KB of
    cache each — too little work per grid step, and at bench shapes the
    per-step pipeline overhead dominates (measured 4.7x slower than this
    row-program on v5e at B=64, Hkv=8, S=256). One program per batch row
    streams Hkv slabs (~0.5 MB) and unrolls the per-head attention; the
    arithmetic is identical (f32 dots), so outputs are bit-equal.
    """
    hkv = q_ref.shape[1]
    s = kq_ref.shape[2]
    valid = len_ref[pl.program_id(0)]
    slot = jax.lax.broadcasted_iota(jnp.int32, (1, s), 1)
    mask = slot < valid
    for head in range(hkv):  # static unroll over kv heads
        out = _q8_attend(
            q_ref[0, head],
            kq_ref[0, head],
            ks_ref[0, head][None, :],
            vq_ref[0, head],
            vs_ref[0, head][None, :],
            mask,
            scale,
        )
        o_ref[0, head] = out.astype(o_ref.dtype)


# Per-program K+V int8 block budget for the row kernel (double-buffered
# by the grid pipeline); caches larger than this fall back to the
# per-(batch, head) grid, whose blocks are Hkv-times smaller.
_ROW_KERNEL_MAX_KV_BYTES = 4 * 1024 * 1024


def flash_decode_attention_q8(
    q: jnp.ndarray,
    k_q: jnp.ndarray,
    k_scale: jnp.ndarray,
    v_q: jnp.ndarray,
    v_scale: jnp.ndarray,
    valid_len: jnp.ndarray,
    interpret: bool | None = None,
) -> jnp.ndarray:
    """Decode attention over the int8 head-major cache.

    q: [B, 1, H, D]; k_q/v_q: [B, Hkv, S, D] int8 (QuantKVCache layout —
    the reshape to per-(b, head) [S, D] slabs is zero-copy, unlike the
    bf16 kernel's transpose); k_scale/v_scale: [B, Hkv, S] f32;
    valid_len: [B]. Returns [B, 1, H, D] in q's dtype.

    Dispatches to the batch-row program (one grid step per row, all kv
    heads — the fast path at decode shapes) when the row's K+V block
    fits the VMEM budget, else to the per-(batch, head) program.
    """
    b, _, h, d = q.shape
    hkv, s = k_q.shape[1], k_q.shape[2]
    g = h // hkv
    if interpret is None:
        interpret = _interpret_default()
    scale = d**-0.5

    if 2 * hkv * s * d <= _ROW_KERNEL_MAX_KV_BYTES:
        q4 = q.reshape(b, 1, hkv, g, d).transpose(0, 2, 1, 3, 4).reshape(
            b, hkv, g, d
        )
        out = pl.pallas_call(
            functools.partial(_decode_q8_row_kernel, scale=scale),
            out_shape=jax.ShapeDtypeStruct((b, hkv, g, d), q.dtype),
            grid=(b,),
            in_specs=[
                pl.BlockSpec(memory_space=pltpu.SMEM),
                pl.BlockSpec(
                    (1, hkv, g, d),
                    lambda i: (i, 0, 0, 0),
                    memory_space=pltpu.VMEM,
                ),
                pl.BlockSpec(
                    (1, hkv, s, d),
                    lambda i: (i, 0, 0, 0),
                    memory_space=pltpu.VMEM,
                ),
                pl.BlockSpec(
                    (1, hkv, s), lambda i: (i, 0, 0), memory_space=pltpu.VMEM
                ),
                pl.BlockSpec(
                    (1, hkv, s, d),
                    lambda i: (i, 0, 0, 0),
                    memory_space=pltpu.VMEM,
                ),
                pl.BlockSpec(
                    (1, hkv, s), lambda i: (i, 0, 0), memory_space=pltpu.VMEM
                ),
            ],
            out_specs=pl.BlockSpec(
                (1, hkv, g, d), lambda i: (i, 0, 0, 0), memory_space=pltpu.VMEM
            ),
            interpret=interpret,
        )(valid_len.astype(jnp.int32), q4, k_q, k_scale, v_q, v_scale)
        return (
            out.reshape(b, hkv, 1, g, d)
            .transpose(0, 2, 1, 3, 4)
            .reshape(b, 1, h, d)
        )

    q4 = q.reshape(b, 1, hkv, g, d).transpose(0, 2, 1, 3, 4).reshape(
        b * hkv, 1, g, d
    )
    kq2 = k_q.reshape(b * hkv, s, d)
    vq2 = v_q.reshape(b * hkv, s, d)
    ks2 = k_scale.reshape(b * hkv, 1, s)
    vs2 = v_scale.reshape(b * hkv, 1, s)
    lens = jnp.repeat(valid_len.astype(jnp.int32), hkv)

    out = pl.pallas_call(
        functools.partial(_decode_q8_kernel, scale=scale),
        out_shape=jax.ShapeDtypeStruct((b * hkv, 1, g, d), q.dtype),
        grid=(b * hkv,),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(
                (1, 1, g, d), lambda bh: (bh, 0, 0, 0), memory_space=pltpu.VMEM
            ),
            pl.BlockSpec(
                (1, s, d), lambda bh: (bh, 0, 0), memory_space=pltpu.VMEM
            ),
            pl.BlockSpec(
                (1, 1, s), lambda bh: (bh, 0, 0), memory_space=pltpu.VMEM
            ),
            pl.BlockSpec(
                (1, s, d), lambda bh: (bh, 0, 0), memory_space=pltpu.VMEM
            ),
            pl.BlockSpec(
                (1, 1, s), lambda bh: (bh, 0, 0), memory_space=pltpu.VMEM
            ),
        ],
        out_specs=pl.BlockSpec(
            (1, 1, g, d), lambda bh: (bh, 0, 0, 0), memory_space=pltpu.VMEM
        ),
        interpret=interpret,
    )(lens, q4, kq2, ks2, vq2, vs2)
    return (
        out.reshape(b, hkv, 1, g, d).transpose(0, 2, 1, 3, 4).reshape(b, 1, h, d)
    )


def flash_decode_attention(
    q: jnp.ndarray,
    k_cache: jnp.ndarray,
    v_cache: jnp.ndarray,
    valid_len: jnp.ndarray,
    interpret: bool | None = None,
) -> jnp.ndarray:
    """One-token decode attention with ragged valid lengths.

    q: [B, 1, H, D]; k_cache/v_cache: [B, max_len, Hkv, D];
    valid_len: [B] int32. Returns [B, 1, H, D] in q's dtype.
    """
    b, _, h, d = q.shape
    s = k_cache.shape[1]
    hkv = k_cache.shape[2]
    g = h // hkv
    if interpret is None:
        interpret = _interpret_default()
    scale = d**-0.5

    q4 = q.reshape(b, 1, hkv, g, d).transpose(0, 2, 1, 3, 4).reshape(
        b * hkv, 1, g, d
    )
    kt = k_cache.transpose(0, 2, 1, 3).reshape(b * hkv, s, d)
    vt = v_cache.transpose(0, 2, 1, 3).reshape(b * hkv, s, d)
    lens = jnp.repeat(valid_len.astype(jnp.int32), hkv)  # [B*Hkv]

    out = pl.pallas_call(
        functools.partial(_decode_kernel, scale=scale),
        out_shape=jax.ShapeDtypeStruct((b * hkv, 1, g, d), q.dtype),
        grid=(b * hkv,),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(
                (1, 1, g, d), lambda bh: (bh, 0, 0, 0), memory_space=pltpu.VMEM
            ),
            pl.BlockSpec(
                (1, s, d), lambda bh: (bh, 0, 0), memory_space=pltpu.VMEM
            ),
            pl.BlockSpec(
                (1, s, d), lambda bh: (bh, 0, 0), memory_space=pltpu.VMEM
            ),
        ],
        out_specs=pl.BlockSpec(
            (1, 1, g, d), lambda bh: (bh, 0, 0, 0), memory_space=pltpu.VMEM
        ),
        interpret=interpret,
    )(lens, q4, kt, vt)
    return (
        out.reshape(b, hkv, 1, g, d).transpose(0, 2, 1, 3, 4).reshape(b, 1, h, d)
    )


def flash_decode_attention_q8_stacked(
    q: jnp.ndarray,
    k_q: jnp.ndarray,
    k_scale: jnp.ndarray,
    v_q: jnp.ndarray,
    v_scale: jnp.ndarray,
    valid_len: jnp.ndarray,
    layer: jnp.ndarray,
    interpret: bool | None = None,
) -> jnp.ndarray:
    """Decode attention reading ONE layer of the stacked int8 cache.

    q: [B, 1, H, D]; k_q/v_q: [L, B, Hkv, S, D] int8 (the WHOLE stacked
    QuantKVCache buffer); k_scale/v_scale: [L, B, Hkv, S] f32;
    valid_len: [B]; layer: traced scalar.

    Inside the layer scan a sliced cache layer must be materialized
    before it can feed ``flash_decode_attention_q8`` (Pallas operands
    are whole buffers) — XLA copies ~2 x B*Hkv*S*D bytes per layer per
    step. Here the stack itself is the operand and the layer index rides
    scalar prefetch into the index_maps, so each row's slab DMAs
    straight from the resident cache. Same arithmetic as the row
    program (:func:`_decode_q8_row_kernel`). Falls back to the sliced
    kernel when the row block exceeds the VMEM budget.
    """
    b, _, h, d = q.shape
    hkv, s = k_q.shape[2], k_q.shape[3]
    g = h // hkv
    if interpret is None:
        interpret = _interpret_default()
    if 2 * hkv * s * d > _ROW_KERNEL_MAX_KV_BYTES:
        idx = layer
        return flash_decode_attention_q8(
            q,
            jax.lax.dynamic_index_in_dim(k_q, idx, 0, keepdims=False),
            jax.lax.dynamic_index_in_dim(k_scale, idx, 0, keepdims=False),
            jax.lax.dynamic_index_in_dim(v_q, idx, 0, keepdims=False),
            jax.lax.dynamic_index_in_dim(v_scale, idx, 0, keepdims=False),
            valid_len,
            interpret=interpret,
        )
    scale = d**-0.5

    q4 = q.reshape(b, 1, hkv, g, d).transpose(0, 2, 1, 3, 4).reshape(
        b, hkv, g, d
    )
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,  # layer index, per-row valid lengths
        grid=(b,),
        in_specs=[
            pl.BlockSpec((1, hkv, g, d), lambda i, l, lens: (i, 0, 0, 0)),
            pl.BlockSpec(
                (1, 1, hkv, s, d), lambda i, l, lens: (l[0], i, 0, 0, 0)
            ),
            pl.BlockSpec((1, 1, hkv, s), lambda i, l, lens: (l[0], i, 0, 0)),
            pl.BlockSpec(
                (1, 1, hkv, s, d), lambda i, l, lens: (l[0], i, 0, 0, 0)
            ),
            pl.BlockSpec((1, 1, hkv, s), lambda i, l, lens: (l[0], i, 0, 0)),
        ],
        out_specs=pl.BlockSpec(
            (1, hkv, g, d), lambda i, l, lens: (i, 0, 0, 0)
        ),
    )
    out = pl.pallas_call(
        functools.partial(_decode_q8_stacked_kernel, scale=scale),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, hkv, g, d), q.dtype),
        interpret=interpret,
    )(
        jnp.atleast_1d(layer).astype(jnp.int32),
        valid_len.astype(jnp.int32),
        q4,
        k_q,
        k_scale,
        v_q,
        v_scale,
    )
    return (
        out.reshape(b, hkv, 1, g, d)
        .transpose(0, 2, 1, 3, 4)
        .reshape(b, 1, h, d)
    )


def _decode_q8_stacked_kernel(
    l_ref, len_ref, q_ref, kq_ref, ks_ref, vq_ref, vs_ref, o_ref, *,
    scale: float,
):
    """One batch-row program against the stacked cache, all kv heads.

    l_ref: [1] layer (consumed by index_maps); len_ref: [B] valid
    lengths; q_ref: [1, Hkv, G, D]; kq_ref/vq_ref: [1, 1, Hkv, S, D]
    int8; ks_ref/vs_ref: [1, 1, Hkv, S] f32; o_ref: [1, Hkv, G, D].
    Arithmetic is identical to :func:`_decode_q8_row_kernel`.
    """
    hkv = q_ref.shape[1]
    s = kq_ref.shape[3]
    valid = len_ref[pl.program_id(0)]
    slot = jax.lax.broadcasted_iota(jnp.int32, (1, s), 1)
    mask = slot < valid
    for head in range(hkv):  # static unroll over kv heads
        out = _q8_attend(
            q_ref[0, head],
            kq_ref[0, 0, head],
            ks_ref[0, 0, head][None, :],
            vq_ref[0, 0, head],
            vs_ref[0, 0, head][None, :],
            mask,
            scale,
        )
        o_ref[0, head] = out.astype(o_ref.dtype)


# ---------------------------------------------------------------------------
# Paged decode attention (vLLM-style page tables, TPU-native)
# ---------------------------------------------------------------------------


def _paged_decode_kernel(
    tbl_ref,  # [B*P] int32 scalar-prefetch: flattened page table
    len_ref,  # [B] int32 scalar-prefetch: valid lengths
    q_ref,  # [1, Hkv, G, D]
    k_ref,  # [1, pg, Hkv, D] — ONE page of the pool, all kv heads
    v_ref,
    o_ref,  # [1, Hkv, G, D]
    m_ref,  # [Hkv*G, 1] f32 scratch: running max
    l_ref,  # [Hkv*G, 1] f32 scratch: running denominator
    acc_ref,  # [Hkv*G, D] f32 scratch: running numerator
    *,
    scale: float,
    window: int,
):
    """One (row, page) program — online softmax across pages, all kv
    heads per program (static unroll; Mosaic requires the pool block's
    trailing dims to cover the [Hkv, D] axes whole, so a per-head grid
    axis cannot legally block the native pool layout).

    The page grid dimension is innermost, so TPU's sequential grid
    execution makes the VMEM scratch a legal accumulator: page j=0
    initializes, every page folds its per-head [G, pg] score tile in,
    the last page writes ``acc / l``. Pages beyond the row's valid
    length contribute exp(-inf)=0 — the NULL page's garbage never
    reaches the output, mirroring the gather path's masking."""
    b = pl.program_id(0)
    j = pl.program_id(1)
    n_pages = pl.num_programs(1)
    _, pg, hkv, d = k_ref.shape
    g = q_ref.shape[2]

    @pl.when(j == 0)
    def _init():
        m_ref[...] = jnp.full((hkv * g, 1), _NEG_INF, jnp.float32)
        l_ref[...] = jnp.zeros((hkv * g, 1), jnp.float32)
        acc_ref[...] = jnp.zeros((hkv * g, d), jnp.float32)

    valid = len_ref[b]
    # Pages wholly BEFORE the sliding window contribute exactly nothing
    # (every slot masked): skip their compute entirely — paired with the
    # sentinel-page remap in the wrapper's index maps, a long-context
    # windowed row costs O(window), not O(total length).
    live = (j + 1) * pg > valid - window if window > 0 else j >= 0

    @pl.when(live)
    def _fold_page():
        slot = j * pg + jax.lax.broadcasted_iota(jnp.int32, (1, pg), 1)
        in_range = slot < valid
        if window > 0:
            # Sliding window (Mistral): only the last `window` slots
            # attend — same rule as ops.attention.decode_attention.
            in_range &= slot >= valid - window
        for head in range(hkv):  # static unroll over kv heads
            hs = slice(head * g, (head + 1) * g)
            q = q_ref[0, head].astype(jnp.float32)  # [G, D]
            k = k_ref[0, :, head, :]  # [pg, D]
            scores = jax.lax.dot_general(
                q,
                k.astype(jnp.float32),
                dimension_numbers=(((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            ) * scale  # [G, pg]
            scores = jnp.where(in_range, scores, _NEG_INF)

            m_prev = m_ref[hs]
            m_new = jnp.maximum(
                m_prev, jnp.max(scores, axis=-1, keepdims=True)
            )
            # A fully-masked page (or row) keeps m at -inf;
            # exp(-inf - -inf) would be NaN — substitute 0 so p stays 0
            # for masked slots.
            m_safe = jnp.where(m_new <= _NEG_INF / 2, 0.0, m_new)
            p = jnp.exp(scores - m_safe)  # [G, pg]
            alpha = jnp.where(
                m_prev <= _NEG_INF / 2, 0.0, jnp.exp(m_prev - m_safe)
            )
            l_ref[hs] = l_ref[hs] * alpha + jnp.sum(
                p, axis=-1, keepdims=True
            )
            pv = jax.lax.dot_general(
                p,
                v_ref[0, :, head, :].astype(jnp.float32),
                dimension_numbers=(((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )  # [G, D]
            acc_ref[hs] = acc_ref[hs] * alpha + pv
            m_ref[hs] = m_new

    @pl.when(j == n_pages - 1)
    def _write():
        denom = jnp.maximum(l_ref[...], 1e-30)
        out = acc_ref[...] / denom  # [Hkv*G, D]
        o_ref[0] = out.reshape(hkv, g, d).astype(o_ref.dtype)


def paged_decode_attention(
    q: jnp.ndarray,
    k_pool: jnp.ndarray,
    v_pool: jnp.ndarray,
    page_table: jnp.ndarray,
    valid_len: jnp.ndarray,
    window: int = 0,
    interpret: bool | None = None,
) -> jnp.ndarray:
    """Decode attention THROUGH the page table — no pool gather.

    q: [B, H, D]; k_pool/v_pool: [n_pages, page, Hkv, D] (one layer's
    pool); page_table: [B, P] int32 page ids (NULL page for unused
    slots); valid_len: [B] tokens readable per row. Returns [B, H, D].

    The jnp reference path (``decode_step_paged``'s
    ``k_pool[tables]``) materializes every row's full padded sequence
    out of the pool per layer per step — O(B * P * page) HBM traffic
    regardless of true lengths. Here each (row, kv-head) program walks
    the row's OWN pages via the scalar-prefetched table: the BlockSpec
    index map reads ``page_table`` to choose which pool page lands in
    VMEM, so only real pages are streamed and the score tile never
    touches HBM. ``window`` > 0 applies the sliding-window rule (only
    the last ``window`` slots attend — Mistral configs). SURVEY §7's
    "ragged/paged decode attention in Pallas" hard part, paged half.
    """
    b, h, d = q.shape
    n_pages, pg, hkv, _ = k_pool.shape
    p_per = page_table.shape[1]
    g = h // hkv
    if interpret is None:
        interpret = _interpret_default()
    scale = d**-0.5

    # [B, Hkv, G, D] q blocks; pool stays in its native layout (any
    # transpose would materialize the whole pool and defeat the point).
    q4 = q.reshape(b, hkv, g, d)
    tbl = page_table.reshape(-1).astype(jnp.int32)
    lens = valid_len.astype(jnp.int32)

    def _page_map(bi, ji, tbl, lens):
        page = tbl[bi * p_per + ji]
        if window > 0:
            # Pages wholly before the window remap to the sentinel page
            # 0: consecutive skipped grid steps then request the SAME
            # block, so their DMAs collapse instead of streaming K/V the
            # kernel would only mask away (the pl.when skip inside).
            page = jnp.where((ji + 1) * pg > lens[bi] - window, page, 0)
        return (page, 0, 0, 0)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,  # page table, valid lengths
        grid=(b, p_per),
        in_specs=[
            pl.BlockSpec(
                (1, hkv, g, d), lambda bi, ji, tbl, lens: (bi, 0, 0, 0)
            ),
            pl.BlockSpec((1, pg, hkv, d), _page_map),
            pl.BlockSpec((1, pg, hkv, d), _page_map),
        ],
        out_specs=pl.BlockSpec(
            (1, hkv, g, d), lambda bi, ji, tbl, lens: (bi, 0, 0, 0)
        ),
        scratch_shapes=[
            pltpu.VMEM((hkv * g, 1), jnp.float32),
            pltpu.VMEM((hkv * g, 1), jnp.float32),
            pltpu.VMEM((hkv * g, d), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        functools.partial(_paged_decode_kernel, scale=scale, window=window),
        out_shape=jax.ShapeDtypeStruct((b, hkv, g, d), q.dtype),
        grid_spec=grid_spec,
        interpret=interpret,
    )(tbl, lens, q4, k_pool, v_pool)
    return out.reshape(b, h, d)


# ---------------------------------------------------------------------------
# Shared-prefix decode attention (two-phase, flash-decoding LSE merge)
# ---------------------------------------------------------------------------
#
# The self-consistency / consensus-panel decode workload is N sequences
# over ONE shared prompt: the ungrouped kernels above stream the common
# prefix KV once PER SEQUENCE, so the KV half of the decode roofline
# scales as N*S instead of S + N*suffix. The kernels below split the
# attention into
#
#   phase 1  all member queries, STACKED, against one copy of the
#            shared-prefix KV (one HBM read for the whole group; the
#            per-row GEMV becomes a [N*G, D] x [D, blk] GEMM — MXU
#            food, not VPU scraps), and
#   phase 2  each sequence against its own suffix slots only,
#
# each emitting flash-decoding (m, l, o) partials that merge EXACTLY via
# ops.attention.merge_decode_partials (log-sum-exp recombination — the
# split is lossless, not an approximation). Three layout variants:
# dense bf16 (the engine's N-fanout cache), dense int8 head-major
# (kv_quant fan-out), and the paged pool (continuous batching, where
# groups come from the PrefixRegistry's shared page runs). No
# sliding-window support anywhere in the family: windowed configs fall
# back to the ungrouped kernels at the call sites.


def _sp_block(s: int, cap: int = 128) -> int:
    """Largest divisor of ``s`` <= cap — the S-axis block width for the
    two-phase DENSE kernels (blocks let the suffix pass SKIP the prefix
    region instead of streaming it per row).

    The cap trades DMA size against skip granularity: the suffix pass
    can only skip whole blocks, so a prefix shorter than one block
    saves nothing there while phase 1 still pays one extra read of the
    prefix region — a bounded overhead of < blk slots per row plus one
    prefix read, flipping to a win as soon as the prefix spans a block
    (the canonical fan-out prompt buckets are >= 128). 128 keeps the
    blocks at lane width and makes that break-even point the smallest
    bucket the engine serves; the paged variant's unit is the page and
    needs none of this.
    """
    blk = min(cap, s)
    while s % blk:
        blk -= 1
    return blk


def _online_fold(m_ref, l_ref, acc_ref, idx, scores, v, v_row_scale=None):
    """Fold one score block into running (m, l, acc) softmax state.

    ``idx`` selects the scratch slice (slice or int); scores [R, blk]
    fp32 (already masked to -inf outside the live range); v [blk, D].
    ``v_row_scale`` [1, blk]: per-slot dequant scale folded into the
    VALUE product only (the l denominator stays the true softmax sum) —
    the same linear-dequant trick as :func:`_q8_attend`. The arithmetic
    is identical to :func:`_paged_decode_kernel`'s in-kernel fold; it
    lives here once so every two-phase variant shares it.
    """
    m_prev = m_ref[idx]
    m_new = jnp.maximum(m_prev, jnp.max(scores, axis=-1, keepdims=True))
    m_safe = jnp.where(m_new <= _NEG_INF / 2, 0.0, m_new)
    p = jnp.exp(scores - m_safe)
    alpha = jnp.where(m_prev <= _NEG_INF / 2, 0.0, jnp.exp(m_prev - m_safe))
    l_ref[idx] = l_ref[idx] * alpha + jnp.sum(p, axis=-1, keepdims=True)
    pv = jax.lax.dot_general(
        p if v_row_scale is None else p * v_row_scale,
        v.astype(jnp.float32),
        dimension_numbers=(((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    acc_ref[idx] = acc_ref[idx] * alpha + pv
    m_ref[idx] = m_new


def _partials_to_rows(m, l, o, b: int, hkv: int, g: int):
    """Phase-1 partials [Hkv, B*G, *] -> phase-2 row layout [B*Hkv, G, *]."""

    def t(x):
        return (
            x.reshape(hkv, b, g, x.shape[-1])
            .transpose(1, 0, 2, 3)
            .reshape(b * hkv, g, x.shape[-1])
        )

    return t(m), t(l), t(o)


def _merge_rows(m1, l1, o1, m2, l2, o2, b, hkv, g, d, dtype):
    """LSE-merge two [B*Hkv, G, *] partial sets -> [B, 1, H, D]."""
    from llm_consensus_tpu.ops.attention import merge_decode_partials

    out = merge_decode_partials(m1, l1, o1, m2, l2, o2)  # [B*Hkv, G, D]
    return out.reshape(b, 1, hkv * g, d).astype(dtype)


def _sp_shared_kernel(
    plen_ref, q_ref, k_ref, v_ref, m_o, l_o, o_o, m_s, l_s, acc_s, *,
    scale: float, blk: int,
):
    """Phase 1, dense bf16: one (kv-head, S-block) program over ROW 0's
    prefix slab with ALL rows' queries stacked.

    plen_ref: [1] prefix length (scalar prefetch — also drives the
    block remap that collapses DMAs past the prefix); q_ref:
    [1, B*G, D]; k_ref/v_ref: [1, blk, D] (row 0's slab, blocked);
    outputs m/l [Hkv, B*G, 1], o [Hkv, B*G, D] fp32 (written at each
    head's last block); scratch per (B*G) row.
    """
    j = pl.program_id(1)
    nblk = pl.num_programs(1)
    plen = plen_ref[0]
    rows, d = q_ref.shape[1], q_ref.shape[2]

    @pl.when(j == 0)
    def _init():
        m_s[...] = jnp.full((rows, 1), _NEG_INF, jnp.float32)
        l_s[...] = jnp.zeros((rows, 1), jnp.float32)
        acc_s[...] = jnp.zeros((rows, d), jnp.float32)

    @pl.when(j * blk < plen)
    def _fold():
        q = q_ref[0].astype(jnp.float32)  # [B*G, D]
        scores = jax.lax.dot_general(
            q,
            k_ref[0].astype(jnp.float32),
            dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale  # [B*G, blk]
        slot = j * blk + jax.lax.broadcasted_iota(jnp.int32, (1, blk), 1)
        scores = jnp.where(slot < plen, scores, _NEG_INF)
        _online_fold(m_s, l_s, acc_s, ..., scores, v_ref[0])

    @pl.when(j == nblk - 1)
    def _write():
        l = l_s[...]
        m_o[0] = m_s[...]
        l_o[0] = l
        o_o[0] = acc_s[...] / jnp.maximum(l, 1e-30)


def _sp_suffix_kernel(
    plen_ref, len_ref, q_ref, k_ref, v_ref, m_o, l_o, o_o, m_s, l_s, acc_s,
    *, scale: float, blk: int,
):
    """Phase 2, dense bf16: one (row x kv-head, S-block) program over the
    row's OWN suffix slots [prefix_len, valid). Blocks wholly inside the
    prefix (or past the fill) are skipped — paired with the wrapper's
    sentinel remap, the suffix pass costs O(suffix), which is the whole
    point of the split.

    plen_ref: [1]; len_ref: [B*Hkv] per-row fills; q_ref: [1, G, D];
    k_ref/v_ref: [1, blk, D]; outputs m/l [B*Hkv, G, 1], o
    [B*Hkv, G, D] fp32.
    """
    r = pl.program_id(0)
    j = pl.program_id(1)
    nblk = pl.num_programs(1)
    plen = plen_ref[0]
    valid = len_ref[r]
    g, d = q_ref.shape[1], q_ref.shape[2]

    @pl.when(j == 0)
    def _init():
        m_s[...] = jnp.full((g, 1), _NEG_INF, jnp.float32)
        l_s[...] = jnp.zeros((g, 1), jnp.float32)
        acc_s[...] = jnp.zeros((g, d), jnp.float32)

    @pl.when(((j + 1) * blk > plen) & (j * blk < valid))
    def _fold():
        q = q_ref[0].astype(jnp.float32)  # [G, D]
        scores = jax.lax.dot_general(
            q,
            k_ref[0].astype(jnp.float32),
            dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale  # [G, blk]
        slot = j * blk + jax.lax.broadcasted_iota(jnp.int32, (1, blk), 1)
        scores = jnp.where((slot >= plen) & (slot < valid), scores, _NEG_INF)
        _online_fold(m_s, l_s, acc_s, ..., scores, v_ref[0])

    @pl.when(j == nblk - 1)
    def _write():
        l = l_s[...]
        m_o[0] = m_s[...]
        l_o[0] = l
        o_o[0] = acc_s[...] / jnp.maximum(l, 1e-30)


def flash_decode_attention_shared_prefix(
    q: jnp.ndarray,
    k_cache: jnp.ndarray,
    v_cache: jnp.ndarray,
    valid_len: jnp.ndarray,
    prefix_len: jnp.ndarray,
    interpret: bool | None = None,
) -> jnp.ndarray:
    """Shared-prefix decode attention, dense bf16 cache (engine fan-out).

    q: [B, 1, H, D]; k_cache/v_cache: [B, max_len, Hkv, D]; valid_len:
    [B]; prefix_len: traced scalar — every row's slots [0, prefix_len)
    hold identical K/V (the shared-prefill precondition). Phase 1 reads
    only ROW 0's copy of that region; phase 2 reads each row's
    [prefix_len, valid) suffix blocks; merged exactly. Matches
    :func:`~llm_consensus_tpu.ops.attention.decode_attention_shared_prefix`
    (and therefore plain decode attention) wherever the precondition
    holds. No sliding-window support — callers fall back.
    """
    b, _, h, d = q.shape
    s = k_cache.shape[1]
    hkv = k_cache.shape[2]
    g = h // hkv
    if interpret is None:
        interpret = _interpret_default()
    scale = d**-0.5
    blk = _sp_block(s)
    nblk = s // blk

    kt = k_cache.transpose(0, 2, 1, 3).reshape(b * hkv, s, d)
    vt = v_cache.transpose(0, 2, 1, 3).reshape(b * hkv, s, d)
    q_sh = q.reshape(b, hkv, g, d).transpose(1, 0, 2, 3).reshape(hkv, b * g, d)
    q_row = q.reshape(b, hkv, g, d).reshape(b * hkv, g, d)
    plen = jnp.atleast_1d(prefix_len).astype(jnp.int32)
    lens = jnp.repeat(valid_len.astype(jnp.int32), hkv)

    def _shared_map(hi, j, plen):
        return (hi, jnp.where(j * blk < plen[0], j, 0), 0)

    m1, l1, o1 = pl.pallas_call(
        functools.partial(_sp_shared_kernel, scale=scale, blk=blk),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(hkv, nblk),
            in_specs=[
                pl.BlockSpec((1, b * g, d), lambda hi, j, plen: (hi, 0, 0)),
                pl.BlockSpec((1, blk, d), _shared_map),
                pl.BlockSpec((1, blk, d), _shared_map),
            ],
            out_specs=[
                pl.BlockSpec((1, b * g, 1), lambda hi, j, plen: (hi, 0, 0)),
                pl.BlockSpec((1, b * g, 1), lambda hi, j, plen: (hi, 0, 0)),
                pl.BlockSpec((1, b * g, d), lambda hi, j, plen: (hi, 0, 0)),
            ],
            scratch_shapes=[
                pltpu.VMEM((b * g, 1), jnp.float32),
                pltpu.VMEM((b * g, 1), jnp.float32),
                pltpu.VMEM((b * g, d), jnp.float32),
            ],
        ),
        out_shape=(
            jax.ShapeDtypeStruct((hkv, b * g, 1), jnp.float32),
            jax.ShapeDtypeStruct((hkv, b * g, 1), jnp.float32),
            jax.ShapeDtypeStruct((hkv, b * g, d), jnp.float32),
        ),
        interpret=interpret,
    )(plen, q_sh, kt[:hkv], vt[:hkv])

    def _suffix_map(r, j, plen, lens):
        live = ((j + 1) * blk > plen[0]) & (j * blk < lens[r])
        return (r, jnp.where(live, j, 0), 0)

    m2, l2, o2 = pl.pallas_call(
        functools.partial(_sp_suffix_kernel, scale=scale, blk=blk),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=(b * hkv, nblk),
            in_specs=[
                pl.BlockSpec((1, g, d), lambda r, j, plen, lens: (r, 0, 0)),
                pl.BlockSpec((1, blk, d), _suffix_map),
                pl.BlockSpec((1, blk, d), _suffix_map),
            ],
            out_specs=[
                pl.BlockSpec((1, g, 1), lambda r, j, plen, lens: (r, 0, 0)),
                pl.BlockSpec((1, g, 1), lambda r, j, plen, lens: (r, 0, 0)),
                pl.BlockSpec((1, g, d), lambda r, j, plen, lens: (r, 0, 0)),
            ],
            scratch_shapes=[
                pltpu.VMEM((g, 1), jnp.float32),
                pltpu.VMEM((g, 1), jnp.float32),
                pltpu.VMEM((g, d), jnp.float32),
            ],
        ),
        out_shape=(
            jax.ShapeDtypeStruct((b * hkv, g, 1), jnp.float32),
            jax.ShapeDtypeStruct((b * hkv, g, 1), jnp.float32),
            jax.ShapeDtypeStruct((b * hkv, g, d), jnp.float32),
        ),
        interpret=interpret,
    )(plen, lens, q_row, kt, vt)

    m1r, l1r, o1r = _partials_to_rows(m1, l1, o1, b, hkv, g)
    return _merge_rows(m1r, l1r, o1r, m2, l2, o2, b, hkv, g, d, q.dtype)


def _sp_shared_q8_kernel(
    plen_ref, q_ref, kq_ref, ks_ref, vq_ref, vs_ref, m_o, l_o, o_o,
    m_s, l_s, acc_s, *, scale: float, blk: int,
):
    """Phase 1, int8 head-major: as :func:`_sp_shared_kernel` with the
    per-slot dequant scales folded into scores/values (`_q8_attend`'s
    linear-dequant trick). kq_ref/vq_ref: [1, blk, D] int8;
    ks_ref/vs_ref: [1, 1, blk] f32 — row 0's slabs only."""
    j = pl.program_id(1)
    nblk = pl.num_programs(1)
    plen = plen_ref[0]
    rows, d = q_ref.shape[1], q_ref.shape[2]

    @pl.when(j == 0)
    def _init():
        m_s[...] = jnp.full((rows, 1), _NEG_INF, jnp.float32)
        l_s[...] = jnp.zeros((rows, 1), jnp.float32)
        acc_s[...] = jnp.zeros((rows, d), jnp.float32)

    @pl.when(j * blk < plen)
    def _fold():
        q = q_ref[0].astype(jnp.float32)
        scores = jax.lax.dot_general(
            q,
            kq_ref[0].astype(jnp.float32),
            dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * (ks_ref[0] * scale)  # [B*G, blk] * [1, blk]
        slot = j * blk + jax.lax.broadcasted_iota(jnp.int32, (1, blk), 1)
        scores = jnp.where(slot < plen, scores, _NEG_INF)
        _online_fold(
            m_s, l_s, acc_s, ..., scores, vq_ref[0], v_row_scale=vs_ref[0]
        )

    @pl.when(j == nblk - 1)
    def _write():
        l = l_s[...]
        m_o[0] = m_s[...]
        l_o[0] = l
        o_o[0] = acc_s[...] / jnp.maximum(l, 1e-30)


def _sp_suffix_q8_kernel(
    plen_ref, len_ref, q_ref, kq_ref, ks_ref, vq_ref, vs_ref, m_o, l_o, o_o,
    m_s, l_s, acc_s, *, scale: float, blk: int,
):
    """Phase 2, int8 head-major: as :func:`_sp_suffix_kernel` with
    dequant scales folded in."""
    r = pl.program_id(0)
    j = pl.program_id(1)
    nblk = pl.num_programs(1)
    plen = plen_ref[0]
    valid = len_ref[r]
    g, d = q_ref.shape[1], q_ref.shape[2]

    @pl.when(j == 0)
    def _init():
        m_s[...] = jnp.full((g, 1), _NEG_INF, jnp.float32)
        l_s[...] = jnp.zeros((g, 1), jnp.float32)
        acc_s[...] = jnp.zeros((g, d), jnp.float32)

    @pl.when(((j + 1) * blk > plen) & (j * blk < valid))
    def _fold():
        q = q_ref[0].astype(jnp.float32)
        scores = jax.lax.dot_general(
            q,
            kq_ref[0].astype(jnp.float32),
            dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * (ks_ref[0] * scale)
        slot = j * blk + jax.lax.broadcasted_iota(jnp.int32, (1, blk), 1)
        scores = jnp.where((slot >= plen) & (slot < valid), scores, _NEG_INF)
        _online_fold(
            m_s, l_s, acc_s, ..., scores, vq_ref[0], v_row_scale=vs_ref[0]
        )

    @pl.when(j == nblk - 1)
    def _write():
        l = l_s[...]
        m_o[0] = m_s[...]
        l_o[0] = l
        o_o[0] = acc_s[...] / jnp.maximum(l, 1e-30)


def flash_decode_attention_shared_prefix_q8(
    q: jnp.ndarray,
    k_q: jnp.ndarray,
    k_scale: jnp.ndarray,
    v_q: jnp.ndarray,
    v_scale: jnp.ndarray,
    valid_len: jnp.ndarray,
    prefix_len: jnp.ndarray,
    interpret: bool | None = None,
) -> jnp.ndarray:
    """Shared-prefix decode attention over the int8 head-major cache.

    q: [B, 1, H, D]; k_q/v_q: [B, Hkv, S, D] int8 (QuantKVCache layout —
    the per-(row, head) slab reshape is zero-copy); k_scale/v_scale:
    [B, Hkv, S] f32; valid_len: [B]; prefix_len: traced scalar. Same
    two-phase split as :func:`flash_decode_attention_shared_prefix`;
    HBM reads stay int8 + one f32 scale per slot.
    """
    b, _, h, d = q.shape
    hkv, s = k_q.shape[1], k_q.shape[2]
    g = h // hkv
    if interpret is None:
        interpret = _interpret_default()
    scale = d**-0.5
    blk = _sp_block(s)
    nblk = s // blk

    kq2 = k_q.reshape(b * hkv, s, d)
    vq2 = v_q.reshape(b * hkv, s, d)
    ks2 = k_scale.reshape(b * hkv, 1, s)
    vs2 = v_scale.reshape(b * hkv, 1, s)
    q_sh = q.reshape(b, hkv, g, d).transpose(1, 0, 2, 3).reshape(hkv, b * g, d)
    q_row = q.reshape(b * hkv, g, d)
    plen = jnp.atleast_1d(prefix_len).astype(jnp.int32)
    lens = jnp.repeat(valid_len.astype(jnp.int32), hkv)

    def _shared_map(hi, j, plen):
        return (hi, jnp.where(j * blk < plen[0], j, 0), 0)

    def _shared_scale_map(hi, j, plen):
        return (hi, 0, jnp.where(j * blk < plen[0], j, 0))

    m1, l1, o1 = pl.pallas_call(
        functools.partial(_sp_shared_q8_kernel, scale=scale, blk=blk),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(hkv, nblk),
            in_specs=[
                pl.BlockSpec((1, b * g, d), lambda hi, j, plen: (hi, 0, 0)),
                pl.BlockSpec((1, blk, d), _shared_map),
                pl.BlockSpec((1, 1, blk), _shared_scale_map),
                pl.BlockSpec((1, blk, d), _shared_map),
                pl.BlockSpec((1, 1, blk), _shared_scale_map),
            ],
            out_specs=[
                pl.BlockSpec((1, b * g, 1), lambda hi, j, plen: (hi, 0, 0)),
                pl.BlockSpec((1, b * g, 1), lambda hi, j, plen: (hi, 0, 0)),
                pl.BlockSpec((1, b * g, d), lambda hi, j, plen: (hi, 0, 0)),
            ],
            scratch_shapes=[
                pltpu.VMEM((b * g, 1), jnp.float32),
                pltpu.VMEM((b * g, 1), jnp.float32),
                pltpu.VMEM((b * g, d), jnp.float32),
            ],
        ),
        out_shape=(
            jax.ShapeDtypeStruct((hkv, b * g, 1), jnp.float32),
            jax.ShapeDtypeStruct((hkv, b * g, 1), jnp.float32),
            jax.ShapeDtypeStruct((hkv, b * g, d), jnp.float32),
        ),
        interpret=interpret,
    )(plen, q_sh, kq2[:hkv], ks2[:hkv], vq2[:hkv], vs2[:hkv])

    def _suffix_map(r, j, plen, lens):
        live = ((j + 1) * blk > plen[0]) & (j * blk < lens[r])
        return (r, jnp.where(live, j, 0), 0)

    def _suffix_scale_map(r, j, plen, lens):
        live = ((j + 1) * blk > plen[0]) & (j * blk < lens[r])
        return (r, 0, jnp.where(live, j, 0))

    m2, l2, o2 = pl.pallas_call(
        functools.partial(_sp_suffix_q8_kernel, scale=scale, blk=blk),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=(b * hkv, nblk),
            in_specs=[
                pl.BlockSpec((1, g, d), lambda r, j, plen, lens: (r, 0, 0)),
                pl.BlockSpec((1, blk, d), _suffix_map),
                pl.BlockSpec((1, 1, blk), _suffix_scale_map),
                pl.BlockSpec((1, blk, d), _suffix_map),
                pl.BlockSpec((1, 1, blk), _suffix_scale_map),
            ],
            out_specs=[
                pl.BlockSpec((1, g, 1), lambda r, j, plen, lens: (r, 0, 0)),
                pl.BlockSpec((1, g, 1), lambda r, j, plen, lens: (r, 0, 0)),
                pl.BlockSpec((1, g, d), lambda r, j, plen, lens: (r, 0, 0)),
            ],
            scratch_shapes=[
                pltpu.VMEM((g, 1), jnp.float32),
                pltpu.VMEM((g, 1), jnp.float32),
                pltpu.VMEM((g, d), jnp.float32),
            ],
        ),
        out_shape=(
            jax.ShapeDtypeStruct((b * hkv, g, 1), jnp.float32),
            jax.ShapeDtypeStruct((b * hkv, g, 1), jnp.float32),
            jax.ShapeDtypeStruct((b * hkv, g, d), jnp.float32),
        ),
        interpret=interpret,
    )(plen, lens, q_row, kq2, ks2, vq2, vs2)

    m1r, l1r, o1r = _partials_to_rows(m1, l1, o1, b, hkv, g)
    return _merge_rows(m1r, l1r, o1r, m2, l2, o2, b, hkv, g, d, q.dtype)


# -- paged variant: groups over the page pool -------------------------------


def _paged_shared_kernel(
    rep_ref, gp_ref, tbl_ref, gid_ref, q_ref, k_ref, v_ref, m_o, l_o, o_o,
    m_s, l_s, acc_s, *, scale: float,
):
    """Phase 1, paged: one (group, shared-page) program — every row's
    queries STACKED against the group's shared page run (read once per
    group via the representative row's table), non-members masked out.

    rep_ref/gp_ref: [Gm] representative row / shared-page count per
    group (scalar prefetch; gp == 0 for padding groups);
    tbl_ref: [B*P] flattened page table (consumed by the index map);
    gid_ref: [B, 1] VMEM group id per row (-1 = ungrouped); q_ref:
    [Hkv, B*G, D]; k_ref/v_ref: [1, pg, Hkv, D] — one pool page.
    Outputs m/l [Hkv, B*G, 1], o [Hkv, B*G, D] fp32, written once at
    the very last program. Scratch is per (head, row) and accumulates
    across ALL groups: each row belongs to at most one group, so its
    rows of the scratch only ever fold scores from that group's pages.
    """
    gi = pl.program_id(0)
    ji = pl.program_id(1)
    last = (gi == pl.num_programs(0) - 1) & (ji == pl.num_programs(1) - 1)
    hkv, rows, d = q_ref.shape
    bsz = gid_ref.shape[0]
    g = rows // bsz
    pg = k_ref.shape[1]

    @pl.when((gi == 0) & (ji == 0))
    def _init():
        m_s[...] = jnp.full((hkv, rows, 1), _NEG_INF, jnp.float32)
        l_s[...] = jnp.zeros((hkv, rows, 1), jnp.float32)
        acc_s[...] = jnp.zeros((hkv, rows, d), jnp.float32)

    @pl.when(ji < gp_ref[gi])
    def _fold_page():
        member = gid_ref[...] == gi  # [B, 1]
        mrow = jnp.broadcast_to(member, (bsz, g)).reshape(rows, 1)
        for head in range(hkv):  # static unroll over kv heads
            q = q_ref[head].astype(jnp.float32)  # [B*G, D]
            scores = jax.lax.dot_general(
                q,
                k_ref[0, :, head, :].astype(jnp.float32),
                dimension_numbers=(((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            ) * scale  # [B*G, pg]
            scores = jnp.where(mrow, scores, _NEG_INF)
            _online_fold(
                m_s, l_s, acc_s, head, scores, v_ref[0, :, head, :]
            )

    @pl.when(last)
    def _write():
        l = l_s[...]
        m_o[...] = m_s[...]
        l_o[...] = l
        o_o[...] = acc_s[...] / jnp.maximum(l, 1e-30)


def _paged_suffix_kernel(
    start_ref, tbl_ref, len_ref, q_ref, k_ref, v_ref, m_o, l_o, o_o,
    m_s, l_s, acc_s, *, scale: float,
):
    """Phase 2, paged: the per-row page walk of
    :func:`_paged_decode_kernel`, restricted to the row's OWN suffix
    pages (pages wholly inside the shared run are skipped — paired with
    the wrapper's sentinel remap their DMAs collapse) and emitting
    (m, l, o) partials instead of the final normalize.

    start_ref: [B] first unshared token per row (0 = whole row, the
    ungrouped case); len_ref: [B]; q_ref: [1, Hkv, G, D];
    k_ref/v_ref: [1, pg, Hkv, D]; outputs m/l [B, Hkv*G, 1],
    o [B, Hkv, G, D].
    """
    b = pl.program_id(0)
    j = pl.program_id(1)
    n_pages = pl.num_programs(1)
    _, pg, hkv, d = k_ref.shape
    g = q_ref.shape[2]

    @pl.when(j == 0)
    def _init():
        m_s[...] = jnp.full((hkv * g, 1), _NEG_INF, jnp.float32)
        l_s[...] = jnp.zeros((hkv * g, 1), jnp.float32)
        acc_s[...] = jnp.zeros((hkv * g, d), jnp.float32)

    start = start_ref[b]
    valid = len_ref[b]

    @pl.when(((j + 1) * pg > start) & (j * pg < valid))
    def _fold_page():
        slot = j * pg + jax.lax.broadcasted_iota(jnp.int32, (1, pg), 1)
        in_range = (slot >= start) & (slot < valid)
        for head in range(hkv):  # static unroll over kv heads
            hs = slice(head * g, (head + 1) * g)
            q = q_ref[0, head].astype(jnp.float32)  # [G, D]
            scores = jax.lax.dot_general(
                q,
                k_ref[0, :, head, :].astype(jnp.float32),
                dimension_numbers=(((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            ) * scale  # [G, pg]
            scores = jnp.where(in_range, scores, _NEG_INF)
            _online_fold(
                m_s, l_s, acc_s, hs, scores, v_ref[0, :, head, :]
            )

    @pl.when(j == n_pages - 1)
    def _write():
        l = l_s[...]
        m_o[0] = m_s[...]
        l_o[0] = l
        o_o[0] = (acc_s[...] / jnp.maximum(l, 1e-30)).reshape(hkv, g, d)


def paged_decode_attention_grouped(
    q: jnp.ndarray,
    k_pool: jnp.ndarray,
    v_pool: jnp.ndarray,
    page_table: jnp.ndarray,
    valid_len: jnp.ndarray,
    group_id: jnp.ndarray,
    group_rep: jnp.ndarray,
    group_pages: jnp.ndarray,
    shared_start: jnp.ndarray,
    interpret: bool | None = None,
) -> jnp.ndarray:
    """Group-aware paged decode attention (serving hot path).

    q: [B, H, D]; k_pool/v_pool: [n_pages, page, Hkv, D]; page_table:
    [B, P]; valid_len: [B]. Group metadata (built by
    :class:`~llm_consensus_tpu.models.paged_cache.GroupTracker` from the
    PrefixRegistry's shared page runs, all int32):

    - group_id [B]: group per row, -1 for ungrouped rows;
    - group_rep [Gm]: a member row whose table phase 1 walks;
    - group_pages [Gm]: pages in the group's shared run (0 = padding);
    - shared_start [B]: tokens phase 1 covers for the row (page-aligned;
      0 for ungrouped rows, whose phase 2 then walks the whole row).

    Phase 1 streams each group's shared run ONCE for all members
    (the ungrouped kernel streams it once per member — the N*S -> S +
    N*suffix KV-bandwidth reduction this family exists for); phase 2
    walks per-row suffix pages only; exact LSE merge. Grouped and
    ungrouped rows coexist: a row with group_id == -1 gets its entire
    result from phase 2. Output-equal to
    :func:`paged_decode_attention` (same masking semantics, same
    arithmetic, reordered reductions). No sliding-window support —
    callers fall back to the ungrouped kernel for windowed configs.
    """
    b, h, d = q.shape
    n_pages, pg, hkv, _ = k_pool.shape
    p_per = page_table.shape[1]
    g = h // hkv
    gm = group_rep.shape[0]
    if interpret is None:
        interpret = _interpret_default()
    scale = d**-0.5

    tbl = page_table.reshape(-1).astype(jnp.int32)
    lens = valid_len.astype(jnp.int32)
    rep = group_rep.astype(jnp.int32)
    gpages = group_pages.astype(jnp.int32)
    start = shared_start.astype(jnp.int32)
    gid_v = group_id.astype(jnp.int32).reshape(b, 1)
    q_sh = q.reshape(b, hkv, g, d).transpose(1, 0, 2, 3).reshape(hkv, b * g, d)
    q4 = q.reshape(b, hkv, g, d)

    def _shared_page_map(gi, ji, rep, gpages, tbl):
        page = tbl[rep[gi] * p_per + ji]
        return (jnp.where(ji < gpages[gi], page, 0), 0, 0, 0)

    m1, l1, o1 = pl.pallas_call(
        functools.partial(_paged_shared_kernel, scale=scale),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=3,  # rep, gpages, tbl
            grid=(gm, p_per),
            in_specs=[
                pl.BlockSpec(
                    (b, 1), lambda gi, ji, rep, gpages, tbl: (0, 0)
                ),
                pl.BlockSpec(
                    (hkv, b * g, d),
                    lambda gi, ji, rep, gpages, tbl: (0, 0, 0),
                ),
                pl.BlockSpec((1, pg, hkv, d), _shared_page_map),
                pl.BlockSpec((1, pg, hkv, d), _shared_page_map),
            ],
            out_specs=[
                pl.BlockSpec(
                    (hkv, b * g, 1),
                    lambda gi, ji, rep, gpages, tbl: (0, 0, 0),
                ),
                pl.BlockSpec(
                    (hkv, b * g, 1),
                    lambda gi, ji, rep, gpages, tbl: (0, 0, 0),
                ),
                pl.BlockSpec(
                    (hkv, b * g, d),
                    lambda gi, ji, rep, gpages, tbl: (0, 0, 0),
                ),
            ],
            scratch_shapes=[
                pltpu.VMEM((hkv, b * g, 1), jnp.float32),
                pltpu.VMEM((hkv, b * g, 1), jnp.float32),
                pltpu.VMEM((hkv, b * g, d), jnp.float32),
            ],
        ),
        out_shape=(
            jax.ShapeDtypeStruct((hkv, b * g, 1), jnp.float32),
            jax.ShapeDtypeStruct((hkv, b * g, 1), jnp.float32),
            jax.ShapeDtypeStruct((hkv, b * g, d), jnp.float32),
        ),
        interpret=interpret,
    )(rep, gpages, tbl, gid_v, q_sh, k_pool, v_pool)

    def _suffix_page_map(bi, ji, start, tbl, lens):
        live = ((ji + 1) * pg > start[bi]) & (ji * pg < lens[bi])
        page = tbl[bi * p_per + ji]
        return (jnp.where(live, page, 0), 0, 0, 0)

    m2, l2, o2 = pl.pallas_call(
        functools.partial(_paged_suffix_kernel, scale=scale),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=3,  # start, tbl, lens
            grid=(b, p_per),
            in_specs=[
                pl.BlockSpec(
                    (1, hkv, g, d),
                    lambda bi, ji, start, tbl, lens: (bi, 0, 0, 0),
                ),
                pl.BlockSpec((1, pg, hkv, d), _suffix_page_map),
                pl.BlockSpec((1, pg, hkv, d), _suffix_page_map),
            ],
            out_specs=[
                pl.BlockSpec(
                    (1, hkv * g, 1),
                    lambda bi, ji, start, tbl, lens: (bi, 0, 0),
                ),
                pl.BlockSpec(
                    (1, hkv * g, 1),
                    lambda bi, ji, start, tbl, lens: (bi, 0, 0),
                ),
                pl.BlockSpec(
                    (1, hkv, g, d),
                    lambda bi, ji, start, tbl, lens: (bi, 0, 0, 0),
                ),
            ],
            scratch_shapes=[
                pltpu.VMEM((hkv * g, 1), jnp.float32),
                pltpu.VMEM((hkv * g, 1), jnp.float32),
                pltpu.VMEM((hkv * g, d), jnp.float32),
            ],
        ),
        out_shape=(
            jax.ShapeDtypeStruct((b, hkv * g, 1), jnp.float32),
            jax.ShapeDtypeStruct((b, hkv * g, 1), jnp.float32),
            jax.ShapeDtypeStruct((b, hkv, g, d), jnp.float32),
        ),
        interpret=interpret,
    )(start, tbl, lens, q4, k_pool, v_pool)

    from llm_consensus_tpu.ops.attention import merge_decode_partials

    m1r = m1.reshape(hkv, b, g, 1).transpose(1, 0, 2, 3)
    l1r = l1.reshape(hkv, b, g, 1).transpose(1, 0, 2, 3)
    o1r = o1.reshape(hkv, b, g, d).transpose(1, 0, 2, 3)
    m2r = m2.reshape(b, hkv, g, 1)
    l2r = l2.reshape(b, hkv, g, 1)
    out = merge_decode_partials(m1r, l1r, o1r, m2r, l2r, o2)
    return out.reshape(b, h, d).astype(q.dtype)
