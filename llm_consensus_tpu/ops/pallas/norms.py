"""Fused RMSNorm kernel.

Twin of the jnp reference :func:`llm_consensus_tpu.ops.norms.rms_norm`:
one VMEM pass computes the fp32 mean-square, rsqrt, and the weighted
scale — no intermediate arrays in HBM.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _rms_kernel(x_ref, w_ref, o_ref, *, eps: float):
    x = x_ref[:].astype(jnp.float32)  # [blk, D]
    ms = jnp.mean(x * x, axis=-1, keepdims=True)
    inv = jax.lax.rsqrt(ms + eps)
    w = w_ref[:].astype(jnp.float32)  # [1, D] (2-D: Mosaic rejects rank-1 blocks)
    o_ref[:] = (x * inv * w).astype(o_ref.dtype)


def fused_rms_norm(
    x: jnp.ndarray,
    weight: jnp.ndarray,
    eps: float = 1e-5,
    blk: int = 256,
    interpret: bool | None = None,
) -> jnp.ndarray:
    """RMSNorm over the last axis. x: [..., D]; weight: [D]."""
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    orig_shape = x.shape
    d = orig_shape[-1]
    x2 = x.reshape(-1, d)
    n = x2.shape[0]
    blk = min(blk, n)
    pad = (-n) % blk
    if pad:
        x2 = jnp.pad(x2, ((0, pad), (0, 0)))

    out = pl.pallas_call(
        functools.partial(_rms_kernel, eps=eps),
        out_shape=jax.ShapeDtypeStruct(x2.shape, x.dtype),
        grid=(x2.shape[0] // blk,),
        in_specs=[
            pl.BlockSpec((blk, d), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, d), lambda i: (0, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec(
            (blk, d), lambda i: (i, 0), memory_space=pltpu.VMEM
        ),
        interpret=interpret,
    )(x2, weight.reshape(1, d))
    if pad:
        out = out[:n]
    return out.reshape(orig_shape)
