"""Fused int8-weight matmul kernel (Pallas/Mosaic).

Why a kernel: XLA:TPU dots read *materialized* operand buffers, so the
weight-only int8 path (``x @ dequantize(w)``) round-trips a bf16 copy of
the weights through HBM — and inside the token-decode ``lax.scan`` XLA
hoists the loop-invariant dequant entirely, making int8 decode no faster
than bf16. This kernel loads int8 tiles straight into VMEM, converts
in-register, and feeds the MXU — per decode step the weights cost half
the HBM traffic of bf16, which is the whole point of
:mod:`llm_consensus_tpu.ops.quant`.

Scope: the M dimension (batch rows) must be small enough that ``x`` fits
VMEM whole — exactly the decode/GEMV regime where weight bandwidth
dominates. Callers fall back to the XLA path for prefill-sized M (there
the dequant is amortized over S columns and XLA's behavior is fine).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _interpret_default() -> bool:
    return jax.default_backend() != "tpu"


def _pick_block(n: int, target: int = 512, align: int = 128) -> int | None:
    """Largest divisor of n that is a multiple of ``align`` and <= target."""
    best = None
    blk = align
    while blk <= min(n, target):
        if n % blk == 0:
            best = blk
        blk += align
    return best


def _qmm_kernel(x_ref, w_ref, s_ref, o_ref):
    """One N-block program: o = (x @ bf16(w_int8)) * scale.

    x_ref: [M, K] bf16; w_ref: [K, blk_n] int8; s_ref: [1, blk_n] f32;
    o_ref: [M, blk_n].
    """
    w = w_ref[...].astype(jnp.bfloat16)  # in-register dequant (int8 HBM read)
    acc = jax.lax.dot_general(
        x_ref[...],
        w,
        dimension_numbers=(((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    o_ref[...] = (acc * s_ref[...]).astype(o_ref.dtype)


def quant_matmul_2d(
    x: jnp.ndarray,
    w_q: jnp.ndarray,
    scale: jnp.ndarray,
    out_dtype=None,
    interpret: bool | None = None,
) -> jnp.ndarray:
    """x [M, K] x int8 w_q [K, N] (per-column ``scale`` [1, N]) -> [M, N].

    Raises ValueError when shapes don't tile (callers pre-check with
    :func:`quant_matmul_supported`).
    """
    m, k = x.shape
    k2, n = w_q.shape
    if k != k2:
        raise ValueError(f"contraction mismatch {k} vs {k2}")
    blk_n = _pick_block(n, target=_blk_target(k))
    if blk_n is None:
        raise ValueError(
            f"N={n} (K={k}) has no 128-aligned block within the VMEM budget"
        )
    if interpret is None:
        interpret = _interpret_default()
    out_dtype = out_dtype or x.dtype

    return pl.pallas_call(
        functools.partial(_qmm_kernel),
        out_shape=jax.ShapeDtypeStruct((m, n), out_dtype),
        grid=(n // blk_n,),
        in_specs=[
            pl.BlockSpec((m, k), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((k, blk_n), lambda i: (0, i), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, blk_n), lambda i: (0, i), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec(
            (m, blk_n), lambda i: (0, i), memory_space=pltpu.VMEM
        ),
        interpret=interpret,
    )(x.astype(jnp.bfloat16), w_q, scale.astype(jnp.float32))


# VMEM budget heuristic (~16 MB/core): x + one int8 weight tile
# (double-buffered by the grid pipeline) + out block must fit.
_MAX_M = 256
_MAX_X_BYTES = 4 * 1024 * 1024
_MAX_W_TILE_BYTES = 4 * 1024 * 1024  # int8 K x blk_n, x2 for double-buffer


def _blk_target(k: int) -> int:
    """Largest 128-multiple blk_n keeping the K x blk_n int8 tile in
    budget (capped at 512 — wider tiles stop helping)."""
    by_vmem = (_MAX_W_TILE_BYTES // max(k, 1)) // 128 * 128
    return max(128, min(512, by_vmem))


def quant_matmul_supported(m: int, k: int, n: int) -> bool:
    return (
        m <= _MAX_M
        and m * k * 2 <= _MAX_X_BYTES
        and n % 128 == 0
        and k % 128 == 0
        and k * 128 <= _MAX_W_TILE_BYTES  # smallest tile must fit
        and _pick_block(n, target=_blk_target(k)) is not None
    )


# ---------------------------------------------------------------------------
# int4 (packed-nibble) variant
#
# STATUS: numerics verified (interpret mode, tests/test_quant.py); the
# small-shape unpack lowers and runs on real TPU, but full-size compiles
# (K=2048, N=32000) have shown pathological Mosaic compile times on this
# environment's toolchain. The kernel is therefore OPT-IN via
# ops.quant.set_kernel4_enabled(True) — the default int4 path is the jnp
# unpack + XLA dot (capacity win, no decode-bandwidth win).
# ---------------------------------------------------------------------------


def _q4mm_kernel(x_ref, w_ref, s_ref, o_ref):
    """One N-block program: o = (x @ bf16(unpack4(w))) * scale.

    x_ref: [M, K] bf16; w_ref: [K/2, blk_n] int8 (two nibbles/byte,
    low nibbles = rows [0, K/2), high = [K/2, K) — the
    ops.quant.Quantized4Tensor contract); s_ref: [1, blk_n] f32.
    Bit ops run in int32 — int8 shifts don't legalize on Mosaic — and
    the K split becomes TWO dots (x_low @ low + x_high @ high) instead
    of a sublane concat of the unpacked halves.
    """
    k2 = w_ref.shape[0]
    w32 = w_ref[...].astype(jnp.int32)
    low = ((w32 & 0xF) - ((w32 & 0x8) << 1)).astype(jnp.bfloat16)
    nib = (w32 >> 4) & 0xF
    high = (nib - ((nib & 0x8) << 1)).astype(jnp.bfloat16)
    dn = (((1,), (0,)), ((), ()))
    acc = jax.lax.dot_general(
        x_ref[:, :k2], low, dn, preferred_element_type=jnp.float32
    ) + jax.lax.dot_general(
        x_ref[:, k2:], high, dn, preferred_element_type=jnp.float32
    )
    o_ref[...] = (acc * s_ref[...]).astype(o_ref.dtype)


def quant4_matmul_2d(
    x: jnp.ndarray,
    w_q: jnp.ndarray,
    scale: jnp.ndarray,
    out_dtype=None,
    interpret: bool | None = None,
) -> jnp.ndarray:
    """x [M, K] x packed-int4 w_q [K/2, N] (per-column ``scale`` [1, N])
    -> [M, N]."""
    m, k = x.shape
    k2, n = w_q.shape
    if k != 2 * k2:
        raise ValueError(f"contraction mismatch {k} vs packed 2*{k2}")
    blk_n = _pick_block(n, target=_blk4_target(k))
    if blk_n is None:
        raise ValueError(
            f"N={n} (K={k}) has no 128-aligned block within the VMEM budget"
        )
    if interpret is None:
        interpret = _interpret_default()
    out_dtype = out_dtype or x.dtype

    return pl.pallas_call(
        _q4mm_kernel,
        out_shape=jax.ShapeDtypeStruct((m, n), out_dtype),
        grid=(n // blk_n,),
        in_specs=[
            pl.BlockSpec((m, k), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec(
                (k2, blk_n), lambda i: (0, i), memory_space=pltpu.VMEM
            ),
            pl.BlockSpec((1, blk_n), lambda i: (0, i), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec(
            (m, blk_n), lambda i: (0, i), memory_space=pltpu.VMEM
        ),
        interpret=interpret,
    )(x.astype(jnp.bfloat16), w_q, scale.astype(jnp.float32))


def _blk4_target(k: int) -> int:
    """blk_n budget for int4: the unpacked bf16 tile (K x blk_n x 2B) is
    4x the packed bytes, so budget against THAT."""
    by_vmem = (_MAX_W_TILE_BYTES // max(2 * k, 1)) // 128 * 128
    return max(128, min(512, by_vmem))


def quant4_matmul_supported(m: int, k: int, n: int) -> bool:
    return (
        m <= _MAX_M
        and m * k * 2 <= _MAX_X_BYTES
        and k % 2 == 0
        and n % 128 == 0
        and (k // 2) % 8 == 0  # packed sublane tiling
        and k % 128 == 0
        and 2 * k * 128 <= _MAX_W_TILE_BYTES  # smallest unpacked tile
        and _pick_block(n, target=_blk4_target(k)) is not None
    )


# ---------------------------------------------------------------------------
# Stacked-weight variant: the layer index rides scalar prefetch
# ---------------------------------------------------------------------------


def _qmm_stacked_kernel(l_ref, x_ref, w_ref, s_ref, o_ref):
    """One N-block program against the [L, K, N] stack.

    l_ref: [1] scalar-prefetch layer index (consumed by the index_maps);
    x_ref: [M, K] bf16; w_ref: [1, K, blk_n] int8 (this layer's tile);
    s_ref: [1, 1, blk_n] f32; o_ref: [M, blk_n].
    """
    w = w_ref[0].astype(jnp.bfloat16)
    acc = jax.lax.dot_general(
        x_ref[...],
        w,
        dimension_numbers=(((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    o_ref[...] = (acc * s_ref[0]).astype(o_ref.dtype)


def quant_matmul_stacked(
    x: jnp.ndarray,
    w_q: jnp.ndarray,
    scale: jnp.ndarray,
    layer: jnp.ndarray,
    out_dtype=None,
    interpret: bool | None = None,
) -> jnp.ndarray:
    """x [M, K] x int8 w_q[layer] from the stacked [L, K, N] buffer.

    Inside the token-decode layer scan, a sliced per-layer weight must
    be MATERIALIZED before it can feed ``quant_matmul_2d`` (Pallas
    operands are whole buffers) — XLA copies every layer's int8 weights
    every step. Here the STACK is the operand and the traced ``layer``
    index rides scalar prefetch into the BlockSpec index_maps, so Mosaic
    DMAs each [K, blk_n] tile straight from the resident stacked buffer:
    zero copies, same arithmetic as :func:`quant_matmul_2d`.
    """
    m, k = x.shape
    n_layers, k2, n = w_q.shape
    if k != k2:
        raise ValueError(f"contraction mismatch {k} vs {k2}")
    blk_n = _pick_block(n, target=_blk_target(k))
    if blk_n is None:
        raise ValueError(
            f"N={n} (K={k}) has no 128-aligned block within the VMEM budget"
        )
    if interpret is None:
        interpret = _interpret_default()
    out_dtype = out_dtype or x.dtype

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(n // blk_n,),
        in_specs=[
            pl.BlockSpec((m, k), lambda i, l: (0, 0)),
            pl.BlockSpec((1, k, blk_n), lambda i, l: (l[0], 0, i)),
            pl.BlockSpec((1, 1, blk_n), lambda i, l: (l[0], 0, i)),
        ],
        out_specs=pl.BlockSpec((m, blk_n), lambda i, l: (0, i)),
    )
    return pl.pallas_call(
        _qmm_stacked_kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((m, n), out_dtype),
        interpret=interpret,
    )(
        jnp.atleast_1d(layer).astype(jnp.int32),
        x.astype(jnp.bfloat16),
        w_q,
        scale.astype(jnp.float32),
    )
