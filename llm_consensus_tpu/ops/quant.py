"""Weight-only int8 quantization (per-output-channel, symmetric).

Decode on TPU is HBM-bandwidth-bound: every generated token re-reads the
full weight set, so halving weight bytes nearly halves the per-token
latency floor. This module stores each large matmul weight as an int8
tensor plus a per-output-channel fp32 scale; the dequantize (convert +
multiply) happens on-chip and XLA fuses it into the consumer matmul's
operand — HBM sees only int8 + scales. (The reference has no local
compute at all to quantize — its model calls are remote HTTPS,
``src/main.rs:82-86``; this is part of the TPU build's own perf work
toward BASELINE.json's >=1k candidate-tokens/sec/chip floor.)

Inference-only: quantized params are not differentiable (training keeps
bf16 masters).
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp

# Weight leaves that get quantized, with the axis index of the
# *contraction* (input) dimension in the stacked [L, ...] layout from
# ``init_params`` (llm_consensus_tpu.models.transformer). Scales keep
# that axis as size 1 (keepdims) so ranks — and therefore the sharding
# rules in parallel/partitioning.py — are unchanged.
_QUANT_AXES_DENSE = {
    "wq": 1,
    "wk": 1,
    "wv": 1,
    "wo": 1,
    "w_gate": 1,
    "w_up": 1,
    "w_down": 1,
}
_QUANT_AXES_MOE = {"w_gate": 2, "w_up": 2, "w_down": 2}


@jax.tree_util.register_dataclass
@dataclass
class QuantizedTensor:
    """int8 weight + fp32 per-output-channel scale (keepdims layout)."""

    q: jnp.ndarray  # int8, same shape as the original weight
    scale: jnp.ndarray  # float32, original shape with contraction dim = 1

    @property
    def shape(self):
        return self.q.shape

    @property
    def ndim(self):
        return self.q.ndim


def quantize_tensor(w: jnp.ndarray, axis: int) -> QuantizedTensor:
    """Symmetric per-channel int8: q = round(w / s), s = amax/127."""
    w32 = w.astype(jnp.float32)
    amax = jnp.max(jnp.abs(w32), axis=axis, keepdims=True)
    scale = jnp.maximum(amax, 1e-8) / 127.0
    q = jnp.clip(jnp.round(w32 / scale), -127, 127).astype(jnp.int8)
    return QuantizedTensor(q=q, scale=scale)


def dequantize(qt: QuantizedTensor, dtype=jnp.bfloat16) -> jnp.ndarray:
    """Materialize the bf16 weight on-chip (fused into the consumer)."""
    return qt.q.astype(dtype) * qt.scale.astype(dtype)


@jax.tree_util.register_dataclass
@dataclass
class Quantized4Tensor:
    """int4 weight (two nibbles per int8 byte) + fp32 per-channel scale.

    Packing contract: the CONTRACTION axis is always the second-to-last
    axis of the logical weight (true for every quantized leaf layout:
    dense [L, K, N], MoE [L, E, K, F], lm_head [K, N]); rows [0, K/2)
    live in the low nibbles and rows [K/2, K) in the high nibbles, so
    ``q``'s contraction dim is K/2 and unpack is a concat — no
    per-element interleave. Halves weight HBM bytes vs int8 (decode's
    bandwidth floor) at int4 precision (symmetric, amax/7).
    """

    q: jnp.ndarray  # int8 carrying 2x int4; contraction dim halved
    scale: jnp.ndarray  # float32, logical shape with contraction dim = 1

    @property
    def shape(self):  # logical (unpacked) shape
        s = list(self.q.shape)
        s[-2] *= 2
        return tuple(s)

    @property
    def ndim(self):
        return self.q.ndim


def quantize_tensor4(w: jnp.ndarray, axis: int) -> Quantized4Tensor:
    """Symmetric per-channel int4: q = round(w/s) in [-8, 7], s = amax/7.

    ``axis`` must be the second-to-last axis (the packing contract) and
    even-sized.
    """
    if axis % w.ndim != w.ndim - 2:
        raise ValueError(
            f"int4 packs along axis -2; got axis {axis} for rank {w.ndim}"
        )
    k = w.shape[axis]
    if k % 2:
        raise ValueError(f"contraction dim {k} must be even for int4")
    w32 = w.astype(jnp.float32)
    amax = jnp.max(jnp.abs(w32), axis=axis, keepdims=True)
    scale = jnp.maximum(amax, 1e-8) / 7.0
    q = jnp.clip(jnp.round(w32 / scale), -8, 7).astype(jnp.int32)
    low, high = jnp.split(q, 2, axis=axis)
    packed = ((low & 0xF) | ((high & 0xF) << 4)).astype(jnp.int8)
    return Quantized4Tensor(q=packed, scale=scale)


def unpack4(packed: jnp.ndarray, dtype=jnp.bfloat16) -> jnp.ndarray:
    """Nibbles -> values in [-8, 7], restoring the logical contraction
    dim (int32 bit ops — int8 shifts don't legalize on Mosaic)."""
    w32 = packed.astype(jnp.int32)
    low = (w32 & 0xF) - ((w32 & 0x8) << 1)
    nib = (w32 >> 4) & 0xF
    high = nib - ((nib & 0x8) << 1)
    return jnp.concatenate([low, high], axis=-2).astype(dtype)


def dequantize4(qt: Quantized4Tensor, dtype=jnp.bfloat16) -> jnp.ndarray:
    return unpack4(qt.q, dtype) * qt.scale.astype(dtype)


def maybe_dequantize(leaf, dtype=jnp.bfloat16):
    """Pass-through for plain arrays; dequantize quantized leaves."""
    if isinstance(leaf, QuantizedTensor):
        return dequantize(leaf, dtype)
    if isinstance(leaf, Quantized4Tensor):
        return dequantize4(leaf, dtype)
    return leaf


# Kernel override: None = auto (kernel on single-chip TPU); True/False
# forces. Settable by tests and by bench.py's no-Pallas/fallback modes —
# without it a quant_matmul lowering regression would be unreachable by
# any fallback (this is the only gate on the kernel).
_FORCE_KERNEL: bool | None = None


def set_kernel_enabled(enabled: bool | None) -> None:
    """Force the fused int8 kernel on/off; None restores auto-detect."""
    global _FORCE_KERNEL
    _FORCE_KERNEL = enabled


# The int4 kernel is OPT-IN only (no auto-detect): its nibble-unpack bit
# ops have shown pathological Mosaic compile times on some toolchain
# versions, and a wedged compile service is worse than the jnp fallback
# (which still stores int4 in HBM — capacity win — but lets XLA
# materialize the dequant, losing the bandwidth win inside scan).
_FORCE_KERNEL4: bool = False


def set_kernel4_enabled(enabled: bool) -> None:
    """Enable the fused int4 matmul kernel (verify it compiles on your
    jax/libtpu first — see ops/pallas/quant_matmul.py)."""
    global _FORCE_KERNEL4
    _FORCE_KERNEL4 = enabled


def _use_kernel4() -> bool:
    return (
        _FORCE_KERNEL4
        and jax.default_backend() == "tpu"
        and jax.device_count() == 1
    )


def _use_kernel() -> bool:
    if _FORCE_KERNEL is not None:
        return _FORCE_KERNEL
    # Single-chip TPU only: pallas_call is opaque to GSPMD, so on a
    # multi-device mesh the kernel would force TP/EP-sharded weights to
    # be all-gathered — the XLA dequant fallback shards fine there.
    return jax.default_backend() == "tpu" and jax.device_count() == 1


def _try_kernel_matmul(x, leaf, out_dtype):
    """Shared fused-kernel dispatch for int8/int4 weights.

    Returns the kernel result, or None when the kernel is gated off or
    the shapes don't tile (caller falls back to dequant + XLA dot).
    """
    if leaf.q.ndim != 2:
        return None
    if isinstance(leaf, QuantizedTensor):
        if not _use_kernel():
            return None
        from llm_consensus_tpu.ops.pallas.quant_matmul import (
            quant_matmul_2d as kernel,
        )
        from llm_consensus_tpu.ops.pallas.quant_matmul import (
            quant_matmul_supported as supported,
        )

        k = leaf.q.shape[0]
    else:
        if not _use_kernel4():
            return None
        from llm_consensus_tpu.ops.pallas.quant_matmul import (
            quant4_matmul_2d as kernel,
        )
        from llm_consensus_tpu.ops.pallas.quant_matmul import (
            quant4_matmul_supported as supported,
        )

        k = 2 * leaf.q.shape[0]  # logical contraction dim (packed)
    n = leaf.q.shape[1]
    lead = x.shape[:-1]
    m = 1
    for s in lead:
        m *= s
    if not supported(m, k, n):
        return None
    out = kernel(x.reshape(m, k), leaf.q, leaf.scale, out_dtype=out_dtype)
    return out.reshape(*lead, n)


@dataclass
class StackedQuant:
    """Trace-local lazy view of one layer of a stacked quantized weight.

    Built by the layer scan (``models.transformer._run_layers``) instead
    of slicing the [L, K, N] stack per iteration: a sliced operand to a
    Pallas kernel must be materialized (XLA copies the whole layer's
    weights every decode step), but the stacked kernel
    (:func:`llm_consensus_tpu.ops.pallas.quant_matmul.quant_matmul_stacked`)
    reads its tiles straight out of the resident stack via a
    scalar-prefetched layer index. Not a pytree — it never crosses a
    jit boundary; :func:`matmul` consumes it in-trace.
    """

    full: QuantizedTensor  # q [L, K, N], scale [L, 1, N]
    layer: jnp.ndarray  # traced scalar int32

    def sliced(self) -> QuantizedTensor:
        return QuantizedTensor(
            q=jax.lax.dynamic_index_in_dim(
                self.full.q, self.layer, 0, keepdims=False
            ),
            scale=jax.lax.dynamic_index_in_dim(
                self.full.scale, self.layer, 0, keepdims=False
            ),
        )


def _try_kernel_matmul_stacked(x, leaf: StackedQuant, out_dtype):
    if not _use_kernel():
        return None
    from llm_consensus_tpu.ops.pallas.quant_matmul import (
        quant_matmul_stacked,
        quant_matmul_supported,
    )

    _, k, n = leaf.full.q.shape
    lead = x.shape[:-1]
    m = 1
    for s in lead:
        m *= s
    if not quant_matmul_supported(m, k, n):
        return None
    out = quant_matmul_stacked(
        x.reshape(m, k),
        leaf.full.q,
        leaf.full.scale,
        leaf.layer,
        out_dtype=out_dtype,
    )
    return out.reshape(*lead, n)


def matmul(x: jnp.ndarray, leaf, out_dtype=None) -> jnp.ndarray:
    """``x [..., K] @ leaf [K, N]`` — quantization-aware.

    Plain arrays use the regular XLA dot. QuantizedTensor weights use the
    fused Pallas int8 kernel in the single-chip decode/GEMV regime
    (small M), where XLA's materialize-the-dequant behavior would
    otherwise erase the int8 bandwidth win (see
    ops/pallas/quant_matmul.py); other shapes and sharded runs fall back
    to dequant + XLA dot. ``StackedQuant`` views additionally skip the
    per-layer slice materialization inside the decode layer scan.
    """
    if isinstance(leaf, StackedQuant):
        out = _try_kernel_matmul_stacked(x, leaf, out_dtype)
        if out is not None:
            return out
        leaf = leaf.sliced()  # XLA fuses the slice into the dequant+dot
    if isinstance(leaf, (QuantizedTensor, Quantized4Tensor)):
        out = _try_kernel_matmul(x, leaf, out_dtype)
        if out is not None:
            return out
        w = maybe_dequantize(leaf, x.dtype)
    else:
        w = leaf
    if out_dtype is not None:
        return jnp.einsum(
            "...k,kn->...n", x, w, preferred_element_type=out_dtype
        )
    return x @ w


def quantize_params(
    params: dict, *, quantize_lm_head: bool = True, bits: int = 8
) -> dict:
    """Quantize the large matmul weights of an ``init_params`` tree.

    Norms, biases, the router (tiny), and the embedding gather table stay
    in their original dtype. Works for dense and MoE block layouts (the
    MoE leaves carry an extra leading expert axis). ``bits``: 8 (int8,
    amax/127) or 4 (packed int4, amax/7 — half the HBM bytes again at
    reduced precision).
    """
    if bits not in (8, 4):
        raise ValueError(f"bits must be 8 or 4, got {bits}")
    qfn = quantize_tensor if bits == 8 else quantize_tensor4
    qtypes = (QuantizedTensor, Quantized4Tensor)
    out = dict(params)
    blocks = dict(params["blocks"])
    for name, w in blocks.items():
        axes = (
            _QUANT_AXES_MOE
            if (name in _QUANT_AXES_MOE and w.ndim == 4)
            else _QUANT_AXES_DENSE
        )
        if name in axes and not isinstance(w, qtypes):
            blocks[name] = qfn(w, axes[name])
    out["blocks"] = blocks
    if quantize_lm_head and "lm_head" in params and not isinstance(
        params["lm_head"], qtypes
    ):
        out["lm_head"] = qfn(params["lm_head"], axis=0)
    return out


def quantized_bytes(params) -> int:
    """Total parameter bytes as stored (int8 + scales count as-is)."""
    return sum(
        leaf.size * leaf.dtype.itemsize
        for leaf in jax.tree_util.tree_leaves(params)
    )
