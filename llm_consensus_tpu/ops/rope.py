"""Rotary position embeddings (RoPE), rotate-half convention.

Replaces the RoPE the BASELINE.json north star attributes to the target's
CUDA path; here it is jnp (XLA fuses the elementwise rotation into the
surrounding projections on TPU). Frequencies are computed on the fly from
integer positions so decode steps with per-sequence offsets need no
precomputed table.
"""

from __future__ import annotations

import jax.numpy as jnp


def rope_cos_sin(
    positions: jnp.ndarray, head_dim: int, theta: float = 10000.0
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """cos/sin tables for given integer positions.

    positions: [...] int array (any shape, e.g. [B, S]).
    Returns cos, sin of shape [..., head_dim] (half-frequencies duplicated,
    matching the rotate-half convention).
    """
    half = head_dim // 2
    freq_exponents = jnp.arange(half, dtype=jnp.float32) / half
    inv_freq = 1.0 / (theta**freq_exponents)  # [half]
    angles = positions[..., None].astype(jnp.float32) * inv_freq  # [..., half]
    angles = jnp.concatenate([angles, angles], axis=-1)  # [..., head_dim]
    return jnp.cos(angles), jnp.sin(angles)


def _rotate_half(x: jnp.ndarray) -> jnp.ndarray:
    half = x.shape[-1] // 2
    x1, x2 = x[..., :half], x[..., half:]
    return jnp.concatenate([-x2, x1], axis=-1)


def apply_rope(
    x: jnp.ndarray, cos: jnp.ndarray, sin: jnp.ndarray
) -> jnp.ndarray:
    """Apply rotary embedding to q or k.

    x: [B, S, H, D]; cos/sin: [B, S, D] (broadcast over the head axis).
    Rotation runs in float32 and is cast back to x.dtype.
    """
    xf = x.astype(jnp.float32)
    c = cos[..., None, :]  # [B, S, 1, D]
    s = sin[..., None, :]
    return (xf * c + _rotate_half(xf) * s).astype(x.dtype)
