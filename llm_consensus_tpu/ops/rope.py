"""Rotary position embeddings (RoPE), rotate-half convention.

Replaces the RoPE the BASELINE.json north star attributes to the target's
CUDA path; here it is jnp (XLA fuses the elementwise rotation into the
surrounding projections on TPU). Frequencies are computed on the fly from
integer positions so decode steps with per-sequence offsets need no
precomputed table.
"""

from __future__ import annotations

import math

import jax.numpy as jnp


def _llama3_rescale(inv_freq: jnp.ndarray, scaling) -> jnp.ndarray:
    """Llama-3.1 'llama3' rope_scaling: long wavelengths divide by
    ``factor``, short ones stay, a smooth ramp interpolates between
    (matches transformers' _compute_llama3_parameters)."""
    orig = scaling.original_max_position_embeddings
    low_wavelen = orig / scaling.low_freq_factor
    high_wavelen = orig / scaling.high_freq_factor
    wavelen = 2.0 * math.pi / inv_freq
    scaled = inv_freq / scaling.factor
    smooth = (orig / wavelen - scaling.low_freq_factor) / (
        scaling.high_freq_factor - scaling.low_freq_factor
    )
    mid = (1 - smooth) * scaled + smooth * inv_freq
    out = jnp.where(wavelen > low_wavelen, scaled, inv_freq)
    return jnp.where(
        (wavelen <= low_wavelen) & (wavelen >= high_wavelen), mid, out
    )


def rope_cos_sin(
    positions: jnp.ndarray,
    head_dim: int,
    theta: float = 10000.0,
    scaling=None,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """cos/sin tables for given integer positions.

    positions: [...] int array (any shape, e.g. [B, S]).
    Returns cos, sin of shape [..., head_dim] (half-frequencies duplicated,
    matching the rotate-half convention). ``scaling``: optional
    :class:`llm_consensus_tpu.models.configs.RopeScaling`.
    """
    half = head_dim // 2
    freq_exponents = jnp.arange(half, dtype=jnp.float32) / half
    inv_freq = 1.0 / (theta**freq_exponents)  # [half]
    if scaling is not None:
        inv_freq = _llama3_rescale(inv_freq, scaling)
    angles = positions[..., None].astype(jnp.float32) * inv_freq  # [..., half]
    angles = jnp.concatenate([angles, angles], axis=-1)  # [..., head_dim]
    return jnp.cos(angles), jnp.sin(angles)


def _rotate_half(x: jnp.ndarray) -> jnp.ndarray:
    half = x.shape[-1] // 2
    x1, x2 = x[..., :half], x[..., half:]
    return jnp.concatenate([-x2, x1], axis=-1)


def apply_rope(
    x: jnp.ndarray, cos: jnp.ndarray, sin: jnp.ndarray
) -> jnp.ndarray:
    """Apply rotary embedding to q or k.

    x: [B, S, H, D]; cos/sin: [B, S, D] (broadcast over the head axis).
    Rotation runs in float32 and is cast back to x.dtype.
    """
    xf = x.astype(jnp.float32)
    c = cos[..., None, :]  # [B, S, 1, D]
    s = sin[..., None, :]
    return (xf * c + _rotate_half(xf) * s).astype(x.dtype)
