"""Parallelism: device meshes, sharding rules, and sequence parallelism.

The reference's only "parallelism" is request-level concurrency over HTTP
futures on one actix arbiter (``src/main.rs:101,156,182,250-253``) — no
DP/TP/EP/SP and no distributed backend (SURVEY.md §2). This package
supplies the real thing, the TPU way: a named ``jax.sharding.Mesh``
(data/pipe/model/expert/seq axes), ``PartitionSpec`` rules for every
param and activation, GSPMD-inserted XLA collectives over ICI/DCN, ring
attention for long-context sequence parallelism, and GPipe-microbatch
pipeline parallelism over the ``pipe`` axis.
"""

from llm_consensus_tpu.parallel.mesh import (
    MeshConfig,
    best_mesh_for,
    make_mesh,
)
from llm_consensus_tpu.parallel.partitioning import (
    batch_pspec,
    cache_pspecs,
    param_pspecs,
    shard_params,
)
from llm_consensus_tpu.parallel.multihost import (
    DistributedConfig,
    initialize_distributed,
    make_multislice_mesh,
)
from llm_consensus_tpu.parallel.pipeline import (
    make_pipeline_forward,
    make_pipeline_train_step,
    place_pipeline_params,
    pp_param_pspecs,
)
from llm_consensus_tpu.parallel.ring import ring_attention

__all__ = [
    "DistributedConfig",
    "MeshConfig",
    "best_mesh_for",
    "initialize_distributed",
    "make_multislice_mesh",
    "batch_pspec",
    "cache_pspecs",
    "make_mesh",
    "make_pipeline_forward",
    "make_pipeline_train_step",
    "param_pspecs",
    "place_pipeline_params",
    "pp_param_pspecs",
    "ring_attention",
    "shard_params",
]
