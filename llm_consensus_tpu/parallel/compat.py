"""API-drift shims for the manual-sharding surface.

``shard_map`` graduated from ``jax.experimental.shard_map`` to
``jax.shard_map`` (and grew the ``axis_names`` kwarg replacing ``auto``,
plus the VMA type system with ``jax.lax.pcast``); this repo targets
whichever jax the container ships, so every manual-collective module
(:mod:`parallel.ring`, :mod:`parallel.pipeline`,
:mod:`consensus.voting`) resolves the API through HERE instead of
hard-coding one spelling.

The shim is resolved once at import: feature-detect, don't
version-parse — jax backports and vendor forks make version strings
unreliable.
"""

from __future__ import annotations

import jax

__all__ = ["shard_map", "pcast_varying", "SUPPORTS_PARTIAL_AUTO"]

_NEW_SHARD_MAP = hasattr(jax, "shard_map")

#: Partial-auto shard_map (manual over a subset of mesh axes, GSPMD
#: auto-sharding over the rest). The experimental API's ``auto=``
#: parameter exists but its lowering hard-crashes XLA's partitioner
#: (``Check failed: sharding.IsManualSubgroup()``) for the GPipe
#: schedule's ppermute-in-scan shape; only the ``axis_names`` API
#: lowers it safely, so feature-gate on that.
SUPPORTS_PARTIAL_AUTO = _NEW_SHARD_MAP


def shard_map(f, mesh, in_specs, out_specs, axis_names=None):
    """``jax.shard_map`` with a fallback to the experimental spelling.

    ``axis_names``: the MANUAL mesh axes (new-API meaning). On the old
    API this maps to ``auto`` = every other mesh axis. ``None`` means
    all axes manual (both APIs' default).
    """
    if _NEW_SHARD_MAP:
        if axis_names is None:
            return jax.shard_map(
                f, mesh=mesh, in_specs=in_specs, out_specs=out_specs
            )
        return jax.shard_map(
            f,
            mesh=mesh,
            in_specs=in_specs,
            out_specs=out_specs,
            axis_names=set(axis_names),
        )
    from jax.experimental.shard_map import shard_map as _shard_map

    # check_rep=False everywhere: the callers mark varying scan carries
    # via pcast (a no-op here — see pcast_varying), which the old
    # replication checker cannot track; its successor (the VMA type
    # system) is exactly what the new API replaced it with.
    kw = {"check_rep": False}
    if axis_names is not None:
        auto = frozenset(mesh.axis_names) - frozenset(axis_names)
        auto = frozenset(a for a in auto if mesh.shape[a] > 1)
        if auto:
            # Refuse loudly rather than feed XLA a program that aborts
            # the whole process (see SUPPORTS_PARTIAL_AUTO).
            raise NotImplementedError(
                "partial-auto shard_map (manual "
                f"{sorted(axis_names)}, auto {sorted(auto)}) is not "
                "supported on this jax version; flatten the auto axes "
                "or upgrade to a jax with jax.shard_map(axis_names=...)"
            )
    return _shard_map(
        f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kw
    )


def distributed_is_initialized() -> bool:
    """``jax.distributed.is_initialized()`` with a fallback for jaxes
    that predate it: the distributed client's global state is the same
    signal the accessor wraps. Safe pre-backend-init on both paths."""
    if hasattr(jax.distributed, "is_initialized"):
        return bool(jax.distributed.is_initialized())
    from jax._src import distributed as _dist

    return _dist.global_state.client is not None


def pcast_varying(x, axes):
    """``jax.lax.pcast(x, axes, to="varying")`` where the VMA type
    system exists; identity elsewhere (pre-VMA jax has no
    varying/replicated distinction to satisfy, so the cast is a no-op
    by construction)."""
    if hasattr(jax.lax, "pcast"):
        return jax.lax.pcast(x, tuple(axes), to="varying")
    return x
