"""Device-mesh construction with the framework's canonical axis names.

Axes (any may be size 1; all shardings in
:mod:`llm_consensus_tpu.parallel.partitioning` are written against them):

- ``data``   — candidate / batch fan-out (self-consistency N, panel rows).
  Weights are replicated across it; the KV cache shards along it
  (BASELINE.json north star).
- ``pipe``   — pipeline parallelism (layer stages; GPipe microbatching in
  :mod:`llm_consensus_tpu.parallel.pipeline`). The *training* schedule is
  point-to-point neighbour activations plus a scalar loss psum, so it
  tolerates slow links (DCN in multi-slice) — but note the inference-path
  caveat in :func:`~llm_consensus_tpu.parallel.pipeline.make_pipeline_forward`
  (its logits broadcast psums a vocab-sized tensor over ``pipe``) and
  that redundant per-stage embedding makes embed-gradient cotangents
  psum over ``pipe`` in training.
- ``model``  — tensor parallelism (attention heads, MLP hidden).
- ``expert`` — expert parallelism for MoE (Mixtral config).
- ``seq``    — sequence/context parallelism (ring attention).

On real hardware ``jax.devices()`` supplies the TPU slice; tests create
the same meshes over ``xla_force_host_platform_device_count`` CPU
devices — the sharded programs are identical either way.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import numpy as np
from jax.sharding import Mesh

AXES = ("data", "pipe", "model", "expert", "seq")


@dataclass(frozen=True)
class MeshConfig:
    data: int = 1
    model: int = 1
    expert: int = 1
    seq: int = 1
    pipe: int = 1

    @property
    def size(self) -> int:
        return self.data * self.pipe * self.model * self.expert * self.seq

    def axis_sizes(self) -> dict[str, int]:
        return {
            "data": self.data,
            "pipe": self.pipe,
            "model": self.model,
            "expert": self.expert,
            "seq": self.seq,
        }


def make_mesh(config: MeshConfig | None = None, devices=None) -> Mesh:
    """Build a 4-axis mesh. Default: all devices on ``data``.

    Axis order is (data, model, expert, seq) — ``model`` and ``seq`` are
    innermost-adjacent so TP/ring collectives ride the fastest ICI links
    when the runtime's device order is physically contiguous.
    """
    if devices is None:
        devices = jax.devices()
    if config is None:
        config = MeshConfig(data=len(devices))
    if config.size != len(devices):
        raise ValueError(
            f"mesh {config} needs {config.size} devices, got {len(devices)}"
        )
    arr = np.asarray(devices).reshape(
        config.data, config.pipe, config.model, config.expert, config.seq
    )
    return Mesh(arr, AXES)


def best_mesh_for(
    n_devices: int,
    *,
    want_model: int = 1,
    want_expert: int = 1,
    want_seq: int = 1,
    want_pipe: int = 1,
) -> MeshConfig:
    """Fill the requested inner axes, spend the remainder on ``data``."""
    inner = want_model * want_expert * want_seq * want_pipe
    if n_devices % inner != 0:
        raise ValueError(
            f"{n_devices} devices not divisible by "
            f"pipe*model*expert*seq={inner}"
        )
    return MeshConfig(
        data=n_devices // inner,
        model=want_model,
        expert=want_expert,
        seq=want_seq,
        pipe=want_pipe,
    )
