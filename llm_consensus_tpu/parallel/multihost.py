"""Multi-host / multi-slice initialization and mesh construction.

The reference's "distributed backend" is in-process actix mailboxes —
single process, single machine (SURVEY.md §2, §5). The TPU-native
counterpart spans hosts two ways:

- **One slice, many hosts** (e.g. v5e-64 = 16 hosts): ``jax.distributed``
  connects the processes; ``jax.devices()`` then returns the *global*
  device list and every jitted program is automatically SPMD across all
  chips — the framework's meshes/shardings work unchanged.
- **Many slices** (DCN between slices, ICI within): the mesh must place
  its outermost axis across slices so only that axis's collectives ride
  DCN. ``make_multislice_mesh`` uses
  ``jax.experimental.mesh_utils.create_hybrid_device_mesh`` for exactly
  that; put ``data`` (gradient psums, amortized per step) or ``pipe``
  (point-to-point microbatch hops) on DCN, never ``model``/``seq``.

All functions degrade to single-process no-ops so the same launch script
runs on a laptop, one TPU VM, or a full pod — and the CPU-simulated
8-device tests exercise the same code paths.
"""

from __future__ import annotations

import logging
import os
from dataclasses import dataclass

import jax
import numpy as np
from jax.sharding import Mesh

from llm_consensus_tpu.parallel.mesh import AXES, MeshConfig

log = logging.getLogger(__name__)


@dataclass(frozen=True)
class DistributedConfig:
    """Connection info for ``jax.distributed.initialize``.

    Every field defaults to "let JAX auto-detect" — on Cloud TPU the
    runtime discovers coordinator/process_id/num_processes from the
    metadata server, so ``initialize_distributed()`` with no arguments is
    the common path. Env vars (``COORDINATOR_ADDRESS``, ``PROCESS_ID``,
    ``NUM_PROCESSES``) override for manual launches.
    """

    coordinator_address: str | None = None
    num_processes: int | None = None
    process_id: int | None = None

    @staticmethod
    def from_env() -> "DistributedConfig":
        return DistributedConfig(
            coordinator_address=os.environ.get("COORDINATOR_ADDRESS"),
            num_processes=_int_env("NUM_PROCESSES"),
            process_id=_int_env("PROCESS_ID"),
        )


def _int_env(name: str) -> int | None:
    v = os.environ.get(name)
    return int(v) if v is not None else None


def initialize_distributed(config: DistributedConfig | None = None) -> bool:
    """Connect this process to the multi-host job (idempotent).

    Returns True if a multi-process runtime is active afterwards. With no
    config and no env hints on a single machine this is a no-op returning
    False — safe to call unconditionally at program start.
    """
    from llm_consensus_tpu.parallel.compat import distributed_is_initialized

    config = config or DistributedConfig.from_env()
    # NOTE: must not touch jax.devices()/process_count() before
    # jax.distributed.initialize() — any backend-initializing call makes
    # the real initialize raise. The initialized check is safe (compat:
    # jaxes without is_initialized() read the client global state).
    if distributed_is_initialized():
        return jax.process_count() > 1
    explicit = config.coordinator_address or config.num_processes
    if not explicit and not _on_cloud_tpu():
        return False
    try:
        jax.distributed.initialize(
            coordinator_address=config.coordinator_address,
            num_processes=config.num_processes,
            process_id=config.process_id,
        )
    except Exception as e:  # noqa: BLE001
        if explicit:
            # The caller configured a real multi-process job; silently
            # proceeding single-host would train divergent replicas.
            raise RuntimeError(
                "jax.distributed.initialize failed for explicitly "
                f"configured job {config}: {e}"
            ) from e
        log.warning("jax.distributed.initialize failed (%s); single host", e)
        return False
    log.info(
        "distributed: process %d/%d, %d local / %d global devices",
        jax.process_index(),
        jax.process_count(),
        jax.local_device_count(),
        jax.device_count(),
    )
    return jax.process_count() > 1


def _on_cloud_tpu() -> bool:
    return bool(
        os.environ.get("TPU_WORKER_HOSTNAMES")
        or os.environ.get("MEGASCALE_COORDINATOR_ADDRESS")
    )


def make_multislice_mesh(
    config: MeshConfig,
    dcn_axis: str = "data",
    n_slices: int | None = None,
) -> Mesh:
    """Build a mesh whose ``dcn_axis`` spans slices over DCN and whose
    remaining axes stay within each slice's ICI.

    ``config`` describes the *global* mesh; ``config.axis_sizes()[dcn_axis]``
    must be divisible by the slice count. Falls back to a plain
    :func:`llm_consensus_tpu.parallel.mesh.make_mesh` when there is only
    one slice (or on CPU test meshes).
    """
    from jax.experimental import mesh_utils

    if dcn_axis not in AXES:
        raise ValueError(f"dcn_axis {dcn_axis!r} not in {AXES}")
    if dcn_axis in ("model", "seq", "expert"):
        raise ValueError(
            f"refusing to put {dcn_axis!r} on DCN: its collectives "
            "(TP gathers/psums, ring-attention permutes, MoE dispatch "
            "all-to-alls) are latency/bandwidth-critical per layer — put "
            "'data' or 'pipe' across slices instead"
        )
    sizes = config.axis_sizes()
    if n_slices is None:
        n_slices = _slice_count()
    if n_slices <= 1:
        from llm_consensus_tpu.parallel.mesh import make_mesh

        return make_mesh(config)
    if sizes[dcn_axis] % n_slices != 0:
        raise ValueError(
            f"{dcn_axis}={sizes[dcn_axis]} not divisible by "
            f"{n_slices} slices"
        )
    ici_sizes = dict(sizes)
    dcn_sizes = {a: 1 for a in AXES}
    dcn_sizes[dcn_axis] = n_slices
    ici_sizes[dcn_axis] = sizes[dcn_axis] // n_slices
    devices = mesh_utils.create_hybrid_device_mesh(
        mesh_shape=[ici_sizes[a] for a in AXES],
        dcn_mesh_shape=[dcn_sizes[a] for a in AXES],
        devices=jax.devices(),
    )
    return Mesh(devices, AXES)


def _slice_count() -> int:
    devices = jax.devices()
    slice_ids = {getattr(d, "slice_index", 0) for d in devices}
    return len(slice_ids)


def local_batch_slice(global_batch: int) -> tuple[int, int]:
    """(per-process batch size, this process's row offset) for feeding a
    ``data``-sharded global batch from per-host input pipelines."""
    n = jax.process_count()
    if global_batch % n:
        raise ValueError(
            f"global batch {global_batch} not divisible by {n} processes"
        )
    per = global_batch // n
    return per, per * jax.process_index()


def host_array_to_global(x: np.ndarray, mesh: Mesh, pspec) -> jax.Array:
    """Assemble a globally-sharded array from per-host shards
    (``jax.make_array_from_process_local_data``) — the multi-host feed
    path for token batches; single-process it is a plain device_put."""
    from jax.sharding import NamedSharding

    sharding = NamedSharding(mesh, pspec)
    if jax.process_count() == 1:
        return jax.device_put(x, sharding)
    return jax.make_array_from_process_local_data(sharding, x)
