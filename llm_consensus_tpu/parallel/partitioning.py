"""PartitionSpec rules: how every param/activation maps onto the mesh.

The sharding recipe (scaling-book style): pick a mesh
(:mod:`llm_consensus_tpu.parallel.mesh`), annotate every array with a
``PartitionSpec`` against the named axes, and let GSPMD insert the
collectives — all-gathers/psums ride ICI. No hand-written NCCL-equivalent
calls anywhere (the reference has none to port either; its comms layer is
in-process actix mailboxes, SURVEY.md §2).

Tensor-parallel layout (Megatron-style, expressed declaratively):
- qkv projections column-sharded over ``model`` (heads split);
- attention output row-sharded over ``model`` (GSPMD inserts the psum);
- MLP gate/up column-sharded, down row-sharded;
- MoE experts sharded over ``expert`` with each expert's FFN additionally
  TP-sharded over ``model``;
- lm_head vocab-sharded; logits gather at the end.
The KV cache shards batch over ``data`` and kv heads over ``model``
(BASELINE.json north star: per-candidate cache sharding in HBM).
"""

from __future__ import annotations

import jax
from jax.sharding import Mesh, NamedSharding
from jax.sharding import PartitionSpec as P

# Rules keyed by param-leaf name. Each value is the PartitionSpec for that
# leaf in the ``init_params`` tree (llm_consensus_tpu.models.transformer).
# Dense (non-MoE) block weights:
_DENSE_RULES: dict[str, P] = {
    "embed": P(None, None),  # gather table; replicate (V small vs FLOPs)
    "norm_f": P(None),
    "lm_head": P(None, "model"),  # vocab-sharded logits
    "attn_norm": P(None, None),
    "mlp_norm": P(None, None),
    "wq": P(None, None, "model"),
    "wk": P(None, None, "model"),
    "wv": P(None, None, "model"),
    "wo": P(None, "model", None),
    "bq": P(None, "model"),
    "bk": P(None, "model"),
    "bv": P(None, "model"),
    "w_gate": P(None, None, "model"),
    "w_up": P(None, None, "model"),
    "w_down": P(None, "model", None),
}
# MoE block weights override (leading expert axis after the layer axis).
_MOE_RULES: dict[str, P] = {
    "router": P(None, None, None),
    "w_gate": P(None, "expert", None, "model"),
    "w_up": P(None, "expert", None, "model"),
    "w_down": P(None, "expert", "model", None),
}


def _leaf_name(path) -> str:
    for entry in reversed(path):
        if isinstance(entry, jax.tree_util.DictKey):
            return entry.key
    raise ValueError(f"no named key in path {path}")


def param_pspecs(params) -> dict:
    """PartitionSpec tree mirroring an ``init_params`` tree."""

    def rule(path, leaf):
        name = _leaf_name(path)
        if name in _MOE_RULES and leaf.ndim == len(_MOE_RULES[name]):
            spec = _MOE_RULES[name]
        elif name in _DENSE_RULES:
            spec = _DENSE_RULES[name]
            if leaf.ndim != len(spec):
                raise ValueError(
                    f"param {name!r} rank {leaf.ndim} != rule rank {len(spec)}"
                )
        else:
            raise ValueError(f"no sharding rule for param {name!r}")
        # Size-1 axes replicate: int8 scale tensors (ops/quant.py) keep
        # the contraction dim as size 1 and would otherwise inherit a
        # sharded spec on an unsplittable axis.
        return P(
            *(
                None if leaf.shape[i] == 1 else spec[i]
                for i in range(len(spec))
            )
        )

    return jax.tree_util.tree_map_with_path(rule, params)


def cache_pspecs() -> "object":
    """Specs for a KVCache pytree: batch over ``data``, kv heads over
    ``model`` — per-candidate cache sharding (BASELINE.json north star)."""
    from llm_consensus_tpu.models.cache import KVCache

    return KVCache(
        k=P(None, "data", None, "model", None),
        v=P(None, "data", None, "model", None),
        length=P("data"),
    )


def batch_pspec() -> P:
    """Token/length batches shard their leading axis over ``data``."""
    return P("data")


def shard_params(params, mesh: Mesh):
    """Place a param tree on the mesh per :func:`param_pspecs`."""
    specs = param_pspecs(params)
    return jax.tree_util.tree_map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)), params, specs
    )


def sharded_param_bytes(tree, mesh_shape: dict) -> int:
    """Per-chip resident bytes of a param tree under this module's rules.

    Walks :func:`param_pspecs` leaf-for-leaf and divides each leaf's
    bytes by the product of the mesh-axis sizes its spec actually names
    — NOT a global model*expert divide, which would pretend replicated
    leaves (embeddings, norms, and on MoE models ALL attention weights,
    which replicate over ``expert``) shard too and understate per-chip
    residency. Accepts concrete arrays or ``jax.eval_shape`` structs
    (capacity planning without allocation).
    """
    specs = param_pspecs(tree)

    def leaf_bytes(leaf, spec) -> int:
        div = 1
        for entry in spec:
            axes = entry if isinstance(entry, tuple) else (entry,)
            for ax in axes:
                if ax is not None:
                    div *= int(mesh_shape.get(ax, 1))
        return leaf.size * leaf.dtype.itemsize // max(div, 1)

    return sum(
        leaf_bytes(leaf, spec)
        for leaf, spec in zip(
            jax.tree_util.tree_leaves(tree), jax.tree_util.tree_leaves(specs)
        )
    )
