"""GPipe-style pipeline parallelism over the ``pipe`` mesh axis.

The reference has no model-parallel execution of any kind (SURVEY.md §2,
"Parallelism strategies — NOT PRESENT"); this module supplies the PP part
of the framework's dp/tp/pp/sp/ep matrix, TPU-first:

- The layer axis of the stacked-params tree (``init_params`` puts layers
  on a leading ``L`` axis) is sharded over ``pipe``: each stage holds
  ``L / n_stages`` contiguous layers and scans them locally.
- Microbatched schedule: the batch splits into ``M`` microbatches; one
  device program runs ``M + n_stages - 1`` ticks of a ``lax.scan``. Each
  tick every stage runs its layer chunk, then activations hop to the next
  stage with a single ``lax.ppermute`` — point-to-point neighbour traffic
  on the ``pipe`` ring, no all-to-all.
- Implemented with ``jax.shard_map`` manual over ``("data", "pipe")``
  only; the ``model``/``expert``/``seq`` axes stay *auto*, so tensor/
  expert-parallel GSPMD sharding composes inside each pipeline stage
  without hand-written collectives.
- Differentiable end-to-end: ``ppermute`` transposes to the reverse
  permutation and replicated in-specs transpose to psums, so
  ``jax.value_and_grad`` of the shard_mapped loss is the 1F1B-equivalent
  backward schedule, derived by AD instead of hand-scheduling.

Embedding/unembedding are computed redundantly per stage (cheap relative
to the block stack); the loss is reduced on the last stage and ``psum``
broadcast so every stage returns the same scalar.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding
from jax.sharding import PartitionSpec as P

from llm_consensus_tpu.models.configs import ModelConfig
from llm_consensus_tpu.models.transformer import _block, _unembed
from llm_consensus_tpu.ops.rope import rope_cos_sin
from llm_consensus_tpu.parallel.compat import pcast_varying, shard_map
from llm_consensus_tpu.parallel.partitioning import param_pspecs


def pp_param_pspecs(params) -> dict:
    """Param specs for pipeline runs: like :func:`param_pspecs` but the
    stacked layer axis of every block leaf shards over ``pipe``."""
    specs = param_pspecs(params)

    def pipe_leading(spec: P) -> P:
        return P("pipe", *spec[1:])

    specs["blocks"] = jax.tree_util.tree_map(
        pipe_leading, specs["blocks"], is_leaf=lambda x: isinstance(x, P)
    )
    return specs


def _check_microbatching(b: int, m: int, mesh: Mesh) -> None:
    """Fail fast (named constraint, like make_mesh) instead of an opaque
    reshape/sharding error inside jit."""
    if b % m != 0:
        raise ValueError(
            f"batch {b} not divisible by n_microbatches={m}"
        )
    dp = mesh.shape["data"]
    if (b // m) % dp != 0:
        raise ValueError(
            f"microbatch rows {b}//{m}={b // m} not divisible by "
            f"data axis size {dp}"
        )


def _stage_chunk(cfg: ModelConfig, blocks, x, cos, sin, remat: bool):
    """Scan this stage's local layer chunk over activations ``x``."""

    def body(carry, p):
        y, _ = _block(cfg, p, carry, cos, sin, None, "full", None, None)
        return y, None

    if remat:
        body = jax.checkpoint(body)
    y, _ = jax.lax.scan(body, x, blocks)
    return y


def _pipeline_logits_local(
    cfg: ModelConfig,
    n_stages: int,
    n_micro: int,
    remat: bool,
    stage: jnp.ndarray,  # scalar int32: this shard's pipe index
    params: dict,
    tokens_mb: jnp.ndarray,  # [M, mb, S] local shard (mb = B/M/dp)
) -> jnp.ndarray:
    """Inside-shard_map pipeline: returns logits [M, mb, S, V] (valid on
    the last stage; garbage elsewhere — callers must mask by stage).

    ``stage`` rides in as a ``P("pipe")``-sharded input instead of
    ``jax.lax.axis_index``: under partial-auto shard_map the axis_index
    lowering emits a ``PartitionId`` op the SPMD partitioner refuses on
    jaxes predating the ``axis_names`` API (and on XLA:CPU generally) —
    a sharded iota carries the same information with no such op."""
    m, mb, s = tokens_mb.shape

    x_mb = params["embed"][tokens_mb]  # [M, mb, S, D] — embed per stage
    positions = jnp.broadcast_to(jnp.arange(s), (mb, s))
    cos, sin = rope_cos_sin(
        positions, cfg.head_dim, cfg.rope_theta, cfg.rope_scaling
    )

    perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]

    def tick(carry, t):
        state, out = carry
        inp = jax.lax.dynamic_index_in_dim(
            x_mb, jnp.clip(t, 0, m - 1), axis=0, keepdims=False
        )
        state = jnp.where(stage == 0, inp, state)
        state = _stage_chunk(cfg, params["blocks"], state, cos, sin, remat)
        # Drain: the last stage finishes microbatch (t - n_stages + 1).
        oidx = jnp.clip(t - (n_stages - 1), 0, m - 1)
        cur = jax.lax.dynamic_index_in_dim(out, oidx, axis=0, keepdims=False)
        new = jnp.where((t >= n_stages - 1) & (stage == n_stages - 1), state, cur)
        out = jax.lax.dynamic_update_index_in_dim(out, new, oidx, axis=0)
        state = jax.lax.ppermute(state, "pipe", perm)
        return (state, out), None

    # The carry becomes pipe-varying after the first ppermute; mark the
    # (replicated) zero initials as varying so the scan carry type is
    # stable under shard_map's VMA check.
    state0 = pcast_varying(jnp.zeros_like(x_mb[0]), ("pipe",))
    out0 = pcast_varying(jnp.zeros_like(x_mb), ("pipe",))
    (_, out), _ = jax.lax.scan(
        tick, (state0, out0), jnp.arange(m + n_stages - 1)
    )
    return _unembed(cfg, params, out)  # [M, mb, S, V] fp32


def make_pipeline_forward(
    cfg: ModelConfig,
    mesh: Mesh,
    n_microbatches: int,
    remat: bool = False,
):
    """Jitted pipelined forward: tokens [B, S] -> logits [B, S, V].

    Params must be placed per :func:`pp_param_pspecs` (use
    :func:`place_pipeline_params`). ``B`` must divide into
    ``n_microbatches * mesh.shape['data']`` microbatch rows.

    Note: returning replicated logits requires broadcasting the last
    stage's [B, S, V] tensor over ``pipe`` (a vocab-sized psum) — fine
    over ICI, but do not map ``pipe`` to DCN for this entry point. The
    training path (:func:`pipeline_causal_lm_loss`) reduces to a scalar
    instead and has no such traffic.
    """
    n_stages = mesh.shape["pipe"]
    m = n_microbatches

    def run(params, tokens):
        b, s = tokens.shape
        _check_microbatching(b, m, mesh)
        tokens_mb = tokens.reshape(m, b // m, s)

        def f(stage_ids, params, tokens_mb):
            stage = stage_ids[0]
            logits = _pipeline_logits_local(
                cfg, n_stages, m, remat, stage, params, tokens_mb
            )
            # Broadcast the last stage's logits to every stage so the
            # output is pipe-invariant.
            logits = jnp.where(stage == n_stages - 1, logits, 0.0)
            return jax.lax.psum(logits, "pipe")

        logits_mb = shard_map(
            f,
            mesh=mesh,
            in_specs=(
                P("pipe"),
                _param_in_specs(params),
                P(None, "data", None),
            ),
            out_specs=P(None, "data"),
            axis_names={"data", "pipe"},
        )(jnp.arange(n_stages, dtype=jnp.int32), params, tokens_mb)
        return logits_mb.reshape(b, s, -1)

    return jax.jit(run)


def _param_in_specs(params):
    """shard_map in-specs for params: blocks split over ``pipe`` on the
    layer axis, everything else replicated w.r.t. the manual axes."""
    specs = jax.tree_util.tree_map(lambda _: P(), params)
    specs["blocks"] = jax.tree_util.tree_map(
        lambda _: P("pipe"), params["blocks"]
    )
    return specs


def pipeline_causal_lm_loss(
    cfg: ModelConfig,
    mesh: Mesh,
    n_microbatches: int,
    params: dict,
    tokens: jnp.ndarray,
    loss_mask: jnp.ndarray,
    remat: bool = True,
    compute_dtype: str | None = None,
) -> jnp.ndarray:
    """Masked next-token CE over a pipelined forward (matches
    ``training.train.causal_lm_loss`` numerics: sum(nll)/sum(mask),
    including its mixed-precision ``compute_dtype`` cast)."""
    from llm_consensus_tpu.training.train import _cast_params

    params = _cast_params(params, compute_dtype)
    n_stages = mesh.shape["pipe"]
    m = n_microbatches
    b, s = tokens.shape
    _check_microbatching(b, m, mesh)
    tokens_mb = tokens.reshape(m, b // m, s)
    mask_mb = loss_mask.reshape(m, b // m, s)

    def f(stage_ids, params, tokens_mb, mask_mb):
        stage = stage_ids[0]
        logits = _pipeline_logits_local(
            cfg, n_stages, m, remat, stage, params, tokens_mb
        )  # [M, mb, S, V]
        targets = tokens_mb[..., 1:]
        lp = jax.nn.log_softmax(logits[..., :-1, :], axis=-1)
        nll = -jnp.take_along_axis(lp, targets[..., None], axis=-1)[..., 0]
        mask = mask_mb[..., :-1].astype(jnp.float32)
        last = stage == n_stages - 1
        nll_sum = jnp.where(last, jnp.sum(nll * mask), 0.0)
        mask_sum = jnp.where(last, jnp.sum(mask), 0.0)
        nll_sum = jax.lax.psum(nll_sum, ("data", "pipe"))
        mask_sum = jax.lax.psum(mask_sum, ("data", "pipe"))
        return nll_sum / jnp.maximum(mask_sum, 1.0)

    return shard_map(
        f,
        mesh=mesh,
        in_specs=(
            P("pipe"),
            _param_in_specs(params),
            P(None, "data", None),
            P(None, "data", None),
        ),
        out_specs=P(),
        axis_names={"data", "pipe"},
    )(jnp.arange(n_stages, dtype=jnp.int32), params, tokens_mb, mask_mb)


def make_pipeline_train_step(cfg, tcfg, mesh: Mesh, n_microbatches: int):
    """Pipelined train step + placement helper.

    Same contract as ``training.train.make_sharded_train_step`` but the
    layer stack is stage-sharded over ``pipe`` and the forward/backward
    run the GPipe microbatch schedule. TP/EP still apply within each
    stage via the auto axes.
    """
    from llm_consensus_tpu.training.train import TrainState, make_optimizer

    opt = make_optimizer(tcfg)

    def step(state, tokens, loss_mask):
        def loss_fn(p):
            return pipeline_causal_lm_loss(
                cfg,
                mesh,
                n_microbatches,
                p,
                tokens,
                loss_mask,
                tcfg.remat,
                tcfg.compute_dtype,
            )

        loss, grads = jax.value_and_grad(loss_fn)(state.params)
        updates, opt_state = opt.update(grads, state.opt_state, state.params)
        params = optax.apply_updates(state.params, updates)
        return (
            TrainState(params=params, opt_state=opt_state, step=state.step + 1),
            loss,
        )

    def place(state, tokens, loss_mask):
        from llm_consensus_tpu.training.train import place_train_state

        return place_train_state(
            state,
            mesh,
            pp_param_pspecs(state.params),
            batch_spec=P("data", None),
            batches=(tokens, loss_mask),
        )

    return jax.jit(step, donate_argnums=(0,)), place


def place_pipeline_params(params, mesh: Mesh):
    """Place a param tree on the mesh per :func:`pp_param_pspecs`."""
    specs = pp_param_pspecs(params)
    return jax.tree_util.tree_map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)), params, specs
    )
