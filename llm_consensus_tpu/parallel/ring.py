"""Ring attention: causal attention over a sequence sharded across devices.

Long-context support (first-class per the build spec; the reference has no
model code at all — SURVEY.md §5 "Long-context: NOT PRESENT"). Each device
holds a contiguous sequence chunk of q/k/v. K/V chunks rotate around the
``seq`` mesh axis via ``lax.ppermute`` (ICI neighbour exchange) while each
device accumulates its queries' attention with the numerically stable
streaming-softmax update (running max + denominator), so the full [S, S]
score matrix never materializes and comm overlaps compute ring-step by
ring-step.

Layout contract: chunk d of the sequence lives on mesh position d of the
``seq`` axis; global position = chunk_index * chunk_len + local offset.
Causality is enforced against *global* positions, so results equal
single-device causal attention exactly (up to fp reordering).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.sharding import Mesh
from jax.sharding import PartitionSpec as P

from llm_consensus_tpu.parallel.compat import pcast_varying, shard_map

_NEG_INF = -1e30


def ring_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    axis_name: str,
    axis_size: int,
    varying_axes: tuple[str, ...] | None = None,
) -> jnp.ndarray:
    """Causal ring attention over one sequence-sharded axis.

    Call from inside ``shard_map``/``pjit`` with ``axis_name`` mapped.
    q: [B, S_loc, H, D]; k/v: [B, S_loc, Hkv, D] (GQA: H = Hkv * G).
    ``axis_size`` is the static number of ring participants.
    ``varying_axes``: every manual mesh axis the inputs are sharded over
    (the scan-carry accumulators must be marked varying over all of
    them); defaults to just the ring axis.
    Returns [B, S_loc, H, D] in q's dtype.
    """
    b, s, h, d = q.shape
    hkv = k.shape[2]
    g = h // hkv
    idx = jax.lax.axis_index(axis_name)
    scale = d**-0.5

    qg = q.reshape(b, s, hkv, g, d).astype(jnp.float32)
    q_pos = idx * s + jnp.arange(s)  # [S_loc] global query positions

    # The accumulators are per-shard state, varying over the ring axis —
    # mark them so the scan carry type matches its updated value.
    def _varying(x):
        return pcast_varying(x, varying_axes or (axis_name,))

    m0 = _varying(jnp.full((b, hkv, g, s), _NEG_INF, jnp.float32))
    l0 = _varying(jnp.zeros((b, hkv, g, s), jnp.float32))
    o0 = _varying(jnp.zeros((b, hkv, g, s, d), jnp.float32))

    def body(carry, step):
        k_blk, v_blk, m, l, o = carry
        origin = (idx - step) % axis_size  # which chunk we hold this step
        k_pos = origin * s + jnp.arange(s)  # [S_loc] global key positions

        scores = (
            jnp.einsum(
                "bqkgd,bskd->bkgqs",
                qg,
                k_blk.astype(jnp.float32),
                preferred_element_type=jnp.float32,
            )
            * scale
        )  # [B, Hkv, G, Sq, Sk]
        mask = (k_pos[None, :] <= q_pos[:, None])[None, None, None]
        scores = jnp.where(mask, scores, _NEG_INF)

        blk_max = scores.max(axis=-1)  # [B, Hkv, G, Sq]
        new_m = jnp.maximum(m, blk_max)
        correction = jnp.exp(m - new_m)
        p = jnp.exp(scores - new_m[..., None])  # masked -> ~0
        p = jnp.where(mask, p, 0.0)
        new_l = l * correction + p.sum(axis=-1)
        new_o = o * correction[..., None] + jnp.einsum(
            "bkgqs,bskd->bkgqd",
            p,
            v_blk.astype(jnp.float32),
            preferred_element_type=jnp.float32,
        )

        # Rotate k/v one hop around the ring (ICI neighbour exchange).
        perm = [(i, (i + 1) % axis_size) for i in range(axis_size)]
        k_next = jax.lax.ppermute(k_blk, axis_name, perm)
        v_next = jax.lax.ppermute(v_blk, axis_name, perm)
        return (k_next, v_next, new_m, new_l, new_o), None

    (_, _, _, l, o), _ = jax.lax.scan(
        body, (k, v, m0, l0, o0), jnp.arange(axis_size)
    )
    out = o / jnp.maximum(l[..., None], 1e-30)  # [B, Hkv, G, Sq, D]
    return out.transpose(0, 3, 1, 2, 4).reshape(b, s, h, d).astype(q.dtype)


def ring_attention_sharded(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    mesh: Mesh,
    axis_name: str = "seq",
) -> jnp.ndarray:
    """Convenience wrapper: shard q/k/v over ``axis_name`` and run the ring.

    q/k/v: full [B, S, H|Hkv, D] arrays; S must divide evenly by the axis
    size. The batch axis shards over ``data`` and heads over ``model``
    when those mesh axes exist and divide evenly — so the ring composes
    with dp/tp instead of forcing a reshard at its boundary.
    """
    axis_size = mesh.shape[axis_name]
    if q.shape[1] % axis_size:
        raise ValueError(
            f"sequence {q.shape[1]} not divisible by {axis_name}={axis_size}"
        )
    b, _, h, _ = q.shape
    hkv = k.shape[2]
    batch_ax = None
    if "data" in mesh.axis_names and b % mesh.shape["data"] == 0:
        batch_ax = "data"
    head_ax = None
    if (
        "model" in mesh.axis_names
        and h % mesh.shape["model"] == 0
        and hkv % mesh.shape["model"] == 0
        # per-shard GQA grouping must stay integral
        and (h // mesh.shape["model"]) % max(hkv // mesh.shape["model"], 1)
        == 0
    ):
        head_ax = "model"
    spec = P(batch_ax, axis_name, head_ax, None)
    varying = tuple(a for a in (batch_ax, axis_name, head_ax) if a)
    fn = shard_map(
        partial(
            ring_attention,
            axis_name=axis_name,
            axis_size=axis_size,
            varying_axes=varying,
        ),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
    )
    return fn(q, k, v)
