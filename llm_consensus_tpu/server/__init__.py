"""Serving gateway: the network-facing layer over the scheduler /
continuous-batcher / coordinator stack.

The reference's serving story is the anti-pattern this package replaces:
unbounded per-request HTTP futures with no admission control and no
observability (``src/main.rs:101,156,182``). Here the entry point is a
hand-rolled asyncio HTTP/1.1 gateway (stdlib only — no new deps) with:

- :mod:`llm_consensus_tpu.server.gateway` — ``POST /v1/generate`` (with
  SSE token streaming), ``POST /v1/consensus`` (the full panel
  protocol), ``GET /metrics``, ``GET /healthz``;
- :mod:`llm_consensus_tpu.server.admission` — bounded per-priority
  queues with load shedding (429 + Retry-After), per-request deadlines,
  graceful drain on SIGTERM;
- :mod:`llm_consensus_tpu.server.metrics` — a process-wide registry of
  counters/gauges/histograms exported in Prometheus text format;
- :mod:`llm_consensus_tpu.server.client` — a stdlib client speaking the
  gateway's wire protocol (incl. SSE parsing).

Every later scale-out layer (multi-replica routing, disaggregated
prefill/decode serving) plugs in behind this gateway.

Submodules import lazily: ``server.metrics`` is imported from the hot
serving/consensus modules for instrumentation, and an eager gateway
import here would cycle back through them.
"""

from __future__ import annotations

__all__ = [
    "AdmissionConfig",
    "AdmissionController",
    "Gateway",
    "GatewayClient",
    "GatewayConfig",
    "MetricsRegistry",
    "REGISTRY",
]

_EXPORTS = {
    "AdmissionConfig": "llm_consensus_tpu.server.admission",
    "AdmissionController": "llm_consensus_tpu.server.admission",
    "Gateway": "llm_consensus_tpu.server.gateway",
    "GatewayConfig": "llm_consensus_tpu.server.gateway",
    "GatewayClient": "llm_consensus_tpu.server.client",
    "MetricsRegistry": "llm_consensus_tpu.server.metrics",
    "REGISTRY": "llm_consensus_tpu.server.metrics",
}


def __getattr__(name: str):
    target = _EXPORTS.get(name)
    if target is None:
        raise AttributeError(name)
    import importlib

    return getattr(importlib.import_module(target), name)
