"""Admission control: bounded per-priority queues, shedding, deadlines, drain.

The reference accepts unbounded concurrent work — every HTTP request
spawns a future immediately (``src/main.rs:101,156,182``), so overload
manifests as memory growth and collapse instead of backpressure. This
module is the opposite contract, the one every production serving stack
makes explicit:

- **Bounded queues, one per priority.** When a priority's queue is full
  the request is SHED at the door (:class:`QueueFullError` -> the
  gateway's ``429`` + ``Retry-After``) instead of admitted into an
  ever-deeper backlog. Dispatch drains strictly by priority order.
- **Deadlines.** A request may carry a deadline; if it expires while
  still queued the work is cancelled before it ever touches the backend
  (:class:`DeadlineExpiredError` -> ``504``), and an admitted request's
  backend call runs under ``asyncio.wait_for`` with the remaining
  budget so in-flight work is cancelled at the deadline too.
- **Graceful drain.** :meth:`AdmissionController.drain` stops admitting
  (:class:`DrainingError` -> ``503``) and waits for every
  already-admitted request — queued and in-flight — to reach its
  terminal outcome. The gateway calls it on SIGTERM.

Single-event-loop asyncio; the controller owns a dispatcher task with a
bounded in-flight window (``max_inflight``) so the backend sees at most
a fixed number of concurrent batch calls regardless of queue depth.

Every transition feeds the metrics registry: queue depth gauges,
admitted/shed/expired/completed counters (all labeled by priority), and
queue-wait histograms — the series the overload integration test
cross-checks against observed HTTP outcomes.
"""

from __future__ import annotations

import asyncio
import logging
import time
from collections import deque
from collections.abc import Awaitable, Callable
from dataclasses import dataclass, field

from llm_consensus_tpu.server import metrics as _metrics
from llm_consensus_tpu.utils import tracing as _tracing

log = logging.getLogger(__name__)

__all__ = [
    "AdmissionConfig",
    "AdmissionController",
    "DeadlineExpiredError",
    "DrainingError",
    "QueueFullError",
]


class QueueFullError(Exception):
    """Load shed: the request's priority queue is at its bound.

    ``slo_miss`` marks a deadline-aware shed (PR 19): the victim was
    chosen because it *will miss its SLO*, not because it was newest.
    ``tenant_over`` marks a fair-share shed: the tenant exceeded its
    weighted share of admitted modeled cost while another tenant was
    waiting. Both ride the exception so the gateway's flight-recorder
    shed event can name the reason.
    """

    def __init__(
        self,
        priority: str,
        retry_after: float,
        *,
        slo_miss: bool = False,
        tenant_over: bool = False,
    ):
        super().__init__(
            f"{priority} queue full; retry after {retry_after:.1f}s"
        )
        self.priority = priority
        self.retry_after = retry_after
        self.slo_miss = slo_miss
        self.tenant_over = tenant_over


class DrainingError(Exception):
    """The controller is draining (SIGTERM): no new admissions."""


class DeadlineExpiredError(Exception):
    """The request's deadline passed before the work completed."""


@dataclass
class AdmissionConfig:
    # Priority order = dispatch order: the first listed priority drains
    # first. Every request names one of these.
    priorities: tuple[str, ...] = ("interactive", "batch")
    # Per-priority queue bound; an int applies to every priority, a dict
    # overrides per name.
    max_queue: int | dict[str, int] = 64
    # Concurrent in-flight executions across all priorities. The backend
    # underneath batches, so a handful of concurrent generate_batch
    # calls keeps the chip full without unbounded task fan-out.
    max_inflight: int = 8
    # Deadline applied when a request does not carry one; None = none.
    default_deadline_s: float | None = None
    # Retry-After hint returned on shed when the queue-wait history is
    # still empty.
    retry_after_s: float = 1.0
    # Hard ceiling on overflow admission (PR 14): a granting
    # overflow_hook stretches a priority's queue bound by at most this
    # factor — preemption absorbs storms, it never REMOVES
    # backpressure (a stale preempt signal + a mega-storm must
    # eventually shed fast 429s instead of queueing requests to
    # deadline death and growing queue memory with offered load).
    # UNIT NORMALIZATION (PR 15): the factor multiplies whatever unit
    # the bound itself uses — requests in classic mode, MODELED BYTES
    # in cost-budget mode — so the hard-cap path can never again mix
    # a bytes-denominated preempt signal with a request-count cap.
    max_overflow_factor: int = 16
    # Cost-budget admission (PR 15): > 0 switches every queue bound
    # from request COUNTS to MODELED BYTES — the same unit the fleet
    # router's load_cost compares and ContinuousBatcher.
    # modeled_request_cost prices (a 32k-context request is not one
    # unit of work). Each submit carries its modeled cost; a request
    # without one is priced at one nominal slot
    # (budget / bound_for(priority)). 0 (default) = classic
    # request-count bounds.
    cost_budget_bytes: float = 0.0
    # SLO classes (PR 19): class name -> queue-wait target in seconds
    # (the admission-controlled component of TTFT — the PR-10 TTFT/TBT
    # histograms become targets instead of telemetry). A request names
    # a class via the ``/v1/generate`` ``"slo"`` payload field; unknown
    # names are a 400 at the door. None = SLO-blind admission.
    slo_classes: dict[str, float] | None = None
    # Class applied to requests that carry no ``"slo"`` field. None =
    # untagged requests stay SLO-blind even when classes are defined.
    default_slo_class: str | None = None
    # Tenant fair-share (PR 19): True enables weighted fair queueing
    # across the ``"tenant"`` payload field — WFQ dispatch order within
    # a priority plus an admitted-cost share cap under contention, so
    # one tenant's storm cannot starve panel traffic. Enforcement uses
    # the same modeled-byte unit as cost-budget admission.
    tenant_fair_share: bool = False
    # Tenant -> WFQ weight. Tenants absent from the map weigh 1.0, so
    # an empty map means equal shares.
    tenant_weights: dict[str, float] | None = None
    # Share-cap slack: a tenant is shed at the door only once its
    # decayed admitted-cost share exceeds fair_weight * slack while
    # another tenant has queued work (1.1 = the ±10% band the fleet
    # bench gates on).
    fair_share_slack: float = 1.1
    # Half-life in seconds of the decayed per-tenant admitted-cost
    # window the share cap is computed over.
    fair_window_s: float = 30.0

    def slo_target(self, name: str | None) -> float | None:
        if name is None or not self.slo_classes:
            return None
        return self.slo_classes.get(name)

    def tenant_weight(self, tenant: str) -> float:
        w = (self.tenant_weights or {}).get(tenant, 1.0)
        return max(float(w), 1e-6)

    def bound_for(self, priority: str) -> int:
        if isinstance(self.max_queue, dict):
            return int(self.max_queue.get(priority, 64))
        return int(self.max_queue)


@dataclass
class _Item:
    thunk: Callable[[], Awaitable]
    priority: str
    deadline: float | None  # monotonic seconds, None = no deadline
    enqueued_at: float
    future: asyncio.Future = field(default_factory=asyncio.Future)
    # Request trace captured at submit: the dispatcher's _run task has
    # its own contextvars context (it is NOT a child of the submitter),
    # so the trace must ride the item and be re-installed around the
    # thunk (tracing.use_trace) for downstream spans to attach.
    trace: object | None = None
    # Modeled cost in bytes (PR 15, cost-budget mode): charged to the
    # priority's queue-cost account while queued, released at dispatch
    # or expiry. 0 in classic request-count mode.
    cost: float = 0.0
    # SLO class + queue-wait target (PR 19); None = SLO-blind request.
    slo_class: str | None = None
    slo_target: float | None = None
    # Tenant the request bills to (PR 19 fair-share); None = untagged.
    tenant: str | None = None
    # WFQ finish tag stamped at admission when fair-share is on; the
    # dispatcher picks the smallest tag within a priority. 0 = untagged
    # (dispatches ahead of tagged work — it is outside fair-share).
    wfq_tag: float = 0.0
    # Work units for rate/fairness accounting: modeled bytes in
    # cost-budget mode, 1.0 per request in classic mode.
    units: float = 1.0


class AdmissionController:
    """Bounded-queue dispatcher between the gateway and a backend."""

    def __init__(
        self,
        config: AdmissionConfig | None = None,
        registry: _metrics.MetricsRegistry | None = None,
    ):
        self.config = config or AdmissionConfig()
        if not self.config.priorities:
            raise ValueError("need at least one priority")
        reg = registry or _metrics.REGISTRY
        self._queues: dict[str, deque[_Item]] = {
            p: deque() for p in self.config.priorities
        }
        # Modeled bytes queued per priority (PR 15 cost-budget mode):
        # charged at append, released at every popleft site — the
        # bound AND the overflow hard cap read this one account, so
        # the two can never drift units.
        self._queue_cost: dict[str, float] = {
            p: 0.0 for p in self.config.priorities
        }
        self._inflight = 0
        self._draining = False
        # Overload overflow hook (PR 14): consulted at a queue-full
        # moment BEFORE shedding. Returning True admits the request
        # past the bound — the fleet's preempt-to-host-tier path
        # (ReplicaSet.preempt_for_admission) frees backend capacity by
        # demoting resident KV chains instead of 429ing, so an
        # overload storm degrades to restore latency, not lost work.
        # The hook must be cheap and non-blocking (it runs on the
        # event loop inside submit) and is expected to become False
        # once nothing is left to preempt — that, not the queue bound,
        # is then the shed condition. None (default) = classic shed.
        # CHEAPNESS CONTRACT with remote stores (PR 16): the fleet's
        # hook reads the page store's headroom to decide whether
        # demotion can still land pages. A RemotePageStore serves that
        # read from its last piggybacked stats snapshot — NEVER a
        # network round-trip — precisely because this call sits on the
        # event loop at peak overload. A store outage therefore reads
        # as zero headroom (hook returns False) and overload degrades
        # to the classic 429 shed, not a wedged submit path.
        self.overflow_hook: Callable[[], bool] | None = None
        self._work = asyncio.Event()
        self._idle = asyncio.Event()
        self._idle.set()
        self._dispatcher: asyncio.Task | None = None
        self._m_depth = reg.gauge(
            "gateway_queue_depth", "Requests waiting for admission"
        )
        self._m_inflight = reg.gauge(
            "gateway_inflight", "Requests currently executing"
        )
        self._m_admitted = reg.counter(
            "gateway_admitted_total", "Requests accepted into a queue"
        )
        self._m_shed = reg.counter(
            "gateway_shed_total", "Requests shed with 429 (queue full)"
        )
        self._m_expired = reg.counter(
            "gateway_deadline_expired_total",
            "Requests that hit their deadline before completing",
        )
        self._m_completed = reg.counter(
            "gateway_completed_total",
            "Admitted requests that reached a terminal outcome",
        )
        self._m_wait = reg.histogram(
            "gateway_queue_wait_seconds",
            "Time from admission to dispatch",
        )
        self._m_cost = reg.gauge(
            "gateway_queue_cost_bytes",
            "Modeled bytes waiting for admission (cost-budget mode)",
        )
        # -- PR 19 SLO / tenant families + their stats() mirrors. The
        # mirrors are incremented in the same statement block as the
        # Prometheus family so the lockstep tests can cross-check.
        self._m_slo_miss = reg.counter(
            "gateway_slo_miss_total",
            "Requests whose queue wait exceeded their SLO class target",
        )
        self._m_slo_shed = reg.counter(
            "gateway_slo_shed_total",
            "Deadline-aware sheds of requests that would miss their SLO",
        )
        self._m_headroom = reg.histogram(
            "gateway_slo_headroom_seconds",
            "Predicted SLO slack at admission (target - estimated wait)",
        )
        self._m_tenant_cost = reg.counter(
            "gateway_tenant_cost_bytes",
            "Admitted modeled cost per tenant (bytes in cost-budget "
            "mode, request units otherwise)",
        )
        self._m_tenant_shed = reg.counter(
            "gateway_tenant_shed_total",
            "Fair-share sheds: tenant over its weighted admitted share",
        )
        # -- PR 20 SLO burn rate: decayed per-class miss fraction
        # (misses / SLO-classed outcomes over a fair_window_s
        # half-life window) — 0.0 = the class is meeting its target,
        # 1.0 = every recent request missed. The fleet controller
        # reads this through burn_rates(); the gauge and the mirror
        # update in the same statement blocks (lockstep tested).
        self._m_burn = reg.gauge(
            "gateway_slo_burn_rate",
            "Decayed SLO miss fraction per class (misses over "
            "SLO-classed outcomes, half-life fair_window_s)",
        )
        # class -> [outcomes, misses], both decayed together.
        self._burn: dict[str, list[float]] = {}
        self._burn_mark = time.monotonic()
        self._slo_missed: dict[str, int] = {}
        self._slo_sheds = 0
        self._headroom_sum = 0.0
        self._headroom_count = 0
        self._tenant_admitted: dict[str, float] = {}
        self._tenant_sheds: dict[str, int] = {}
        # Queued-request count per tenant (all lanes): the contention
        # signal for the share cap — a tenant is capped only while
        # someone ELSE is waiting.
        self._tenant_queued: dict[str, int] = {}
        # Decayed admitted-units window per tenant (half-life
        # fair_window_s) the share cap compares against weights.
        self._tenant_recent: dict[str, float] = {}
        self._recent_mark = time.monotonic()
        # WFQ virtual time: per-tenant last finish tag + global floor.
        self._vt: dict[str, float] = {}
        self._vtime = 0.0
        # Dispatch-rate EWMA in units/s — the queue-drain model behind
        # predicted waits and would-miss selection.
        self._rate: float | None = None
        self._rate_mark: float | None = None

    # -- admission ------------------------------------------------------

    @property
    def draining(self) -> bool:
        return self._draining

    def pending(self) -> int:
        """Admitted-but-unfinished request count (queued + in-flight)."""
        return sum(len(q) for q in self._queues.values()) + self._inflight

    async def submit(
        self,
        thunk: Callable[[], Awaitable],
        *,
        priority: str | None = None,
        deadline_s: float | None = None,
        cost: float | None = None,
        slo: str | None = None,
        tenant: str | None = None,
    ):
        """Admit ``thunk`` and await its terminal outcome.

        Raises :class:`DrainingError` / :class:`QueueFullError` at the
        door, :class:`DeadlineExpiredError` when the deadline passes
        (queued or in-flight), else returns/raises whatever the awaited
        thunk does.

        ``cost`` (PR 15): the request's modeled bytes
        (``ContinuousBatcher.modeled_request_cost`` — the unit
        ``load_cost`` routes on). Read only in cost-budget mode
        (``AdmissionConfig.cost_budget_bytes > 0``), where the queue
        bound, the overflow hard cap, and the shed decision all
        compare in modeled bytes; a costless submit is priced at one
        nominal slot (budget / bound) so legacy callers keep
        approximately the classic depth bound.

        ``slo`` (PR 19): SLO class name from ``AdmissionConfig.
        slo_classes`` (unknown -> ValueError -> the gateway's 400);
        None falls back to ``default_slo_class``. At a full queue the
        shed victim is the request that *will miss its SLO* — predicted
        from modeled cost ahead of it and the live dispatch rate —
        never simply the newest arrival.

        ``tenant`` (PR 19): fair-share billing key. With
        ``tenant_fair_share`` on, dispatch within a priority follows
        weighted-fair-queueing finish tags, and a tenant whose decayed
        admitted-cost share exceeds its fair weight is shed at the door
        while another tenant has queued work.
        """
        prio = priority or self.config.priorities[0]
        q = self._queues.get(prio)
        if q is None:
            raise ValueError(
                f"unknown priority {prio!r}; have {self.config.priorities}"
            )
        if self._draining:
            raise DrainingError("gateway is draining; not admitting")
        if slo is None:
            slo = self.config.default_slo_class
        slo_target = self.config.slo_target(slo)
        if slo is not None and self.config.slo_classes and slo_target is None:
            raise ValueError(
                f"unknown slo class {slo!r}; "
                f"have {sorted(self.config.slo_classes)}"
            )
        if slo_target is None:
            slo = None
        bound = self.config.bound_for(prio)
        budget = self.config.cost_budget_bytes
        factor = self.config.max_overflow_factor
        if budget > 0:
            # Cost-budget mode: bound and hard cap in ONE unit,
            # modeled bytes — a 32k-context request charges what it
            # costs, N small ones fit where one huge one would not.
            # An EMPTY queue always admits (classic mode's invariant):
            # the budget bounds the BACKLOG, never a single request's
            # size — a request whose lone modeled cost exceeds the
            # budget must not be unservable forever on an idle
            # gateway.
            if cost is None or cost <= 0:
                cost = budget / max(1, bound)
            units = cost
            queued = self._queue_cost[prio]
            over = len(q) > 0 and queued + cost > budget
            capped = len(q) > 0 and queued + cost > budget * factor
        else:
            cost = 0.0
            units = 1.0
            over = len(q) >= bound
            capped = len(q) >= bound * factor
        now = time.monotonic()
        fair = self.config.tenant_fair_share and tenant is not None
        if fair:
            self._decay_recent(now)
            if len(q) > 0 and self._tenant_over_share(tenant, units):
                # Fair-share shed: this tenant is past its weighted
                # share of the admitted-cost window while another
                # tenant waits. The overflow hook is NOT consulted —
                # preempting backend capacity cannot fix unfairness.
                self._m_shed.labels(priority=prio).inc()
                self._m_tenant_shed.labels(tenant=tenant).inc()
                self._tenant_sheds[tenant] = (
                    self._tenant_sheds.get(tenant, 0) + 1
                )
                raise QueueFullError(
                    prio, self._retry_after_hint(), tenant_over=True
                )
        if over:
            hook = self.overflow_hook
            preempted = False
            if hook is not None and not capped:
                try:
                    preempted = bool(hook())
                except Exception:  # noqa: BLE001 - hook must not 500
                    log.exception("admission overflow hook failed")
            if not preempted and not self._shed_would_miss(
                prio, q, now, slo, slo_target, units
            ):
                # Classic shed: nobody queued is predicted to miss
                # worse than the newcomer (or SLO admission is off).
                self._m_shed.labels(priority=prio).inc()
                miss = False
                if slo_target is not None:
                    est = self._est_wait(self._units_ahead(prio))
                    miss = est > slo_target
                    if miss:
                        self._count_slo_shed(slo)
                raise QueueFullError(
                    prio, self._retry_after_hint(), slo_miss=miss
                )
        if deadline_s is None:
            deadline_s = self.config.default_deadline_s
        item = _Item(
            thunk=thunk,
            priority=prio,
            deadline=(now + deadline_s) if deadline_s is not None else None,
            enqueued_at=now,
            trace=_tracing.current_trace(),
            cost=cost,
            slo_class=slo,
            slo_target=slo_target,
            tenant=tenant,
            units=units,
        )
        if slo_target is not None:
            # Predicted slack at the door: target minus the modeled
            # wait behind everything already queued at >= priority.
            headroom = slo_target - self._est_wait(self._units_ahead(prio))
            self._m_headroom.observe(headroom)
            self._headroom_sum += headroom
            self._headroom_count += 1
        if fair:
            # WFQ finish tag: service start is the later of the global
            # virtual time and the tenant's own last finish, so an idle
            # tenant re-enters at the current front instead of owing
            # phantom debt (or banking phantom credit).
            start = max(self._vtime, self._vt.get(tenant, 0.0))
            item.wfq_tag = start + units / self.config.tenant_weight(tenant)
            self._vt[tenant] = item.wfq_tag
        if tenant is not None:
            self._tenant_admitted[tenant] = (
                self._tenant_admitted.get(tenant, 0.0) + units
            )
            self._m_tenant_cost.labels(tenant=tenant).inc(units)
            self._tenant_recent[tenant] = (
                self._tenant_recent.get(tenant, 0.0) + units
            )
            self._tenant_queued[tenant] = (
                self._tenant_queued.get(tenant, 0) + 1
            )
        q.append(item)
        self._queue_cost[prio] += item.cost
        self._m_admitted.labels(priority=prio).inc()
        self._m_depth.labels(priority=prio).set(len(q))
        self._m_cost.labels(priority=prio).set(self._queue_cost[prio])
        self._idle.clear()
        self._ensure_dispatcher()
        self._work.set()
        if item.deadline is not None:
            # Wake the dispatcher at the deadline so a queued item is
            # cancelled on time, not on the next unrelated admission.
            asyncio.get_running_loop().call_later(
                deadline_s, self._work.set
            )
        return await item.future

    # -- PR 19 SLO / tenant machinery -----------------------------------

    def _est_wait(self, ahead_units: float) -> float:
        """Predicted queue wait behind ``ahead_units`` of work, from the
        dispatch-rate EWMA; falls back to the historical mean wait while
        the rate model is cold, then to zero on a fresh controller."""
        if self._rate is not None and self._rate > 1e-9:
            return ahead_units / self._rate
        h = self._m_wait
        if h.count:
            return h.sum / h.count
        return 0.0

    def _units_ahead(self, prio: str) -> float:
        """Work units queued at ``prio`` and every higher priority —
        what a new arrival at ``prio``'s tail drains behind."""
        total = 0.0
        for p in self.config.priorities:
            for it in self._queues[p]:
                total += it.units
            if p == prio:
                break
        return total

    def _count_slo_shed(self, cls: str | None) -> None:
        label = cls or "default"
        self._m_slo_shed.labels(**{"class": label}).inc()
        self._m_slo_miss.labels(**{"class": label}).inc()
        self._slo_sheds += 1
        self._slo_missed[label] = self._slo_missed.get(label, 0) + 1
        self._burn_observe(label, missed=True)

    def _burn_observe(self, cls: str, missed: bool) -> None:
        """Fold one SLO-classed outcome into the class's decayed burn
        window and refresh the gauge (PR 20). Every SLO outcome site —
        on-time dispatch, late dispatch, deadline-aware shed — lands
        here, so the gauge is the live miss fraction, not a counter
        ratio a scraper has to difference."""
        now = time.monotonic()
        dt = now - self._burn_mark
        self._burn_mark = now
        w = self.config.fair_window_s
        if dt > 0 and w > 0:
            f = 0.5 ** (dt / w)
            for b in self._burn.values():
                b[0] *= f
                b[1] *= f
        b = self._burn.setdefault(cls, [0.0, 0.0])
        b[0] += 1.0
        if missed:
            b[1] += 1.0
        self._m_burn.labels(**{"class": cls}).set(b[1] / b[0])

    def burn_rates(self) -> dict[str, float]:
        """Decayed per-class SLO miss fraction — the gauge's value,
        readable in-process (the PR-19 FleetController's tick pulls
        this instead of scraping its own gateway)."""
        return {
            cls: (b[1] / b[0] if b[0] > 0 else 0.0)
            for cls, b in self._burn.items()
        }

    def _shed_would_miss(
        self,
        prio: str,
        q: deque[_Item],
        now: float,
        slo: str | None,
        slo_target: float | None,
        units: float,
    ) -> bool:
        """Deadline-aware victim selection at a full queue: walk the
        lane computing each queued request's predicted SLO slack
        (target - waited - modeled wait for its position) and compare
        against the newcomer's. If a QUEUED request is more doomed than
        the newcomer, shed IT and admit the newcomer — returns True and
        the caller skips the classic newest-arrival shed. Requests
        without an SLO class are never victimized."""
        if not self.config.slo_classes:
            return False
        ahead = 0.0
        for p in self.config.priorities:
            if p == prio:
                break
            for it in self._queues[p]:
                ahead += it.units
        worst_idx = -1
        worst_slack = (
            slo_target - self._est_wait(self._units_ahead(prio))
            if slo_target is not None
            else float("inf")
        )
        run = ahead
        for i, it in enumerate(q):
            if it.slo_target is not None and not it.future.done():
                slack = (
                    it.slo_target
                    - (now - it.enqueued_at)
                    - self._est_wait(run)
                )
                if slack < worst_slack:
                    worst_slack = slack
                    worst_idx = i
            run += it.units
        if worst_idx < 0:
            return False
        victim = q[worst_idx]
        del q[worst_idx]
        self._release_cost(victim)
        self._m_depth.labels(priority=prio).set(len(q))
        self._m_shed.labels(priority=prio).inc()
        self._count_slo_shed(victim.slo_class)
        # The victim WAS admitted, so its terminal outcome must land in
        # the completed account like every other queue exit.
        self._m_completed.labels(priority=victim.priority).inc()
        if not victim.future.done():
            victim.future.set_exception(
                QueueFullError(
                    victim.priority,
                    self._retry_after_hint(),
                    slo_miss=True,
                )
            )
        self._maybe_idle()
        return True

    def _decay_recent(self, now: float) -> None:
        """Age the per-tenant admitted-cost window (half-life
        ``fair_window_s``) so the share cap reflects current pressure,
        not all-time history."""
        dt = now - self._recent_mark
        if dt <= 0:
            return
        self._recent_mark = now
        w = self.config.fair_window_s
        if w <= 0:
            return
        f = 0.5 ** (dt / w)
        for t in list(self._tenant_recent):
            v = self._tenant_recent[t] * f
            if v < 1e-9:
                del self._tenant_recent[t]
            else:
                self._tenant_recent[t] = v

    def _tenant_over_share(self, tenant: str, units: float) -> bool:
        """True when admitting ``units`` would push ``tenant`` past its
        weighted share of the decayed admitted-cost window while some
        OTHER tenant has queued work. With no contention the cap is
        inert — fair share is work-conserving, spare capacity flows to
        whoever offers load."""
        others = [
            t
            for t, n in self._tenant_queued.items()
            if n > 0 and t != tenant
        ]
        if not others:
            return False
        active = set(others)
        active.add(tenant)
        wsum = sum(self.config.tenant_weight(t) for t in active)
        fair = self.config.tenant_weight(tenant) / max(wsum, 1e-9)
        mine = self._tenant_recent.get(tenant, 0.0) + units
        total = (
            sum(self._tenant_recent.get(t, 0.0) for t in active) + units
        )
        share = mine / max(total, 1e-9)
        return share > fair * self.config.fair_share_slack

    def stats(self) -> dict:
        """Mirror of the PR-19 SLO/tenant counters for lockstep checks
        against the Prometheus families (same increments, same units)."""
        return {
            "slo_miss": dict(self._slo_missed),
            "slo_burn_rate": self.burn_rates(),
            "slo_sheds": self._slo_sheds,
            "slo_headroom_sum": self._headroom_sum,
            "slo_headroom_count": self._headroom_count,
            "tenant_cost_bytes": dict(self._tenant_admitted),
            "tenant_sheds": dict(self._tenant_sheds),
            "tenant_queued": {
                t: n for t, n in self._tenant_queued.items() if n
            },
        }

    def _retry_after_hint(self) -> float:
        """Shed hint: recent mean queue wait, else the configured floor."""
        h = self._m_wait
        if h.count:
            return max(self.config.retry_after_s, h.sum / h.count)
        return self.config.retry_after_s

    # -- dispatch -------------------------------------------------------

    def _ensure_dispatcher(self) -> None:
        if self._dispatcher is None or self._dispatcher.done():
            self._dispatcher = asyncio.create_task(
                self._dispatch_loop(), name="admission-dispatcher"
            )

    def _next_item(self) -> _Item | None:
        """Pop the next runnable item in strict priority order, resolving
        any already-expired queued items along the way. With tenant
        fair-share on, the pick within a priority is the smallest WFQ
        finish tag instead of FIFO — that interleaving is what bounds a
        quiet tenant's wait under another tenant's storm."""
        now = time.monotonic()
        fair = self.config.tenant_fair_share
        for prio in self.config.priorities:
            q = self._queues[prio]
            while q:
                idx = 0
                if fair and len(q) > 1:
                    for i in range(1, len(q)):
                        if q[i].wfq_tag < q[idx].wfq_tag:
                            idx = i
                item = q[idx]
                del q[idx]
                self._release_cost(item)
                self._m_depth.labels(priority=prio).set(len(q))
                if item.wfq_tag:
                    self._vtime = max(self._vtime, item.wfq_tag)
                if item.future.done():
                    # Caller gave up while queued (e.g. an aborted SSE
                    # client cancelled its submit): terminal already —
                    # don't burn backend time on a dead request.
                    self._m_completed.labels(priority=item.priority).inc()
                    self._maybe_idle()
                    continue
                if item.deadline is not None and item.deadline <= now:
                    self._expire(item)
                    continue
                return item
        return None

    def _release_cost(self, item: _Item) -> None:
        """Release a dequeued item's modeled-cost charge and its
        tenant's queued-count (every dequeue site calls this exactly
        once — the accounts mirror queue membership, nothing else)."""
        if item.cost:
            c = self._queue_cost[item.priority] = max(
                0.0, self._queue_cost[item.priority] - item.cost
            )
            self._m_cost.labels(priority=item.priority).set(c)
        if item.tenant is not None:
            n = self._tenant_queued.get(item.tenant, 0)
            if n > 1:
                self._tenant_queued[item.tenant] = n - 1
            else:
                self._tenant_queued.pop(item.tenant, None)

    def _expire(self, item: _Item) -> None:
        self._m_expired.labels(priority=item.priority).inc()
        self._m_completed.labels(priority=item.priority).inc()
        if not item.future.done():
            item.future.set_exception(
                DeadlineExpiredError(
                    f"deadline expired after "
                    f"{time.monotonic() - item.enqueued_at:.3f}s in queue"
                )
            )
        self._maybe_idle()

    def _expire_due(self) -> None:
        """Resolve every queued item whose deadline has passed. Runs on
        each dispatcher wake-up even when the in-flight window is full —
        a queued 504 must not wait for an unrelated slot to free."""
        now = time.monotonic()
        for prio in self.config.priorities:
            q = self._queues[prio]
            for _ in range(len(q)):
                item = q.popleft()
                if item.deadline is not None and item.deadline <= now:
                    self._release_cost(item)
                    self._expire(item)
                else:
                    q.append(item)
            self._m_depth.labels(priority=prio).set(len(q))

    async def _dispatch_loop(self) -> None:
        while True:
            if self._inflight >= self.config.max_inflight:
                self._expire_due()
                await self._work.wait()
                self._work.clear()
                continue
            item = self._next_item()
            if item is None:
                self._maybe_idle()
                await self._work.wait()
                self._work.clear()
                continue
            now = time.monotonic()
            wait = now - item.enqueued_at
            self._m_wait.observe(wait)
            # Dispatch-rate EWMA (units/s): the live drain model the
            # SLO headroom predictions divide by. Updated only while
            # work was actually waiting — idle gaps would read as a
            # collapsed rate.
            if self._rate_mark is not None and wait > 1e-3:
                dt = max(now - self._rate_mark, 1e-6)
                inst = item.units / dt
                self._rate = (
                    inst
                    if self._rate is None
                    else 0.2 * inst + 0.8 * self._rate
                )
            self._rate_mark = now
            if item.slo_target is not None:
                label = item.slo_class or "default"
                missed = wait > item.slo_target
                if missed:
                    # The PR-10 wait histogram is now a TARGET: a
                    # dispatch past its class budget is a recorded
                    # miss, in both the Prometheus family and the
                    # stats() mirror.
                    self._m_slo_miss.labels(**{"class": label}).inc()
                    self._slo_missed[label] = (
                        self._slo_missed.get(label, 0) + 1
                    )
                self._burn_observe(label, missed=missed)
            if item.trace is not None:
                # The admission wait, recorded at dispatch (start
                # reconstructed in the trace's clock).
                item.trace.add_span(
                    "queued",
                    time.perf_counter() - wait,
                    wait,
                    priority=item.priority,
                )
            self._inflight += 1
            self._m_inflight.set(self._inflight)
            asyncio.create_task(self._run(item))

    async def _run(self, item: _Item) -> None:
        try:
            with _tracing.use_trace(item.trace), _tracing.request_span(
                "execute", priority=item.priority
            ):
                coro = item.thunk()
                if item.deadline is not None:
                    remaining = item.deadline - time.monotonic()
                    result = await asyncio.wait_for(coro, max(remaining, 0.0))
                else:
                    result = await coro
        except (asyncio.TimeoutError, TimeoutError):
            self._m_expired.labels(priority=item.priority).inc()
            if not item.future.done():
                item.future.set_exception(
                    DeadlineExpiredError("deadline expired mid-execution")
                )
        except Exception as e:  # noqa: BLE001 - forwarded to the caller
            if not item.future.done():
                item.future.set_exception(e)
        else:
            if not item.future.done():
                item.future.set_result(result)
        finally:
            self._inflight -= 1
            self._m_inflight.set(self._inflight)
            self._m_completed.labels(priority=item.priority).inc()
            self._maybe_idle()
            self._work.set()

    def _maybe_idle(self) -> None:
        if self.pending() == 0:
            self._idle.set()

    # -- drain ----------------------------------------------------------

    def begin_drain(self) -> None:
        """Stop admitting; already-admitted work keeps running."""
        self._draining = True

    async def drain(self) -> None:
        """Stop admitting and wait until every admitted request (queued
        and in-flight) has reached its terminal outcome."""
        self.begin_drain()
        self._work.set()
        await self._idle.wait()
        if self._dispatcher is not None:
            self._dispatcher.cancel()
            try:
                await self._dispatcher
            except asyncio.CancelledError:
                pass
            self._dispatcher = None
